"""Process-wide metric primitives and the MetricsRegistry.

One registry of named, labeled series — counters (monotone), gauges
(last value / watermark), and windowed histograms (percentiles over a
bounded ring of recent observations, because an operator wants the
CURRENT tail, not the all-time one).  Everything that used to count
things privately — ``serve/stats.ModelStats``, ``utils/timer``'s time
tags, the per-tree training records — now lands in one place with one
export surface (``telemetry/export.py`` renders Prometheus text and
JSON; the serve HTTP server mounts it at ``/metrics``).

The reference ships ``Common::Timer`` timetags compiled into every layer
(include/LightGBM/utils/common.h:931); this module is the registry those
fragments report into here.

Design constraints:
  * thread-safe — serving bumps counters from request threads while
    ``/metrics`` scrapes concurrently;
  * cheap — a counter bump is one lock + one dict add (the serving hot
    path bumps per micro-batch, not per row);
  * labels are fixed per metric at creation; each label VALUE
    combination is one independent series (Prometheus's data model).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["percentile", "SlidingWindow", "Counter", "Gauge",
           "WindowedHistogram", "MetricsRegistry", "default_registry"]


def percentile(sorted_vals: List[float], p: float) -> float:
    """Nearest-rank percentile over pre-sorted values.

    The single shared implementation (formerly duplicated between
    ``serve/stats.py`` and ``benchmarks/serve_latency.py``) so the
    ``/stats`` endpoint, ``/metrics`` export and the latency benchmark
    can never diverge."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class SlidingWindow:
    """Bounded ring of recent float observations (the serving latency
    ring, generalized).  NOT internally locked — the owning metric or
    caller serializes access."""

    __slots__ = ("capacity", "_vals", "_pos", "count", "total")

    def __init__(self, capacity: int = 4096) -> None:
        self.capacity = int(capacity)
        self._vals: List[float] = []
        self._pos = 0
        self.count = 0      # lifetime observations (window may be smaller)
        self.total = 0.0    # lifetime sum

    def add(self, v: float) -> None:
        self.count += 1
        self.total += v
        if len(self._vals) < self.capacity:
            self._vals.append(v)
        else:
            self._vals[self._pos] = v
            self._pos = (self._pos + 1) % self.capacity

    def __len__(self) -> int:
        return len(self._vals)

    def sorted_values(self) -> List[float]:
        return sorted(self._vals)

    def percentile(self, p: float) -> float:
        return percentile(self.sorted_values(), p)

    def summary(self, ps: Tuple[float, ...] = (50.0, 99.0)) -> Dict:
        vals = self.sorted_values()
        out = {"window": len(vals), "count": self.count,
               "sum": self.total}
        for p in ps:
            out[f"p{p:g}"] = percentile(vals, p)
        return out


def _label_key(label_names: Tuple[str, ...], labels: Dict[str, str]
               ) -> Tuple[str, ...]:
    if set(labels) != set(label_names):
        raise ValueError(f"metric expects labels {label_names}, "
                         f"got {tuple(labels)}")
    return tuple(str(labels[k]) for k in label_names)


class _Metric:
    """Shared labeled-series plumbing for Counter/Gauge/WindowedHistogram."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labels: Tuple[str, ...] = ()) -> None:
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, ...], object] = {}

    def _new_series(self):
        raise NotImplementedError

    def _get(self, labels: Dict[str, str]):
        key = _label_key(self.label_names, labels)
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = self._new_series()
        return s

    def remove_series(self, **labels) -> int:
        """Drop every series whose labels match ``labels`` (a SUBSET of
        the metric's label names — ``model="a"`` drops all buckets of
        model a).  Returns the number of series removed.  This is the
        zoo-eviction path: a bounded model cache must be able to retire
        a tenant's series or the registry ratchets under churn."""
        unknown = set(labels) - set(self.label_names)
        if unknown:
            raise ValueError(f"metric {self.name!r} has no labels "
                             f"{sorted(unknown)} (labels: "
                             f"{self.label_names})")
        want = {k: str(v) for k, v in labels.items()}
        idx = [self.label_names.index(k) for k in want]
        vals = [want[self.label_names[i]] for i in idx]
        with self._lock:
            doomed = [key for key in self._series
                      if all(key[i] == v for i, v in zip(idx, vals))]
            for key in doomed:
                del self._series[key]
        return len(doomed)

    def series(self) -> List[Tuple[Dict[str, str], object]]:
        """[(labels dict, snapshot value)] — value is a float for
        counter/gauge, a summary dict for windowed histograms.  Snapped
        under the metric lock so a concurrent observe can never tear a
        window summary."""
        with self._lock:
            return [(dict(zip(self.label_names, key)), self._snap(s))
                    for key, s in self._series.items()]

    def _snap(self, s):
        return s


class Counter(_Metric):
    kind = "counter"

    def _new_series(self):
        return 0.0

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        key = _label_key(self.label_names, labels)
        with self._lock:
            return float(self._series.get(key, 0.0))


class Gauge(_Metric):
    kind = "gauge"

    def _new_series(self):
        return 0.0

    def set(self, value: float, **labels) -> None:
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = float(value)

    def max(self, value: float, **labels) -> None:
        """Watermark update: keep the largest value seen (device-memory
        peaks)."""
        key = _label_key(self.label_names, labels)
        with self._lock:
            cur = self._series.get(key)
            if cur is None or value > cur:
                self._series[key] = float(value)

    def add(self, amount: float, **labels) -> None:
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        key = _label_key(self.label_names, labels)
        with self._lock:
            return float(self._series.get(key, 0.0))


class _SeriesHandle:
    """A label-resolved histogram series: ``observe`` skips the per-call
    label validation/key-building of the dict path (the serving tier
    records three windows per request — the handle keeps that at one
    lock + one ring append each).  The handle shares the metric's lock,
    so snapshots stay tear-free."""

    __slots__ = ("_lock", "_win")

    def __init__(self, lock, win: SlidingWindow) -> None:
        self._lock = lock
        self._win = win

    def observe(self, value: float) -> None:
        with self._lock:
            self._win.add(value)


class WindowedHistogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labels: Tuple[str, ...] = (), window: int = 4096,
                 percentiles: Tuple[float, ...] = (50.0, 99.0)) -> None:
        super().__init__(name, help, labels)
        self.window = int(window)
        self.percentiles = tuple(percentiles)

    def _new_series(self):
        return SlidingWindow(self.window)

    def observe(self, value: float, **labels) -> None:
        with self._lock:
            self._get(labels).add(value)

    def handle(self, **labels) -> _SeriesHandle:
        """Pre-resolve one label set into a hot-path observe handle
        (validates the labels once, here)."""
        with self._lock:
            return _SeriesHandle(self._lock, self._get(labels))

    def window_of(self, **labels) -> SlidingWindow:
        """The underlying ring for one label set (callers who need the
        raw values, e.g. ModelStats.snapshot)."""
        with self._lock:
            return self._get(labels)

    def values_of(self, **labels) -> List[float]:
        """Sorted copy of one label set's current window, taken under
        the metric lock (safe against concurrent observes)."""
        with self._lock:
            return self._get(labels).sorted_values()

    def _snap(self, s: SlidingWindow):
        return s.summary(self.percentiles)


class MetricsRegistry:
    """Thread-safe name -> metric store with get-or-create semantics.

    ``counter``/``gauge``/``histogram`` are idempotent: the first call
    creates, later calls return the same object (and raise if the kind
    or label names conflict — two subsystems silently sharing a
    mistyped metric is a debugging tarpit)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labels: Tuple[str, ...], **kw):
        labels = tuple(labels)
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, labels, **kw)
            elif not isinstance(m, cls) or m.label_names != labels or \
                    any(getattr(m, k) != v for k, v in kw.items()):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind} with "
                    f"labels {m.label_names}; requested {cls.kind} with "
                    f"{labels} {kw or ''}")
            return m

    def counter(self, name: str, help: str = "",
                labels: Iterable[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, tuple(labels))

    def gauge(self, name: str, help: str = "",
              labels: Iterable[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, tuple(labels))

    def histogram(self, name: str, help: str = "",
                  labels: Iterable[str] = (), window: int = 4096,
                  percentiles: Tuple[float, ...] = (50.0, 99.0)
                  ) -> WindowedHistogram:
        return self._get_or_create(WindowedHistogram, name, help,
                                   tuple(labels), window=window,
                                   percentiles=percentiles)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name: str) -> bool:
        with self._lock:
            return self._metrics.pop(name, None) is not None

    def remove_series(self, **labels) -> int:
        """Drop matching label-series from EVERY metric that carries all
        the given label names (metrics without them are untouched).
        Returns total series removed — the registry-wide half of zoo
        eviction (``remove_series(model="tenant-7")``)."""
        removed = 0
        for m in self.collect():
            if set(labels) <= set(m.label_names):
                removed += m.remove_series(**labels)
        return removed

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()

    def collect(self) -> List[_Metric]:
        """Metrics in registration order (export renders from this)."""
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> Dict[str, Dict]:
        """JSON-ready {name: {kind, help, series: [{labels, value}]}}."""
        out = {}
        for m in self.collect():
            out[m.name] = {
                "kind": m.kind,
                "help": m.help,
                "series": [{"labels": lbl, "value": val}
                           for lbl, val in m.series()],
            }
        return out


_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry (training records, serving counters and
    time tags all land here; ``/metrics`` renders it)."""
    return _default
