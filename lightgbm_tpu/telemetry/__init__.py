"""lightgbm_tpu.telemetry — unified observability for training and serving.

Four pieces (see the module docstrings for depth):

  * :mod:`.metrics` — the process-wide :class:`MetricsRegistry` of
    counters / gauges / windowed histograms with labeled series;
    ``serve/stats.ModelStats`` and ``utils/timer.global_timer`` report
    into it.
  * :mod:`.trace` — hierarchical ``span("tree/wave/psum")`` host spans
    paired with ``jax.profiler.TraceAnnotation``, chrome-trace export;
    near-zero overhead when disabled.
  * :mod:`.train_record` — the per-run :class:`TrainRecord` (histogram
    passes per tree, trace-time collective counts/bytes, XLA compile
    events, device-memory watermark, per-phase wall time), accumulated
    by ``models/gbdt.py`` and surfaced as ``Booster.train_record``.
  * :mod:`.export` — Prometheus text / JSON renderers; the serve HTTP
    server mounts ``GET /metrics``; ``python -m lightgbm_tpu profile``
    wraps a run in a ``jax.profiler.trace`` capture plus a dump.
  * :mod:`.slo` — declarative service-level objectives keyed to
    registry series, evaluated with multi-window burn-rate math
    (``GET /slo``, SLO-aware ``/healthz``, slowest-request exemplars).
  * :mod:`.flight` — the training flight recorder: a bounded ring of
    per-iteration events dumped to JSONL on crash/SIGTERM.

Master switch: ``enabled()`` / ``enable()`` / ``disable()`` (env
``LGBM_TPU_TELEMETRY=0`` to opt out).  Telemetry-on and telemetry-off
training produce bit-identical models — accumulation only observes.
"""

from ._config import enable, disable, enabled
from .metrics import (Counter, Gauge, MetricsRegistry, SlidingWindow,
                      WindowedHistogram, default_registry, percentile)
from .trace import Tracer, global_tracer, span
from .train_record import (TrainRecord, collectives_reset,
                           collectives_snapshot, device_memory_peak,
                           last_train_record, note_collective,
                           set_last_train_record)
from .export import (PROMETHEUS_CONTENT_TYPE, render_json,
                     render_prometheus, write_snapshot)
from .slo import (SLO, SloEngine, all_slos, default_engine, slo)
from .flight import FlightRecorder

__all__ = [
    "enable", "disable", "enabled",
    "Counter", "Gauge", "MetricsRegistry", "SlidingWindow",
    "WindowedHistogram", "default_registry", "percentile",
    "Tracer", "global_tracer", "span",
    "TrainRecord", "collectives_reset", "collectives_snapshot",
    "device_memory_peak", "last_train_record", "note_collective",
    "set_last_train_record",
    "PROMETHEUS_CONTENT_TYPE", "render_json", "render_prometheus",
    "write_snapshot",
    "SLO", "SloEngine", "all_slos", "default_engine", "slo",
    "FlightRecorder",
]
