"""Training flight recorder: a bounded ring of per-iteration events.

Post-mortems of interrupted pod runs should not be archaeology: the
recorder accumulates one structured event per boosting iteration —
eval losses, last-tree best split gain / histogram passes / leaf count,
trace-time collective bytes, chunked-ingest host->HBM bytes, the
device-memory watermark — in a ``deque(maxlen=...)`` ring, and the
engine's PreemptionGuard / crash path (``resilience/``) dumps it to
JSONL next to the final checkpoint on SIGTERM or an uncaught training
error.  The last event's iteration therefore matches the checkpoint's
iteration (both are flushed at the same drained boundary), which the
resilience suite asserts.

Like :class:`~lightgbm_tpu.telemetry.train_record.TrainRecord`, the
recorder is purely observational: it reads values the boosting loop
already computed, keeps device scalars un-synced until a dump (batched
``jax.device_get``, so the async dispatch pipeline never stalls), and
recorder-on vs recorder-off training is bit-identical (tested).

Anomaly detection rides the eval stream: a non-finite loss or a loss
spiking past ``spike_factor`` x its EWMA marks the event, bumps
``flight_anomalies_total{kind}`` and logs a warning — the flight tape
points at WHERE a run went wrong, not just that it died.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Any, Dict, List, Optional

from . import _config
from .metrics import default_registry
from .train_record import collectives_snapshot, device_memory_peak
from ..utils.log import log_warning

__all__ = ["FlightRecorder"]

_MEM_SAMPLE_EVERY = 16  # iterations between device-memory watermark reads


def _h2d_bytes() -> float:
    """Chunked-ingest host->HBM byte counter (0 outside chunked runs)."""
    m = default_registry().get("ingest_train_h2d_bytes_total")
    value = getattr(m, "value", None)
    if m is None or value is None:
        return 0.0
    try:
        return float(value())
    except Exception:
        return 0.0


class FlightRecorder:
    """Bounded per-iteration event ring for one training run."""

    def __init__(self, capacity: int = 1024, enabled: bool = True,
                 meta: Optional[Dict[str, Any]] = None,
                 spike_factor: float = 4.0, min_history: int = 5) -> None:
        self.enabled = bool(enabled) and _config.enabled()
        self.capacity = int(capacity)
        self.meta = dict(meta or {})
        self.spike_factor = float(spike_factor)
        self.min_history = int(min_history)
        self._lock = threading.Lock()
        self._ring: "collections.deque" = collections.deque(
            maxlen=self.capacity)
        self._t0 = time.perf_counter()
        self._loss_ewma: Optional[float] = None
        self._loss_n = 0
        self.anomalies: List[Dict[str, Any]] = []
        self._counter = default_registry().counter(
            "flight_anomalies_total",
            "training anomalies flagged by the flight recorder",
            labels=("kind",))

    # -- accumulation (boosting loop) ------------------------------------
    def note_iter(self, iteration: int, hist_passes=None, num_leaves=None,
                  best_gain=None, **extra) -> None:
        """Record one completed boosting iteration.  ``hist_passes`` /
        ``num_leaves`` / ``best_gain`` may be device scalars; they stay
        un-synced until :meth:`events` / :meth:`dump` pulls them in one
        batched fetch."""
        if not self.enabled:
            return
        ev: Dict[str, Any] = {
            "iteration": int(iteration),
            "elapsed_s": round(time.perf_counter() - self._t0, 6),
            "unix": time.time(),
            "hist_passes": hist_passes,
            "num_leaves": num_leaves,
            "best_gain": best_gain,
            "collective_bytes": sum(
                rec["bytes"] for rec in collectives_snapshot().values()),
            "h2d_bytes": _h2d_bytes(),
            "anomaly": None,
        }
        if extra:
            ev.update(extra)
        if iteration % _MEM_SAMPLE_EVERY == 1:
            ev["device_memory_peak_bytes"] = device_memory_peak()
        with self._lock:
            self._ring.append(ev)

    def note_eval(self, iteration: int, evals) -> None:
        """Attach the iteration's eval results (``(data_name, metric,
        value, higher_is_better)`` tuples) to its event and run anomaly
        detection on the first metric's stream."""
        if not self.enabled or not evals:
            return
        ev_map = {f"{d} {m}": float(v) for d, m, v, *_ in evals}
        loss = float(evals[0][2])
        anomaly = self._check_loss(loss)
        with self._lock:
            target = None
            for ev in reversed(self._ring):
                if ev["iteration"] == int(iteration):
                    target = ev
                    break
            if target is None:        # eval without a recorded iteration
                target = {"iteration": int(iteration),
                          "elapsed_s": round(
                              time.perf_counter() - self._t0, 6),
                          "anomaly": None}
                self._ring.append(target)
            target["evals"] = ev_map
            target["loss"] = loss
            if anomaly is not None:
                target["anomaly"] = anomaly
        if anomaly is not None:
            self._counter.inc(1, kind=anomaly)
            rec = {"iteration": int(iteration), "kind": anomaly,
                   "loss": loss, "ewma": self._loss_ewma}
            with self._lock:
                self.anomalies.append(rec)
            log_warning(f"flight recorder: {anomaly} at iteration "
                        f"{iteration} (loss={loss!r}, "
                        f"ewma={self._loss_ewma})")

    def _check_loss(self, loss: float) -> Optional[str]:
        import math
        if not math.isfinite(loss):
            return "nan_loss"
        ewma = self._loss_ewma
        n = self._loss_n
        self._loss_n = n + 1
        if ewma is None:
            self._loss_ewma = loss
            return None
        kind = None
        if n >= self.min_history and \
                abs(loss) > self.spike_factor * max(abs(ewma), 1e-12):
            kind = "loss_spike"
        self._loss_ewma = 0.8 * ewma + 0.2 * loss
        return kind

    # -- read-out --------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def events(self) -> List[Dict[str, Any]]:
        """Materialized events (device scalars pulled in one batched
        fetch, converted to plain ints/floats)."""
        with self._lock:
            evs = [dict(e) for e in self._ring]
        lazy_keys = ("hist_passes", "num_leaves", "best_gain")
        pend = [(i, k, ev[k]) for i, ev in enumerate(evs)
                for k in lazy_keys if ev.get(k) is not None]
        if pend:
            try:
                import jax
                vals = jax.device_get([p[2] for p in pend])
            except Exception:
                vals = [p[2] for p in pend]
            for (i, k, _), v in zip(pend, vals):
                try:
                    evs[i][k] = float(v) if k == "best_gain" else int(v)
                except (TypeError, ValueError):
                    evs[i][k] = None
        else:
            for ev in evs:
                for k in lazy_keys:
                    ev.setdefault(k, None)
        return evs

    def snapshot(self) -> Dict[str, Any]:
        return {
            "schema": "flight-record-v1",
            "meta": dict(self.meta),
            "capacity": self.capacity,
            "num_events": len(self),
            "anomalies": list(self.anomalies),
            "events": self.events(),
        }

    def dump(self, path: str, reason: str = "") -> str:
        """Write the tape as JSONL (one event per line, a header line
        first) via an atomic write — the crash path must never leave a
        half-written post-mortem."""
        from ..io_utils import atomic_write_bytes
        snap = self.snapshot()
        header = {"schema": snap["schema"], "meta": snap["meta"],
                  "reason": reason, "capacity": snap["capacity"],
                  "num_events": snap["num_events"],
                  "anomalies": snap["anomalies"]}
        lines = [json.dumps(header, default=str)]
        lines.extend(json.dumps(ev, default=str) for ev in snap["events"])
        atomic_write_bytes(path, ("\n".join(lines) + "\n").encode())
        return path
