"""Hierarchical host-side span tracing with chrome-trace export.

``span("tree/wave/psum")`` contexts nest through a thread-local stack,
producing events whose names are full slash paths; each host span also
opens a ``jax.profiler.TraceAnnotation`` so the same names line up with
device rows when a ``jax.profiler.trace`` capture is running (the
``profile`` CLI verb wires both together).

Cost model: when the tracer is disabled (the default) ``span()`` returns
a shared no-op context manager — the entire overhead is one function
call and two attribute reads, so spans can stay compiled into the
boosting loop the way the reference leaves ``FunctionTimer`` timetags
compiled in (common.h:995).  When only ``utils/timer.global_timer`` is
enabled (the ``LGBM_TPU_TIMETAG=1`` compat shim), spans feed the timer's
per-tag accumulators without recording trace events.

Export: ``global_tracer.export_chrome_trace(path)`` writes the
``chrome://tracing`` / Perfetto JSON array format.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List

from ..utils.timer import global_timer

__all__ = ["Tracer", "global_tracer", "span"]


class _NoopSpan:
    """Shared do-nothing context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()
_tls = threading.local()


class Tracer:
    """Collects (path, start, duration, thread) span events."""

    MAX_EVENTS = 1 << 20  # hard cap: a forgotten enable() can't eat RAM

    def __init__(self) -> None:
        self.enabled = os.environ.get("LGBM_TPU_TRACE", "0") == "1"
        self._lock = threading.Lock()
        self._events: List[Dict] = []
        self._t0 = time.perf_counter()
        self._dropped = 0

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._events = []
            self._dropped = 0
            self._t0 = time.perf_counter()

    def _record(self, path: str, start_s: float, dur_s: float,
                tid: int) -> None:
        ev = {"name": path,
              "ts": (start_s - self._t0) * 1e6,   # chrome trace wants us
              "dur": dur_s * 1e6,
              "ph": "X", "pid": os.getpid(), "tid": tid}
        with self._lock:
            if len(self._events) < self.MAX_EVENTS:
                self._events.append(ev)
            else:
                self._dropped += 1

    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._events)

    def export_chrome_trace(self, path: str) -> int:
        """Write the collected spans as chrome-trace JSON; returns the
        event count written."""
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        if dropped:
            doc["metadata"] = {"dropped_events": dropped}
        with open(path, "w") as fh:
            json.dump(doc, fh)
        return len(events)


global_tracer = Tracer()


class _Span:
    """Live span: pushes its name on the thread-local path stack, times
    the region, and mirrors it to the jax profiler + global_timer."""

    __slots__ = ("name", "path", "_trace", "_timer", "_t0", "_jax_scope")

    def __init__(self, name: str, trace_on: bool, timer_on: bool) -> None:
        self.name = name
        self._trace = trace_on
        self._timer = timer_on
        self._jax_scope = None

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        self.path = "/".join(stack + [self.name]) if stack else self.name
        stack.append(self.name)
        if self._timer:
            global_timer.start(self.path)
        if self._trace:
            try:
                import jax.profiler
                self._jax_scope = jax.profiler.TraceAnnotation(self.path)
                self._jax_scope.__enter__()
            except Exception:
                self._jax_scope = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        if self._jax_scope is not None:
            self._jax_scope.__exit__(*exc)
        if self._timer:
            global_timer.stop(self.path)
        if self._trace:
            global_tracer._record(self.path, self._t0, t1 - self._t0,
                                  threading.get_ident())
        stack = getattr(_tls, "stack", None)
        if stack:
            stack.pop()
        return False


def span(name: str):
    """``with span("tree/grow"):`` — nested scope timer/tracer.

    Near-zero overhead when both the tracer and the timetag timer are
    disabled (returns a shared no-op context manager)."""
    trace_on = global_tracer.enabled
    timer_on = global_timer.enabled
    if not (trace_on or timer_on):
        return _NOOP
    return _Span(name, trace_on, timer_on)
