"""Telemetry export: Prometheus text format and JSON renderers.

``render_prometheus`` turns the process-wide :class:`MetricsRegistry`
plus the last training run's :class:`TrainRecord` into the Prometheus
exposition text format (v0.0.4) — the serve HTTP server mounts it at
``GET /metrics``, so one scrape covers serving counters AND the last
training run's per-phase/per-pass numbers.  ``render_json`` is the same
content as one JSON document (the CI telemetry artifact and the
``profile`` CLI verb's dump).

Windowed histograms are exported as percentile gauges
(``<name>_p50``/``_p99``) plus lifetime ``_count``/``_sum`` — the
window is a recent-tail estimator, not a Prometheus bucket histogram,
and exporting it as one would misrepresent it.
"""

from __future__ import annotations

import json
import re
import time
from typing import Dict, List, Optional

from .metrics import MetricsRegistry, default_registry
from .train_record import TrainRecord, last_train_record

__all__ = ["render_prometheus", "render_json", "write_snapshot",
           "PROMETHEUS_CONTENT_TYPE"]

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_PREFIX = "lgbm_tpu_"
_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _name(n: str) -> str:
    return _PREFIX + _NAME_RE.sub("_", n)


def _escape(v) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


def _labels(d: Dict[str, str]) -> str:
    if not d:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"'
                          for k, v in sorted(d.items())) + "}"


def _num(v) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def _render_registry(registry: MetricsRegistry, out: List[str]) -> None:
    for m in registry.collect():
        series = m.series()
        if m.kind in ("counter", "gauge"):
            n = _name(m.name)
            out.append(f"# HELP {n} {m.help or m.name}")
            out.append(f"# TYPE {n} {m.kind}")
            if not series:
                continue
            for lbl, val in series:
                out.append(f"{n}{_labels(lbl)} {_num(val)}")
        else:  # windowed histogram -> percentile gauges + count/sum
            base = _name(m.name)
            out.append(f"# HELP {base} {m.help or m.name} "
                       f"(windowed percentiles)")
            for lbl, summ in series:
                for k, v in summ.items():
                    out.append(f"{base}_{k}{_labels(lbl)} {_num(v)}")


def _render_train_record(snap: Dict, out: List[str]) -> None:
    def line(suffix: str, value, labels: Optional[Dict] = None,
             typ: str = "gauge", help_: str = "") -> None:
        n = _PREFIX + "train_" + suffix
        if help_:
            out.append(f"# HELP {n} {help_}")
            out.append(f"# TYPE {n} {typ}")
        out.append(f"{n}{_labels(labels or {})} {_num(value)}")

    line("trees_total", snap["num_trees"], typ="counter",
         help_="trees grown by the last training run")
    line("hist_passes_total", snap["hist_passes_total"], typ="counter",
         help_="full-data histogram passes (GrownTree.hist_passes sum; "
               "0 = grower does not track)")
    line("hist_passes_last", snap["hist_passes_last"],
         help_="histogram passes of the last grown tree")
    first = True
    for ph, secs in sorted(snap["phase_seconds"].items()):
        line("phase_seconds_total", secs, {"phase": ph}, "counter",
             "wall seconds per boosting phase" if first else "")
        first = False
    first = True
    for site, rec in sorted(snap["collectives_traced"].items()):
        lbl = {"site": site, "op": rec["op"]}
        line("collectives_traced_total", rec["count"], lbl, "counter",
             "collective call sites per traced program (trace-time "
             "tally; matches jaxpr op counts)" if first else "")
        out.append(f"{_PREFIX}train_collectives_traced_bytes_total"
                   f"{_labels(lbl)} {_num(rec['bytes'])}")
        first = False
    first = True
    for ev, cnt in sorted(snap["compile_events"].items()):
        line("compile_events_total", cnt, {"event": ev}, "counter",
             "XLA compile/retrace events (jax.monitoring)" if first
             else "")
        first = False
    if snap.get("device_memory_peak_bytes") is not None:
        line("device_memory_peak_bytes", snap["device_memory_peak_bytes"],
             help_="max device.memory_stats() watermark seen")
    line("elapsed_seconds", snap["elapsed_seconds"],
         help_="wall seconds since the training record was created")


def render_prometheus(registry: Optional[MetricsRegistry] = None,
                      train_record: Optional[TrainRecord] = None) -> str:
    """The /metrics payload: registry series + last TrainRecord."""
    registry = registry if registry is not None else default_registry()
    train_record = (train_record if train_record is not None
                    else last_train_record())
    out: List[str] = []
    _render_registry(registry, out)
    if train_record is not None:
        _render_train_record(train_record.snapshot(), out)
    return "\n".join(out) + "\n"


def render_json(registry: Optional[MetricsRegistry] = None,
                train_record: Optional[TrainRecord] = None) -> Dict:
    registry = registry if registry is not None else default_registry()
    train_record = (train_record if train_record is not None
                    else last_train_record())
    return {
        "schema": "telemetry-snapshot-v1",
        "generated_unix": time.time(),
        "metrics": registry.snapshot(),
        "train_record": (train_record.snapshot()
                         if train_record is not None else None),
    }


def write_snapshot(path: str,
                   registry: Optional[MetricsRegistry] = None,
                   train_record: Optional[TrainRecord] = None) -> None:
    """One JSON telemetry snapshot on disk (CI artifact / profile dump)."""
    with open(path, "w") as fh:
        json.dump(render_json(registry, train_record), fh, indent=2,
                  default=str)
