"""Declarative service-level objectives over the metrics registry.

An :class:`SLO` is declared NEXT TO the code it bounds (the
``analysis/contracts.py`` pattern: the serving stats module declares the
per-bucket p99 latency objective, ``resilience/admission.py`` the shed
budget, ``serve/compiler.py`` the fallback budget, the HTTP server the
availability target) and keyed to an existing
:class:`~lightgbm_tpu.telemetry.metrics.MetricsRegistry` series — so the
objective, the metric it reads and the code that bumps the metric are
one named thing and cannot drift apart.  ``analysis/slo_cover.py``
lint-checks that every declared SLO references a registered series (an
SLO keyed to a metric nobody emits would silently never burn).

Evaluation uses the standard multi-window burn-rate recipe: the error
ratio (bad / total for counter ratios, fraction-over-threshold for
latency windows) is normalized by the error budget ``1 - target`` into
a *burn rate* (1.0 = spending exactly the budget), computed over a fast
and a slow window.  A breach requires BOTH windows to burn hot (the
fast window reacts, the slow window filters blips); a *sustained* fast
burn (``SloEngine.sustain`` consecutive evaluations) flips ``/healthz``
degraded before the slow window confirms.

Counters are lifetime-monotone, so the engine keeps its own sample ring
per SLO — (timestamp, bad, total) pairs appended at every evaluation —
and takes windowed deltas, exactly how a Prometheus ``rate()`` would.
Latency objectives read the existing ``SlidingWindow`` rings (a
recent-tail estimator by construction) and window the *evaluations*:
the fast/slow error ratio is the mean over-threshold fraction of the
scrapes inside each window.
"""

from __future__ import annotations

import fnmatch
import heapq
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

from .metrics import (Gauge, MetricsRegistry, WindowedHistogram,
                      default_registry, percentile)

__all__ = ["SLO", "slo", "slo_for", "all_slos", "remove_slo",
           "register_metric_ensurer", "ensure_metrics", "SloEngine",
           "default_engine", "ExemplarRing"]


@dataclass(frozen=True)
class SLO:
    """One declared objective.

    ``metric`` is the registry series the SLO is keyed to (the coverage
    lint validates it exists); ``kind`` is ``"ratio"`` (bad events over
    a total, both counters), ``"latency"`` (a windowed histogram whose
    observations must stay under ``threshold_ms``), or ``"gauge_floor"``
    (a gauge that must stay at or above ``floor`` — the fleet
    supervision kind: every evaluation with ANY matching series below
    the floor spends budget, so "no worker alive" burns exactly like
    "every request 5xx"), or ``"gauge_ceiling"`` (the mirror image: a
    gauge that must stay at or below ``ceiling`` — the staleness kind:
    any worker's rounds-behind gauge above the ceiling spends budget).
    ``target`` is the good fraction (0.999
    availability = 0.1% error budget).  For ratio SLOs ``bad_labels``
    selects the bad series of ``metric`` (label values may be fnmatch
    patterns: ``{"code": "5*"}``) and ``total_metric`` names the
    denominator counter.  For latency SLOs every label combination of
    the histogram (e.g. each shape bucket) is evaluated independently —
    one declaration covers the ladder.  Gauge-floor SLOs have a per
    scrape error of 0 or 1, so declare them with a wide budget and low
    burn thresholds (e.g. ``target=0.5, burn_fast=1.9``: a breach means
    essentially EVERY fast-window scrape saw the gauge under its
    floor)."""

    name: str
    metric: str
    kind: str        # "ratio" | "latency" | "gauge_floor" | "gauge_ceiling"
    target: float
    threshold_ms: float = 0.0        # latency kind only
    floor: float = 0.0               # gauge_floor kind only
    ceiling: float = 0.0             # gauge_ceiling kind only
    total_metric: str = ""           # ratio kind denominator
    bad_labels: Mapping[str, str] = field(default_factory=dict)
    labels: Mapping[str, str] = field(default_factory=dict)
    window_fast_s: float = 300.0
    window_slow_s: float = 3600.0
    burn_fast: float = 14.4          # classic page-at thresholds
    burn_slow: float = 6.0
    min_events: float = 0.0          # ratio kind: below this many total
    #                                  events in a window the burn is 0
    #                                  (a 1-in-10 blip on a near-idle
    #                                  tier is noise, not a breach)
    declared_in: str = ""
    note: str = ""

    @property
    def budget(self) -> float:
        return max(1e-9, 1.0 - float(self.target))


_lock = threading.Lock()
_slos: Dict[str, SLO] = {}


def slo(name: str, *, metric: str, kind: str, target: float,
        threshold_ms: float = 0.0, floor: float = 0.0,
        ceiling: float = 0.0, total_metric: str = "",
        bad_labels: Optional[Mapping[str, str]] = None,
        labels: Optional[Mapping[str, str]] = None,
        window_fast_s: float = 300.0, window_slow_s: float = 3600.0,
        burn_fast: float = 14.4, burn_slow: float = 6.0,
        min_events: float = 0.0, note: str = "") -> SLO:
    """Declare (or redeclare) one objective.  Call at module scope next
    to the code whose behavior it bounds; ``declared_in`` records that
    module for diagnostics (the contracts.py convention)."""
    import inspect
    frame = inspect.currentframe()
    declared_in = ""
    if frame is not None and frame.f_back is not None:
        declared_in = frame.f_back.f_globals.get("__name__", "")
    if kind not in ("ratio", "latency", "gauge_floor", "gauge_ceiling"):
        raise ValueError(f"SLO kind must be ratio|latency|gauge_floor|"
                         f"gauge_ceiling, got {kind!r}")
    s = SLO(name=name, metric=metric, kind=kind, target=float(target),
            threshold_ms=float(threshold_ms), floor=float(floor),
            ceiling=float(ceiling), total_metric=total_metric,
            bad_labels=dict(bad_labels or {}), labels=dict(labels or {}),
            window_fast_s=float(window_fast_s),
            window_slow_s=float(window_slow_s),
            burn_fast=float(burn_fast), burn_slow=float(burn_slow),
            min_events=float(min_events),
            declared_in=declared_in, note=note)
    with _lock:
        _slos[name] = s
    return s


def slo_for(name: str) -> Optional[SLO]:
    with _lock:
        return _slos.get(name)


def all_slos() -> Dict[str, SLO]:
    with _lock:
        return dict(_slos)


def remove_slo(name: str) -> None:
    """Unregister (tests planting temporary SLOs clean up here)."""
    with _lock:
        _slos.pop(name, None)


def set_latency_threshold(name: str, threshold_ms: float) -> SLO:
    """Re-declare a latency SLO's threshold in place (the load-test
    harness tunes the declared objective to the environment under
    test without forking the declaration site)."""
    with _lock:
        cur = _slos.get(name)
        if cur is None:
            raise KeyError(f"no SLO named {name!r}")
        s = replace(cur, threshold_ms=float(threshold_ms))
        _slos[name] = s
    return s


# ---------------------------------------------------------------------------
# Metric ensurers: subsystems register a callable that creates their
# metric families (no series) in a registry, so the coverage lint can
# validate SLO->series keys statically, before any traffic exists.
# ---------------------------------------------------------------------------

_ensurers: List[Callable[[MetricsRegistry], None]] = []


def register_metric_ensurer(fn: Callable[[MetricsRegistry], None]
                            ) -> Callable[[MetricsRegistry], None]:
    with _lock:
        if fn not in _ensurers:
            _ensurers.append(fn)
    return fn


def ensure_metrics(registry: Optional[MetricsRegistry] = None) -> None:
    registry = registry if registry is not None else default_registry()
    with _lock:
        fns = list(_ensurers)
    for fn in fns:
        fn(registry)


# ---------------------------------------------------------------------------
# Exemplar ring: bounded slowest-N requests, dumped alongside breaches
# ---------------------------------------------------------------------------

class ExemplarRing:
    """Keep the N worst exemplars by a score (request latency): a p99
    regression comes with the offending requests attached instead of a
    bare number.  Thread-safe; bounded by a min-heap so steady-state
    cost is O(log N) per offer and memory is N dicts."""

    def __init__(self, capacity: int = 32) -> None:
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._seq = 0                       # heap tie-break, never compared
        self._heap: List[Tuple[float, int, Dict[str, Any]]] = []

    def would_accept(self, score: float) -> bool:
        """Cheap hot-path pre-check: only a score that would survive the
        heap is worth building an exemplar dict for (the serving path
        calls this per request; >99% of requests are not among the N
        slowest)."""
        heap = self._heap           # unlocked snapshot: a stale read can
        #                             only cause one extra offer, never
        #                             a missed one the lock would accept
        return len(heap) < self.capacity or score > heap[0][0]

    def offer(self, score: float, exemplar: Dict[str, Any]) -> None:
        with self._lock:
            self._seq += 1
            item = (float(score), self._seq, dict(exemplar))
            if len(self._heap) < self.capacity:
                heapq.heappush(self._heap, item)
            elif item[0] > self._heap[0][0]:
                heapq.heapreplace(self._heap, item)

    def snapshot(self) -> List[Dict[str, Any]]:
        """Exemplars worst-first."""
        with self._lock:
            items = sorted(self._heap, key=lambda it: -it[0])
        return [dict(e, score=s) for s, _, e in items]

    def clear(self) -> None:
        with self._lock:
            self._heap = []

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------

def _labels_match(series_labels: Mapping[str, str],
                  selector: Mapping[str, str]) -> bool:
    """Subset match; selector values are fnmatch patterns."""
    for k, pat in selector.items():
        v = series_labels.get(k)
        if v is None or not fnmatch.fnmatchcase(str(v), str(pat)):
            return False
    return True


class SloEngine:
    """Evaluates every declared SLO against one registry.

    ``evaluate()`` appends one sample per SLO and returns the verdict
    report; it is called from the ``/slo`` and ``/healthz`` handlers
    (and by the load-test harness between scrapes), so evaluation
    cadence == scrape cadence, which is exactly the cadence the sample
    rings window over.  Burn-rate gauges land back in the registry so a
    plain ``/metrics`` scrape carries them too."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 sustain: int = 3,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.registry = registry if registry is not None \
            else default_registry()
        self.sustain = int(sustain)
        self._clock = clock
        self._lock = threading.Lock()
        # name -> list[(t, bad, total)] (ratio) / [(t, frac_over)] (latency)
        self._samples: Dict[str, List[tuple]] = {}
        # name -> pooled lifetime observation count at the last
        # evaluation (latency kinds' live-vs-stale-window detector)
        self._latency_counts: Dict[str, int] = {}
        self._fast_streak: Dict[str, int] = {}
        self._last_report: Optional[Dict[str, Any]] = None

    # -- metric reads ------------------------------------------------------
    def _counter_sum(self, name: str, selector: Mapping[str, str]) -> float:
        m = self.registry.get(name)
        if m is None:
            return 0.0
        total = 0.0
        for lbl, val in m.series():
            if _labels_match(lbl, selector) and isinstance(val, (int, float)):
                total += float(val)
        return total

    def _latency_series(self, s: SLO) -> List[Tuple[Dict[str, str],
                                                    List[float], int]]:
        """(labels, window values, lifetime observation count) per
        matching series — the count lets the evaluator tell a live
        window from a stale one."""
        m = self.registry.get(s.metric)
        if not isinstance(m, WindowedHistogram):
            return []
        out = []
        for lbl, summ in m.series():
            if _labels_match(lbl, s.labels):
                count = summ.get("count", 0) if isinstance(summ, dict) else 0
                out.append((lbl, m.values_of(**lbl), int(count)))
        return out

    # -- window math -------------------------------------------------------
    @staticmethod
    def _trim(samples: List[tuple], now: float, keep_s: float) -> None:
        cutoff = now - keep_s
        while len(samples) > 2 and samples[1][0] <= cutoff:
            samples.pop(0)

    @staticmethod
    def _ratio_over(samples: List[tuple], now: float, window: float
                    ) -> Tuple[float, float, float]:
        """(error_ratio, d_bad, d_total) across the samples inside the
        window (oldest in-window sample vs the newest).  No traffic
        DELTA in the window -> zero burn: an idle service spends no
        budget, and the engine's very first sample deliberately judges
        nothing — falling back to the counters' lifetime ratio there
        would page on arbitrarily stale history (a startup burst hours
        ago) the moment a fresh engine takes its first scrape."""
        inside = [s for s in samples if s[0] >= now - window]
        if not inside:
            inside = samples[-1:]
        base = inside[0]
        cur = samples[-1]
        d_bad = cur[1] - base[1]
        d_total = cur[2] - base[2]
        if d_total <= 0:
            return 0.0, max(0.0, d_bad), max(0.0, d_total)
        return max(0.0, d_bad) / d_total, d_bad, d_total

    @staticmethod
    def _latency_over(samples: List[tuple], now: float, window: float
                      ) -> float:
        inside = [s for s in samples if s[0] >= now - window]
        if not inside:
            inside = samples[-1:]
        if not inside:
            return 0.0
        return sum(s[1] for s in inside) / len(inside)

    # -- evaluation --------------------------------------------------------
    def _eval_ratio(self, s: SLO, now: float) -> Dict[str, Any]:
        bad_sel = dict(s.labels)
        bad_sel.update(s.bad_labels)
        bad = self._counter_sum(s.metric, bad_sel)
        total = self._counter_sum(s.total_metric or s.metric, s.labels)
        ring = self._samples.setdefault(s.name, [])
        ring.append((now, bad, total))
        self._trim(ring, now, s.window_slow_s * 1.25)
        rf, dbf, dtf = self._ratio_over(ring, now, s.window_fast_s)
        rs, dbs, dts = self._ratio_over(ring, now, s.window_slow_s)
        low_traffic = False
        if s.min_events > 0:
            # below the traffic floor a window has no statistical power:
            # one bad event on a near-idle tier must not page anyone
            if dtf < s.min_events:
                rf, low_traffic = 0.0, True
            if dts < s.min_events:
                rs, low_traffic = 0.0, True
        return {"error_ratio": {"fast": rf, "slow": rs},
                "burn": {"fast": rf / s.budget, "slow": rs / s.budget},
                "detail": {"bad": bad, "total": total,
                           "window_bad": dbf, "window_total": dtf,
                           "low_traffic": low_traffic}}

    def _eval_latency(self, s: SLO, now: float) -> Dict[str, Any]:
        series = self._latency_series(s)
        per_series = []
        worst_frac = 0.0
        pooled_n = 0
        total_count = 0
        for lbl, vals, count in series:
            total_count += count
            if not vals:
                continue
            over = sum(1 for v in vals if v > s.threshold_ms)
            frac = over / len(vals)
            # the traffic floor, latency edition: a window of one slow
            # request is frac_over=1.0 — below min_events a series is
            # reported but never drives the burn (the ratio kinds'
            # near-idle-blip rule)
            if not (s.min_events > 0 and len(vals) < s.min_events):
                worst_frac = max(worst_frac, frac)
            pooled_n += len(vals)
            per_series.append({"labels": lbl,
                               "p50_ms": round(percentile(vals, 50.0), 4),
                               "p99_ms": round(percentile(vals, 99.0), 4),
                               "frac_over": round(frac, 6),
                               "window": len(vals)})
        # the histogram windows are count-bounded, not time-bounded: a
        # hot window from a past burst would otherwise re-read hot on
        # every scrape and keep the burn lit with ZERO live traffic.
        # No new observations since the last evaluation -> this scrape
        # contributes no burn, and the windowed mean decays as idle
        # scrapes accumulate (the latency twin of the ratio kinds'
        # no-traffic-no-burn rule).
        last_count = self._latency_counts.get(s.name)
        self._latency_counts[s.name] = total_count
        idle = last_count is not None and total_count <= last_count
        ring = self._samples.setdefault(s.name, [])
        ring.append((now, 0.0 if idle else worst_frac))
        self._trim(ring, now, s.window_slow_s * 1.25)
        rf = self._latency_over(ring, now, s.window_fast_s)
        rs = self._latency_over(ring, now, s.window_slow_s)
        return {"error_ratio": {"fast": rf, "slow": rs},
                "burn": {"fast": rf / s.budget, "slow": rs / s.budget},
                "detail": {"threshold_ms": s.threshold_ms,
                           "observations": pooled_n,
                           "series": per_series}}

    def _eval_gauge_floor(self, s: SLO, now: float) -> Dict[str, Any]:
        """Per-scrape binary error: 1.0 while any matching gauge series
        sits below the declared floor, 0.0 otherwise.  No series yet ->
        no data -> no burn (the tier hasn't reported; a fleet booting
        must not page before its first supervision tick), exactly the
        ratio kinds' idle rule."""
        m = self.registry.get(s.metric)
        values: List[float] = []
        if isinstance(m, Gauge):
            for lbl, val in m.series():
                if _labels_match(lbl, s.labels) and \
                        isinstance(val, (int, float)):
                    values.append(float(val))
        frac = 1.0 if values and min(values) < s.floor else 0.0
        ring = self._samples.setdefault(s.name, [])
        ring.append((now, frac))
        self._trim(ring, now, s.window_slow_s * 1.25)
        rf = self._latency_over(ring, now, s.window_fast_s)
        rs = self._latency_over(ring, now, s.window_slow_s)
        return {"error_ratio": {"fast": rf, "slow": rs},
                "burn": {"fast": rf / s.budget, "slow": rs / s.budget},
                "detail": {"floor": s.floor,
                           "value": min(values) if values else None,
                           "series": len(values)}}

    def _eval_gauge_ceiling(self, s: SLO, now: float) -> Dict[str, Any]:
        """Per-scrape binary error: 1.0 while any matching gauge series
        sits ABOVE the declared ceiling (the staleness mirror of
        :meth:`_eval_gauge_floor`; same no-series -> no-burn idle
        rule — a fleet that has not measured staleness yet must not
        page)."""
        m = self.registry.get(s.metric)
        values: List[float] = []
        if isinstance(m, Gauge):
            for lbl, val in m.series():
                if _labels_match(lbl, s.labels) and \
                        isinstance(val, (int, float)):
                    values.append(float(val))
        frac = 1.0 if values and max(values) > s.ceiling else 0.0
        ring = self._samples.setdefault(s.name, [])
        ring.append((now, frac))
        self._trim(ring, now, s.window_slow_s * 1.25)
        rf = self._latency_over(ring, now, s.window_fast_s)
        rs = self._latency_over(ring, now, s.window_slow_s)
        return {"error_ratio": {"fast": rf, "slow": rs},
                "burn": {"fast": rf / s.budget, "slow": rs / s.budget},
                "detail": {"ceiling": s.ceiling,
                           "value": max(values) if values else None,
                           "series": len(values)}}

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = self._clock() if now is None else float(now)
        burn_g = self.registry.gauge(
            "slo_burn_rate", "error-budget burn rate per declared SLO "
            "(1.0 = spending exactly the budget)", labels=("slo", "window"))
        ok_g = self.registry.gauge(
            "slo_ok", "1 while the SLO is met (no multi-window breach)",
            labels=("slo",))
        verdicts = []
        breached, fast_burning, degraded = [], [], []
        with self._lock:
            for name, s in sorted(all_slos().items()):
                if s.kind == "ratio":
                    ev = self._eval_ratio(s, now)
                elif s.kind == "gauge_floor":
                    ev = self._eval_gauge_floor(s, now)
                elif s.kind == "gauge_ceiling":
                    ev = self._eval_gauge_ceiling(s, now)
                else:
                    ev = self._eval_latency(s, now)
                bf, bs = ev["burn"]["fast"], ev["burn"]["slow"]
                is_fast = bf >= s.burn_fast
                is_breach = is_fast and bs >= s.burn_slow
                streak = self._fast_streak.get(name, 0) + 1 if is_fast else 0
                self._fast_streak[name] = streak
                if is_breach:
                    breached.append(name)
                if is_fast:
                    fast_burning.append(name)
                if streak >= self.sustain:
                    degraded.append(name)
                burn_g.set(bf, slo=name, window="fast")
                burn_g.set(bs, slo=name, window="slow")
                ok_g.set(0.0 if is_breach else 1.0, slo=name)
                verdicts.append({
                    "name": name, "metric": s.metric, "kind": s.kind,
                    "target": s.target, "budget": s.budget,
                    "declared_in": s.declared_in,
                    "burn": {"fast": round(bf, 4), "slow": round(bs, 4)},
                    "burn_thresholds": {"fast": s.burn_fast,
                                        "slow": s.burn_slow},
                    "error_ratio": {k: round(v, 6) for k, v in
                                    ev["error_ratio"].items()},
                    "fast_burning": is_fast,
                    "fast_streak": streak,
                    "breached": is_breach,
                    "ok": not is_breach,
                    "detail": ev["detail"],
                })
            report = {
                "schema": "slo-report-v1",
                "ok": not breached,
                "breached": breached,
                "fast_burning": fast_burning,
                "degraded": degraded,
                "sustain": self.sustain,
                "slos": verdicts,
            }
            self._last_report = report
        return report

    def degraded(self) -> List[str]:
        """SLO names whose fast window has burned hot for ``sustain``
        consecutive evaluations (the /healthz degraded reason)."""
        with self._lock:
            return [n for n, k in self._fast_streak.items()
                    if k >= self.sustain]

    def last_report(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._last_report

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
            self._latency_counts.clear()
            self._fast_streak.clear()
            self._last_report = None


_default_engine: Optional[SloEngine] = None
_engine_lock = threading.Lock()


def default_engine() -> SloEngine:
    """The process-wide engine over the default registry (the serve
    HTTP server's ``/slo`` and ``/healthz`` evaluate through it)."""
    global _default_engine
    with _engine_lock:
        if _default_engine is None:
            _default_engine = SloEngine()
        return _default_engine
