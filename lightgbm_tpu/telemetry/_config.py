"""Telemetry master switch (kept in its own leaf module so metrics/
trace/train_record can read it without import cycles).

Default ON — accumulation is cheap host-side bookkeeping and purely
observational (bit-identical training is a tested contract).  Disable
with ``LGBM_TPU_TELEMETRY=0`` or ``lightgbm_tpu.telemetry.disable()``;
the span TRACER and the timetag timer stay separately opt-in."""

from __future__ import annotations

import os

_enabled = os.environ.get("LGBM_TPU_TELEMETRY", "1") != "0"


def enabled() -> bool:
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False
