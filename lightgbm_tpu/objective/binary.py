"""Binary log-loss objective (reference src/objective/binary_objective.hpp:
gradients at :105-133, unbalance label weights at :90-102, BoostFromScore at
:139-159)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .base import EPS, ObjectiveFunction, weighted_mean


class BinaryLogloss(ObjectiveFunction):
    name = "binary"

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        self.is_unbalance = bool(config.is_unbalance)
        self.scale_pos_weight = float(config.scale_pos_weight)
        if self.is_unbalance and self.scale_pos_weight != 1.0:
            raise ValueError("cannot set both is_unbalance and scale_pos_weight")

    def check_label(self, label):
        u = np.unique(label)
        if not np.all(np.isin(u, [0.0, 1.0])):
            raise ValueError("binary objective requires labels in {0, 1}")

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.asarray(metadata.label)
        cnt_pos = float((lab > 0).sum())
        cnt_neg = float((lab <= 0).sum())
        w0 = w1 = 1.0
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w0 = cnt_pos / cnt_neg
            else:
                w1 = cnt_neg / cnt_pos
        w1 *= self.scale_pos_weight
        self.label_weight = (w0, w1)

    def get_gradients(self, score):
        y = self.label
        sig = self.sigmoid
        w0, w1 = self.label_weight
        p = 1.0 / (1.0 + jnp.exp(-sig * score))
        lw = jnp.where(y > 0, w1, w0)
        grad = sig * (p - y) * lw
        hess = sig * sig * p * (1.0 - p) * lw
        if self.weight is not None:
            grad = grad * self.weight
            hess = hess * self.weight
        return grad.astype(jnp.float32), hess.astype(jnp.float32)

    def boost_from_score(self, class_id: int = 0) -> float:
        lab = np.asarray(self.label)
        w = None if self.weight is None else np.asarray(self.weight)
        pavg = weighted_mean(lab, w)
        pavg = min(max(pavg, EPS), 1.0 - EPS)
        return float(np.log(pavg / (1.0 - pavg)) / self.sigmoid)

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * score))
