"""Cross-entropy objectives for probabilistic labels in [0, 1]
(reference src/objective/xentropy_objective.hpp: CrossEntropy gradients at
:82-92, CrossEntropyLambda weighted parameterization at :195-216, init scores
at :134/:262)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .base import EPS, ObjectiveFunction, weighted_mean


class CrossEntropy(ObjectiveFunction):
    name = "cross_entropy"

    def check_label(self, label):
        if (label < 0).any() or (label > 1).any():
            raise ValueError("cross_entropy labels must be in [0, 1]")

    def get_gradients(self, score):
        z = 1.0 / (1.0 + jnp.exp(-score))
        grad = z - self.label
        hess = z * (1.0 - z)
        if self.weight is not None:
            grad = grad * self.weight
            hess = hess * self.weight
        return grad.astype(jnp.float32), hess.astype(jnp.float32)

    def boost_from_score(self, class_id: int = 0) -> float:
        pavg = weighted_mean(np.asarray(self.label), self._np_weight())
        pavg = min(max(pavg, EPS), 1.0 - EPS)
        return float(np.log(pavg / (1.0 - pavg)))

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-score))


class CrossEntropyLambda(ObjectiveFunction):
    name = "cross_entropy_lambda"

    def check_label(self, label):
        if (label < 0).any() or (label > 1).any():
            raise ValueError("cross_entropy_lambda labels must be in [0, 1]")

    def get_gradients(self, score):
        y = self.label
        if self.weight is None:
            z = 1.0 / (1.0 + jnp.exp(-score))
            grad = z - y
            hess = z * (1.0 - z)
        else:
            w = self.weight
            epf = jnp.exp(score)
            hhat = jnp.log1p(epf)
            z = 1.0 - jnp.exp(-w * hhat)
            enf = 1.0 / epf
            grad = (1.0 - y / jnp.maximum(z, EPS)) * w / (1.0 + enf)
            c = 1.0 / jnp.maximum(1.0 - z, EPS)
            d = 1.0 + epf
            a = w * epf / (d * d)
            d2 = jnp.maximum(c - 1.0, EPS)
            b = (c / (d2 * d2)) * (1.0 + w * epf - c)
            hess = a * (1.0 + y * b)
        return grad.astype(jnp.float32), hess.astype(jnp.float32)

    def boost_from_score(self, class_id: int = 0) -> float:
        # havg = weighted mean label; initscore = log(exp(havg) - 1)
        # (xentropy_objective.hpp:262)
        havg = weighted_mean(np.asarray(self.label), self._np_weight())
        return float(np.log(max(np.exp(havg) - 1.0, EPS)))

    def convert_output(self, score):
        # output is the exponential parameter lambda (xentropy_objective.hpp:234)
        return jnp.log1p(jnp.exp(score))
