"""Objective functions: gradients/hessians as device-resident jnp math.

TPU-native re-implementation of the reference objective layer
(reference: include/LightGBM/objective_function.h:19 ``ObjectiveFunction``
interface — ``GetGradients`` at :37 — and the factory
``CreateObjectiveFunction`` in src/objective/objective_function.cpp:15).

All 16 objectives are supported.  Where the reference iterates rows with
OpenMP, the math here is one fused elementwise jnp expression under jit
(VPU-bound on TPU); ranking objectives vectorize per-query loops via padded
(query, doc) tensors and vmap.
"""

from __future__ import annotations

from typing import Optional

from ..config import Config
from .base import ObjectiveFunction
from .regression import (RegressionL2, RegressionL1, Huber, Fair, Poisson,
                         Quantile, Mape, Gamma, Tweedie)
from .binary import BinaryLogloss
from .multiclass import MulticlassSoftmax, MulticlassOVA
from .xentropy import CrossEntropy, CrossEntropyLambda
from .rank import LambdarankNDCG, RankXENDCG

__all__ = ["create_objective", "ObjectiveFunction"]

_REGISTRY = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": Huber,
    "fair": Fair,
    "poisson": Poisson,
    "quantile": Quantile,
    "mape": Mape,
    "gamma": Gamma,
    "tweedie": Tweedie,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
    "rank_xendcg": RankXENDCG,
}


def create_objective(name: str, config: Config) -> Optional[ObjectiveFunction]:
    """Factory (reference src/objective/objective_function.cpp:15).
    Returns None for objective='none' (custom objective supplies gradients
    directly, reference boosting.h:85 TrainOneIter(grad, hess))."""
    if name in ("none", None, ""):
        return None
    if name not in _REGISTRY:
        raise ValueError(f"Unknown objective: {name}. "
                         f"Known: {sorted(_REGISTRY)}")
    return _REGISTRY[name](config)
