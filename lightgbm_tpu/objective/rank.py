"""Learning-to-rank objectives (reference src/objective/rank_objective.hpp:
``RankingObjective`` base parallelizing per query at :25-67, LambdarankNDCG
pairwise lambdas at :140-227, RankXENDCG at :284-352).

The reference loops documents per query with OpenMP; here queries are padded
to a common length M and the per-query pairwise computation is a vmapped
(M, M) tensor expression, chunked over queries with ``lax.map`` to bound the
pairwise memory.  The sigmoid lookup table (rank_objective.hpp:249) is
unnecessary — the VPU computes exact sigmoids faster than a gather."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .base import EPS, ObjectiveFunction

KMIN_SCORE = -1e30


def pad_queries(query_boundaries: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Build (Q, M) flat-index + validity tensors from query boundaries."""
    sizes = np.diff(query_boundaries)
    q = len(sizes)
    m = int(sizes.max()) if q else 1
    # round M up to a lane-friendly multiple
    m = int(np.ceil(m / 8) * 8)
    idx = np.zeros((q, m), dtype=np.int32)
    valid = np.zeros((q, m), dtype=bool)
    for i in range(q):
        s, e = query_boundaries[i], query_boundaries[i + 1]
        idx[i, : e - s] = np.arange(s, e)
        valid[i, : e - s] = True
    return idx, valid


class RankingObjective(ObjectiveFunction):
    need_group = True

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError(f"objective {self.name} requires query/group data")
        self.query_boundaries = metadata.query_boundaries
        idx, valid = pad_queries(self.query_boundaries)
        self.q_idx = jnp.asarray(idx)
        self.q_valid = jnp.asarray(valid)
        self.num_queries = idx.shape[0]
        # chunk queries so the (chunk, M, M) pairwise tensor stays ~64MB
        m = idx.shape[1]
        self.q_chunk = max(1, min(self.num_queries, int((16 << 20) / max(1, m * m))))


class LambdarankNDCG(RankingObjective):
    name = "lambdarank"

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        self.norm = bool(config.lambdarank_norm)
        self.truncation_level = int(config.lambdarank_truncation_level)
        self.label_gain = np.asarray(config.label_gain, dtype=np.float64)

    def check_label(self, label):
        if (label < 0).any():
            raise ValueError("ranking labels must be non-negative integers")
        if int(label.max()) >= len(self.label_gain):
            raise ValueError("label exceeds label_gain size")

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        # inverse max DCG per query at the truncation level
        # (rank_objective.hpp:121-135 via dcg_calculator.cpp CalMaxDCGAtK)
        lab = np.asarray(metadata.label)
        qb = self.query_boundaries
        inv = np.zeros(self.num_queries)
        for i in range(self.num_queries):
            ql = np.sort(lab[qb[i]:qb[i + 1]])[::-1][:self.truncation_level]
            gains = self.label_gain[ql.astype(np.int32)]
            disc = 1.0 / np.log2(np.arange(len(ql)) + 2.0)
            mdcg = float((gains * disc).sum())
            inv[i] = 1.0 / mdcg if mdcg > 0 else 0.0
        self.inverse_max_dcg = jnp.asarray(inv, jnp.float32)
        self.label_gain_dev = jnp.asarray(self.label_gain, jnp.float32)
        self._grad_fn = self._build_grad_fn()

    def _build_grad_fn(self):
        sig = self.sigmoid
        trunc = self.truncation_level
        norm = self.norm
        m = int(self.q_idx.shape[1])

        def one_query(s, lab, valid, inv_max_dcg):
            # sort docs by score desc (stable); padding scores are KMIN_SCORE
            s_in = jnp.where(valid, s, KMIN_SCORE)
            order = jnp.argsort(-s_in, stable=True)
            ss = s_in[order]
            sl = lab[order]
            sv = valid[order]
            gains = self.label_gain_dev[jnp.clip(sl.astype(jnp.int32), 0, None)]
            ranks = jnp.arange(m)
            disc = 1.0 / jnp.log2(ranks + 2.0)
            n_valid = jnp.sum(sv.astype(jnp.int32))
            best = ss[0]
            worst = ss[jnp.maximum(n_valid - 1, 0)]

            iu = ranks[:, None]
            ju = ranks[None, :]
            pair = ((iu < ju) & sv[:, None] & sv[None, :] &
                    (sl[:, None] != sl[None, :]) & (iu < trunc))
            hi_is_i = sl[:, None] > sl[None, :]
            s_hi = jnp.where(hi_is_i, ss[:, None], ss[None, :])
            s_lo = jnp.where(hi_is_i, ss[None, :], ss[:, None])
            delta_score = s_hi - s_lo
            dcg_gap = jnp.abs(gains[:, None] - gains[None, :])
            paired_disc = jnp.abs(disc[:, None] - disc[None, :])
            delta_ndcg = dcg_gap * paired_disc * inv_max_dcg
            if norm:
                delta_ndcg = jnp.where(best != worst,
                                       delta_ndcg / (0.01 + jnp.abs(delta_score)),
                                       delta_ndcg)
            p = 1.0 / (1.0 + jnp.exp(sig * delta_score))
            p_lambda = -sig * delta_ndcg * p
            p_hess = sig * sig * delta_ndcg * p * (1.0 - p)
            p_lambda = jnp.where(pair, p_lambda, 0.0)
            p_hess = jnp.where(pair, p_hess, 0.0)

            sign = jnp.where(hi_is_i, 1.0, -1.0)
            contrib = sign * p_lambda
            lam_sorted = jnp.sum(contrib, axis=1) - jnp.sum(contrib, axis=0)
            hess_sorted = jnp.sum(p_hess, axis=1) + jnp.sum(p_hess, axis=0)
            sum_lambdas = -2.0 * jnp.sum(p_lambda)
            if norm:
                factor = jnp.where(sum_lambdas > 0,
                                   jnp.log2(1.0 + sum_lambdas) / jnp.maximum(
                                       sum_lambdas, EPS), 1.0)
                lam_sorted = lam_sorted * factor
                hess_sorted = hess_sorted * factor
            # unsort back to query-document order
            inv_order = jnp.argsort(order, stable=True)
            return lam_sorted[inv_order], hess_sorted[inv_order]

        vq = jax.vmap(one_query)

        @jax.jit
        def grad_fn(score, label, q_idx, q_valid, inv_max_dcg):
            n = score.shape[0]
            q, mm = q_idx.shape
            chunk = self.q_chunk
            nchunks = -(-q // chunk)
            padq = nchunks * chunk - q
            qi = jnp.pad(q_idx, ((0, padq), (0, 0)))
            qv = jnp.pad(q_valid, ((0, padq), (0, 0)))
            qd = jnp.pad(inv_max_dcg, (0, padq))
            s_g = score[qi]
            l_g = label[qi]

            def do_chunk(args):
                s, l, v, d = args
                return vq(s, l, v, d)

            lam, hes = jax.lax.map(do_chunk, (
                s_g.reshape(nchunks, chunk, mm),
                l_g.reshape(nchunks, chunk, mm),
                qv.reshape(nchunks, chunk, mm),
                qd.reshape(nchunks, chunk)))
            lam = lam.reshape(-1, mm)[:q]
            hes = hes.reshape(-1, mm)[:q]
            v = q_valid
            grad = jnp.zeros((n,), jnp.float32).at[q_idx.reshape(-1)].add(
                jnp.where(v, lam, 0.0).reshape(-1), mode="drop")
            hess = jnp.zeros((n,), jnp.float32).at[q_idx.reshape(-1)].add(
                jnp.where(v, hes, 0.0).reshape(-1), mode="drop")
            return grad, hess

        return grad_fn

    def get_gradients(self, score):
        return self._grad_fn(score, self.label, self.q_idx, self.q_valid,
                             self.inverse_max_dcg)


class RankXENDCG(RankingObjective):
    name = "rank_xendcg"

    def __init__(self, config):
        super().__init__(config)
        self.seed = int(config.objective_seed)
        self._iter = 0

    def check_label(self, label):
        if (label < 0).any():
            raise ValueError("ranking labels must be non-negative integers")

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self._grad_fn = self._build_grad_fn()

    def _build_grad_fn(self):
        @jax.jit
        def grad_fn(score, label, q_idx, q_valid, key):
            n = score.shape[0]
            q, m = q_idx.shape
            s = jnp.where(q_valid, score[q_idx], KMIN_SCORE)
            lab = label[q_idx]
            gammas = jax.random.uniform(key, (q, m))

            # per-query softmax over valid docs
            smax = jnp.max(s, axis=1, keepdims=True)
            es = jnp.where(q_valid, jnp.exp(s - smax), 0.0)
            rho = es / jnp.maximum(jnp.sum(es, axis=1, keepdims=True), EPS)

            phi = jnp.where(q_valid, jnp.exp2(jnp.floor(lab)) - gammas, 0.0)
            inv_den = 1.0 / jnp.maximum(jnp.sum(phi, axis=1, keepdims=True), EPS)

            # first-order terms (rank_objective.hpp:330-338)
            t1 = -phi * inv_den + rho
            params1 = jnp.where(q_valid, t1 / jnp.maximum(1.0 - rho, EPS), 0.0)
            sum_l1 = jnp.sum(params1, axis=1, keepdims=True)
            # second-order
            t2 = rho * (sum_l1 - params1)
            params2 = jnp.where(q_valid, t2 / jnp.maximum(1.0 - rho, EPS), 0.0)
            sum_l2 = jnp.sum(params2, axis=1, keepdims=True)
            lam = t1 + t2 + rho * (sum_l2 - params2)
            hess = rho * (1.0 - rho)
            # queries with <2 docs get zero gradients
            few = (jnp.sum(q_valid, axis=1, keepdims=True) <= 1)
            lam = jnp.where(few | ~q_valid, 0.0, lam)
            hess = jnp.where(few | ~q_valid, 0.0, hess)

            grad = jnp.zeros((n,), jnp.float32).at[q_idx.reshape(-1)].add(
                lam.reshape(-1), mode="drop")
            hs = jnp.zeros((n,), jnp.float32).at[q_idx.reshape(-1)].add(
                hess.reshape(-1), mode="drop")
            return grad, hs

        return grad_fn

    def get_gradients(self, score):
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), self._iter)
        self._iter += 1
        return self._grad_fn(score, self.label, self.q_idx, self.q_valid, key)
