"""Objective base class (reference include/LightGBM/objective_function.h:19)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..dataset import Metadata

EPS = 1e-15


class ObjectiveFunction:
    """Base: holds device copies of label/weight and exposes gradient math.

    Subclasses implement ``_grad_hess(score) -> (grad, hess)`` over device
    arrays; scores and gradients are (N,) float32, or (N, K) for multiclass.
    """

    name = "base"
    is_constant_hessian = False
    need_group = False

    def __init__(self, config) -> None:
        self.config = config
        self.label: Optional[jnp.ndarray] = None
        self.weight: Optional[jnp.ndarray] = None
        self.num_data = 0

    # -- lifecycle (reference ObjectiveFunction::Init) -----------------------
    def init(self, metadata: Metadata, num_data: int) -> None:
        if metadata.label is None:
            raise ValueError(f"objective {self.name} requires labels")
        self.check_label(metadata.label)
        self.label = jnp.asarray(metadata.label, jnp.float32)
        self.weight = (jnp.asarray(metadata.weight, jnp.float32)
                       if metadata.weight is not None else None)
        self.num_data = num_data

    def check_label(self, label: np.ndarray) -> None:
        pass

    @property
    def num_model_per_iteration(self) -> int:
        return 1

    # -- gradients (reference GetGradients, objective_function.h:37) ---------
    def get_gradients(self, score: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        grad, hess = self._grad_hess(score)
        if self.weight is not None:
            w = self.weight if grad.ndim == 1 else self.weight[:, None]
            grad, hess = grad * w, hess * w
        return grad.astype(jnp.float32), hess.astype(jnp.float32)

    def _grad_hess(self, score):
        raise NotImplementedError

    # -- init score (reference BoostFromScore) -------------------------------
    def boost_from_score(self, class_id: int = 0) -> float:
        return 0.0

    # -- output transform (reference ConvertOutput) --------------------------
    def convert_output(self, score: jnp.ndarray) -> jnp.ndarray:
        return score

    def _np_label(self) -> np.ndarray:
        return np.asarray(self.label)

    def _np_weight(self) -> Optional[np.ndarray]:
        return None if self.weight is None else np.asarray(self.weight)


def weighted_mean(values: np.ndarray, weights: Optional[np.ndarray]) -> float:
    if weights is None:
        return float(np.mean(values))
    return float(np.sum(values * weights) / np.sum(weights))


def weighted_percentile(values: np.ndarray, weights: Optional[np.ndarray],
                        alpha: float) -> float:
    """Weighted percentile (reference regression_objective.hpp:24
    ``PercentileFun``/``WeightedPercentileFun``)."""
    order = np.argsort(values, kind="stable")
    v = values[order]
    if weights is None:
        n = len(v)
        if n == 0:
            return 0.0
        pos = alpha * n
        idx = int(np.floor(pos))
        if idx >= n:
            return float(v[-1])
        if abs(pos - idx) < 1e-12 and idx > 0:
            return float((v[idx - 1] + v[idx]) / 2.0)
        return float(v[idx])
    w = weights[order]
    cum = np.cumsum(w) - 0.5 * w
    total = np.sum(w)
    if total <= 0:
        return 0.0
    target = alpha * total
    idx = int(np.searchsorted(cum, target))
    idx = min(idx, len(v) - 1)
    return float(v[idx])
