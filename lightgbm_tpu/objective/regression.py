"""Regression objective family (reference src/objective/
regression_objective.hpp — L2:132, L1:223, Huber:320, Fair:368, Poisson:445,
Quantile:497, MAPE:616, Gamma:692, Tweedie:728, with BoostFromScore and
percentile leaf renewal hooks)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .base import ObjectiveFunction, weighted_mean, weighted_percentile


class RegressionL2(ObjectiveFunction):
    name = "regression"
    is_constant_hessian = True

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = bool(config.reg_sqrt)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.sqrt:
            lab = np.asarray(metadata.label, np.float64)
            self._raw_label = self.label
            self.label = jnp.asarray(np.sign(lab) * np.sqrt(np.abs(lab)), jnp.float32)

    def _grad_hess(self, score):
        return score - self.label, jnp.ones_like(score)

    def boost_from_score(self, class_id: int = 0) -> float:
        return weighted_mean(np.asarray(self.label), self._np_weight())

    def convert_output(self, score):
        if self.sqrt:
            return jnp.sign(score) * score * score
        return score

    def _np_weight(self):
        return None if self.weight is None else np.asarray(self.weight)


class RegressionL1(RegressionL2):
    name = "regression_l1"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False

    def _grad_hess(self, score):
        diff = score - self.label
        return jnp.sign(diff), jnp.ones_like(score)

    def boost_from_score(self, class_id: int = 0) -> float:
        return weighted_percentile(np.asarray(self.label), self._np_weight(), 0.5)

    # reference IsRenewTreeOutput: leaf values are refit to the residual
    # median (RenewTreeOutput) — see boosting/gbdt renew step
    is_renew_tree_output = True
    renew_alpha = 0.5


class Huber(RegressionL2):
    name = "huber"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False
        self.alpha = float(config.alpha)

    def _grad_hess(self, score):
        diff = score - self.label
        grad = jnp.where(jnp.abs(diff) <= self.alpha, diff,
                         jnp.sign(diff) * self.alpha)
        return grad, jnp.ones_like(score)


class Fair(RegressionL2):
    name = "fair"
    is_constant_hessian = False

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False
        self.c = float(config.fair_c)

    def _grad_hess(self, score):
        x = score - self.label
        denom = jnp.abs(x) + self.c
        return self.c * x / denom, self.c * self.c / (denom * denom)

    def boost_from_score(self, class_id: int = 0) -> float:
        return weighted_percentile(np.asarray(self.label), self._np_weight(), 0.5)


class Poisson(RegressionL2):
    name = "poisson"
    is_constant_hessian = False

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False
        self.max_delta_step = float(config.poisson_max_delta_step)

    def check_label(self, label):
        if (label < 0).any():
            raise ValueError("poisson objective requires non-negative labels")

    def _grad_hess(self, score):
        ex = jnp.exp(score)
        return ex - self.label, jnp.exp(score + self.max_delta_step)

    def boost_from_score(self, class_id: int = 0) -> float:
        mean = weighted_mean(np.asarray(self.label), self._np_weight())
        return float(np.log(max(mean, 1e-15)))

    def convert_output(self, score):
        return jnp.exp(score)


class Quantile(RegressionL2):
    name = "quantile"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False
        self.alpha = float(config.alpha)

    def _grad_hess(self, score):
        # reference regression_objective.hpp:496-499: grad = (1-alpha) when
        # delta >= 0 else -alpha, so gradient equilibrium targets the
        # alpha-quantile (pinball loss d/ds)
        diff = score - self.label
        grad = jnp.where(diff >= 0, 1.0 - self.alpha, -self.alpha)
        return grad, jnp.ones_like(score)

    def boost_from_score(self, class_id: int = 0) -> float:
        return weighted_percentile(np.asarray(self.label), self._np_weight(),
                                   self.alpha)

    is_renew_tree_output = True

    @property
    def renew_alpha(self):
        return self.alpha


class Mape(RegressionL2):
    name = "mape"

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = False

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.abs(np.asarray(metadata.label, np.float64))
        lw = 1.0 / np.maximum(1.0, lab)
        if metadata.weight is not None:
            lw = lw * metadata.weight
        self.label_weight = jnp.asarray(lw, jnp.float32)

    def get_gradients(self, score):
        # label_weight already folds user weights (regression_objective.hpp:616)
        diff = score - self.label
        grad = jnp.sign(diff) * self.label_weight
        hess = (jnp.ones_like(score) if self.weight is None else
                jnp.broadcast_to(self.weight, score.shape))
        return grad.astype(jnp.float32), hess.astype(jnp.float32)

    def boost_from_score(self, class_id: int = 0) -> float:
        return weighted_percentile(np.asarray(self.label),
                                   np.asarray(self.label_weight), 0.5)

    is_renew_tree_output = True
    renew_alpha = 0.5


class Gamma(Poisson):
    name = "gamma"

    def check_label(self, label):
        if (label <= 0).any():
            raise ValueError("gamma objective requires positive labels")

    def _grad_hess(self, score):
        enx = jnp.exp(-score)
        return 1.0 - self.label * enx, self.label * enx


class Tweedie(Poisson):
    name = "tweedie"

    def __init__(self, config):
        super().__init__(config)
        self.rho = float(config.tweedie_variance_power)

    def check_label(self, label):
        if (label < 0).any():
            raise ValueError("tweedie objective requires non-negative labels")

    def _grad_hess(self, score):
        e1 = jnp.exp((1.0 - self.rho) * score)
        e2 = jnp.exp((2.0 - self.rho) * score)
        grad = -self.label * e1 + e2
        hess = -self.label * (1.0 - self.rho) * e1 + (2.0 - self.rho) * e2
        return grad, hess
