"""Multiclass objectives (reference src/objective/multiclass_objective.hpp:
softmax gradients at :86-126 with hessian factor num_class/(num_class-1) at
:31, OVA wrapper at :228, BoostFromScore log(class prob) at :155)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .base import EPS, ObjectiveFunction
from .binary import BinaryLogloss


class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.factor = self.num_class / (self.num_class - 1.0)

    def check_label(self, label):
        if (label < 0).any() or (label >= self.num_class).any():
            raise ValueError(f"multiclass labels must be in [0, {self.num_class})")

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lab = np.asarray(metadata.label).astype(np.int32)
        w = metadata.weight
        probs = np.zeros(self.num_class)
        for k in range(self.num_class):
            sel = lab == k
            probs[k] = (w[sel].sum() / w.sum()) if w is not None else sel.mean()
        self.class_init_probs = probs
        self.onehot = jnp.asarray(
            np.eye(self.num_class, dtype=np.float32)[lab])

    @property
    def num_model_per_iteration(self) -> int:
        return self.num_class

    def get_gradients(self, score):
        # score: (N, K)
        p = jnp.exp(score - jnp.max(score, axis=1, keepdims=True))
        p = p / jnp.sum(p, axis=1, keepdims=True)
        grad = p - self.onehot
        hess = self.factor * p * (1.0 - p)
        if self.weight is not None:
            grad = grad * self.weight[:, None]
            hess = hess * self.weight[:, None]
        return grad.astype(jnp.float32), hess.astype(jnp.float32)

    def boost_from_score(self, class_id: int = 0) -> float:
        return float(np.log(max(EPS, self.class_init_probs[class_id])))

    def convert_output(self, score):
        p = jnp.exp(score - jnp.max(score, axis=-1, keepdims=True))
        return p / jnp.sum(p, axis=-1, keepdims=True)


class MulticlassOVA(ObjectiveFunction):
    name = "multiclassova"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.sigmoid = float(config.sigmoid)
        self._binary = [BinaryLogloss(config) for _ in range(self.num_class)]

    def check_label(self, label):
        if (label < 0).any() or (label >= self.num_class).any():
            raise ValueError(f"multiclassova labels must be in [0, {self.num_class})")

    def init(self, metadata, num_data):
        if metadata.label is None:
            raise ValueError("multiclassova requires labels")
        self.check_label(metadata.label)
        lab = np.asarray(metadata.label).astype(np.int32)
        self.label = jnp.asarray(lab, jnp.float32)
        self.weight = (jnp.asarray(metadata.weight, jnp.float32)
                       if metadata.weight is not None else None)
        self.num_data = num_data
        from ..dataset import Metadata
        for k, b in enumerate(self._binary):
            md = Metadata()
            md.set_label((lab == k).astype(np.float32))
            if metadata.weight is not None:
                md.set_weight(metadata.weight)
            b.init(md, num_data)

    @property
    def num_model_per_iteration(self) -> int:
        return self.num_class

    def get_gradients(self, score):
        grads, hesss = [], []
        for k, b in enumerate(self._binary):
            g, h = b.get_gradients(score[:, k])
            grads.append(g)
            hesss.append(h)
        return jnp.stack(grads, axis=1), jnp.stack(hesss, axis=1)

    def boost_from_score(self, class_id: int = 0) -> float:
        return self._binary[class_id].boost_from_score(0)

    def convert_output(self, score):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * score))
