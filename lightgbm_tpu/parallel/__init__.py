"""Distributed tree learners over a JAX device mesh.

TPU-native replacement for the reference's network layer + parallel learners
(reference: src/network/ socket/MPI collectives + src/treelearner/
parallel_tree_learner.h — see SURVEY.md §2.5's mapping note): the Bruck
allgather / recursive-halving reduce-scatter over TCP/MPI collapse into
``jax.lax`` collectives (psum / all_gather / reduce_scatter semantics) over
ICI/DCN inside ``shard_map``; ``jax.distributed.initialize`` replaces the
machine-list bootstrap.
"""

from __future__ import annotations

from .data_parallel import DataParallelTreeLearner
from .feature_parallel import FeatureParallelTreeLearner
from .voting_parallel import VotingParallelTreeLearner
from .mesh import get_mesh


def create_parallel_learner(config, num_features, max_bins, num_bins, is_cat,
                            has_nan, monotone=None, interaction_groups=(),
                            cegb_lazy=(), forced_splits=()):
    """Factory (reference tree_learner.h:104 TreeLearner::CreateTreeLearner
    dispatching on tree_learner type)."""
    kind = config.tree_learner
    cls = {
        "data": DataParallelTreeLearner,
        "feature": FeatureParallelTreeLearner,
        "voting": VotingParallelTreeLearner,
    }.get(kind)
    if cls is None:
        raise ValueError(f"Unknown tree_learner: {kind}")
    if kind == "data":
        return cls(config, num_features, max_bins, num_bins, is_cat,
                   has_nan, monotone, interaction_groups=interaction_groups,
                   cegb_lazy=cegb_lazy, forced_splits=forced_splits)
    if interaction_groups or cegb_lazy or forced_splits:
        from ..utils.log import log_warning
        log_warning("interaction_constraints / cegb_penalty_feature_lazy / "
                    "forcedsplits_filename are applied by the serial and "
                    "data-parallel learners only; this learner ignores them")
    return cls(config, num_features, max_bins, num_bins, is_cat, has_nan,
               monotone)
