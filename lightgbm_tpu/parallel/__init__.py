"""Distributed tree learners over a JAX device mesh.

TPU-native replacement for the reference's network layer + parallel learners
(reference: src/network/ socket/MPI collectives + src/treelearner/
parallel_tree_learner.h — see SURVEY.md §2.5's mapping note): the Bruck
allgather / recursive-halving reduce-scatter over TCP/MPI collapse into
``jax.lax`` collectives (psum / all_gather / reduce_scatter semantics) over
ICI/DCN inside ``shard_map``; ``jax.distributed.initialize`` replaces the
machine-list bootstrap.
"""

from __future__ import annotations

from .data_parallel import DataParallelTreeLearner
from .feature_parallel import FeatureParallelTreeLearner
from .voting_parallel import VotingParallelTreeLearner
from .mesh import get_mesh


def create_parallel_learner(config, num_features, max_bins, num_bins, is_cat,
                            has_nan, monotone=None, interaction_groups=(),
                            cegb_lazy=(), forced_splits=(),
                            feature_contri=()):
    """Factory (reference tree_learner.h:104 TreeLearner::CreateTreeLearner
    dispatching on tree_learner type)."""
    kind = config.tree_learner
    cls = {
        "data": DataParallelTreeLearner,
        "feature": FeatureParallelTreeLearner,
        "voting": VotingParallelTreeLearner,
    }.get(kind)
    if cls is None:
        raise ValueError(f"Unknown tree_learner: {kind}")
    import jax
    from .mesh import get_mesh
    if get_mesh(int(config.num_devices)).devices.size == 1 and \
            jax.process_count() == 1:
        # a parallel learner over a 1-device mesh IS the serial learner
        # with collective overhead on top — the reference likewise runs
        # serial when num_machines == 1 (application.cpp).  Fall back so
        # single-chip runs of parallel configs get the fast wave path.
        from ..utils.log import log_info
        from ..learner.serial import SerialTreeLearner
        log_info(f"tree_learner={kind} on a single-device mesh: using "
                 "the serial learner (no collectives needed)")
        return SerialTreeLearner(
            config, num_features, max_bins, num_bins, is_cat, has_nan,
            monotone, forced_splits,
            interaction_groups=interaction_groups, cegb_lazy=cegb_lazy,
            feature_contri=feature_contri)
    if kind == "data":
        return cls(config, num_features, max_bins, num_bins, is_cat,
                   has_nan, monotone, interaction_groups=interaction_groups,
                   cegb_lazy=cegb_lazy, forced_splits=forced_splits)
    if interaction_groups or cegb_lazy or forced_splits:
        from ..utils.log import log_warning
        log_warning("interaction_constraints / cegb_penalty_feature_lazy / "
                    "forcedsplits_filename are applied by the serial and "
                    "data-parallel learners only; this learner ignores them")
    return cls(config, num_features, max_bins, num_bins, is_cat, has_nan,
               monotone)
