"""Device mesh helpers (replaces reference Network::Init bootstrap,
src/network/linkers_socket.cpp machine-list TCP handshake — on TPU the mesh
is declared, XLA routes collectives over ICI/DCN)."""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["get_mesh", "shard_rows", "replicate", "shard_map_compat",
           "psum_scatter_compat"]


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes it at the top level with ``check_vma``; older
    releases only have ``jax.experimental.shard_map.shard_map`` with the
    flag spelled ``check_rep``.  Every parallel learner builds its grower
    through this shim so the mesh path works on both.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def psum_scatter_compat(x, axis_name, *, scatter_dimension=0, tiled=True,
                        axis_size: Optional[int] = None):
    """``jax.lax.psum_scatter`` with an emulation fallback.

    The reduce-scatter collective is the backbone of the feature-sliced
    histogram merge (the reference's ReduceScatter,
    data_parallel_tree_learner.cpp:155-173 / network.h:164): every shard
    receives ONE reduced block of the operand instead of the whole
    reduced tensor.  Old jax builds that lack the primitive fall back to
    ``psum`` + this shard's slice — functionally identical, without the
    1/k wire saving (``axis_size`` must then be given, since the slice
    width cannot be derived from a traced axis index)."""
    try:
        return jax.lax.psum_scatter(x, axis_name,
                                    scatter_dimension=scatter_dimension,
                                    tiled=tiled)
    except (AttributeError, NotImplementedError):
        if axis_size is None:
            raise RuntimeError(
                "this jax build lacks lax.psum_scatter and no axis_size "
                "was provided for the psum+slice emulation")
        full = jax.lax.psum(x, axis_name)
        blk = x.shape[scatter_dimension] // int(axis_size)
        idx = jax.lax.axis_index(axis_name) * blk
        return jax.lax.dynamic_slice_in_dim(full, idx, blk,
                                            axis=scatter_dimension)


def get_mesh(num_devices: int = 0, axis_name: str = "workers") -> Mesh:
    """1-D mesh over visible devices (the GBDT parallelism axis — the analog
    of the reference's num_machines rank space)."""
    devs = jax.devices()
    if num_devices and num_devices > 0:
        devs = devs[:num_devices]
    return Mesh(np.array(devs), (axis_name,))


def shard_rows(mesh: Mesh, arr, axis_name: str = "workers"):
    """Place an array row-sharded over the mesh (data-parallel layout)."""
    return jax.device_put(arr, NamedSharding(mesh, P(axis_name)))


def replicate(mesh: Mesh, arr):
    return jax.device_put(arr, NamedSharding(mesh, P()))
