"""Data-parallel tree learner: rows sharded over the mesh.

TPU-native re-implementation of the reference DataParallelTreeLearner
(reference: src/treelearner/data_parallel_tree_learner.cpp — rows partitioned
across machines, local histograms ReduceScatter'd so each machine reduces a
disjoint feature block :155-173 with the block layout computed at :58-124,
local best splits on owned features only :176-251, allreduce-max of the best
SplitInfo :244, global leaf counts via parallel_tree_learner.h:67).

Here the learner is the shared grower wrapped in ``shard_map`` over a 1-D
mesh: the binned matrix, gradients and row_leaf partition live row-sharded;
the per-leaf histogram pool keeps shard-LOCAL histograms (histogram
subtraction is linear, so local parent − local child = local sibling), and
each candidate search runs ``psum_scatter`` so every device reduces and
scans ONE disjoint feature block — per-device communication is F·B/ndev
instead of the F·B a full psum moves, exactly the reference's
reduce-scatter refinement.  The winning candidate is then combined with a
pmax + owner-broadcast (the SplitInfo allreduce-max analog); global leaf
counts fall out of the psum'd count channel (GetGlobalDataCountInLeaf).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..config import Config
from ..learner.serial import (CommStrategy, GrownTree, local_best_candidate,
                              make_grow_fn, hist_pool_fits, resolve_hist_impl,
                              split_params_from_config)
from ..analysis.contracts import (collective_contract, memory_budget,
                                  world_size)
from ..telemetry.train_record import note_collective
from .mesh import get_mesh, psum_scatter_compat, shard_map_compat

__all__ = ["DataParallelTreeLearner", "DataParallelStrategy"]

BIG_FEAT = np.int32(2 ** 30)


def _masked_scan_budget(ctx):
    """Masked-grower candidate scans per traced program: bounded by a
    small multiple of the static leaf budget (the while body traces
    once; root + two children per commit site)."""
    return 8 * max(2, int(ctx.get("leaves", 2)))


def _masked_hist_block_bytes(ctx):
    """psum_scatter operand: the full LOCAL (Fp, B, 3) histogram goes in,
    each shard receives its Fp/k block fully reduced (the reference's
    per-split ReduceScatter, data_parallel_tree_learner.cpp:155-173).
    ``k`` is the mesh world size so one declaration covers W=4..W=256."""
    k = world_size(ctx)
    f_pad = -(-int(ctx["features"]) // k) * k
    return f_pad * int(ctx["bins"]) * 3 * int(ctx.get("itemsize", 4))


# Contracts for the MASKED sequential DP grower's sites (the wave-path
# sites are declared next to their merge logic in learner/wave.py).
collective_contract("data_parallel/masked/leaf_sum", "psum",
                    max_count=_masked_scan_budget, max_bytes_per_op=256)
collective_contract("data_parallel/masked/hist_reduce_scatter",
                    "psum_scatter", max_count=_masked_scan_budget,
                    max_bytes_per_op=_masked_hist_block_bytes,
                    note="one reduce-scatter per candidate scan; "
                         "operand is the local histogram")
collective_contract("data_parallel/masked/best_gain", "pmax",
                    max_count=_masked_scan_budget, max_bytes_per_op=64)
collective_contract("data_parallel/masked/best_feature", "pmin",
                    max_count=_masked_scan_budget, max_bytes_per_op=64)
collective_contract("data_parallel/masked/winner_bcast", "psum",
                    max_count=lambda ctx: 8 * _masked_scan_budget(ctx),
                    max_bytes_per_op=lambda ctx: 4 * max(
                        64, int(ctx["bins"])),
                    note="winner payload incl. the (B,) cat membership")


# ---------------------------------------------------------------------------
# Memory budget for the sliced DP-wave program family (lint-mem
# enforced): the per-device working set on the reduce-scatter path.
# Two full-F local kernel banks (the pre-merge local histograms the
# quantized kernel builds at Q_WAVE_SIZE=42 channels) dominate; AFTER
# the merge everything is a ceil(F/k) feature slice — the per-leaf bank,
# the scan operands, the winner rescans.  An un-scattered merge (the
# planted regression class) re-inflates the post-merge terms to full F
# and blows through this curve.
# ---------------------------------------------------------------------------

def dp_sliced_hbm_bytes(ctx):
    """Per-device HBM curve of one sliced DP-wave tree program as a
    function of (rows, features, bins, wave_size, leaves, world_size)."""
    from ..learner.wave import Q_WAVE_SIZE, WAVE_SIZE
    k = world_size(ctx)
    f = int(ctx["features"])
    b = int(ctx["bins"])
    it = int(ctx.get("itemsize", 4))
    r = -(-int(ctx["rows"]) // k)
    wave = int(ctx.get("wave_size", WAVE_SIZE))
    kernel_ch = Q_WAVE_SIZE if ctx.get("quantized", True) else WAVE_SIZE
    # pre-merge: 2.5 local full-F channel banks in flight (build + merge)
    local_banks = int(2.5 * max(2 * wave, kernel_ch) * f * b * 3 * it)
    # post-merge: per-leaf bank + scan/rescan temporaries on the slice
    f_blk = -(-f // k)
    sliced = (int(ctx.get("leaves", 2)) + 6 * wave) * f_blk * b * 3 * it
    rows = r * (f + 24)
    return local_banks + sliced + rows + (1 << 20)


memory_budget(
    "data_parallel/wave_sliced", ("dp_scatter", "spec_ramp"),
    dp_sliced_hbm_bytes,
    note="2.5 local full-F kernel banks + F/k post-merge slice + rows")


class DataParallelStrategy(CommStrategy):
    rows_sharded = True
    """Local histograms + per-candidate psum_scatter over feature blocks
    (SURVEY.md §2.5 mapping; data_parallel_tree_learner.cpp:155-173)."""

    def __init__(self, axis_name, f_local, num_bins, is_cat, has_nan):
        super().__init__(num_bins, is_cat, has_nan)
        self.axis_name = axis_name
        self.f_local = f_local

    def reduce_sum(self, v):
        note_collective("data_parallel/masked/leaf_sum", "psum", v)
        return jax.lax.psum(v, self.axis_name)

    # reduce_hist stays identity: the pool keeps shard-LOCAL histograms;
    # cross-shard reduction happens inside leaf_candidates on disjoint
    # feature blocks (reduce-scatter), never on the full tensor.

    def leaf_candidates(self, hist_local, leaf_sum, feature_mask, params,
                        bound=None, depth=None, parent_out=None):
        fb = self.f_local
        r = jax.lax.axis_index(self.axis_name)
        start = r * fb
        # each device reduces + owns one contiguous feature block
        note_collective("data_parallel/masked/hist_reduce_scatter",
                        "psum_scatter", hist_local)
        blk = psum_scatter_compat(hist_local, self.axis_name,
                                  scatter_dimension=0, tiled=True)
        sl = lambda a: jax.lax.dynamic_slice(a, (start,), (fb,))
        mono = sl(self.monotone_full) if self.monotone_full is not None \
            else None
        g, f_loc, b, dl, ls, rs, member = local_best_candidate(
            blk, leaf_sum, sl(self.num_bins_full), sl(self.is_cat_full),
            sl(self.has_nan_full), sl(feature_mask), params, mono, bound,
            depth, parent_out=parent_out)
        # allreduce-max of the per-block winners with deterministic
        # tie-break on the global feature index (SplitInfo ladder)
        note_collective("data_parallel/masked/best_gain", "pmax", g)
        gmax = jax.lax.pmax(g, self.axis_name)
        f_glob = start.astype(jnp.int32) + f_loc
        cand = jnp.where(g >= gmax, f_glob, BIG_FEAT)
        note_collective("data_parallel/masked/best_feature", "pmin", cand)
        f_win = jax.lax.pmin(cand, self.axis_name)
        is_win = (f_glob == f_win) & (g >= gmax)

        def bcast(v):
            note_collective("data_parallel/masked/winner_bcast", "psum", v)
            return jax.lax.psum(
                jnp.where(is_win, v, jnp.zeros_like(v)), self.axis_name)

        return (gmax, f_win, bcast(b), bcast(dl.astype(jnp.int32)) > 0,
                bcast(ls), bcast(rs), bcast(member.astype(jnp.int32)) > 0)

    def pair_candidates(self, hist_l, hist_r, lsum, rsum, feature_mask,
                        params, bound_l, bound_r, depth, fm_l=None,
                        fm_r=None, po_l=None, po_r=None):
        # collectives are not vmap-batched: two sequential candidate calls
        return (self.leaf_candidates(
                    hist_l, lsum,
                    feature_mask if fm_l is None else fm_l, params,
                    bound_l, depth, po_l),
                self.leaf_candidates(
                    hist_r, rsum,
                    feature_mask if fm_r is None else fm_r, params,
                    bound_r, depth, po_r))


class WaveDPStrategy(CommStrategy):
    """Row-sharded strategy for the wave grower: ONE histogram collective
    per wave (up to 25/42 splits' smaller children).

    Two merge modes for that collective:

    * ``hist_scatter=False`` — full-batch ``psum``: every shard holds the
      whole merged histogram and the candidate scans run replicated.
    * ``hist_scatter=True`` — feature-sliced ``psum_scatter`` (the
      reference DP learner's ReduceScatter refinement,
      data_parallel_tree_learner.cpp:155-173, amortized over the wave's
      channels): each shard materializes only its F/k feature block of
      the merged batch, scans that slice, and the per-leaf winners are
      combined by the wave grower's O(W*k) winner exchange
      (learner/wave.py).  1/k the wire residency and 1/k the scan FLOPs
      per pass; results identical to the psum mode.

    ``spec_ok``/``nshards`` unlock the speculative ramp on this path:
    each shard strides its local rows for the provisional subsample
    (global budget / nshards each) and the provisional passes reduce
    their histogram batches like committed waves — one extra collective
    per provisional pass, nothing else (learner/wave.py _spec_state)."""

    rows_sharded = True
    spec_ok = True

    def __init__(self, axis_name: str, nshards: int = 1,
                 hist_scatter: bool = False):
        self.axis_name = axis_name
        self.nshards = int(nshards)
        self.hist_scatter = bool(hist_scatter)
        self.monotone_full = None

    def reduce_sum(self, v):
        note_collective("data_parallel/wave/scalar_sum", "psum", v)
        return jax.lax.psum(v, self.axis_name)

    def reduce_max(self, v):
        """Global quantization scales: every shard must see the same max
        (gradient_discretizer scales are global in the reference too)."""
        note_collective("data_parallel/wave/quant_scale", "pmax", v)
        return jax.lax.pmax(v, self.axis_name)

    def shard_key(self, key):
        """Independent stochastic-rounding streams per row shard."""
        return jax.random.fold_in(key, jax.lax.axis_index(self.axis_name))

    def reduce_hist(self, hist):
        # THE data-parallel collective: one histogram-batch psum per wave
        # / provisional pass (PERF.md's one-psum-per-pass contract,
        # asserted on the traced program in tests/test_specramp.py — this
        # tally counts the same sites at trace time)
        note_collective("data_parallel/wave/hist_psum", "psum", hist)
        return jax.lax.psum(hist, self.axis_name)

    def reduce_hist_scatter(self, hist):
        """Feature-sliced merge: reduce-scatter the (k, Fp, B, 3) batch
        over the padded feature axis so this shard receives only its
        Fp/nshards block, fully reduced.  The telemetry note records the
        scattered OUTPUT (the per-device received payload — 1/k of the
        psum mode's full-batch residency)."""
        out = psum_scatter_compat(hist, self.axis_name,
                                  scatter_dimension=1, tiled=True,
                                  axis_size=self.nshards)
        note_collective("data_parallel/wave/hist_reduce_scatter",
                        "psum_scatter", out)
        return out

    def exchange_collectives(self):
        """(pmax, pmin, psum) hooks of the wave grower's winner exchange,
        telemetry-tagged — the SplitInfo allreduce-max analog
        (data_parallel_tree_learner.cpp:244), O(W*k) bytes per scan."""
        ax = self.axis_name

        def xmax(v):
            note_collective("data_parallel/wave/winner_exchange", "pmax", v)
            return jax.lax.pmax(v, ax)

        def xmin(v):
            note_collective("data_parallel/wave/winner_exchange", "pmin", v)
            return jax.lax.pmin(v, ax)

        def xsum(v):
            note_collective("data_parallel/wave/winner_exchange", "psum", v)
            return jax.lax.psum(v, ax)

        return xmax, xmin, xsum


class DataParallelTreeLearner:
    """Host-side wrapper building the shard_map'd grower.

    Two growers: the WAVE grower (TPU default — leaf-batched histograms,
    one psum per wave, no row movement) and the masked sequential grower
    with per-split psum_scatter blocks (the reference DP layout,
    data_parallel_tree_learner.cpp:155-173; used off-TPU and when wave is
    gated off)."""

    name = "data"

    def __init__(self, config: Config, num_features: int, max_bins: int,
                 num_bins: np.ndarray, is_cat: np.ndarray, has_nan: np.ndarray,
                 monotone: Optional[np.ndarray] = None,
                 interaction_groups: tuple = (),
                 cegb_lazy: tuple = (), forced_splits: tuple = ()):
        self.config = config
        self.max_bins = int(max_bins)
        self.num_features = num_features
        self.interaction_groups = tuple(tuple(g) for g in interaction_groups)
        self.cegb_lazy = tuple(float(v) for v in cegb_lazy)
        self.forced_splits = tuple(tuple(f) for f in forced_splits)
        self.mesh = get_mesh(int(config.num_devices))
        self.ndev = self.mesh.devices.size
        self.axis = self.mesh.axis_names[0]
        mode = str(config.tree_grow_mode)
        impl_wave = resolve_hist_impl(config, parallel=True, wave=True,
                                      max_bins=self.max_bins)
        # same gates as SerialTreeLearner's wave_ok: the wave state carries
        # the full (L, G, B, 3) histogram pool — fall back to the masked
        # sequential grower when it would blow the HBM budget
        wave_able = (int(config.num_leaves) > 2 and
                     hist_pool_fits(config, num_features, self.max_bins))
        self.wave = wave_able and (mode == "wave" or
                                   (mode == "auto" and
                                    impl_wave == "pallas"))
        if not self.wave and not hasattr(jax, "shard_map"):
            # jax<0.5 only ships jax.experimental.shard_map, whose legacy
            # SPMD partitioner hits a hard CHECK (hlo_sharding_util merge
            # of manual/tuple shardings) on the MASKED grower's program
            # and aborts the process.  The wave grower compiles fine there
            # — route through it when it can serve the config, otherwise
            # fail cleanly instead of crashing the interpreter.
            if wave_able and mode != "partition":
                from ..utils.log import log_warning
                log_warning("this jax version cannot compile the masked "
                            "data-parallel grower (legacy SPMD "
                            "partitioner); using the DP-wave grower")
                self.wave = True
            else:
                raise RuntimeError(
                    "tree_learner=data with the masked grower requires "
                    "jax.shard_map (jax>=0.5); upgrade jax or use "
                    "tree_grow_mode=wave")
        if self.wave:
            self._init_wave(config, num_features, num_bins, is_cat, has_nan,
                            monotone, impl_wave)
            return
        self.quantized = False
        self.supports_extras = False
        if config.use_quantized_grad:
            from ..utils.log import log_warning
            log_warning("use_quantized_grad requires the wave grower; the "
                        "masked data-parallel grower trains with exact "
                        "gradients")
        if self.forced_splits:
            from ..utils.log import log_warning
            log_warning("forcedsplits_filename is applied by the DP-wave "
                        "grower only; the masked data-parallel grower "
                        "ignores it")
        from ..learner.serial import (resolve_monotone_method,
                                      split_params_from_config as _spc)
        resolve_monotone_method(config, _spc(config, num_bins,
                                             is_cat).use_monotone,
                                wave=False)
        if self.interaction_groups or self.cegb_lazy or \
                config.extra_trees or \
                config.feature_fraction_bynode < 1.0 or \
                config.cegb_penalty_split > 0 or \
                config.cegb_penalty_feature_coupled:
            from ..utils.log import log_warning
            log_warning("extra_trees / bynode sampling / cegb / interaction"
                        " constraints under tree_learner=data require the "
                        "wave grower (tree_grow_mode=wave, or auto on TPU);"
                        " the masked DP grower ignores them")
        # pad the feature axis to a multiple of the mesh so psum_scatter
        # blocks are uniform (padded features are trivial: 1 bin, never
        # splittable — the analog of the reference's balanced block layout)
        self.f_pad = (-num_features) % self.ndev
        fp = num_features + self.f_pad
        self.f_local = fp // self.ndev
        self.num_bins = jnp.asarray(
            np.concatenate([num_bins, np.ones(self.f_pad, np.int32)]),
            jnp.int32)
        self.is_cat = jnp.asarray(
            np.concatenate([is_cat, np.zeros(self.f_pad, bool)]), jnp.bool_)
        self.has_nan = jnp.asarray(
            np.concatenate([has_nan, np.zeros(self.f_pad, bool)]), jnp.bool_)
        mono_np = monotone if monotone is not None else np.zeros(num_features)
        self.monotone = jnp.asarray(
            np.concatenate([mono_np, np.zeros(self.f_pad)]), jnp.int32)
        strategy = DataParallelStrategy(self.axis, self.f_local,
                                        self.num_bins, self.is_cat,
                                        self.has_nan)
        grow_t = make_grow_fn(
            num_leaves=int(config.num_leaves), max_bins=self.max_bins,
            max_depth=int(config.max_depth),
            split_params=split_params_from_config(config, num_bins, is_cat),
            hist_impl=resolve_hist_impl(config, parallel=True),
            rows_per_chunk=int(config.tpu_rows_per_chunk),
            use_hist_pool=hist_pool_fits(config, fp, self.max_bins),
            strategy=strategy, jit=False)

        def grow(X, g, h, m, nb, ic, hn, mono, fm):
            return grow_t(X, None, g, h, m, nb, ic, hn, mono, fm)
        tree_specs = self._tree_specs(self.axis)
        self._grow = jax.jit(shard_map_compat(
            grow, mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis), P(self.axis), P(self.axis),
                      P(), P(), P(), P(), P()),
            out_specs=tree_specs,
            check_vma=False))

    @staticmethod
    def _tree_specs(axis):
        return GrownTree(
            split_feature=P(), threshold_bin=P(), nan_bin=P(),
            cat_member=P(), decision_type=P(), left_child=P(),
            right_child=P(), split_gain=P(), internal_value=P(),
            internal_weight=P(), internal_count=P(), leaf_value=P(),
            leaf_weight=P(), leaf_count=P(), num_leaves=P(),
            row_leaf=P(axis), hist_passes=P())

    def _init_wave(self, config, num_features, num_bins, is_cat, has_nan,
                   monotone, impl):
        from ..learner.wave import make_wave_grow_fn
        self.f_pad = 0
        self.pallas = impl == "pallas"
        self.num_bins = jnp.asarray(num_bins, jnp.int32)
        self.is_cat = jnp.asarray(is_cat, jnp.bool_)
        self.has_nan = jnp.asarray(has_nan, jnp.bool_)
        mono_np = monotone if monotone is not None else np.zeros(num_features)
        self.monotone = jnp.asarray(mono_np, jnp.int32)
        self._x_src = None
        self.supports_extras = True
        from ..ops.quantize import quant_levels
        self.quantized = bool(config.use_quantized_grad)
        sp = split_params_from_config(config, num_bins, is_cat)
        if np.any(np.asarray(is_cat)):
            # the DP-WAVE scan runs replicated in FULL feature space
            # (unlike the masked psum_scatter blocks) — attach the static
            # cat positions that bound the subset search's argsort
            sp = sp._replace(cat_idx=tuple(
                int(j) for j in np.where(np.asarray(is_cat))[0]))
        self.split_params = sp
        from ..learner.serial import resolve_monotone_method
        mc_inter = resolve_monotone_method(config, sp.use_monotone,
                                           wave=True)
        self._use_node_key = sp.feature_fraction_bynode < 1.0 or \
            sp.extra_trees
        gq_max, hq_max = quant_levels(int(config.num_grad_quant_bins))
        strategy = WaveDPStrategy(
            self.axis, nshards=self.ndev,
            hist_scatter=bool(config.tpu_dp_hist_scatter))
        grow_w = make_wave_grow_fn(
            num_leaves=int(config.num_leaves), num_features=num_features,
            max_bins=self.max_bins, max_depth=int(config.max_depth),
            split_params=sp,
            hist_impl=impl, any_cat=bool(np.any(np.asarray(is_cat))),
            wave_size=int(config.tpu_wave_size), strategy=strategy,
            jit=False, quantized=self.quantized, gq_max=gq_max,
            hq_max=hq_max,
            renew_leaf=bool(config.quant_train_renew_leaf),
            stochastic=bool(config.stochastic_rounding),
            interaction_groups=self.interaction_groups,
            cegb_lazy=self.cegb_lazy, forced_splits=self.forced_splits,
            mc_inter=mc_inter,
            spec_ramp=bool(config.tpu_speculative_ramp),
            spec_tol=float(config.tpu_spec_tolerance),
            exact_endgame=bool(config.tpu_exact_endgame))

        # cegb penalties, the quantization/bynode keys and the persistent
        # lazy-CEGB bitmap ride extra operands; arity is static config
        nq = int(self.quantized)
        nn = int(self._use_node_key)
        nl = int(bool(self.cegb_lazy))

        def grow(X_T, g, h, m, nb, ic, hn, mono, fm, cegb, *rest):
            kw = {}
            ki = 0
            if nq:
                kw["quant_key"] = rest[ki]
                ki += 1
            if nn:
                kw["node_key"] = rest[ki]
                ki += 1
            if nl:
                kw["lazy_used"] = rest[ki]
            return grow_w(X_T, g, h, m, nb, ic, hn, mono, cegb, (), fm,
                          **kw)

        tree_specs = self._tree_specs(self.axis)
        out_specs = (tree_specs, P(None, self.axis)) if nl else tree_specs
        self._grow = jax.jit(shard_map_compat(
            grow, mesh=self.mesh,
            in_specs=(P(None, self.axis), P(self.axis), P(self.axis),
                      P(self.axis), P(), P(), P(), P(), P(), P()) +
            (P(),) * (nq + nn) +
            ((P(None, self.axis),) if nl else ()),
            out_specs=out_specs,
            check_vma=False))
        self._lazy_used = None

    def train(self, X_dev: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
              sample_mask: jnp.ndarray,
              feature_mask: Optional[jnp.ndarray] = None,
              quant_key: Optional[jnp.ndarray] = None,
              cegb_penalty: Optional[jnp.ndarray] = None,
              node_key: Optional[jnp.ndarray] = None) -> GrownTree:
        if feature_mask is None:
            feature_mask = jnp.ones((self.num_features,), jnp.bool_)
        n = X_dev.shape[0]
        if self.wave:
            # each shard's rows must satisfy the Pallas row-block contract
            if self.pallas:
                from ..ops.histogram_pallas import DEFAULT_ROW_BLOCK
                quantum = self.ndev * DEFAULT_ROW_BLOCK
            else:
                # x8 so each shard's rows (and the packed lazy-CEGB
                # bitmap's byte columns) stay 8-divisible
                quantum = self.ndev * 8
            pad = (-n) % quantum
            if self._x_src is not X_dev:
                Xp = jnp.pad(X_dev, ((0, pad), (0, 0))) if pad else X_dev
                self._XpT = jnp.asarray(jnp.swapaxes(Xp, 0, 1))
                self._x_src = X_dev
                self._lazy_used = None  # fresh data -> fresh bitmap
            if pad:
                grad = jnp.pad(grad, (0, pad))
                hess = jnp.pad(hess, (0, pad))
                sample_mask = jnp.pad(sample_mask, (0, pad))
            if cegb_penalty is None:
                cegb_penalty = jnp.zeros((self.num_features,), jnp.float32)
            keys = []
            if self.quantized:
                if quant_key is None:
                    self._quant_calls = getattr(self, "_quant_calls", 0) + 1
                    quant_key = jax.random.PRNGKey(self._quant_calls)
                keys.append(quant_key)
            if self._use_node_key:
                if node_key is None:
                    node_key = jnp.zeros((2, 2), jnp.uint32)
                keys.append(node_key)
            if self.cegb_lazy:
                from ..learner.wave import LAZY_PACK, lazy_bitmap_init
                n_pad_all = self._XpT.shape[1]
                if self._lazy_used is None or \
                        self._lazy_used.shape[1] != n_pad_all // LAZY_PACK:
                    self._lazy_used = lazy_bitmap_init(
                        self.num_features, n_pad_all)
                keys.append(self._lazy_used)
            out = self._grow(self._XpT, grad, hess, sample_mask,
                             self.num_bins, self.is_cat, self.has_nan,
                             self.monotone, feature_mask, cegb_penalty,
                             *keys)
            if self.cegb_lazy:
                grown, self._lazy_used = out
            else:
                grown = out
            if pad:
                grown = grown._replace(row_leaf=grown.row_leaf[:n])
            return grown
        if self.f_pad:
            X_dev = jnp.pad(X_dev, ((0, 0), (0, self.f_pad)))
            feature_mask = jnp.pad(feature_mask, (0, self.f_pad))
        pad = (-n) % self.ndev
        if pad:
            X_dev = jnp.pad(X_dev, ((0, pad), (0, 0)))
            grad = jnp.pad(grad, (0, pad))
            hess = jnp.pad(hess, (0, pad))
            sample_mask = jnp.pad(sample_mask, (0, pad))
        grown = self._grow(X_dev, grad, hess, sample_mask, self.num_bins,
                           self.is_cat, self.has_nan, self.monotone,
                           feature_mask)
        if pad:
            grown = grown._replace(row_leaf=grown.row_leaf[:n])
        return grown
