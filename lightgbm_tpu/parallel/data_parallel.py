"""Data-parallel tree learner: rows sharded over the mesh.

TPU-native re-implementation of the reference DataParallelTreeLearner
(reference: src/treelearner/data_parallel_tree_learner.cpp — rows partitioned
across machines, local histograms ReduceScatter'd so each machine reduces a
disjoint feature block :155-173, local best splits, allreduce-max of the best
SplitInfo :244, global leaf counts via parallel_tree_learner.h:67).

Here the learner is the shared grower wrapped in ``shard_map`` over a 1-D
mesh: the binned matrix, gradients and row_leaf partition live row-sharded;
per-leaf histograms are ``psum``'d across shards after each masked build (one
allreduce per split — the reduce-scatter + per-feature-block split-finding
refinement is a bandwidth optimization tracked for the perf milestones); all
tree state is computed redundantly and identically on every device, so no
split broadcast is needed.  Global leaf counts fall out of the psum'd count
channel — the analog of GetGlobalDataCountInLeaf.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..config import Config
from ..learner.serial import (CommStrategy, GrownTree, make_grow_fn,
                              hist_pool_fits, resolve_hist_impl,
                              split_params_from_config)
from .mesh import get_mesh

__all__ = ["DataParallelTreeLearner", "DataParallelStrategy"]


class DataParallelStrategy(CommStrategy):
    rows_sharded = True
    """psum histograms + sums across row shards (SURVEY.md §2.5 mapping)."""

    def __init__(self, axis_name, num_bins, is_cat, has_nan):
        super().__init__(num_bins, is_cat, has_nan)
        self.axis_name = axis_name

    def reduce_sum(self, v):
        return jax.lax.psum(v, self.axis_name)

    def reduce_hist(self, hist):
        return jax.lax.psum(hist, self.axis_name)


class DataParallelTreeLearner:
    """Host-side wrapper building the shard_map'd grower."""

    name = "data"

    def __init__(self, config: Config, num_features: int, max_bins: int,
                 num_bins: np.ndarray, is_cat: np.ndarray, has_nan: np.ndarray,
                 monotone: Optional[np.ndarray] = None):
        self.config = config
        self.max_bins = int(max_bins)
        self.num_features = num_features
        self.mesh = get_mesh(int(config.num_devices))
        self.ndev = self.mesh.devices.size
        self.axis = self.mesh.axis_names[0]
        self.num_bins = jnp.asarray(num_bins, jnp.int32)
        self.is_cat = jnp.asarray(is_cat, jnp.bool_)
        self.has_nan = jnp.asarray(has_nan, jnp.bool_)
        self.monotone = jnp.asarray(
            monotone if monotone is not None else np.zeros(num_features),
            jnp.int32)
        strategy = DataParallelStrategy(self.axis, self.num_bins, self.is_cat,
                                        self.has_nan)
        grow_t = make_grow_fn(
            num_leaves=int(config.num_leaves), max_bins=self.max_bins,
            max_depth=int(config.max_depth),
            split_params=split_params_from_config(config, num_bins,
                                                  is_cat),
            hist_impl=resolve_hist_impl(config, parallel=True),
            rows_per_chunk=int(config.tpu_rows_per_chunk),
            use_hist_pool=hist_pool_fits(config, num_features, self.max_bins),
            strategy=strategy, jit=False)

        def grow(X, g, h, m, nb, ic, hn, mono, fm):
            return grow_t(X, None, g, h, m, nb, ic, hn, mono, fm)
        tree_specs = GrownTree(
            split_feature=P(), threshold_bin=P(), nan_bin=P(),
            cat_member=P(), decision_type=P(), left_child=P(), right_child=P(),
            split_gain=P(), internal_value=P(), internal_weight=P(),
            internal_count=P(), leaf_value=P(), leaf_weight=P(),
            leaf_count=P(), num_leaves=P(), row_leaf=P(self.axis))
        self._grow = jax.jit(jax.shard_map(
            grow, mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis), P(self.axis), P(self.axis),
                      P(), P(), P(), P(), P()),
            out_specs=tree_specs,
            check_vma=False))

    def train(self, X_dev: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
              sample_mask: jnp.ndarray,
              feature_mask: Optional[jnp.ndarray] = None) -> GrownTree:
        if feature_mask is None:
            feature_mask = jnp.ones((self.num_features,), jnp.bool_)
        n = X_dev.shape[0]
        pad = (-n) % self.ndev
        if pad:
            X_dev = jnp.pad(X_dev, ((0, pad), (0, 0)))
            grad = jnp.pad(grad, (0, pad))
            hess = jnp.pad(hess, (0, pad))
            sample_mask = jnp.pad(sample_mask, (0, pad))
        grown = self._grow(X_dev, grad, hess, sample_mask, self.num_bins,
                           self.is_cat, self.has_nan, self.monotone,
                           feature_mask)
        if pad:
            grown = grown._replace(row_leaf=grown.row_leaf[:n])
        return grown
