"""Feature-parallel tree learner: the feature axis sharded over the mesh.

TPU-native re-implementation of the reference FeatureParallelTreeLearner
(reference: src/treelearner/feature_parallel_tree_learner.cpp — features
partitioned per machine :40-56, local best split on owned features, global
best via ``SyncUpGlobalBestSplit`` allreduce-max, parallel_tree_learner.h:
191-214, then all machines split identically).

The reference keeps FULL data on every machine and partitions only the
histogram/split work.  On a TPU mesh we go further and shard the binned
matrix itself column-wise (halving HBM per chip as the mesh grows): the
winning split's bin column — which only its owner holds — is broadcast with
one (N,)-int psum per split, the FP analog of the reference's tiny
per-split allreduce.

Cross-device argmax uses pmax on gain + pmin on the encoded feature index
for deterministic tie-breaking (the SplitInfo comparison ladder,
split_info.hpp:280)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..config import Config
from ..learner.serial import (CommStrategy, GrownTree, local_best_candidate,
                              make_grow_fn, hist_pool_fits, resolve_hist_impl,
                              split_params_from_config)
from ..analysis.contracts import collective_contract
from ..telemetry.train_record import note_collective
from .mesh import get_mesh, shard_map_compat

__all__ = ["FeatureParallelTreeLearner", "FeatureParallelStrategy"]

BIG_FEAT = np.int32(2 ** 30)


def _per_split_budget(ctx):
    """Candidate-scan collectives trace once per scan SITE, not per
    executed split (the grower's while body traces once); scan sites are
    bounded by a small multiple of the static leaf budget."""
    return 8 * max(2, int(ctx.get("leaves", 2)))


# The FP learner's wire profile (SyncUpGlobalBestSplit + owner column
# broadcast): winner scalars/payloads per scan site plus one (N,)-sized
# column psum per committed split — never a histogram.
collective_contract("feature_parallel/best_gain", "pmax",
                    max_count=_per_split_budget, max_bytes_per_op=64)
collective_contract("feature_parallel/best_feature", "pmin",
                    max_count=_per_split_budget, max_bytes_per_op=64)
collective_contract("feature_parallel/winner_bcast", "psum",
                    max_count=_per_split_budget, max_bytes_per_op=256,
                    note="winner payload scalars/vectors (SplitInfo)")
collective_contract("feature_parallel/column_bcast", "psum",
                    max_count=_per_split_budget,
                    note="owner broadcast of the winning bin column; "
                         "O(N) by design, unbounded bytes")


class FeatureParallelStrategy(CommStrategy):
    def __init__(self, axis_name, f_local, num_bins_full, is_cat_full,
                 has_nan_full):
        super().__init__(num_bins_full, is_cat_full, has_nan_full)
        self.axis_name = axis_name
        self.f_local = f_local

    def _local_slices(self):
        r = jax.lax.axis_index(self.axis_name)
        start = r * self.f_local
        sl = lambda a: jax.lax.dynamic_slice(a, (start,), (self.f_local,))
        return sl(self.num_bins_full), sl(self.is_cat_full), \
            sl(self.has_nan_full), start

    def leaf_candidates(self, hist_local, leaf_sum, feature_mask, params,
                        bound=None, depth=None, parent_out=None):
        nb, ic, hn, start = self._local_slices()
        r = jax.lax.axis_index(self.axis_name)
        fm = jax.lax.dynamic_slice(feature_mask, (r * self.f_local,),
                                   (self.f_local,))
        mono = jax.lax.dynamic_slice(self.monotone_full,
                                     (r * self.f_local,), (self.f_local,)) \
            if self.monotone_full is not None else None
        g, f_loc, b, dl, ls, rs, member = local_best_candidate(
            hist_local, leaf_sum, nb, ic, hn, fm, params, mono, bound, depth, parent_out=parent_out)
        # global best with deterministic tie-break on the feature index
        # (reference SyncUpGlobalBestSplit allreduce-max)
        note_collective("feature_parallel/best_gain", "pmax", g)
        gmax = jax.lax.pmax(g, self.axis_name)
        f_glob = start.astype(jnp.int32) + f_loc
        cand = jnp.where(g >= gmax, f_glob, BIG_FEAT)
        note_collective("feature_parallel/best_feature", "pmin", cand)
        f_win = jax.lax.pmin(cand, self.axis_name)
        is_win = (f_glob == f_win) & (g >= gmax)

        def bcast(v):
            note_collective("feature_parallel/winner_bcast", "psum", v)
            return jax.lax.psum(
                jnp.where(is_win, v, jnp.zeros_like(v)), self.axis_name)

        return (gmax, f_win, bcast(b), bcast(dl.astype(jnp.int32)) > 0,
                bcast(ls), bcast(rs),
                bcast(member.astype(jnp.int32)) > 0)

    def pair_candidates(self, hist_l, hist_r, lsum, rsum, feature_mask,
                        params, bound_l, bound_r, depth, fm_l=None,
                        fm_r=None, po_l=None, po_r=None):
        # collectives are not vmap-batched: two sequential candidate calls
        return (self.leaf_candidates(
                    hist_l, lsum,
                    feature_mask if fm_l is None else fm_l, params,
                    bound_l, depth, po_l),
                self.leaf_candidates(
                    hist_r, rsum,
                    feature_mask if fm_r is None else fm_r, params,
                    bound_r, depth, po_r))

    def get_column(self, X_local, feat_global):
        r = jax.lax.axis_index(self.axis_name)
        owner = feat_global // self.f_local
        lidx = feat_global % self.f_local
        col = jnp.take(X_local, lidx, axis=1).astype(jnp.int32)
        col = jnp.where(r == owner, col, 0)
        note_collective("feature_parallel/column_bcast", "psum", col)
        return jax.lax.psum(col, self.axis_name)


class FeatureParallelTreeLearner:
    name = "feature"

    def __init__(self, config: Config, num_features: int, max_bins: int,
                 num_bins: np.ndarray, is_cat: np.ndarray, has_nan: np.ndarray,
                 monotone: Optional[np.ndarray] = None):
        self.config = config
        if not hasattr(jax, "shard_map"):
            # jax<0.5's legacy SPMD partitioner aborts the process (hard
            # CHECK in hlo_sharding_util) compiling this learner's
            # shard_map program; fail cleanly instead
            raise RuntimeError(
                "tree_learner=feature requires jax.shard_map (jax>=0.5); "
                "upgrade jax, or use tree_learner=data (wave grower)")
        if config.use_quantized_grad:
            from ..utils.log import log_warning
            log_warning("use_quantized_grad is only applied by the wave "
                        "grower (serial / tree_learner=data); training "
                        "with exact gradients")
        self.max_bins = int(max_bins)
        self.num_features = num_features
        self.mesh = get_mesh(int(config.num_devices))
        self.ndev = self.mesh.devices.size
        self.axis = self.mesh.axis_names[0]
        # pad the feature axis to a multiple of the mesh (padded features are
        # trivial: 1 bin -> never splittable)
        self.f_pad = (-num_features) % self.ndev
        fp = num_features + self.f_pad
        self.f_local = fp // self.ndev
        self.num_bins = jnp.asarray(
            np.concatenate([num_bins, np.ones(self.f_pad, np.int32)]), jnp.int32)
        self.is_cat = jnp.asarray(
            np.concatenate([is_cat, np.zeros(self.f_pad, bool)]), jnp.bool_)
        self.has_nan = jnp.asarray(
            np.concatenate([has_nan, np.zeros(self.f_pad, bool)]), jnp.bool_)
        mono_np = monotone if monotone is not None else np.zeros(num_features)
        self.monotone = jnp.asarray(
            np.concatenate([mono_np, np.zeros(self.f_pad)]), jnp.int32)
        strategy = FeatureParallelStrategy(self.axis, self.f_local,
                                           self.num_bins, self.is_cat,
                                           self.has_nan)
        from ..learner.serial import resolve_monotone_method
        resolve_monotone_method(
            config, bool(config.monotone_constraints and
                         any(int(v) for v in config.monotone_constraints)),
            wave=False)
        grow_t = make_grow_fn(
            num_leaves=int(config.num_leaves), max_bins=self.max_bins,
            max_depth=int(config.max_depth),
            split_params=split_params_from_config(config, num_bins,
                                                  is_cat),
            hist_impl=resolve_hist_impl(config, parallel=True),
            rows_per_chunk=int(config.tpu_rows_per_chunk),
            use_hist_pool=hist_pool_fits(config, self.f_local, self.max_bins),
            strategy=strategy, jit=False)

        def grow(X, g, h, m, nb, ic, hn, mono, fm):
            return grow_t(X, None, g, h, m, nb, ic, hn, mono, fm)
        tree_specs = GrownTree(
            split_feature=P(), threshold_bin=P(), nan_bin=P(),
            cat_member=P(), decision_type=P(), left_child=P(), right_child=P(),
            split_gain=P(), internal_value=P(), internal_weight=P(),
            internal_count=P(), leaf_value=P(), leaf_weight=P(),
            leaf_count=P(), num_leaves=P(), row_leaf=P(),
            hist_passes=P())
        # X is feature-sharded; rows + every descriptor replicated.  The
        # descriptor args reaching the grower must be FULL arrays (global
        # feature indexing), so they ride in replicated and the strategy
        # slices per shard.
        self._grow = jax.jit(shard_map_compat(
            grow, mesh=self.mesh,
            in_specs=(P(None, self.axis), P(), P(), P(), P(), P(), P(), P(),
                      P()),
            out_specs=tree_specs,
            check_vma=False))

    def train(self, X_dev: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
              sample_mask: jnp.ndarray,
              feature_mask: Optional[jnp.ndarray] = None) -> GrownTree:
        if feature_mask is None:
            feature_mask = jnp.ones((self.num_features,), jnp.bool_)
        if self.f_pad:
            X_dev = jnp.pad(X_dev, ((0, 0), (0, self.f_pad)))
            feature_mask = jnp.pad(feature_mask, (0, self.f_pad))
        return self._grow(X_dev, grad, hess, sample_mask, self.num_bins,
                          self.is_cat, self.has_nan, self.monotone,
                          feature_mask)
