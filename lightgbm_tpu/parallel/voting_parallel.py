"""Voting-parallel tree learner (PV-Tree): data-parallel with top-k voting.

TPU-native re-implementation of the reference VotingParallelTreeLearner
(reference: src/treelearner/voting_parallel_tree_learner.cpp — local top-k
vote, Allgather of compact LightSplitInfo :322, ``GlobalVoting`` picks the
global top-2k features :151, ``CopyLocalHistogram`` into the reduce-scatter
layout :184, full scan only on aggregated features; local min_data /
min_hessian scaled by 1/num_machines :62-63; paper: Meng et al., "A
Communication-Efficient Parallel Algorithm for Decision Tree", NIPS 2016).

Rows are sharded like data-parallel, but instead of reducing the full
(F, B, 3) histogram, each shard votes its top-k features (``lax.top_k`` on
local gains), votes are combined with an ``all_gather`` of k feature ids per
shard, and only the winning 2k features' histogram slices are ``psum``'d —
the communication volume drops from F*B to 2k*B per leaf, the whole point of
the algorithm."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..config import Config
from ..learner.serial import (CommStrategy, GrownTree, local_best_candidate,
                              make_grow_fn, hist_pool_fits, resolve_hist_impl,
                              split_params_from_config)
from ..ops.split import NEG_INF, best_split_per_feature
from ..analysis.contracts import collective_contract
from ..telemetry.train_record import note_collective
from .mesh import get_mesh, shard_map_compat

__all__ = ["VotingParallelTreeLearner", "VotingStrategy"]


def _vote_budget(ctx):
    return 8 * max(2, int(ctx.get("leaves", 2)))


def _voted_hist_bytes(ctx):
    """The PV-Tree refinement (arXiv:1611.01276): only the voted top-2k
    features' histograms cross the wire — a (2k, B, 3) psum replacing
    the (F, B, 3) merge; 2k defaults to ctx['top_k']*2 but never exceeds
    the full feature space."""
    two_k = min(2 * int(ctx.get("top_k", 10)), int(ctx["features"]))
    return two_k * int(ctx["bins"]) * 3 * int(ctx.get("itemsize", 4))


collective_contract("voting_parallel/leaf_sum", "psum",
                    max_count=_vote_budget, max_bytes_per_op=256)
collective_contract("voting_parallel/vote_allgather", "all_gather",
                    max_count=_vote_budget,
                    max_bytes_per_op=lambda ctx: 8 * int(
                        ctx.get("top_k", 10)),
                    note="local top-k feature ids, O(k) ints")
collective_contract("voting_parallel/voted_hist_psum", "psum",
                    max_count=_vote_budget,
                    max_bytes_per_op=_voted_hist_bytes,
                    note="top-2k voted feature histograms only")


class VotingStrategy(CommStrategy):
    rows_sharded = True
    def __init__(self, axis_name, top_k, num_features, ndev,
                 num_bins, is_cat, has_nan, local_params):
        super().__init__(num_bins, is_cat, has_nan)
        self.axis_name = axis_name
        self.top_k = top_k
        self.num_features = num_features
        self.ndev = ndev
        self.local_params = local_params  # 1/num_machines-scaled constraints

    def reduce_sum(self, v):
        note_collective("voting_parallel/leaf_sum", "psum", v)
        return jax.lax.psum(v, self.axis_name)

    # reduce_hist stays identity: the pool keeps shard-LOCAL histograms and
    # only voted features are aggregated below.

    def leaf_candidates(self, hist_local, leaf_sum, feature_mask, params,
                        bound=None, depth=None, parent_out=None):
        k = self.top_k
        # 1. local candidate gains with relaxed (1/num_machines) constraints
        #    (voting_parallel_tree_learner.cpp:62-63)
        local_sum = leaf_sum / self.ndev
        fs = best_split_per_feature(hist_local, local_sum, self.num_bins_full,
                                    self.is_cat_full, self.has_nan_full,
                                    self.local_params, self.monotone_full,
                                    bound, depth, parent_out=parent_out)
        gain = jnp.where(feature_mask, fs.gain, NEG_INF)
        # 2. local top-k vote -> allgather (LightSplitInfo allgather :322)
        _, top_ids = jax.lax.top_k(gain, k)
        note_collective("voting_parallel/vote_allgather", "all_gather",
                        top_ids)
        all_ids = jax.lax.all_gather(top_ids, self.axis_name)  # (ndev, k)
        # 3. global voting: feature vote counts, top-2k selected
        #    (GlobalVoting :151); ties break toward lower feature index via
        #    a small index-based epsilon
        votes = jnp.zeros((self.num_features,), jnp.float32).at[
            all_ids.reshape(-1)].add(1.0, mode="drop")
        anti_index = -jnp.arange(self.num_features, dtype=jnp.float32) * 1e-6
        _, selected = jax.lax.top_k(votes + anti_index, min(2 * k,
                                                           self.num_features))
        # 4. aggregate only the selected features' histograms (the 2k*B psum
        #    replacing the F*B reduce-scatter)
        sel_local = hist_local[selected]
        note_collective("voting_parallel/voted_hist_psum", "psum",
                        sel_local)
        hist_sel = jax.lax.psum(sel_local, self.axis_name)
        nb = self.num_bins_full[selected]
        ic = self.is_cat_full[selected]
        hn = self.has_nan_full[selected]
        fm = feature_mask[selected]
        mono = self.monotone_full[selected] \
            if self.monotone_full is not None else None
        g, f_loc, b, dl, ls, rs, member = local_best_candidate(
            hist_sel, leaf_sum, nb, ic, hn, fm, params, mono, bound, depth, parent_out=parent_out)
        return (g, selected[f_loc], b, dl, ls, rs, member)

    def pair_candidates(self, hist_l, hist_r, lsum, rsum, feature_mask,
                        params, bound_l, bound_r, depth, fm_l=None,
                        fm_r=None, po_l=None, po_r=None):
        # collectives are not vmap-batched: two sequential candidate calls
        return (self.leaf_candidates(
                    hist_l, lsum,
                    feature_mask if fm_l is None else fm_l, params,
                    bound_l, depth, po_l),
                self.leaf_candidates(
                    hist_r, rsum,
                    feature_mask if fm_r is None else fm_r, params,
                    bound_r, depth, po_r))


class VotingParallelTreeLearner:
    name = "voting"

    def __init__(self, config: Config, num_features: int, max_bins: int,
                 num_bins: np.ndarray, is_cat: np.ndarray, has_nan: np.ndarray,
                 monotone: Optional[np.ndarray] = None):
        self.config = config
        if config.use_quantized_grad:
            from ..utils.log import log_warning
            log_warning("use_quantized_grad is only applied by the wave "
                        "grower (serial / tree_learner=data); training "
                        "with exact gradients")
        self.max_bins = int(max_bins)
        self.num_features = num_features
        self.mesh = get_mesh(int(config.num_devices))
        self.ndev = self.mesh.devices.size
        self.axis = self.mesh.axis_names[0]
        self.num_bins = jnp.asarray(num_bins, jnp.int32)
        self.is_cat = jnp.asarray(is_cat, jnp.bool_)
        self.has_nan = jnp.asarray(has_nan, jnp.bool_)
        self.monotone = jnp.asarray(
            monotone if monotone is not None else np.zeros(num_features),
            jnp.int32)
        from ..learner.serial import resolve_monotone_method
        resolve_monotone_method(
            config, bool(config.monotone_constraints and
                         any(int(v) for v in
                             config.monotone_constraints)),
            wave=False)
        sp = split_params_from_config(config, num_bins, is_cat)
        local_sp = sp._replace(
            min_data_in_leaf=max(1, sp.min_data_in_leaf // self.ndev),
            min_sum_hessian_in_leaf=sp.min_sum_hessian_in_leaf / self.ndev)
        top_k = max(1, min(int(config.top_k), num_features))
        strategy = VotingStrategy(self.axis, top_k, num_features, self.ndev,
                                  self.num_bins, self.is_cat, self.has_nan,
                                  local_sp)
        grow_t = make_grow_fn(
            num_leaves=int(config.num_leaves), max_bins=self.max_bins,
            max_depth=int(config.max_depth), split_params=sp,
            hist_impl=resolve_hist_impl(config, parallel=True),
            rows_per_chunk=int(config.tpu_rows_per_chunk),
            use_hist_pool=hist_pool_fits(config, num_features, self.max_bins),
            strategy=strategy, jit=False)

        def grow(X, g, h, m, nb, ic, hn, mono, fm):
            return grow_t(X, None, g, h, m, nb, ic, hn, mono, fm)
        tree_specs = GrownTree(
            split_feature=P(), threshold_bin=P(), nan_bin=P(),
            cat_member=P(), decision_type=P(), left_child=P(), right_child=P(),
            split_gain=P(), internal_value=P(), internal_weight=P(),
            internal_count=P(), leaf_value=P(), leaf_weight=P(),
            leaf_count=P(), num_leaves=P(), row_leaf=P(self.axis),
            hist_passes=P())
        self._grow = jax.jit(shard_map_compat(
            grow, mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis), P(self.axis), P(self.axis),
                      P(), P(), P(), P(), P()),
            out_specs=tree_specs,
            check_vma=False))

    def train(self, X_dev: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
              sample_mask: jnp.ndarray,
              feature_mask: Optional[jnp.ndarray] = None) -> GrownTree:
        if feature_mask is None:
            feature_mask = jnp.ones((self.num_features,), jnp.bool_)
        n = X_dev.shape[0]
        pad = (-n) % self.ndev
        if pad:
            X_dev = jnp.pad(X_dev, ((0, pad), (0, 0)))
            grad = jnp.pad(grad, (0, pad))
            hess = jnp.pad(hess, (0, pad))
            sample_mask = jnp.pad(sample_mask, (0, pad))
        grown = self._grow(X_dev, grad, hess, sample_mask, self.num_bins,
                           self.is_cat, self.has_nan, self.monotone,
                           feature_mask)
        if pad:
            grown = grown._replace(row_leaf=grown.row_leaf[:n])
        return grown
