"""Voting-parallel tree learner (PV-Tree): data-parallel with top-k voting.

TPU-native re-implementation of the reference VotingParallelTreeLearner
(reference: src/treelearner/voting_parallel_tree_learner.cpp — local top-k
vote, Allgather of compact LightSplitInfo :322, ``GlobalVoting`` picks the
global top-2k features :151, ``CopyLocalHistogram`` into the reduce-scatter
layout :184, full scan only on aggregated features; local min_data /
min_hessian scaled by 1/num_machines :62-63; paper: Meng et al., "A
Communication-Efficient Parallel Algorithm for Decision Tree", NIPS 2016).

Rows are sharded like data-parallel, but instead of reducing the full
(F, B, 3) histogram, each shard votes its top-k features (``lax.top_k`` on
local gains), votes are combined with an ``all_gather`` of k feature ids per
shard, and only the winning 2k features' histogram slices are ``psum``'d —
the communication volume drops from F*B to 2k*B per leaf, the whole point of
the algorithm."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..config import Config
from ..learner.serial import (CommStrategy, GrownTree, local_best_candidate,
                              make_grow_fn, hist_pool_fits, resolve_hist_impl,
                              split_params_from_config)
from ..ops.split import NEG_INF, best_split_per_feature
from ..analysis.contracts import collective_contract, memory_budget
from ..telemetry.train_record import note_collective
from .mesh import get_mesh, shard_map_compat

__all__ = ["VotingParallelTreeLearner", "VotingStrategy",
           "WaveVotingStrategy", "QuantizedGradUnsupportedError",
           "modeled_pass_bytes", "voting_favored"]


class QuantizedGradUnsupportedError(ValueError):
    """use_quantized_grad requested on a grower that cannot honor it.

    The WAVE voting learner trains quantized for real (int32 voted
    slices psum exactly); only the masked sequential fallback cannot —
    and silently downgrading to exact gradients there would make two
    'identical' configs train different models."""


def _vote_budget(ctx):
    return 8 * max(2, int(ctx.get("leaves", 2)))


def _voted_hist_bytes(ctx):
    """The PV-Tree refinement (arXiv:1611.01276): only the voted top-2k
    features' histograms cross the wire — a (2k, B, 3) psum replacing
    the (F, B, 3) merge; 2k defaults to ctx['top_k']*2 but never exceeds
    the full feature space."""
    two_k = min(2 * int(ctx.get("top_k", 10)), int(ctx["features"]))
    return two_k * int(ctx["bins"]) * 3 * int(ctx.get("itemsize", 4))


collective_contract("voting_parallel/leaf_sum", "psum",
                    max_count=_vote_budget, max_bytes_per_op=256)
collective_contract("voting_parallel/vote_allgather", "all_gather",
                    max_count=_vote_budget,
                    max_bytes_per_op=lambda ctx: 8 * int(
                        ctx.get("top_k", 10)),
                    note="local top-k feature ids, O(k) ints")
collective_contract("voting_parallel/voted_hist_psum", "psum",
                    max_count=_vote_budget,
                    max_bytes_per_op=_voted_hist_bytes,
                    note="top-2k voted feature histograms only")


# ---------------------------------------------------------------------------
# Contracts for the WAVE voting learner's sites (WaveVotingStrategy below;
# the per-wave machinery lives in learner/wave.py _voting_candidates).
# Counts mirror the DP-wave merge budget: one vote + one voted psum per
# candidate-scan site (root / wave body / endgame, plus the spec-ramp
# provisional passes), because the voted merge IS the merge on this path.
# Cross-host (DCN) limits: on a host-major 1-D mesh a hierarchical
# collective moves (H-1)/H of the payload over DCN — declared explicitly
# so lint-trace at abstract W=64 bounds the pod bytes, not just the
# per-op payload (analysis/contracts.py max_dcn_bytes_per_op).
# ---------------------------------------------------------------------------

def _wave_vote_budget(ctx):
    from ..learner.wave import _wave_merge_budget
    return _wave_merge_budget(ctx)


def _wave_vote_ids_bytes(ctx):
    """all_gather operand: (k_leaves, top_k) int32 feature ids — O(W*k)
    ints, never a histogram."""
    from ..learner.wave import WAVE_SIZE
    w = int(ctx.get("wave_size", WAVE_SIZE))
    return (4 * max(2 * w, int(ctx.get("leaves", 2 * w))) *
            int(ctx.get("top_k", 10)))


def _wave_voted_batch_bytes(ctx):
    """The voted merge payload: (k_leaves, min(2k, F), B, 3) selected
    slices — the 2k/F refinement of the full (k_leaves, F, B, 3) psum."""
    from ..learner.wave import WAVE_SIZE
    w = int(ctx.get("wave_size", WAVE_SIZE))
    two_k = min(2 * int(ctx.get("top_k", 10)), int(ctx["features"]))
    return (max(2 * w, int(ctx.get("leaves", 2 * w))) * two_k *
            int(ctx["bins"]) * 3 * int(ctx.get("itemsize", 4)))


def _dcn(limit):
    """DCN ceiling: the modeled cross-host share — (H-1)/H on a
    host-major axis, analysis.contracts.dcn_fraction — of a payload."""
    def dcn_bytes(ctx):
        from ..analysis.contracts import dcn_fraction
        base = limit(ctx) if callable(limit) else limit
        return base * dcn_fraction(ctx)
    return dcn_bytes


def _wave_exchange_bytes(ctx):
    from ..learner.wave import _exchange_payload_bytes
    return _exchange_payload_bytes(ctx)


def _wave_full_batch_bytes(ctx):
    from ..learner.wave import _hist_batch_bytes
    return _hist_batch_bytes(ctx)


collective_contract(
    "voting_parallel/wave/vote_allgather", "all_gather",
    max_count=_wave_vote_budget, max_bytes_per_op=_wave_vote_ids_bytes,
    max_dcn_bytes_per_op=_dcn(_wave_vote_ids_bytes),
    note="local top-k feature-id vote per scan site, O(W*k) ints")
collective_contract(
    "voting_parallel/wave/voted_hist_psum", "psum",
    max_count=_wave_vote_budget, max_bytes_per_op=_wave_voted_batch_bytes,
    max_dcn_bytes_per_op=_dcn(_wave_voted_batch_bytes),
    note="voted top-2k feature slices only — the PV-Tree merge")
collective_contract(
    "voting_parallel/wave/hist_psum", "psum",
    max_count=_wave_vote_budget, max_bytes_per_op=_wave_full_batch_bytes,
    max_dcn_bytes_per_op=_dcn(_wave_full_batch_bytes),
    note="full-batch fallback merge for voting-gated shapes (cats/EFB)")
collective_contract(
    "voting_parallel/wave/scalar_sum", "psum",
    max_count=8, max_bytes_per_op=_wave_exchange_bytes,
    max_dcn_bytes_per_op=_dcn(_wave_exchange_bytes),
    note="leaf totals / root sums — small vectors only")
collective_contract(
    "voting_parallel/wave/quant_scale", "pmax",
    max_count=2, max_bytes_per_op=8, max_dcn_bytes_per_op=8,
    note="global gradient/hessian quantization scales (two scalars)")


# ---------------------------------------------------------------------------
# Memory budget for the voting-wave program (lint-mem enforced).  Voting
# trades WIRE bytes, not resident bytes: every device keeps FULL-F local
# kernel banks AND the full-F per-leaf pool (only the voted 2k slices
# are psum'd), so unlike the scatter path there is no post-merge F/k
# slicing — the pool and scan temporaries stay on all F features.
# ---------------------------------------------------------------------------

def voting_wave_hbm_bytes(ctx):
    """Per-device HBM curve of one voting-wave tree program: the DP
    local-bank term plus pool/scan temporaries on FULL F (the voted
    merge never slices the resident histograms)."""
    from ..learner.wave import Q_WAVE_SIZE, WAVE_SIZE
    from ..analysis.contracts import world_size
    k = world_size(ctx)
    f = int(ctx["features"])
    b = int(ctx["bins"])
    it = int(ctx.get("itemsize", 4))
    r = -(-int(ctx["rows"]) // k)
    wave = int(ctx.get("wave_size", WAVE_SIZE))
    kernel_ch = Q_WAVE_SIZE if ctx.get("quantized", True) else WAVE_SIZE
    local_banks = int(2.5 * max(2 * wave, kernel_ch) * f * b * 3 * it)
    pool = (int(ctx.get("leaves", 2)) + 6 * wave) * f * b * 3 * it
    rows = r * (f + 24)
    return local_banks + pool + rows + (1 << 20)


memory_budget(
    "voting_parallel/wave_full", ("voting",), voting_wave_hbm_bytes,
    note="2.5 local full-F kernel banks + full-F pool/scan (voting "
         "slices the wire, not the residents) + rows")


# ---------------------------------------------------------------------------
# Modeled bytes per histogram pass: the auto-selection rule and the
# multichip artifact both read this ONE model, so the CI snapshot and the
# learner pick cannot drift.
# ---------------------------------------------------------------------------

def modeled_pass_bytes(num_features: int, bins: int, top_k: int,
                       world: int, *, wave: int = 0, itemsize: int = 4,
                       devices_per_host: int = 8) -> dict:
    """Modeled per-pass histogram-merge bytes for the DP reduce-scatter
    path vs the voting path at world size ``world``, split per-host
    (ICI) vs cross-host (DCN) assuming a host-major 1-D axis with
    ``devices_per_host`` devices per host.

    Reduce-scatter moves the whole (W, F, B, 3) batch once around the
    ring (each shard receives its F/k block fully reduced); voting moves
    the O(k) vote ids plus the (W, 2k, B, 3) selected slices, allreduced
    (2x a reduce-scatter's volume for the slice payload)."""
    from ..learner.wave import WAVE_SIZE
    w = int(wave) or WAVE_SIZE
    hosts_ = max(1, int(world) // max(1, int(devices_per_host)))
    dcn = (hosts_ - 1) / hosts_ if hosts_ > 1 else 0.0
    two_k = min(2 * int(top_k), int(num_features))
    ch = 3 * int(itemsize) * int(bins) * w
    full = int(num_features) * ch          # (W, F, B, 3) batch bytes
    voted = two_k * ch                     # (W, 2k, B, 3) voted slices
    vote_ids = 4 * w * int(top_k) * int(world)   # gathered id payload
    rs_total = full                        # reduce-scatter: ~1x volume
    vote_total = 2 * voted + vote_ids      # allreduce: ~2x + the vote
    return {
        "world": int(world),
        "hosts": hosts_,
        "reduce_scatter": {
            "total": rs_total,
            "cross_host": int(rs_total * dcn),
            "per_host": int(rs_total * (1.0 - dcn)),
        },
        "voting": {
            "total": vote_total,
            "cross_host": int(vote_total * dcn),
            "per_host": int(vote_total * (1.0 - dcn)),
        },
        "voted_full_ratio": voted / full,
    }


#: world size at or above which ``tree_learner=auto`` considers voting
AUTO_VOTING_MIN_WORLD = 4


def voting_favored(num_features: int, bins: int, top_k: int,
                   world: int, **kw) -> bool:
    """The ``tree_learner=auto`` flip rule: voting wins when its modeled
    CROSS-HOST bytes per pass undercut the reduce-scatter path's (PV-Tree
    is a DCN optimisation — on a single host the scatter path's exact
    merge is strictly better)."""
    if int(world) < AUTO_VOTING_MIN_WORLD:
        return False
    m = modeled_pass_bytes(num_features, bins, top_k, world, **kw)
    if m["hosts"] > 1:
        return m["voting"]["cross_host"] < m["reduce_scatter"]["cross_host"]
    return m["voting"]["total"] < m["reduce_scatter"]["total"]


class WaveVotingStrategy(CommStrategy):
    """Row-sharded strategy for the WAVE grower with the PV-Tree voted
    merge (learner/wave.py use_voting): the per-leaf histogram pool stays
    shard-LOCAL and each candidate scan votes, all_gathers O(k) feature
    ids and psums only the voted top-2k feature slices — per-leaf wire
    volume drops from F*B to 2k*B, the communication-efficient recipe
    for DCN-bound pod meshes (arXiv:1611.01276).

    Voting-gated shapes (cats / EFB / lazy CEGB / forced splits) fall
    back to ``reduce_hist``'s full-batch psum, so every config still
    trains correctly.  ``spec_ok`` unlocks the speculative ramp: the
    provisional passes vote exactly like committed waves."""

    rows_sharded = True
    spec_ok = True
    hist_voting = True

    def __init__(self, axis_name: str, nshards: int = 1, top_k: int = 20,
                 local_params=None):
        self.axis_name = axis_name
        self.nshards = int(nshards)
        self.top_k = int(top_k)
        self.local_params = local_params
        self.monotone_full = None

    def reduce_sum(self, v):
        note_collective("voting_parallel/wave/scalar_sum", "psum", v)
        return jax.lax.psum(v, self.axis_name)

    def reduce_max(self, v):
        """Global quantization scales (shared with the DP wave path)."""
        note_collective("voting_parallel/wave/quant_scale", "pmax", v)
        return jax.lax.pmax(v, self.axis_name)

    def shard_key(self, key):
        """Independent stochastic-rounding streams per row shard."""
        return jax.random.fold_in(key, jax.lax.axis_index(self.axis_name))

    def reduce_hist(self, hist):
        # fallback full-batch merge for the voting-gated configs — and
        # the single collective those configs pay per wave
        note_collective("voting_parallel/wave/hist_psum", "psum", hist)
        return jax.lax.psum(hist, self.axis_name)

    def vote_allgather(self, top_ids):
        """(k_leaves, top_k) local winner ids -> (nshards, k_leaves,
        top_k): the ONLY full-world exchange the vote needs."""
        note_collective("voting_parallel/wave/vote_allgather",
                        "all_gather", top_ids)
        return jax.lax.all_gather(top_ids, self.axis_name)

    def reduce_hist_voted(self, sel):
        """Exact merge of the voted (k_leaves, 2k, B, 3) slices —
        int32 under quantized gradients, so the sum is order-free."""
        note_collective("voting_parallel/wave/voted_hist_psum", "psum",
                        sel)
        return jax.lax.psum(sel, self.axis_name)


class VotingStrategy(CommStrategy):
    rows_sharded = True
    def __init__(self, axis_name, top_k, num_features, ndev,
                 num_bins, is_cat, has_nan, local_params):
        super().__init__(num_bins, is_cat, has_nan)
        self.axis_name = axis_name
        self.top_k = top_k
        self.num_features = num_features
        self.ndev = ndev
        self.local_params = local_params  # 1/num_machines-scaled constraints

    def reduce_sum(self, v):
        note_collective("voting_parallel/leaf_sum", "psum", v)
        return jax.lax.psum(v, self.axis_name)

    # reduce_hist stays identity: the pool keeps shard-LOCAL histograms and
    # only voted features are aggregated below.

    def leaf_candidates(self, hist_local, leaf_sum, feature_mask, params,
                        bound=None, depth=None, parent_out=None):
        k = self.top_k
        # 1. local candidate gains with relaxed (1/num_machines) constraints
        #    (voting_parallel_tree_learner.cpp:62-63)
        local_sum = leaf_sum / self.ndev
        fs = best_split_per_feature(hist_local, local_sum, self.num_bins_full,
                                    self.is_cat_full, self.has_nan_full,
                                    self.local_params, self.monotone_full,
                                    bound, depth, parent_out=parent_out)
        gain = jnp.where(feature_mask, fs.gain, NEG_INF)
        # 2. local top-k vote -> allgather (LightSplitInfo allgather :322)
        _, top_ids = jax.lax.top_k(gain, k)
        note_collective("voting_parallel/vote_allgather", "all_gather",
                        top_ids)
        all_ids = jax.lax.all_gather(top_ids, self.axis_name)  # (ndev, k)
        # 3. global voting: feature vote counts, top-2k selected
        #    (GlobalVoting :151); ties break toward lower feature index via
        #    a small index-based epsilon
        votes = jnp.zeros((self.num_features,), jnp.float32).at[
            all_ids.reshape(-1)].add(1.0, mode="drop")
        anti_index = -jnp.arange(self.num_features, dtype=jnp.float32) * 1e-6
        _, selected = jax.lax.top_k(votes + anti_index, min(2 * k,
                                                           self.num_features))
        # 4. aggregate only the selected features' histograms (the 2k*B psum
        #    replacing the F*B reduce-scatter)
        sel_local = hist_local[selected]
        note_collective("voting_parallel/voted_hist_psum", "psum",
                        sel_local)
        hist_sel = jax.lax.psum(sel_local, self.axis_name)
        nb = self.num_bins_full[selected]
        ic = self.is_cat_full[selected]
        hn = self.has_nan_full[selected]
        fm = feature_mask[selected]
        mono = self.monotone_full[selected] \
            if self.monotone_full is not None else None
        g, f_loc, b, dl, ls, rs, member = local_best_candidate(
            hist_sel, leaf_sum, nb, ic, hn, fm, params, mono, bound, depth, parent_out=parent_out)
        return (g, selected[f_loc], b, dl, ls, rs, member)

    def pair_candidates(self, hist_l, hist_r, lsum, rsum, feature_mask,
                        params, bound_l, bound_r, depth, fm_l=None,
                        fm_r=None, po_l=None, po_r=None):
        # collectives are not vmap-batched: two sequential candidate calls
        return (self.leaf_candidates(
                    hist_l, lsum,
                    feature_mask if fm_l is None else fm_l, params,
                    bound_l, depth, po_l),
                self.leaf_candidates(
                    hist_r, rsum,
                    feature_mask if fm_r is None else fm_r, params,
                    bound_r, depth, po_r))


class VotingParallelTreeLearner:
    """Two growers, like the DP learner: the WAVE grower with the voted
    merge (first-class: quantized gradients, exact endgame, spec ramp —
    learner/wave.py use_voting + WaveVotingStrategy) and the masked
    sequential grower with per-scan voting (VotingStrategy; off-TPU
    fallback).  The masked fallback cannot train quantized — that combo
    raises QuantizedGradUnsupportedError instead of silently training a
    different model."""

    name = "voting"

    def __init__(self, config: Config, num_features: int, max_bins: int,
                 num_bins: np.ndarray, is_cat: np.ndarray, has_nan: np.ndarray,
                 monotone: Optional[np.ndarray] = None):
        self.config = config
        self.max_bins = int(max_bins)
        self.num_features = num_features
        self.mesh = get_mesh(int(config.num_devices))
        self.ndev = self.mesh.devices.size
        self.axis = self.mesh.axis_names[0]
        self.num_bins = jnp.asarray(num_bins, jnp.int32)
        self.is_cat = jnp.asarray(is_cat, jnp.bool_)
        self.has_nan = jnp.asarray(has_nan, jnp.bool_)
        self.monotone = jnp.asarray(
            monotone if monotone is not None else np.zeros(num_features),
            jnp.int32)
        self.top_k = max(1, min(int(config.top_k), num_features))
        sp = split_params_from_config(config, num_bins, is_cat)
        local_sp = sp._replace(
            min_data_in_leaf=max(1, sp.min_data_in_leaf // self.ndev),
            min_sum_hessian_in_leaf=sp.min_sum_hessian_in_leaf / self.ndev)
        self._local_sp = local_sp
        mode = str(config.tree_grow_mode)
        impl_wave = resolve_hist_impl(config, parallel=True, wave=True,
                                      max_bins=self.max_bins)
        wave_able = (int(config.num_leaves) > 2 and
                     hist_pool_fits(config, num_features, self.max_bins))
        self.wave = wave_able and (mode == "wave" or
                                   (mode == "auto" and
                                    impl_wave == "pallas"))
        if not self.wave and config.use_quantized_grad and wave_able \
                and mode != "partition":
            # quantized voting is a wave-grower feature; ride it rather
            # than refuse when the config merely defaulted off-TPU
            self.wave = True
        if self.wave:
            self._init_wave(config, num_features, num_bins, is_cat,
                            has_nan, monotone, impl_wave, sp, local_sp)
            return
        self.quantized = False
        self.supports_extras = False
        if config.use_quantized_grad:
            raise QuantizedGradUnsupportedError(
                "use_quantized_grad with tree_learner=voting requires the "
                "wave grower (tree_grow_mode=wave, or auto on TPU); the "
                "masked voting grower trains exact gradients only — "
                "drop use_quantized_grad or enable the wave grower")
        from ..learner.serial import resolve_monotone_method
        resolve_monotone_method(
            config, bool(config.monotone_constraints and
                         any(int(v) for v in
                             config.monotone_constraints)),
            wave=False)
        strategy = VotingStrategy(self.axis, self.top_k, num_features,
                                  self.ndev, self.num_bins, self.is_cat,
                                  self.has_nan, local_sp)
        grow_t = make_grow_fn(
            num_leaves=int(config.num_leaves), max_bins=self.max_bins,
            max_depth=int(config.max_depth), split_params=sp,
            hist_impl=resolve_hist_impl(config, parallel=True),
            rows_per_chunk=int(config.tpu_rows_per_chunk),
            use_hist_pool=hist_pool_fits(config, num_features, self.max_bins),
            strategy=strategy, jit=False)

        def grow(X, g, h, m, nb, ic, hn, mono, fm):
            return grow_t(X, None, g, h, m, nb, ic, hn, mono, fm)
        tree_specs = self._tree_specs(self.axis)
        self._grow = jax.jit(shard_map_compat(
            grow, mesh=self.mesh,
            in_specs=(P(self.axis), P(self.axis), P(self.axis), P(self.axis),
                      P(), P(), P(), P(), P()),
            out_specs=tree_specs,
            check_vma=False))

    @staticmethod
    def _tree_specs(axis):
        return GrownTree(
            split_feature=P(), threshold_bin=P(), nan_bin=P(),
            cat_member=P(), decision_type=P(), left_child=P(),
            right_child=P(), split_gain=P(), internal_value=P(),
            internal_weight=P(), internal_count=P(), leaf_value=P(),
            leaf_weight=P(), leaf_count=P(), num_leaves=P(),
            row_leaf=P(axis), hist_passes=P())

    def _init_wave(self, config, num_features, num_bins, is_cat, has_nan,
                   monotone, impl, sp, local_sp):
        from ..learner.wave import make_wave_grow_fn
        from ..ops.quantize import quant_levels
        self.pallas = impl == "pallas"
        self._x_src = None
        self.supports_extras = True
        self.quantized = bool(config.use_quantized_grad)
        if np.any(np.asarray(is_cat)):
            # voting gates cats off inside the grower (full-batch psum
            # fallback) but the wave scan still runs full feature space
            sp = sp._replace(cat_idx=tuple(
                int(j) for j in np.where(np.asarray(is_cat))[0]))
        self.split_params = sp
        from ..learner.serial import resolve_monotone_method
        mc_inter = resolve_monotone_method(config, sp.use_monotone,
                                           wave=True)
        self._use_node_key = sp.feature_fraction_bynode < 1.0 or \
            sp.extra_trees
        gq_max, hq_max = quant_levels(int(config.num_grad_quant_bins))
        strategy = WaveVotingStrategy(self.axis, nshards=self.ndev,
                                      top_k=self.top_k,
                                      local_params=local_sp)
        grow_w = make_wave_grow_fn(
            num_leaves=int(config.num_leaves), num_features=num_features,
            max_bins=self.max_bins, max_depth=int(config.max_depth),
            split_params=sp,
            hist_impl=impl, any_cat=bool(np.any(np.asarray(is_cat))),
            wave_size=int(config.tpu_wave_size), strategy=strategy,
            jit=False, quantized=self.quantized, gq_max=gq_max,
            hq_max=hq_max,
            renew_leaf=bool(config.quant_train_renew_leaf),
            stochastic=bool(config.stochastic_rounding),
            mc_inter=mc_inter,
            spec_ramp=bool(config.tpu_speculative_ramp),
            spec_tol=float(config.tpu_spec_tolerance),
            exact_endgame=bool(config.tpu_exact_endgame))

        nq = int(self.quantized)
        nn = int(self._use_node_key)

        def grow(X_T, g, h, m, nb, ic, hn, mono, fm, cegb, *rest):
            kw = {}
            ki = 0
            if nq:
                kw["quant_key"] = rest[ki]
                ki += 1
            if nn:
                kw["node_key"] = rest[ki]
            return grow_w(X_T, g, h, m, nb, ic, hn, mono, cegb, (), fm,
                          **kw)

        tree_specs = self._tree_specs(self.axis)
        self._grow = jax.jit(shard_map_compat(
            grow, mesh=self.mesh,
            in_specs=(P(None, self.axis), P(self.axis), P(self.axis),
                      P(self.axis), P(), P(), P(), P(), P(), P()) +
            (P(),) * (nq + nn),
            out_specs=tree_specs,
            check_vma=False))

    def train(self, X_dev: jnp.ndarray, grad: jnp.ndarray, hess: jnp.ndarray,
              sample_mask: jnp.ndarray,
              feature_mask: Optional[jnp.ndarray] = None,
              quant_key=None, cegb_penalty=None,
              node_key=None) -> GrownTree:
        if feature_mask is None:
            feature_mask = jnp.ones((self.num_features,), jnp.bool_)
        n = X_dev.shape[0]
        if self.wave:
            if self.pallas:
                from ..ops.histogram_pallas import DEFAULT_ROW_BLOCK
                quantum = self.ndev * DEFAULT_ROW_BLOCK
            else:
                quantum = self.ndev * 8
            pad = (-n) % quantum
            if self._x_src is not X_dev:
                Xp = jnp.pad(X_dev, ((0, pad), (0, 0))) if pad else X_dev
                self._XpT = jnp.asarray(jnp.swapaxes(Xp, 0, 1))
                self._x_src = X_dev
            if pad:
                grad = jnp.pad(grad, (0, pad))
                hess = jnp.pad(hess, (0, pad))
                sample_mask = jnp.pad(sample_mask, (0, pad))
            if cegb_penalty is None:
                cegb_penalty = jnp.zeros((self.num_features,), jnp.float32)
            keys = []
            if self.quantized:
                if quant_key is None:
                    self._quant_calls = getattr(self, "_quant_calls", 0) + 1
                    quant_key = jax.random.PRNGKey(self._quant_calls)
                keys.append(quant_key)
            if self._use_node_key:
                if node_key is None:
                    node_key = jnp.zeros((2, 2), jnp.uint32)
                keys.append(node_key)
            grown = self._grow(self._XpT, grad, hess, sample_mask,
                               self.num_bins, self.is_cat, self.has_nan,
                               self.monotone, feature_mask, cegb_penalty,
                               *keys)
            if pad:
                grown = grown._replace(row_leaf=grown.row_leaf[:n])
            return grown
        pad = (-n) % self.ndev
        if pad:
            X_dev = jnp.pad(X_dev, ((0, pad), (0, 0)))
            grad = jnp.pad(grad, (0, pad))
            hess = jnp.pad(hess, (0, pad))
            sample_mask = jnp.pad(sample_mask, (0, pad))
        grown = self._grow(X_dev, grad, hess, sample_mask, self.num_bins,
                           self.is_cat, self.has_nan, self.monotone,
                           feature_mask)
        if pad:
            grown = grown._replace(row_leaf=grown.row_leaf[:n])
        return grown
