from .tree import Tree, TreeBatch, predict_binned, predict_raw
