"""Model text serialization, reference-compatible.

Implements the reference's versioned model text format
(reference: src/boosting/gbdt_model_text.cpp:311 ``SaveModelToString`` — the
``version=v3`` header + per-tree blocks from src/io/tree.cpp:343
``Tree::ToString`` — and :583 model parsing; JSON dump per
gbdt_model_text.cpp:24 ``DumpModel``), so models interchange with the
reference implementation: a model trained here loads in reference LightGBM
and vice versa.

Split feature indices in the file are REAL (original column) indices; in
device tree arrays they are inner (used-feature) indices — the maps convert
on save/load (reference Dataset real<->inner feature mapping,
dataset.h:282)."""

from __future__ import annotations

import io
from typing import Any, Dict, List

import numpy as np

from .tree import CAT_MASK, DEFAULT_LEFT_MASK, Tree

MODEL_VERSION = "v3"


class ModelCorruptError(ValueError):
    """A model text file/string is truncated or not a model at all.

    Typed so callers (serving registry reloads, checkpoint restore, CLI)
    can distinguish "this file is damaged" from ordinary ValueErrors;
    names the source and the byte offset where parsing failed — which
    for a crash-truncated file is its (short) length."""

    def __init__(self, source: str, offset: int, detail: str) -> None:
        super().__init__(f"{source}: corrupt or truncated model text at "
                         f"byte {offset}: {detail}")
        self.source = source
        self.offset = int(offset)


def _offset_of(lines: List[str], idx: int) -> int:
    """Byte offset of ``lines[idx]`` in the original utf-8 text (lines
    were split on '\\n', so each earlier line contributes len + 1)."""
    return sum(len(ln.encode("utf-8")) + 1 for ln in lines[:min(idx, len(lines))])


def _fmt(x: float) -> str:
    # %.17g round-trips doubles exactly (reference Common::DoubleToStr);
    # positional formatting would truncate tiny magnitudes to "0"
    return f"{float(x):.17g}"


def _join(arr, fmt=str) -> str:
    return " ".join(fmt(v) for v in arr)


def _objective_string(gbdt) -> str:
    cfg = gbdt.config
    obj = cfg.objective
    if obj == "binary":
        return f"binary sigmoid:{cfg.sigmoid:g}"
    if obj in ("multiclass", "multiclassova"):
        suffix = f" num_class:{cfg.num_class}"
        if obj == "multiclassova":
            return f"multiclassova{suffix} sigmoid:{cfg.sigmoid:g}"
        return f"multiclass{suffix}"
    if obj in ("lambdarank", "rank_xendcg"):
        return obj
    return obj


def _tree_to_string(tree: Tree, real_feature_map: np.ndarray, index: int) -> str:
    n_int = tree.num_internal()
    nl = tree.num_leaves
    buf = io.StringIO()
    buf.write(f"Tree={index}\n")
    buf.write(f"num_leaves={nl}\n")
    cat_nodes = [i for i in range(n_int)
                 if tree.decision_type[i] & CAT_MASK]
    buf.write(f"num_cat={len(cat_nodes)}\n")
    if nl > 1:
        real_feat = [int(real_feature_map[tree.split_feature[i]])
                     for i in range(n_int)]
        buf.write("split_feature=" + _join(real_feat) + "\n")
        buf.write("split_gain=" + _join(tree.split_gain[:n_int], _fmt) + "\n")
        # categorical nodes store the index into cat_boundaries as threshold;
        # cat_threshold carries the full bitset words over raw category
        # values (reference tree.cpp Tree::ToString cat fields)
        thresholds = []
        cat_boundaries = [0]
        cat_threshold: List[int] = []
        for i in range(n_int):
            if tree.decision_type[i] & CAT_MASK:
                if tree.cat_boundaries is not None:
                    rank = int(tree.threshold[i])
                    lo = int(tree.cat_boundaries[rank])
                    hi = int(tree.cat_boundaries[rank + 1])
                    words = [int(w) for w in tree.cat_threshold[lo:hi]]
                else:  # legacy single-category node
                    cat_val = int(tree.threshold[i])
                    words = [0] * (cat_val // 32 + 1)
                    words[cat_val // 32] |= 1 << (cat_val % 32)
                thresholds.append(float(len(cat_boundaries) - 1))
                cat_threshold.extend(words)
                cat_boundaries.append(len(cat_threshold))
            else:
                thresholds.append(float(tree.threshold[i]))
        buf.write("threshold=" + _join(thresholds, _fmt) + "\n")
        buf.write("decision_type=" + _join(tree.decision_type[:n_int]) + "\n")
        buf.write("left_child=" + _join(tree.left_child[:n_int]) + "\n")
        buf.write("right_child=" + _join(tree.right_child[:n_int]) + "\n")
        buf.write("leaf_value=" + _join(tree.leaf_value[:nl], _fmt) + "\n")
        buf.write("leaf_weight=" + _join(tree.leaf_weight[:nl], _fmt) + "\n")
        buf.write("leaf_count=" + _join(tree.leaf_count[:nl].astype(int)) + "\n")
        buf.write("internal_value=" + _join(tree.internal_value[:n_int], _fmt) + "\n")
        buf.write("internal_weight=" + _join(tree.internal_weight[:n_int], _fmt) + "\n")
        buf.write("internal_count=" + _join(tree.internal_count[:n_int].astype(int)) + "\n")
        if cat_nodes:
            buf.write("cat_boundaries=" + _join(cat_boundaries) + "\n")
            buf.write("cat_threshold=" + _join(cat_threshold) + "\n")
    else:
        buf.write("leaf_value=" + _fmt(tree.leaf_value[0]) + "\n")
    if tree.is_linear:
        # per-leaf linear models (reference tree.cpp:378-399 linear fields:
        # leaf_const + per-leaf feature lists/coefficients, flattened)
        buf.write("is_linear=1\n")
        buf.write("leaf_const=" + _join(tree.leaf_const[:nl], _fmt) + "\n")
        buf.write("num_features=" +
                  _join(len(f) for f in tree.leaf_features[:nl]) + "\n")
        buf.write("leaf_features=" + _join(
            f for fs in tree.leaf_features[:nl] for f in fs) + "\n")
        buf.write("leaf_coeff=" + _join(
            (c for cs in tree.leaf_coeff[:nl] for c in cs), _fmt) + "\n")
    else:
        buf.write("is_linear=0\n")
    buf.write(f"shrinkage={_fmt(tree.shrinkage)}\n")
    buf.write("\n")
    return buf.getvalue()


def model_to_string(gbdt, start_iteration: int = 0,
                    num_iteration: int = -1) -> str:
    ds = gbdt.train_set
    real_map, num_total, feature_names = gbdt.feature_mapping()
    if ds is not None:
        infos = []
        for j in range(num_total):
            m = ds.bin_mappers[j]
            if m.is_trivial:
                infos.append("none")
            elif m.is_categorical:
                infos.append(":".join(str(int(c)) for c in m.bin_to_cat))
            else:
                infos.append(f"[{_fmt(m.min_value)}:{_fmt(m.max_value)}]")
    else:
        infos = getattr(gbdt, "loaded_feature_infos", ["none"] * num_total)

    k = gbdt.num_tree_per_iteration
    t0 = start_iteration * k
    t1 = len(gbdt.models) if num_iteration <= 0 else min(
        len(gbdt.models), (start_iteration + num_iteration) * k)

    head = io.StringIO()
    head.write("tree\n")
    head.write(f"version={MODEL_VERSION}\n")
    head.write(f"num_class={gbdt.config.num_class}\n")
    head.write(f"num_tree_per_iteration={k}\n")
    head.write("label_index=0\n")
    head.write(f"max_feature_idx={num_total - 1}\n")
    head.write(f"objective={_objective_string(gbdt)}\n")
    if getattr(gbdt, "name", "gbdt") == "rf":
        head.write("average_output\n")
    head.write("feature_names=" + " ".join(feature_names) + "\n")
    head.write("feature_infos=" + " ".join(infos) + "\n")

    tree_strs = [_tree_to_string(gbdt.models[t], real_map, t - t0)
                 for t in range(t0, t1)]
    head.write("tree_sizes=" + _join(len(s) for s in tree_strs) + "\n\n")
    body = "".join(tree_strs)

    tail = io.StringIO()
    tail.write("end of trees\n\n")
    # feature_importance is full-length over ORIGINAL columns already
    imp = gbdt.feature_importance("split")
    pairs = sorted(((imp[i], feature_names[i])
                    for i in range(len(imp)) if imp[i] > 0), reverse=True)
    tail.write("feature_importances:\n")
    for val, name in pairs:
        tail.write(f"{name}={int(val)}\n")
    tail.write("\nparameters:\n")
    for key, value in sorted(gbdt.config.to_dict().items()):
        if key in ("resume", "checkpoint_dir", "checkpoint_keep",
                   "tpu_ingest_mode", "flight_recorder", "flight_events",
                   "flight_dir", "publish_dir", "publish_every"):
            # transient run directives, not training config: a preempted-
            # and-resumed run must produce byte-identical model text to
            # the run that never stopped, a shipped model must not embed
            # machine-local checkpoint paths, a model trained
            # streamed-chunked must match its in-core twin byte for byte,
            # and the flight recorder (observation only) must not fork
            # the model text between recorder-on and recorder-off runs
            continue
        if isinstance(value, list):
            value = ",".join(str(v) for v in value)
        tail.write(f"[{key}: {value}]\n")
    tail.write("end of parameters\n")
    tail.write("\npandas_categorical:null\n")
    return head.getvalue() + body + tail.getvalue()


def _parse_kv_block(lines: List[str], idx: int) -> Dict[str, str]:
    out = {}
    while idx < len(lines):
        line = lines[idx].strip()
        if not line:
            break
        if "=" in line:
            key, val = line.split("=", 1)
            out[key] = val
        idx += 1
    return out


def string_to_model(model_str: str, config, source: str = "<model string>"):
    """Parse a reference-format model file into a GBDT holding Tree objects
    (reference gbdt_model_text.cpp:583 LoadModelFromString).

    Raises :class:`ModelCorruptError` (naming ``source`` and the byte
    offset) on garbage input or a crash-truncated file instead of an
    arbitrary downstream parse exception."""
    from .gbdt import GBDT
    from .boosting import RF
    lines = model_str.split("\n")
    first = next((ln.strip() for ln in lines if ln.strip()), "")
    if first != "tree":
        raise ModelCorruptError(
            source, 0, "does not start with the 'tree' model header "
            f"(first content line: {first[:40]!r})")
    header: Dict[str, str] = {}
    i = 0
    average_output = False
    while i < len(lines):
        line = lines[i].strip()
        if line.startswith("Tree="):
            break
        if line == "average_output":
            average_output = True
        elif "=" in line:
            key, val = line.split("=", 1)
            header[key] = val
        i += 1

    num_class = int(header.get("num_class", 1))
    k = int(header.get("num_tree_per_iteration", 1))
    max_feature_idx = int(header.get("max_feature_idx", 0))
    objective = header.get("objective", "regression")
    obj_name = objective.split(" ")[0]
    params = {"num_class": num_class, "objective": obj_name}
    for tok in objective.split(" ")[1:]:
        if ":" in tok:
            pk, pv = tok.split(":", 1)
            if pk == "sigmoid":
                params["sigmoid"] = float(pv)
            elif pk == "num_class":
                params["num_class"] = int(pv)
    cfg = config.update(params)

    gbdt = RF(cfg, None) if average_output else GBDT(cfg, None)
    gbdt.config = cfg
    gbdt.num_tree_per_iteration = k
    gbdt.num_features = max_feature_idx + 1
    gbdt.train_set = None
    gbdt.loaded_feature_names = header.get(
        "feature_names", "").split(" ") if header.get("feature_names") else \
        [f"Column_{j}" for j in range(max_feature_idx + 1)]
    gbdt.loaded_feature_infos = header.get("feature_infos", "").split(" ")
    gbdt.loaded_real_map = np.arange(max_feature_idx + 1)
    gbdt.loaded_num_total = max_feature_idx + 1
    if gbdt.objective is None and obj_name not in ("none", ""):
        from ..objective import create_objective
        try:
            gbdt.objective = create_objective(obj_name, cfg)
        except ValueError:
            gbdt.objective = None

    # trees
    expected = None
    if header.get("tree_sizes", "").strip():
        expected = len(header["tree_sizes"].split())
    trees: List[Tree] = []
    saw_end = False
    while i < len(lines):
        line = lines[i].strip()
        if line.startswith("Tree="):
            block = _parse_kv_block(lines, i)
            try:
                trees.append(_tree_from_block(block))
            except (KeyError, ValueError, IndexError) as exc:
                raise ModelCorruptError(
                    source, _offset_of(lines, i),
                    f"tree {len(trees)} is unparseable "
                    f"({type(exc).__name__}: {exc})") from exc
            while i < len(lines) and lines[i].strip():
                i += 1
        elif line.startswith("end of trees"):
            saw_end = True
            break
        else:
            i += 1
    if expected is not None and len(trees) != expected:
        raise ModelCorruptError(
            source, _offset_of(lines, i),
            f"header declares {expected} trees (tree_sizes) but only "
            f"{len(trees)} parsed before the text ended — the file was "
            f"cut off mid-write")
    if not saw_end and expected is None:
        raise ModelCorruptError(
            source, _offset_of(lines, i),
            "neither a tree_sizes header nor an 'end of trees' marker — "
            "not a complete model text")
    gbdt.models = trees
    gbdt.iter_ = len(trees) // max(k, 1)
    return gbdt


def _tree_from_block(block: Dict[str, str]) -> Tree:
    nl = int(block["num_leaves"])
    n_int = max(nl - 1, 0)

    def arr(key, dtype, size, default=0):
        if key not in block or not block[key].strip():
            return np.full(size, default, dtype)
        vals = block[key].split()
        if len(vals) != size:
            # a crash-truncated file ends mid-line; the default-fill path
            # above must never paper over a short field
            raise ValueError(f"field '{key}' has {len(vals)} values, "
                             f"expected {size}")
        out = np.asarray([float(v) for v in vals], np.float64)
        return out.astype(dtype)

    if nl <= 1:
        lv = float(block.get("leaf_value", "0"))
        return Tree(num_leaves=1,
                    split_feature=np.zeros(0, np.int32),
                    threshold_bin=np.zeros(0, np.int32),
                    nan_bin=np.full(0, -1, np.int32),
                    threshold=np.zeros(0, np.float64),
                    decision_type=np.zeros(0, np.uint8),
                    left_child=np.zeros(0, np.int32),
                    right_child=np.zeros(0, np.int32),
                    split_gain=np.zeros(0, np.float32),
                    internal_value=np.zeros(0, np.float64),
                    internal_weight=np.zeros(0, np.float64),
                    internal_count=np.zeros(0, np.int64),
                    leaf_value=np.asarray([lv]),
                    leaf_weight=np.zeros(1),
                    leaf_count=np.zeros(1, np.int64),
                    shrinkage=float(block.get("shrinkage", 1.0)))

    for req in ("split_feature", "threshold", "left_child", "right_child",
                "leaf_value"):
        if not block.get(req, "").strip():
            raise ValueError(f"split node block is missing required "
                             f"field '{req}'")
    decision_type = arr("decision_type", np.uint8, n_int)
    threshold = arr("threshold", np.float64, n_int)
    num_cat = int(block.get("num_cat", 0))
    cat_boundaries = None
    cat_threshold = None
    if num_cat > 0:
        # full bitset splits survive the round trip; threshold stays the
        # rank into cat_boundaries (reference gbdt_model_text.cpp parsing)
        cat_boundaries = arr("cat_boundaries", np.int32, num_cat + 1)
        cat_threshold = arr("cat_threshold", np.uint32,
                            int(cat_boundaries[-1]) if num_cat else 0)

    is_linear = int(block.get("is_linear", 0)) != 0
    leaf_const = None
    leaf_coeff = None
    leaf_features = None
    if is_linear:
        leaf_const = arr("leaf_const", np.float64, nl)
        nfeat = arr("num_features", np.int64, nl)
        flat_f = [int(v) for v in block.get("leaf_features", "").split()]
        flat_c = [float(v) for v in block.get("leaf_coeff", "").split()]
        leaf_features = []
        leaf_coeff = []
        pos = 0
        for i in range(nl):
            k = int(nfeat[i])
            leaf_features.append(flat_f[pos:pos + k])
            leaf_coeff.append(flat_c[pos:pos + k])
            pos += k

    return Tree(
        cat_boundaries=cat_boundaries,
        cat_threshold=cat_threshold,
        is_linear=is_linear,
        leaf_const=leaf_const,
        leaf_coeff=leaf_coeff,
        leaf_features=leaf_features,
        leaf_features_inner=leaf_features,  # loaded models: identity map
        num_leaves=nl,
        split_feature=arr("split_feature", np.int32, n_int),
        threshold_bin=np.zeros(n_int, np.int32),  # unknown without a Dataset
        nan_bin=np.full(n_int, -1, np.int32),
        threshold=threshold,
        decision_type=decision_type,
        left_child=arr("left_child", np.int32, n_int),
        right_child=arr("right_child", np.int32, n_int),
        split_gain=arr("split_gain", np.float32, n_int),
        internal_value=arr("internal_value", np.float64, n_int),
        internal_weight=arr("internal_weight", np.float64, n_int),
        internal_count=arr("internal_count", np.int64, n_int),
        leaf_value=arr("leaf_value", np.float64, nl),
        leaf_weight=arr("leaf_weight", np.float64, nl),
        leaf_count=arr("leaf_count", np.int64, nl),
        shrinkage=float(block.get("shrinkage", 1.0)))


def model_to_dict(gbdt, start_iteration: int = 0,
                  num_iteration: int = -1) -> Dict[str, Any]:
    """JSON model dump (reference gbdt_model_text.cpp:24 DumpModel)."""
    real_map, _num_total, feature_names = gbdt.feature_mapping()
    k = gbdt.num_tree_per_iteration
    t0 = start_iteration * k
    t1 = len(gbdt.models) if num_iteration <= 0 else min(
        len(gbdt.models), (start_iteration + num_iteration) * k)

    def node_to_dict(tree: Tree, node: int) -> Dict[str, Any]:
        if node < 0:
            leaf = ~node
            return {"leaf_index": int(leaf),
                    "leaf_value": float(tree.leaf_value[leaf]),
                    "leaf_weight": float(tree.leaf_weight[leaf]),
                    "leaf_count": int(tree.leaf_count[leaf])}
        dt = int(tree.decision_type[node])
        thr = ("||".join(str(c) for c in tree.cat_values(node))
               if dt & CAT_MASK else float(tree.threshold[node]))
        return {
            "split_index": int(node),
            "split_feature": int(real_map[tree.split_feature[node]]),
            "split_gain": float(tree.split_gain[node]),
            "threshold": thr,
            "decision_type": "==" if dt & CAT_MASK else "<=",
            "default_left": bool(dt & DEFAULT_LEFT_MASK),
            "missing_type": ["None", "Zero", "NaN"][(dt >> 2) & 3],
            "internal_value": float(tree.internal_value[node]),
            "internal_weight": float(tree.internal_weight[node]),
            "internal_count": int(tree.internal_count[node]),
            "left_child": node_to_dict(tree, int(tree.left_child[node])),
            "right_child": node_to_dict(tree, int(tree.right_child[node])),
        }

    tree_infos = []
    for t in range(t0, t1):
        tree = gbdt.models[t]
        root = (node_to_dict(tree, 0) if tree.num_leaves > 1 else
                {"leaf_value": float(tree.leaf_value[0])})
        tree_infos.append({
            "tree_index": t - t0,
            "num_leaves": int(tree.num_leaves),
            "num_cat": 0,
            "shrinkage": float(tree.shrinkage),
            "tree_structure": root,
        })
    return {
        "name": "tree",
        "version": MODEL_VERSION,
        "num_class": gbdt.config.num_class,
        "num_tree_per_iteration": k,
        "label_index": 0,
        "max_feature_idx": len(feature_names) - 1,
        "objective": _objective_string(gbdt),
        "average_output": getattr(gbdt, "name", "gbdt") == "rf",
        "feature_names": feature_names,
        "feature_importances": {
            feature_names[i]: float(v)
            for i, v in enumerate(gbdt.feature_importance("split")) if v > 0},
        "tree_info": tree_infos,
    }
