"""GBDT boosting driver.

TPU-native re-implementation of the reference boosting layer
(reference: src/boosting/gbdt.cpp — ``Train`` loop at :264, ``TrainOneIter``
at :369, bagging at :228, ``BoostFromAverage`` at :344 with the init score
folded into the first tree via AddBias at :414-427, score updates via
ScoreUpdater at :491, metric output at :517).

The boosting loop is host-driven; everything per-iteration — gradients,
sampling, tree growth, score update — runs as jitted device computations on
device-resident arrays.  Host<->device traffic per iteration is only the
handful of tree description arrays (O(num_leaves)) pulled back to record the
model, plus metric scalars when evaluation runs.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import contracts as _contracts
from ..config import Config
from ..dataset import Dataset
from ..learner.serial import GrownTree, SerialTreeLearner
from ..metric import Metric, create_metrics
from ..objective import ObjectiveFunction, create_objective
from ..telemetry.train_record import TrainRecord, set_last_train_record
from ..utils.log import log_info, log_warning
from ..utils.random import host_rng
from ..utils.timer import FunctionTimer
from .tree import Tree, TreeBatch, pad_rows, predict_raw
from ..ops.split import leaf_output as _leaf_output_fn

EPSILON = 1e-12


def _grown_to_tree(grown: GrownTree, shrinkage: float, dataset: Dataset,
                   leaf_value_override: Optional[np.ndarray] = None) -> Tree:
    """Pull one grown tree to host, attach raw-value thresholds and
    categorical bitsets (reference tree.h:85 SplitCategorical: cat nodes
    store a rank into cat_boundaries; cat_threshold words are a bitset over
    raw category values)."""
    num_leaves = int(grown.num_leaves)
    split_feature = np.asarray(grown.split_feature)
    threshold_bin = np.asarray(grown.threshold_bin)
    decision_type = np.asarray(grown.decision_type)
    member = np.asarray(grown.cat_member)
    mappers = [dataset.bin_mappers[j] for j in dataset.used_feature_map]
    thresh = np.zeros(len(split_feature), dtype=np.float64)
    cat_boundaries: List[int] = [0]
    cat_words: List[int] = []
    has_cat = False
    for i in range(num_leaves - 1):
        f = int(split_feature[i])
        if f < 0:
            continue
        from .tree import CAT_MASK as _CM
        if decision_type[i] & _CM:
            has_cat = True
            bins = np.nonzero(member[i])[0]
            b2c = mappers[f].bin_to_cat
            cats = [int(b2c[b]) for b in bins if b < len(b2c)] or [0]
            nw = max(cats) // 32 + 1
            wd = np.zeros(nw, np.uint32)
            for c in cats:
                wd[c // 32] |= np.uint32(1 << (c % 32))
            thresh[i] = float(len(cat_boundaries) - 1)   # rank
            cat_words.extend(int(w) for w in wd)
            cat_boundaries.append(len(cat_words))
        else:
            thresh[i] = mappers[f].bin_to_value(int(threshold_bin[i]))
    tree = Tree(
        num_leaves=max(num_leaves, 1),
        split_feature=split_feature.astype(np.int32),
        threshold_bin=threshold_bin.astype(np.int32),
        nan_bin=np.asarray(grown.nan_bin, dtype=np.int32),
        threshold=thresh,
        decision_type=np.asarray(grown.decision_type).astype(np.uint8),
        left_child=np.asarray(grown.left_child).astype(np.int32),
        right_child=np.asarray(grown.right_child).astype(np.int32),
        split_gain=np.asarray(grown.split_gain),
        internal_value=np.asarray(grown.internal_value, dtype=np.float64),
        internal_weight=np.asarray(grown.internal_weight, dtype=np.float64),
        internal_count=np.asarray(grown.internal_count).astype(np.int64),
        leaf_value=(np.asarray(grown.leaf_value, dtype=np.float64)
                    if leaf_value_override is None
                    else np.asarray(leaf_value_override, dtype=np.float64)),
        leaf_weight=np.asarray(grown.leaf_weight, dtype=np.float64),
        leaf_count=np.asarray(grown.leaf_count).astype(np.int64),
        cat_boundaries=(np.asarray(cat_boundaries, np.int32)
                        if has_cat else None),
        cat_threshold=(np.asarray(cat_words, np.uint32)
                       if has_cat else None),
        cat_member_bins=member[:max(num_leaves - 1, 1)] if has_cat else None,
    )
    if shrinkage != 1.0:
        tree.shrink(shrinkage)
    return tree


def _tree_cat_member(tree: Tree) -> jnp.ndarray:
    """Binned categorical membership for a host tree's device walk (width-1
    zeros when the tree has no categorical nodes)."""
    if tree.cat_member_bins is not None:
        return jnp.asarray(tree.cat_member_bins)
    return jnp.zeros((max(len(tree.split_feature), 1), 1), jnp.bool_)


def _mappers_equal(a, b) -> bool:
    """Bin-mapper alignment by VALUE (reference dataset.h:304 CheckAlign) —
    identity fails for equal mappers reloaded from the binary dataset
    cache."""
    if len(a) != len(b):
        return False
    for ma, mb in zip(a, b):
        if (ma.num_bin != mb.num_bin or
                ma.is_categorical != mb.is_categorical or
                ma.missing_type != mb.missing_type):
            return False
        if ma.bin_upper_bound is not None or mb.bin_upper_bound is not None:
            if ma.bin_upper_bound is None or mb.bin_upper_bound is None or \
                    not np.array_equal(ma.bin_upper_bound,
                                       mb.bin_upper_bound):
                return False
        if ma.cat_to_bin != mb.cat_to_bin:
            return False
    return True


def _update_score_impl(score, row_leaf, leaf_value, shrinkage):
    """score += shrinkage * leaf_value[row_leaf] — training-set score update
    using the grower's final leaf assignment (replaces the reference's
    ScoreUpdater::AddScore tree walk for train data, score_updater.hpp:54)."""
    return score + shrinkage * leaf_value[row_leaf]


# Undonated entry: the multitrain driver vmaps this over the model axis
# (donation annotations do not survive inner-jit batching).
_update_score_by_leaf = jax.jit(_update_score_impl)

# Standalone boosting path: the incoming (N,)/(N,) column score buffer is
# dead after the call (``self.score`` is rebound to the result; the
# multiclass call site passes a fresh slice), so the buffer is donated
# and XLA updates the score in place instead of allocating a second
# N-row buffer per tree.  The aliasing contract — donated input aval
# must exactly match an output aval, or XLA silently copies — is
# machine-checked by ``lint-trace``'s donation rule via the declaration
# below.  TPU-only at dispatch: the XLA:CPU runtime in this jax version
# frees a donated buffer while earlier in-flight consumers of the same
# score array may still be reading it (observed as a hard runtime abort
# in the capi update path); on TPU the aliasing is what buys back an
# N-row HBM buffer per tree.
SCORE_DONATE_ARGNUMS = (0,)
_update_score_by_leaf_donated = jax.jit(
    _update_score_impl, donate_argnums=SCORE_DONATE_ARGNUMS)


def _score_update_entry():
    """The donated entry on TPU, the plain one elsewhere."""
    from ..utils.backend import default_backend
    if default_backend() == "tpu":
        return _update_score_by_leaf_donated
    return _update_score_by_leaf

_contracts.donation_contract(
    "gbdt/score_update", lambda: _update_score_by_leaf_donated,
    SCORE_DONATE_ARGNUMS,
    lambda: (jnp.zeros((64,), jnp.float32), jnp.zeros((64,), jnp.int32),
             jnp.zeros((8,), jnp.float32), np.float32(0.1)))


# -- host-side per-iteration sampling (pure functions of (config, iter)) ----
# Single-sourced here so the multi-model trainer (lightgbm_tpu/multitrain/)
# draws bit-identical bags/feature sets for every model in a batch: a
# train_many() variant and a standalone train() with the same seeds MUST
# sample the same rows/features or the bit-identity contract breaks.

def bagging_mask_np(cfg, n: int, iteration: int,
                    label: Optional[np.ndarray] = None,
                    rows: Optional[np.ndarray] = None) -> Optional[np.ndarray]:
    """Per-iteration bagging mask (gbdt.cpp:228 Bagging, resampled every
    bagging_freq iters with a deterministic per-block seed).

    Returns a float32 (n,) 0/1 mask, or None when bagging is inactive
    (caller keeps/creates the all-ones mask).  ``rows`` restricts the draw
    to those row indices: the generator then samples positions in
    ``range(len(rows))`` — exactly the draws a standalone run on the
    compacted ``dataset[rows]`` would make — and scatters back to full
    length (the masked-fold CV path of train_many)."""
    pos_neg = (cfg.objective == "binary" and
               (cfg.pos_bagging_fraction < 1.0 or
                cfg.neg_bagging_fraction < 1.0))
    if not (cfg.bagging_freq > 0 and (cfg.bagging_fraction < 1.0 or pos_neg)):
        return None
    block = iteration // cfg.bagging_freq
    rng = host_rng(cfg.bagging_seed, block)
    nn = n if rows is None else len(rows)
    sub = np.zeros(nn, np.float32)
    if pos_neg:
        # balanced bagging (gbdt.cpp:199 BaggingHelper pos/neg fractions)
        lab = label if rows is None else label[rows]
        pos = np.nonzero(lab > 0)[0]
        neg = np.nonzero(lab <= 0)[0]
        kp = int(len(pos) * cfg.pos_bagging_fraction)
        kn = int(len(neg) * cfg.neg_bagging_fraction)
        if kp:
            sub[rng.choice(pos, size=kp, replace=False)] = 1.0
        if kn:
            sub[rng.choice(neg, size=kn, replace=False)] = 1.0
    else:
        k = int(nn * cfg.bagging_fraction)
        sub[rng.choice(nn, size=k, replace=False)] = 1.0
    if rows is None:
        return sub
    mask = np.zeros(n, np.float32)
    mask[rows] = sub
    return mask


def goss_sample_np(cfg, grad: np.ndarray, hess: np.ndarray, iteration: int,
                   rows: Optional[np.ndarray] = None):
    """Host GOSS draw (goss.hpp:103-152): keep the top ``top_rate`` rows by
    |grad*hess|, Bernoulli-sample ``other_rate`` of the rest at b/(1-a) and
    amplify the survivors' gradients by (1-a)/b; sampling is skipped for the
    first 1/learning_rate iterations (goss.hpp:157).

    Single-sourced for the standalone trainer (models/boosting.py), the
    chunked streamed driver (ingest/train.py) and the multi-model trainer
    (multitrain/batched.py): one Philox stream per (bagging_seed, iteration)
    means all three paths thin exactly the same rows and the bit-identity
    contracts hold across them.  ``rows`` restricts the draw to those row
    indices (the masked-fold CV path): thresholds and Bernoulli draws are
    computed over the compacted subset — exactly what a standalone run on
    ``dataset[rows]`` would draw — and scattered back to full length.

    Returns ``(mask, mult)`` float32 (n,) arrays — 0/1 survivorship and the
    per-row gradient multiplier — or None when sampling is inactive this
    iteration (warmup, or top_rate+other_rate >= 1)."""
    a, b = float(cfg.top_rate), float(cfg.other_rate)
    warmup = int(1.0 / max(float(cfg.learning_rate), 1e-12))
    if iteration < warmup or a + b >= 1.0:
        return None
    grad = np.asarray(grad)
    hess = np.asarray(hess)
    score = np.abs(grad * hess)
    if score.ndim == 2:  # multiclass: sum |g*h| over classes (goss.hpp:118)
        score = score.sum(axis=1)
    n = len(score)
    sub = score if rows is None else score[rows]
    nn = len(sub)
    k = max(1, int(nn * a))
    thr = np.partition(sub, nn - k)[nn - k]
    top = sub >= thr
    rng = host_rng(cfg.bagging_seed, iteration)
    rest_p = b / max(1.0 - a, 1e-12)
    keep_rest = (~top) & (rng.random(nn) < rest_p)
    amp = (1.0 - a) / max(b, 1e-12)
    sub_mask = (top | keep_rest).astype(np.float32)
    sub_mult = np.where(keep_rest, np.float32(amp),
                        np.float32(1.0)).astype(np.float32)
    if rows is None:
        return sub_mask, sub_mult
    mask = np.zeros(n, np.float32)
    mask[rows] = sub_mask
    mult = np.ones(n, np.float32)
    mult[rows] = sub_mult
    return mask, mult


def feature_mask_np(cfg, num_features: int,
                    iteration: int) -> Optional[np.ndarray]:
    """Per-iteration feature_fraction mask (ColSampler per-tree draw), or
    None when feature_fraction is 1.0."""
    if cfg.feature_fraction >= 1.0:
        return None
    rng = host_rng(cfg.feature_fraction_seed, iteration)
    k = max(1, int(np.ceil(num_features * cfg.feature_fraction)))
    idx = rng.choice(num_features, size=k, replace=False)
    mask = np.zeros(num_features, bool)
    mask[idx] = True
    return mask


def make_walk_fn(efb_walk, dense_ok: bool):
    """Binned tree-walk selector shared by GBDT._walk and multitrain:
    EFB-bundled datasets decode bundle columns; categorical-free datasets
    take the dense matmul walk (no per-row gathers)."""
    if efb_walk is not None:
        if dense_ok:
            def walk(bins, *tree_args):
                (sf, tb, nb, _cm, dt, lc, rc, lv, nl) = tree_args
                return _walk_binned_dense_efb(bins, efb_walk, sf, tb, nb,
                                              dt, lc, rc, lv, nl)
            return walk
        return lambda bins, *tree_args: _walk_binned_efb(bins, efb_walk,
                                                         *tree_args)
    if dense_ok:
        def walk(bins, *tree_args):
            (sf, tb, nb, _cm, dt, lc, rc, lv, nl) = tree_args
            return _walk_binned_dense(bins, sf, tb, nb, dt, lc, rc, lv, nl)
        return walk
    return _walk_binned


from .tree import (_walk_binned,  # tree walk for valid-set score updates
                   _walk_binned_dense, _walk_binned_dense_efb,
                   _walk_binned_efb)


class GBDT:
    """Boosting driver (reference include/LightGBM/boosting.h:27 ``Boosting``
    interface + src/boosting/gbdt.h:540 ``GBDT``)."""

    name = "gbdt"
    # Deferred tree materialization: grown trees stay device-side and are
    # pulled to host in one batched fetch only when the model is actually
    # read (predict/save/rollback/...).  Keeps the boosting loop fully
    # async — crucial when the accelerator link has high latency.  DART
    # needs host trees every iteration and opts out.
    _defer_trees = True

    def __init__(self, config: Config, train_set: Optional[Dataset],
                 objective: Optional[ObjectiveFunction] = None) -> None:
        self.config = config
        self._models_list: List[Tree] = []
        self._pending: List[tuple] = []
        self.train_set: Optional[Dataset] = None
        self.valid_sets: List[Tuple[str, Dataset]] = []
        self.valid_scores: List[jnp.ndarray] = []
        self.valid_metrics: List[List[Metric]] = []
        self.train_metrics: List[Metric] = []
        self.objective = objective
        self.iter_ = 0
        self.init_scores: Optional[np.ndarray] = None
        self.best_iteration = -1
        # loaded (train-set-less) models keep an inert record so the
        # eval/snapshot surfaces never need a None check; _init_train
        # replaces it with the published per-run record (same deal for
        # the flight recorder: inert/disabled until a training run)
        self.train_record = TrainRecord(meta={"boosting": self.name})
        from ..telemetry.flight import FlightRecorder
        self.flight = FlightRecorder(capacity=1, enabled=False)
        if train_set is not None:
            self._init_train(train_set)

    # -- setup ---------------------------------------------------------------
    def _init_train(self, train_set: Dataset) -> None:
        cfg = self.config
        # params verbosity drives the global log level (reference: the C++
        # global Log level is set from config at Booster creation)
        from ..utils.log import set_verbosity
        set_verbosity(int(cfg.verbosity))
        from ..config import warn_unimplemented_params
        warn_unimplemented_params(cfg)
        train_set.construct(cfg)
        self.train_set = train_set
        self.num_data = train_set.num_data()
        self.num_features = train_set.num_feature()
        mappers = [train_set.bin_mappers[j] for j in train_set.used_feature_map]
        from ..binning import MissingType
        self.max_bins = int(max(m.num_bin for m in mappers))
        num_bins = np.array([m.num_bin for m in mappers], np.int32)
        is_cat = np.array([m.is_categorical for m in mappers], bool)
        has_nan = np.array([m.missing_type == MissingType.NAN for m in mappers],
                           bool)
        if cfg.tree_learner == "auto":
            # world-size + modeled-bytes learner selection (PV-Tree,
            # arXiv:1611.01276): voting when its modeled CROSS-HOST
            # histogram bytes per pass undercut the DP reduce-scatter
            # path's, data-parallel otherwise; single-device worlds are
            # the serial learner.  Resolved in place so every downstream
            # gate (EFB, pre_partition, shard counts, model text) sees
            # the concrete learner.
            from ..parallel.voting_parallel import voting_favored
            _world = jax.device_count()
            if _world <= 1 and jax.process_count() == 1:
                cfg.tree_learner = "serial"
            elif voting_favored(self.num_features, self.max_bins,
                                int(cfg.top_k), _world):
                cfg.tree_learner = "voting"
            else:
                cfg.tree_learner = "data"
            log_info(f"tree_learner=auto resolved to "
                     f"'{cfg.tree_learner}' (world={_world}, "
                     f"features={self.num_features}, "
                     f"top_k={int(cfg.top_k)})")
        learner_cfg = cfg
        from ..utils.backend import default_backend as _safe_backend
        _backend = _safe_backend()
        _autotune_ok = (
            cfg.tpu_histogram_impl == "auto" and
            train_set.X_binned.size <= (1 << 22) and
            self.max_bins <= 256 and
            cfg.tree_learner in ("serial", "") and
            # EFB bundles histogram in BUNDLE space (bundle_bins can
            # exceed the per-feature max) and the probe would time the
            # wrong shapes — keep the static choice there
            train_set.efb is None and
            (_backend == "tpu" or
             # CPU: the joint-nibble packed4 scatter only competes when
             # every feature fits 4-bit bins, and the probe's compiles
             # only pay off past benchmark-ish scale
             (self.max_bins <= 16 and
              train_set.X_binned.size >= (1 << 18))))
        if _autotune_ok:
            # small shapes: time the kernel variants (pallas dma /
            # blockspec / packed / onehot on TPU; segment vs packed4 on
            # CPU) on the real data once (dataset.cpp:659-670's
            # ShareStates timing analog); large shapes keep the measured
            # static choice.  Winners persist per (shape, backend) in
            # the autotune disk cache, and go to a COPY so the user's
            # 'auto' survives param round-trips.
            from ..learner.autotune import apply_winner, pick_hist_impl
            import copy as _copy
            learner_cfg = _copy.copy(cfg)
            apply_winner(learner_cfg,
                         pick_hist_impl(train_set.X_binned, self.max_bins))
        self.learner = self._create_learner(num_bins, is_cat, has_nan,
                                            self._inner_monotone(),
                                            cfg=learner_cfg)
        # dense binned walk gate: per-node categorical membership needs a
        # gather (EFB bundles decode elementwise and are fine)
        self._walk_dense_ok = not bool(np.any(is_cat))
        _shards = jax.device_count() \
            if cfg.tree_learner in ("data", "voting") else 1
        if self.num_data > (1 << 24) * _shards and \
                not cfg.use_quantized_grad:
            # f32 histogram counts are exact to 2^24 rows PER SHARD
            # (ops/histogram.py); the quantized path accumulates int32
            # counts exact to 2^31 (reference data_size_t, meta.h:28)
            log_warning(f"num_data={self.num_data} exceeds the f32 "
                        "histogram count channel's 16.7M-rows-per-shard "
                        "exactness range; set use_quantized_grad=true for "
                        "exact int32 counts (and faster training) at this "
                        "scale")
        if cfg.use_quantized_grad:
            # int32 g_q/h_q channel sums overflow once one bin can hold
            # more than 2^31/gq_max quantized units per shard (the count
            # channel alone is exact to 2^31); warn at the per-shard bound
            from ..ops.quantize import quant_levels
            _gq = max(quant_levels(int(cfg.num_grad_quant_bins)))
            if self.num_data > (1 << 31) // _gq * _shards:
                log_warning(
                    f"num_data={self.num_data} exceeds the quantized "
                    f"histogram's int32 channel-sum exactness bound "
                    f"(2^31/{_gq} rows per shard at num_grad_quant_bins="
                    f"{cfg.num_grad_quant_bins}); lower num_grad_quant_bins "
                    "or shard rows across more devices")
        if getattr(train_set, "distributed_rows", False):
            # pre-partitioned ingest: assemble the global row-sharded
            # matrix from each process's local shard (features never
            # replicate across hosts)
            if cfg.tree_learner not in ("data", "voting"):
                raise ValueError("pre_partition-ed training requires "
                                 "tree_learner=data or voting")
            from jax.sharding import NamedSharding, PartitionSpec as _P
            from ..parallel.mesh import get_mesh as _get_mesh
            _mesh = _get_mesh(int(cfg.num_devices))
            _ax = _mesh.axis_names[0]
            self.X_dev = jax.make_array_from_process_local_data(
                NamedSharding(_mesh, _P(_ax)), train_set.X_binned)
            self._row_valid = jax.make_array_from_process_local_data(
                NamedSharding(_mesh, _P(_ax)), train_set._dist_valid_local)
        else:
            self.X_dev = jnp.asarray(train_set.X_binned)
            self._row_valid = None
        self._is_cat_np = is_cat
        # bundle-space tree-walk decode arrays (EFB valid sets / rebuilds)
        # — the standard efb_arrays layout minus exp_map (unused by the
        # walk's decode)
        efb = getattr(train_set, "efb", None)
        self._efb_walk = None if efb is None else (
            None, jnp.asarray(efb.f_bundle), jnp.asarray(efb.f_offset),
            jnp.asarray(efb.f_default), jnp.asarray(efb.f_nbins),
            jnp.asarray(efb.f_single))
        # CEGB (cost_effective_gradient_boosting.hpp): coupled per-feature
        # penalties charge once until the feature is first used; tracked
        # host-side across trees (per-tree granularity)
        self._cegb_coupled = None
        serial = isinstance(self.learner, SerialTreeLearner)
        supports_extras = serial or getattr(self.learner,
                                            "supports_extras", False)
        if cfg.cegb_penalty_feature_coupled or cfg.cegb_penalty_split > 0:
            if not supports_extras:
                log_warning("CEGB penalties are applied by the serial and "
                            "data-parallel(wave) learners only; this "
                            "learner ignores them")
            elif cfg.cegb_penalty_feature_coupled:
                full = np.zeros(train_set.num_total_features, np.float64)
                cpl = cfg.cegb_penalty_feature_coupled
                full[:len(cpl)] = [float(v) for v in cpl]
                self._cegb_coupled = (full[train_set.used_feature_map] *
                                      float(cfg.cegb_tradeoff))
                self._cegb_used = np.zeros(self.num_features, bool)
                self._defer_trees = False  # used-set updates per tree
        if cfg.feature_fraction_bynode < 1.0 and not supports_extras:
            log_warning("feature_fraction_bynode is applied by the serial "
                        "and data-parallel(wave) learners only; this "
                        "learner ignores it")
        self._linear = bool(cfg.linear_tree)
        if self._linear and self.name != "gbdt":
            log_warning(f"linear_tree is not supported with "
                        f"boosting={self.name}; training plain trees")
            self._linear = False
        if self._linear:
            # linear leaves re-fit on raw values each iteration; tree
            # deferral buys nothing here
            self._defer_trees = False
            if getattr(train_set, "distributed_rows", False):
                # pre-partitioned: assemble the row-sharded global raw
                # matrix like X_dev (local shards never replicate)
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as _P2
                from ..parallel.mesh import get_mesh as _get_mesh2
                _mesh2 = _get_mesh2(int(cfg.num_devices))
                self.X_raw_dev = jax.make_array_from_process_local_data(
                    NamedSharding(_mesh2, _P2(_mesh2.axis_names[0])),
                    train_set.raw_used)
            else:
                self.X_raw_dev = jnp.asarray(train_set.raw_used)

        if self.objective is None and cfg.objective != "none":
            self.objective = create_objective(cfg.objective, cfg)
        if self.objective is not None:
            self.objective.init(train_set.metadata, self.num_data)
        self.num_tree_per_iteration = (
            self.objective.num_model_per_iteration if self.objective else
            max(1, cfg.num_class if cfg.num_class > 1 else 1))
        k = self.num_tree_per_iteration
        shape = (self.num_data,) if k == 1 else (self.num_data, k)

        # initial scores: user init_score > boost_from_average > zero
        self._pending_bias = np.zeros(k)
        score0 = np.zeros(shape, np.float32)
        md = train_set.metadata
        if md.init_score is not None:
            init = md.init_score.reshape(shape)
            score0 = score0 + init.astype(np.float32)
        elif cfg.boost_from_average and self.objective is not None:
            for cid in range(k):
                s = self.objective.boost_from_score(cid)
                self._pending_bias[cid] = s
                if abs(s) > EPSILON:
                    log_info(f"Start training from score {s:.6f}")
            if k == 1:
                score0 = score0 + np.float32(self._pending_bias[0])
            else:
                score0 = score0 + self._pending_bias[None, :].astype(np.float32)
        self.score = jnp.asarray(score0)

        self.train_metrics = []
        if cfg.is_provide_training_metric:
            self.train_metrics = create_metrics(cfg)
            for m in self.train_metrics:
                m.init(md, self.num_data)

        # telemetry: one TrainRecord per training run (per-tree histogram
        # passes, per-phase wall time, trace-time collective tallies,
        # compile events, device-memory watermark).  Purely observational
        # — reads values the loop already computes — and published as the
        # process's freshest record so /metrics can export it.
        self.train_record = TrainRecord(meta={
            "boosting": self.name,
            "objective": str(cfg.objective),
            "tree_learner": str(cfg.tree_learner) or "serial",
            "num_leaves": int(cfg.num_leaves),
            "num_data": int(self.num_data),
            "num_features": int(self.num_features),
        })
        set_last_train_record(self.train_record)
        # flight recorder: bounded per-iteration event ring for crash/
        # preemption post-mortems (telemetry/flight.py).  Observation
        # only — recorder-on training is bit-identical to recorder-off.
        from ..telemetry.flight import FlightRecorder
        self.flight = FlightRecorder(
            capacity=int(cfg.flight_events),
            enabled=bool(cfg.flight_recorder),
            meta={"boosting": self.name, "objective": str(cfg.objective),
                  "num_data": int(self.num_data)})

    def _inner_monotone(self) -> Optional[np.ndarray]:
        """Map config.monotone_constraints (original column indexing, may be
        shorter than the column count) onto the inner used-feature axis."""
        mc = self.config.monotone_constraints
        if not mc or not any(int(v) != 0 for v in mc):
            return None
        ts = self.train_set
        full = np.zeros(ts.num_total_features, np.int32)
        full[:len(mc)] = [int(v) for v in mc]
        return full[ts.used_feature_map]

    def _parse_forced_splits(self) -> tuple:
        """forcedsplits_filename JSON -> BFS-ordered (leaf, inner feature,
        threshold bin) triples (reference serial_tree_learner.cpp:450
        ForceSplits + application-level json load)."""
        fn = self.config.forcedsplits_filename
        if not fn:
            return ()
        import json
        from collections import deque
        with open(fn) as fh:
            root = json.load(fh)
        ts = self.train_set
        inner_of_real = {int(r): i for i, r in enumerate(ts.used_feature_map)}
        mappers = [ts.bin_mappers[j] for j in ts.used_feature_map]
        out = []
        q = deque([(root, 0)])
        next_id = 1
        while q and len(out) < self.config.num_leaves - 1:
            node, leaf = q.popleft()
            if not node or "feature" not in node:
                continue
            rf = int(node["feature"])
            if rf not in inner_of_real:
                log_warning(f"forced split on trivial/unknown feature {rf} "
                            f"skipped (with its subtree)")
                continue
            f = inner_of_real[rf]
            b = int(mappers[f].value_to_bin(
                np.array([float(node["threshold"])]))[0])
            out.append((leaf, f, b))
            new_id = next_id
            next_id += 1
            if "left" in node:
                q.append((node["left"], leaf))
            if "right" in node:
                q.append((node["right"], new_id))
        return tuple(out)

    def _inner_cegb_lazy(self) -> tuple:
        """cegb_penalty_feature_lazy mapped to inner features, pre-scaled
        by cegb_tradeoff (like the coupled penalties)."""
        lz = self.config.cegb_penalty_feature_lazy
        if not lz:
            return ()
        full = np.zeros(self.train_set.num_total_features, np.float64)
        full[:len(lz)] = [float(v) for v in lz]
        inner = full[self.train_set.used_feature_map] * \
            float(self.config.cegb_tradeoff)
        if not np.any(inner):
            return ()  # numerically a no-op: skip the bitmap machinery
        return tuple(float(v) for v in inner)

    def _inner_contri(self) -> tuple:
        """config.feature_contri (original column indexing) -> per-inner-
        feature gain multipliers (feature_histogram.hpp:94 penalty)."""
        fc = self.config.feature_contri
        if not fc:
            return ()
        ts = self.train_set
        full = np.ones(ts.num_total_features, np.float64)
        full[:len(fc)] = [float(v) for v in fc]
        return tuple(full[ts.used_feature_map])

    def _parse_interaction_constraints(self) -> tuple:
        """config.interaction_constraints "[0,1],[2,3]" -> tuples of INNER
        feature indices (reference col_sampler.hpp constraint sets)."""
        spec = self.config.interaction_constraints
        if not spec:
            return ()
        import re
        ts = self.train_set
        inner_of_real = {int(r): i for i, r in enumerate(ts.used_feature_map)}
        groups = []
        for grp in re.findall(r"\[([^\]]*)\]", str(spec)):
            feats = [inner_of_real[int(v)] for v in grp.split(",")
                     if v.strip() and int(v) in inner_of_real]
            if feats:
                groups.append(tuple(sorted(set(feats))))
        return tuple(groups)

    def _create_learner(self, num_bins, is_cat, has_nan, monotone=None,
                        cfg=None):
        cfg = cfg if cfg is not None else self.config
        if cfg.tree_learner == "serial" or cfg.num_machines <= 1 and \
                cfg.tree_learner not in ("data", "feature", "voting"):
            return SerialTreeLearner(cfg, self.num_features, self.max_bins,
                                     num_bins, is_cat, has_nan, monotone,
                                     self._parse_forced_splits(),
                                     efb=self.train_set.efb,
                                     interaction_groups=
                                     self._parse_interaction_constraints(),
                                     feature_contri=self._inner_contri(),
                                     cegb_lazy=self._inner_cegb_lazy())
        from ..parallel import create_parallel_learner
        return create_parallel_learner(
            cfg, self.num_features, self.max_bins, num_bins, is_cat,
            has_nan, monotone,
            interaction_groups=self._parse_interaction_constraints(),
            cegb_lazy=self._inner_cegb_lazy(),
            forced_splits=self._parse_forced_splits(),
            feature_contri=self._inner_contri())

    def _walk(self, bins, *tree_args):
        """Binned tree walk; routes through the bundle-space decode
        when the dataset is EFB-bundled (valid sets aligned to an EFB
        reference carry BUNDLE columns).  Categorical-free non-EFB
        datasets take the dense matmul walk (no per-row gathers)."""
        return make_walk_fn(self._efb_walk,
                            getattr(self, "_walk_dense_ok", False))(
            bins, *tree_args)

    def add_valid(self, valid_set: Dataset, name: str) -> None:
        # a valid set must share the train set's bin mappers (and bundle
        # layout under EFB) — the binned walk reads TRAIN-space codes
        # (reference dataset.h:304 alignment check raises the same way)
        if valid_set is not self.train_set and \
                getattr(valid_set, "reference", None) is not self.train_set \
                and not valid_set.constructed:
            valid_set.reference = self.train_set
        valid_set.construct(self.config)
        if getattr(self, "_row_valid", None) is not None and \
                valid_set is not self.train_set:
            # pre_partition training evaluates valid metrics per process
            # with NO cross-process reduction; every rank must therefore
            # hold the SAME (replicated) validation data, or metric-driven
            # decisions (early stopping) would diverge and desync the
            # collectives.  Checked by label checksum across ranks.
            from .. import distributed as _dist
            lab = valid_set.metadata.label
            sig = np.asarray([0.0 if lab is None else float(lab.sum()),
                              0.0 if lab is None else float(len(lab))],
                             np.float64)
            sigs = _dist.allgather_host(sig).reshape(-1, 2)
            if not np.allclose(sigs, sigs[0]):
                raise ValueError(
                    "under pre_partition every process must pass the SAME "
                    "validation data (metrics are evaluated per process); "
                    "got differing label checksums across ranks")
        if valid_set is not self.train_set and \
                valid_set.bin_mappers is not self.train_set.bin_mappers and \
                not _mappers_equal(valid_set.bin_mappers,
                                   self.train_set.bin_mappers):
            raise ValueError(
                "cannot add validation data: it was constructed without "
                "reference to the training Dataset (different bin "
                "mappers); pass reference=train_set when creating it")
        if valid_set.num_feature() != self.num_features:
            raise ValueError("validation set feature count differs from train")
        k = self.num_tree_per_iteration
        n = valid_set.num_data()
        shape = (n,) if k == 1 else (n, k)
        score0 = np.zeros(shape, np.float32)
        if valid_set.metadata.init_score is not None:
            score0 = score0 + valid_set.metadata.init_score.reshape(shape).astype(
                np.float32)
        elif self.config.boost_from_average and self.objective is not None:
            score0 = score0 + (np.float32(self._pending_bias[0]) if k == 1 else
                               self._pending_bias[None, :].astype(np.float32))
        metrics = create_metrics(self.config)
        for m in metrics:
            m.init(valid_set.metadata, n)
        self.valid_sets.append((name, valid_set))
        vscore = jnp.asarray(score0)
        valid_set._device_cache["bins"] = jnp.asarray(valid_set.X_binned)
        if self.models:  # continued training: include loaded trees' scores
            vbins = valid_set._device_cache["bins"]
            for t, tree in enumerate(self.models):
                cid = t % k
                delta = self._walk(
                    vbins, jnp.asarray(tree.split_feature),
                    jnp.asarray(tree.threshold_bin), jnp.asarray(tree.nan_bin),
                    _tree_cat_member(tree),
                    jnp.asarray(tree.decision_type.astype(np.int32)),
                    jnp.asarray(tree.left_child), jnp.asarray(tree.right_child),
                    jnp.asarray(tree.leaf_value, dtype=jnp.float32),
                    jnp.asarray(tree.num_leaves, dtype=jnp.int32))
                vscore = (vscore + delta if k == 1
                          else vscore.at[:, cid].add(delta))
        self.valid_scores.append(vscore)
        self.valid_metrics.append(metrics)

    # -- sampling (bagging / GOSS hooks) -------------------------------------
    def _prepare_iter_sampling(self, grad: jnp.ndarray, hess: jnp.ndarray
                               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """Per-iteration row sampling: returns (grad, hess, mask).  Base GBDT
        implements bagging (gbdt.cpp:228 Bagging, resampled every
        bagging_freq iters); GOSS/RF override."""
        cfg = self.config
        n = self.num_data
        label = (np.asarray(self.train_set.metadata.label)
                 if cfg.objective == "binary" and
                 self.train_set.metadata.label is not None else None)
        mask = bagging_mask_np(cfg, n, self.iter_, label=label)
        if mask is not None:
            self._bag_mask = jnp.asarray(mask)
        elif not hasattr(self, "_bag_mask") or self._bag_mask.shape[0] != n:
            self._bag_mask = jnp.ones(n, jnp.float32)
        return grad, hess, self._bag_mask

    def _feature_mask(self) -> Optional[jnp.ndarray]:
        mask = feature_mask_np(self.config, self.num_features, self.iter_)
        return None if mask is None else jnp.asarray(mask)

    # -- one boosting iteration (gbdt.cpp:369 TrainOneIter) ------------------
    def train_one_iter(self, grad: Optional[jnp.ndarray] = None,
                       hess: Optional[jnp.ndarray] = None) -> bool:
        cfg = self.config
        k = self.num_tree_per_iteration
        rec = self.train_record
        with FunctionTimer("GBDT::train_one_iter"):
            if grad is None or hess is None:
                if self.objective is None:
                    raise ValueError("no objective: pass gradients explicitly "
                                     "(custom objective path, boosting.h:85)")
                with rec.phase("gradients"):
                    grad, hess = self.objective.get_gradients(self.score)
            else:
                def _coerce(a):
                    a = jnp.asarray(a, jnp.float32)
                    if k == 1:
                        return a.reshape((self.num_data,))
                    if a.ndim == 2:
                        if a.shape == (self.num_data, k):
                            return a
                        if a.shape == (k, self.num_data):
                            return a.T
                        raise ValueError(
                            f"custom objective gradients have shape {a.shape}; "
                            f"expected ({self.num_data}, {k}) or flat "
                            f"class-major length {self.num_data * k}")
                    # flat custom-fobj output is CLASS-MAJOR in the reference
                    # API (grouped by class_id then row_id, c_api.cpp
                    # UpdateOneIterCustom convention)
                    return a.reshape((k, self.num_data)).T
                grad = _coerce(grad)
                hess = _coerce(hess)

            # Lagged no-split stop for the deferred-tree path: the previous
            # iteration's tree sizes are device-computed by now, so this host
            # pull is a bare RTT and doesn't stall the dispatch pipeline.
            # When the previous iteration grew only stumps, pop them (the
            # reference pops non-splitting trees, gbdt.cpp:430-450) and stop.
            prev = getattr(self, "_prev_iter_leaves", None)
            if prev is not None and \
                    all(int(x) <= 1 for x in jax.device_get(prev)):
                self._prev_iter_leaves = None
                self._pop_stump_iteration()
                log_warning("Stopped training because there are no more "
                            "leaves that meet the split requirements")
                return True

            finished = True
            fl_leaves = fl_gain = None  # flight-event fields (last class)
            fmask = self._feature_mask()
            grad, hess, mask = self._prepare_iter_sampling(grad, hess)
            if getattr(self, "_row_valid", None) is not None:
                # pre_partition padding rows never enter a tree (applied
                # centrally so GOSS's override is covered too)
                mask = mask * self._row_valid
            self._last_sample_mask = mask
            leaves_this_iter = []
            for cid in range(k):
                g = grad if k == 1 else grad[:, cid]
                h = hess if k == 1 else hess[:, cid]
                self._cur_gh = (g, h)
                extra = {}
                it = self.iter_ * k + cid
                if getattr(self.learner, "supports_extras", False):
                    if self._cegb_coupled is not None:
                        extra["cegb_penalty"] = jnp.asarray(
                            np.where(self._cegb_used, 0.0,
                                     self._cegb_coupled), jnp.float32)
                    if cfg.feature_fraction_bynode < 1.0 or cfg.extra_trees:
                        # independent streams, like the reference's separate
                        # ColSampler and ExtraTrees RNGs: row 0 = bynode
                        # sampling (feature_fraction_seed), row 1 =
                        # ExtraTrees thresholds (extra_seed)
                        extra["node_key"] = jnp.stack([
                            jax.random.fold_in(jax.random.PRNGKey(
                                cfg.feature_fraction_seed), it),
                            jax.random.fold_in(jax.random.PRNGKey(
                                cfg.extra_seed), it)])
                if getattr(self.learner, "quantized", False):
                    # per-tree stochastic-rounding stream
                    # (gradient_discretizer.cpp seeds from config seed)
                    extra["quant_key"] = jax.random.fold_in(
                        jax.random.PRNGKey(cfg.seed), it)
                with rec.phase("grow"):
                    grown = self.learner.train(self.X_dev, g, h, mask,
                                               feature_mask=fmask, **extra)
                # full-data histogram passes of the last grown tree (wave
                # grower; 0 = untracked) — a device scalar, pulled lazily
                # by bench/diagnostic readers only
                self.last_hist_passes = grown.hist_passes
                rec.add_tree(self.iter_, cid, grown.hist_passes,
                             grown.num_leaves)
                if self.flight.enabled:
                    # last grown tree's fields for this iteration's
                    # flight event (device scalars, pulled lazily on
                    # dump; the max over split gains is one tiny
                    # device reduce)
                    fl_leaves = grown.num_leaves
                    fl_gain = jnp.max(grown.split_gain)
                with rec.phase("record"):
                    tree = self._record_tree(grown, cid)
                if tree is not None and self._cegb_coupled is not None:
                    sf = tree.split_feature[:tree.num_leaves - 1]
                    self._cegb_used[sf[sf >= 0]] = True
                if tree is None:
                    # deferred: the lagged check above decides next iteration
                    finished = False
                    leaves_this_iter.append(grown.num_leaves)
                elif tree.num_leaves > 1:
                    finished = False
            self._prev_iter_leaves = leaves_this_iter or None
            for x in leaves_this_iter:
                # start the device->host copy NOW so next iteration's
                # lagged stump check reads a landed value instead of
                # paying a blocking ~100 ms round trip per iteration
                # (small-shape configs spend more time in that RTT than
                # in their kernels)
                if hasattr(x, "copy_to_host_async"):
                    x.copy_to_host_async()
            self.iter_ += 1
            if self.flight.enabled:
                self.flight.note_iter(
                    self.iter_, hist_passes=self.last_hist_passes,
                    num_leaves=fl_leaves, best_gain=fl_gain)
            if self.iter_ % 16 == 1:
                # periodic device-memory watermark sample (cheap local
                # PJRT query; None on backends without memory_stats)
                rec.note_memory()
            if finished:
                log_warning("Stopped training because there are no more leaves "
                            "that meet the split requirements")
            return finished

    def _pop_stump_iteration(self) -> None:
        """Drop the previous iteration's no-split stump trees (they carry a
        near-zero constant; their score nudge is left in place — training is
        over and prediction reads only the model list).  The FIRST
        iteration's trees are kept even when they are stumps: they carry the
        boost-from-average constant (reference gbdt.cpp:443-450 pops only
        when models_.size() > num_tree_per_iteration)."""
        k = self.num_tree_per_iteration
        if len(self._models_list) + len(self._pending) <= k:
            return
        for _ in range(k):
            if self._pending:
                self._pending.pop()
            elif self._models_list:
                self._models_list.pop()
        self.iter_ = max(0, self.iter_ - 1)

    def _current_shrinkage(self) -> float:
        """Per-iteration shrinkage; DART overrides with lr/(1+k_dropped)."""
        return float(self.config.learning_rate)

    def _renew_leaf_values(self, grown: GrownTree,
                           class_id: int) -> Optional[np.ndarray]:
        """Percentile leaf refit for L1/quantile/MAPE (reference
        serial_tree_learner.cpp:684 RenewTreeOutput +
        regression_objective.hpp RenewTreeOutput): each leaf's value becomes
        the weighted alpha-percentile of the residuals of its (in-bag)
        rows."""
        obj = self.objective
        if obj is None or not getattr(obj, "is_renew_tree_output", False):
            return None
        from ..objective.base import weighted_percentile
        alpha = float(getattr(obj, "renew_alpha", 0.5))
        row_leaf = np.asarray(grown.row_leaf)
        score = np.asarray(self.score if self.num_tree_per_iteration == 1
                           else self.score[:, class_id])
        label = np.asarray(self.train_set.metadata.label)
        resid = label - score
        w = getattr(obj, "label_weight", None)  # MAPE folds weights here
        if w is not None:
            w = np.asarray(w)
        elif self.train_set.metadata.weight is not None:
            w = np.asarray(self.train_set.metadata.weight)
        mask = np.asarray(self._last_sample_mask) > 0 \
            if getattr(self, "_last_sample_mask", None) is not None else \
            np.ones(len(label), bool)
        out = np.asarray(grown.leaf_value, np.float64).copy()
        for leaf in range(int(grown.num_leaves)):
            sel = (row_leaf == leaf) & mask
            if sel.any():
                out[leaf] = weighted_percentile(
                    resid[sel], None if w is None else w[sel], alpha)
        return out

    @property
    def models(self) -> List[Tree]:
        """Host-side tree list; materializes any pending device trees."""
        self._flush_trees()
        return self._models_list

    @models.setter
    def models(self, value: List[Tree]) -> None:
        self._pending = []
        self._models_list = value

    def _flush_trees(self) -> None:
        if not self._pending:
            return
        pend, self._pending = self._pending, []
        host_grown = jax.device_get([p[0] for p in pend])  # one batched pull
        for (_, shrinkage, bias), grown in zip(pend, host_grown):
            tree = _grown_to_tree(grown, shrinkage, self.train_set)
            if abs(bias) > EPSILON:
                tree.add_bias(bias)
            self._models_list.append(tree)

    def _record_tree(self, grown: GrownTree, class_id: int) -> Optional[Tree]:
        if getattr(self, "_linear", False):
            return self._record_tree_linear(grown, class_id)
        shrinkage = self._current_shrinkage()
        renewed = None
        defer = self._defer_trees and not (
            self.objective is not None and
            getattr(self.objective, "is_renew_tree_output", False))
        if not defer:
            renewed = self._renew_leaf_values(grown, class_id)
        bias = self._pending_bias[class_id] if self.iter_ == 0 else 0.0
        if defer:
            # keep only what _grown_to_tree reads: dropping row_leaf
            # releases the (N,) per-tree assignment (42 MB/tree at Higgs
            # scale) instead of holding it in HBM until flush and hauling
            # it through the device->host pull
            self._pending.append(
                (grown._replace(row_leaf=jnp.zeros((0,), jnp.int32)),
                 shrinkage, bias))
            tree = None
        else:
            tree = _grown_to_tree(grown, shrinkage, self.train_set,
                                  leaf_value_override=renewed)
            # fold init score into the first iteration's trees
            # (gbdt.cpp:414-427)
            if abs(bias) > EPSILON:
                tree.add_bias(bias)
            self._flush_trees()
            self._models_list.append(tree)

        # update train scores from the grower's leaf assignment
        lv = (grown.leaf_value if renewed is None
              else jnp.asarray(renewed, jnp.float32)) * shrinkage
        upd = _score_update_entry()
        if self.num_tree_per_iteration == 1:
            self.score = upd(self.score, grown.row_leaf, lv, 1.0)
        else:
            col = upd(self.score[:, class_id], grown.row_leaf, lv, 1.0)
            self.score = self.score.at[:, class_id].set(col)
        # update validation scores with a tree walk on their binned matrices
        for vi, (_, vset) in enumerate(self.valid_sets):
            vbins = vset._device_cache["bins"]
            delta = self._walk(vbins, grown.split_feature, grown.threshold_bin,
                                 grown.nan_bin, grown.cat_member,
                                 grown.decision_type,
                                 grown.left_child, grown.right_child,
                                 lv, grown.num_leaves)
            if self.num_tree_per_iteration == 1:
                self.valid_scores[vi] = self.valid_scores[vi] + delta
            else:
                self.valid_scores[vi] = self.valid_scores[vi].at[:, class_id].add(delta)
        return tree

    def _linear_device_arrays(self, tree: Tree):
        """Pad the tree's per-leaf linear models into device arrays for
        vectorized evaluation."""
        L = tree.max_leaves
        feats = tree.leaf_features_inner
        K = max(1, max((len(f) for f in feats), default=1))
        lf = np.zeros((L, K), np.int32)
        fm = np.zeros((L, K), np.float32)
        co = np.zeros((L, K), np.float32)
        for i, (fs, cs) in enumerate(zip(feats, tree.leaf_coeff)):
            lf[i, :len(fs)] = fs
            fm[i, :len(fs)] = 1.0
            co[i, :len(cs)] = cs
        return (jnp.asarray(lf), jnp.asarray(fm), jnp.asarray(co),
                jnp.asarray(tree.leaf_const, jnp.float32),
                jnp.asarray(tree.leaf_value, jnp.float32))

    def _record_tree_linear(self, grown: GrownTree, class_id: int
                            ) -> Optional[Tree]:
        """Linear-tree variant of _record_tree: fit per-leaf linear models
        on the raw branch features (learner/linear.py) before recording."""
        from ..learner.linear import fit_linear_leaves, linear_score_delta
        cfg = self.config
        shrinkage = self._current_shrinkage()
        g, h = self._cur_gh
        mask = self._last_sample_mask
        sf, lc, rc, nl, lv = jax.device_get(
            (grown.split_feature, grown.left_child, grown.right_child,
             grown.num_leaves, grown.leaf_value))
        feats_i, coefs, const = fit_linear_leaves(
            self.X_raw_dev, g, h, mask, grown.row_leaf, sf, lc, rc,
            max(int(nl), 1), self._is_cat_np, float(cfg.linear_lambda), lv)
        tree = _grown_to_tree(grown, 1.0, self.train_set)
        real_map, _, _ = self.feature_mapping()
        tree.is_linear = True
        tree.leaf_const = np.asarray(const, np.float64)
        tree.leaf_coeff = coefs
        tree.leaf_features_inner = feats_i
        tree.leaf_features = [[int(real_map[f]) for f in fs]
                              for fs in feats_i]
        if shrinkage != 1.0:
            tree.shrink(shrinkage)
        # device score update with POST-shrink, PRE-bias values (scores
        # already carry the boost-from-average bias)
        lf, fm, co, lconst, lval = self._linear_device_arrays(tree)
        delta = linear_score_delta(self.X_raw_dev, grown.row_leaf, lf, fm,
                                   co, lconst, lval, 1.0)
        if self.num_tree_per_iteration == 1:
            self.score = self.score + delta
        else:
            self.score = self.score.at[:, class_id].add(delta)
        for vi, (_, vset) in enumerate(self.valid_sets):
            vbins = vset._device_cache["bins"]
            idx_f = self._walk(
                vbins, grown.split_feature, grown.threshold_bin,
                grown.nan_bin, grown.cat_member, grown.decision_type,
                grown.left_child, grown.right_child,
                jnp.arange(tree.max_leaves, dtype=jnp.float32),
                grown.num_leaves)
            vleaf = idx_f.astype(jnp.int32)
            vraw = vset._device_cache.get("raw")
            if vraw is None:
                vraw = jnp.asarray(vset.raw_used)
                vset._device_cache["raw"] = vraw
            vdelta = linear_score_delta(vraw, vleaf, lf, fm, co, lconst,
                                        lval, 1.0)
            if self.num_tree_per_iteration == 1:
                self.valid_scores[vi] = self.valid_scores[vi] + vdelta
            else:
                self.valid_scores[vi] = \
                    self.valid_scores[vi].at[:, class_id].add(vdelta)
        bias = self._pending_bias[class_id] if self.iter_ == 0 else 0.0
        if abs(bias) > EPSILON:
            tree.add_bias(bias)
        self._flush_trees()
        self._models_list.append(tree)
        return tree

    # -- evaluation (gbdt.cpp:472 EvalAndCheckEarlyStopping) -----------------
    def eval_train(self) -> List[Tuple[str, str, float, bool]]:
        out = []
        if not self.train_metrics:
            return out
        with self.train_record.phase("eval"):
            score = np.asarray(self.score)
            for m in self.train_metrics:
                for name, val, hib in m.eval(score):
                    out.append(("training", name, val, hib))
        return out

    def eval_valid(self) -> List[Tuple[str, str, float, bool]]:
        out = []
        if not self.valid_sets:
            return out
        with self.train_record.phase("eval"):
            for vi, (vname, _) in enumerate(self.valid_sets):
                score = np.asarray(self.valid_scores[vi])
                for m in self.valid_metrics[vi]:
                    for name, val, hib in m.eval(score):
                        out.append((vname, name, val, hib))
        return out

    # -- prediction ----------------------------------------------------------
    def _tree_batch(self, start: int = 0, num_iteration: Optional[int] = None
                    ) -> Optional[TreeBatch]:
        if not self.models:
            return None
        k = self.num_tree_per_iteration
        end = len(self.models) if num_iteration is None else min(
            len(self.models), (start + num_iteration) * k)
        cache = getattr(self, "_predict_cache", None)
        key = (start * k, end)
        if cache is not None and key in cache:
            return cache[key]
        trees = self.models[start * k:end]
        batch = TreeBatch(trees) if trees else None
        if cache is not None:
            # per-predict-call memo only (set up by the chunk loop); the
            # model is immutable across one call's chunks, so no
            # invalidation hazard
            cache[key] = batch
        return batch

    def _dense_program(self, t0: int, t1: int, num_features: int):
        """The serving compiler's fused program for trees [t0, t1), or
        None when the walk serves this call (mode/cost-model/lowering —
        the reason is recorded by serve/compiler.py, never silent).
        Memoized in the per-call ``_predict_cache`` so chunked predicts
        lower once."""
        cache = getattr(self, "_predict_cache", None)
        ck = ("dense", t0, t1)
        if cache is not None and ck in cache:
            return cache[ck]
        from ..serve.compiler import compile_ensemble
        cfg = self.config
        k = self.num_tree_per_iteration
        full = t0 == 0 and t1 == len(self.models)
        dense, _reason = compile_ensemble(
            self.models[t0:t1], k, num_features,
            class_ids=[t % k for t in range(t0, t1)],
            mode=getattr(cfg, "tpu_predict_compiler", "auto"),
            leaf_bits=int(getattr(cfg, "tpu_predict_leaf_bits", 0)),
            shard=int(getattr(cfg, "tpu_predict_shard", 0)),
            batch=self._tree_batch() if full else None)
        if cache is not None:
            cache[ck] = dense
        return dense

    def _explain_program(self, t0: int, t1: int, num_features: int):
        """The explain compiler's dense TreeSHAP program for trees
        [t0, t1), or None when the host walk serves this call
        (mode/budget — the reason is recorded by explain/compiler.py,
        never silent).  Memoized in the per-call ``_predict_cache`` so
        chunked contrib predicts lower once."""
        cache = getattr(self, "_predict_cache", None)
        ck = ("explain", t0, t1)
        if cache is not None and ck in cache:
            return cache[ck]
        from ..explain.compiler import compile_explain
        k = self.num_tree_per_iteration
        full = t0 == 0 and t1 == len(self.models)
        exe, _reason = compile_explain(
            self.models[t0:t1], k, num_features,
            class_ids=[t % k for t in range(t0, t1)],
            mode=getattr(self.config, "tpu_explain_compiler", "auto"),
            num_cols=self.num_features + 1,
            batch=self._tree_batch() if full else None)
        if cache is not None:
            cache[ck] = exe
        return exe

    def _predict_contrib(self, Xi, start_iteration, num_iteration):
        """SHAP contributions, routed through tpu_explain_compiler: the
        dense TreeSHAP program when it lowers, else the host walk —
        both respect the iteration window, and the dense result is
        additivity-checked (a failed invariant falls back WITH a
        recorded reason, like every other fallback)."""
        from .shap import predict_contrib, trees_window
        t0, t1 = trees_window(self, start_iteration, num_iteration)
        exe = self._explain_program(t0, t1, Xi.shape[1]) if t1 > t0 else None
        if exe is not None:
            from ..explain.compiler import (ExplainAdditivityError,
                                            note_explain_fallback_batch)
            if any(t.is_linear for t in self.models[t0:t1]):
                from ..utils.log import log_warning
                log_warning("pred_contrib on linear trees attributes each "
                            "leaf's PLAIN output (per-leaf linear terms "
                            "are not decomposed)")
            try:
                return exe.explain(Xi)
            except ExplainAdditivityError:
                note_explain_fallback_batch("additivity", "")
        return predict_contrib(self, Xi, start_iteration, num_iteration)

    def predict(self, X: np.ndarray, raw_score: bool = False,
                start_iteration: int = 0,
                num_iteration: Optional[int] = None,
                pred_leaf: bool = False, pred_contrib: bool = False,
                pred_early_stop: bool = False,
                pred_early_stop_freq: Optional[int] = None,
                pred_early_stop_margin: Optional[float] = None) -> np.ndarray:
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        # per-call memo for TreeBatch/dense-program builds: ONE build per
        # predict() call whether the call chunks or not (the dense route
        # consults the TreeBatch twice — cost model + lowering — so an
        # unmemoized call would build it twice)
        own_cache = getattr(self, "_predict_cache", None) is None
        if own_cache:
            self._predict_cache = {}
        try:
            return self._predict_cached(
                X, raw_score, start_iteration, num_iteration, pred_leaf,
                pred_contrib, pred_early_stop, pred_early_stop_freq,
                pred_early_stop_margin)
        finally:
            if own_cache:
                self._predict_cache = None

    def _predict_cached(self, X, raw_score, start_iteration, num_iteration,
                        pred_leaf, pred_contrib, pred_early_stop,
                        pred_early_stop_freq, pred_early_stop_margin
                        ) -> np.ndarray:
        # bound the device working set: very large batches walk in row
        # chunks (the reference predicts row blocks too,
        # gbdt_prediction.cpp).  The dense walk's temporaries scale with
        # rows x num_leaves, so the chunk shrinks for wide models; the
        # TreeBatch is built once per outer call, not per chunk.
        chunk = min(1 << 20,
                    max(1 << 14, (1 << 28) //
                        max(int(self.config.num_leaves), 256)))
        k = self.num_tree_per_iteration
        if X.shape[0] > chunk:
            parts = [self._predict_cached(
                X[lo:lo + chunk], raw_score, start_iteration,
                num_iteration, pred_leaf, pred_contrib, pred_early_stop,
                pred_early_stop_freq, pred_early_stop_margin)
                for lo in range(0, X.shape[0], chunk)]
            return np.concatenate(parts, axis=0)
        # map raw columns to inner (used) features
        used = self.train_set.used_feature_map if self.train_set is not None \
            else np.arange(X.shape[1])
        Xi = X[:, used]
        if pred_leaf:
            return self._predict_leaf(Xi, start_iteration, num_iteration)
        if pred_contrib:
            return self._predict_contrib(Xi, start_iteration, num_iteration)
        if pred_early_stop or self.config.pred_early_stop:
            out = self._predict_early_stop(
                Xi, start_iteration, num_iteration,
                pred_early_stop_freq or self.config.pred_early_stop_freq,
                pred_early_stop_margin if pred_early_stop_margin is not None
                else self.config.pred_early_stop_margin)
            if out is not None:
                if raw_score or self.objective is None:
                    return out[:, 0] if k == 1 else out
                conv = self.objective.convert_output(
                    jnp.asarray(out if k > 1 else out[:, 0]))
                return np.asarray(conv)
        batch = self._tree_batch()
        if batch is None:
            raw = np.zeros((X.shape[0], k), np.float32)
        else:
            t0 = start_iteration * k
            t1 = batch.num_trees if num_iteration is None else min(
                batch.num_trees, (start_iteration + num_iteration) * k)
            # rows pad up the shape-bucket ladder so repeated odd-sized
            # predict calls reuse a few compiled programs instead of
            # tracing per novel row count (padding rows are sliced away
            # and cannot perturb real rows: every walk reduces per row)
            n_rows = Xi.shape[0]
            Xd = jnp.asarray(pad_rows(Xi))
            dense = self._dense_program(t0, t1, Xi.shape[1])
            if dense is not None:
                # the inference compiler's fused loop-free program:
                # every class's trees in one dense contraction set
                raw = np.asarray(dense.predict_raw(Xd))[:n_rows]
            elif k == 1:
                raw = np.asarray(
                    predict_raw(batch, Xd, t0, t1 - t0))[:n_rows, None]
            else:
                # class c's trees are at indices i*k + c
                cols = []
                cache = getattr(self, "_predict_cache", None)
                for c in range(k):
                    sel = [t for t in range(t0, t1) if t % k == c]
                    ck = ("mc", c, t0, t1)
                    if cache is not None and ck in cache:
                        sub = cache[ck]
                    else:
                        sub = TreeBatch([self.models[t] for t in sel]) \
                            if sel else None
                        if cache is not None:
                            cache[ck] = sub
                    cols.append(np.asarray(predict_raw(sub, Xd))[:n_rows]
                                if sub is not None
                                else np.zeros(n_rows, np.float32))
                raw = np.stack(cols, axis=1)
        if raw_score or self.objective is None:
            return raw[:, 0] if k == 1 else raw
        out = self.objective.convert_output(jnp.asarray(raw if k > 1 else raw[:, 0]))
        return np.asarray(out)

    def _predict_early_stop(self, Xi, start_iteration, num_iteration,
                            freq, margin):
        """Margin-based prediction early stop (prediction_early_stop.cpp):
        binary and multiclass only; None when not applicable."""
        from .tree import predict_raw_early_stop
        k = self.num_tree_per_iteration
        obj = self.config.objective
        if k > 1:
            mode = "multiclass"
        elif obj == "binary":
            mode = "binary"
        else:
            log_warning("pred_early_stop applies to binary/multiclass "
                        "objectives only; predicting normally")
            return None
        batch = self._tree_batch()
        if batch is None:
            return np.zeros((Xi.shape[0], k), np.float32)
        if batch.has_linear:
            log_warning("pred_early_stop is not supported with linear "
                        "trees; predicting normally")
            return None
        t0 = start_iteration * k
        t1 = batch.num_trees if num_iteration is None else min(
            batch.num_trees, (start_iteration + num_iteration) * k)
        base = (batch.split_feature, batch.threshold, batch.cat_words,
                batch.decision_type, batch.left_child, batch.right_child,
                batch.leaf_value, batch.num_leaves)
        per_class = tuple(tuple(a[t0 + c:t1:k] for a in base)
                          for c in range(k))
        Xp = pad_rows(np.asarray(Xi))
        # padding rows start pre-stopped: they must not keep the tree
        # loop alive after every real row has hit its margin
        stopped0 = jnp.asarray(np.arange(Xp.shape[0]) >= Xi.shape[0])
        out = predict_raw_early_stop(per_class, jnp.asarray(Xp),
                                     float(margin), stopped0,
                                     freq=max(1, int(freq)), mode=mode)
        return np.asarray(out)[:Xi.shape[0]]

    def _predict_leaf(self, Xi, start_iteration, num_iteration):
        k = self.num_tree_per_iteration
        t0 = start_iteration * k
        t1 = len(self.models) if num_iteration is None else min(
            len(self.models), (start_iteration + num_iteration) * k)
        Xd = jnp.asarray(pad_rows(np.asarray(Xi)))
        if t1 > t0:
            # pred-leaf rides the same compiled dense program (the hit
            # one-hot's argmax IS the leaf index)
            dense = self._dense_program(t0, t1, Xi.shape[1])
            if dense is not None:
                return np.asarray(dense.predict_leaf(Xd))[:Xi.shape[0]]
        leaves = []
        for t in range(t0, t1):
            tree = self.models[t]
            # walk returning leaf index: reuse raw walk on leaf-index values
            idx_tree = Tree(**{**tree.__dict__})
            idx_tree.leaf_value = np.arange(tree.max_leaves, dtype=np.float64)
            idx_tree.is_linear = False  # leaf INDEX lookup, not outputs
            tb = TreeBatch([idx_tree])
            leaves.append(np.asarray(predict_raw(tb, Xd))
                          [:Xi.shape[0]].astype(np.int32))
        return np.stack(leaves, axis=1) if leaves else np.zeros(
            (Xi.shape[0], 0), np.int32)

    # -- continued training / refit (reference gbdt.cpp:285 RefitTree;
    #    CreateBoosting(type, filename) boosting.cpp:35-67; CLI input_model
    #    path application.cpp:87-96) --------------------------------------
    def _align_loaded_tree(self, tree: Tree) -> Tree:
        """Re-key a loaded tree (REAL feature indices, raw thresholds, no bin
        info) onto this training Dataset: inner feature indices plus
        threshold_bin/nan_bin recovered through the BinMappers so the binned
        device walks work.  Exact when the data/binning match the one the
        model was trained on (the continued-training contract)."""
        ds = self.train_set
        inner_of_real = {int(r): i for i, r in enumerate(ds.used_feature_map)}
        t = Tree(**{**tree.__dict__})
        t.split_feature = np.array(tree.split_feature, np.int32, copy=True)
        t.threshold_bin = np.zeros_like(t.split_feature)
        t.nan_bin = np.full_like(t.split_feature, -1)
        from ..binning import MissingType
        from .tree import CAT_MASK as _CM
        n_int = max(t.num_leaves - 1, 1)
        member_bins = None
        for i in range(t.num_leaves - 1):
            rf = int(tree.split_feature[i])
            if rf not in inner_of_real:
                raise ValueError(
                    f"loaded model splits on feature {rf}, which is trivial "
                    f"(constant) in the continued-training dataset")
            f = inner_of_real[rf]
            t.split_feature[i] = f
            m = ds.bin_mappers[int(ds.used_feature_map[f])]
            if m.is_categorical:
                # recover the category SET (bitset over raw values) as
                # binned membership for the training-time walks
                if member_bins is None:
                    member_bins = np.zeros((n_int, self.max_bins), bool)
                if tree.cat_boundaries is not None:
                    rank = int(tree.threshold[i])
                    lo = int(tree.cat_boundaries[rank])
                    hi = int(tree.cat_boundaries[rank + 1])
                    cats = [w * 32 + b
                            for w in range(hi - lo)
                            for b in range(32)
                            if int(tree.cat_threshold[lo + w]) & (1 << b)]
                else:  # legacy single-category node
                    cats = [int(tree.threshold[i])]
                bins = [m.cat_to_bin[c] for c in cats if c in m.cat_to_bin]
                for b in bins:
                    member_bins[i, b] = True
                t.threshold_bin[i] = bins[0] if bins else 0
            else:
                t.threshold_bin[i] = int(
                    m.value_to_bin(np.array([tree.threshold[i]]))[0])
            if m.missing_type == MissingType.NAN:
                t.nan_bin[i] = m.num_bin - 1
        t.cat_member_bins = member_bins
        return t

    def init_from_model(self, other: "GBDT") -> None:
        """Prime this booster with an existing model's trees and keep
        boosting (continued training)."""
        k = self.num_tree_per_iteration
        ok = getattr(other, "num_tree_per_iteration", 1)
        if ok != k:
            raise ValueError(f"init_model has {ok} trees/iteration, this "
                             f"training configuration needs {k}")
        self._pending = []
        self._models_list = [self._align_loaded_tree(t) for t in other.models]
        self.iter_ = len(self._models_list) // max(k, 1)
        # the loaded first tree already carries any boost-from-average bias
        self._pending_bias[:] = 0.0
        self._rebuild_scores()

    def merge_from(self, other: "GBDT") -> None:
        """Append another booster's trees to this model
        (reference c_api.h:489 LGBM_BoosterMerge; GBDT::MergeFrom).
        Thresholds re-bin against THIS dataset's mappers so the appended
        trees join the binned score/walk paths."""
        k = self.num_tree_per_iteration
        ok = getattr(other, "num_tree_per_iteration", 1)
        if ok != k:
            raise ValueError(f"cannot merge: {ok} trees/iteration vs {k}")
        merged = self.models + [self._align_loaded_tree(t)
                                for t in other.models]
        self.models = merged
        self.iter_ = len(self._models_list) // max(k, 1)
        self._rebuild_scores()

    def shuffle_models(self, start_iter: int = 0,
                       end_iter: int = -1) -> None:
        """Shuffle tree-iteration order in [start_iter, end_iter)
        (reference c_api.h:497 LGBM_BoosterShuffleModels;
        GBDT::ShuffleModels) — used by the refit flow to decorrelate."""
        k = max(self.num_tree_per_iteration, 1)
        models = self.models
        n_iter = len(models) // k
        s = max(0, int(start_iter))
        e = n_iter if end_iter <= 0 else min(int(end_iter), n_iter)
        if e - s <= 1:
            return
        order = np.arange(n_iter)
        rng = np.random.RandomState(int(self.config.seed) + 1)
        mid = order[s:e].copy()
        rng.shuffle(mid)
        order[s:e] = mid
        self.models = [models[i * k + j] for i in order for j in range(k)]
        self._rebuild_scores()

    def reset_train_data(self, new_train: Dataset) -> None:
        """Swap the training dataset under the existing model (reference
        GBDT::ResetTrainingData; c_api.h:478).  The new dataset aligns to
        this model's bin mappers (construct-with-reference), every
        data-dependent piece rebuilds through the normal setup path, the
        trees re-align, and scores rebuild — continued training then
        proceeds on the new rows."""
        if not new_train.constructed and new_train.reference is None \
                and self.train_set is not None:
            new_train.reference = self.train_set
        self._flush_trees()
        models = self._models_list
        valid_state = (self.valid_sets, self.valid_scores,
                       self.valid_metrics)
        self._init_train(new_train)   # construct + upload + learner +
        #                               objective/metric re-init + score0
        self.valid_sets, self.valid_scores, self.valid_metrics = valid_state
        if models:
            k = max(self.num_tree_per_iteration, 1)
            self._pending = []
            self._models_list = [self._align_loaded_tree(t) for t in models]
            self.iter_ = len(self._models_list) // k
            # the loaded first tree already carries any boost-from-average
            # bias (same contract as init_from_model)
            self._pending_bias[:] = 0.0
            self._rebuild_scores()

    def refit_trees(self, source: "GBDT", leaf_preds: np.ndarray) -> None:
        """Re-learn every loaded tree's leaf values on THIS dataset with the
        tree structures fixed (reference gbdt.cpp:285 RefitTree +
        serial_tree_learner.cpp:211 FitByExistingTree): scores restart from
        the init score, gradients are recomputed per iteration, each leaf's
        new value is the closed-form output of its (fixed) row set, mixed as
        decay*old + (1-decay)*new."""
        if self.objective is None:
            raise ValueError("cannot refit without an objective")
        k = self.num_tree_per_iteration
        any_linear = any(t.is_linear for t in source.models)
        if any_linear and getattr(self, "X_raw_dev", None) is None:
            # linear leaves predict from raw values; refit needs them on
            # device even if this booster trains plain trees
            if self.train_set.raw_used is None:
                raise ValueError(
                    "refit of a linear-tree model needs raw feature "
                    "values; construct the dataset with linear_tree=true")
            self.X_raw_dev = jnp.asarray(self.train_set.raw_used)
        trees = [self._align_loaded_tree(t) for t in source.models]
        n = self.num_data
        if leaf_preds.shape != (n, len(trees)):
            raise ValueError(f"leaf_preds shape {leaf_preds.shape} != "
                             f"({n}, {len(trees)})")
        decay = float(self.config.refit_decay_rate)
        sp = self.learner.split_params
        md = self.train_set.metadata
        shape = (n,) if k == 1 else (n, k)
        score = np.zeros(shape, np.float32)
        if md.init_score is not None:
            score = score + md.init_score.reshape(shape).astype(np.float32)
        for it in range(len(trees) // max(k, 1)):
            grad, hess = self.objective.get_gradients(jnp.asarray(score))
            grad = np.asarray(grad)
            hess = np.asarray(hess)
            for cid in range(k):
                ti = it * k + cid
                tree = trees[ti]
                g = grad if k == 1 else grad[:, cid]
                h = hess if k == 1 else hess[:, cid]
                lp = leaf_preds[:, ti]
                nl = tree.num_leaves
                sum_g = np.bincount(lp, weights=g, minlength=nl)[:nl]
                sum_h = np.bincount(lp, weights=h, minlength=nl)[:nl] + EPSILON
                new_out = np.asarray(_leaf_output_fn(
                    jnp.asarray(sum_g, jnp.float32),
                    jnp.asarray(sum_h, jnp.float32), sp), np.float64)
                new_out *= tree.shrinkage
                old_vals = tree.leaf_value[:len(new_out)].copy()
                tree.leaf_value = (decay * old_vals +
                                   (1.0 - decay) * new_out)
                tree.leaf_count = np.bincount(lp, minlength=nl)[:nl].astype(
                    np.int64)
                if tree.is_linear:
                    # linear leaves keep their fitted coefficients (the
                    # reference's FitByExistingTree copies the tree and
                    # refits only the leaf OUTPUT); shifting the constant
                    # by the output delta re-centers the linear model on
                    # the new rows consistently with the refit value
                    shift = tree.leaf_value - old_vals
                    tree.leaf_const = tree.leaf_const[:len(shift)] + shift
                    from ..learner.linear import linear_score_delta
                    lf, fm, co, lconst, lval = \
                        self._linear_device_arrays(tree)
                    delta = np.asarray(linear_score_delta(
                        self.X_raw_dev, jnp.asarray(lp, jnp.int32), lf, fm,
                        co, lconst, lval, 1.0), np.float32)
                else:
                    delta = tree.leaf_value[lp].astype(np.float32)
                if k == 1:
                    score += delta
                else:
                    score[:, cid] += delta
        self._pending = []
        self._models_list = trees
        self.iter_ = len(trees) // max(k, 1)
        self._pending_bias[:] = 0.0
        self.score = jnp.asarray(score)

    # -- checkpoint/resume (resilience/checkpoint.py rides these) ------------
    def capture_checkpoint_arrays(self) -> Dict[str, Any]:
        """The mutable boosting state beyond the model text, pulled to
        host with EXACT bits: the f32 train/valid scores (rebuilding
        them from trees re-rounds in a different order and can drift
        the last ulp, forking the remaining trajectory), the CEGB
        used-feature set, and the lagged stump-stop bookkeeping."""
        prev = getattr(self, "_prev_iter_leaves", None)
        return {
            "score": np.asarray(self.score),
            "valid_names": [name for name, _ in self.valid_sets],
            "valid_scores": [np.asarray(s) for s in self.valid_scores],
            "cegb_used": (None if self._cegb_coupled is None
                          else np.asarray(self._cegb_used)),
            "prev_iter_leaves": (None if prev is None else
                                 [int(x) for x in jax.device_get(prev)]),
        }

    def restore_boosting_state(self, model_text: str, iteration: int,
                               score: np.ndarray,
                               valid_scores: List[np.ndarray],
                               cegb_used: Optional[np.ndarray] = None,
                               prev_iter_leaves: Optional[List[int]] = None
                               ) -> None:
        """Continue boosting from a checkpoint: trees reload from model
        text (%.17g round-trips every double) and re-key onto this
        dataset's binning; scores restore from the saved f32 bits
        instead of a tree-walk rebuild.  With the same data, params and
        seeds the continuation is bit-identical to a run that never
        stopped."""
        if self.name in ("dart", "rf"):
            raise ValueError(
                f"checkpoint/resume is not supported for boosting="
                f"{self.name}: its per-tree weight/averaging caches "
                f"(DART drop weights, RF running tree sums) are not part "
                f"of the model text")
        from .model_text import string_to_model
        loaded = string_to_model(model_text, self.config)
        k = self.num_tree_per_iteration
        ok = getattr(loaded, "num_tree_per_iteration", 1)
        if ok != k:
            raise ValueError(f"checkpoint model has {ok} trees/iteration, "
                             f"this training configuration needs {k}")
        self._pending = []
        self._models_list = [self._align_loaded_tree(t)
                             for t in loaded.models]
        self.iter_ = int(iteration)
        # tree 0 already carries any boost-from-average bias
        self._pending_bias[:] = 0.0
        score = np.asarray(score, np.float32)
        want = (self.num_data,) if k == 1 else (self.num_data, k)
        if score.shape != want:
            raise ValueError(f"checkpoint score shape {score.shape} does "
                             f"not match this dataset ({want})")
        self.score = jnp.asarray(score)
        if len(valid_scores) != len(self.valid_scores):
            raise ValueError(
                f"checkpoint carries {len(valid_scores)} validation score "
                f"sets, this run registered {len(self.valid_scores)} "
                f"valid sets")
        self.valid_scores = [jnp.asarray(np.asarray(vs, np.float32))
                             for vs in valid_scores]
        if cegb_used is not None and self._cegb_coupled is not None:
            self._cegb_used[:] = np.asarray(cegb_used, bool)
        self._prev_iter_leaves = (None if prev_iter_leaves is None else
                                  [int(x) for x in prev_iter_leaves])

    # -- model management ----------------------------------------------------
    def rollback_one_iter(self) -> None:
        """Reference gbdt.cpp:454 RollbackOneIter."""
        if self.iter_ <= 0:
            return
        k = self.num_tree_per_iteration
        for _ in range(k):
            if self.models:
                self.models.pop()
        self.iter_ -= 1
        # scores must be rebuilt from remaining trees
        self._rebuild_scores()

    def _rebuild_scores(self) -> None:
        k = self.num_tree_per_iteration
        n = self.num_data
        shape = (n,) if k == 1 else (n, k)
        score0 = np.zeros(shape, np.float32)
        md = self.train_set.metadata
        if md.init_score is not None:
            score0 += md.init_score.reshape(shape).astype(np.float32)
        elif not self.models and self.config.boost_from_average and \
                self.objective is not None:
            # with no trees left the bias is no longer carried by tree 0;
            # restore it so gradients and the next first tree stay consistent
            score0 += (np.float32(self._pending_bias[0]) if k == 1 else
                       self._pending_bias[None, :].astype(np.float32))
        self.score = jnp.asarray(score0)
        if self.models:
            score = self.score
            for t, tree in enumerate(self.models):
                cid = t % k
                if tree.is_linear:
                    from ..learner.linear import linear_score_delta
                    idx_f = self._walk(self.X_dev, jnp.asarray(tree.split_feature),
                               jnp.asarray(tree.threshold_bin),
                               jnp.asarray(tree.nan_bin),
                               _tree_cat_member(tree),
                               jnp.asarray(tree.decision_type.astype(np.int32)),
                               jnp.asarray(tree.left_child),
                               jnp.asarray(tree.right_child),
                               jnp.arange(tree.max_leaves, dtype=jnp.float32),
                               jnp.asarray(tree.num_leaves, dtype=jnp.int32))
                    lf, fm, co, lconst, lval = self._linear_device_arrays(tree)
                    delta = linear_score_delta(
                        self.X_raw_dev, idx_f.astype(jnp.int32), lf, fm, co,
                        lconst, lval, 1.0)
                else:
                    delta = self._walk(
                        self.X_dev, jnp.asarray(tree.split_feature),
                               jnp.asarray(tree.threshold_bin),
                               jnp.asarray(tree.nan_bin),
                               _tree_cat_member(tree),
                               jnp.asarray(tree.decision_type.astype(np.int32)),
                               jnp.asarray(tree.left_child),
                               jnp.asarray(tree.right_child),
                               jnp.asarray(tree.leaf_value, dtype=jnp.float32),
                               jnp.asarray(tree.num_leaves, dtype=jnp.int32))
                if k == 1:
                    score = score + delta
                else:
                    score = score.at[:, cid].add(delta)
            self.score = score

    @property
    def current_iteration(self) -> int:
        return self.iter_

    def num_trees(self) -> int:
        return len(self.models)

    # model text IO lives in model_text.py
    def save_model_to_string(self, start_iteration: int = 0,
                             num_iteration: int = -1,
                             importance_type: int = 0) -> str:
        from .model_text import model_to_string
        return model_to_string(self, start_iteration, num_iteration)

    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        """Reference Booster::FeatureImportance (gbdt.cpp).

        Returns a full-length array over the ORIGINAL columns (the reference
        reports num_total_features entries; trivially-filtered columns get
        zero), so ``zip(X.columns, importances)`` works."""
        imp = np.zeros(self.num_features, np.float64)
        for tree in self.models:
            for i in range(tree.num_leaves - 1):
                f = tree.split_feature[i]
                if f >= 0:
                    if importance_type == "split":
                        imp[f] += 1.0
                    else:
                        imp[f] += max(tree.split_gain[i], 0.0)
        real_map, num_total, _ = self.feature_mapping()
        full = np.zeros(num_total, np.float64)
        full[real_map] = imp
        return full

    def feature_mapping(self):
        """(inner->original index map, num original columns, original names) —
        the single source for mapping tree-internal feature indices back to
        the user's columns (trained models: Dataset's trivial-filter map;
        loaded models: identity over max_feature_idx+1)."""
        ts = self.train_set
        if ts is not None and ts.used_feature_map is not None:
            return (np.asarray(ts.used_feature_map),
                    int(ts.num_total_features), list(ts.feature_names_))
        num_total = int(getattr(self, "loaded_num_total", self.num_features))
        real_map = np.asarray(getattr(self, "loaded_real_map",
                                      np.arange(self.num_features)))
        names = getattr(self, "loaded_feature_names", None) or \
            [f"Column_{i}" for i in range(num_total)]
        return real_map, num_total, names
