"""Boosting variants: GOSS, DART, RF + factory
(reference: src/boosting/boosting.cpp:35 ``Boosting::CreateBoosting``,
goss.hpp:25 ``GOSS``, dart.hpp ``DART``, rf.hpp:25 ``RF``)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..dataset import Dataset
from ..utils.log import log_warning
from ..utils.random import host_rng
from .gbdt import GBDT


class GOSS(GBDT):
    """Gradient-based One-Side Sampling (reference src/boosting/goss.hpp:
    keep top ``top_rate`` rows by |g*h|, Bernoulli-sample ``other_rate`` of
    the rest and amplify their gradients by (1-a)/b, :103-152; sampling is
    skipped for the first 1/learning_rate iterations, :157).

    The reference samples an exact count with a per-thread RNG; here the
    "rest" rows are sampled i.i.d. Bernoulli — same distribution,
    deterministic per (seed, iteration).  The draw itself is HOST-side
    (``gbdt.goss_sample_np``): one shared Philox stream serves this
    trainer, the chunked streamed driver and the multi-model batcher, so
    all three thin the same rows and stay bit-identical to each other."""

    name = "goss"

    def __init__(self, config: Config, train_set: Optional[Dataset],
                 objective=None) -> None:
        super().__init__(config, train_set, objective)
        if config.bagging_freq > 0 and config.bagging_fraction < 1.0:
            log_warning("cannot use bagging in GOSS (ignored)")

    def _prepare_iter_sampling(self, grad, hess):
        from .gbdt import goss_sample_np
        gm = goss_sample_np(self.config, jax.device_get(grad),
                            jax.device_get(hess), self.iter_)
        if gm is None:
            return grad, hess, jnp.ones(self.num_data, jnp.float32)
        mask, mult = gm
        scale = jnp.asarray(mult)
        if grad.ndim == 2:
            scale = scale[:, None]
        return grad * scale, hess * scale, jnp.asarray(mask)


class DART(GBDT):
    """Dropouts meet MART (reference src/boosting/dart.hpp: ``DroppingTrees``
    at :97 — weighted drop selection, train-score subtraction, per-iteration
    shrinkage lr/(1+k) — and ``Normalize`` at :158 — dropped trees rescaled
    to weight*k/(k+1)).

    Each tree's unshrunk train/valid predictions are cached on device so
    drop/renormalize score adjustments are O(N) axpy ops instead of tree
    re-walks."""

    name = "dart"
    # DART reads/rescales host trees every iteration (Normalize), so tree
    # deferral buys nothing and would corrupt weights if _normalize ever
    # indexed _models_list directly — opt out explicitly.
    _defer_trees = False

    def __init__(self, config: Config, train_set: Optional[Dataset],
                 objective=None) -> None:
        super().__init__(config, train_set, objective)
        self._base_pred: list = []        # per iteration: raw train pred (N,[K])
        self._valid_base_pred: list = []  # per iteration: list per valid set
        self._weights: list = []          # current weight (includes shrinkage)
        self._sum_weight = 0.0
        self._cur_shrinkage = float(config.learning_rate)
        self._drop_idx: list = []

    def _current_shrinkage(self) -> float:
        return self._cur_shrinkage

    def train_one_iter(self, grad=None, hess=None) -> bool:
        cfg = self.config
        lr = float(cfg.learning_rate)
        rng = host_rng(cfg.drop_seed, self.iter_)
        t = self.iter_
        drop: list = []
        if t > 0 and not (rng.random() < cfg.skip_drop):
            if cfg.uniform_drop:
                p = cfg.drop_rate
                if cfg.max_drop > 0:
                    p = min(p, cfg.max_drop / float(t))
                for i in range(t):
                    if rng.random() < p:
                        drop.append(i)
                        if cfg.max_drop > 0 and len(drop) >= cfg.max_drop:
                            break
            else:
                inv_avg = t / max(self._sum_weight, 1e-12)
                p = cfg.drop_rate
                if cfg.max_drop > 0:
                    p = min(p, cfg.max_drop * inv_avg / max(self._sum_weight,
                                                            1e-12))
                for i in range(t):
                    if rng.random() < p * self._weights[i] * inv_avg:
                        drop.append(i)
                        if cfg.max_drop > 0 and len(drop) >= cfg.max_drop:
                            break
        self._drop_idx = drop
        kd = float(len(drop))
        # remove dropped trees from the TRAIN score (valid handled in
        # normalize, like the reference)
        for d in drop:
            self.score = self.score - self._base_pred[d] * self._weights[d]
        if cfg.xgboost_dart_mode:
            self._cur_shrinkage = lr if not drop else lr / (lr + kd)
        else:
            self._cur_shrinkage = lr / (1.0 + kd)
        res = super().train_one_iter(grad, hess)
        self._normalize(drop)
        return res

    def _record_tree(self, grown, class_id):
        self._valid_deltas_this_tree = []
        n_valid_before = [np.asarray(v).copy() for v in self.valid_scores]
        tree = super()._record_tree(grown, class_id)
        w = self._cur_shrinkage
        base = grown.leaf_value[grown.row_leaf]  # raw, unshrunk
        if self.num_tree_per_iteration == 1:
            pred = base
        else:
            z = jnp.zeros(self.score.shape, jnp.float32)
            pred = z.at[:, class_id].set(base)
        if class_id == 0:
            self._base_pred.append(pred)
            self._weights.append(w)
            self._sum_weight += w
            vb = []
            for vi in range(len(self.valid_sets)):
                delta = jnp.asarray(self.valid_scores[vi]) - jnp.asarray(
                    n_valid_before[vi])
                vb.append(delta / w)
            self._valid_base_pred.append(vb)
        else:
            self._base_pred[-1] = self._base_pred[-1] + pred
            for vi in range(len(self.valid_sets)):
                delta = jnp.asarray(self.valid_scores[vi]) - jnp.asarray(
                    n_valid_before[vi])
                self._valid_base_pred[-1][vi] = \
                    self._valid_base_pred[-1][vi] + delta / w
        return tree

    def _normalize(self, drop_idx) -> None:
        cfg = self.config
        kd = float(len(drop_idx))
        if kd == 0:
            return
        lr = float(cfg.learning_rate)
        factor = kd / (kd + lr) if cfg.xgboost_dart_mode else kd / (kd + 1.0)
        kk = self.num_tree_per_iteration
        for d in drop_idx:
            old_w = self._weights[d]
            new_w = old_w * factor
            self._weights[d] = new_w
            self._sum_weight -= old_w - new_w
            for c in range(kk):
                self.models[d * kk + c].shrink(factor)
            # train score: re-add at the new weight (was fully removed)
            self.score = self.score + self._base_pred[d] * new_w
            # valid score: adjust by the weight delta (was never removed)
            for vi in range(len(self.valid_sets)):
                self.valid_scores[vi] = self.valid_scores[vi] + \
                    self._valid_base_pred[d][vi] * (new_w - old_w)


class RF(GBDT):
    """Random forest mode (reference src/boosting/rf.hpp:25): bagging
    mandatory, no shrinkage, scores are the average of tree outputs and
    gradients are always computed against the averaged score.

    The boost-from-average init score is folded into EVERY tree's leaf
    values (averaging then preserves it, and loaded models predict
    correctly with a plain tree-average)."""

    name = "rf"

    def __init__(self, config: Config, train_set: Optional[Dataset],
                 objective=None) -> None:
        if train_set is not None and \
                not (config.bagging_freq > 0 and config.bagging_fraction < 1.0) \
                and config.feature_fraction >= 1.0:
            raise ValueError("RF mode requires bagging "
                             "(bagging_freq > 0 and bagging_fraction < 1) "
                             "or feature_fraction < 1")
        super().__init__(config, train_set, objective)
        self._tree_sum: Optional[jnp.ndarray] = None
        self._valid_tree_sum: list = []
        self._valid_base: list = []
        if train_set is not None:
            md = self.train_set.metadata
            if md.init_score is not None:
                self._rf_base = jnp.asarray(
                    md.init_score.reshape(self.score.shape), jnp.float32)
            else:
                self._rf_base = jnp.zeros(self.score.shape, jnp.float32)

    def _current_shrinkage(self) -> float:
        return 1.0

    def add_valid(self, valid_set, name):
        super().add_valid(valid_set, name)
        md = valid_set.metadata
        shape = self.valid_scores[-1].shape
        if md.init_score is not None:
            self._valid_base.append(jnp.asarray(md.init_score.reshape(shape),
                                                jnp.float32))
        else:
            self._valid_base.append(jnp.zeros(shape, jnp.float32))
        self._valid_tree_sum.append(None)

    def _record_tree(self, grown, class_id):
        from .gbdt import _grown_to_tree
        tree = _grown_to_tree(grown, 1.0, self.train_set)
        bias = float(self._pending_bias[class_id])
        if abs(bias) > 1e-12:
            tree.add_bias(bias)
        self.models.append(tree)
        k = self.num_tree_per_iteration
        lv = grown.leaf_value + bias
        pred = lv[grown.row_leaf]
        t = self.iter_ + 1
        if self._tree_sum is None:
            self._tree_sum = jnp.zeros(self.score.shape, jnp.float32)
        if k == 1:
            self._tree_sum = self._tree_sum + pred
        else:
            self._tree_sum = self._tree_sum.at[:, class_id].add(pred)
        self.score = self._rf_base + self._tree_sum / t
        for vi, (_, vset) in enumerate(self.valid_sets):
            vbins = vset._device_cache["bins"]
            delta = self._walk(vbins, grown.split_feature, grown.threshold_bin,
                                 grown.nan_bin, grown.cat_member,
                                 grown.decision_type,
                                 grown.left_child, grown.right_child,
                                 jnp.asarray(lv, jnp.float32), grown.num_leaves)
            if self._valid_tree_sum[vi] is None:
                self._valid_tree_sum[vi] = jnp.zeros(
                    self.valid_scores[vi].shape, jnp.float32)
            if k == 1:
                self._valid_tree_sum[vi] = self._valid_tree_sum[vi] + delta
            else:
                self._valid_tree_sum[vi] = \
                    self._valid_tree_sum[vi].at[:, class_id].add(delta)
            self.valid_scores[vi] = self._valid_base[vi] + \
                self._valid_tree_sum[vi] / t
        return tree

    def predict(self, X, raw_score=False, start_iteration=0,
                num_iteration=None, pred_leaf=False, pred_contrib=False,
                **kwargs):
        out = super().predict(X, raw_score=True,
                              start_iteration=start_iteration,
                              num_iteration=num_iteration,
                              pred_leaf=pred_leaf, pred_contrib=pred_contrib)
        if pred_leaf or pred_contrib:
            return out
        k = self.num_tree_per_iteration
        t = max(1, len(self.models) // k)
        out = out / t
        if raw_score or self.objective is None:
            return out
        return np.asarray(self.objective.convert_output(jnp.asarray(out)))


def create_boosting(config: Config, train_set: Optional[Dataset],
                    objective=None) -> GBDT:
    """Factory (reference src/boosting/boosting.cpp:35)."""
    kind = config.boosting
    if kind == "gbdt":
        return GBDT(config, train_set, objective)
    if kind == "goss":
        return GOSS(config, train_set, objective)
    if kind == "dart":
        return DART(config, train_set, objective)
    if kind == "rf":
        return RF(config, train_set, objective)
    raise ValueError(f"Unknown boosting type: {kind}")
