"""Fused dense ensemble prediction — the serving compiler's kernel tier.

Lowers a whole trained ensemble (every tree, every class) into ONE
dense program of path-condition contractions, the "Booster" accelerator
formulation (PAPERS.md arXiv:2011.02022) generalized from the per-tree
``_walk_raw_dense`` in :mod:`.tree`:

* the per-node feature lookup is a one-hot contraction (MXU) or a
  static-index take (CPU) over ALL ``T*(L-1)`` nodes at once;
* numeric thresholds are one broadcast compare against the stacked
  threshold row; NaN/default-direction/missing-type decision bits are
  folded into the same condition matrix;
* **categorical splits are a bitset-membership contraction**: the
  per-node ``cat_words`` uint32 bitsets unpack to a dense
  ``(cat_features * 32W, cat_nodes)`` 0/1 table and membership is the
  dot product of the row's category one-hot with that table — the
  FindInBitset bit-gather reformulated as AND+popcount on the MXU, so
  categorical ensembles no longer fall back to the sequential walk;
* leaf resolution is the satisfied-path-condition count: one batched
  contraction ``dec @ path_dir`` per tree axis and an EXACT
  ``relu(S - (plen_total - plen_right - 1))`` hit indicator (S is
  integer-valued and bounded by the path length, so the ReLU is a 0/1
  one-hot over leaves — no equality select needed on the matmul output);
* **leaf tables may be quantized** to i8/i16 codes with a per-tree
  scale, dequantized inside the final contraction (bit-controlled
  tolerance: per-tree error <= scale/2);
* piece-wise-linear leaves ride the same shape as a leaf-gather+matmul
  (arXiv:1802.05640): a dense ``(T, L, F)`` coefficient table contracts
  with the row block and the hit one-hot selects the active model, with
  the reference's NaN fallback to the plain leaf value.

The program contains NO ``while``/``scan`` loops (machine-checked by
the ``serve_dense`` trace-lint config) and, when sharded over the tree
axis, exactly one ``psum`` of the per-shard partial scores.

Host-side lowering lives in :func:`lower_ensemble`; the jitted entries
take the lowered arrays as ARGUMENTS so XLA's compile cache keys on
shapes/dtypes only — every model with the same shape signature shares
one compiled program per row bucket (the ``CompiledPredictor``
contract).
"""

from __future__ import annotations

import functools
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .tree import CAT_MASK, DEFAULT_LEFT_MASK, MISSING_NAN, Tree, TreeBatch

__all__ = ["DenseLoweringError", "DenseMeta", "DenseArrays",
           "lower_ensemble", "dense_predict_raw", "dense_predict_leaf",
           "make_sharded_predict", "dense_table_bytes",
           "stack_dense_arrays", "stacked_predict_raw",
           "make_stacked_sharded_predict",
           "CAT_TABLE_BUDGET", "LINEAR_TABLE_BUDGET"]

# Lowering budgets: a categorical bitset table or a linear-leaf
# coefficient table past these sizes would dominate HBM/cache for no
# win — the compiler falls back to the walk with a recorded reason.
CAT_TABLE_BUDGET = 128 << 20       # bytes of (Fc*C, NC) + top-bucket V block
LINEAR_TABLE_BUDGET = 256 << 20    # bytes of the dense (T, L, F) tables


class DenseLoweringError(ValueError):
    """The ensemble cannot (or should not) lower to the dense program.

    ``reason`` is a short machine-usable tag (``cat_table_budget``,
    ``linear_table_budget`` ...) surfaced by the serve compiler's
    fallback telemetry."""

    def __init__(self, reason: str, detail: str = "") -> None:
        self.reason = reason
        super().__init__(f"dense lowering unavailable ({reason})"
                         + (f": {detail}" if detail else ""))


class DenseMeta(NamedTuple):
    """Static (hashable) half of a lowered ensemble — the jit cache key
    next to the array shapes."""

    num_class: int
    num_trees: int            # REAL trees (before shard padding)
    has_cat: bool
    has_linear: bool
    leaf_bits: int            # 0 = exact f32 leaf table, else 8 | 16
    mxu: bool                 # True: one-hot/bf16 contractions (TPU);
                              # False: take/f32 lowering (CPU, interpret)


class DenseArrays(NamedTuple):
    """Device half of a lowered ensemble (a jax pytree; ``None`` fields
    collapse to empty subtrees so the jit cache keys on presence)."""

    split_feature: jnp.ndarray     # (T, Nn) int32 inner feature per node
    threshold: jnp.ndarray         # (T, Nn) f32
    dleft: jnp.ndarray             # (T, Nn) bool — default-left bit
    miss_nan: jnp.ndarray          # (T, Nn) bool — missing type == nan
    is_cat: jnp.ndarray            # (T, Nn) bool
    path_dir: jnp.ndarray          # (T, Nn, L) int8 — +1 left / -1 right
    qthresh: jnp.ndarray           # (T, L) f32 = plen_total - plen_right - 1
    leaf_codes: jnp.ndarray        # (T, L) f32 | int8 | int16
    leaf_scale: jnp.ndarray        # (T, 1) f32 dequant scale (1.0 when f32)
    class_onehot: jnp.ndarray      # (T, K) f32
    # categorical bitset contraction (None on cat-free ensembles)
    cat_feats: Optional[jnp.ndarray] = None       # (Fc,) int32 inner idx
    cat_table: Optional[jnp.ndarray] = None       # (Fc*C, NCp) f32|bf16
    node_cat_slot: Optional[jnp.ndarray] = None   # (T, Nn) int32, 0 = none
    # piece-wise-linear leaf tables (None on non-linear ensembles)
    lin_w: Optional[jnp.ndarray] = None           # (T, L, F) f32
    lin_mask: Optional[jnp.ndarray] = None        # (T, L, F) f32 0/1
    lin_const: Optional[jnp.ndarray] = None       # (T, L) f32
    lin_flag: Optional[jnp.ndarray] = None        # (T, 1) f32


def _unpack_bits32(words: np.ndarray) -> np.ndarray:
    """uint32 word vector -> (32 * len,) 0/1 float32 (LSB first)."""
    bits = (words[:, None] >> np.arange(32, dtype=np.uint32)[None, :]) & 1
    return bits.reshape(-1).astype(np.float32)


def dense_table_bytes(arrays: DenseArrays) -> int:
    """Total bytes of the lowered model tables (the ``info()`` figure)."""
    total = 0
    for a in arrays:
        if a is not None:
            total += a.size * a.dtype.itemsize
    return int(total)


def lower_ensemble(trees: List[Tree], num_class: int, num_features: int,
                   class_ids: Optional[List[int]] = None, *,
                   leaf_bits: int = 0, mxu: bool = False, shard: int = 1,
                   batch: Optional[TreeBatch] = None,
                   cat_budget: int = CAT_TABLE_BUDGET,
                   linear_budget: int = LINEAR_TABLE_BUDGET,
                   ) -> Tuple[DenseArrays, DenseMeta]:
    """Lower ``trees`` (classes interleaved ``t % num_class`` unless
    ``class_ids`` is given) into the fused dense program's arrays.

    ``shard > 1`` pads the tree axis to a multiple of ``shard`` with
    inert trees (unreachable leaves, zero class row) so the tree axis
    divides a mesh.  Raises :class:`DenseLoweringError` when a table
    would blow its budget."""
    if not trees:
        raise DenseLoweringError("no_trees")
    if leaf_bits not in (0, 8, 16):
        raise DenseLoweringError("leaf_bits", f"{leaf_bits} not in 0|8|16")
    b = batch if batch is not None else TreeBatch(trees)
    T = b.num_trees
    ml = b.max_leaves
    L = ml
    Nn = max(ml - 1, 1)
    if class_ids is None:
        class_ids = [t % num_class for t in range(T)]

    sf = np.zeros((T, Nn), np.int32)
    thr = np.zeros((T, Nn), np.float32)
    dt = np.zeros((T, Nn), np.uint8)
    sf[:, :max(ml - 1, 0)] = np.asarray(b.split_feature)
    thr[:, :max(ml - 1, 0)] = np.asarray(b.threshold)
    dt[:, :max(ml - 1, 0)] = np.asarray(b.decision_type)
    # only the first num_leaves-1 node slots of each tree are real; mask
    # the rest inert so stray decision bits on padding cannot mark a
    # nonexistent categorical node
    nl = np.asarray(b.num_leaves)
    real = np.arange(Nn)[None, :] < np.maximum(nl - 1, 0)[:, None]
    dt = np.where(real, dt, 0).astype(np.uint8)
    is_cat = (dt & CAT_MASK) != 0
    dleft = (dt & DEFAULT_LEFT_MASK) != 0
    miss_nan = (dt & (3 << 2)) == MISSING_NAN

    # path matrices: TreeBatch builds (T, Nn, L) host-side already
    pd = np.asarray(b.path_dir, np.int8)
    qt = (np.asarray(b.plen_total, np.float32) -
          np.asarray(b.plen_right, np.float32) - 1.0)

    # quantized leaf table: i8/i16 codes + per-tree scale, dequantized
    # in the final contraction (bit-controlled tolerance <= scale/2)
    leaf = np.asarray(b.leaf_value, np.float32)
    if leaf_bits:
        qmax = float((1 << (leaf_bits - 1)) - 1)
        maxabs = np.max(np.abs(leaf), axis=1)
        scale = np.where(maxabs > 0, maxabs / qmax, 1.0).astype(np.float32)
        codes = np.rint(leaf / scale[:, None]).astype(
            np.int8 if leaf_bits == 8 else np.int16)
    else:
        scale = np.ones(T, np.float32)
        codes = leaf

    cls = np.zeros((T, num_class), np.float32)
    cls[np.arange(T), np.asarray(class_ids, np.int64)] = 1.0

    # --- categorical bitset -> dense membership table ----------------------
    has_cat = bool(is_cat.any())
    cat_feats = cat_table = node_slot = None
    if has_cat:
        words = np.asarray(b.cat_words)               # (T, Nn', W)
        W = words.shape[2]
        C = 32 * W
        feats = np.unique(sf[is_cat])
        slot_of = {int(f): j for j, f in enumerate(feats)}
        Fc = len(feats)
        cat_idx = np.argwhere(is_cat)                  # (NC, 2)
        NC = len(cat_idx)
        NCp = max(8, -(-NC // 8) * 8)
        top_bucket = 4096
        table_b = 4 * Fc * C * NCp + 4 * top_bucket * Fc * C
        if table_b > cat_budget:
            raise DenseLoweringError(
                "cat_table_budget",
                f"{Fc} cat features x {C} categories x {NC} cat nodes "
                f"needs ~{table_b >> 20} MiB (> {cat_budget >> 20} MiB)")
        K = np.zeros((Fc * C, NCp), np.float32)
        node_slot = np.zeros((T, Nn), np.int32)
        for m, (ti, ni) in enumerate(cat_idx):
            j = slot_of[int(sf[ti, ni])]
            K[j * C:(j + 1) * C, m] = _unpack_bits32(
                words[ti, ni] if ni < words.shape[1]
                else np.zeros(W, np.uint32))
            node_slot[ti, ni] = m + 1
        cat_feats = feats.astype(np.int32)
        cat_table = K.astype(np.float32)

    # --- piece-wise-linear leaves as dense (T, L, F) tables ----------------
    has_linear = bool(b.has_linear)
    lin_w = lin_mask = lin_const = lin_flag = None
    if has_linear:
        table_b = 2 * 4 * T * L * num_features
        if table_b > linear_budget:
            raise DenseLoweringError(
                "linear_table_budget",
                f"(T={T}, L={L}, F={num_features}) linear tables need "
                f"~{table_b >> 20} MiB (> {linear_budget >> 20} MiB)")
        lin_w = np.zeros((T, L, num_features), np.float32)
        lin_mask = np.zeros((T, L, num_features), np.float32)
        lin_const = np.zeros((T, L), np.float32)
        lin_flag = np.zeros((T, 1), np.float32)
        for ti, t in enumerate(trees):
            if not t.is_linear:
                continue
            lin_flag[ti, 0] = 1.0
            lin_const[ti, :len(t.leaf_const)] = np.asarray(
                t.leaf_const, np.float32)
            feats_per_leaf = (t.leaf_features_inner
                              if t.leaf_features_inner is not None
                              else t.leaf_features)
            for leaf_i, (fs, cs) in enumerate(zip(feats_per_leaf,
                                                  t.leaf_coeff)):
                for f, c in zip(fs, cs):
                    lin_w[ti, leaf_i, f] += np.float32(c)
                    lin_mask[ti, leaf_i, f] = 1.0

    # --- shard padding: inert trees make the tree axis divide a mesh -------
    if shard > 1 and T % shard:
        pad = shard - T % shard
        sf = np.pad(sf, ((0, pad), (0, 0)))
        thr = np.pad(thr, ((0, pad), (0, 0)))
        dleft = np.pad(dleft, ((0, pad), (0, 0)))
        miss_nan = np.pad(miss_nan, ((0, pad), (0, 0)))
        is_cat = np.pad(is_cat, ((0, pad), (0, 0)))
        pd = np.pad(pd, ((0, pad), (0, 0), (0, 0)))
        qt = np.pad(qt, ((0, pad), (0, 0)), constant_values=np.float32(1e9))
        codes = np.pad(codes, ((0, pad), (0, 0)))
        scale = np.pad(scale, (0, pad), constant_values=np.float32(1.0))
        cls = np.pad(cls, ((0, pad), (0, 0)))
        if node_slot is not None:
            node_slot = np.pad(node_slot, ((0, pad), (0, 0)))
        if lin_w is not None:
            lin_w = np.pad(lin_w, ((0, pad), (0, 0), (0, 0)))
            lin_mask = np.pad(lin_mask, ((0, pad), (0, 0), (0, 0)))
            lin_const = np.pad(lin_const, ((0, pad), (0, 0)))
            lin_flag = np.pad(lin_flag, ((0, pad), (0, 0)))

    j = jnp.asarray
    arrays = DenseArrays(
        split_feature=j(sf), threshold=j(thr), dleft=j(dleft),
        miss_nan=j(miss_nan), is_cat=j(is_cat), path_dir=j(pd),
        qthresh=j(qt), leaf_codes=j(codes),
        leaf_scale=j(scale.reshape(-1, 1)), class_onehot=j(cls),
        cat_feats=None if cat_feats is None else j(cat_feats),
        cat_table=None if cat_table is None else j(
            cat_table.astype(np.float32)),
        node_cat_slot=None if node_slot is None else j(node_slot),
        lin_w=None if lin_w is None else j(lin_w),
        lin_mask=None if lin_mask is None else j(lin_mask),
        lin_const=None if lin_const is None else j(lin_const),
        lin_flag=None if lin_flag is None else j(lin_flag))
    meta = DenseMeta(num_class=num_class, num_trees=T, has_cat=has_cat,
                     has_linear=has_linear, leaf_bits=leaf_bits,
                     mxu=bool(mxu))
    return arrays, meta


# ---------------------------------------------------------------------------
# the fused program
# ---------------------------------------------------------------------------

def _node_values(X, flat_feature, mxu: bool):
    """(N, T*Nn) per-node row values: a one-hot contraction on the MXU
    (exact f32 at Precision.HIGHEST — a bf16-rounded value could flip a
    near-threshold decision), a static-index take elsewhere (the
    indices are model constants, so XLA lowers a plain column copy)."""
    if not mxu:
        return jnp.take(X, flat_feature, axis=1)
    f_count = X.shape[1]
    onehot = (jnp.arange(f_count, dtype=jnp.int32)[:, None] ==
              flat_feature[None, :]).astype(jnp.float32)
    return jax.lax.dot_general(X, onehot, (((1,), (0,)), ((), ())),
                               precision=jax.lax.Precision.HIGHEST)


def _decision_matrix(X, A: DenseArrays, meta: DenseMeta):
    """The fused condition matrix ``dec`` (N, T, Nn) in {0,1}: numeric
    broadcast compares, NaN/default-direction bits, and the categorical
    bitset contraction, all folded in."""
    n = X.shape[0]
    T, Nn = A.split_feature.shape
    flat_sf = A.split_feature.reshape(-1)
    P = _node_values(jnp.nan_to_num(X), flat_sf, meta.mxu)
    isn = _node_values(jnp.isnan(X).astype(jnp.float32), flat_sf,
                       meta.mxu) > 0.5
    dec = P <= A.threshold.reshape(-1)[None, :]
    if meta.has_cat:
        Fc = A.cat_feats.shape[0]
        C = A.cat_table.shape[0] // Fc
        # the row's category one-hot over (feature, category); NaN and
        # non-integer / out-of-range values one-hot to all-zero rows,
        # which contract to "not a member" (go right) exactly like the
        # reference FindInBitset out-of-range path
        Xc = jnp.take(X, A.cat_feats, axis=1)
        Xc = jnp.where(jnp.isnan(Xc), -1.0, Xc)
        V = (Xc[:, :, None] ==
             jnp.arange(C, dtype=X.dtype)[None, None, :])
        V = V.reshape(n, Fc * C)
        # membership = AND+popcount as a dense contraction: the row
        # one-hot dotted with the unpacked per-node bitset table
        if meta.mxu:
            member = jax.lax.dot_general(
                V.astype(jnp.bfloat16), A.cat_table.astype(jnp.bfloat16),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            member = jax.lax.dot_general(
                V.astype(jnp.float32), A.cat_table,
                (((1,), (0,)), ((), ())))
        member = jnp.concatenate(
            [jnp.zeros((n, 1), member.dtype), member], axis=1)
        member = jnp.take(member, A.node_cat_slot.reshape(-1), axis=1)
        dec = jnp.where(A.is_cat.reshape(-1)[None, :], member > 0.5, dec)
    # NaN routing: categorical and missing-nan numeric nodes take the
    # default direction; other numeric nodes already compare the
    # sanitized 0.0 (the reference's missing-zero path)
    nan_default = (A.miss_nan | A.is_cat).reshape(-1)
    dec = jnp.where(isn & nan_default[None, :],
                    A.dleft.reshape(-1)[None, :], dec)
    return dec.reshape(n, T, Nn)


def _hit_matrix(dec, A: DenseArrays, meta: DenseMeta):
    """(T, N, L) EXACT 0/1 leaf one-hot via the satisfied-condition
    count.  ``S`` counts correct turns along each leaf's root path
    (integer-valued, <= path length), so ``relu(S - (len-1))`` is 1
    exactly on the reached leaf and 0 elsewhere — the equality test of
    the per-tree dense walk without a select on the matmul output."""
    acc = jnp.bfloat16 if meta.mxu else jnp.float32
    dec_t = jnp.transpose(dec, (1, 0, 2)).astype(acc)       # (T, N, Nn)
    S = jax.lax.dot_general(dec_t, A.path_dir.astype(acc),
                            (((2,), (1,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)
    # right-expected nodes contribute (1 - dec); their +1-per-right
    # constant is folded into qthresh = plen_total - plen_right - 1
    return jax.nn.relu(S - A.qthresh[:, None, :])


def _per_tree_scores(X, hit, A: DenseArrays, meta: DenseMeta):
    """(T, N) per-tree outputs: quantized leaf tables dequantized in the
    contraction; linear leaves as leaf-gather + matmul with the NaN
    fallback."""
    leaf_deq = A.leaf_codes.astype(jnp.float32) * A.leaf_scale  # (T, L)
    if not meta.has_linear:
        # hit is an exact one-hot, so the select-free product-sum picks
        # the reached leaf's value exactly (one nonzero term)
        return jnp.sum(hit * leaf_deq[:, None, :], axis=2)
    Xs = jnp.nan_to_num(X)
    isnX = jnp.isnan(X).astype(jnp.float32)
    lin_vals = jax.lax.dot_general(
        A.lin_w, Xs, (((2,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST)                 # (T, L, N)
    lin_nan = jax.lax.dot_general(
        A.lin_mask, isnX, (((2,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST) > 0.5           # (T, L, N)
    lin_out = A.lin_const[:, :, None] + lin_vals
    use_lin = (A.lin_flag[:, :, None] > 0) & ~lin_nan
    vals = jnp.where(use_lin, lin_out, leaf_deq[:, :, None])  # (T, L, N)
    return jnp.sum(hit * jnp.transpose(vals, (0, 2, 1)), axis=2)


def _dense_raw(X, A: DenseArrays, meta: DenseMeta):
    """(N, K) raw scores — the whole ensemble in one loop-free program."""
    dec = _decision_matrix(X, A, meta)
    hit = _hit_matrix(dec, A, meta)
    per_tree = _per_tree_scores(X, hit, A, meta)             # (T, N)
    return jax.lax.dot_general(per_tree.T, A.class_onehot,
                               (((1,), (0,)), ((), ())),
                               precision=jax.lax.Precision.HIGHEST)


@functools.partial(jax.jit, static_argnames=("meta",))
def dense_predict_raw(X, arrays: DenseArrays, meta: DenseMeta):
    """Jitted fused-ensemble raw prediction: (N, num_class) f32."""
    return _dense_raw(X, arrays, meta)


@functools.partial(jax.jit, static_argnames=("meta",))
def dense_predict_leaf(X, arrays: DenseArrays, meta: DenseMeta):
    """Jitted fused pred-leaf: (N, T) int32 leaf index per REAL tree
    (callers slice away shard-padding trees)."""
    dec = _decision_matrix(X, arrays, meta)
    hit = _hit_matrix(dec, arrays, meta)
    return jnp.argmax(hit, axis=2).astype(jnp.int32).T


def stack_dense_arrays(arrays_list):
    """Stack M same-signature models' lowered tables on a NEW leading
    model axis: every (T, ...) table becomes (M, T, ...).  Requires
    identical shapes/dtypes AND identical optional-field presence (both
    guaranteed by an equal ``DenseExecutable.signature``), so the None
    fields collapse consistently and the tree structures match."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *arrays_list)


@functools.partial(jax.jit, static_argnames=("meta",))
def stacked_predict_raw(Xs, stacked: DenseArrays, meta: DenseMeta):
    """(M, N, K) raw scores for M same-signature models in ONE fused
    launch — the zoo's cross-model hot path.  ``Xs`` is (M, N, F): each
    lane carries its own tenant's padded micro-batch.  vmap over the
    model axis turns every contraction of :func:`_dense_raw` into a
    batched contraction of the same per-slice shape, so each lane's
    scores are bitwise identical to a solo :func:`dense_predict_raw`
    call (asserted by the zoo parity tests)."""
    return jax.vmap(lambda x, a: _dense_raw(x, a, meta))(Xs, stacked)


def _stacked_shard_specs(stacked: DenseArrays, axis: str):
    """PartitionSpec tree for tree-axis sharding of STACKED tables: the
    model axis is leading and never sharded; the tree axis (now dim 1)
    splits; the categorical contraction tables stay replicated."""
    from jax.sharding import PartitionSpec as P
    replicated = ("cat_feats", "cat_table")
    vals = {}
    for name in stacked._fields:
        a = getattr(stacked, name)
        if a is None:
            vals[name] = None
        elif name in replicated:
            vals[name] = P()
        else:
            vals[name] = P(None, axis)
    return DenseArrays(**vals)


def make_stacked_sharded_predict(stacked: DenseArrays, meta: DenseMeta,
                                 mesh, axis: str = "trees"):
    """Tree-sharded stacked prediction: per-shard partials over every
    model lane and exactly ONE psum of the (M, N, K) partial scores —
    the ``serve/zoo_stack/score_psum`` collective contract (one psum
    per STACK, not one per tenant; declared in serve/zoo.py)."""
    from jax.sharding import PartitionSpec as P
    from ..parallel.mesh import shard_map_compat
    from ..telemetry.train_record import note_collective

    def body(Xs, A):
        part = jax.vmap(lambda x, a: _dense_raw(x, a, meta))(Xs, A)
        note_collective("serve/zoo_stack/score_psum", "psum", part)
        return jax.lax.psum(part, axis)

    return jax.jit(shard_map_compat(
        body, mesh=mesh,
        in_specs=(P(), _stacked_shard_specs(stacked, axis)),
        out_specs=P()))


def _shard_specs(arrays: DenseArrays, axis: str):
    """PartitionSpec tree for the tree-axis sharding: every (T, ...)
    table splits on ``axis``; the categorical contraction tables are
    replicated (every shard tests its own nodes against the full
    category space)."""
    from jax.sharding import PartitionSpec as P
    replicated = ("cat_feats", "cat_table")
    vals = {}
    for name in arrays._fields:
        a = getattr(arrays, name)
        if a is None:
            vals[name] = None
        elif name in replicated:
            vals[name] = P()
        else:
            vals[name] = P(axis)
    return DenseArrays(**vals)


def make_sharded_predict(arrays: DenseArrays, meta: DenseMeta, mesh,
                         axis: str = "trees"):
    """pjit-sharded fused prediction over the tree axis for ensembles
    too wide for one device: per-shard partial scores and exactly ONE
    psum of the (N, K) partials — the declared
    ``serve/dense_predict/score_psum`` collective contract."""
    from jax.sharding import PartitionSpec as P
    from ..parallel.mesh import shard_map_compat
    from ..telemetry.train_record import note_collective

    def body(X, A):
        part = _dense_raw(X, A, meta)
        note_collective("serve/dense_predict/score_psum", "psum", part)
        return jax.lax.psum(part, axis)

    return jax.jit(shard_map_compat(
        body, mesh=mesh, in_specs=(P(), _shard_specs(arrays, axis)),
        out_specs=P()))
