"""SHAP feature contributions (reference: src/io/tree.cpp PredictContrib —
the TreeSHAP recursive algorithm of Lundberg et al.; exposed via
predict(..., pred_contrib=True), c_api predict type C_API_PREDICT_CONTRIB).

Host-side recursive TreeSHAP over the flat tree arrays.  Prediction-time
only (not on the training hot path), so a clear host implementation is
preferred; a vectorized device path can land with the perf milestones."""

from __future__ import annotations

import numpy as np

from .tree import CAT_MASK, DEFAULT_LEFT_MASK, Tree


def _tree_shap(tree: Tree, x: np.ndarray, phi: np.ndarray) -> None:
    """Accumulate SHAP values of one tree for one row into phi
    (len num_features + 1; last = expected value/bias)."""

    # fractions: list of (node, zero_fraction, one_fraction, feature) path
    def extend(path, zero_frac, one_frac, feat):
        path = path + [[zero_frac, one_frac, feat, 0.0]]
        l = len(path)
        path[l - 1][3] = 1.0 if l == 1 else 0.0
        for i in range(l - 2, -1, -1):
            path[i + 1][3] += one_frac * path[i][3] * (i + 1) / l
            path[i][3] = zero_frac * path[i][3] * (l - 1 - i) / l
        return path

    def unwind(path, i):
        l = len(path)
        one_frac = path[i][1]
        zero_frac = path[i][0]
        n = path[l - 1][3]
        path = [row[:] for row in path]
        for j in range(l - 2, -1, -1):
            if one_frac != 0:
                t = path[j][3]
                path[j][3] = n * l / ((j + 1) * one_frac)
                n = t - path[j][3] * zero_frac * (l - 1 - j) / l
            else:
                path[j][3] = path[j][3] * l / (zero_frac * (l - 1 - j))
        for j in range(i, l - 1):
            path[j][0] = path[j + 1][0]
            path[j][1] = path[j + 1][1]
            path[j][2] = path[j + 1][2]
        path.pop()
        return path

    def unwound_sum(path, i):
        l = len(path)
        one_frac = path[i][1]
        zero_frac = path[i][0]
        total = 0.0
        n = path[l - 1][3]
        for j in range(l - 2, -1, -1):
            if one_frac != 0:
                t = n * l / ((j + 1) * one_frac)
                total += t
                n = path[j][3] - t * zero_frac * (l - 1 - j) / l
            else:
                total += path[j][3] * l / (zero_frac * (l - 1 - j))
        return total

    def node_count(node):
        if node < 0:
            return float(tree.leaf_count[~node])
        return float(tree.internal_count[node])

    def go_left(node, v):
        dt = tree.decision_type[node]
        if dt & CAT_MASK:
            return (not np.isnan(v)) and int(v) == int(tree.threshold[node])
        if np.isnan(v):
            if (dt >> 2) & 3 == 2:
                return bool(dt & DEFAULT_LEFT_MASK)
            v = 0.0
        return v <= tree.threshold[node]

    def recurse(node, path, zero_frac, one_frac, feat):
        path = extend(path, zero_frac, one_frac, feat)
        if node < 0:
            for i in range(1, len(path)):
                w = unwound_sum(path, i)
                phi[path[i][2]] += w * (path[i][1] - path[i][0]) * \
                    tree.leaf_value[~node]
            return
        f = int(tree.split_feature[node])
        hot = int(tree.left_child[node]) if go_left(node, x[f]) else \
            int(tree.right_child[node])
        cold = (int(tree.right_child[node]) if hot == int(tree.left_child[node])
                else int(tree.left_child[node]))
        incoming_zero, incoming_one = 1.0, 1.0
        path_idx = -1
        for i in range(1, len(path)):
            if path[i][2] == f:
                path_idx = i
                break
        if path_idx >= 0:
            incoming_zero = path[path_idx][0]
            incoming_one = path[path_idx][1]
            path = unwind(path, path_idx)
        cnt = node_count(node)
        hot_frac = node_count(hot) / cnt if cnt > 0 else 0.0
        cold_frac = node_count(cold) / cnt if cnt > 0 else 0.0
        recurse(hot, path, hot_frac * incoming_zero, incoming_one, f)
        recurse(cold, path, cold_frac * incoming_zero, 0.0, f)

    if tree.num_leaves <= 1:
        phi[-1] += tree.leaf_value[0]
        return
    # expected value
    phi[-1] += _expected_value(tree, 0)
    recurse(0, [], 1.0, 1.0, -1)


def _expected_value(tree: Tree, node: int) -> float:
    if node < 0:
        return float(tree.leaf_value[~node])
    cnt = float(tree.internal_count[node])
    l, r = int(tree.left_child[node]), int(tree.right_child[node])
    lc = float(tree.leaf_count[~l]) if l < 0 else float(tree.internal_count[l])
    rc = float(tree.leaf_count[~r]) if r < 0 else float(tree.internal_count[r])
    if cnt <= 0:
        return 0.0
    return (lc * _expected_value(tree, l) + rc * _expected_value(tree, r)) / cnt


def predict_contrib(gbdt, Xi: np.ndarray) -> np.ndarray:
    """Per-feature SHAP contributions + bias column
    (reference predictor contrib path; output (N, num_features+1) or
    num_class blocks thereof)."""
    n = Xi.shape[0]
    k = gbdt.num_tree_per_iteration
    nf = gbdt.num_features
    out = np.zeros((n, (nf + 1) * k), np.float64)
    for t, tree in enumerate(gbdt.models):
        cid = t % k
        for i in range(n):
            phi = np.zeros(nf + 1)
            _tree_shap(tree, Xi[i], phi)
            out[i, cid * (nf + 1):(cid + 1) * (nf + 1)] += phi
    return out if k > 1 else out
