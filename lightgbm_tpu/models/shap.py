"""SHAP feature contributions (reference: src/io/tree.cpp PredictContrib —
the TreeSHAP algorithm of Lundberg, Erion & Lee, "Consistent Individualized
Feature Attribution for Tree Ensembles" (Algorithm 2); exposed via
predict(..., pred_contrib=True), c_api predict type C_API_PREDICT_CONTRIB).

Host-side recursive TreeSHAP over the flat tree arrays.  Prediction-time
only (not on the training hot path), so a clear host implementation is
preferred; a vectorized device path can land with the perf milestones.

Path entries are [feature, zero_fraction, one_fraction, pweight]."""

from __future__ import annotations

import numpy as np

from .tree import CAT_MASK, DEFAULT_LEFT_MASK, Tree


def _extend(m, pz, po, pi):
    l = len(m)
    m = [row[:] for row in m]
    m.append([pi, pz, po, 1.0 if l == 0 else 0.0])
    for i in range(l - 1, -1, -1):
        m[i + 1][3] += po * m[i][3] * (i + 1) / (l + 1)
        m[i][3] = pz * m[i][3] * (l - i) / (l + 1)
    return m


def _unwind(m, i):
    l = len(m) - 1
    o, z = m[i][2], m[i][1]
    m = [row[:] for row in m]
    n = m[l][3]
    for j in range(l - 1, -1, -1):
        if o != 0:
            t = m[j][3]
            m[j][3] = n * (l + 1) / ((j + 1) * o)
            n = t - m[j][3] * z * (l - j) / (l + 1)
        else:
            m[j][3] = m[j][3] * (l + 1) / (z * (l - j))
    for j in range(i, l):
        m[j][0], m[j][1], m[j][2] = m[j + 1][0], m[j + 1][1], m[j + 1][2]
    m.pop()
    return m


def _unwound_sum(m, i):
    l = len(m) - 1
    o, z = m[i][2], m[i][1]
    n = m[l][3]
    total = 0.0
    for j in range(l - 1, -1, -1):
        if o != 0:
            t = n * (l + 1) / ((j + 1) * o)
            total += t
            n = m[j][3] - t * z * (l - j) / (l + 1)
        else:
            total += m[j][3] * (l + 1) / (z * (l - j))
    return total


def _tree_shap(tree: Tree, x: np.ndarray, phi: np.ndarray) -> None:
    """Accumulate SHAP values of one tree for one row into phi
    (len num_features + 1; last slot = expected value/bias)."""

    def node_count(node):
        if node < 0:
            return float(tree.leaf_count[~node])
        return float(tree.internal_count[node])

    def go_left(node, v):
        dt = tree.decision_type[node]
        if dt & CAT_MASK:
            return tree.cat_decision(node, v)
        if np.isnan(v):
            if (dt >> 2) & 3 == 2:
                return bool(dt & DEFAULT_LEFT_MASK)
            v = 0.0
        return v <= tree.threshold[node]

    def recurse(node, m, pz, po, pi):
        m = _extend(m, pz, po, pi)
        if node < 0:
            v = tree.leaf_value[~node]
            for i in range(1, len(m)):
                w = _unwound_sum(m, i)
                phi[m[i][0]] += w * (m[i][2] - m[i][1]) * v
            return
        f = int(tree.split_feature[node])
        l, r = int(tree.left_child[node]), int(tree.right_child[node])
        hot, cold = (l, r) if go_left(node, x[f]) else (r, l)
        iz, io = 1.0, 1.0
        k = -1
        for i in range(1, len(m)):
            if m[i][0] == f:
                k = i
                break
        if k >= 0:
            iz, io = m[k][1], m[k][2]
            m = _unwind(m, k)
        cnt = node_count(node)
        hf = node_count(hot) / cnt if cnt > 0 else 0.0
        cf = node_count(cold) / cnt if cnt > 0 else 0.0
        recurse(hot, m, iz * hf, io, f)
        recurse(cold, m, iz * cf, 0.0, f)

    if tree.num_leaves <= 1:
        phi[-1] += tree.leaf_value[0]
        return
    phi[-1] += _expected_value(tree, 0)
    recurse(0, [], 1.0, 1.0, -1)


def _expected_value(tree: Tree, node: int) -> float:
    if node < 0:
        return float(tree.leaf_value[~node])
    cnt = float(tree.internal_count[node])
    l, r = int(tree.left_child[node]), int(tree.right_child[node])
    lc = float(tree.leaf_count[~l]) if l < 0 else float(tree.internal_count[l])
    rc = float(tree.leaf_count[~r]) if r < 0 else float(tree.internal_count[r])
    if cnt <= 0:
        return 0.0
    return (lc * _expected_value(tree, l) + rc * _expected_value(tree, r)) / cnt


def predict_contrib(gbdt, Xi: np.ndarray) -> np.ndarray:
    """Per-feature SHAP contributions + bias column
    (reference predictor contrib path; output (N, num_features+1), or
    num_class stacked blocks for multiclass)."""
    n = Xi.shape[0]
    k = gbdt.num_tree_per_iteration
    nf = gbdt.num_features
    out = np.zeros((n, (nf + 1) * k), np.float64)
    for t, tree in enumerate(gbdt.models):
        cid = t % k
        for i in range(n):
            phi = np.zeros(nf + 1)
            _tree_shap(tree, Xi[i], phi)
            out[i, cid * (nf + 1):(cid + 1) * (nf + 1)] += phi
    return out
