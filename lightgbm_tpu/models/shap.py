"""SHAP feature contributions (reference: src/io/tree.cpp PredictContrib —
the TreeSHAP algorithm of Lundberg, Erion & Lee, "Consistent Individualized
Feature Attribution for Tree Ensembles" (Algorithm 2); exposed via
predict(..., pred_contrib=True), c_api predict type C_API_PREDICT_CONTRIB).

ROW-VECTORIZED TreeSHAP: the recursion's control structure (which nodes are
visited, in which order, and which feature sits at each path level) is
row-independent — only the hot/cold weight assignment differs per row — so
one traversal per tree carries the whole batch: every path-state scalar of
Algorithm 2 (zero fraction, one fraction, pweight) becomes an (N,) vector
and the extend/unwind algebra becomes elementwise numpy.  Rows are chunked
to bound the path-state working set.  (The round-2 implementation recursed
per row in Python: ~rows× slower.)
"""

from __future__ import annotations

import numpy as np

from .tree import CAT_MASK, DEFAULT_LEFT_MASK, Tree

_CHUNK = 4096


def _go_left_vec(tree: Tree, node: int, v: np.ndarray) -> np.ndarray:
    """Vectorized per-row decision at one node (matches tree walks)."""
    dt = tree.decision_type[node]
    nanmask = np.isnan(v)
    if dt & CAT_MASK:
        dleft = bool(dt & DEFAULT_LEFT_MASK)
        iv = np.where(nanmask, -1.0, v)
        ivi = iv.astype(np.int64)
        exact = (ivi >= 0) & (ivi.astype(np.float64) == iv)
        cats = np.asarray(tree.cat_values(node), dtype=np.int64)
        member = np.isin(ivi, cats) & exact
        return np.where(nanmask, dleft, member)
    thr = tree.threshold[node]
    if (dt >> 2) & 3 == 2:  # missing nan
        dleft = bool(dt & DEFAULT_LEFT_MASK)
        return np.where(nanmask, dleft, v <= thr)
    return np.where(nanmask, 0.0 <= thr, v <= thr)


def _tree_shap_batch(tree: Tree, X: np.ndarray, phi: np.ndarray) -> None:
    """Accumulate one tree's SHAP values for a row chunk into phi
    (shape (n, num_features + 1); last slot = expected value)."""
    n = X.shape[0]
    if tree.num_leaves <= 1:
        phi[:, -1] += tree.leaf_value[0]
        return
    phi[:, -1] += _expected_value(tree, 0)

    def node_count(node):
        if node < 0:
            return float(tree.leaf_count[~node])
        return float(tree.internal_count[node])

    ones = np.ones(n)

    # path state: parallel lists of (feature index, z (n,), o (n,), pw (n,))
    def extend(m, pz, po, pi):
        l = len(m)
        m = [(f, z, o, w.copy()) for f, z, o, w in m]
        m.append((pi, pz, po, ones.copy() if l == 0 else np.zeros(n)))
        for i in range(l - 1, -1, -1):
            f_i1, z_i1, o_i1, w_i1 = m[i + 1]
            f_i, z_i, o_i, w_i = m[i]
            w_i1 += po * w_i * (i + 1) / (l + 1)
            m[i] = (f_i, z_i, o_i, pz * w_i * (l - i) / (l + 1))
        return m

    def unwound_sum(m, i):
        l = len(m) - 1
        o, z = m[i][2], m[i][1]
        nn = m[l][3].copy()
        total = np.zeros(n)
        o_nz = o != 0
        o_safe = np.where(o_nz, o, 1.0)
        z_safe = np.where(z != 0, z, 1.0)
        for j in range(l - 1, -1, -1):
            t = nn * (l + 1) / ((j + 1) * o_safe)
            total += np.where(o_nz, t,
                              m[j][3] * (l + 1) / (z_safe * (l - j)))
            nn = np.where(o_nz, m[j][3] - t * z * (l - j) / (l + 1), nn)
        return total

    def unwind(m, i):
        l = len(m) - 1
        o, z = m[i][2], m[i][1]
        nn = m[l][3].copy()
        m = [(f, zz, oo, w.copy()) for f, zz, oo, w in m]
        o_nz = o != 0
        o_safe = np.where(o_nz, o, 1.0)
        z_safe = np.where(z != 0, z, 1.0)
        for j in range(l - 1, -1, -1):
            f_j, z_j, o_j, w_j = m[j]
            t = nn * (l + 1) / ((j + 1) * o_safe)
            nn = np.where(o_nz, w_j - t * z * (l - j) / (l + 1), nn)
            new_w = np.where(o_nz, t, w_j * (l + 1) / (z_safe * (l - j)))
            m[j] = (f_j, z_j, o_j, new_w)
        for j in range(i, l):
            # shift feature/z/o down, KEEP this slot's pweight (Algorithm 2)
            m[j] = (m[j + 1][0], m[j + 1][1], m[j + 1][2], m[j][3])
        m.pop()
        return m

    def recurse(node, m, pz, po, pi):
        m = extend(m, pz, po, pi)
        if node < 0:
            v = float(tree.leaf_value[~node])
            for i in range(1, len(m)):
                w = unwound_sum(m, i)
                phi[:, m[i][0]] += w * (m[i][2] - m[i][1]) * v
            return
        f = int(tree.split_feature[node])
        l_, r_ = int(tree.left_child[node]), int(tree.right_child[node])
        hot_left = _go_left_vec(tree, node, X[:, f]).astype(bool)
        iz, io = ones, ones
        k = -1
        for i in range(1, len(m)):
            if m[i][0] == f:
                k = i
                break
        if k >= 0:
            iz, io = m[k][1], m[k][2]
            m = unwind(m, k)
        cnt = node_count(node)
        lf = node_count(l_) / cnt if cnt > 0 else 0.0
        rf = node_count(r_) / cnt if cnt > 0 else 0.0
        # the zero fraction of a child is its count share either way; the
        # one fraction is io where the child is the row's hot side, else 0
        recurse(l_, m, iz * lf, np.where(hot_left, io, 0.0), f)
        recurse(r_, m, iz * rf, np.where(hot_left, 0.0, io), f)

    recurse(0, [], ones, ones, -1)


def node_expectations(tree: Tree) -> np.ndarray:
    """Leaf-count-weighted expected value of every INTERNAL node, in one
    bottom-up pass (shape (num_leaves - 1,)).  Memoized on the tree; the
    token guards against in-place leaf mutation (refit decay,
    ``LGBM_BoosterSetLeafValue``) so a stale memo can never survive a
    value edit."""
    nl = int(tree.num_leaves)
    if nl <= 1:
        return np.zeros(0, np.float64)
    token = hash((tree.leaf_value.tobytes(), tree.leaf_count.tobytes(),
                  tree.internal_count.tobytes()))
    memo = getattr(tree, "_expected_memo", None)
    if memo is not None and memo[0] == token:
        return memo[1]
    exp = np.zeros(nl - 1, np.float64)
    # iterative post-order: reversed preorder visits children before
    # parents without assuming any index ordering (and without Python
    # recursion limits on deep trees)
    order = []
    stack = [0]
    while stack:
        node = stack.pop()
        order.append(node)
        for ch in (int(tree.left_child[node]), int(tree.right_child[node])):
            if ch >= 0:
                stack.append(ch)

    def val(ch: int) -> float:
        return float(tree.leaf_value[~ch]) if ch < 0 else exp[ch]

    def cnt(ch: int) -> float:
        return float(tree.leaf_count[~ch]) if ch < 0 \
            else float(tree.internal_count[ch])

    for node in reversed(order):
        l, r = int(tree.left_child[node]), int(tree.right_child[node])
        c = float(tree.internal_count[node])
        exp[node] = ((cnt(l) * val(l) + cnt(r) * val(r)) / c) if c > 0 \
            else 0.0
    tree._expected_memo = (token, exp)
    return exp


def _expected_value(tree: Tree, node: int) -> float:
    if node < 0:
        return float(tree.leaf_value[~node])
    return float(node_expectations(tree)[node])


def trees_window(gbdt, start_iteration: int = 0,
                 num_iteration=None):
    """The (t0, t1) tree-index window of an iteration range — the same
    slice ``_tree_batch`` serves, so contrib/leaf/raw predictions all
    window identically."""
    k = gbdt.num_tree_per_iteration
    t0 = start_iteration * k
    t1 = len(gbdt.models) if num_iteration is None else min(
        len(gbdt.models), (start_iteration + num_iteration) * k)
    return t0, max(t0, t1)


def predict_contrib(gbdt, Xi: np.ndarray, start_iteration: int = 0,
                    num_iteration=None) -> np.ndarray:
    """Per-feature SHAP contributions + bias column
    (reference predictor contrib path; output (N, num_features+1), or
    num_class stacked blocks for multiclass).  Respects the
    start_iteration/num_iteration window exactly like raw prediction
    (the reference windows its contrib path too)."""
    k = gbdt.num_tree_per_iteration
    t0, t1 = trees_window(gbdt, start_iteration, num_iteration)
    models = gbdt.models[t0:t1]
    if any(t.is_linear for t in models):
        from ..utils.log import log_warning
        log_warning("pred_contrib on linear trees attributes each leaf's "
                    "PLAIN output (per-leaf linear terms are not decomposed)")
    n = Xi.shape[0]
    nf = gbdt.num_features
    out = np.zeros((n, (nf + 1) * k), np.float64)
    for lo in range(0, n, _CHUNK):
        hi = min(lo + _CHUNK, n)
        chunk = Xi[lo:hi]
        for t, tree in enumerate(models, start=t0):
            cid = t % k
            _tree_shap_batch(tree, chunk,
                             out[lo:hi, cid * (nf + 1):(cid + 1) * (nf + 1)])
    return out
