"""Flat-array decision tree model + jitted prediction.

TPU-native re-implementation of the reference tree model
(reference: include/LightGBM/tree.h:25 ``Tree`` — flat arrays
``split_feature_``, ``threshold_``, ``left_child_``, ``right_child_``,
``leaf_value_``; child pointers use ``~leaf_index`` for leaves, and
prediction is a branchy walk, tree.h:133 ``Tree::Predict``).

Here every tree of a model shares the same max size (num_leaves from config),
so a whole boosted ensemble stacks into (T, ...) arrays and prediction is one
jitted vectorized tree walk over (rows x trees) — no per-node branching, the
walk advances all rows one level per iteration of a ``lax.while_loop``.

decision_type bit layout follows the reference (tree.h decision_type):
  bit0: categorical, bit1: default_left, bits 2-3: missing type
  (0 none, 1 zero, 2 nan).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Tree", "TreeBatch", "predict_binned", "predict_raw",
           "SHAPE_BUCKETS", "bucket_rows", "pad_rows",
           "ensemble_serve_fields", "predict_raw_ensemble"]

CAT_MASK = 1
DEFAULT_LEFT_MASK = 2
MISSING_ZERO = 1 << 2
MISSING_NAN = 2 << 2

# Row-count ladder for compiled prediction: requests pad up to the next
# bucket so arbitrary batch sizes hit a handful of compiled programs
# instead of one XLA trace per novel shape.  Beyond the ladder, sizes
# round up to the next MULTIPLE of the top bucket — waste stays under
# one bucket (vs up to 2x for power-of-two rounding) while the distinct
# compiled-shape count stays bounded.
SHAPE_BUCKETS = (1, 8, 64, 512, 4096)


def bucket_rows(n: int, ladder=SHAPE_BUCKETS) -> int:
    """Smallest ladder bucket holding ``n`` rows (multiple of the top
    bucket above the ladder's end)."""
    if n <= 0:
        return ladder[0]
    for b in ladder:
        if n <= b:
            return b
    top = ladder[-1]
    return (n + top - 1) // top * top


def pad_rows(X: np.ndarray, ladder=SHAPE_BUCKETS) -> np.ndarray:
    """Zero-pad ``X`` (N, F) up to its row bucket.  Padding rows cannot
    perturb real rows: every prediction path reduces per row."""
    nb = bucket_rows(X.shape[0], ladder)
    if nb == X.shape[0]:
        return X
    return np.concatenate(
        [X, np.zeros((nb - X.shape[0], X.shape[1]), X.dtype)], axis=0)


@dataclasses.dataclass
class Tree:
    """Host-side view of one trained tree (numpy arrays).

    Internal node arrays have length num_leaves-1 (only the first
    ``num_leaves_actual - 1`` entries are meaningful); leaf arrays have length
    num_leaves.  Child pointers >= 0 index internal nodes; negative pointers
    are leaves encoded as ``~leaf_index`` (reference tree.h convention).
    """

    num_leaves: int                    # actual leaves
    split_feature: np.ndarray          # (L-1,) int32, inner feature index
    threshold_bin: np.ndarray          # (L-1,) int32
    nan_bin: np.ndarray                # (L-1,) int32 bin holding NaN (-1: none)
    threshold: np.ndarray              # (L-1,) float64 raw-value threshold
    decision_type: np.ndarray          # (L-1,) uint8
    left_child: np.ndarray             # (L-1,) int32
    right_child: np.ndarray            # (L-1,) int32
    split_gain: np.ndarray             # (L-1,) float32
    internal_value: np.ndarray         # (L-1,) float64
    internal_weight: np.ndarray        # (L-1,) float64
    internal_count: np.ndarray         # (L-1,) int64
    leaf_value: np.ndarray             # (L,) float64
    leaf_weight: np.ndarray            # (L,) float64
    leaf_count: np.ndarray             # (L,) int64
    shrinkage: float = 1.0
    # Categorical set splits (reference tree.h:85 SplitCategorical):
    # cat nodes store threshold = RANK into cat_boundaries; the flat
    # cat_threshold uint32 words are a bitset over RAW category values
    # (cat_boundaries[rank]..cat_boundaries[rank+1] words per node).
    cat_boundaries: Optional[np.ndarray] = None   # (num_cat+1,) int32
    cat_threshold: Optional[np.ndarray] = None    # flat uint32 words
    # runtime-only binned membership for training-time walks (not
    # serialized; rebuilt from the bin mappers on load): (L-1, B) bool
    cat_member_bins: Optional[np.ndarray] = None
    # Linear-tree fields (reference tree.h is_linear_/leaf_const_/
    # leaf_coeff_/leaf_features_): per-leaf linear models on branch
    # features; leaf_features holds REAL column indices; prediction is
    # leaf_const + sum(coef * x), falling back to leaf_value when any
    # leaf feature is NaN.
    is_linear: bool = False
    leaf_const: Optional[np.ndarray] = None       # (L,) float64
    leaf_coeff: Optional[List[List[float]]] = None
    leaf_features: Optional[List[List[int]]] = None        # REAL indices
    leaf_features_inner: Optional[List[List[int]]] = None  # inner indices

    @property
    def max_leaves(self) -> int:
        return len(self.leaf_value)

    def num_cat_nodes(self) -> int:
        return 0 if self.cat_boundaries is None else \
            len(self.cat_boundaries) - 1

    def cat_values(self, node: int) -> List[int]:
        """Raw category values in the node's LEFT set."""
        if self.cat_boundaries is None:
            return [int(self.threshold[node])]
        rank = int(self.threshold[node])
        lo = int(self.cat_boundaries[rank])
        hi = int(self.cat_boundaries[rank + 1])
        return [w * 32 + b for w in range(hi - lo) for b in range(32)
                if int(self.cat_threshold[lo + w]) & (1 << b)]

    def cat_decision(self, node: int, value: float) -> bool:
        """Set-membership decision for a categorical node on a RAW value
        (reference tree.h FindInBitset + Tree::CategoricalDecision).
        True -> go left."""
        if np.isnan(value):
            return bool(self.decision_type[node] & DEFAULT_LEFT_MASK)
        iv = int(value)
        if iv < 0 or iv != value:
            return False
        if self.cat_boundaries is None:
            return iv == int(self.threshold[node])  # legacy single-category
        rank = int(self.threshold[node])
        lo = int(self.cat_boundaries[rank])
        hi = int(self.cat_boundaries[rank + 1])
        word = iv // 32
        if word >= hi - lo:
            return False
        return bool((int(self.cat_threshold[lo + word]) >> (iv % 32)) & 1)

    def num_internal(self) -> int:
        return max(self.num_leaves - 1, 0)

    def shrink(self, rate: float) -> None:
        """In-place shrinkage (reference tree.h Shrinkage)."""
        self.leaf_value = self.leaf_value * rate
        self.internal_value = self.internal_value * rate
        if self.is_linear:
            self.leaf_const = self.leaf_const * rate
            self.leaf_coeff = [[c * rate for c in cs]
                               for cs in self.leaf_coeff]
        self.shrinkage *= rate

    def add_bias(self, val: float) -> None:
        self.leaf_value = self.leaf_value + val
        self.internal_value = self.internal_value + val
        if self.is_linear:
            self.leaf_const = self.leaf_const + val

    def linear_predict_row(self, leaf: int, row: np.ndarray) -> float:
        """Host reference linear-leaf evaluation (tree.cpp
        PredictionFunLinear): NaN in any leaf feature -> plain output."""
        feats = (self.leaf_features_inner if self.leaf_features_inner
                 is not None else self.leaf_features)[leaf]
        total = float(self.leaf_const[leaf])
        for f, c in zip(feats, self.leaf_coeff[leaf]):
            v = row[f]
            if np.isnan(v):
                return float(self.leaf_value[leaf])
            total += c * v
        return total

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Raw-feature prediction, host reference implementation
        (tree.h:133 Tree::Predict).  Used for testing; batch prediction goes
        through TreeBatch."""
        out = np.empty(len(X), dtype=np.float64)
        for i, row in enumerate(X):
            node = 0
            if self.num_leaves <= 1:
                out[i] = self.leaf_value[0]
                continue
            while node >= 0:
                f = self.split_feature[node]
                v = row[f]
                dt = self.decision_type[node]
                if dt & CAT_MASK:
                    left = self.cat_decision(node, v)
                else:
                    if np.isnan(v):
                        if (dt >> 2) == 2:  # missing nan
                            left = bool(dt & DEFAULT_LEFT_MASK)
                        else:
                            v = 0.0
                            left = v <= self.threshold[node]
                    else:
                        left = v <= self.threshold[node]
                node = self.left_child[node] if left else self.right_child[node]
            out[i] = (self.linear_predict_row(~node, row) if self.is_linear
                      else self.leaf_value[~node])
        return out


class TreeBatch:
    """Stacked device arrays for T trees of identical max size; the ensemble
    prediction structure (replaces the reference's per-tree virtual calls in
    gbdt_prediction.cpp with one vectorized walk)."""

    FIELDS = ("split_feature", "threshold_bin", "threshold", "decision_type",
              "left_child", "right_child", "leaf_value")

    def __init__(self, trees: List[Tree]):
        if not trees:
            raise ValueError("no trees")
        self.num_trees = len(trees)
        self.max_leaves = max(max(t.max_leaves, t.num_leaves) for t in trees)
        ml = self.max_leaves

        def stack(attr, size, dtype=None, fill=0):
            arrs = []
            for t in trees:
                a = np.asarray(getattr(t, attr))
                if len(a) < size:
                    a = np.concatenate([a, np.full(size - len(a), fill,
                                                   a.dtype if a.size else
                                                   np.float64)])
                arrs.append(a[:size])
            out = np.stack(arrs)
            return jnp.asarray(out if dtype is None else out.astype(dtype))

        self.split_feature = stack("split_feature", ml - 1, np.int32)
        self.threshold_bin = stack("threshold_bin", ml - 1, np.int32)
        self.nan_bin = stack("nan_bin", ml - 1, np.int32, fill=-1)
        self.threshold = stack("threshold", ml - 1, np.float32)
        self.decision_type = stack("decision_type", ml - 1, np.uint8)
        self.left_child = stack("left_child", ml - 1, np.int32)
        self.right_child = stack("right_child", ml - 1, np.int32)
        self.leaf_value = stack("leaf_value", ml, np.float32)
        self.num_leaves = jnp.asarray(np.array([t.num_leaves for t in trees],
                                               dtype=np.int32))

        # categorical-set arrays: binned membership (training walks) and
        # raw-value bitset words (inference walks); width 1 when no tree
        # has categorical nodes so the jitted walks stay uniform
        bm = max([1] + [t.cat_member_bins.shape[1] for t in trees
                        if t.cat_member_bins is not None])
        member = np.zeros((len(trees), ml - 1, bm), bool)
        for ti, t in enumerate(trees):
            if t.cat_member_bins is not None:
                m = t.cat_member_bins
                member[ti, :m.shape[0], :m.shape[1]] = m
        self.cat_member = jnp.asarray(member)

        wmax = 1
        for t in trees:
            if t.cat_boundaries is not None:
                for r in range(len(t.cat_boundaries) - 1):
                    wmax = max(wmax, int(t.cat_boundaries[r + 1]) -
                               int(t.cat_boundaries[r]))
            else:  # legacy single-category nodes: threshold IS the category
                for i in range(t.num_leaves - 1):
                    if t.decision_type[i] & CAT_MASK:
                        wmax = max(wmax, int(t.threshold[i]) // 32 + 1)
        words = np.zeros((len(trees), ml - 1, wmax), np.uint32)
        for ti, t in enumerate(trees):
            for i in range(t.num_leaves - 1):
                if not (t.decision_type[i] & CAT_MASK):
                    continue
                if t.cat_boundaries is not None:
                    rank = int(t.threshold[i])
                    lo = int(t.cat_boundaries[rank])
                    hi = int(t.cat_boundaries[rank + 1])
                    words[ti, i, :hi - lo] = t.cat_threshold[lo:hi]
                else:
                    v = int(t.threshold[i])
                    words[ti, i, v // 32] |= np.uint32(1 << (v % 32))
        self.cat_words = jnp.asarray(words)

        # linear-tree leaf models (tree.h leaf_coeff_/leaf_const_)
        self.has_linear = any(t.is_linear for t in trees)
        lk = 1
        if self.has_linear:
            for t in trees:
                if t.is_linear:
                    lk = max(lk, max((len(f) for f in
                                      (t.leaf_features_inner or
                                       t.leaf_features)), default=1))
        lconst = np.zeros((len(trees), ml), np.float32)
        lcoef = np.zeros((len(trees), ml, lk), np.float32)
        lfeat = np.zeros((len(trees), ml, lk), np.int32)
        lfmask = np.zeros((len(trees), ml, lk), np.float32)
        lflag = np.zeros((len(trees),), np.float32)
        for ti, t in enumerate(trees):
            if not t.is_linear:
                continue
            lflag[ti] = 1.0
            lconst[ti, :len(t.leaf_const)] = t.leaf_const
            feats = t.leaf_features_inner if t.leaf_features_inner \
                is not None else t.leaf_features
            for leaf, (fs, cs) in enumerate(zip(feats, t.leaf_coeff)):
                lfeat[ti, leaf, :len(fs)] = fs
                lfmask[ti, leaf, :len(fs)] = 1.0
                lcoef[ti, leaf, :len(cs)] = cs
        self.leaf_const = jnp.asarray(lconst)
        self.leaf_coef = jnp.asarray(lcoef)
        self.leaf_feat = jnp.asarray(lfeat)
        self.leaf_fmask = jnp.asarray(lfmask)
        self.linear_flag = jnp.asarray(lflag)

        # Dense-walk path matrices (the MXU inference formulation,
        # _walk_raw_dense): path_dir[n, l] = +1 when node n sits on leaf
        # l's root path expecting a LEFT decision, -1 expecting RIGHT;
        # a row's leaf is the unique l whose satisfied-condition count
        # S = dec @ path_dir + plen_right equals the path length.  Leaf
        # slots beyond num_leaves get an unreachable path length.
        self.has_cat = any(bool(np.bitwise_and(
            np.asarray(t.decision_type[:max(t.num_leaves - 1, 0)],
                       np.uint8), CAT_MASK).any()) for t in trees)
        pd = np.zeros((len(trees), max(ml - 1, 1), ml), np.int8)
        pr = np.zeros((len(trees), ml), np.float32)
        pt = np.full((len(trees), ml), 1e9, np.float32)
        for ti, t in enumerate(trees):
            if t.num_leaves <= 1:
                pt[ti, 0] = 0.0
                continue
            lc = np.asarray(t.left_child)
            rc = np.asarray(t.right_child)
            work = [(0, [])]
            while work:
                node, path = work.pop()
                for child, d in ((int(lc[node]), 1), (int(rc[node]), -1)):
                    p2 = path + [(node, d)]
                    if child < 0:
                        leaf = ~child
                        if leaf < ml:
                            for nn_, dd in p2:
                                pd[ti, nn_, leaf] = dd
                            pr[ti, leaf] = float(
                                sum(1 for _, dd in p2 if dd < 0))
                            pt[ti, leaf] = float(len(p2))
                    else:
                        work.append((child, p2))
        self.path_dir = jnp.asarray(pd)
        self.plen_right = jnp.asarray(pr)
        self.plen_total = jnp.asarray(pt)

    def as_tuple(self):
        return (self.split_feature, self.threshold_bin, self.nan_bin,
                self.cat_member, self.decision_type, self.left_child,
                self.right_child, self.leaf_value, self.num_leaves)


@functools.partial(jax.jit, static_argnames=("freq", "mode"))
def predict_raw_early_stop(fields, X, margin, stopped0, *, freq: int,
                           mode: str):
    """Raw prediction with per-row margin-based early exit across trees
    (reference src/boosting/prediction_early_stop.cpp:54 binary — stop when
    2|raw| > margin — and :25 multiclass — stop when top-2 margin exceeds
    the threshold; checked every ``freq`` trees).  Stopped rows freeze
    their partial sum (the reference returns the truncated score); the
    tree loop exits entirely once every row has stopped.

    fields: per-class tuple trees-first arrays as in predict_raw; for
    multiclass a list of per-class field tuples sharing the walk.
    stopped0: (N,) bool initial stop mask — shape-bucket padding rows
    ride in pre-stopped so they can never hold the tree loop open past
    the point where every real row has exited.
    """
    per_class = fields
    k = len(per_class)
    t_total = per_class[0][0].shape[0]
    n = X.shape[0]

    def tree_at(c, t):
        return tuple(a[t] for a in per_class[c])

    def body(state):
        t, out, stopped = state
        deltas = []
        for c in range(k):
            val, _ = _walk_raw(X, *tree_at(c, t))
            deltas.append(jnp.where(stopped, 0.0, val))
        out = out + jnp.stack(deltas, axis=1)
        check = ((t + 1) % freq == 0)
        if mode == "binary":
            stop_now = 2.0 * jnp.abs(out[:, 0]) > margin
        else:
            top2 = jax.lax.top_k(out, 2)[0]
            stop_now = (top2[:, 0] - top2[:, 1]) > margin
        stopped = stopped | (check & stop_now)
        return t + 1, out, stopped

    def cond(state):
        t, _, stopped = state
        return (t < t_total) & jnp.logical_not(jnp.all(stopped))

    _, out, _ = jax.lax.while_loop(
        cond, body, (jnp.asarray(0, jnp.int32), jnp.zeros((n, k), jnp.float32),
                     stopped0))
    return out


def _walk_impl(fetch_bin, n, split_feature, threshold_bin, nan_bin,
               cat_member, decision_type, left_child, right_child,
               leaf_value, num_leaves):
    """Shared body of the binned tree walkers: ``fetch_bin(nd, f)`` returns
    each row's FEATURE-space bin code for node feature ``f`` — plain
    column take for feature-space matrices, bundle-column decode under
    EFB.  One implementation so walk semantics (NaN routing, categorical
    membership, default-left) can never diverge between the two."""
    node = jnp.where(num_leaves <= 1, -1, 0) * jnp.ones((n,), jnp.int32)
    bm = cat_member.shape[1]

    def cond(state):
        node, _ = state
        return jnp.any(node >= 0)

    def body(state):
        node, out = state
        active = node >= 0
        nd = jnp.maximum(node, 0)
        f = split_feature[nd]
        thr = threshold_bin[nd]
        dt = decision_type[nd]
        b = fetch_bin(nd, f)
        is_cat = (dt & CAT_MASK) != 0
        dleft = (dt & DEFAULT_LEFT_MASK) != 0
        # the NaN bin is the feature's last bin, above any real threshold,
        # so "missing right" is automatic; "missing left" overrides via
        # nan_bin
        is_nanbin = b == nan_bin[nd]
        cat_go = cat_member.reshape(-1)[nd * bm + jnp.minimum(b, bm - 1)]
        go_left = jnp.where(is_cat, cat_go,
                            jnp.where(is_nanbin, dleft, b <= thr))
        nxt = jnp.where(go_left, left_child[nd], right_child[nd])
        new_node = jnp.where(active, nxt, node)
        out = jnp.where(active & (new_node < 0),
                        leaf_value[jnp.maximum(~new_node, 0)], out)
        return new_node, out

    out0 = jnp.where(num_leaves <= 1,
                     jnp.broadcast_to(leaf_value[0], (n,)),
                     jnp.zeros((n,), jnp.float32))
    node, out = jax.lax.while_loop(cond, body, (node, out0))
    return out


def _device_path_matrices(left_child, right_child, num_leaves, L):
    """Path matrices built ON DEVICE with one pass over the node arrays
    (valid because the growers allocate child node ids after their
    parents).  Rebuilt per call — ~L tiny scatter steps, negligible next
    to the walk."""
    nn = left_child.shape[0]

    def build(i, carry):
        pathmat, leaf_dir, plen_r, plen_t = carry
        active = i < num_leaves - 1
        base = pathmat[i]
        for child, d in ((left_child[i], 1), (right_child[i], -1)):
            vec = base.at[i].set(jnp.int8(d))
            isleaf = child < 0
            nidx = jnp.where(active & jnp.logical_not(isleaf), child, nn)
            pathmat = pathmat.at[nidx].set(vec, mode="drop")
            lidx = jnp.where(active & isleaf, ~child, L)
            leaf_dir = leaf_dir.at[:, lidx].set(vec, mode="drop")
            plen_r = plen_r.at[lidx].set(
                jnp.sum((vec == -1).astype(jnp.float32)), mode="drop")
            plen_t = plen_t.at[lidx].set(
                jnp.sum((vec != 0).astype(jnp.float32)), mode="drop")
        return pathmat, leaf_dir, plen_r, plen_t

    pathmat0 = jnp.zeros((nn, nn), jnp.int8)
    leaf_dir0 = jnp.zeros((nn, L), jnp.int8)
    plen_r0 = jnp.zeros((L,), jnp.float32)
    plen_t0 = jnp.full((L,), 1e9, jnp.float32)
    _, leaf_dir, plen_r, plen_t = jax.lax.fori_loop(
        0, nn, build, (pathmat0, leaf_dir0, plen_r0, plen_t0))
    return leaf_dir, plen_r, plen_t


@jax.jit
def _walk_binned_dense(bins, split_feature, threshold_bin, nan_bin,
                       decision_type, left_child, right_child, leaf_value,
                       num_leaves):
    """Dense matmul walk on BINNED data for one (categorical-free,
    non-EFB) tree whose arrays live on device (the deferred grown trees
    driving valid-set score updates).  The path matrices are built
    on-device with a single pass over the nodes — valid because the
    growers allocate child node ids AFTER their parents — then the leaf
    resolution is the same satisfied-condition count as
    :func:`_walk_raw_dense`.  Replaces a depth-deep gather walk."""
    P = _onehot_feature_lookup(bins.astype(jnp.float32), split_feature)
    return _binned_dense_from_codes(P, threshold_bin, nan_bin,
                                    decision_type, left_child,
                                    right_child, leaf_value, num_leaves)


def _binned_dense_from_codes(P, threshold_bin, nan_bin, decision_type,
                             left_child, right_child, leaf_value,
                             num_leaves):
    """Shared tail of the dense binned walks: decision + path-count leaf
    resolution from per-node FEATURE-space bin codes ``P`` (N, Nn)."""
    n = P.shape[0]
    L = leaf_value.shape[0]
    leaf_dir, plen_r, plen_t = _device_path_matrices(
        left_child, right_child, num_leaves, L)
    dleft = (decision_type & DEFAULT_LEFT_MASK) != 0
    dec = jnp.where(P == nan_bin[None, :].astype(jnp.float32),
                    dleft[None, :],
                    P <= threshold_bin[None, :]).astype(jnp.bfloat16)
    out, _ = _dense_leaf_out(dec, leaf_dir, plen_r, plen_t, leaf_value,
                             want_leaf=False)
    return jnp.where(num_leaves <= 1,
                     jnp.broadcast_to(leaf_value[0], (n,)), out)


@jax.jit
def _walk_binned_dense_efb(bins, efb_walk, split_feature, threshold_bin,
                           nan_bin, decision_type, left_child, right_child,
                           leaf_value, num_leaves):
    """Dense binned walk over an EFB-BUNDLED matrix: each node's bundle
    column rides the one-hot lookup, then the SAME decode closure the
    growers use (efb.make_bundle_decode, broadcast over (N, Nn)) maps
    bundle codes to feature space — no per-row gathers."""
    from ..efb import make_bundle_decode
    _, f_bundle, *_rest = efb_walk
    Pb = _onehot_feature_lookup(bins.astype(jnp.float32),
                                f_bundle[split_feature])
    Pf = make_bundle_decode(efb_walk)(
        Pb.astype(jnp.int32), split_feature[None, :]).astype(jnp.float32)
    return _binned_dense_from_codes(Pf, threshold_bin, nan_bin,
                                    decision_type, left_child,
                                    right_child, leaf_value, num_leaves)


@jax.jit
def _walk_binned(bins, split_feature, threshold_bin, nan_bin, cat_member,
                 decision_type, left_child, right_child, leaf_value,
                 num_leaves):
    """Vectorized tree walk on BINNED data for one tree.

    bins: (N, F) int; tree arrays as in TreeBatch rows; cat_member is the
    (L-1, B) categorical LEFT-set membership over bins.
    Returns (N,) float32 leaf values.
    """
    def fetch_bin(nd, f):
        return jnp.take_along_axis(bins, f[:, None],
                                   axis=1)[:, 0].astype(jnp.int32)

    return _walk_impl(fetch_bin, bins.shape[0], split_feature,
                      threshold_bin, nan_bin, cat_member, decision_type,
                      left_child, right_child, leaf_value, num_leaves)


@jax.jit
def _walk_binned_efb(bins, efb_walk, split_feature, threshold_bin, nan_bin,
                     cat_member, decision_type, left_child, right_child,
                     leaf_value, num_leaves):
    """_walk_binned over an EFB-bundled matrix: ``bins`` is (N, G)
    BUNDLE-space codes; each node's feature code is decoded from its
    bundle column (efb.make_bundle_decode — the same decode the growers
    use) before the threshold test.  ``efb_walk`` is the standard
    efb_arrays tuple (exp_map may be None; the decode ignores it)."""
    from ..efb import make_bundle_decode
    decode = make_bundle_decode(efb_walk)
    f_bundle = efb_walk[1]

    def fetch_bin(nd, f):
        v = jnp.take_along_axis(bins, f_bundle[f][:, None],
                                axis=1)[:, 0].astype(jnp.int32)
        return decode(v, f)

    return _walk_impl(fetch_bin, bins.shape[0], split_feature,
                      threshold_bin, nan_bin, cat_member, decision_type,
                      left_child, right_child, leaf_value, num_leaves)


def predict_binned(batch: TreeBatch, bins: jnp.ndarray,
                   num_iteration: Optional[int] = None) -> jnp.ndarray:
    """Sum of per-tree leaf outputs on binned rows (training-time scoring)."""
    fields = batch.as_tuple()
    t = batch.num_trees if num_iteration is None else min(num_iteration, batch.num_trees)

    def body(carry, tree_fields):
        return carry + _walk_binned(bins, *tree_fields), None

    sliced = tuple(a[:t] for a in fields)
    out, _ = jax.lax.scan(body, jnp.zeros((bins.shape[0],), jnp.float32), sliced)
    return out


@jax.jit
def _walk_raw(X, split_feature, threshold, cat_words, decision_type,
              left_child, right_child, leaf_value, num_leaves):
    """Vectorized walk on RAW float features for one tree (inference path).

    cat_words: (L-1, W) uint32 bitset over raw category values per node
    (reference tree.h FindInBitset)."""
    n = X.shape[0]
    node = jnp.where(num_leaves <= 1, -1, 0) * jnp.ones((n,), jnp.int32)
    w = cat_words.shape[1]

    def cond(state):
        return jnp.any(state[0] >= 0)

    def body(state):
        node, out, leaf = state
        active = node >= 0
        nd = jnp.maximum(node, 0)
        f = split_feature[nd]
        thr = threshold[nd]
        dt = decision_type[nd]
        v = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
        is_cat = (dt & CAT_MASK) != 0
        dleft = (dt & DEFAULT_LEFT_MASK) != 0
        miss_nan = (dt & (3 << 2)) == MISSING_NAN
        is_nan = jnp.isnan(v)
        v_num = jnp.where(is_nan & ~miss_nan, 0.0, v)
        go_left_num = jnp.where(is_nan & miss_nan, dleft, v_num <= thr)
        # categorical set membership on the raw value; NaN categoricals
        # follow default_left ("is bin 0 / the most frequent category in
        # the left set", recorded by the grower)
        vi = jnp.where(is_nan, -1.0, v).astype(jnp.int32)
        in_range = (vi >= 0) & (vi < w * 32) & \
            (vi.astype(jnp.float32) == jnp.where(is_nan, -1.0, v))
        word = cat_words.reshape(-1)[nd * w + jnp.clip(vi, 0, w * 32 - 1) // 32]
        bit = (word >> (jnp.clip(vi, 0) % 32).astype(jnp.uint32)) & 1
        go_left_cat = jnp.where(is_nan, dleft, in_range & (bit > 0))
        go_left = jnp.where(is_cat, go_left_cat, go_left_num)
        nxt = jnp.where(go_left, left_child[nd], right_child[nd])
        new_node = jnp.where(active, nxt, node)
        out = jnp.where(active & (new_node < 0),
                        leaf_value[jnp.maximum(~new_node, 0)], out)
        leaf = jnp.where(active & (new_node < 0),
                         jnp.maximum(~new_node, 0), leaf)
        return new_node, out, leaf

    out0 = jnp.where(num_leaves <= 1,
                     jnp.broadcast_to(leaf_value[0], (n,)),
                     jnp.zeros((n,), jnp.float32))
    leaf0 = jnp.zeros((n,), jnp.int32)
    node, out, leaf = jax.lax.while_loop(cond, body, (node, out0, leaf0))
    return out, leaf


def _onehot_feature_lookup(V, split_feature):
    """(N, Nn) per-node feature values via a one-hot contraction.
    Precision.HIGHEST: bf16-rounded values could flip near-threshold
    decisions (and uint16 bin codes exceed bf16's exact range)."""
    f_count = V.shape[1]
    onehot = (jnp.arange(f_count, dtype=jnp.int32)[:, None] ==
              split_feature[None, :]).astype(jnp.float32)
    return jax.lax.dot_general(V, onehot, (((1,), (0,)), ((), ())),
                               precision=jax.lax.Precision.HIGHEST)


def _dense_leaf_out(dec, path_dir, plen_right, plen_total, leaf_value,
                    want_leaf=True):
    """Leaf resolution by satisfied-path-condition count.  0/1 decisions
    and +-1 directions are bf16-exact and the matmul accumulates in f32,
    so the equality test is exact."""
    S = jax.lax.dot_general(dec, path_dir.astype(jnp.bfloat16),
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32) + \
        plen_right[None, :]
    hit = S == plen_total[None, :]
    out = jnp.sum(jnp.where(hit, leaf_value[None, :], 0.0), axis=1)
    if not want_leaf:
        return out, None
    return out, jnp.argmax(hit, axis=1).astype(jnp.int32)


def _walk_raw_dense(X, split_feature, threshold, decision_type, path_dir,
                    plen_right, plen_total, leaf_value, want_leaf=True):
    """Matmul-form tree walk for one (categorical-free) tree: the
    feature lookup is a one-hot contraction on the MXU (exact f32 via
    Precision.HIGHEST — a bf16-rounded value could flip a near-threshold
    decision) and the leaf resolution is a satisfied-condition count
    against the host-built path matrices.  Replaces the depth-deep
    gather loop of :func:`_walk_raw`, which is ~1000x slower on TPU
    (per-row gathers are the slow primitive; matmuls are free)."""
    # NaNs poison a one-hot contraction (0 * NaN = NaN), so the values
    # ride sanitized and the NaN indicator takes its own exact 0/1 matmul
    P = _onehot_feature_lookup(jnp.nan_to_num(X), split_feature)
    isn = _onehot_feature_lookup(jnp.isnan(X).astype(jnp.float32),
                                 split_feature) > 0.5
    dt = decision_type
    dleft = (dt & DEFAULT_LEFT_MASK) != 0
    miss_nan = (dt & (3 << 2)) == MISSING_NAN
    # P is already 0.0 at NaN cells (nan_to_num upstream), which is the
    # non-miss_nan fallback value; miss_nan nodes take default_left.
    # 0/1 decisions and +-1 path directions are bf16-exact; the S matmul
    # accumulates in f32, so the equality test stays exact
    dec = jnp.where(isn & miss_nan[None, :], dleft[None, :],
                    P <= threshold[None, :]).astype(jnp.bfloat16)
    # S counts satisfied path conditions: 0/1 x (+-1) products are
    # bf16-exact and the f32 accumulation of <=Nn terms is exact, so the
    # equality test below is safe at default matmul precision
    S = jax.lax.dot_general(dec, path_dir.astype(jnp.bfloat16),
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32) + \
        plen_right[None, :]
    hit = S == plen_total[None, :]                              # (N, L)
    out = jnp.sum(jnp.where(hit, leaf_value[None, :], 0.0), axis=1)
    if not want_leaf:
        return out, None
    leaf = jnp.argmax(hit, axis=1).astype(jnp.int32)
    return out, leaf


def _linear_leaf_eval(X, val, leaf, lin_fields):
    """Linear-leaf evaluation with the NaN fallback (tree.cpp
    PredictionFunLinear) — shared by the dense and sequential walks."""
    lconst, lcoef, lfeat, lfmask, lflag = lin_fields
    rf = lfeat[leaf]
    rm = lfmask[leaf]
    vals = jnp.take_along_axis(X, rf, axis=1)
    nan_row = jnp.any(jnp.isnan(vals) & (rm > 0), axis=1)
    vals = jnp.where(rm > 0, jnp.nan_to_num(vals), 0.0)
    lin = lconst[leaf] + jnp.sum(lcoef[leaf] * vals, axis=1)
    use_lin = (lflag > 0) & jnp.logical_not(nan_row)
    return jnp.where(use_lin, lin, val)


@functools.partial(jax.jit, static_argnames=("has_linear",))
def _predict_dense_scan(X, fields, lin_fields=None, has_linear=False):
    """Jitted tree-scan over the dense walk (one compiled program per
    (shape, tree-count) instead of per-op eager dispatch)."""
    if not has_linear:
        def body(carry, tf):
            return carry + _walk_raw_dense(X, *tf, want_leaf=False)[0], None
        out, _ = jax.lax.scan(body, jnp.zeros((X.shape[0],), jnp.float32),
                              fields)
        return out

    def body_lin(carry, tf):
        tree_fields, lf = tf
        val, leaf = _walk_raw_dense(X, *tree_fields)
        return carry + _linear_leaf_eval(X, val, leaf, lf), None

    out, _ = jax.lax.scan(body_lin, jnp.zeros((X.shape[0],), jnp.float32),
                          (fields, lin_fields))
    return out


@functools.partial(jax.jit, static_argnames=("has_linear",))
def _predict_seq_scan(X, fields, lin_fields=None, has_linear=False):
    """Jitted tree-scan over the sequential raw walk (categorical
    ensembles) — the seq counterpart of :func:`_predict_dense_scan`, so
    the categorical inference path also compiles once per shape."""
    if not has_linear:
        def body(carry, tf):
            return carry + _walk_raw(X, *tf)[0], None
        out, _ = jax.lax.scan(body, jnp.zeros((X.shape[0],), jnp.float32),
                              fields)
        return out

    def body_lin(carry, tf):
        tree_fields, lf = tf
        val, leaf = _walk_raw(X, *tree_fields)
        return carry + _linear_leaf_eval(X, val, leaf, lf), None

    out, _ = jax.lax.scan(body_lin, jnp.zeros((X.shape[0],), jnp.float32),
                          (fields, lin_fields))
    return out


def ensemble_serve_fields(batch: TreeBatch, start: int = 0,
                          end: Optional[int] = None):
    """Pure-array view of one ensemble for :func:`predict_raw_ensemble`:
    ``(kind, fields, lin_fields)`` where ``kind`` is a static dispatch tag
    and the arrays are plain device-residable jnp arrays.  Because the
    jitted entry takes the arrays as ARGUMENTS, XLA's compile cache keys
    on shapes/dtypes only — two models with the same shape signature
    (tree count, leaves, features) share every compiled program."""
    t1 = batch.num_trees if end is None else min(end, batch.num_trees)
    t0 = min(start, t1)
    if batch.max_leaves <= 1:
        return "const", (batch.leaf_value[t0:t1],), None
    lin = None
    if batch.has_linear:
        lin = tuple(a[t0:t1] for a in
                    (batch.leaf_const, batch.leaf_coef, batch.leaf_feat,
                     batch.leaf_fmask, batch.linear_flag))
    if not batch.has_cat:
        fields = tuple(a[t0:t1] for a in
                       (batch.split_feature, batch.threshold,
                        batch.decision_type, batch.path_dir,
                        batch.plen_right, batch.plen_total,
                        batch.leaf_value))
        return ("dense_lin" if lin is not None else "dense"), fields, lin
    fields = tuple(a[t0:t1] for a in
                   (batch.split_feature, batch.threshold, batch.cat_words,
                    batch.decision_type, batch.left_child,
                    batch.right_child, batch.leaf_value, batch.num_leaves))
    return ("seq_lin" if lin is not None else "seq"), fields, lin


@functools.partial(jax.jit, static_argnames=("kinds",))
def predict_raw_ensemble(X, per_class, kinds):
    """Pure jitted ensemble prediction entry for the serving layer:
    ``per_class`` is a tuple over model classes of ``(fields,
    lin_fields)`` from :func:`ensemble_serve_fields`, ``kinds`` the
    matching static tags.  Returns (N, k) raw scores.  Module-level and
    argument-driven so every model with the same shape signature reuses
    one compiled program per row bucket."""
    cols = []
    for (fields, lin), kind in zip(per_class, kinds):
        if kind == "const":
            cols.append(jnp.broadcast_to(
                jnp.sum(fields[0]).astype(jnp.float32), (X.shape[0],)))
        elif kind == "dense":
            cols.append(_predict_dense_scan(X, fields))
        elif kind == "dense_lin":
            cols.append(_predict_dense_scan(X, fields, lin, has_linear=True))
        elif kind == "seq":
            cols.append(_predict_seq_scan(X, fields))
        elif kind == "seq_lin":
            cols.append(_predict_seq_scan(X, fields, lin, has_linear=True))
        else:
            raise ValueError(f"unknown ensemble kind: {kind}")
    return jnp.stack(cols, axis=1)


def predict_raw(batch: TreeBatch, X: jnp.ndarray,
                start_iteration: int = 0,
                num_iteration: Optional[int] = None) -> jnp.ndarray:
    """Ensemble raw-score prediction on raw features
    (reference gbdt_prediction.cpp:PredictRaw; linear-leaf evaluation per
    tree.cpp PredictionFunLinear with NaN fallback).  Categorical-free
    ensembles take the dense MXU walk; categorical trees keep the
    sequential walk (their bitset membership is a per-row gather)."""
    t_end = batch.num_trees if num_iteration is None else min(
        start_iteration + num_iteration, batch.num_trees)
    if batch.max_leaves <= 1:
        # all-stump ensemble: the prediction is the constants' sum (the
        # walks' node arrays are empty at ml == 1)
        const = jnp.sum(batch.leaf_value[start_iteration:t_end, 0])
        return jnp.full((X.shape[0],), const, jnp.float32)
    dense = not batch.has_cat
    if dense:
        fields = (batch.split_feature, batch.threshold,
                  batch.decision_type, batch.path_dir, batch.plen_right,
                  batch.plen_total, batch.leaf_value)
        sliced = tuple(a[start_iteration:t_end] for a in fields)
        if not batch.has_linear:
            return _predict_dense_scan(X, sliced)
        lin_sliced = tuple(
            a[start_iteration:t_end] for a in
            (batch.leaf_const, batch.leaf_coef, batch.leaf_feat,
             batch.leaf_fmask, batch.linear_flag))
        return _predict_dense_scan(X, sliced, lin_sliced, has_linear=True)
    fields = (batch.split_feature, batch.threshold, batch.cat_words,
              batch.decision_type, batch.left_child,
              batch.right_child, batch.leaf_value, batch.num_leaves)
    sliced = tuple(a[start_iteration:t_end] for a in fields)
    if not batch.has_linear:
        return _predict_seq_scan(X, sliced)
    lin_fields = tuple(a[start_iteration:t_end] for a in
                       (batch.leaf_const, batch.leaf_coef, batch.leaf_feat,
                        batch.leaf_fmask, batch.linear_flag))
    return _predict_seq_scan(X, sliced, lin_fields, has_linear=True)
