"""Dataset: binned feature matrix + metadata, resident on device.

TPU-native re-implementation of the reference data layer
(reference: include/LightGBM/dataset.h:282 ``Dataset``, dataset.h:41
``Metadata``, src/io/dataset_loader.cpp ``DatasetLoader``).

Key departures from the reference, driven by TPU/XLA:

* The reference stores per-feature-group ``Bin`` objects with dense/sparse/
  4-bit/multi-value layouts chosen per feature (src/io/dense_bin.hpp,
  sparse_bin.hpp).  On TPU the working set is ONE dense uint8/uint16 array of
  shape (rows, features) — static shape, MXU/VPU friendly, shardable over a
  mesh along the row axis (data parallel) or feature axis (feature parallel).
* Bin construction runs host-side on a row sample (numpy), mirroring
  ``DatasetLoader::ConstructBinMappersFromTextData``; the binned matrix is
  then device_put once.
* Validation datasets are aligned to the training dataset's bin mappers
  (reference dataset.h:304 alignment check / create_valid).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .binning import (BinMapper, bin_matrix, find_bin,
                      find_bin_from_summary)
from .config import Config
from .utils.log import log_info

__all__ = ["Dataset", "Metadata", "DatasetCorruptError"]


class DatasetCorruptError(ValueError):
    """A binary dataset file could not be read or failed validation
    (truncated/garbage payload, missing fields, or a fingerprint that
    does not match the stored binned matrix) — the Dataset analog of
    ``ModelCorruptError``."""

    def __init__(self, source: str, detail: str) -> None:
        super().__init__(f"{source}: {detail}")
        self.source = source
        self.detail = detail

_ArrayLike = Union[np.ndarray, Sequence[float], "Any"]


class Metadata:
    """Labels / weights / query boundaries / init scores
    (reference dataset.h:41, src/io/metadata.cpp)."""

    def __init__(self) -> None:
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.group: Optional[np.ndarray] = None            # sizes per query
        self.query_boundaries: Optional[np.ndarray] = None  # cumulative offsets
        self.init_score: Optional[np.ndarray] = None
        self.position: Optional[np.ndarray] = None

    def set_label(self, label: _ArrayLike) -> None:
        self.label = np.asarray(label, dtype=np.float32).ravel()

    def set_weight(self, weight: Optional[_ArrayLike]) -> None:
        if weight is None:
            self.weight = None
        else:
            w = np.asarray(weight, dtype=np.float32).ravel()
            if (w < 0).any():
                raise ValueError("weights must be non-negative")
            self.weight = w

    def set_group(self, group: Optional[_ArrayLike]) -> None:
        if group is None:
            self.group = None
            self.query_boundaries = None
            return
        g = np.asarray(group, dtype=np.int64).ravel()
        self.group = g
        self.query_boundaries = np.concatenate([[0], np.cumsum(g)]).astype(np.int64)

    def set_init_score(self, init_score: Optional[_ArrayLike]) -> None:
        if init_score is None:
            self.init_score = None
        else:
            self.init_score = np.asarray(init_score, dtype=np.float64).ravel()

    @property
    def num_queries(self) -> int:
        return 0 if self.group is None else len(self.group)


class Dataset:
    """User-facing dataset, lazily constructed (reference python-package
    basic.py ``Dataset`` + C++ ``Dataset``/``DatasetLoader``).

    Parameters mirror the reference Python API.  ``data`` may be a numpy
    array, a pandas DataFrame, or a path to a CSV/TSV/LibSVM file.
    """

    def __init__(self, data: Any, label: Optional[_ArrayLike] = None,
                 reference: Optional["Dataset"] = None,
                 weight: Optional[_ArrayLike] = None,
                 group: Optional[_ArrayLike] = None,
                 init_score: Optional[_ArrayLike] = None,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List[Union[int, str]]] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True) -> None:
        self.data = data
        self.params = dict(params or {})
        self.reference = reference
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.free_raw_data = free_raw_data
        self.metadata = Metadata()
        self._label_arg = label
        self._weight_arg = weight
        self._group_arg = group
        self._init_score_arg = init_score
        # populated by construct()
        self.constructed = False
        self.bin_mappers: List[BinMapper] = []
        self.X_binned: Optional[np.ndarray] = None   # (N, F) uint8/uint16, host copy
        self.num_bins_per_feature: Optional[np.ndarray] = None
        self.used_feature_map: Optional[np.ndarray] = None  # inner -> real index
        self.num_total_features = 0
        self.efb = None  # BundleInfo when EFB-bundled (efb.py)
        self._device_cache: Dict[Any, Any] = {}

    # -- construction --------------------------------------------------------
    def construct(self, config: Optional[Config] = None) -> "Dataset":
        if self.constructed:
            return self
        cfg = config or Config(self.params)
        raw, feature_names = self._materialize_raw()
        sparse = hasattr(raw, "tocsc")
        if sparse:
            raw = raw.tocsc()
        n, f = raw.shape
        self.num_total_features = f
        self.feature_names_ = feature_names
        self.efb = None

        cat_indices = self._resolve_categoricals(feature_names)

        if cfg.linear_tree and sparse:
            # linear leaves fit on RAW dense feature values
            # (linear_tree_learner.cpp reads raw columns); the reference
            # rejects this combination too
            raise ValueError("linear_tree requires dense input (the "
                             "per-leaf linear fits read raw feature "
                             "values); densify or disable linear_tree")

        # pre-partitioned multi-process ingest (reference pre_partition +
        # distributed bin finding, dataset_loader.cpp:1040-1130): each
        # process holds only ITS row range; bin-finding samples are
        # allgathered so every rank derives identical mappers, and
        # metadata is replicated (small next to the sharded features)
        from . import distributed as _dist
        dist_rows = (bool(cfg.pre_partition) and _dist.is_initialized()
                     and _dist.process_count() > 1
                     and self.reference is None)
        self.distributed_rows = dist_rows
        if dist_rows:
            if self._group_arg is not None:
                raise ValueError(
                    "pre_partition cannot shard query/group data (queries "
                    "must not straddle partitions); drop pre_partition or "
                    "the group argument")
        rng = np.random.RandomState(cfg.data_random_seed)
        if dist_rows:
            sample_cnt = min(n, max(1, int(cfg.bin_construct_sample_cnt) //
                                    _dist.process_count()))
        else:
            sample_cnt = min(n, int(cfg.bin_construct_sample_cnt))
        # one code path with the streamed sketch pass (the bit-identity
        # root); the shared generator keeps the sparse path's remaining
        # stream identical
        from .ingest.sketch import sample_row_indices
        sample_idx = sample_row_indices(n, sample_cnt,
                                        cfg.data_random_seed, rng=rng)
        dist_sketch = None
        dist_sparse_cols = None
        n_total = n
        if dist_rows:
            n_total = int(_dist.allgather_host(
                np.asarray([n], np.int32)).sum())
            if sparse:
                # per-column sampled NONZEROS gathered flat (one
                # variable-length collective), plus global nnz counts so
                # every rank derives identical zero fractions — the
                # sparse analog of the dense sample allgather below; the
                # raw shard itself never leaves this process
                vals_list, lens_loc, nnz_loc = [], [], []
                for j in range(f):
                    lo, hi = raw.indptr[j], raw.indptr[j + 1]
                    vals = np.asarray(raw.data[lo:hi], np.float64)
                    if len(vals) > sample_cnt:
                        vals = vals[np.sort(rng.choice(len(vals),
                                                       sample_cnt, False))]
                    vals_list.append(vals)
                    lens_loc.append(len(vals))
                    nnz_loc.append(hi - lo)
                flat_all = _dist.allgather_host(
                    np.concatenate(vals_list) if vals_list
                    else np.zeros(0, np.float64))
                lens_all = _dist.allgather_host(
                    np.asarray(lens_loc, np.int32)).reshape(-1, f)
                nnz_all = _dist.allgather_host(
                    np.asarray(nnz_loc, np.int32)).reshape(-1, f)
                nnz_glob = nnz_all.sum(axis=0)
                rank_off = np.concatenate(
                    [[0], np.cumsum(lens_all.sum(axis=1))])
                col_off = np.cumsum(
                    np.concatenate([np.zeros((len(lens_all), 1), np.int64),
                                    lens_all], axis=1), axis=1)
                dist_sparse_cols = []
                for j in range(f):
                    parts = [flat_all[rank_off[r] + col_off[r, j]:
                                      rank_off[r] + col_off[r, j + 1]]
                             for r in range(len(lens_all))]
                    vals = np.concatenate(parts) if parts else \
                        np.zeros(0, np.float64)
                    zfrac = 1.0 - nnz_glob[j] / max(n_total, 1)
                    nz = int(round(len(vals) * zfrac /
                                   max(1e-9, 1 - zfrac))) \
                        if zfrac < 1.0 else sample_cnt
                    nz = min(nz, sample_cnt * max(len(lens_all), 1))
                    dist_sparse_cols.append(
                        np.concatenate([vals, np.zeros(nz)]))
            else:
                # dense: per-rank per-feature SUMMARIES allgathered and
                # merged in rank order — the streamed sketch's wire form
                # (ingest/sketch.py), one code path with single-process
                # and streamed binning (both finalize through
                # binning.find_bin_from_summary), and never more bytes
                # than the raw sample-row gather it replaces
                from .ingest.sketch import BinningSketch
                dist_sketch = BinningSketch(f, cat_indices)
                dist_sketch.update(np.asarray(raw[sample_idx], np.float64))
                dist_sketch.allgather_merge()

        if self.reference is not None:
            ref = self.reference
            if not ref.constructed:
                ref.construct(config)
            # align bins with the reference dataset (dataset.h:304)
            self.bin_mappers = ref.bin_mappers
            self.used_feature_map = ref.used_feature_map
            self.num_bins_per_feature = ref.num_bins_per_feature
            self.efb = ref.efb
        else:
            # sample rows for bin finding (dataset_loader.cpp:902
            # SampleTextDataFromFile — here rows are already in memory)
            forced_bins = self._load_forced_bins(cfg)
            self.bin_mappers = []
            for j in range(f):
                if dist_sketch is not None:
                    # distributed dense: finalize the merged summaries
                    # through the shared sketch machinery
                    summary = dist_sketch.summary(j)
                    filt = max(1, int(cfg.min_data_in_leaf *
                                      summary.total_cnt /
                                      max(1, n_total))) \
                        if cfg.feature_pre_filter else 0
                    self.bin_mappers.append(find_bin_from_summary(
                        summary, cfg.max_bin,
                        min_data_in_bin=cfg.min_data_in_bin,
                        use_missing=cfg.use_missing,
                        zero_as_missing=cfg.zero_as_missing,
                        forced_bounds=forced_bins.get(j),
                        pre_filter_cnt=filt))
                    continue
                if dist_sparse_cols is not None:
                    col_sample = dist_sparse_cols[j]
                elif sparse:
                    # sparse column: sampled nonzeros + proportional
                    # implied zeros (no densification)
                    lo, hi = raw.indptr[j], raw.indptr[j + 1]
                    vals = np.asarray(raw.data[lo:hi], np.float64)
                    if len(vals) > sample_cnt:
                        vals = vals[np.sort(rng.choice(len(vals),
                                                       sample_cnt, False))]
                    zfrac = 1.0 - (hi - lo) / max(n, 1)
                    nz = int(round(len(vals) * zfrac / max(1e-9, 1 - zfrac))) \
                        if zfrac < 1.0 else sample_cnt
                    nz = min(nz, sample_cnt)
                    col_sample = np.concatenate([vals, np.zeros(nz)])
                else:
                    col_sample = raw[sample_idx, j]
                # the reference's pre-filter threshold scales
                # min_data_in_leaf by the sample fraction
                # (dataset_loader.cpp filter_cnt)
                # 0 disables the pre-filter (feature_pre_filter=false
                # keeps even never-splittable features, like the reference)
                filt = max(1, int(cfg.min_data_in_leaf * len(col_sample) /
                                  max(1, n_total))) \
                    if cfg.feature_pre_filter else 0
                self.bin_mappers.append(find_bin(
                    col_sample, max_bin=cfg.max_bin,
                    min_data_in_bin=cfg.min_data_in_bin,
                    total_cnt=len(col_sample),
                    is_categorical=(j in cat_indices),
                    use_missing=cfg.use_missing,
                    zero_as_missing=cfg.zero_as_missing,
                    forced_bounds=forced_bins.get(j),
                    pre_filter_cnt=filt))
            self._finalize_used_features(f)

        used = self.used_feature_map
        mappers = [self.bin_mappers[j] for j in used]

        if self.efb is None:
            self.efb = self._maybe_bundle(cfg, raw, sparse, used, mappers,
                                          sample_idx, n)
        if self.efb is not None:
            from .efb import bundle_binned_matrix, bundle_sparse_csc
            if sparse:
                self.X_binned = bundle_sparse_csc(raw[:, used].tocsc(),
                                                  mappers, self.efb)
            else:
                self.X_binned = bundle_binned_matrix(
                    bin_matrix(raw[:, used], mappers), self.efb)
            log_info(f"EFB: bundled {len(used)} features into "
                     f"{self.efb.n_bundles} device columns "
                     f"({self.efb.bundle_bins} bundle bins)")
        elif sparse:
            # no beneficial bundling: densify the BINNED codes (uint8),
            # never the raw float64 values
            cols = []
            csc = raw[:, used].tocsc()
            for jj, m in enumerate(mappers):
                col = np.full(n, m.default_bin, np.uint8)
                lo, hi = csc.indptr[jj], csc.indptr[jj + 1]
                col[csc.indices[lo:hi]] = m.value_to_bin(
                    np.asarray(csc.data[lo:hi], np.float64)).astype(np.uint8)
                cols.append(col)
            self.X_binned = np.stack(cols, axis=1)
        else:
            self.X_binned = bin_matrix(raw[:, used], mappers)
        if cfg.linear_tree and not sparse:
            # linear trees fit on RAW feature values (reference
            # linear_tree_learner.cpp raw_index); keep the used columns
            # (under pre_partition this is the LOCAL row shard — padded
            # in _finalize_distributed_rows and assembled row-sharded on
            # the mesh by the GBDT driver)
            self.raw_used = raw[:, used].astype(np.float32)
        else:
            self.raw_used = None
        if self.distributed_rows:
            n = self._finalize_distributed_rows(n)
        self._set_metadata(n)
        self.constructed = True
        if self.free_raw_data:
            self.data = None
        return self

    @staticmethod
    def _load_forced_bins(cfg) -> Dict[int, list]:
        """forcedbins_filename JSON -> {feature: [upper bounds]}
        (dataset_loader.cpp:641 GetForcedBins) — shared with the
        streamed construct (ingest/stream.py)."""
        forced_bins: Dict[int, list] = {}
        if getattr(cfg, "forcedbins_filename", ""):
            import json as _json
            with open(cfg.forcedbins_filename) as fh:
                for ent in _json.load(fh):
                    forced_bins[int(ent["feature"])] = \
                        list(ent["bin_upper_bound"])
        return forced_bins

    def _finalize_used_features(self, f: int) -> None:
        """Trivial-feature pre-filter (config.h feature_pre_filter) ->
        used_feature_map / num_bins_per_feature — shared with the
        streamed construct so the filter policy cannot drift between
        the in-core and streamed mapper sets."""
        used = [j for j, m in enumerate(self.bin_mappers)
                if not m.is_trivial]
        if len(used) == 0:
            raise ValueError("cannot construct Dataset: all features are "
                             "trivial (constant); nothing to split on")
        if len(used) < f:
            log_info(f"Dataset: filtered {f - len(used)} trivial features, "
                     f"{len(used)} remain")
        self.used_feature_map = np.asarray(used, dtype=np.int32)
        self.num_bins_per_feature = np.asarray(
            [self.bin_mappers[j].num_bin for j in used], dtype=np.int32)

    def _finalize_distributed_rows(self, n_local: int) -> int:
        """Pad the LOCAL binned shard to the mesh row quantum and
        replicate the (small) metadata across processes; the feature
        matrix itself never leaves this process (the point of
        pre_partition — Experiments.rst:228's 176 GB -> per-machine
        shards)."""
        from . import distributed as _dist
        import jax
        from .utils.backend import default_backend
        rb = 4096 if default_backend() == "tpu" else 1
        quantum = max(1, jax.local_device_count()) * rb
        lens = _dist.allgather_host(np.asarray([n_local], np.int64)).ravel()
        pad_to = int(-(-int(lens.max()) // quantum) * quantum)
        pad = pad_to - n_local
        if pad:
            self.X_binned = np.pad(self.X_binned, ((0, pad), (0, 0)))
            if self.raw_used is not None:
                self.raw_used = np.pad(self.raw_used, ((0, pad), (0, 0)))

        def padded(a, fill=0.0):
            a = np.asarray(a, np.float64).ravel()
            if len(a) != n_local:
                raise ValueError(f"metadata length {len(a)} != local rows "
                                 f"{n_local} under pre_partition")
            return np.concatenate([a, np.full(pad, fill, np.float64)])

        lab = np.zeros(n_local) if self._label_arg is None \
            else np.asarray(self._label_arg, np.float64).ravel()
        w = np.ones(n_local) if self._weight_arg is None \
            else np.asarray(self._weight_arg, np.float64).ravel()
        self._label_arg = _dist.allgather_host(padded(lab))
        # padded rows carry zero weight so objectives/metrics ignore them
        self._weight_arg = _dist.allgather_host(padded(w))
        if self._init_score_arg is not None:
            self._init_score_arg = _dist.allgather_host(
                padded(self._init_score_arg))
        self._dist_valid_local = np.concatenate(
            [np.ones(n_local, np.float32), np.zeros(pad, np.float32)])
        self._dist_pad_to = pad_to
        self._dist_global_rows = pad_to * _dist.process_count()
        log_info(f"pre_partition: rank {_dist.process_index()} holds "
                 f"{n_local} rows (padded {pad_to}); global "
                 f"{self._dist_global_rows} across "
                 f"{_dist.process_count()} processes")
        return self._dist_global_rows

    def _maybe_bundle(self, cfg, raw, sparse, used, mappers, sample_idx, n):
        """Decide + build EFB bundles (dataset.cpp:239 FastFeatureBundling);
        serial-learner training only, and only when it shrinks the device
        matrix."""
        from .efb import build_bundle_info, find_bundles
        if (not cfg.enable_bundle or cfg.tree_learner != "serial"
                or cfg.linear_tree or len(used) < 3):
            return None
        # non-default masks over the sampled rows; categorical features
        # stay singleton (their set-membership decisions read raw bins)
        nondefault = []
        cand = []
        from .efb import MAX_BUNDLE_BINS
        for jj, m in enumerate(mappers):
            if m.is_categorical:
                continue
            if m.num_bin > MAX_BUNDLE_BINS:
                # a >256-bin feature (max_bin > 256) cannot ride a uint8
                # bundle column; it stays a standalone uint16 column
                continue
            j = int(used[jj])
            if sparse:
                lo, hi = raw.indptr[j], raw.indptr[j + 1]
                mask = np.zeros(len(sample_idx), bool)
                mask[np.searchsorted(sample_idx,
                                     np.intersect1d(raw.indices[lo:hi],
                                                    sample_idx))] = True
            else:
                col = mappers[jj].value_to_bin(raw[sample_idx, j])
                mask = col != mappers[jj].default_bin
            # only near-sparse features are worth bundling
            if mask.mean() <= 0.5:
                nondefault.append(mask)
                cand.append(jj)
        if len(cand) < 2:
            return None
        cand_mappers = [mappers[jj] for jj in cand]
        bundles_local = find_bundles(cand_mappers, nondefault, n,
                                     len(sample_idx))
        bundles = [[cand[i] for i in b] for b in bundles_local]
        in_bundle = {f for b in bundles for f in b}
        for jj in range(len(mappers)):
            if jj not in in_bundle:
                bundles.append([jj])
        if len(bundles) > 0.9 * len(mappers):
            return None  # not worth the indirection
        max_b = max(m.num_bin for m in mappers)
        return build_bundle_info(mappers, bundles, max_b)

    def _materialize_raw(self):
        data = self.data
        if data is None:
            raise ValueError("Dataset raw data was freed; pass free_raw_data=False "
                             "to reuse it")
        if isinstance(data, str):
            from .io_utils import load_data_file
            raw, names, label = load_data_file(data, self.params)
            if label is not None and self._label_arg is None:
                self._label_arg = label
            return raw, names
        try:  # pandas without a hard dependency
            import pandas as pd  # type: ignore
            if isinstance(data, pd.DataFrame):
                names = [str(c) for c in data.columns]
                raw = data.to_numpy(dtype=np.float64, na_value=np.nan)
                return raw, names
        except ImportError:
            pass
        if hasattr(data, "tocsc"):  # scipy sparse: handled without
            raw = data                # densification in construct()
            if self.feature_name != "auto" and self.feature_name is not None:
                return raw, list(self.feature_name)
            return raw, [f"Column_{i}" for i in range(raw.shape[1])]
        raw = np.asarray(data, dtype=np.float64)
        if raw.ndim == 1:
            raw = raw.reshape(-1, 1)
        if self.feature_name != "auto" and self.feature_name is not None:
            names = list(self.feature_name)
        else:
            names = [f"Column_{i}" for i in range(raw.shape[1])]
        return raw, names

    def _resolve_categoricals(self, feature_names: List[str]) -> set:
        cats = self.categorical_feature
        if cats == "auto" or cats is None:
            from_params = self.params.get("categorical_feature", "")
            if isinstance(from_params, str) and from_params:
                cats = from_params.split(",")
            else:
                return set()
        out = set()
        for c in cats:
            if isinstance(c, str) and c in feature_names:
                out.add(feature_names.index(c))
            elif isinstance(c, str) and c.strip().isdigit():
                out.add(int(c))
            elif isinstance(c, (int, np.integer)):
                out.add(int(c))
        return out

    def _set_metadata(self, n: int) -> None:
        if self._label_arg is not None:
            self.metadata.set_label(self._label_arg)
            if len(self.metadata.label) != n:
                raise ValueError(f"label length {len(self.metadata.label)} != rows {n}")
        self.metadata.set_weight(self._weight_arg)
        self.metadata.set_group(self._group_arg)
        self.metadata.set_init_score(self._init_score_arg)

    # -- reference-API surface ----------------------------------------------
    def create_valid(self, data: Any, label: Optional[_ArrayLike] = None,
                     weight: Optional[_ArrayLike] = None,
                     group: Optional[_ArrayLike] = None,
                     init_score: Optional[_ArrayLike] = None,
                     params: Optional[Dict[str, Any]] = None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score,
                       params=params or self.params)

    def set_label(self, label: _ArrayLike) -> "Dataset":
        self._label_arg = label
        if self.constructed:
            self.metadata.set_label(label)
        return self

    def set_weight(self, weight: Optional[_ArrayLike]) -> "Dataset":
        self._weight_arg = weight
        if self.constructed:
            self.metadata.set_weight(weight)
        return self

    def set_group(self, group: Optional[_ArrayLike]) -> "Dataset":
        self._group_arg = group
        if self.constructed:
            self.metadata.set_group(group)
        return self

    def set_init_score(self, init_score: Optional[_ArrayLike]) -> "Dataset":
        self._init_score_arg = init_score
        if self.constructed:
            self.metadata.set_init_score(init_score)
        return self

    def get_label(self) -> Optional[np.ndarray]:
        return self.metadata.label if self.constructed else (
            None if self._label_arg is None else np.asarray(self._label_arg))

    def get_weight(self) -> Optional[np.ndarray]:
        return self.metadata.weight

    def get_group(self) -> Optional[np.ndarray]:
        return self.metadata.group

    def get_init_score(self) -> Optional[np.ndarray]:
        return self.metadata.init_score

    def num_data(self) -> int:
        self._check_constructed()
        if getattr(self, "distributed_rows", False):
            return int(self._dist_global_rows)
        return int(self.X_binned.shape[0])

    def num_feature(self) -> int:
        self._check_constructed()
        # inner FEATURE count — under EFB the device matrix is narrower
        # (bundle columns), but the feature surface stays per-feature
        return int(len(self.used_feature_map))

    def fingerprint(self) -> Dict[str, Any]:
        """Identity of the BINNED training matrix for checkpoint/resume
        validation: a resume against different rows or different binning
        cannot be bit-identical, so the bundle records (shape, a sha256
        over every used mapper's bin edges / category maps, a crc32 over
        the binned codes) and restore fails loudly on mismatch.

        Cached: the crc over X_binned is the only non-trivial cost and
        the binned matrix is immutable once constructed.  Under
        pre-partitioned multi-process ingest this fingerprints the LOCAL
        shard — resume must keep the same process count and sharding.
        """
        self._check_constructed()
        fp = self._device_cache.get("_fingerprint")
        if fp is not None:
            return fp
        import zlib
        crc = zlib.crc32(np.ascontiguousarray(self.X_binned).tobytes())
        fp = self._fingerprint_with_crc(crc)
        self._device_cache["_fingerprint"] = fp
        return fp

    def _fingerprint_with_crc(self, crc: int) -> Dict[str, Any]:
        """Fingerprint dict from a precomputed binned-codes crc — the
        mapper sha and field layout single-sourced here so the streamed
        subclass (which streams the crc over chunks) cannot drift from
        the in-core fingerprint it must equal bit for bit."""
        import hashlib
        h = hashlib.sha256()
        for j in self.used_feature_map:
            m = self.bin_mappers[j]
            h.update(f"{int(j)}:{m.num_bin}:{int(m.is_categorical)}:"
                     f"{m.missing_type.value}".encode())
            if m.bin_upper_bound is not None:
                h.update(np.ascontiguousarray(
                    m.bin_upper_bound, np.float64).tobytes())
            if m.cat_to_bin:
                h.update(repr(sorted(m.cat_to_bin.items())).encode())
        return {
            "num_data": int(self.num_data()),
            "binned_shape": [int(v) for v in self.X_binned.shape],
            "num_features": int(self.num_feature()),
            "binning_sha256": h.hexdigest(),
            "data_crc32": int(crc),
        }

    @property
    def feature_names(self) -> List[str]:
        self._check_constructed()
        return [self.feature_names_[j] for j in self.used_feature_map]

    def subset(self, used_indices: Sequence[int],
               params: Optional[Dict[str, Any]] = None) -> "Dataset":
        """Row subset sharing this dataset's bin mappers (reference
        Dataset::CopySubrow, used by cv/bagging)."""
        self._check_constructed()
        idx = np.asarray(used_indices, dtype=np.int64)
        sub = copy.copy(self)
        sub._device_cache = {}
        sub.X_binned = self.X_binned[idx]
        sub.metadata = Metadata()
        if self.metadata.label is not None:
            sub.metadata.set_label(self.metadata.label[idx])
        if self.metadata.weight is not None:
            sub.metadata.set_weight(self.metadata.weight[idx])
        if self.metadata.init_score is not None:
            sub.metadata.set_init_score(self.metadata.init_score[idx])
        if self.metadata.group is not None:
            # remap query boundaries: the subset must consist of whole
            # queries (reference Metadata partition re-indexing,
            # src/io/metadata.cpp:37)
            qb = self.metadata.query_boundaries
            qid = np.searchsorted(qb, idx, side="right") - 1
            sel_q, counts = np.unique(qid, return_counts=True)
            if not np.array_equal(counts, self.metadata.group[sel_q]):
                raise ValueError("subset() of ranking data must select whole "
                                 "queries (use query-aware folds)")
            if not np.all(np.diff(idx) > 0):
                raise ValueError("subset() of ranking data requires sorted, "
                                 "query-contiguous indices")
            sub.metadata.set_group(self.metadata.group[sel_q])
        return sub

    # -- binary serialization (reference Dataset::SaveBinaryFile /
    #    DatasetLoader::LoadFromBinFile) -------------------------------------
    def save_binary(self, filename: str) -> "Dataset":
        """Crash-safe binary save: the payload lands via
        ``io_utils.atomic_write_bytes`` (temp + fsync + rename — the same
        path Booster.save_model takes), and carries the dataset
        :meth:`fingerprint` so ``load_binary`` can validate the stored
        binned matrix against its recorded identity."""
        self._check_constructed()
        import pickle
        from .io_utils import atomic_write_bytes
        payload = {
            "format": "lightgbm_tpu.dataset.v1",
            "X_binned": np.asarray(self.X_binned),
            "bin_mappers": self.bin_mappers,
            "used_feature_map": self.used_feature_map,
            "num_bins_per_feature": self.num_bins_per_feature,
            "feature_names": self.feature_names_,
            "efb": self.efb,
            "label": self.metadata.label,
            "weight": self.metadata.weight,
            "group": self.metadata.group,
            "init_score": self.metadata.init_score,
            "fingerprint": self.fingerprint(),
        }
        atomic_write_bytes(filename, pickle.dumps(payload, protocol=4))
        return self

    _BINARY_REQUIRED = ("X_binned", "bin_mappers", "used_feature_map",
                        "num_bins_per_feature", "feature_names", "label",
                        "weight", "group", "init_score")

    @staticmethod
    def load_binary(filename: str, params: Optional[Dict[str, Any]] = None) -> "Dataset":
        """Load a :meth:`save_binary` file.  Truncated/garbage payloads
        raise a typed :class:`DatasetCorruptError` (never a raw pickle
        exception), and the stored :meth:`fingerprint` is recomputed and
        compared — a binned matrix that no longer matches its recorded
        identity fails loudly."""
        import pickle
        try:
            with open(filename, "rb") as fh:
                payload = pickle.load(fh)
        except OSError:
            raise
        except Exception as exc:
            raise DatasetCorruptError(
                str(filename), f"not a readable binary dataset "
                f"({type(exc).__name__}: {exc})") from exc
        if not isinstance(payload, dict) or \
                payload.get("format") != "lightgbm_tpu.dataset.v1":
            raise DatasetCorruptError(
                str(filename), "not a lightgbm_tpu binary dataset "
                "(missing/unknown format marker)")
        missing = [k for k in Dataset._BINARY_REQUIRED if k not in payload]
        if missing:
            raise DatasetCorruptError(
                str(filename),
                f"binary dataset is missing fields: {', '.join(missing)}")
        ds = Dataset(None, params=params)
        ds.X_binned = payload["X_binned"]
        ds.bin_mappers = payload["bin_mappers"]
        ds.used_feature_map = payload["used_feature_map"]
        ds.num_bins_per_feature = payload["num_bins_per_feature"]
        ds.feature_names_ = payload["feature_names"]
        ds.efb = payload.get("efb")
        ds.num_total_features = len(ds.feature_names_)
        if payload["label"] is not None:
            ds.metadata.set_label(payload["label"])
        ds.metadata.set_weight(payload["weight"])
        ds.metadata.set_group(payload["group"])
        ds.metadata.set_init_score(payload["init_score"])
        ds.constructed = True
        stored = payload.get("fingerprint")
        if stored:  # absent in pre-fingerprint files: accept
            try:
                got = ds.fingerprint()
            except Exception as exc:
                raise DatasetCorruptError(
                    str(filename), f"stored arrays are inconsistent "
                    f"({type(exc).__name__}: {exc})") from exc
            diffs = [k for k in stored if k in got and got[k] != stored[k]]
            if diffs:
                raise DatasetCorruptError(
                    str(filename),
                    "stored binned matrix does not match its recorded "
                    "fingerprint (" + ", ".join(
                        f"{k}: stored={stored[k]!r} got={got[k]!r}"
                        for k in diffs) + ")")
        return ds

    def _check_constructed(self) -> None:
        if not self.constructed:
            raise RuntimeError("Dataset not constructed yet; call construct() "
                               "(done automatically by train())")

    # -- device placement ----------------------------------------------------
    def device_bins(self, max_bin_global: int):
        """Return the binned matrix as a device array (cached)."""
        import jax.numpy as jnp
        key = ("bins", max_bin_global)
        if key not in self._device_cache:
            self._device_cache[key] = jnp.asarray(self.X_binned)
        return self._device_cache[key]

    def device_bins_packed4(self, row_block: int = 4096):
        """FEATURE-MAJOR nibble-packed device bins: two 4-bit bin codes
        per int8 lane (reference src/io/dense_bin.hpp 4-bit dense bins),
        rows padded to the Pallas kernel row block — the layout the
        packed histogram kernels stream (half the HBM bytes of the
        uint8 matrix).  Requires every used feature to fit 16 bins.
        Cached per row_block."""
        self._check_constructed()
        import numpy as _np
        import jax.numpy as jnp
        from .ops.histogram_pallas import (PACK4_MAX_BINS, pack_bins4,
                                           pad_rows)
        key = ("bins_packed4", row_block)
        if key not in self._device_cache:
            max_b = int(_np.max(self.num_bins_per_feature))
            if max_b > PACK4_MAX_BINS:
                raise ValueError(
                    f"device_bins_packed4 requires every feature to fit "
                    f"{PACK4_MAX_BINS} bins (max is {max_b}); set "
                    f"max_bin<={PACK4_MAX_BINS}")
            n = self.X_binned.shape[0]
            n_pad = pad_rows(n, row_block)
            xp = _np.pad(self.X_binned, ((0, n_pad - n), (0, 0)))
            self._device_cache[key] = pack_bins4(
                jnp.asarray(_np.ascontiguousarray(xp.T), jnp.uint8))
        return self._device_cache[key]
