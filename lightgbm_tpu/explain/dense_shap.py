"""Dense TreeSHAP — Algorithm 2 lowered to loop-free-in-rows MXU algebra.

The host reference (:mod:`..models.shap`) recurses every tree per row
chunk in Python.  But the recursion's CONTROL structure is entirely
row-independent: which nodes each root path visits, which feature sits
at each path level, and every zero fraction (count ratio) are model
constants — only the per-(row, node) hot-branch bit varies.  That bit
is exactly the condition matrix the PR-13 serving compiler already
builds (``models/dense_predict._decision_matrix``), so TreeSHAP lowers
the same way prediction did:

* **Host lowering** (:func:`lower_explain`) walks each tree's leaf root
  paths once (the same DFS as ``TreeBatch``'s path matrices) and merges
  duplicate features into at most ``D`` *slots* per path — Algorithm
  2's unwind-on-revisit collapses statically: a revisited feature's
  zero fraction is the PRODUCT of its occurrences' count ratios and its
  one fraction the AND of their hot bits.  Out come padded per-tree
  tensors over (leaf, slot): feature column, static zero fraction, and
  a signed node-occurrence matrix ``occ_dir`` (+1 left-expected, -1
  right-expected) whose contraction with the condition matrix counts
  matching hot bits per slot.
* **Padding is exactly inert**: a slot with (z=1, o=1) leaves the
  subset-weight algebra invariant (extending Algorithm 2 with a dummy
  (1, 1) item rescales pweights by precisely the factor the unwound sum
  divides back out), so every path pads to ``D`` slots and every tree
  to ``L`` leaves with zero-valued leaves — no masks in the kernel.
* **Device program** (:func:`dense_explain`): one-fractions are
  ``relu(dec @ occ_dir + negs - count + 1)`` — integer-valued counts,
  so the ReLU is an EXACT 0/1 AND, the ``_hit_matrix`` trick — then the
  extend recursion and Sum(UNWIND) evaluate as Python-unrolled
  elementwise f32 ops over a static (D+1) position axis: the jaxpr
  contains NO while/scan at all, in rows or otherwise (machine-checked
  by the ``serve_explain`` lint config).  Per-leaf contributions
  scatter-add into the phi block with STATIC column indices, and the
  program also returns the plain raw score (reach-indicator dot leaf
  values) so callers enforce the additivity invariant on every batch.

Parity: matches the f64 host walk within rtol 1e-4 (exact f32 leaf
values — the explain path never quantizes leaf tables).  Linear-leaf
trees attribute each leaf's PLAIN output, same as the host warning
path.
"""

from __future__ import annotations

import functools
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.dense_predict import DenseArrays, DenseLoweringError, DenseMeta
from ..models.dense_predict import _decision_matrix
from ..models.shap import node_expectations
from ..models.tree import Tree, TreeBatch

__all__ = ["EXPLAIN_DEPTH_BUDGET", "EXPLAIN_TABLE_BUDGET", "ExplainArrays",
           "ExplainMeta", "lower_explain", "dense_explain"]

# Budgets (lowering falls back to the host walk with a recorded reason
# past these — the PR-13 never-silent contract):
#   depth — the unwound-sum algebra unrolls O(D^2) elementwise steps;
#   past ~48 unique features per path the program would trade compile
#   time for no win over the walk.
#   table — bytes of the (T, Nn, L*D) signed occurrence matrix, the one
#   static tensor that scales with all three model axes at once.
EXPLAIN_DEPTH_BUDGET = 48
EXPLAIN_TABLE_BUDGET = 256 << 20


class ExplainMeta(NamedTuple):
    """Static (hashable) half of an explain lowering — the jit cache key
    next to the array shapes."""

    num_class: int
    num_trees: int            # real trees
    num_cols: int             # phi block width = feature columns + bias
    depth: int                # D: merged-slot count per root path
    mxu: bool                 # bf16 contraction w/ f32 accum (TPU)


class ExplainArrays(NamedTuple):
    """Device half: padded per-(tree, leaf, slot) root-path tensors (all
    static host work, a la the ``TreeBatch`` path matrices)."""

    occ_dir: jnp.ndarray      # (T, Nn, L*D) f32 +1 left / -1 right / 0
    occ_neg: jnp.ndarray      # (T, 1, L*D) f32 — right-expected count
    occ_cnt: jnp.ndarray      # (T, 1, L*D) f32 — occurrences (0 = pad)
    zfrac: jnp.ndarray        # (T, 1, L, D) f32 — static zero fractions
    leaf_val: jnp.ndarray     # (T, 1, L) f32 — PLAIN leaf values, exact
    seg: jnp.ndarray          # (T*L*D,) i32 — phi column per slot
    bias: jnp.ndarray         # (K*num_cols,) f32 — expected-value row
    class_onehot: jnp.ndarray  # (T, K) f32


def _leaf_paths(tree: Tree) -> List[List[Tuple[int, bool]]]:
    """Root path of every leaf as (internal node, went_left) pairs —
    the same DFS the ``TreeBatch`` path matrices run."""
    nl = int(tree.num_leaves)
    if nl <= 1:
        return [[]]
    out: List[Optional[List[Tuple[int, bool]]]] = [None] * nl
    work: List[Tuple[int, List[Tuple[int, bool]]]] = [(0, [])]
    while work:
        node, path = work.pop()
        for child, went_left in ((int(tree.left_child[node]), True),
                                 (int(tree.right_child[node]), False)):
            p2 = path + [(node, went_left)]
            if child < 0:
                out[~child] = p2
            else:
                work.append((child, p2))
    return out  # type: ignore[return-value]


def _node_count(tree: Tree, node: int) -> float:
    if node < 0:
        return float(tree.leaf_count[~node])
    return float(tree.internal_count[node])


def lower_explain(trees: List[Tree], num_class: int, num_cols: int,
                  class_ids: Optional[List[int]] = None, *,
                  mxu: bool = False, batch: Optional[TreeBatch] = None,
                  depth_budget: int = EXPLAIN_DEPTH_BUDGET,
                  table_budget: int = EXPLAIN_TABLE_BUDGET,
                  ) -> Tuple[ExplainArrays, ExplainMeta]:
    """Lower ``trees`` into the dense TreeSHAP tensors.

    ``num_cols`` is the phi block width (``num_features + 1``; the bias
    sits in the last column, matching ``models/shap.predict_contrib``'s
    layout).  Raises :class:`DenseLoweringError` with reason
    ``explain_depth_budget`` / ``explain_table_budget`` when the
    unrolled algebra or the occurrence table would blow its budget."""
    if not trees:
        raise DenseLoweringError("no_trees")
    b = batch if batch is not None else TreeBatch(trees)
    T = b.num_trees
    L = int(b.max_leaves)
    Nn = max(L - 1, 1)
    if class_ids is None:
        class_ids = [t % num_class for t in range(T)]

    # pass 1: merge duplicate features into slots; find the slot depth D
    merged = []   # per tree: list over leaves of (feat[], z[], occ[][])
    depth = 0
    for tree in trees:
        per_leaf = []
        for li, path in enumerate(_leaf_paths(tree)):
            slots: dict = {}
            feat: List[int] = []
            zfrac: List[float] = []
            occ: List[List[Tuple[int, bool]]] = []
            for pos, (node, went_left) in enumerate(path):
                f = int(tree.split_feature[node])
                child = int(tree.left_child[node] if went_left
                            else tree.right_child[node])
                cnt = _node_count(tree, node)
                ratio = _node_count(tree, child) / cnt if cnt > 0 else 0.0
                if f in slots:
                    s = slots[f]
                    zfrac[s] *= ratio
                    occ[s].append((node, went_left))
                else:
                    slots[f] = len(feat)
                    feat.append(f)
                    zfrac.append(ratio)
                    occ.append([(node, went_left)])
            depth = max(depth, len(feat))
            per_leaf.append((feat, zfrac, occ))
        merged.append(per_leaf)
    D = depth
    if D > depth_budget:
        raise DenseLoweringError(
            "explain_depth_budget",
            f"{D} merged path slots > budget {depth_budget}")
    table = 4 * T * Nn * L * max(D, 1)
    if table > table_budget:
        raise DenseLoweringError(
            "explain_table_budget",
            f"occurrence table {table} B > budget {table_budget} B")

    occ_dir = np.zeros((T, Nn, L * max(D, 1)), np.float32)
    occ_neg = np.zeros((T, 1, L * max(D, 1)), np.float32)
    occ_cnt = np.zeros((T, 1, L * max(D, 1)), np.float32)
    zfr = np.ones((T, 1, L, max(D, 1)), np.float32)
    leaf_val = np.zeros((T, 1, L), np.float32)
    # inert pads scatter into their class's bias column (their
    # contribution is exactly zero, so the target only has to be valid)
    seg = np.empty((T, L, max(D, 1)), np.int32)
    bias = np.zeros(num_class * num_cols, np.float64)
    class_onehot = np.zeros((T, num_class), np.float32)
    for t, tree in enumerate(trees):
        cid = int(class_ids[t])
        class_onehot[t, cid] = 1.0
        seg[t] = cid * num_cols + (num_cols - 1)
        nl = int(tree.num_leaves)
        if nl <= 1:
            # stump: empty path — only the bias moves, but the leaf
            # value still rides the reach indicator so the returned raw
            # score (the additivity right-hand side) includes it
            bias[cid * num_cols + num_cols - 1] += float(tree.leaf_value[0])
            leaf_val[t, 0, 0] = np.float32(tree.leaf_value[0])
            continue
        bias[cid * num_cols + num_cols - 1] += float(
            node_expectations(tree)[0])
        for li in range(nl):
            leaf_val[t, 0, li] = np.float32(tree.leaf_value[li])
            feat, zf, occ = merged[t][li]
            for s in range(len(feat)):
                col = li * D + s
                seg[t, li, s] = cid * num_cols + feat[s]
                zfr[t, 0, li, s] = np.float32(zf[s])
                occ_cnt[t, 0, col] = float(len(occ[s]))
                for node, went_left in occ[s]:
                    if went_left:
                        occ_dir[t, node, col] = 1.0
                    else:
                        occ_dir[t, node, col] = -1.0
                        occ_neg[t, 0, col] += 1.0

    arrays = ExplainArrays(
        occ_dir=jnp.asarray(occ_dir), occ_neg=jnp.asarray(occ_neg),
        occ_cnt=jnp.asarray(occ_cnt), zfrac=jnp.asarray(zfr),
        leaf_val=jnp.asarray(leaf_val),
        seg=jnp.asarray(seg.reshape(-1)),
        bias=jnp.asarray(bias.astype(np.float32)),
        class_onehot=jnp.asarray(class_onehot))
    meta = ExplainMeta(num_class=num_class, num_trees=T, num_cols=num_cols,
                       depth=D, mxu=bool(mxu))
    return arrays, meta


# ---------------------------------------------------------------------------
# device program
# ---------------------------------------------------------------------------

def _one_fractions(dec, E: ExplainArrays, emeta: ExplainMeta):
    """(T, N, L, D) EXACT 0/1 slot one-fractions: the signed-occurrence
    contraction counts matching hot bits (left-expected nodes contribute
    ``dec``, right-expected ``1 - dec`` via the folded ``occ_neg``
    constant), and ``relu(count - total + 1)`` is 1 exactly when every
    occurrence matches — integer-valued, so no equality select (the
    ``_hit_matrix`` trick).  Zero-occurrence pads come out 1: inert."""
    acc = jnp.bfloat16 if emeta.mxu else jnp.float32
    dec_t = jnp.transpose(dec, (1, 0, 2)).astype(acc)        # (T, N, Nn)
    hot = jax.lax.dot_general(dec_t, E.occ_dir.astype(acc),
                              (((2,), (1,)), ((0,), (0,))),
                              preferred_element_type=jnp.float32)
    o = jax.nn.relu(hot + E.occ_neg - E.occ_cnt + 1.0)       # (T, N, L*D)
    T, n = o.shape[0], o.shape[1]
    L = E.leaf_val.shape[2]
    return o.reshape(T, n, L, max(emeta.depth, 1))


def _extend_all(O, Z, emeta: ExplainMeta):
    """Algorithm 2's EXTEND over the full path, Python-unrolled: pweight
    state (T, N, L, D+1) over a static position axis; step ``s`` folds
    slot ``s``'s fractions in with static numpy coefficient rows (the
    (l-i)/(l+1), (i+1)/(l+1) factors).  No scan: D is a model constant
    and each step is a handful of fused elementwise ops."""
    D = emeta.depth
    T, n, L = O.shape[0], O.shape[1], O.shape[2]
    w = jnp.concatenate([jnp.ones((T, n, L, 1), jnp.float32),
                         jnp.zeros((T, n, L, D), jnp.float32)], axis=-1)
    pos = np.arange(D + 1, dtype=np.float64)
    for s in range(1, D + 1):
        pz = Z[..., s - 1:s]                                  # (T,1,L,1)
        po = O[..., s - 1:s]                                  # (T,N,L,1)
        keep = jnp.asarray(np.maximum(s - pos, 0.0) / (s + 1.0),
                           jnp.float32)
        shift = jnp.asarray(pos / (s + 1.0), jnp.float32)
        shifted = jnp.concatenate(
            [jnp.zeros_like(w[..., :1]), w[..., :-1]], axis=-1)
        w = pz * w * keep + po * shifted * shift
    return w


def _unwound_contribs(w, O, Z, E: ExplainArrays, emeta: ExplainMeta):
    """(T, N, L, D) per-slot contributions ``Sum(UNWIND(w, i)) *
    (o_i - z_i) * leaf_value`` — the host walk's ``unwound_sum`` with
    both loops unrolled over the static slot/position axes.  Inert pads
    have o == z == 1, so their factor is exactly 0."""
    D = emeta.depth
    out = []
    for i in range(1, D + 1):
        o = O[..., i - 1]                                     # (T,N,L)
        z = Z[..., i - 1]                                     # (T,1,L)
        o_nz = o != 0
        o_safe = jnp.where(o_nz, o, 1.0)
        z_safe = jnp.where(z != 0, z, 1.0)
        nn = w[..., D]
        total = jnp.zeros_like(o)
        for j in range(D - 1, -1, -1):
            t = nn * ((D + 1.0) / (j + 1.0)) / o_safe
            total = total + jnp.where(
                o_nz, t, w[..., j] * ((D + 1.0) / (D - j)) / z_safe)
            nn = jnp.where(o_nz, w[..., j] - t * z * ((D - j) / (D + 1.0)),
                           nn)
        out.append(total * (o - z))
    c = jnp.stack(out, axis=-1) if out else \
        jnp.zeros(O.shape[:3] + (0,), jnp.float32)
    return c * E.leaf_val[..., None]


def _explain(X, A: DenseArrays, dmeta: DenseMeta, E: ExplainArrays,
             emeta: ExplainMeta):
    n = X.shape[0]
    dec = _decision_matrix(X, A, dmeta)                       # (N, T, Nn)
    O = _one_fractions(dec, E, emeta)
    if emeta.depth == 0:
        # all-stump ensemble: one inert slot per leaf (matching seg's
        # max(D, 1) layout), zero contribution — only bias + raw move
        c = jnp.zeros(O.shape[:3] + (1,), jnp.float32)
    else:
        w = _extend_all(O, E.zfrac, emeta)
        c = _unwound_contribs(w, O, E.zfrac, E, emeta)        # (T,N,L,D)
    T = c.shape[0]
    L = c.shape[2]
    flat = jnp.transpose(c, (1, 0, 2, 3)).reshape(
        n, T * L * max(emeta.depth, 1))
    phi = jnp.zeros((n, emeta.num_class * emeta.num_cols), jnp.float32)
    phi = phi.at[:, E.seg].add(flat) + E.bias[None, :]
    # plain raw score for the additivity invariant: the product of a
    # path's slot one-fractions is its reach indicator (pads are 1)
    reach = jnp.prod(O, axis=-1)                              # (T, N, L)
    per_tree = jnp.sum(reach * E.leaf_val, axis=-1)           # (T, N)
    raw = jax.lax.dot_general(per_tree.T, E.class_onehot,
                              (((1,), (0,)), ((), ())),
                              precision=jax.lax.Precision.HIGHEST)
    return phi, raw


@functools.partial(jax.jit, static_argnames=("dmeta", "emeta"))
def dense_explain(X, arrays: DenseArrays, dmeta: DenseMeta,
                  exp: ExplainArrays, emeta: ExplainMeta):
    """Jitted dense TreeSHAP: ``(phi (N, K*num_cols) f32, raw (N, K)
    f32)``.  The lowered arrays are ARGUMENTS so the XLA cache keys on
    shapes only (the ``CompiledPredictor`` contract); ``raw`` is the
    plain-leaf raw score the phi rows must sum to."""
    return _explain(X, arrays, dmeta, exp, emeta)
