"""Explain compile entry — the serving-compiler contract for TreeSHAP.

Mirrors ``serve/compiler.py``: :func:`compile_explain` returns either an
:class:`ExplainExecutable` or ``(None, reason)`` with the reason
recorded in the ``serve_explain_fallback`` counter — a fallback to the
host walk is NEVER silent (the PR-13 rule).  The executable evaluates
the :mod:`.dense_shap` program on row chunks sized by the declared
working-set budget and enforces the additivity invariant (phi rows sum
to the plain raw score) on every batch it returns.

Policy note: unlike prediction there is no CPU cost model — the host
TreeSHAP walk is a Python-level recursion per tree, so the vectorized
dense program wins on every backend whenever it lowers; ``auto`` only
falls back on lowering budgets (depth/table), which it records.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..analysis.contracts import memory_budget
from ..models.dense_predict import (DenseArrays, DenseLoweringError,
                                    DenseMeta, lower_ensemble)
from ..models.tree import SHAPE_BUCKETS, Tree, TreeBatch, pad_rows
from ..telemetry.metrics import default_registry
from ..telemetry.slo import register_metric_ensurer
from ..utils.backend import default_backend
from .dense_shap import (EXPLAIN_DEPTH_BUDGET, EXPLAIN_TABLE_BUDGET,
                         ExplainArrays, ExplainMeta, dense_explain,
                         lower_explain)

__all__ = ["EXPLAIN_FALLBACK_COUNTER", "EXPLAIN_FALLBACK_BATCHES",
           "EXPLAIN_WORKSET_BUDGET", "ExplainAdditivityError",
           "ExplainExecutable", "check_additivity", "compile_explain",
           "explain_fallback_counts", "note_explain_fallback_batch",
           "dense_explain_hbm_bytes"]

# The pweight state (T, rows, L, D+1) f32 is the explain program's
# working set; chunk rows so the handful of live copies the unrolled
# algebra keeps stays under this.
EXPLAIN_WORKSET_BUDGET = 256 << 20

# additivity slack: f32 accumulation over the tree axis vs the exact
# sum — generous so legitimate programs never trip it, tight enough
# that a wrong unwind (systematic, O(leaf value)) always does
ADDITIVITY_RTOL = 5e-3
ADDITIVITY_ATOL = 1e-3


def dense_explain_hbm_bytes(ctx):
    """Per-device HBM curve of one explain bucket program: the (T*L, D)
    root-path working set of the issue title — condition matrix, slot
    one-fractions, a few live (bucket, T, L, D+1) pweight copies from
    the unrolled extend/unwind chain, the phi/scatter block, and the
    static occurrence table."""
    n = int(ctx.get("bucket", max(SHAPE_BUCKETS)))
    t = int(ctx.get("trees", 64))
    leaves = int(ctx.get("leaves", 64))
    nn = max(leaves - 1, 1)
    d = max(int(ctx.get("depth", 8)), 1)
    k = max(int(ctx.get("num_class", 1)), 1)
    cols = int(ctx.get("cols", int(ctx.get("features", 32)) + 1))
    rows = n * (3 * 4 * t * nn              # P / isn / dec blocks
                + 4 * 4 * t * leaves * (d + 1)   # live pweight copies
                + 4 * 4 * t * leaves * d    # one-fractions + contribs
                + 2 * 4 * k * cols)         # phi + scatter update
    tables = 4 * t * nn * leaves * d + 12 * t * leaves * d
    return rows + tables + (8 << 20)


memory_budget("serve/dense_explain", ("serve_explain",),
              dense_explain_hbm_bytes,
              note="condition matrix + (T*L, D) path slots + unrolled "
                   "pweight chain + occurrence table")


# ---------------------------------------------------------------------------
# fallback telemetry — never silent
# ---------------------------------------------------------------------------

EXPLAIN_FALLBACK_COUNTER = "serve_explain_fallback"
EXPLAIN_FALLBACK_BATCHES = "serve_explain_fallback_batches_total"
_fb_lock = threading.Lock()
_fb_counts: Dict[str, int] = {}


def _note_fallback(reason: str, model: str = "") -> None:
    with _fb_lock:
        _fb_counts[reason] = _fb_counts.get(reason, 0) + 1
    default_registry().counter(
        EXPLAIN_FALLBACK_COUNTER,
        "dense-explain compiler fallbacks to the host TreeSHAP walk, "
        "by reason", labels=("reason", "model")).inc(
        reason=reason, model=model or "-")


def note_explain_fallback_batch(reason: str, model: str) -> None:
    """One served explain batch answered by the host walk (the
    predictor calls this per dispatch, so the fallback rate is measured
    in traffic, not in compiles)."""
    default_registry().counter(
        EXPLAIN_FALLBACK_BATCHES,
        "explain batches served by the host-walk fallback, by reason",
        labels=("reason", "model")).inc(1, reason=reason,
                                        model=model or "-")


@register_metric_ensurer
def _ensure_explain_metrics(reg) -> None:
    reg.counter(EXPLAIN_FALLBACK_COUNTER,
                "dense-explain compiler fallbacks to the host TreeSHAP "
                "walk, by reason", labels=("reason", "model"))
    reg.counter(EXPLAIN_FALLBACK_BATCHES,
                "explain batches served by the host-walk fallback, by "
                "reason", labels=("reason", "model"))


def explain_fallback_counts() -> Dict[str, int]:
    """Process-wide explain-fallback tally by reason (mirrors the
    labeled ``serve_explain_fallback`` counter series)."""
    with _fb_lock:
        return dict(_fb_counts)


class ExplainAdditivityError(RuntimeError):
    """The dense program's phi rows failed to sum to its raw score —
    the invariant every TreeSHAP result must satisfy.  Callers fall
    back to the host walk and record reason ``additivity``."""


class ExplainExecutable:
    """A lowered dense-TreeSHAP program bound to one ensemble."""

    def __init__(self, arrays: DenseArrays, dmeta: DenseMeta,
                 exp: ExplainArrays, emeta: ExplainMeta) -> None:
        self.arrays = arrays
        self.dmeta = dmeta
        self.exp = exp
        self.emeta = emeta
        self._leaves = int(exp.leaf_val.shape[2])
        self._nodes = int(arrays.split_feature.shape[1])

    @property
    def signature(self):
        """Shape/dtype signature — programs with equal signatures share
        the XLA cache entries (same contract as ``DenseExecutable``)."""
        return ("explain", self.emeta,
                tuple((tuple(a.shape), str(a.dtype))
                      for a in self.exp if a is not None))

    def max_rows(self, budget: int = EXPLAIN_WORKSET_BUDGET) -> int:
        """Largest shape bucket whose pweight working set fits."""
        d = max(self.emeta.depth, 1)
        t = max(self.emeta.num_trees, 1)
        per_row = 4 * t * (3 * self._nodes
                           + 8 * self._leaves * (d + 1))
        best = SHAPE_BUCKETS[0]
        for b in SHAPE_BUCKETS:
            if b * per_row <= budget:
                best = b
        return best

    def explain_padded(self, Xp):
        """(phi, raw) device arrays for an already-padded row block —
        the predictor's bucket-ladder entry."""
        return dense_explain(Xp, self.arrays, self.dmeta,
                             self.exp, self.emeta)

    def explain(self, X: np.ndarray, check: bool = True,
                buckets=None) -> np.ndarray:
        """Chunked, padded, additivity-checked phi for arbitrary rows
        (the Booster predict path's and the serving lane's entry)."""
        n = X.shape[0]
        chunk = self.max_rows()
        outs = []
        for lo in range(0, n, chunk):
            Xc = np.asarray(X[lo:lo + chunk], np.float32)
            nc = Xc.shape[0]
            Xp = pad_rows(Xc, buckets) if buckets is not None \
                else pad_rows(Xc)
            phi, raw = self.explain_padded(Xp)
            phi = np.asarray(phi[:nc], np.float64)
            if check:
                check_additivity(phi, np.asarray(raw[:nc], np.float64),
                                 self.emeta.num_cols)
            outs.append(phi)
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)

    def info(self) -> Dict[str, object]:
        return {"compiled": True, "trees": self.emeta.num_trees,
                "depth": self.emeta.depth, "leaves": self._leaves,
                "num_class": self.emeta.num_class,
                "cols": self.emeta.num_cols, "mxu": self.emeta.mxu}


def check_additivity(phi: np.ndarray, raw: np.ndarray, num_cols: int,
                     rtol: float = ADDITIVITY_RTOL,
                     atol: float = ADDITIVITY_ATOL) -> None:
    """Enforce ``sum(phi block) == raw score`` per class; raises
    :class:`ExplainAdditivityError` with the worst row's numbers."""
    n = phi.shape[0]
    k = max(phi.shape[1] // num_cols, 1)
    sums = phi.reshape(n, k, num_cols).sum(axis=2)
    err = np.abs(sums - raw)
    lim = atol + rtol * np.abs(raw)
    if np.all(err <= lim):
        return
    i = int(np.unravel_index(np.argmax(err - lim), err.shape)[0])
    raise ExplainAdditivityError(
        f"phi rows do not sum to the raw score: worst row {i}: "
        f"sum={sums[i].tolist()} raw={raw[i].tolist()}")


def compile_explain(trees: List[Tree], num_class: int, num_features: int,
                    class_ids: Optional[List[int]] = None, *,
                    mode: str = "auto", num_cols: Optional[int] = None,
                    batch: Optional[TreeBatch] = None,
                    depth_budget: int = EXPLAIN_DEPTH_BUDGET,
                    table_budget: int = EXPLAIN_TABLE_BUDGET,
                    model_label: str = "",
                    ) -> Tuple[Optional[ExplainExecutable], Optional[str]]:
    """Compile the dense TreeSHAP program, or report why not.

    ``num_features`` is the inner (used-column) width the condition
    matrix reads; ``num_cols`` the phi block width (defaults to
    ``num_features + 1`` — Boosters pass their full feature count + 1
    so the output layout matches the host ``predict_contrib``).
    Returns ``(executable, None)`` or ``(None, reason)`` with the
    reason recorded in ``serve_explain_fallback`` — mirror of
    ``serve/compiler.compile_ensemble``."""
    if mode not in ("auto", "dense", "walk"):
        raise ValueError(f"tpu_explain_compiler must be auto|dense|walk, "
                         f"got {mode!r}")
    if mode == "walk":
        _note_fallback("forced_walk", model_label)
        return None, "forced_walk"
    if not trees:
        _note_fallback("no_trees", model_label)
        return None, "no_trees"
    mxu = default_backend() == "tpu"
    cols = num_features + 1 if num_cols is None else num_cols
    try:
        arrays, dmeta = lower_ensemble(
            trees, num_class, num_features, class_ids,
            leaf_bits=0, mxu=mxu, shard=1, batch=batch)
        exp, emeta = lower_explain(
            trees, num_class, cols, class_ids, mxu=mxu, batch=batch,
            depth_budget=depth_budget, table_budget=table_budget)
    except DenseLoweringError as e:
        if mode == "dense":
            raise
        _note_fallback(e.reason, model_label)
        return None, e.reason
    return ExplainExecutable(arrays, dmeta, exp, emeta), None
