"""Explanation serving: the dense MXU TreeSHAP lowering + compile entry.

``dense_shap`` lowers an ensemble's TreeSHAP computation (Lundberg's
Algorithm 2, the exact algebra of ``models/shap.py``) into ONE
loop-free-in-rows jitted program; ``compiler`` wraps it into the
serving-compiler contract — ``compile_explain`` returning either an
executable or a machine-usable fallback reason, never silence.
"""

from .compiler import (EXPLAIN_FALLBACK_COUNTER, ExplainAdditivityError,
                       ExplainExecutable, check_additivity, compile_explain,
                       explain_fallback_counts, note_explain_fallback_batch)
from .dense_shap import (EXPLAIN_DEPTH_BUDGET, EXPLAIN_TABLE_BUDGET,
                         ExplainArrays, ExplainMeta, dense_explain,
                         lower_explain)

__all__ = [
    "EXPLAIN_DEPTH_BUDGET", "EXPLAIN_TABLE_BUDGET",
    "EXPLAIN_FALLBACK_COUNTER", "ExplainAdditivityError", "ExplainArrays",
    "ExplainExecutable", "ExplainMeta", "check_additivity",
    "compile_explain", "dense_explain", "explain_fallback_counts",
    "lower_explain", "note_explain_fallback_batch",
]
