"""Exclusive Feature Bundling (EFB) — one of the two core LightGBM tricks.

TPU-native re-implementation of the reference's bundling
(reference: src/io/dataset.cpp:53 ``GetConflictCount``, :100 ``FindGroups``
greedy conflict-bounded grouping, :239 ``FastFeatureBundling``, bin offsets
per feature inside a group à la feature_group.h).

TPU-first design: the DEVICE matrix holds one uint8 column per BUNDLE
(width ≈ bundle count, the whole point for wide-sparse data), histograms
are built and pooled in bundle space (G, Bb, 3), and a cheap gather
"expansion" rebuilds per-ORIGINAL-feature histograms (F, B, 3) right
before each split scan — each feature's default (zero) bin is restored
from the leaf totals, the reference's Dataset::FixHistogram trick
(dataset.cpp:1239).  Tree structure, split finding, and the model format
stay entirely in original-feature space, so EFB is invisible outside
training.

Bundle bin layout: bundle bin 0 = "every member feature at its default
bin"; member feature f with nb_f bins gets the range
[offset_f, offset_f + nb_f - 1) for its non-default bins (the default is
elided).  Singleton bundles keep their feature's bins verbatim.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

import numpy as np


MAX_BUNDLE_BINS = 256    # uint8 device columns
CONFLICT_RATE = 1e-4     # max conflicting rows per bundle, as fraction of N


@dataclasses.dataclass
class BundleInfo:
    """Static bundling descriptors over INNER (used) features."""
    n_bundles: int
    bundle_bins: int                 # Bb: max bins over bundles
    f_bundle: np.ndarray             # (F,) bundle id per feature
    f_offset: np.ndarray             # (F,) non-default bin offset in bundle
    f_default: np.ndarray            # (F,) the feature's default bin
    f_nbins: np.ndarray              # (F,) the feature's bin count
    f_single: np.ndarray             # (F,) bool: singleton bundle (verbatim)
    exp_map: np.ndarray              # (F, B) flat bundle-bin id or -1
    fix_mask: np.ndarray             # (F,) bool: restore default via totals

    @property
    def needs_fix(self) -> bool:
        return bool(self.fix_mask.any())


def find_bundles(mappers: Sequence, nondefault: List[np.ndarray], n_rows: int,
                 sample_rows: int,
                 max_bundle_bins: int = MAX_BUNDLE_BINS,
                 conflict_rate: float = CONFLICT_RATE) -> List[List[int]]:
    """Greedy conflict-bounded grouping (dataset.cpp:100 FindGroups).

    nondefault[f] is a bool mask over the SAMPLED rows where feature f is
    away from its default bin.  Returns bundles as lists of feature ids.
    """
    max_conflict = max(0, int(conflict_rate * sample_rows))
    counts = np.array([int(m.sum()) for m in nondefault])
    order = np.argsort(-counts, kind="stable")

    bundles: List[List[int]] = []
    bundle_mask: List[np.ndarray] = []
    bundle_conflict: List[int] = []
    bundle_bins: List[int] = []
    for f in order:
        nb_extra = int(mappers[f].num_bin) - 1
        placed = False
        for bi in range(len(bundles)):
            if bundle_bins[bi] + nb_extra >= max_bundle_bins:
                continue
            conflict = int(np.count_nonzero(bundle_mask[bi] & nondefault[f]))
            if bundle_conflict[bi] + conflict <= max_conflict:
                bundles[bi].append(int(f))
                bundle_mask[bi] |= nondefault[f]
                bundle_conflict[bi] += conflict
                bundle_bins[bi] += nb_extra
                placed = True
                break
        if not placed:
            bundles.append([int(f)])
            bundle_mask.append(nondefault[f].copy())
            bundle_conflict.append(0)
            bundle_bins.append(1 + nb_extra)
    return bundles


def build_bundle_info(mappers: Sequence, bundles: List[List[int]],
                      max_feature_bins: int) -> BundleInfo:
    F = len(mappers)
    B = max_feature_bins
    f_bundle = np.zeros(F, np.int32)
    f_offset = np.zeros(F, np.int32)
    f_default = np.asarray([int(m.default_bin) for m in mappers], np.int32)
    f_nbins = np.asarray([int(m.num_bin) for m in mappers], np.int32)
    f_single = np.zeros(F, bool)
    bb = 1
    for g, feats in enumerate(bundles):
        if len(feats) == 1:
            f = feats[0]
            f_bundle[f] = g
            f_offset[f] = 0
            f_single[f] = True
            bb = max(bb, int(f_nbins[f]))
        else:
            off = 1
            for f in feats:
                f_bundle[f] = g
                f_offset[f] = off
                off += int(f_nbins[f]) - 1
            bb = max(bb, off)

    G = len(bundles)
    exp_map = np.full((F, B), -1, np.int64)
    fix_mask = np.zeros(F, bool)
    for f in range(F):
        g = int(f_bundle[f])
        nb = int(f_nbins[f])
        if f_single[f]:
            exp_map[f, :nb] = g * bb + np.arange(nb)
        else:
            fix_mask[f] = True
            d = int(f_default[f])
            o = int(f_offset[f])
            for b in range(nb):
                if b == d:
                    continue  # restored from leaf totals (FixHistogram)
                exp_map[f, b] = g * bb + o + b - (1 if b > d else 0)
    return BundleInfo(n_bundles=G, bundle_bins=bb, f_bundle=f_bundle,
                      f_offset=f_offset, f_default=f_default,
                      f_nbins=f_nbins, f_single=f_single,
                      exp_map=exp_map.astype(np.int32), fix_mask=fix_mask)


def bundle_binned_matrix(X_binned: np.ndarray, info: BundleInfo) -> np.ndarray:
    """Compress a per-feature binned matrix (N, F) into bundle columns
    (N, G) (dense-input path)."""
    n = X_binned.shape[0]
    out = np.zeros((n, info.n_bundles), np.uint8)
    for f in range(X_binned.shape[1]):
        g = int(info.f_bundle[f])
        col = X_binned[:, f].astype(np.int32)
        if info.f_single[f]:
            out[:, g] = col.astype(np.uint8)
        else:
            d = int(info.f_default[f])
            o = int(info.f_offset[f])
            nd = col != d
            vals = o + col[nd] - (col[nd] > d)
            out[nd, g] = vals.astype(np.uint8)
    return out


def bundle_sparse_csc(csc, mappers: Sequence, info: BundleInfo) -> np.ndarray:
    """Build the bundled matrix straight from a scipy CSC matrix — the raw
    data is never densified (sparse-ingestion path; reference
    sparse_bin.hpp's role collapses into this one pass)."""
    n = csc.shape[0]
    out = np.zeros((n, info.n_bundles), np.uint8)
    for f in range(len(mappers)):
        g = int(info.f_bundle[f])
        lo, hi = csc.indptr[f], csc.indptr[f + 1]
        rows = csc.indices[lo:hi]
        vals = np.asarray(csc.data[lo:hi], np.float64)
        bins = mappers[f].value_to_bin(vals).astype(np.int32)
        d = int(mappers[f].default_bin)
        if info.f_single[f]:
            if d:
                out[:, g] = np.uint8(d)  # implied zeros sit in bin(0.0)
            out[rows, g] = bins.astype(np.uint8)
        else:
            o = int(info.f_offset[f])
            nd = bins != d
            out[rows[nd], g] = (o + bins[nd] - (bins[nd] > d)).astype(np.uint8)
    return out


# ---------------------------------------------------------------------------
# Device-side helpers shared by the growers (learner/partitioned.py and
# learner/wave.py).  ``efb_arrays`` is the jnp tuple built by
# SerialTreeLearner from BundleInfo: (exp_map, f_bundle, f_offset,
# f_default, f_nbins, f_single).
# ---------------------------------------------------------------------------


def make_expand_hist(efb_arrays, num_features: int, n_bundles: int,
                     bundle_bins: int):
    """Closure mapping a bundle-space (G, Bb, 3) histogram to per-feature
    (F, B, 3) space, restoring each feature's default bin from the leaf
    totals (Dataset::FixHistogram, reference src/io/dataset.cpp:1239).
    Identity when ``efb_arrays`` is empty (no bundling)."""
    import jax.numpy as jnp

    if not efb_arrays:
        return lambda hb, total: hb
    exp_map, f_bundle, f_off, f_def, f_nb, f_single = efb_arrays
    G, Bb, F = n_bundles, bundle_bins, num_features

    def expand(hb, total):
        flat = hb.reshape(G * Bb, 3)
        e = jnp.where((exp_map >= 0)[:, :, None],
                      flat[jnp.maximum(exp_map, 0)], 0.0)
        fix = total[None, :] - jnp.sum(e, axis=1)
        fixable = jnp.logical_not(f_single).astype(jnp.float32)
        e = e.at[jnp.arange(F), f_def].add(fix * fixable[:, None])
        return e

    return expand


def make_bundle_decode(efb_arrays):
    """Closure mapping a BUNDLE-space bin column ``v`` (int32 values of
    feature ``feat``'s bundle column) to FEATURE-space bin codes —
    the inverse of the offset encoding in bundle_binned_matrix().
    Identity when ``efb_arrays`` is empty."""
    import jax.numpy as jnp

    if not efb_arrays:
        return lambda v, feat: v
    exp_map, f_bundle, f_off, f_def, f_nb, f_single = efb_arrays

    def decode(v, feat):
        u = v - f_off[feat]
        inr = (u >= 0) & (u < f_nb[feat] - 1)
        mapped = jnp.where(inr, u + (u >= f_def[feat]).astype(jnp.int32),
                           f_def[feat])
        return jnp.where(f_single[feat], v, mapped)

    return decode
