"""Trainer-side delta publisher.

``engine.train`` creates one of these when ``publish_dir`` is set and
calls :meth:`maybe_publish` after each boosting round (plus a forced
publish on the PreemptionGuard drain path and at normal completion), so
the journal head always equals what ``Booster.save_model`` would write
at the same iteration — the fragment and the base are produced by the
same :func:`model_to_string` serializer, byte for byte."""

from __future__ import annotations

from typing import Optional

from ..models.model_text import model_to_string
from ..telemetry.metrics import default_registry
from .delta import DeltaJournal

__all__ = ["DeltaPublisher"]


class DeltaPublisher:
    """Publishes per-round model deltas into a :class:`DeltaJournal`.

    ``every`` is the round cadence; ``compact_after`` (0 = never) folds
    the chain into a fresh BASE once that many deltas pile up, bounding
    replay cost for late subscribers.  A publisher always starts its own
    chain with a BASE at the first published round (a restarted trainer
    re-anchors rather than guessing at a prior chain's fingerprints)."""

    def __init__(self, directory: str, every: int = 1,
                 compact_after: int = 0, registry=None) -> None:
        self.journal = DeltaJournal(directory)
        self.every = max(1, int(every))
        self.compact_after = max(0, int(compact_after))
        self._last_round: Optional[int] = None
        reg = registry if registry is not None else default_registry()
        self._deltas_total = reg.counter(
            "publish_deltas_total",
            "Delta records appended to the publish journal",
            labels=("journal",))
        self._round_gauge = reg.gauge(
            "publish_round",
            "Newest boosting round in the publish journal",
            labels=("journal",))
        self._label = {"journal": self.journal.directory}

    @property
    def last_round(self) -> Optional[int]:
        return self._last_round

    def maybe_publish(self, gbdt, iteration: int) -> bool:
        """Publish when ``iteration`` (1-based completed rounds) lands
        on the cadence; returns True when something was written."""
        if iteration % self.every:
            return False
        return self.publish(gbdt)

    def publish(self, gbdt) -> bool:
        """Publish everything trained since the last publish: a BASE on
        the first call, a chained delta fragment afterwards.  No-op when
        no new full round exists."""
        k = max(1, int(gbdt.num_tree_per_iteration))
        rnd = len(gbdt.models) // k
        if rnd <= 0:
            return False
        if self._last_round is None:
            self.journal.write_base(model_to_string(gbdt), rnd)
        elif rnd > self._last_round:
            self.journal.append_delta(
                model_to_string(gbdt, start_iteration=self._last_round,
                                num_iteration=rnd - self._last_round),
                rnd, num_tree_per_iteration=k)
            self._deltas_total.inc(**self._label)
            if self.compact_after and \
                    self.journal.chain_length() >= self.compact_after:
                self.journal.compact(model_to_string(gbdt), rnd)
        else:
            return False
        self._last_round = rnd
        self._round_gauge.set(float(rnd), **self._label)
        return True
