"""Subscriber-side chain folding: journal/records -> trees / models.

A delta payload is a *standalone* model text for its round slice (the
publisher renders it with the same serializer as ``save_model``), so
parsing reuses the full ``string_to_model`` machinery — tree_sizes
truncation detection, ``ModelCorruptError`` offsets, real-index feature
mapping — instead of a second parser."""

from __future__ import annotations

from typing import List, Tuple

from .delta import DeltaChainError, DeltaJournal, DeltaRecord

__all__ = ["trees_from_fragment", "fold_chain", "load_journal"]


def _parse_model_text(text: str, source: str = "<delta payload>"):
    from ..config import Config
    from ..models.model_text import string_to_model
    return string_to_model(text, Config({}), source=source)


def trees_from_fragment(payload: str, source: str = "<delta payload>"
                        ) -> Tuple[List, int]:
    """Parse one delta payload into ``(trees, num_tree_per_iteration)``.
    The trees carry real (untranslated) feature indices, ready to append
    to a text-loaded booster or re-lower into the dense program."""
    gbdt = _parse_model_text(payload, source=source)
    return list(gbdt.models), max(1, int(gbdt.num_tree_per_iteration))


def fold_chain(base_text: str, records: List[DeltaRecord]):
    """Fold a validated chain into one GBDT: load the base, append each
    record's trees in order.  Round bookkeeping (``iter_``) tracks the
    appended trees so ``save_model``/``predict`` see one continuous
    model."""
    gbdt = _parse_model_text(base_text, source="<journal base>")
    k = max(1, int(gbdt.num_tree_per_iteration))
    for rec in records:
        trees, frag_k = trees_from_fragment(
            rec.payload, source=f"<delta round {rec.round}>")
        if frag_k != k:
            raise DeltaChainError(
                f"delta round {rec.round}: num_tree_per_iteration "
                f"{frag_k} != base {k}")
        expect = (rec.round - rec.base_round) * k
        if len(trees) != expect:
            raise DeltaChainError(
                f"delta round {rec.round}: {len(trees)} trees for "
                f"{rec.round - rec.base_round} rounds (expected {expect})")
        gbdt.models.extend(trees)
    gbdt.iter_ = len(gbdt.models) // k
    return gbdt


def load_journal(directory: str) -> Tuple[object, int]:
    """Materialize a journal into ``(gbdt, round)`` — the cold-start /
    full-reload path for subscribers too far behind to replay deltas."""
    journal = DeltaJournal(directory)
    base_text, base_round, records = journal.chain()
    gbdt = fold_chain(base_text, records)
    rnd = records[-1].round if records else base_round
    return gbdt, rnd
