"""Continuous-learning lane: per-round model delta publishing.

The trainer appends each published round's new trees to a crash-safe
delta journal (:mod:`.delta`); the serving tier replays the journal to
extend compiled ensembles in place (:mod:`.subscriber`), and the fleet
supervisor pushes deltas to workers with a staleness SLO
(:mod:`lightgbm_tpu.serve.fleet`)."""

from .delta import (DeltaChainError, DeltaJournal, DeltaRecord,
                    fingerprint_text)
from .publisher import DeltaPublisher
from .subscriber import fold_chain, load_journal, trees_from_fragment

__all__ = ["DeltaChainError", "DeltaJournal", "DeltaRecord",
           "fingerprint_text", "DeltaPublisher", "fold_chain",
           "load_journal", "trees_from_fragment"]
