"""Delta wire format and the crash-safe publish journal.

A journal directory holds three kinds of entries:

* ``BASE.00004.txt`` — a full model-text file (exactly what
  ``Booster.save_model`` writes) anchoring the chain at round 4;
* ``DELTA.00007`` — a binary append record carrying the model-text
  fragment for rounds (base_round, round], crc-guarded and
  fingerprint-chained to its parent;
* ``HEAD`` — a pointer file naming the newest entry.

Every write goes through :func:`io_utils.atomic_write_bytes` and then
repoints ``HEAD`` — the same write-then-repoint ring discipline as
``resilience/checkpoint.py``, so a crash between the two leaves the
previous head intact and :meth:`DeltaJournal.head` falls back to a
directory scan when the pointer is stale or torn.

The fingerprint chain makes replay-onto-the-wrong-base a typed error
instead of silent corruption: a BASE's fingerprint is the sha256 of its
model text; each delta's fingerprint is the sha256 of its parent's
fingerprint plus its own payload, so any gap, reorder, or divergent
base surfaces as :class:`DeltaChainError` at validation time.

Record layout (all integers little-endian)::

    MAGIC(8) | u32 header_len | u32 payload_len |
    u32 crc32(header || payload) | header_json | payload_utf8
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import struct
import threading
import zlib
from typing import List, NamedTuple, Optional, Tuple

from ..io_utils import atomic_write_bytes, atomic_write_text

__all__ = ["DeltaChainError", "DeltaRecord", "DeltaJournal",
           "fingerprint_text", "chain_fingerprint", "DELTA_FORMAT"]

MAGIC = b"LGTPDELT"
DELTA_FORMAT = "lgbm-tpu-delta-v1"
_HDR = struct.Struct("<III")            # header_len, payload_len, crc32

_BASE_RE = re.compile(r"^BASE\.(\d+)\.txt$")
_DELTA_RE = re.compile(r"^DELTA\.(\d+)$")
HEAD = "HEAD"


class DeltaChainError(ValueError):
    """The delta chain is broken: torn/corrupt record, round gap,
    fingerprint mismatch, or replay onto the wrong base model."""


def fingerprint_text(text: str) -> str:
    """Chain anchor for a full model text (a BASE entry)."""
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def chain_fingerprint(parent_fp: str, payload: str) -> str:
    """Chained fingerprint of one delta: binds the fragment bytes to the
    exact parent state, so replays detect gaps and reorders."""
    h = hashlib.sha256()
    h.update(parent_fp.encode("ascii"))
    h.update(b"\n")
    h.update(payload.encode("utf-8"))
    return h.hexdigest()


@dataclasses.dataclass(frozen=True)
class DeltaRecord:
    """One append record: the model-text fragment for boosting rounds
    ``(base_round, round]``, fingerprint-chained to its parent entry."""

    base_round: int          # chain position this record extends
    round: int               # rounds complete after applying this record
    parent_fp: str           # fingerprint of the parent entry
    fp: str                  # chain_fingerprint(parent_fp, payload)
    num_tree_per_iteration: int
    payload: str             # standalone model text of the new rounds

    def to_bytes(self) -> bytes:
        header = json.dumps({
            "format": DELTA_FORMAT,
            "base_round": self.base_round,
            "round": self.round,
            "parent_fp": self.parent_fp,
            "fp": self.fp,
            "num_tree_per_iteration": self.num_tree_per_iteration,
        }, sort_keys=True).encode("utf-8")
        payload = self.payload.encode("utf-8")
        crc = zlib.crc32(header + payload) & 0xFFFFFFFF
        return MAGIC + _HDR.pack(len(header), len(payload), crc) \
            + header + payload

    @classmethod
    def from_bytes(cls, data: bytes, source: str = "<bytes>"
                   ) -> "DeltaRecord":
        if len(data) < len(MAGIC) + _HDR.size:
            raise DeltaChainError(f"{source}: truncated delta record "
                                  f"({len(data)} bytes)")
        if data[:len(MAGIC)] != MAGIC:
            raise DeltaChainError(f"{source}: bad magic "
                                  f"{data[:len(MAGIC)]!r}")
        hlen, plen, crc = _HDR.unpack_from(data, len(MAGIC))
        body = data[len(MAGIC) + _HDR.size:]
        if len(body) != hlen + plen:
            raise DeltaChainError(
                f"{source}: torn record (expected {hlen + plen} body "
                f"bytes, got {len(body)})")
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            raise DeltaChainError(f"{source}: crc mismatch")
        try:
            header = json.loads(body[:hlen].decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as exc:
            raise DeltaChainError(f"{source}: bad header: {exc}") from exc
        if header.get("format") != DELTA_FORMAT:
            raise DeltaChainError(
                f"{source}: format {header.get('format')!r} != "
                f"{DELTA_FORMAT!r}")
        payload = body[hlen:].decode("utf-8")
        rec = cls(base_round=int(header["base_round"]),
                  round=int(header["round"]),
                  parent_fp=str(header["parent_fp"]),
                  fp=str(header["fp"]),
                  num_tree_per_iteration=int(
                      header["num_tree_per_iteration"]),
                  payload=payload)
        if chain_fingerprint(rec.parent_fp, payload) != rec.fp:
            raise DeltaChainError(f"{source}: payload does not match "
                                  f"its declared fingerprint")
        if rec.round <= rec.base_round:
            raise DeltaChainError(
                f"{source}: non-monotonic rounds {rec.base_round} -> "
                f"{rec.round}")
        return rec


class HeadInfo(NamedTuple):
    round: int
    fp: str
    kind: str                # "base" | "delta"
    name: str                # entry filename


def _base_name(rnd: int) -> str:
    return f"BASE.{rnd:05d}.txt"


def _delta_name(rnd: int) -> str:
    return f"DELTA.{rnd:05d}"


class DeltaJournal:
    """Monotonic publish journal with checkpoint-ring crash discipline.

    Writers (one per journal) call :meth:`write_base` /
    :meth:`append_delta` / :meth:`compact`; readers call :meth:`head`,
    :meth:`chain` and :meth:`records_after`.  All mutation is
    lock-serialized and atomic: entry file first, ``HEAD`` repoint
    second, prune last."""

    def __init__(self, directory: str) -> None:
        self.directory = str(directory)
        self._lock = threading.Lock()

    # -- read side ----------------------------------------------------------

    def _entries(self) -> List[Tuple[int, str, str]]:
        """[(round, kind, name)] sorted by (round, kind) — deltas sort
        after a base at the same round (a base is folded state)."""
        out = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for n in names:
            m = _BASE_RE.match(n)
            if m:
                out.append((int(m.group(1)), "base", n))
                continue
            m = _DELTA_RE.match(n)
            if m:
                out.append((int(m.group(1)), "delta", n))
        out.sort(key=lambda e: (e[0], e[1] == "delta"))
        return out

    def _read(self, name: str) -> bytes:
        with open(os.path.join(self.directory, name), "rb") as fh:
            return fh.read()

    def _info_of(self, name: str) -> Optional[HeadInfo]:
        m = _BASE_RE.match(name)
        if m:
            text = self._read(name).decode("utf-8")
            return HeadInfo(int(m.group(1)), fingerprint_text(text),
                            "base", name)
        m = _DELTA_RE.match(name)
        if m:
            rec = DeltaRecord.from_bytes(self._read(name), source=name)
            return HeadInfo(rec.round, rec.fp, "delta", name)
        return None

    def head(self) -> Optional[HeadInfo]:
        """Newest entry: the ``HEAD`` pointer when fresh, else the
        highest-round entry on disk (pointer-with-fallback, so a crash
        between entry write and repoint still resolves)."""
        ptr = os.path.join(self.directory, HEAD)
        try:
            with open(ptr) as fh:
                name = fh.read().strip()
            if name and os.path.exists(
                    os.path.join(self.directory, name)):
                info = self._info_of(name)
                if info is not None:
                    return info
        except (OSError, DeltaChainError):
            pass
        for rnd, kind, name in reversed(self._entries()):
            try:
                return self._info_of(name)
            except DeltaChainError:
                continue        # torn tail entry: fall back further
        return None

    def chain(self) -> Tuple[str, int, List[DeltaRecord]]:
        """(base_text, base_round, ordered records) — the full validated
        chain from the newest BASE to the head.  Raises
        :class:`DeltaChainError` on any gap, crc failure, or
        fingerprint mismatch."""
        entries = self._entries()
        bases = [e for e in entries if e[1] == "base"]
        if not bases:
            raise DeltaChainError(
                f"{self.directory}: journal has no BASE entry")
        base_round, _, base_name = bases[-1]
        base_text = self._read(base_name).decode("utf-8")
        fp = fingerprint_text(base_text)
        records: List[DeltaRecord] = []
        rnd = base_round
        for e_rnd, kind, name in entries:
            if kind != "delta" or e_rnd <= base_round:
                continue
            rec = DeltaRecord.from_bytes(self._read(name), source=name)
            if rec.base_round != rnd:
                raise DeltaChainError(
                    f"{name}: chain gap — record extends round "
                    f"{rec.base_round}, chain is at round {rnd}")
            if rec.parent_fp != fp:
                raise DeltaChainError(
                    f"{name}: fingerprint mismatch — record parent "
                    f"{rec.parent_fp[:12]}..., chain head {fp[:12]}...")
            records.append(rec)
            rnd, fp = rec.round, rec.fp
        return base_text, base_round, records

    def records_after(self, round: int) -> List[DeltaRecord]:
        """Validated chain records with ``round`` strictly past the
        given round (the fleet replay primitive)."""
        _, _, records = self.chain()
        return [r for r in records if r.round > round]

    def base_entry(self) -> Optional[Tuple[str, int]]:
        """(absolute path, round) of the newest BASE file — the
        full-reload anchor a subscriber that fell off the chain loads
        before replaying :meth:`records_after` forward."""
        bases = [e for e in self._entries() if e[1] == "base"]
        if not bases:
            return None
        rnd, _, name = bases[-1]
        return os.path.join(self.directory, name), rnd

    # -- write side ---------------------------------------------------------

    def write_base(self, model_text: str, round: int) -> str:
        """Anchor (or re-anchor) the chain with a full model text at
        ``round``; returns the base fingerprint."""
        with self._lock:
            os.makedirs(self.directory, exist_ok=True)
            name = _base_name(round)
            atomic_write_bytes(os.path.join(self.directory, name),
                               model_text.encode("utf-8"))
            atomic_write_text(os.path.join(self.directory, HEAD), name)
        return fingerprint_text(model_text)

    def append_delta(self, payload: str, round: int,
                     num_tree_per_iteration: int = 1) -> DeltaRecord:
        """Append the fragment for rounds ``(head, round]``.  The chain
        position and parent fingerprint come from the journal head, so
        concurrent/replayed writers cannot fork the chain silently."""
        with self._lock:
            h = self.head()
            if h is None:
                raise DeltaChainError(
                    f"{self.directory}: cannot append to an empty "
                    f"journal — write a BASE first")
            if round <= h.round:
                raise DeltaChainError(
                    f"{self.directory}: journal already at round "
                    f"{h.round}, refusing non-monotonic append to "
                    f"round {round}")
            rec = DeltaRecord(
                base_round=h.round, round=round, parent_fp=h.fp,
                fp=chain_fingerprint(h.fp, payload),
                num_tree_per_iteration=num_tree_per_iteration,
                payload=payload)
            name = _delta_name(round)
            atomic_write_bytes(os.path.join(self.directory, name),
                               rec.to_bytes())
            atomic_write_text(os.path.join(self.directory, HEAD), name)
        return rec

    def compact(self, model_text: str, round: int) -> str:
        """Fold the chain: write a full BASE at ``round`` and prune
        every entry it supersedes (older bases, deltas <= round).  A
        crash mid-prune leaves only redundant entries behind — the next
        :meth:`chain` still reads from the newest base."""
        with self._lock:
            os.makedirs(self.directory, exist_ok=True)
            name = _base_name(round)
            atomic_write_bytes(os.path.join(self.directory, name),
                               model_text.encode("utf-8"))
            atomic_write_text(os.path.join(self.directory, HEAD), name)
            for e_rnd, kind, e_name in self._entries():
                if e_name == name:
                    continue
                if kind == "base" and e_rnd <= round or \
                        kind == "delta" and e_rnd <= round:
                    try:
                        os.unlink(os.path.join(self.directory, e_name))
                    except OSError:
                        pass
        return fingerprint_text(model_text)

    def chain_length(self) -> int:
        """Deltas on top of the newest base (the compaction trigger)."""
        entries = self._entries()
        bases = [e for e in entries if e[1] == "base"]
        if not bases:
            return 0
        base_round = bases[-1][0]
        return sum(1 for e_rnd, kind, _ in entries
                   if kind == "delta" and e_rnd > base_round)
