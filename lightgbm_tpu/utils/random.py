"""Deterministic RNG helpers (reference include/LightGBM/utils/random.h —
a seeded LCG used for bagging/feature sampling).  Host-side sampling uses
numpy Generators seeded per (seed, iteration) so results are reproducible
regardless of call order; device-side sampling uses jax.random keys."""

from __future__ import annotations

import numpy as np


def host_rng(seed: int, stream: int = 0) -> np.random.Generator:
    return np.random.Generator(np.random.Philox(key=(seed & 0xFFFFFFFF) + (stream << 32)))


def sample_indices(n: int, k: int, seed: int, stream: int = 0) -> np.ndarray:
    """Sample k of n indices without replacement, sorted (reference
    Random::Sample used by bagging/feature_fraction)."""
    rng = host_rng(seed, stream)
    if k >= n:
        return np.arange(n, dtype=np.int32)
    return np.sort(rng.choice(n, size=k, replace=False)).astype(np.int32)
