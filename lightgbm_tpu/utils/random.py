"""Deterministic RNG helpers (reference include/LightGBM/utils/random.h —
a seeded LCG used for bagging/feature sampling).  Host-side sampling uses
numpy Generators seeded per (seed, iteration) so results are reproducible
regardless of call order; device-side sampling uses jax.random keys."""

from __future__ import annotations

import numpy as np


def host_rng(seed: int, stream: int = 0,
             model: int = 0) -> np.random.Generator:
    """Philox generator keyed on (seed, stream[, model]).

    ``model`` joins the key as an independent Philox key word so a
    multi-model training batch (lightgbm_tpu/multitrain/) can derive
    decorrelated per-model streams from one base seed as a PURE function
    of (seed, stream, model) — no sequential state.  ``model=0`` keys the
    generator exactly like the historical 1-word form (Philox pads the
    key with zero words), so every existing single-model stream — and a
    ``train_many`` batch of one — is bit-identical to before."""
    key = (seed & 0xFFFFFFFF) + (stream << 32)
    return np.random.Generator(np.random.Philox(
        key=key if model == 0 else (key, model)))


def model_stream_seed(seed: int, model: int) -> int:
    """Derive a per-model 32-bit seed from a base seed as a pure function
    of (seed, model) — used by ``train_many(replicas=M)`` to materialize
    per-model bagging/quantization seeds INTO the variant params, so the
    standalone counterpart ``train(params_m)`` reproduces model m
    bit-for-bit.  Model 0 keeps the base seed."""
    if model == 0:
        return int(seed)
    return int(host_rng(seed, stream=0x5EED, model=model)
               .integers(0, 1 << 31))


def sample_indices(n: int, k: int, seed: int, stream: int = 0) -> np.ndarray:
    """Sample k of n indices without replacement, sorted (reference
    Random::Sample used by bagging/feature_fraction)."""
    rng = host_rng(seed, stream)
    if k >= n:
        return np.arange(n, dtype=np.int32)
    return np.sort(rng.choice(n, size=k, replace=False)).astype(np.int32)


# Seeds whose derived streams are part of the training trajectory: every
# sampler above (and the jax.random fold_in sites in models/gbdt.py /
# boosting.py) keys its generator on (one of these seeds, iteration), so
# a checkpoint needs no opaque generator blobs — the seeds plus the
# iteration counter ARE the RNG state, and restoring them reproduces the
# bagging / feature-fraction / extra-trees / dropout / quantization
# streams bit-for-bit.
CHECKPOINT_SEED_KEYS = ("seed", "bagging_seed", "feature_fraction_seed",
                       "extra_seed", "drop_seed")


def rng_checkpoint_state(config) -> dict:
    """The RNG state a checkpoint must carry (see CHECKPOINT_SEED_KEYS).

    Checked — not merely recorded — on resume: a changed seed silently
    forks the sampling trajectory, so restore fails loudly instead."""
    return {k: int(getattr(config, k)) for k in CHECKPOINT_SEED_KEYS}
