"""Deterministic RNG helpers (reference include/LightGBM/utils/random.h —
a seeded LCG used for bagging/feature sampling).  Host-side sampling uses
numpy Generators seeded per (seed, iteration) so results are reproducible
regardless of call order; device-side sampling uses jax.random keys."""

from __future__ import annotations

import numpy as np


def host_rng(seed: int, stream: int = 0) -> np.random.Generator:
    return np.random.Generator(np.random.Philox(key=(seed & 0xFFFFFFFF) + (stream << 32)))


def sample_indices(n: int, k: int, seed: int, stream: int = 0) -> np.ndarray:
    """Sample k of n indices without replacement, sorted (reference
    Random::Sample used by bagging/feature_fraction)."""
    rng = host_rng(seed, stream)
    if k >= n:
        return np.arange(n, dtype=np.int32)
    return np.sort(rng.choice(n, size=k, replace=False)).astype(np.int32)


# Seeds whose derived streams are part of the training trajectory: every
# sampler above (and the jax.random fold_in sites in models/gbdt.py /
# boosting.py) keys its generator on (one of these seeds, iteration), so
# a checkpoint needs no opaque generator blobs — the seeds plus the
# iteration counter ARE the RNG state, and restoring them reproduces the
# bagging / feature-fraction / extra-trees / dropout / quantization
# streams bit-for-bit.
CHECKPOINT_SEED_KEYS = ("seed", "bagging_seed", "feature_fraction_seed",
                       "extra_seed", "drop_seed")


def rng_checkpoint_state(config) -> dict:
    """The RNG state a checkpoint must carry (see CHECKPOINT_SEED_KEYS).

    Checked — not merely recorded — on resume: a changed seed silently
    forks the sampling trajectory, so restore fails loudly instead."""
    return {k: int(getattr(config, k)) for k in CHECKPOINT_SEED_KEYS}
