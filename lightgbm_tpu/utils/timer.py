"""Aggregate wall-time tracing (reference include/LightGBM/utils/common.h:931
``Common::Timer`` + common.h:995 RAII ``FunctionTimer``; compiled in with
USE_TIMETAG).  Here always available, enabled via env LGBM_TPU_TIMETAG=1 or
``global_timer.enable()``; pairs with ``jax.profiler`` named scopes for
device-side traces.

Rebased onto the telemetry registry: every ``stop`` also lands in the
process-wide :class:`~lightgbm_tpu.telemetry.MetricsRegistry` as
``timetag_seconds_total{tag=...}`` / ``timetag_calls_total{tag=...}``,
so the ``/metrics`` endpoint and the exit report can never disagree.
``telemetry.span`` drives this timer when it is enabled, which makes
``LGBM_TPU_TIMETAG=1`` the zero-code compat shim for span timings."""

from __future__ import annotations

import atexit
import collections
import os
import time
from typing import Dict


class Timer:
    def __init__(self) -> None:
        self._acc: Dict[str, float] = collections.defaultdict(float)
        self._count: Dict[str, int] = collections.defaultdict(int)
        self._start: Dict[str, float] = {}
        self.enabled = os.environ.get("LGBM_TPU_TIMETAG", "0") == "1"
        self._reg_secs = None
        self._reg_calls = None

    def enable(self) -> None:
        self.enabled = True

    def start(self, name: str) -> None:
        if self.enabled:
            self._start[name] = time.perf_counter()

    def stop(self, name: str) -> None:
        if not self.enabled:
            return
        if name not in self._start:
            # a stop with no matching start is a probe bug; surface it
            # loudly under debug verbosity instead of passing silently
            from .log import LEVEL_DEBUG, get_verbosity
            if get_verbosity() >= LEVEL_DEBUG:
                raise RuntimeError(
                    f"Timer.stop({name!r}) without a matching start()")
            return
        dt = time.perf_counter() - self._start.pop(name)
        self._acc[name] += dt
        self._count[name] += 1
        self._publish(name, dt)

    def _publish(self, name: str, dt: float) -> None:
        if self._reg_secs is None:
            # deferred import: telemetry.trace imports this module
            from ..telemetry.metrics import default_registry
            reg = default_registry()
            self._reg_secs = reg.counter(
                "timetag_seconds_total",
                "accumulated wall time per timetag", labels=("tag",))
            self._reg_calls = reg.counter(
                "timetag_calls_total",
                "start/stop pairs per timetag", labels=("tag",))
        self._reg_secs.inc(dt, tag=name)
        self._reg_calls.inc(1, tag=name)

    def report(self) -> str:
        lines = [f"{name} = {secs:.6f}s (n={self._count[name]})"
                 for name, secs in sorted(self._acc.items())]
        return "\n".join(lines)

    def print_at_exit(self) -> None:
        if self.enabled and self._acc:
            # routed through the log sink so a registered callback
            # captures it, but NOT verbosity-filtered: the user enabled
            # the timetag explicitly (the reference prints timetags
            # unconditionally under USE_TIMETAG), and training configs
            # routinely set verbosity=-1
            from .log import _emit
            _emit("[LightGBM-TPU] [Info] time tags:\n" + self.report())


global_timer = Timer()
atexit.register(global_timer.print_at_exit)


class FunctionTimer:
    """``with FunctionTimer("name"):`` — RAII scope timer, optionally also
    emitting a jax.profiler trace annotation."""

    def __init__(self, name: str, use_jax_scope: bool = False) -> None:
        self.name = name
        self._scope = None
        if use_jax_scope:
            try:
                import jax.profiler
                self._scope = jax.profiler.TraceAnnotation(name)
            except Exception:
                self._scope = None

    def __enter__(self):
        global_timer.start(self.name)
        if self._scope is not None:
            self._scope.__enter__()
        return self

    def __exit__(self, *exc):
        if self._scope is not None:
            self._scope.__exit__(*exc)
        global_timer.stop(self.name)
        return False
