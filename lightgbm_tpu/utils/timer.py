"""Aggregate wall-time tracing (reference include/LightGBM/utils/common.h:931
``Common::Timer`` + common.h:995 RAII ``FunctionTimer``; compiled in with
USE_TIMETAG).  Here always available, enabled via env LGBM_TPU_TIMETAG=1 or
``global_timer.enable()``; pairs with ``jax.profiler`` named scopes for
device-side traces."""

from __future__ import annotations

import atexit
import collections
import os
import time
from typing import Dict


class Timer:
    def __init__(self) -> None:
        self._acc: Dict[str, float] = collections.defaultdict(float)
        self._count: Dict[str, int] = collections.defaultdict(int)
        self._start: Dict[str, float] = {}
        self.enabled = os.environ.get("LGBM_TPU_TIMETAG", "0") == "1"

    def enable(self) -> None:
        self.enabled = True

    def start(self, name: str) -> None:
        if self.enabled:
            self._start[name] = time.perf_counter()

    def stop(self, name: str) -> None:
        if self.enabled and name in self._start:
            self._acc[name] += time.perf_counter() - self._start.pop(name)
            self._count[name] += 1

    def report(self) -> str:
        lines = [f"{name} = {secs:.6f}s (n={self._count[name]})"
                 for name, secs in sorted(self._acc.items())]
        return "\n".join(lines)

    def print_at_exit(self) -> None:
        if self.enabled and self._acc:
            print("[LightGBM-TPU] time tags:\n" + self.report())


global_timer = Timer()
atexit.register(global_timer.print_at_exit)


class FunctionTimer:
    """``with FunctionTimer("name"):`` — RAII scope timer, optionally also
    emitting a jax.profiler trace annotation."""

    def __init__(self, name: str, use_jax_scope: bool = False) -> None:
        self.name = name
        self._scope = None
        if use_jax_scope:
            try:
                import jax.profiler
                self._scope = jax.profiler.TraceAnnotation(name)
            except Exception:
                self._scope = None

    def __enter__(self):
        global_timer.start(self.name)
        if self._scope is not None:
            self._scope.__enter__()
        return self

    def __exit__(self, *exc):
        if self._scope is not None:
            self._scope.__exit__(*exc)
        global_timer.stop(self.name)
        return False
