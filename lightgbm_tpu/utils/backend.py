"""Accelerator-backend probing that degrades to CPU instead of crashing.

The container registers the TPU PJRT plugin eagerly; when the device is
absent or the tunnel is down, the first ``jax.default_backend()`` call
raises ``RuntimeError: Unable to initialize backend ... UNAVAILABLE``.
Anything that merely ASKS which backend is active (bench harnesses, the
histogram autotune gate) must not die on that probe — it should fall back
to CPU and keep going.
"""

from __future__ import annotations

import jax

from .log import log_warning

_resolved: str | None = None
_fallback_reason: str | None = None


def fallback_reason() -> str | None:
    """Why the probe degraded to CPU, or None when the backend came up
    clean.  The serve tier's ``/healthz`` reports ``degraded`` while
    this is set — traffic is still served, but on the CPU fallback."""
    return _fallback_reason


def _reset_probe_for_tests() -> None:
    """Forget the cached probe result (chaos tests re-probe under an
    armed device_loss fault)."""
    global _resolved, _fallback_reason
    _resolved = None
    _fallback_reason = None


def default_backend() -> str:
    """``jax.default_backend()`` with CPU fallback.

    On the first probe failure the platform is pinned to CPU (legal while
    no client exists — the failed init leaves none) and the warning names
    the broken plugin.  The result is cached: the backend cannot change
    within a process once a client is live.
    """
    global _resolved, _fallback_reason
    if _resolved is not None:
        return _resolved
    try:
        # chaos layer: an armed device_loss fault makes the probe behave
        # exactly like a lost accelerator (resilience/faults.py)
        from ..resilience.faults import faults
        faults.check_device_probe()
        _resolved = jax.default_backend()
    except RuntimeError as exc:
        _fallback_reason = str(exc)
        log_warning(f"accelerator backend unavailable ({exc}); "
                    "falling back to CPU")
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass  # a client appeared concurrently; use whatever it is
        try:
            _resolved = jax.default_backend()
        except RuntimeError:
            # even the pinned-CPU retry failed (a half-initialized plugin
            # client won the race).  Callers only branch on "tpu" vs
            # not-"tpu" — report cpu so backend SNIFFING never crashes;
            # actual device work will surface the real error.
            _resolved = "cpu"
    return _resolved
