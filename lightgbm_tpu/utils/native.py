"""Native host-runtime loader: compiles + loads the C++ helpers in
``native/`` on first use (ctypes ABI; reference's ingest hot loops are C++
too — src/io/bin.cpp / dense_bin.hpp).  Falls back to numpy silently when
no compiler is available, so the framework stays pure-Python-runnable."""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native")


def _n_threads() -> int:
    return max(1, min(os.cpu_count() or 1, 32))


def _build_and_load() -> Optional[ctypes.CDLL]:
    src = os.path.join(_NATIVE_DIR, "binning.cc")
    if not os.path.exists(src):
        return None
    cache = os.path.join(tempfile.gettempdir(),
                         f"lgbm_tpu_native_{os.getuid()}")
    os.makedirs(cache, exist_ok=True)
    lib_path = os.path.join(cache, "libbinning.so")
    if (not os.path.exists(lib_path) or
            os.path.getmtime(lib_path) < os.path.getmtime(src)):
        tmp = f"{lib_path}.{os.getpid()}.tmp"  # per-pid: no build races
        try:
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
                 src, "-o", tmp],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, lib_path)
        except Exception:
            return None
    try:
        lib = ctypes.CDLL(lib_path)
    except OSError:
        return None
    lib.bin_numerical.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double), ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32]
    lib.bin_matrix_f64.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32]
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if not _TRIED:
        _TRIED = True
        _LIB = _build_and_load()
    return _LIB


def build_capi_shim() -> Optional[str]:
    """Compile the native ``LGBM_*`` ABI shim (native/capi_shim.cc) and
    return the shared-library path, or None if the toolchain/headers are
    unavailable.  The shim exports the reference's out-pointer calling
    convention (c_api.h) as real C symbols backed by the embedded
    interpreter; dlopen it from C/C++/ctypes and call LGBM_* directly.
    """
    import sysconfig
    src = os.path.join(_NATIVE_DIR, "capi_shim.cc")
    if not os.path.exists(src):
        return None
    cache = os.path.join(tempfile.gettempdir(),
                         f"lgbm_tpu_native_{os.getuid()}")
    os.makedirs(cache, exist_ok=True)
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or \
        sysconfig.get_config_var("VERSION")
    # python version in the name: a shim linked against another
    # libpython must never be reused after an interpreter upgrade
    lib_path = os.path.join(cache, f"liblightgbm_tpu_capi-py{ver}.so")
    if (os.path.exists(lib_path) and
            os.path.getmtime(lib_path) >= os.path.getmtime(src)):
        return lib_path
    tmp = f"{lib_path}.{os.getpid()}.tmp"  # per-pid: no build races
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src,
           f"-I{inc}", "-o", tmp]
    if libdir:
        cmd += [f"-L{libdir}", f"-Wl,-rpath,{libdir}"]
    cmd += [f"-lpython{ver}"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=180)
        os.replace(tmp, lib_path)
    except Exception:
        return None
    return lib_path


def _ptr(a: np.ndarray, ct):
    return a.ctypes.data_as(ctypes.POINTER(ct))


def bin_numerical(values: np.ndarray, uppers: np.ndarray, num_bin: int,
                  missing_nan: bool) -> Optional[np.ndarray]:
    """Threaded value->bin for one numerical column; None -> use numpy."""
    lib = get_lib()
    if lib is None or len(values) < (1 << 16):
        return None
    vals = np.ascontiguousarray(values, np.float64)
    ub = np.ascontiguousarray(uppers, np.float64)
    out = np.empty(len(vals), np.uint8)
    lib.bin_numerical(_ptr(vals, ctypes.c_double), len(vals),
                      _ptr(ub, ctypes.c_double), len(ub), int(num_bin),
                      1 if missing_nan else 0,
                      _ptr(out, ctypes.c_uint8), _n_threads())
    return out


def bin_matrix_numerical(X: np.ndarray, uppers_list, num_bins, missing_nan
                         ) -> Optional[np.ndarray]:
    """Threaded whole-matrix binning (all columns NUMERICAL with <=256
    bins); None -> use the per-column python path."""
    lib = get_lib()
    if lib is None or X.shape[0] * X.shape[1] < (1 << 18):
        return None
    n, f = X.shape
    Xc = np.ascontiguousarray(X, np.float64)
    uppers_flat = np.ascontiguousarray(np.concatenate(uppers_list),
                                       np.float64)
    offsets = np.zeros(f + 1, np.int64)
    offsets[1:] = np.cumsum([len(u) for u in uppers_list])
    nb = np.ascontiguousarray(num_bins, np.int32)
    mn = np.ascontiguousarray(missing_nan, np.int32)
    out = np.empty((n, f), np.uint8)
    lib.bin_matrix_f64(_ptr(Xc, ctypes.c_double), n, f,
                       _ptr(uppers_flat, ctypes.c_double),
                       _ptr(offsets, ctypes.c_int64),
                       _ptr(nb, ctypes.c_int32), _ptr(mn, ctypes.c_int32),
                       _ptr(out, ctypes.c_uint8), _n_threads())
    return out
