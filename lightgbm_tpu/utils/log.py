"""Logging with levels and a redirectable callback
(reference include/LightGBM/utils/log.h:71 ``LogLevel``/``Log``; the
callback redirect is what the reference Python package uses to route C++ log
lines to Python, log.h:90 ``ResetCallBack``)."""

from __future__ import annotations

import sys
import threading
from typing import Callable, Optional

LEVEL_FATAL = -1
LEVEL_WARNING = 0
LEVEL_INFO = 1
LEVEL_DEBUG = 2

_verbosity = LEVEL_INFO
_callback: Optional[Callable[[str], None]] = None
# serializes sink swaps against emission so a message never lands on a
# half-replaced callback and concurrent writers can't interleave lines;
# reentrant so a callback may itself log or swap the sink
_emit_lock = threading.RLock()


def set_verbosity(level: int) -> None:
    global _verbosity
    _verbosity = level


def get_verbosity() -> int:
    return _verbosity


def register_log_callback(cb: Optional[Callable[[str], None]]) -> None:
    """Reference c_api.h:54 LGBM_RegisterLogCallback."""
    global _callback
    with _emit_lock:
        _callback = cb


def _emit(msg: str) -> None:
    with _emit_lock:
        if _callback is not None:
            _callback(msg + "\n")
        else:
            sys.stdout.write(msg + "\n")


def log_debug(msg: str) -> None:
    if _verbosity >= LEVEL_DEBUG:
        _emit(f"[LightGBM-TPU] [Debug] {msg}")


def log_info(msg: str) -> None:
    if _verbosity >= LEVEL_INFO:
        _emit(f"[LightGBM-TPU] [Info] {msg}")


def log_warning(msg: str) -> None:
    if _verbosity >= LEVEL_WARNING:
        _emit(f"[LightGBM-TPU] [Warning] {msg}")


class LightGBMError(Exception):
    pass


def log_fatal(msg: str) -> None:
    raise LightGBMError(msg)
