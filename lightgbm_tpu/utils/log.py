"""Logging with levels and a redirectable callback
(reference include/LightGBM/utils/log.h:71 ``LogLevel``/``Log``; the
callback redirect is what the reference Python package uses to route C++ log
lines to Python, log.h:90 ``ResetCallBack``)."""

from __future__ import annotations

import sys
import threading
from typing import Callable, Optional

_state = threading.local()

LEVEL_FATAL = -1
LEVEL_WARNING = 0
LEVEL_INFO = 1
LEVEL_DEBUG = 2

_verbosity = LEVEL_INFO
_callback: Optional[Callable[[str], None]] = None


def set_verbosity(level: int) -> None:
    global _verbosity
    _verbosity = level


def register_log_callback(cb: Optional[Callable[[str], None]]) -> None:
    """Reference c_api.h:54 LGBM_RegisterLogCallback."""
    global _callback
    _callback = cb


def _emit(msg: str) -> None:
    if _callback is not None:
        _callback(msg + "\n")
    else:
        sys.stdout.write(msg + "\n")


def log_debug(msg: str) -> None:
    if _verbosity >= LEVEL_DEBUG:
        _emit(f"[LightGBM-TPU] [Debug] {msg}")


def log_info(msg: str) -> None:
    if _verbosity >= LEVEL_INFO:
        _emit(f"[LightGBM-TPU] [Info] {msg}")


def log_warning(msg: str) -> None:
    if _verbosity >= LEVEL_WARNING:
        _emit(f"[LightGBM-TPU] [Warning] {msg}")


class LightGBMError(Exception):
    pass


def log_fatal(msg: str) -> None:
    raise LightGBMError(msg)
