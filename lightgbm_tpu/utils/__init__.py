from .log import (log_debug, log_info, log_warning, log_fatal,
                  register_log_callback, set_verbosity)
from .timer import global_timer, FunctionTimer
