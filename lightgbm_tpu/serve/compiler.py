"""Inference compiler: lower a trained ensemble into one fused dense
MXU program for serving.

The compile step (:func:`compile_ensemble`) takes the model's trees and
produces a :class:`DenseExecutable` — device-resident lowered tables
(:mod:`..models.dense_predict`) plus jitted loop-free prediction
entries, optionally pjit-sharded over the tree axis for ensembles too
wide for one device.  ``CompiledPredictor`` and ``Booster.predict``
both route through it behind ``tpu_predict_compiler=dense|walk|auto``:

* ``dense`` — force the fused program; raise if the ensemble cannot
  lower (a table budget would blow);
* ``walk``  — keep the sequential per-tree walk;
* ``auto``  — dense whenever the ensemble lowers AND the backend
  profits.  On the MXU the dense formulation is the measured ~70x
  serving win (PERF.md round 4: 26 ms/tree/1M rows vs ~1.8 s for the
  gather walk); on CPU/interpret backends gathers are cheap and matmuls
  are not, so a host cost model keeps the walk where it measures faster
  — and RECORDS WHY (the ``serve_compiler_fallback`` telemetry counter
  + ``CompiledPredictor.info()``), fixing the silent categorical
  fallback this compiler exists to kill.

Program contracts (machine-checked by the ``serve_dense`` lint config):
the ``serve/dense_predict`` MemoryBudget bounds the per-device peak of
one bucket program, and the ``serve/dense_predict/score_psum``
collective contract pins the sharded program to exactly one psum of the
(bucket, num_class) partial scores.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..analysis.contracts import collective_contract, memory_budget, \
    world_size
from ..models.dense_predict import (DenseArrays, DenseLoweringError,
                                    DenseMeta, dense_predict_leaf,
                                    dense_predict_raw, dense_table_bytes,
                                    lower_ensemble, make_sharded_predict,
                                    stack_dense_arrays, stacked_predict_raw)
from ..models.tree import SHAPE_BUCKETS, TreeBatch
from ..telemetry.metrics import default_registry
from ..utils.backend import default_backend

__all__ = ["DenseExecutable", "StackedExecutable", "compile_ensemble",
           "DenseLoweringError", "dense_cost_model", "fallback_counts",
           "FALLBACK_COUNTER"]

# ---------------------------------------------------------------------------
# program contracts — declared next to the code they constrain
# ---------------------------------------------------------------------------

collective_contract(
    "serve/dense_predict/score_psum", "psum",
    max_count=1,
    max_bytes_per_op=lambda ctx: 4 * int(ctx.get("bucket", 4096)) *
    max(1, int(ctx.get("num_class", 1))),
    note="ONE psum of the per-shard (bucket, num_class) partial scores "
         "— the whole collective cost of tree-sharded dense serving")


def dense_predict_hbm_bytes(ctx):
    """Per-device HBM curve of one fused dense bucket program.

    Dominated by the (bucket, T/W * Nn) condition matrix and the
    (T/W, bucket, L) count/hit blocks (two resident at the peak), plus
    the lowered model tables (path matrices, bitset table, linear
    tables) and the request block."""
    n = int(ctx.get("bucket", max(SHAPE_BUCKETS)))
    t = -(-int(ctx.get("trees", 64)) // world_size(ctx))
    leaves = int(ctx.get("leaves", 64))
    nn = max(leaves - 1, 1)
    f = int(ctx.get("features", 32))
    cat_cols = int(ctx.get("cat_cols", 0))        # Fc * C
    cat_nodes = int(ctx.get("cat_nodes", 0))
    lin = 2 * 4 * t * leaves * f if ctx.get("has_linear") else 0
    rows = n * (3 * 4 * t * nn            # P / isn / dec condition blocks
                + 3 * 4 * t * leaves      # S + hit + value blocks
                + 4 * f + 4 * cat_cols + 4 * (cat_nodes + 1))
    tables = t * nn * (leaves + 16) + 4 * cat_cols * max(cat_nodes, 1) + lin
    return rows + tables + (8 << 20)


memory_budget("serve/dense_predict", ("serve_dense",),
              dense_predict_hbm_bytes,
              note="condition matrix + count/hit blocks + lowered tables")


# ---------------------------------------------------------------------------
# fallback telemetry: never again a silent 70x-slower path
# ---------------------------------------------------------------------------

FALLBACK_COUNTER = "serve_compiler_fallback"
_fb_lock = threading.Lock()
_fb_counts: Dict[str, int] = {}

# Fallback-budget objective, declared next to the counters it reads.
# ``serve_compiler_fallback`` fires once per COMPILE, so a ratio over
# request traffic would be inert; the SLO instead reads
# ``serve_fallback_batches_total`` — bumped by the predictor on every
# device call served by a fallback-built walk — against total batches.
# The bad set is the ``*_budget`` reasons (a table blowing its budget
# is the silent-70x-regression class); ``cpu_cost_model`` and
# ``forced_walk`` are policy, not regressions, and stay outside it.
from ..telemetry.slo import register_metric_ensurer, slo as _slo  # noqa: E402

FALLBACK_BATCHES = "serve_fallback_batches_total"

_slo("serve/compiler_fallback_rate", metric=FALLBACK_BATCHES,
     total_metric="serve_batches_total", kind="ratio", target=0.99,
     bad_labels={"reason": "*_budget"}, min_events=50,
     note="share of device batches served by budget-blown walk "
          "fallbacks")


def note_fallback_batch(reason: str, model: str) -> None:
    """One dispatched batch served by a fallback-built walk predictor
    (serve/predictor.py calls this per device call, so the fallback
    rate is measured in traffic, not in compiles)."""
    default_registry().counter(
        FALLBACK_BATCHES,
        "device batches served by a dense-compiler fallback, by reason",
        labels=("reason", "model")).inc(1, reason=reason,
                                        model=model or "-")


@register_metric_ensurer
def _ensure_fallback_metric(reg) -> None:
    reg.counter(FALLBACK_COUNTER,
                "auto-mode dense-compiler fallbacks to the sequential "
                "walk, by reason", labels=("reason", "model"))
    reg.counter(FALLBACK_BATCHES,
                "device batches served by a dense-compiler fallback, "
                "by reason", labels=("reason", "model"))


def _note_fallback(reason: str, model: str = "") -> None:
    with _fb_lock:
        _fb_counts[reason] = _fb_counts.get(reason, 0) + 1
    default_registry().counter(
        FALLBACK_COUNTER,
        "auto-mode dense-compiler fallbacks to the sequential walk, "
        "by reason", labels=("reason", "model")).inc(
        reason=reason, model=model or "-")


def fallback_counts() -> Dict[str, int]:
    """Process-wide auto-fallback tally by reason (mirrors the labeled
    ``serve_compiler_fallback`` counter series)."""
    with _fb_lock:
        return dict(_fb_counts)


# ---------------------------------------------------------------------------
# backend cost model for auto mode
# ---------------------------------------------------------------------------

def dense_cost_model(num_trees: int, max_leaves: int, max_depth: int,
                     backend: Optional[str] = None) -> bool:
    """True when the fused dense program should beat the sequential
    walk on this backend.

    On TPU the answer is always yes (per-row gathers are the slow
    primitive; PERF.md round 4 measured the 70x).  On CPU/interpret the
    walk's gathers run near memory speed while the dense program pays
    O(T * Nn * L) matmul work per row, so dense only wins when the
    per-row dense work is small next to the walk's sequential
    depth-loop cost (measured on the 1-core CI env, PERF.md round 13)."""
    backend = backend if backend is not None else default_backend()
    if backend == "tpu":
        return True
    nn = max(max_leaves - 1, 1)
    dense_units = num_trees * nn * (2 + max_leaves)
    walk_units = num_trees * (max_depth + 1) * 24
    return dense_units < walk_units


def _max_depth(batch: TreeBatch) -> int:
    """Deepest real leaf across the ensemble (host-side, from the
    path-length matrices TreeBatch already built)."""
    pt = np.asarray(batch.plen_total)
    real = pt < 1e8
    return int(pt[real].max()) if real.any() else 0


# ---------------------------------------------------------------------------
# the executable
# ---------------------------------------------------------------------------

class DenseExecutable:
    """One compiled-dense model version: device-resident lowered tables
    plus the jitted (optionally tree-sharded) prediction entries.

    Immutable once built — hot-swap replaces the whole object, so there
    is no window where path matrices and leaf tables disagree.

    ``real_trees`` tracks the live tree count separately from
    ``meta.num_trees`` (the count at the ORIGINAL lowering): the jitted
    program never reads the count — shard-padding trees are inert purely
    through their array values — so :meth:`extended` can splice appended
    trees into padding rows while keeping ``meta`` (and therefore the
    jit cache signature) bit-identical: zero recompiles until the
    padding envelope is exhausted."""

    def __init__(self, arrays: DenseArrays, meta: DenseMeta,
                 shard: int = 0) -> None:
        self.meta = meta
        self.real_trees = meta.num_trees
        self.shard = 0
        self._sharded_fn: Optional[Any] = None
        if shard and shard > 1:
            ndev = len(jax.devices())
            k = min(shard, ndev)
            if k > 1 and arrays.path_dir.shape[0] % k == 0:
                from ..parallel.mesh import get_mesh
                self.shard = k
                self._mesh = get_mesh(k, "trees")
                self._sharded_fn = make_sharded_predict(
                    arrays, meta, self._mesh)
        # ONE device_put pins every table; requests then ship only rows.
        # The sharded program's tables commit with the SAME sharding its
        # in_specs demand, so no per-request redistribution happens.
        if self.shard:
            from jax.sharding import NamedSharding, PartitionSpec
            from ..models.dense_predict import _shard_specs
            shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(self._mesh, s),
                _shard_specs(arrays, "trees"),
                is_leaf=lambda x: isinstance(x, PartitionSpec))
            self.arrays = jax.device_put(arrays, shardings)
        else:
            self.arrays = jax.device_put(arrays)
        self.table_bytes = dense_table_bytes(arrays)

    @property
    def signature(self) -> tuple:
        """Shape/dtype signature — what XLA's jit cache keys on besides
        the row bucket (drives the /stats recompile counter)."""
        leaves = jax.tree_util.tree_leaves(self.arrays)
        return ("dense", self.meta, self.shard,
                tuple((a.shape, str(a.dtype)) for a in leaves))

    def predict_raw(self, Xp) -> Any:
        """(N, num_class) raw scores for a bucket-padded row block."""
        if self._sharded_fn is not None:
            return self._sharded_fn(Xp, self.arrays)
        return dense_predict_raw(Xp, self.arrays, self.meta)

    def predict_leaf(self, Xp) -> Any:
        """(N, num_trees) leaf indices (shard-padding trees sliced)."""
        out = dense_predict_leaf(Xp, self.arrays, self.meta)
        return out[:, :self.real_trees]

    @property
    def capacity(self) -> int:
        """Tree-axis rows in the lowered tables (real + shard padding):
        the append envelope for :meth:`extended`."""
        return int(self.arrays.path_dir.shape[0])

    def extended(self, new_trees: List[Any], num_features: int
                 ) -> Optional["DenseExecutable"]:
        """Splice ``new_trees`` into this executable's padding rows.

        Returns a NEW executable sharing ``meta`` (identical signature,
        so the jit cache is hit — zero recompiles), or ``None`` when an
        in-place extension cannot stay exact: the padding envelope is
        exhausted, the new trees are wider/deeper than the lowered
        tables, or they need table kinds (categorical splits, linear
        leaves) the original lowering did not build.  ``None`` means
        "rebuild from scratch", never a silent approximation."""
        n = len(new_trees)
        if n == 0:
            return self
        r = self.real_trees
        if r + n > self.capacity:
            return None
        meta = self.meta
        class_ids = [(r + i) % meta.num_class for i in range(n)]
        try:
            na, nm = lower_ensemble(
                new_trees, meta.num_class, num_features, class_ids,
                leaf_bits=meta.leaf_bits, mxu=meta.mxu, shard=1)
        except DenseLoweringError:
            return None
        if nm.has_cat:
            # splicing into the bitset-membership table would have to
            # regrow (Fc*C, NCp) — a shape change, i.e. a recompile
            return None
        if nm.has_linear and not meta.has_linear:
            return None
        host = jax.device_get(self.arrays)
        Nn = host.split_feature.shape[1]
        L = host.qthresh.shape[1]
        nNn = int(na.split_feature.shape[1])
        nL = int(na.qthresh.shape[1])
        if nNn > Nn or nL > L:
            return None

        def _pad(a, shape, fill=0.0):
            out = np.full(shape, fill, dtype=np.asarray(a).dtype)
            out[tuple(slice(0, s) for s in np.asarray(a).shape)] = \
                np.asarray(a)
            return out

        vals = {k: np.array(v, copy=True) if v is not None else None
                for k, v in host._asdict().items()}
        vals["split_feature"][r:r + n] = _pad(na.split_feature, (n, Nn))
        vals["threshold"][r:r + n] = _pad(na.threshold, (n, Nn))
        vals["dleft"][r:r + n] = _pad(na.dleft, (n, Nn))
        vals["miss_nan"][r:r + n] = _pad(na.miss_nan, (n, Nn))
        vals["is_cat"][r:r + n] = _pad(na.is_cat, (n, Nn))
        vals["path_dir"][r:r + n] = _pad(na.path_dir, (n, Nn, L))
        # unreal leaf slots keep the 1e9 sentinel so they can never hit
        vals["qthresh"][r:r + n] = _pad(na.qthresh, (n, L),
                                        fill=np.float32(1e9))
        vals["leaf_codes"][r:r + n] = _pad(na.leaf_codes, (n, L))
        vals["leaf_scale"][r:r + n] = np.asarray(na.leaf_scale)
        vals["class_onehot"][r:r + n] = np.asarray(na.class_onehot)
        if meta.has_cat:
            vals["node_cat_slot"][r:r + n] = 0
        if meta.has_linear:
            if nm.has_linear:
                F = vals["lin_w"].shape[2]
                vals["lin_w"][r:r + n] = _pad(na.lin_w, (n, L, F))
                vals["lin_mask"][r:r + n] = _pad(na.lin_mask, (n, L, F))
                vals["lin_const"][r:r + n] = _pad(na.lin_const, (n, L))
                vals["lin_flag"][r:r + n] = np.asarray(na.lin_flag)
            else:
                vals["lin_w"][r:r + n] = 0.0
                vals["lin_mask"][r:r + n] = 0.0
                vals["lin_const"][r:r + n] = 0.0
                vals["lin_flag"][r:r + n] = 0.0
        ex = DenseExecutable(DenseArrays(**vals), meta, shard=self.shard)
        ex.real_trees = r + n
        return ex

    def info(self) -> Dict[str, Any]:
        return {
            "mode": "dense",
            "num_trees": self.real_trees,
            "num_class": self.meta.num_class,
            "capacity": self.capacity,
            "has_cat": self.meta.has_cat,
            "has_linear": self.meta.has_linear,
            "leaf_bits": self.meta.leaf_bits,
            "mxu": self.meta.mxu,
            "shard": self.shard,
            "table_bytes": self.table_bytes,
        }


class StackedExecutable:
    """M same-signature :class:`DenseExecutable`s fused on a leading
    model axis — ONE MXU launch serves every member's micro-batch.

    Built by the zoo (serve/zoo.py) from unsharded dense executables
    whose :attr:`DenseExecutable.signature` match exactly: same meta
    (tree/node/leaf envelope, leaf_bits, MXU flag), same shard spec,
    same table shapes/dtypes.  The stacked tables are (M, T, ...);
    ``predict_raw`` takes an (M, N, F) lane-block and returns (M, N, K)
    — each lane bitwise identical to the member's solo dispatch.

    Immutable like its members: membership changes rebuild the stack
    (cheap — one jnp.stack of resident device arrays, no recompile as
    long as M is unchanged), and a delta-extended member splices ONLY
    its lane via :meth:`splice` (same shapes, so the jit cache is hit —
    zero recompiles in-envelope)."""

    def __init__(self, names: List[str],
                 exes: List["DenseExecutable"]) -> None:
        if len(names) != len(exes) or not exes:
            raise ValueError("stack needs one name per executable")
        sig = exes[0].signature
        for e in exes[1:]:
            if e.signature != sig:
                raise ValueError("stack members must share one signature")
        if exes[0].shard:
            raise ValueError("sharded executables ride their own "
                             "shard_map entry; stacks take unsharded ones")
        self.names = tuple(names)
        self.meta = exes[0].meta
        self.member_sig = sig
        self.stacked = stack_dense_arrays([e.arrays for e in exes])

    @property
    def width(self) -> int:
        return len(self.names)

    @property
    def signature(self) -> tuple:
        """The stacked program's jit-cache key: member signature plus
        the model-axis width (a different M is a different program)."""
        return ("zoo_stack", self.width, self.member_sig)

    def lane(self, name: str) -> int:
        return self.names.index(name)

    def predict_raw(self, Xs) -> Any:
        """(M, N, K) raw scores for an (M, N, F) lane-block — one fused
        launch for the whole stack."""
        return stacked_predict_raw(Xs, self.stacked, self.meta)

    def splice(self, name: str, exe: "DenseExecutable"
               ) -> "StackedExecutable":
        """A NEW stack with ``name``'s lane replaced by ``exe``'s tables
        (a delta-extended member inside the shard-padding envelope:
        same signature, so every other lane's rows are untouched and
        the stacked program's jit cache is hit — zero recompiles)."""
        if exe.signature != self.member_sig:
            raise ValueError("spliced member changed signature; "
                             "rebuild the stack")
        i = self.lane(name)
        out = StackedExecutable.__new__(StackedExecutable)
        out.names = self.names
        out.meta = self.meta
        out.member_sig = self.member_sig
        out.stacked = jax.tree_util.tree_map(
            lambda S, a: S.at[i].set(a), self.stacked, exe.arrays)
        return out

    def info(self) -> Dict[str, Any]:
        return {"mode": "zoo_stack", "width": self.width,
                "members": list(self.names),
                "num_class": self.meta.num_class,
                "leaf_bits": self.meta.leaf_bits}


def compile_ensemble(trees: List[Any], num_class: int, num_features: int,
                     class_ids: Optional[List[int]] = None, *,
                     mode: str = "auto", leaf_bits: int = 0,
                     shard: int = 0, batch: Optional[TreeBatch] = None,
                     model_label: str = ""
                     ) -> Tuple[Optional[DenseExecutable], Optional[str]]:
    """Compile ``trees`` into a :class:`DenseExecutable`, or decide the
    walk and say why.

    Returns ``(executable, None)`` on a dense lowering and
    ``(None, reason)`` on the walk path.  ``mode='dense'`` raises
    :class:`DenseLoweringError` instead of falling back; auto-mode
    fallbacks bump the ``serve_compiler_fallback{reason}`` counter."""
    if mode not in ("auto", "dense", "walk"):
        raise ValueError(f"tpu_predict_compiler must be auto|dense|walk, "
                         f"got '{mode}'")
    if mode == "walk":
        return None, "forced_walk"
    if not trees:
        if mode == "dense":
            raise DenseLoweringError("no_trees")
        _note_fallback("no_trees", model_label)
        return None, "no_trees"
    b = batch if batch is not None else TreeBatch(trees)
    backend = default_backend()
    if mode == "auto" and not dense_cost_model(
            b.num_trees, b.max_leaves, _max_depth(b), backend):
        _note_fallback("cpu_cost_model", model_label)
        return None, "cpu_cost_model"
    try:
        arrays, meta = lower_ensemble(
            trees, num_class, num_features, class_ids,
            leaf_bits=leaf_bits, mxu=(backend == "tpu"),
            shard=max(1, shard), batch=b)
    except DenseLoweringError as exc:
        if mode == "dense":
            raise
        _note_fallback(exc.reason, model_label)
        return None, exc.reason
    return DenseExecutable(arrays, meta, shard=shard), None
