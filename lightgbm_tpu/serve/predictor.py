"""CompiledPredictor: a trained model held warm for low-latency inference.

Wraps a ``GBDT``/``Booster``/model file as device-resident ensemble
arrays and drives the pure jitted entry
:func:`lightgbm_tpu.models.tree.predict_raw_ensemble`.  Request rows pad
up a fixed shape-bucket ladder (``SHAPE_BUCKETS``) so arbitrary batch
sizes hit a handful of compiled programs; ``warmup()`` compiles every
bucket ahead of the first request.

Compile-cache sharing: the jitted entry takes the model arrays as
ARGUMENTS, so XLA keys its cache on shapes/dtypes only — every model
with the same shape signature (tree count, max leaves, feature count,
walk kind) reuses one compiled program per bucket.  The process-wide
``_COMPILE_KEYS`` set mirrors that cache to drive the ``/stats``
recompile counter: a (signature, bucket) pair counts as a recompile the
first time any predictor in the process dispatches it.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional, Tuple

import jax
import numpy as np

from ..analysis.contracts import memory_budget
from ..models.tree import (SHAPE_BUCKETS, bucket_rows, ensemble_serve_fields,
                           pad_rows, predict_raw_ensemble)
from .compiler import DenseExecutable, compile_ensemble
from .stats import ModelStats

__all__ = ["CompiledPredictor", "SHAPE_BUCKETS"]


def serve_ladder_hbm_bytes(ctx):
    """Per-device HBM curve of one serve-bucket program (lint-mem
    enforced): the padded request block dominates — the walk kernels
    hold ~3 row-block-sized temporaries (feature gathers, comparisons,
    per-tree leaf one-hots) next to the input — plus the resident
    ensemble arrays (~16 B per tree-leaf across the serve fields)."""
    bucket = int(ctx.get("bucket", max(SHAPE_BUCKETS)))
    f = int(ctx["features"])
    it = int(ctx.get("itemsize", 4))
    trees = int(ctx.get("trees", 1000))
    leaves = int(ctx.get("leaves", 255))
    request = 4 * bucket * f * it
    model = 16 * trees * leaves
    return request + model + (1 << 20)


memory_budget("serve/bucket_ladder", ("serve",), serve_ladder_hbm_bytes,
              note="4 request-block temporaries + resident ensemble")

# (shape-signature, bucket) pairs that have already been dispatched — the
# process-wide mirror of XLA's jit cache for predict_raw_ensemble
_COMPILE_KEYS: set = set()
_COMPILE_LOCK = threading.Lock()


def _note_dispatch(key) -> bool:
    """True when ``key`` is new to the process (an XLA trace happens)."""
    with _COMPILE_LOCK:
        if key in _COMPILE_KEYS:
            return False
        _COMPILE_KEYS.add(key)
        return True


def release_compile_keys(sig) -> int:
    """Drop every ``(sig, bucket)`` entry of one shape signature from the
    dispatch mirror.  The registry calls this when the LAST model of a
    shape is evicted: the mirror must shrink with the cache it mirrors or
    zoo churn ratchets it without bound.  Returns entries removed."""
    with _COMPILE_LOCK:
        doomed = [k for k in _COMPILE_KEYS
                  if isinstance(k, tuple) and len(k) == 2 and k[0] == sig]
        for k in doomed:
            _COMPILE_KEYS.discard(k)
    return len(doomed)


def compile_key_count() -> int:
    """Current size of the process-wide dispatch mirror (the churn
    regression test bounds this)."""
    with _COMPILE_LOCK:
        return len(_COMPILE_KEYS)


def _resolve_gbdt(source):
    """Accept a Booster, a GBDT, a model file path, or a model string."""
    from ..basic import Booster
    from ..models.gbdt import GBDT
    if isinstance(source, Booster):
        return source._gbdt
    if isinstance(source, GBDT):
        return source
    if isinstance(source, str):
        if "\n" in source:  # model TEXT always spans lines
            return Booster(model_str=source)._gbdt
        if not os.path.exists(source):
            raise FileNotFoundError(f"no such model file: {source}")
        return Booster(model_file=source)._gbdt
    raise TypeError(f"cannot build a predictor from {type(source).__name__}")


class CompiledPredictor:
    """Shape-bucketed compiled inference handle for one model version.

    Immutable once built (hot-swap replaces the whole object), so reads
    need no lock: concurrent ``predict`` calls share the device arrays
    and the jit cache.
    """

    def __init__(self, source, num_iteration: Optional[int] = None,
                 buckets: Tuple[int, ...] = SHAPE_BUCKETS,
                 stats: Optional[ModelStats] = None,
                 compiler: Optional[str] = None,
                 leaf_bits: Optional[int] = None,
                 shard: Optional[int] = None,
                 explain_compiler: Optional[str] = None) -> None:
        gbdt = _resolve_gbdt(source)
        self._gbdt = gbdt          # retained for delta appends (extended)
        self.buckets = tuple(sorted(buckets))
        self.stats = stats if stats is not None else ModelStats()
        self.objective = gbdt.objective
        self.num_class = k = gbdt.num_tree_per_iteration
        self.num_features = gbdt.feature_mapping()[1]
        models = gbdt.models
        self.num_trees = len(models) if num_iteration is None else min(
            len(models), num_iteration * k)
        # RF / average_output models predict the MEAN of tree outputs;
        # the divisor is the FULL model count even under num_iteration
        # truncation (RF.predict divides by len(models)//k regardless)
        self._avg_div = (max(1, len(models) // k)
                         if getattr(gbdt, "name", "gbdt") == "rf" else 1)
        ts = gbdt.train_set
        self._used = (np.asarray(ts.used_feature_map)
                      if ts is not None else None)
        # inference-compiler routing: explicit kwargs win, then the
        # model's params, then the defaults (auto / exact / unsharded)
        cfg = getattr(gbdt, "config", None)
        self._compiler_mode = compiler if compiler is not None else \
            getattr(cfg, "tpu_predict_compiler", "auto")
        self._leaf_bits = leaf_bits if leaf_bits is not None else \
            int(getattr(cfg, "tpu_predict_leaf_bits", 0))
        self._shard = shard if shard is not None else \
            int(getattr(cfg, "tpu_predict_shard", 0))
        self._explain_mode = explain_compiler if explain_compiler is not None \
            else getattr(cfg, "tpu_explain_compiler", "auto")
        # explain lane state: compiled LAZILY on the first explain()
        # call — the (T, Nn, L*D) occurrence table costs real host work
        # and HBM, and most predictors (fleet workers, zoo tenants)
        # never serve /explain traffic
        self._explain_lock = threading.Lock()
        self._explain_state: Optional[tuple] = None
        self._dense: Optional[DenseExecutable] = None
        self._fallback_reason: Optional[str] = None
        self._kinds: tuple = ()
        self._sig: tuple = ()
        self._per_class = None
        from ..models.tree import TreeBatch
        sel = [models[t] for t in range(self.num_trees)]
        if not sel or self.num_trees < k:
            raise ValueError("predictor needs at least one tree per class")
        # the dense program fuses every class's trees into ONE loop-free
        # jitted program per bucket (serve/compiler.py); the walk keeps
        # the historical per-class scan kernels
        self._dense, self._fallback_reason = compile_ensemble(
            sel, k, len(self._used) if self._used is not None
            else self.num_features,
            mode=self._compiler_mode, leaf_bits=self._leaf_bits,
            shard=self._shard,
            model_label=getattr(self.stats, "model", "") or "")
        if self._dense is not None:
            self._kinds = ("dense_compiled",)
            self._sig = self._dense.signature
            return
        per_class = []
        kinds = []
        for c in range(k):
            selc = [models[t] for t in range(self.num_trees) if t % k == c]
            kind, fields, lin = ensemble_serve_fields(TreeBatch(selc))
            kinds.append(kind)
            per_class.append((fields, lin))
        # one device_put pins every array; requests then ship only rows
        self._per_class = jax.device_put(tuple(per_class))
        self._kinds = tuple(kinds)
        # shape signature: kinds + every model array's (shape, dtype) —
        # exactly what XLA's cache keys on besides the row bucket
        leaves = jax.tree_util.tree_leaves(self._per_class)
        self._sig = (self._kinds,
                     tuple((a.shape, str(a.dtype)) for a in leaves))

    # -- zoo grouping -------------------------------------------------------
    @property
    def signature(self) -> tuple:
        """The shape signature XLA's compile cache keys on (and the zoo
        groups stacked tenants by): dense meta + shard + array shapes,
        or walk kinds + array shapes."""
        return self._sig

    @property
    def group_key(self) -> str:
        """Short stable digest of :attr:`signature` — the operator-facing
        lowering-shape group id (`GET /models` reports it so co-batching
        tenants are visible)."""
        import hashlib
        return hashlib.sha1(repr(self._sig).encode()).hexdigest()[:12]

    @property
    def stackable(self) -> bool:
        """Whether this predictor can join a cross-model stack: dense
        program, unsharded executable (sharded stacks ride their own
        shard_map entry); the RF mean divisor is fine (elementwise,
        applied per lane), but a walk-path model never stacks."""
        return self._dense is not None and not self._dense.shard

    # -- core ---------------------------------------------------------------
    def predict_raw(self, X: np.ndarray,
                    request_ids: tuple = ()) -> np.ndarray:
        """Bucketed raw-score prediction: (N,) for single-class models,
        (N, k) for multiclass.  Bitwise identical to ``Booster.predict``
        (both pad up the same ladder and run the same walk kernels).

        ``request_ids`` is the per-request trace propagated from the
        HTTP layer through the micro-batcher: the device call runs
        under a ``serve/predict`` span and a recompile is attributed to
        the requests that triggered it (they show up flagged in the
        slowest-request exemplar ring)."""
        from ..telemetry.trace import span
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        n = X.shape[0]
        if X.shape[1] != self.num_features:
            raise ValueError(
                f"request has {X.shape[1]} features; model expects "
                f"{self.num_features}")
        Xi = X[:, self._used] if self._used is not None else X
        nb = bucket_rows(n, self.buckets)
        Xp = pad_rows(Xi, self.buckets)
        new = _note_dispatch((self._sig, nb))
        t0 = time.perf_counter()
        with span(f"serve/predict/b{nb}"):
            if self._dense is not None:
                out = np.asarray(self._dense.predict_raw(Xp))[:n]
            else:
                out = np.asarray(predict_raw_ensemble(Xp, self._per_class,
                                                      self._kinds))[:n]
        self.stats.record_batch(n, nb, (time.perf_counter() - t0) * 1e3,
                                recompiled=new, request_ids=request_ids)
        if self._dense is None and self._fallback_reason:
            # fallback rate measured in TRAFFIC: every batch this
            # fallback-built walk serves counts against the
            # serve/compiler_fallback_rate budget (compiler.py)
            from .compiler import note_fallback_batch
            note_fallback_batch(self._fallback_reason,
                                getattr(self.stats, "model", "") or "")
        if self._avg_div != 1:
            out = out / self._avg_div
        return out[:, 0] if self.num_class == 1 else out

    # -- explanation lane ---------------------------------------------------
    def _explain_program(self) -> tuple:
        """``(executable | None, fallback_reason | None)``, compiled on
        first use and cached for the predictor's lifetime (immutable
        like the predict program; hot-swap replaces the whole object)."""
        st = self._explain_state
        if st is not None:
            return st
        with self._explain_lock:
            if self._explain_state is None:
                from ..explain.compiler import compile_explain
                models = self._gbdt.models
                sel = [models[t] for t in range(self.num_trees)]
                self._explain_state = compile_explain(
                    sel, self.num_class,
                    len(self._used) if self._used is not None
                    else self.num_features,
                    mode=self._explain_mode,
                    num_cols=self.num_features + 1,
                    model_label=getattr(self.stats, "model", "") or "")
            return self._explain_state

    def explain(self, X: np.ndarray,
                request_ids: tuple = ()) -> np.ndarray:
        """Bucketed SHAP contributions ``(N, (num_features + 1) *
        num_class)`` — the /explain serving lane's device entry, same
        layout as ``Booster.predict(pred_contrib=True)``.

        Rides the dense TreeSHAP program on the shape-bucket ladder
        when it lowers; otherwise the host walk serves the batch and
        the reason lands in ``serve_explain_fallback_batches_total`` —
        per dispatched batch, never silent.  Dense results are
        additivity-checked (phi rows sum to the raw score); a failed
        invariant falls back with reason ``additivity``."""
        from ..telemetry.trace import span
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        n = X.shape[0]
        if X.shape[1] != self.num_features:
            raise ValueError(
                f"request has {X.shape[1]} features; model expects "
                f"{self.num_features}")
        Xi = X[:, self._used] if self._used is not None else X
        nb = bucket_rows(n, self.buckets)
        exe, reason = self._explain_program()
        if exe is not None:
            from ..explain.compiler import ExplainAdditivityError
            try:
                with span(f"serve/explain/b{nb}"):
                    return exe.explain(Xi, buckets=self.buckets)
            except ExplainAdditivityError:
                reason = "additivity"
        from ..explain.compiler import note_explain_fallback_batch
        note_explain_fallback_batch(reason or "unknown",
                                    getattr(self.stats, "model", "") or "")
        from ..models.shap import predict_contrib
        with span(f"serve/explain_walk/b{nb}"):
            return predict_contrib(self._gbdt, Xi, 0,
                                   self.num_trees // max(1, self.num_class))

    def predict(self, X: np.ndarray, raw_score: bool = False,
                request_ids: tuple = ()) -> np.ndarray:
        """Prediction with the model objective's output transform (same
        contract as ``Booster.predict`` without the special modes)."""
        import jax.numpy as jnp
        raw = self.predict_raw(X, request_ids=request_ids)
        if raw_score or self.objective is None:
            return raw
        return np.asarray(self.objective.convert_output(jnp.asarray(raw)))

    # -- delta append (publish/ continuous-learning lane) -------------------
    def extended(self, new_trees) -> Tuple["CompiledPredictor", str]:
        """A NEW predictor serving this model plus ``new_trees``
        (appended boosting rounds, real/untranslated feature indices —
        what a delta payload parses to).

        Returns ``(predictor, mode)`` where mode is ``"extend"`` (the
        dense tables were spliced in place inside the shard-padding
        envelope — same jit signature, zero recompiles) or
        ``"rebuild"`` (a full recompile was needed).  ``self`` is never
        mutated, so a failure part-way leaves the serving predictor
        untouched — the hot-swap discipline of ``ModelRegistry.load``."""
        import copy as _copy
        new_trees = list(new_trees)
        if not new_trees:
            return self, "noop"
        if self._used is not None:
            # a train-set-attached model stores INNER feature indices;
            # delta trees carry real ones — mixing would misroute splits
            raise ValueError(
                "extended() needs a file/text-loaded predictor (real "
                "feature indices); this one is train-set-attached")
        g2 = _copy.copy(self._gbdt)
        g2.models = list(self._gbdt.models[:self.num_trees]) + new_trees
        g2.iter_ = len(g2.models) // max(1, self.num_class)
        if self._avg_div == 1 and self._dense is not None:
            ex = self._dense.extended(new_trees, self.num_features)
            if ex is not None:
                p2 = _copy.copy(self)
                p2._gbdt = g2
                p2._dense = ex
                p2.num_trees = self.num_trees + len(new_trees)
                p2._sig = ex.signature
                # the explain program binds the OLD tree set: recompile
                # lazily on the new predictor's first explain() call
                p2._explain_lock = threading.Lock()
                p2._explain_state = None
                return p2, "extend"
        # RF (mean-output divisor changes with tree count), walk-path
        # models, or an exhausted padding envelope: full rebuild
        p2 = CompiledPredictor(
            g2, buckets=self.buckets, stats=self.stats,
            compiler=self._compiler_mode, leaf_bits=self._leaf_bits,
            shard=self._shard, explain_compiler=self._explain_mode)
        return p2, "rebuild"

    # -- warmup -------------------------------------------------------------
    def warmup(self, buckets: Optional[Tuple[int, ...]] = None) -> int:
        """Ahead-of-time compile every shape bucket (zeros ride the same
        kernels).  Returns the number of buckets traced for the first
        time process-wide."""
        before = self.stats.snapshot()["recompiles"]
        for b in (buckets if buckets is not None else self.buckets):
            self.predict_raw(np.zeros((b, self.num_features), np.float32))
        return self.stats.snapshot()["recompiles"] - before

    def info(self) -> dict:
        out = {
            "num_trees": self.num_trees,
            "num_class": self.num_class,
            "num_features": self.num_features,
            "kinds": list(self._kinds),
            "buckets": list(self.buckets),
            # the compiler decision, never silent: which program serves
            # this model and (on the walk path) exactly why
            "compiler": "dense" if self._dense is not None else "walk",
            "compiler_mode": self._compiler_mode,
            "fallback_reason": self._fallback_reason,
            # lowering-shape group: tenants sharing this key share XLA
            # programs, and (dense, unsharded) ones co-batch in a stack
            "group_key": self.group_key,
            "stackable": self.stackable,
            # the explain lane's compiler decision ("lazy" = no explain
            # traffic yet, nothing compiled)
            "explain_mode": self._explain_mode,
            "explain_compiler": (
                "lazy" if self._explain_state is None else
                "dense" if self._explain_state[0] is not None else "walk"),
            "explain_fallback_reason": (
                None if self._explain_state is None
                else self._explain_state[1]),
        }
        if self._dense is not None:
            out["dense"] = self._dense.info()
        if self._explain_state is not None and \
                self._explain_state[0] is not None:
            out["explain"] = self._explain_state[0].info()
        return out
