"""In-process synthetic load generator for the HTTP serving tier.

Drives the REAL server (``serve/server.py``) over real sockets —
``http.client`` connections, JSON bodies, the full handler → batcher →
predictor path — with either of the two canonical load models:

  * **open loop** (``target_qps > 0``): requests depart on a schedule
    drawn once up front (uniform or Poisson arrivals at the target
    rate) regardless of completions, so an overloaded tier shows queue
    growth and sheds instead of the generator politely slowing down
    (the coordinated-omission trap closed-loop benchmarks fall into);
  * **closed loop** (``target_qps = 0``): each worker fires
    back-to-back, measuring the tier's ceiling.

The request-shape mix rides the ``SHAPE_BUCKETS`` ladder: each bucket
size gets a weight, bodies are pre-encoded once per bucket (the
generator must not spend its CPU budget on ``json.dumps``), and every
request carries an ``X-Request-Id`` so server-side exemplars can name
the offending load-test request on a breach.

The generator reports only CLIENT-side observations (codes, client
latency, achieved rate).  The load-test harness's pass/breach verdict
comes exclusively from ``/metrics`` + ``/slo`` scrapes — the
``scrape_*`` / ``parse_prometheus`` helpers here are that path.
"""

from __future__ import annotations

import http.client
import json
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..telemetry.metrics import percentile

__all__ = ["LoadSpec", "LoadResult", "LoadGenerator", "parse_prometheus",
           "metric_sum", "scrape_metrics", "scrape_json"]


@dataclass
class LoadSpec:
    """One load rung.  ``bucket_mix`` maps rows-per-request to weight;
    ``target_qps=0`` switches to closed-loop."""

    duration_s: float = 5.0
    target_qps: float = 0.0
    workers: int = 2
    features: int = 4
    bucket_mix: Dict[int, float] = field(default_factory=lambda: {4096: 1.0})
    arrival: str = "uniform"           # "uniform" | "poisson"
    model: Optional[str] = None        # /predict "model" field
    deadline_ms: float = 0.0           # per-request deadline (0 = none)
    seed: int = 0
    timeout_s: float = 30.0            # per-connection connect/read timeout


@dataclass
class LoadResult:
    """Client-side view of one rung (the verdict does NOT use this —
    it reads the server's own /metrics + /slo)."""

    requests_sent: int = 0
    rows_sent: int = 0
    by_code: Dict[int, int] = field(default_factory=dict)
    errors: int = 0
    connect_errors: int = 0            # connection-level failures (a
    #                                    worker restart mid-request);
    #                                    counted as failed requests,
    #                                    never abort a worker thread
    elapsed_s: float = 0.0
    latencies_ms: List[float] = field(default_factory=list)
    late_departures: int = 0           # open loop: schedule slips

    @property
    def achieved_qps(self) -> float:
        return self.requests_sent / self.elapsed_s if self.elapsed_s else 0.0

    @property
    def achieved_rows_per_s(self) -> float:
        return self.rows_sent / self.elapsed_s if self.elapsed_s else 0.0

    def summary(self) -> Dict[str, Any]:
        lat = sorted(self.latencies_ms)
        return {
            "requests_sent": self.requests_sent,
            "rows_sent": self.rows_sent,
            "elapsed_s": round(self.elapsed_s, 3),
            "achieved_qps": round(self.achieved_qps, 2),
            "achieved_rows_per_s": round(self.achieved_rows_per_s, 1),
            "by_code": {str(k): v for k, v in sorted(self.by_code.items())},
            "errors": self.errors,
            "connect_errors": self.connect_errors,
            "late_departures": self.late_departures,
            "client_p50_ms": round(percentile(lat, 50.0), 3),
            "client_p99_ms": round(percentile(lat, 99.0), 3),
        }


class LoadGenerator:
    """Drive one :class:`LoadSpec` against a running server."""

    def __init__(self, host: str, port: int, spec: LoadSpec) -> None:
        self.host, self.port = host, int(port)
        self.spec = spec
        rng = np.random.RandomState(spec.seed)
        # one pre-encoded body per bucket size: the generator's hot loop
        # is socket I/O, not serialization
        self._bodies: Dict[int, bytes] = {}
        for rows in spec.bucket_mix:
            X = rng.randn(int(rows), spec.features).astype(np.float32)
            req: Dict[str, Any] = {"rows": X.tolist()}
            if spec.model:
                req["model"] = spec.model
            if spec.deadline_ms:
                req["deadline_ms"] = spec.deadline_ms
            self._bodies[int(rows)] = json.dumps(req).encode()
        sizes = sorted(spec.bucket_mix)
        w = np.asarray([spec.bucket_mix[s] for s in sizes], np.float64)
        self._sizes = sizes
        self._weights = w / w.sum()
        self._rng = rng

    def _schedule(self) -> Optional[np.ndarray]:
        """Departure offsets for open loop (None = closed loop)."""
        s = self.spec
        if s.target_qps <= 0:
            return None
        n = max(1, int(s.target_qps * s.duration_s))
        if s.arrival == "poisson":
            gaps = self._rng.exponential(1.0 / s.target_qps, n)
            return np.cumsum(gaps)
        return np.arange(n) / s.target_qps

    def run(self) -> LoadResult:
        s = self.spec
        res = LoadResult()
        lock = threading.Lock()
        stop_at = [0.0]                # filled once t0 is known
        sched = self._schedule()
        cursor = [0]                   # next schedule slot (open loop)
        # per-request row sizes drawn up front (deterministic under seed)
        draw_n = len(sched) if sched is not None else \
            int(max(64, s.duration_s * 2000))
        sizes = self._rng.choice(self._sizes, size=draw_n, p=self._weights)

        def new_conn() -> http.client.HTTPConnection:
            return http.client.HTTPConnection(self.host, self.port,
                                              timeout=s.timeout_s)

        def worker(wid: int) -> None:
            conn = new_conn()
            sent = rows = errors = conn_errors = late = 0
            codes: Dict[int, int] = {}
            lats: List[float] = []
            while True:
                now = time.perf_counter()
                if now >= stop_at[0]:
                    break
                if sched is not None:
                    with lock:
                        i = cursor[0]
                        if i >= len(sched):
                            break
                        cursor[0] = i + 1
                    depart = t0 + sched[i]
                    delay = depart - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                    elif delay < -0.05:
                        late += 1
                else:
                    with lock:
                        i = cursor[0]
                        cursor[0] = i + 1
                    if i >= len(sizes):
                        i = i % len(sizes)
                nrows = int(sizes[i % len(sizes)])
                body = self._bodies[nrows]
                rid = f"load-{wid}-{sent}"
                t_req = time.perf_counter()
                code: Optional[int] = None
                # one bounded reconnect: a worker restart mid-request
                # severs the keep-alive connection; the SECOND attempt
                # runs on a fresh socket, and a second failure counts
                # as a failed request (connect_errors) rather than
                # aborting the generator thread or burning the slot in
                # a reconnect storm
                for attempt in (0, 1):
                    try:
                        conn.request("POST", "/predict", body, {
                            "Content-Type": "application/json",
                            "Content-Length": str(len(body)),
                            "X-Request-Id": rid})
                        r = conn.getresponse()
                        r.read()
                        code = r.status
                        break
                    except Exception:
                        try:
                            conn.close()
                        except Exception:
                            pass
                        conn = new_conn()
                if code is None:
                    errors += 1
                    conn_errors += 1
                    sent += 1       # a failed request, not a non-event
                    continue
                lats.append((time.perf_counter() - t_req) * 1e3)
                codes[code] = codes.get(code, 0) + 1
                sent += 1
                if code == 200:
                    rows += nrows
            try:
                conn.close()
            except Exception:
                pass
            with lock:
                res.requests_sent += sent
                res.rows_sent += rows
                res.errors += errors
                res.connect_errors += conn_errors
                res.late_departures += late
                res.latencies_ms.extend(lats)
                for c, k in codes.items():
                    res.by_code[c] = res.by_code.get(c, 0) + k

        t0 = time.perf_counter()
        stop_at[0] = t0 + s.duration_s
        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(max(1, s.workers))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        res.elapsed_s = time.perf_counter() - t0
        return res


# ---------------------------------------------------------------------------
# Scrape helpers: the verdict path (server-side numbers only)
# ---------------------------------------------------------------------------

_LINE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$")
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> Dict[str, List[Tuple[Dict[str, str],
                                                        float]]]:
    """Prometheus exposition text -> {name: [(labels, value), ...]}."""
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE_RE.match(line)
        if not m:
            continue
        labels = {k: v.replace(r'\"', '"').replace(r"\\", "\\")
                  for k, v in _LABEL_RE.findall(m.group("labels") or "")}
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        out.setdefault(m.group("name"), []).append((labels, value))
    return out


def metric_sum(parsed: Dict[str, List[Tuple[Dict[str, str], float]]],
               name: str, **labels) -> float:
    """Sum of a metric's series whose labels contain ``labels``."""
    total = 0.0
    for lbl, val in parsed.get(name, ()):
        if all(lbl.get(k) == str(v) for k, v in labels.items()):
            total += val
    return total


def scrape_metrics(host: str, port: int) -> str:
    conn = http.client.HTTPConnection(host, int(port), timeout=30)
    try:
        conn.request("GET", "/metrics")
        r = conn.getresponse()
        body = r.read().decode()
        if r.status != 200:
            raise RuntimeError(f"/metrics returned {r.status}")
        return body
    finally:
        conn.close()


def scrape_json(host: str, port: int, path: str) -> Dict[str, Any]:
    conn = http.client.HTTPConnection(host, int(port), timeout=30)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        body = r.read().decode()
        if r.status != 200:
            raise RuntimeError(f"{path} returned {r.status}")
        return json.loads(body)
    finally:
        conn.close()
