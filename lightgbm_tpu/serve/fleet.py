"""Supervised multi-worker serving fleet: N ``PredictionServer``
processes behind one dispatcher, kept alive under faults.

A single serving process (``serve/server.py``) dies with its host: a
crash or deploy drops every in-flight request.  The fleet tier closes
that gap the way the reference survives rank failure at its Network
layer — independent worker processes, a supervisor that restarts them,
and a front door that routes around the dead:

**Supervision.**  Each worker is a ``python -m lightgbm_tpu serve``
subprocess announcing its bound port through an atomic ``port_file``.
The supervisor runs a liveness + ``/healthz`` watchdog per worker
(process exit is caught within a tick; ``hang_probes`` consecutive
probe timeouts declare a WEDGED worker and kill it), restarts failures
with exponential backoff + jitter, and opens a crash-loop circuit
breaker when ``breaker_failures`` failures land inside
``breaker_window_s``: the worker is quarantined (no restart storm),
fleet ``/healthz`` goes degraded, and after ``breaker_halfopen_s`` the
breaker half-opens with ONE probe restart — ``probe_ok_needed`` clean
health probes close it, another death re-quarantines.

**Dispatching.**  ``/predict`` routes by health-weighted smooth round
robin (an ``ok`` worker gets 4x the weight of a ``degraded`` one;
quarantined/backoff/starting workers get none).  A request's
``deadline_ms`` is decremented by the time already burned in the hop
before being forwarded, so the worker-side deadline reflects what the
CLIENT has left.  Connection-reset failures (refused / reset / EOF
before a status line — classes where the request provably never reached
a predictor) are retried against a DIFFERENT worker inside a
``retry_budget``; a 5xx that came back from a worker is forwarded
verbatim, never retried.  With every worker quarantined the dispatcher
fast-fails 503 + ``Retry-After`` pointing at the next breaker probe.

**Lifecycle.**  Fleet SIGTERM runs a rolling drain: each worker in turn
is removed from dispatch, SIGTERMed (the worker stops accepting, drains
its ``MicroBatcher``, finishes in-flight requests, exits
``128+signum``), and only then does the next worker start draining; the
dispatcher exits ``128+signum`` once all workers stopped.  The same
per-worker discipline gives zero-downtime rolling deploys: ``POST
/models`` swaps one worker at a time (the worker loads + warms the new
version BEFORE its atomic registry swap), checks the worker's post-swap
health, and automatically rolls the worker back to its previous source
on a regression — old or new version answers every request throughout.

**Zoo placement.**  ``placement=hash`` stops replicating the model set
and SHARDS it: a consistent-hash ring (vnodes over the static worker-id
set) assigns each model name one owner, workers boot + sync only their
placed subset (zoo mode is switched on for them, so each worker runs
bounded admission and stacks its co-placed same-shape tenants), and the
dispatcher routes ``/predict`` by the request's ``model`` to the owner.
Re-placement is the ring's routability filter: a dead worker's names
fall to the next node at lookup time — no migration step — and the
per-tick placement sync loads them onto the new owner; when the worker
revives, its names come home and the squatter's stale copies decay out
through the zoo's traffic-weighted LRU (the dispatcher no longer routes
to them).  The delta journal follow tracks the OWNER of the published
model, not every worker.

**Continuous learning.**  With ``publish_dir=`` the supervisor follows
a trainer's delta journal (``publish/delta.py``): every published round
is pushed to each worker over ``POST /models/<name>/delta`` (an
incremental tree append on the worker — zero recompiles inside the
dense shard-padding envelope), per-worker acked rounds are tracked
across respawns, and a worker that fell off the chain (respawn, 409
chain mismatch) is re-anchored by a full reload of the journal's
newest BASE and replayed forward.  ``fleet_model_rounds_behind``
gauges the head-to-worker staleness and the ``fleet/model_staleness``
SLO burns while any worker sits more than one round behind.

**Observability.**  Fleet-level ``/metrics`` renders the fleet's own
registry (``fleet_workers_{alive,quarantined}``,
``fleet_restarts_total{reason}``, ``fleet_retries_total``, dispatcher
response counters, SLO burn gauges) and appends every worker's scrape
re-labeled ``worker="wN"`` under ``lgbm_tpu_worker_*`` names; ``/slo``
evaluates the declared objectives against the fleet registry and
attaches each worker's own ``/slo`` verdict.  The chaos harness judges
kill-under-load recovery from these two endpoints alone.
"""

from __future__ import annotations

import base64
import bisect
import hashlib
import http.client
import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from ..telemetry.metrics import MetricsRegistry
from ..telemetry.slo import SloEngine, register_metric_ensurer, slo
from ..utils.log import log_debug, log_info, log_warning

__all__ = ["FleetSupervisor", "WorkerHandle", "main"]

# Fleet-availability-at-the-supervision-layer objective: the alive-worker
# gauge must never sit below 1.  Gauge-floor error is 0/1 per scrape, so
# the budget is wide and the burn thresholds low: a breach means the
# whole fleet was down for essentially every fast-window scrape.
slo("fleet/workers_alive", metric="fleet_workers_alive",
    kind="gauge_floor", floor=1.0, target=0.5,
    burn_fast=1.9, burn_slow=1.5,
    note="at least one worker serving; burns while the fleet is down")

# Retry-budget objective: bounded connection-reset retries are the
# mechanism that hides worker deaths from clients — but a sustained
# retry rate means workers are churning, not blipping.  At most 5% of
# dispatched /predict responses may have needed a cross-worker retry.
slo("fleet/retry_rate", metric="fleet_retries_total",
    total_metric="serve_predict_responses_total", kind="ratio",
    target=0.95, min_events=50,
    note="cross-worker connection-reset retry budget")

# Continuous-learning freshness objective: while a trainer publishes
# per-round deltas into the followed journal, no worker may serve a
# model more than one published round behind the head.  The
# rounds-behind gauge is maintained by the delta sync loop (it keeps
# aging for a crashed worker as the head advances), so a worker that
# keeps missing its pushes — crash-looping, rejecting the chain —
# burns this budget until re-anchor + replay catches it up.
slo("fleet/model_staleness", metric="fleet_model_rounds_behind",
    kind="gauge_ceiling", ceiling=1.0, target=0.5,
    burn_fast=1.9, burn_slow=1.5,
    note="live-refresh freshness: every worker within one published "
         "round of the delta journal head")


@register_metric_ensurer
def _ensure_fleet_metrics(reg: MetricsRegistry) -> None:
    """SLO-coverage ensurer: the fleet metric families exist in a
    registry before any worker does (declared here, next to the
    supervisor that bumps them, so the lint validates the real
    schema)."""
    reg.gauge("fleet_workers_alive", "workers in the alive state",
              labels=())
    reg.gauge("fleet_workers_quarantined",
              "workers held by an open crash-loop breaker", labels=())
    reg.counter("fleet_restarts_total",
                "worker restarts by trigger (exit/hang/probe)",
                labels=("reason",))
    reg.counter("fleet_retries_total",
                "/predict calls retried on another worker after a "
                "connection reset", labels=())
    reg.gauge("fleet_model_round",
              "last published round acked by each worker",
              labels=("model", "worker"))
    reg.gauge("fleet_model_rounds_behind",
              "delta journal head round minus the worker's acked round",
              labels=("model", "worker"))
    reg.counter("fleet_delta_pushes_total",
                "delta records pushed to workers by outcome "
                "(ok/reanchor/rejected/error)", labels=("outcome",))


# connection-level failure classes that are safe to retry on another
# worker: the request provably never produced a response (refused,
# reset, or the socket closed before a status line).  A read timeout is
# NOT here — the request may have executed.
_RETRYABLE = (ConnectionError, http.client.BadStatusLine)

_WEIGHT_OK = 4
_WEIGHT_DEGRADED = 1


def _ring_hash(s: str) -> int:
    return int(hashlib.sha1(s.encode()).hexdigest()[:8], 16)


class _HashRing:
    """Consistent-hash placement over a STATIC worker-id set.

    The ring never changes shape — liveness is a routability filter at
    lookup time: :meth:`owner` walks clockwise from the name's hash to
    the first vnode whose worker is in ``routable``.  A worker death
    therefore re-places only ITS names (each falls to the next distinct
    node on the ring), and its revival takes exactly those names back —
    the minimal-disruption property replication-by-rendezvous would
    also give, bought here with one sorted array and a bisect."""

    def __init__(self, wids: List[int], vnodes: int = 64) -> None:
        self.vnodes = int(vnodes)
        points = [(_ring_hash(f"w{wid}#{v}"), wid)
                  for wid in wids for v in range(self.vnodes)]
        points.sort()
        self._ring = points
        self._keys = [h for h, _ in points]

    def owner(self, name: str, routable) -> Optional[int]:
        """The routable worker id owning ``name``, or None."""
        if not self._ring or not routable:
            return None
        i = bisect.bisect_right(self._keys, _ring_hash(name))
        for k in range(len(self._ring)):
            wid = self._ring[(i + k) % len(self._ring)][1]
            if wid in routable:
                return wid
        return None


class WorkerHandle:
    """Supervision record for one worker process."""

    def __init__(self, wid: int, port_file: str, log_path: str) -> None:
        self.wid = wid
        self.name = f"w{wid}"
        self.port_file = port_file
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.state = "stopped"   # starting|alive|backoff|quarantined|
        #                          draining|stopped
        self.incarnation = 0
        self.spawn_t = 0.0
        self.last_probe_t = 0.0
        self.last_health = "unknown"
        self.consecutive_probe_failures = 0
        self.probe_ok_streak = 0
        self.probing = False            # half-open breaker probe worker
        self.fail_times: Deque[float] = deque()
        self.backoff_s = 0.0
        self.next_restart_t = 0.0
        self.quarantined_at = 0.0
        self.restarts = 0
        self.current_weight = 0.0       # smooth-WRR scheduling state
        self.synced_incarnation = 0     # last incarnation whose model
        #                                 set was caught up to deploys
        self.placed_gen = 0             # last placement epoch this
        #                                 worker's model set was synced
        #                                 against (hash placement only)
        self.acked_round: Optional[int] = None  # delta-chain position
        #                                 this worker has acked
        self.delta_incarnation = 0      # incarnation acked_round is
        #                                 valid for (a respawn boots
        #                                 from the CLI file: unknown)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "state": self.state, "port": self.port,
            "incarnation": self.incarnation, "restarts": self.restarts,
            "last_health": self.last_health,
            "recent_failures": len(self.fail_times),
            "probing": self.probing,
            "acked_round": self.acked_round,
            "pid": self.proc.pid if self.proc is not None else None,
        }


class FleetSupervisor:
    """Spawn, supervise and front N serving workers.

    ``model_files`` are passed to every worker (registered under their
    basenames; a single file honors ``worker_args['name']``).
    ``worker_args`` are extra ``key=value`` pairs for the worker CLI
    (``max_queue_rows``, ``max_wait_ms``, ...).  ``worker_cmd`` swaps
    the whole worker command line (tests drive stub workers through the
    full supervision/dispatch machinery without a jax process);
    ``per_worker_env`` adds env vars to every spawn of one worker id and
    ``first_spawn_env`` only to its FIRST incarnation (chaos arming: the
    replacement worker boots clean).
    """

    def __init__(self, model_files: List[str], workers: int = 2,
                 host: str = "127.0.0.1", port: int = 0,
                 worker_args: Optional[Dict[str, str]] = None,
                 worker_cmd: Optional[Callable[[int, str], List[str]]]
                 = None,
                 worker_env: Optional[Dict[str, str]] = None,
                 per_worker_env: Optional[Dict[int, Dict[str, str]]] = None,
                 first_spawn_env: Optional[Dict[int, Dict[str, str]]]
                 = None,
                 run_dir: Optional[str] = None,
                 probe_interval_s: float = 1.0,
                 probe_timeout_s: float = 2.0,
                 hang_probes: int = 3,
                 breaker_failures: int = 3,
                 breaker_window_s: float = 30.0,
                 breaker_halfopen_s: float = 5.0,
                 probe_ok_needed: int = 2,
                 backoff_base_s: float = 0.2,
                 backoff_max_s: float = 5.0,
                 backoff_jitter: float = 0.25,
                 retry_budget: int = 1,
                 deadline_ms: float = 0.0,
                 forward_timeout_s: float = 30.0,
                 deploy_timeout_s: float = 120.0,
                 startup_timeout_s: float = 120.0,
                 drain_timeout_s: float = 30.0,
                 publish_dir: Optional[str] = None,
                 publish_model: Optional[str] = None,
                 placement: str = "replicate",
                 placement_vnodes: int = 64,
                 metrics_registry: Optional[MetricsRegistry] = None
                 ) -> None:
        if workers < 1:
            raise ValueError(f"a fleet needs >= 1 worker, got {workers}")
        self._model_files = [os.path.abspath(f) for f in model_files]
        self._current_models: Dict[str, str] = {}
        for f in self._model_files:
            name = os.path.splitext(os.path.basename(f))[0]
            if len(self._model_files) == 1 and worker_args and \
                    worker_args.get("name"):
                name = str(worker_args["name"])
            self._current_models[name] = f
        self._host = host
        self._worker_args = dict(worker_args or {})
        self._worker_cmd = worker_cmd
        self._worker_env = dict(worker_env or {})
        self._per_worker_env = {int(k): dict(v) for k, v in
                                (per_worker_env or {}).items()}
        self._first_spawn_env = {int(k): dict(v) for k, v in
                                 (first_spawn_env or {}).items()}
        if run_dir is None:
            import tempfile
            run_dir = tempfile.mkdtemp(prefix="lgbm-tpu-fleet-")
        self.run_dir = run_dir
        os.makedirs(run_dir, exist_ok=True)
        self._probe_interval_s = float(probe_interval_s)
        self._probe_timeout_s = float(probe_timeout_s)
        self._hang_probes = int(hang_probes)
        self._breaker_failures = int(breaker_failures)
        self._breaker_window_s = float(breaker_window_s)
        self._halfopen_s = float(breaker_halfopen_s)
        self._probe_ok_needed = int(probe_ok_needed)
        self._backoff_base_s = float(backoff_base_s)
        self._backoff_max_s = float(backoff_max_s)
        self._backoff_jitter = float(backoff_jitter)
        self._retry_budget = max(0, int(retry_budget))
        self._deadline_ms = float(deadline_ms)
        self._forward_timeout_s = float(forward_timeout_s)
        self._deploy_timeout_s = float(deploy_timeout_s)
        self._startup_timeout_s = float(startup_timeout_s)
        self._drain_timeout_s = float(drain_timeout_s)

        # continuous-learning lane: follow a trainer's delta journal
        # and keep every worker's serving model within a round of it
        self._journal = None
        self._publish_model: Optional[str] = None
        if publish_dir:
            from ..publish.delta import DeltaJournal
            self._journal = DeltaJournal(os.path.abspath(publish_dir))
            self._publish_model = (str(publish_model) if publish_model
                                   else next(iter(self._current_models)))
        self._journal_head_round: Optional[int] = None
        self._journal_poll_t = 0.0

        self._metrics = metrics_registry if metrics_registry is not None \
            else MetricsRegistry()
        self.slo_engine = SloEngine(registry=self._metrics)
        _ensure_fleet_metrics(self._metrics)
        self._alive_g = self._metrics.gauge(
            "fleet_workers_alive", "workers in the alive state", labels=())
        self._quar_g = self._metrics.gauge(
            "fleet_workers_quarantined",
            "workers held by an open crash-loop breaker", labels=())
        self._restarts = self._metrics.counter(
            "fleet_restarts_total",
            "worker restarts by trigger (exit/hang/probe)",
            labels=("reason",))
        self._retries = self._metrics.counter(
            "fleet_retries_total",
            "/predict calls retried on another worker after a "
            "connection reset", labels=())
        self._model_round_g = self._metrics.gauge(
            "fleet_model_round",
            "last published round acked by each worker",
            labels=("model", "worker"))
        self._rounds_behind_g = self._metrics.gauge(
            "fleet_model_rounds_behind",
            "delta journal head round minus the worker's acked round",
            labels=("model", "worker"))
        self._delta_pushes = self._metrics.counter(
            "fleet_delta_pushes_total",
            "delta records pushed to workers by outcome "
            "(ok/reanchor/rejected/error)", labels=("outcome",))
        self._responses = self._metrics.counter(
            "serve_http_responses_total", "HTTP responses by status code",
            labels=("code",))
        self._predict_responses = self._metrics.counter(
            "serve_predict_responses_total",
            "/predict responses by status code (the availability SLO's "
            "series)", labels=("code",))

        self._lock = threading.RLock()
        self._deploy_lock = threading.Lock()
        self._workers = [
            WorkerHandle(i, os.path.join(run_dir, f"worker-{i}.port"),
                         os.path.join(run_dir, f"worker-{i}.log"))
            for i in range(int(workers))]
        # zoo placement: hash mode shards the model set across workers
        # (one owner per name) instead of replicating it on every one
        if placement not in ("replicate", "hash"):
            raise ValueError(f"placement must be 'replicate' or 'hash', "
                             f"got {placement!r}")
        self.placement = placement
        self._ring = _HashRing([w.wid for w in self._workers],
                               vnodes=placement_vnodes) \
            if placement == "hash" else None
        self._placement_gen = 1
        self._alive_ids: Tuple[int, ...] = ()
        if self._ring is not None and not any(
                k in self._worker_args for k in
                ("zoo", "max_resident", "zoo_dir", "tenant_queue_rows")):
            # placed workers run the zoo tier (bounded admission +
            # cross-model stacking over their placed subset) by default
            self._worker_args["zoo"] = "1"
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._sup_thread: Optional[threading.Thread] = None
        self._httpd = ThreadingHTTPServer((host, int(port)),
                                          _make_fleet_handler(self))
        self._httpd.daemon_threads = True
        self._http_thread: Optional[threading.Thread] = None
        self._active_cv = threading.Condition()
        self._active = 0
        self._draining = False
        self._shut_down = False
        self.signal_received: Optional[int] = None
        self._rng = random.Random(0x5EED ^ os.getpid())

    # -- properties ---------------------------------------------------------
    @property
    def port(self) -> int:
        return int(self._httpd.server_address[1])

    @property
    def host(self) -> str:
        h = self._httpd.server_address[0]
        return h.decode() if isinstance(h, (bytes, bytearray)) else str(h)

    @property
    def metrics_registry(self) -> MetricsRegistry:
        return self._metrics

    def workers(self) -> List[WorkerHandle]:
        return list(self._workers)

    # -- spawning -----------------------------------------------------------
    def _placed_models(self, w: WorkerHandle,
                       routable=None) -> Dict[str, str]:
        """The ``_current_models`` subset the ring places on ``w``
        among ``routable`` workers (default: the alive set plus ``w``
        itself, so a booting worker syncs what it is ABOUT to own).
        Replicate mode: everything."""
        if self._ring is None:
            return dict(self._current_models)
        if routable is None:
            with self._lock:
                routable = {x.wid for x in self._workers
                            if x.state == "alive"} | {w.wid}
        return {n: p for n, p in self._current_models.items()
                if self._ring.owner(n, routable) == w.wid}

    def _boot_models(self, w: WorkerHandle) -> Dict[str, str]:
        """The ``_current_models`` entries a worker CLI spawn registers
        under the right logical name: all of them for a single-model
        fleet (the ``name=`` pin), otherwise those whose
        basename-derived name matches.  Renamed deploy sources are
        caught up over ``POST /models`` once the worker is alive
        (``_sync_models``) — the worker still needs >= 1 CLI file to
        boot, so an all-renamed fleet boots its first entry and lets
        the sync re-register it.

        Hash placement boots only the worker's STATIC share (the ring
        over the full id set — liveness at spawn time is stale by the
        time the worker answers): the placement sync settles the live
        assignment.  A worker whose static share is empty still needs a
        boot file unless a ``zoo_dir`` resolver can cold-load on
        demand."""
        if len(self._current_models) == 1:
            return dict(self._current_models)
        pool = self._current_models
        if self._ring is not None:
            all_ids = {x.wid for x in self._workers}
            pool = {n: p for n, p in pool.items()
                    if self._ring.owner(n, all_ids) == w.wid}
        boot = {n: p for n, p in pool.items()
                if os.path.splitext(os.path.basename(p))[0] == n}
        if not boot and not (self._ring is not None and
                             self._worker_args.get("zoo_dir")):
            src = pool if pool else self._current_models
            n = next(iter(src))
            boot = {n: src[n]}
        return boot

    def _build_cmd(self, w: WorkerHandle) -> List[str]:
        if self._worker_cmd is not None:
            return list(self._worker_cmd(w.wid, w.port_file))
        cmd = [sys.executable, "-m", "lightgbm_tpu", "serve"]
        boot = self._boot_models(w)
        cmd += list(boot.values())
        if len(self._current_models) == 1:
            # pin the registry name so a deploy's renamed file still
            # serves under the logical model name after a respawn
            cmd += [f"name={next(iter(self._current_models))}"]
        for k, v in self._worker_args.items():
            if k not in ("name", "port", "port_file", "host"):
                cmd += [f"{k}={v}"]
        cmd += [f"host={self._host}", "port=0",
                f"port_file={w.port_file}"]
        return cmd

    def _spawn(self, w: WorkerHandle, now: float) -> None:
        try:
            os.unlink(w.port_file)
        except OSError:
            pass
        env = dict(os.environ)
        env.update(self._worker_env)
        env.update(self._per_worker_env.get(w.wid, {}))
        if w.incarnation == 0:
            env.update(self._first_spawn_env.get(w.wid, {}))
        cmd = self._build_cmd(w)
        with open(w.log_path, "ab") as fh:
            w.proc = subprocess.Popen(cmd, env=env, stdout=fh,
                                      stderr=subprocess.STDOUT)
        w.incarnation += 1
        w.spawn_t = now
        w.port = None
        w.consecutive_probe_failures = 0
        w.probe_ok_streak = 0
        with self._lock:
            w.state = "starting"
        log_debug(f"fleet: spawned {w.name} incarnation {w.incarnation} "
                  f"(pid {w.proc.pid})")

    def _read_port_file(self, w: WorkerHandle) -> Optional[int]:
        try:
            with open(w.port_file) as fh:
                return int(fh.read().strip())
        except (OSError, ValueError):
            return None

    # -- supervision --------------------------------------------------------
    def _record_failure(self, w: WorkerHandle, reason: str,
                        now: float) -> None:
        """One restart-worthy failure: open the breaker past K recent
        failures, else schedule a backed-off restart."""
        w.port = None
        w.fail_times.append(now)
        while w.fail_times and \
                w.fail_times[0] < now - self._breaker_window_s:
            w.fail_times.popleft()
        if w.probing or len(w.fail_times) >= self._breaker_failures:
            with self._lock:
                w.state = "quarantined"
            w.quarantined_at = now
            w.probing = False
            log_warning(
                f"fleet: breaker OPEN for {w.name}: "
                f"{len(w.fail_times)} failures in "
                f"{self._breaker_window_s:.0f}s (last: {reason}); "
                f"half-open probe in {self._halfopen_s:.1f}s")
            return
        w.backoff_s = min(self._backoff_max_s,
                          (w.backoff_s * 2.0) if w.backoff_s
                          else self._backoff_base_s)
        delay = w.backoff_s * (1.0 + self._backoff_jitter *
                               self._rng.random())
        w.next_restart_t = now + delay
        with self._lock:
            w.state = "backoff"
        w.restarts += 1
        self._restarts.inc(1, reason=reason)
        log_warning(f"fleet: {w.name} failed ({reason}); restart "
                    f"{w.restarts} in {delay:.2f}s")

    def _kill_worker(self, w: WorkerHandle) -> None:
        if w.proc is not None and w.proc.poll() is None:
            try:
                w.proc.kill()
                w.proc.wait(5.0)
            except (OSError, subprocess.TimeoutExpired):
                pass

    def _sync_models(self, w: WorkerHandle) -> bool:
        """Catch a freshly-alive worker up to the deployed model set:
        every ``_current_models`` entry its CLI spawn could not register
        under the right logical name (renamed deploy sources in a
        multi-model fleet) is loaded over ``POST /models``.  Returns
        True when the worker serves every logical name (retried next
        tick otherwise).

        Hash placement syncs the worker's PLACED subset instead of the
        whole set — including names just re-placed onto it by another
        worker's death.  Names that moved away are not evicted here:
        the dispatcher already routes them elsewhere, so the stale
        copies cool off and fall to the worker zoo's traffic-weighted
        LRU."""
        if self._ring is not None:
            placed = self._placed_models(w)
            try:
                have = self._worker_get_json(w, "/models",
                                             self._probe_timeout_s)
            except Exception:
                return False
            pending = {n: p for n, p in placed.items()
                       if (have.get(n) or {}).get("source") != p}
            ok = True
            for name, path in pending.items():
                try:
                    status, detail = self._worker_post_json(
                        w, "/models", {"name": name, "file": path},
                        self._deploy_timeout_s)
                except Exception as exc:
                    log_warning(f"fleet: {w.name} placement sync "
                                f"'{name}' failed: "
                                f"{type(exc).__name__}: {exc}")
                    ok = False
                    continue
                if status != 200:
                    log_warning(f"fleet: {w.name} rejected placed model "
                                f"'{name}' ({status}): "
                                f"{detail.get('error', detail)}")
                    ok = False
                else:
                    log_info(f"fleet: placed '{name}' on {w.name} "
                             f"({os.path.basename(path)})")
            return ok
        if len(self._current_models) == 1:
            return True   # the spawn's name= pin registers it correctly
        # pending = every entry the CLI spawn registers under the WRONG
        # name (file basename != logical name) — including the fallback
        # boot entry of an all-renamed fleet, which boots under its
        # basename and is re-registered here
        pending = {n: p for n, p in self._current_models.items()
                   if os.path.splitext(os.path.basename(p))[0] != n}
        if not pending:
            return True
        try:
            have = self._worker_get_json(w, "/models",
                                         self._probe_timeout_s)
        except Exception:
            return False
        ok = True
        for name, path in pending.items():
            if (have.get(name) or {}).get("source") == path:
                continue
            try:
                status, detail = self._worker_post_json(
                    w, "/models", {"name": name, "file": path},
                    self._deploy_timeout_s)
            except Exception as exc:
                log_warning(f"fleet: {w.name} model sync '{name}' "
                            f"failed: {type(exc).__name__}: {exc}")
                ok = False
                continue
            if status != 200:
                log_warning(f"fleet: {w.name} rejected synced model "
                            f"'{name}' ({status}): "
                            f"{detail.get('error', detail)}")
                ok = False
            else:
                log_info(f"fleet: {w.name} caught up to deployed "
                         f"'{name}' ({os.path.basename(path)})")
        return ok

    # -- continuous-learning lane (publish/) --------------------------------
    def _journal_target(self, now: float) -> Optional[int]:
        """Throttled journal head poll: the newest published round, or
        None while no journal is followed / the journal is empty.  One
        small read per probe interval, not per worker per tick."""
        if self._journal is None:
            return None
        if self._journal_head_round is not None and \
                now - self._journal_poll_t < self._probe_interval_s:
            return self._journal_head_round
        self._journal_poll_t = now
        try:
            h = self._journal.head()
        except Exception as exc:
            log_warning(f"fleet: delta journal head unreadable: "
                        f"{type(exc).__name__}: {exc}")
            return self._journal_head_round
        if h is not None:
            self._journal_head_round = int(h.round)
        return self._journal_head_round

    def _note_rounds(self, w: WorkerHandle, target: int) -> None:
        """Refresh the per-worker freshness gauges.  Called for DEAD
        workers too: a crashed worker's acked round freezes while the
        head advances, so its rounds-behind gauge keeps aging and the
        staleness SLO burns until re-anchor + replay catches it up."""
        if w.acked_round is None or self._publish_model is None:
            return
        self._model_round_g.set(float(w.acked_round),
                                model=self._publish_model, worker=w.name)
        self._rounds_behind_g.set(float(max(0, target - w.acked_round)),
                                  model=self._publish_model,
                                  worker=w.name)

    def _owns_published(self, w: WorkerHandle) -> bool:
        """Hash placement: only the published model's current OWNER is
        followed by the delta lane — pushing rounds to workers the
        dispatcher never routes the model to would just burn deploy
        bandwidth.  A non-owner's freshness series is dropped (not
        frozen): a dead ex-owner must not burn the staleness SLO while
        the live owner is current."""
        if self._ring is None or self._publish_model is None:
            return True
        with self._lock:
            alive = {x.wid for x in self._workers if x.state == "alive"}
        if self._ring.owner(self._publish_model, alive or {w.wid}) \
                == w.wid:
            return True
        self._model_round_g.remove_series(worker=w.name)
        self._rounds_behind_g.remove_series(worker=w.name)
        return False

    def _anchor_base(self, w: WorkerHandle) -> bool:
        """Re-anchor one worker on the journal's newest BASE by a full
        ``POST /models`` reload (which clears the worker registry's
        chain position), so the next delta replays cleanly from the
        base round."""
        try:
            entry = self._journal.base_entry()
        except Exception:
            return False
        if entry is None:
            return False
        path, base_round = entry
        name = self._publish_model
        try:
            status, detail = self._worker_post_json(
                w, "/models", {"name": name, "file": path},
                self._deploy_timeout_s)
        except Exception as exc:
            log_warning(f"fleet: {w.name} delta re-anchor failed: "
                        f"{type(exc).__name__}: {exc}")
            return False
        if status != 200:
            log_warning(f"fleet: {w.name} rejected re-anchor base for "
                        f"'{name}' ({status}): "
                        f"{detail.get('error', detail)}")
            return False
        w.acked_round = base_round
        w.delta_incarnation = w.incarnation
        log_info(f"fleet: {w.name} re-anchored '{name}' at round "
                 f"{base_round} ({os.path.basename(path)})")
        return True

    def _sync_deltas(self, w: WorkerHandle, now: float) -> None:
        """Push published delta records to one alive worker until it
        serves the journal head round.  A worker with an unknown chain
        position (fresh incarnation) or one that 409s a push (chain
        mismatch after a deploy or a divergent base) is re-anchored by
        a full reload of the newest BASE and replayed forward — the
        fallback the DeltaChainError contract promises."""
        target = self._journal_target(now)
        if target is None or self._publish_model is None:
            return
        if not self._owns_published(w):
            return
        if w.delta_incarnation != w.incarnation or w.acked_round is None:
            # a respawn boots from its CLI model file: position unknown
            if not self._anchor_base(w):
                return
        if w.acked_round >= target:
            self._note_rounds(w, target)
            return
        try:
            records = self._journal.records_after(w.acked_round)
        except Exception as exc:
            log_warning(f"fleet: delta journal chain unreadable: "
                        f"{type(exc).__name__}: {exc}")
            return
        name = self._publish_model
        for rec in records:
            if rec.round <= w.acked_round:
                continue
            try:
                status, detail = self._worker_post_json(
                    w, f"/models/{name}/delta",
                    {"record_b64": base64.b64encode(
                        rec.to_bytes()).decode("ascii")},
                    self._deploy_timeout_s)
            except Exception as exc:
                self._delta_pushes.inc(1, outcome="error")
                log_warning(f"fleet: {w.name} delta push (round "
                            f"{rec.round}) failed: "
                            f"{type(exc).__name__}: {exc}")
                return
            if status == 409:
                # the worker's chain diverged: full reload + replay
                # resumes next tick from the fresh anchor
                self._delta_pushes.inc(1, outcome="reanchor")
                w.acked_round = None
                self._anchor_base(w)
                return
            if status != 200:
                self._delta_pushes.inc(1, outcome="rejected")
                log_warning(f"fleet: {w.name} rejected delta round "
                            f"{rec.round} ({status}): "
                            f"{detail.get('error', detail)}")
                return
            self._delta_pushes.inc(1, outcome="ok")
            w.acked_round = int(rec.round)
            log_debug(f"fleet: {w.name} applied delta round "
                      f"{rec.round} ({detail.get('mode', '?')})")
        self._note_rounds(w, max(target, w.acked_round))

    def _probe_health(self, w: WorkerHandle,
                      timeout: Optional[float] = None) -> Optional[str]:
        """One /healthz probe; the status string, or None when the
        worker is unreachable/hung past the probe timeout."""
        if w.port is None:
            return None
        try:
            payload = self._worker_get_json(
                w, "/healthz", timeout or self._probe_timeout_s)
            return str(payload.get("status", "ok"))
        except Exception:
            return None

    def _tick(self) -> None:
        now = time.monotonic()
        if self._ring is not None:
            # placement epoch: any alive-set change re-places names, so
            # every worker's placed subset is re-synced against the new
            # assignment (death -> the fallen names load onto the next
            # ring node; revival -> the names come home)
            cur = tuple(sorted(w.wid for w in self._workers
                               if w.state == "alive"))
            if cur != self._alive_ids:
                self._alive_ids = cur
                self._placement_gen += 1
                log_info(f"fleet: placement epoch {self._placement_gen} "
                         f"over alive workers "
                         f"{[f'w{i}' for i in cur] or 'none'}")
        for w in self._workers:
            state = w.state
            if state in ("stopped", "draining"):
                continue
            if state in ("starting", "alive") and w.proc is not None and \
                    w.proc.poll() is not None:
                rc = w.proc.poll()
                log_warning(f"fleet: {w.name} exited with code {rc}")
                self._record_failure(w, "exit", now)
                continue
            if state == "starting":
                if w.port is None:
                    w.port = self._read_port_file(w)
                boot_health = (self._probe_health(w)
                               if w.port is not None else None)
                if boot_health is not None:
                    with self._lock:
                        w.state = "alive"
                    w.last_probe_t = now
                    # keep the REAL boot status: a worker that comes up
                    # degraded (CPU fallback) must weigh 1x in dispatch
                    # from its first request, not 4x until the next probe
                    w.last_health = boot_health
                    if self._sync_models(w):
                        w.synced_incarnation = w.incarnation
                        w.placed_gen = self._placement_gen
                    self._sync_deltas(w, now)
                    log_info(f"fleet: {w.name} alive on port {w.port}"
                             + (" (breaker half-open probe)"
                                if w.probing else ""))
                elif now - w.spawn_t > self._startup_timeout_s:
                    log_warning(f"fleet: {w.name} never became healthy "
                                f"within {self._startup_timeout_s:.0f}s")
                    self._kill_worker(w)
                    self._record_failure(w, "hang", now)
                continue
            if state == "backoff":
                if now >= w.next_restart_t:
                    self._spawn(w, now)
                continue
            if state == "quarantined":
                if now - w.quarantined_at >= self._halfopen_s:
                    log_info(f"fleet: breaker half-open for {w.name}; "
                             f"spawning one probe worker")
                    w.probing = True
                    w.restarts += 1
                    self._restarts.inc(1, reason="probe")
                    self._spawn(w, now)
                continue
            if state == "alive" and \
                    now - w.last_probe_t >= self._probe_interval_s:
                w.last_probe_t = now
                status = self._probe_health(w)
                if status is None:
                    w.consecutive_probe_failures += 1
                    if w.consecutive_probe_failures >= self._hang_probes:
                        log_warning(
                            f"fleet: {w.name} failed "
                            f"{w.consecutive_probe_failures} health "
                            f"probes; killing the wedged worker")
                        self._kill_worker(w)
                        self._record_failure(w, "hang", now)
                    continue
                w.consecutive_probe_failures = 0
                w.last_health = status
                # age failures out of the breaker window during stable
                # operation too, and give a clean sheet its base
                # backoff again — an isolated crash a day should not
                # pay the escalated delay of last week's blip
                while w.fail_times and \
                        w.fail_times[0] < now - self._breaker_window_s:
                    w.fail_times.popleft()
                if not w.fail_times and not w.probing:
                    w.backoff_s = 0.0
                if (w.synced_incarnation != w.incarnation or
                        (self._ring is not None and
                         w.placed_gen != self._placement_gen)) and \
                        self._sync_models(w):
                    w.synced_incarnation = w.incarnation
                    w.placed_gen = self._placement_gen
                self._sync_deltas(w, now)
                if w.probing:
                    w.probe_ok_streak += 1
                    if w.probe_ok_streak >= self._probe_ok_needed:
                        w.probing = False
                        w.fail_times.clear()
                        w.backoff_s = 0.0
                        log_info(f"fleet: breaker CLOSED for {w.name} "
                                 f"({w.probe_ok_streak} clean probes)")
        alive = sum(1 for w in self._workers if w.state == "alive")
        quarantined = sum(1 for w in self._workers
                          if w.state == "quarantined")
        self._alive_g.set(float(alive))
        self._quar_g.set(float(quarantined))
        if self._journal is not None:
            # age every worker's freshness gauge against the head —
            # including dead/restarting workers, whose frozen acked
            # round falls behind as the trainer keeps publishing
            target = self._journal_target(now)
            if target is not None:
                for w in self._workers:
                    if self._owns_published(w):
                        self._note_rounds(w, target)

    def _run_supervision(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(min(0.25, self._probe_interval_s))
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self._tick()
            except Exception as exc:   # supervision must never die
                log_warning(f"fleet: supervision tick failed: "
                            f"{type(exc).__name__}: {exc}")

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "FleetSupervisor":
        now = time.monotonic()
        for w in self._workers:
            self._spawn(w, now)
        deadline = now + self._startup_timeout_s
        while time.monotonic() < deadline:
            self._tick()
            if all(w.state == "alive" for w in self._workers):
                break
            time.sleep(0.05)
        if not all(w.state == "alive" for w in self._workers):
            bad = [w.name for w in self._workers if w.state != "alive"]
            for w in self._workers:
                self._kill_worker(w)
            self._httpd.server_close()
            raise RuntimeError(
                f"fleet startup failed: worker(s) {bad} never became "
                f"healthy within {self._startup_timeout_s:.0f}s (logs in "
                f"{self.run_dir})")
        self._sup_thread = threading.Thread(
            target=self._run_supervision, daemon=True,
            name="lgb-tpu-fleet-supervisor")
        self._sup_thread.start()
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="lgb-tpu-fleet-dispatch")
        self._http_thread.start()
        return self

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT -> rolling drain and exit ``128+signum`` (a
        repeat signal aborts immediately).  Main-thread only."""
        def _on_signal(signum: int, frame) -> None:
            if self.signal_received is not None:
                os._exit(128 + int(signum))
            self.signal_received = int(signum)
            log_warning(f"fleet: received signal {signum}; rolling "
                        f"drain (repeat to abort)")
            threading.Thread(target=self._httpd.shutdown,
                             daemon=True).start()
        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)

    def shutdown(self) -> None:
        """Rolling drain: workers leave dispatch one at a time, each
        SIGTERMed and given ``drain_timeout_s`` to finish its in-flight
        requests (the worker-side drain discipline) before the next one
        starts; the dispatcher then stops."""
        if self._shut_down:
            return
        self._shut_down = True
        self._stop.set()
        self._wake.set()
        if self._sup_thread is not None:
            self._sup_thread.join(5.0)
        for w in self._workers:
            with self._lock:
                w.state = "draining"
            proc = w.proc
            if proc is not None and proc.poll() is None:
                try:
                    proc.terminate()
                    proc.wait(self._drain_timeout_s)
                except subprocess.TimeoutExpired:
                    log_warning(f"fleet: {w.name} ignored SIGTERM for "
                                f"{self._drain_timeout_s:.0f}s; killing")
                    self._kill_worker(w)
                except OSError:
                    pass
            with self._lock:
                w.state = "stopped"
        with self._active_cv:
            self._draining = True
        if self._http_thread is not None:
            self._httpd.shutdown()
        deadline = time.monotonic() + 5.0
        with self._active_cv:
            while self._active > 0 and time.monotonic() < deadline:
                self._active_cv.wait(0.2)
        self._httpd.server_close()
        if self._http_thread is not None:
            self._http_thread.join(5.0)

    # -- dispatch -----------------------------------------------------------
    def note_dispatch_failure(self, w: WorkerHandle) -> None:
        """A forward hit a connection failure: wake supervision so the
        dead process is noticed this tick, not next poll."""
        self._wake.set()

    def pick_worker(self, exclude: Tuple[int, ...] = ()
                    ) -> Optional[WorkerHandle]:
        """Health-weighted smooth round-robin over routable workers
        (the nginx algorithm: add each candidate's effective weight,
        pick the largest accumulated weight, subtract the total)."""
        with self._lock:
            cands: List[Tuple[WorkerHandle, float]] = []
            for w in self._workers:
                if w.state != "alive" or w.port is None or \
                        w.wid in exclude:
                    continue
                weight = _WEIGHT_DEGRADED if w.last_health == "degraded" \
                    else _WEIGHT_OK
                cands.append((w, float(weight)))
            if not cands:
                return None
            total = sum(wt for _, wt in cands)
            best: Optional[WorkerHandle] = None
            for w, wt in cands:
                w.current_weight += wt
                if best is None or w.current_weight > best.current_weight:
                    best = w
            assert best is not None
            best.current_weight -= total
            return best

    def _pick_placed(self, name: Optional[str],
                     exclude: Tuple[int, ...] = ()
                     ) -> Optional[WorkerHandle]:
        """Hash placement's router: the ring owner of ``name`` among
        routable workers.  ``exclude`` (connection-reset retries) walks
        to the NEXT ring node — the same fallback order re-placement
        uses, so the retry lands where the model will live next."""
        if name is None:
            name = next(iter(self._current_models), None)
            if name is None:
                return None
        with self._lock:
            routable = {w.wid for w in self._workers
                        if w.state == "alive" and w.port is not None and
                        w.wid not in exclude}
            wid = self._ring.owner(name, routable)
            if wid is None:
                return None
            return next(w for w in self._workers if w.wid == wid)

    def _retry_after_s(self) -> float:
        """Backoff hint while nothing is routable: time to the next
        restart attempt or breaker half-open probe."""
        now = time.monotonic()
        horizons = []
        for w in self._workers:
            if w.state == "backoff":
                horizons.append(max(0.0, w.next_restart_t - now))
            elif w.state == "quarantined":
                horizons.append(max(0.0, w.quarantined_at +
                                    self._halfopen_s - now))
            elif w.state == "starting":
                horizons.append(self._probe_interval_s)
        return max(1.0, min(horizons)) if horizons else 1.0

    def dispatch_predict(self, body: bytes, rid: str
                         ) -> Tuple[int, bytes, Dict[str, str]]:
        """Route one /predict body; returns (status, body, headers).
        Connection-reset failures retry against a different worker
        within the retry budget; worker responses (including 5xx) are
        forwarded verbatim."""
        t0 = time.monotonic()
        base_deadline = 0.0
        req: Optional[Dict[str, Any]] = None
        if self._ring is not None or self._deadline_ms > 0 or \
                b"deadline_ms" in body:
            # hash placement must parse the body regardless of deadline
            # config: routing is BY the request's model name
            try:
                req = json.loads(body)
                base_deadline = float(req.get("deadline_ms") or
                                      self._deadline_ms)
            except (ValueError, TypeError, AttributeError):
                req = None   # malformed body: forward raw, worker 400s
        route_model: Optional[str] = None
        if self._ring is not None and req is not None and \
                req.get("model"):
            route_model = str(req["model"])
        tried: List[int] = []
        attempts = 0
        last_err = "no routable worker"
        while attempts <= self._retry_budget:
            w = self._pick_placed(route_model, exclude=tuple(tried)) \
                if self._ring is not None \
                else self.pick_worker(exclude=tuple(tried))
            if w is None:
                if not tried:
                    # nothing routable at all (every worker quarantined
                    # or restarting): fast-fail with a backoff hint
                    retry_after = self._retry_after_s()
                    payload = json.dumps({
                        "error": "no serving worker available "
                                 "(fleet degraded)",
                        "retry_after_s": retry_after}).encode()
                    return 503, payload, {
                        "Retry-After": str(max(1, int(-(-retry_after
                                                        // 1))))}
                break   # reset with no alternate worker left
            port = w.port
            if port is None:
                # the worker died between pick_worker and the connect
                # (supervision nulls the port without the dispatch
                # lock): not a dispatched attempt — skip it, burn
                # neither retry budget nor the retry counter
                tried.append(w.wid)
                continue
            if attempts:
                # a cross-worker retry is actually dispatching now that
                # an alternate routable worker exists
                self._retries.inc(1)
                log_debug(f"fleet: retrying /predict on {w.name} after "
                          f"{last_err}")
            payload_bytes = body
            if req is not None and base_deadline > 0:
                remaining = base_deadline - (time.monotonic() - t0) * 1e3
                if remaining <= 1.0:
                    return 504, json.dumps({
                        "error": "deadline exhausted in the dispatch "
                                 "hop"}).encode(), {}
                req["deadline_ms"] = remaining
                payload_bytes = json.dumps(req).encode()
            conn = None
            try:
                conn = http.client.HTTPConnection(
                    self._host, port, timeout=self._forward_timeout_s)
                conn.request("POST", "/predict", payload_bytes, {
                    "Content-Type": "application/json",
                    "Content-Length": str(len(payload_bytes)),
                    "X-Request-Id": rid})
                resp = conn.getresponse()
                data = resp.read()
                headers = {}
                for key in ("Retry-After", "X-Request-Id"):
                    v = resp.getheader(key)
                    if v:
                        headers[key] = v
                return resp.status, data, headers
            except TimeoutError as exc:
                # connect/read timeout: the request MAY have executed on
                # the worker (a wedged device call, serve_hang_ms chaos)
                # — never retried, surfaced as a gateway timeout rather
                # than a dispatcher bug
                self.note_dispatch_failure(w)
                return 504, json.dumps({
                    "error": f"worker {w.name} timed out after "
                             f"{self._forward_timeout_s:.0f}s in the "
                             f"forward hop: {type(exc).__name__}"
                }).encode(), {}
            except _RETRYABLE as exc:
                tried.append(w.wid)
                attempts += 1
                last_err = f"{type(exc).__name__}: {exc}"
                self.note_dispatch_failure(w)
            finally:
                if conn is not None:
                    try:
                        conn.close()
                    except Exception:
                        pass
        payload = json.dumps({
            "error": f"worker connection failed and the retry budget "
                     f"({self._retry_budget}) is spent: {last_err}"
        }).encode()
        return 502, payload, {}

    # -- worker HTTP helpers ------------------------------------------------
    def _worker_get_text(self, w: WorkerHandle, path: str,
                         timeout: float) -> str:
        if w.port is None:
            raise ConnectionError(f"{w.name} has no port")
        conn = http.client.HTTPConnection(self._host, w.port,
                                          timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            data = resp.read().decode()
            if resp.status != 200:
                raise RuntimeError(f"{w.name}{path} -> {resp.status}")
            return data
        finally:
            conn.close()

    def _worker_get_json(self, w: WorkerHandle, path: str,
                         timeout: float) -> Dict[str, Any]:
        out = json.loads(self._worker_get_text(w, path, timeout))
        return out if isinstance(out, dict) else {"payload": out}

    def _worker_post_json(self, w: WorkerHandle, path: str,
                          payload: Dict[str, Any], timeout: float
                          ) -> Tuple[int, Dict[str, Any]]:
        if w.port is None:
            raise ConnectionError(f"{w.name} has no port")
        body = json.dumps(payload).encode()
        conn = http.client.HTTPConnection(self._host, w.port,
                                          timeout=timeout)
        try:
            conn.request("POST", path, body,
                         {"Content-Type": "application/json",
                          "Content-Length": str(len(body))})
            resp = conn.getresponse()
            data = resp.read()
            try:
                parsed = json.loads(data)
            except ValueError:
                parsed = {"raw": data.decode(errors="replace")}
            return resp.status, parsed
        finally:
            conn.close()

    # -- rolling deploy -----------------------------------------------------
    def deploy(self, name: str, path: str) -> Dict[str, Any]:
        """Zero-downtime rolling model deploy: one worker at a time
        loads + warms the new version (the worker's registry swap is
        atomic, so it serves old-version traffic until the instant the
        warm predictor is ready), then its post-swap health is checked —
        a regression rolls THAT worker back to its previous source and
        aborts the roll.  Workers not currently alive are skipped; they
        boot the new version on their next respawn once the roll
        completes."""
        path = os.path.abspath(path)
        report: Dict[str, Any] = {"model": name, "file": path,
                                  "deployed": [], "skipped": [],
                                  "rolled_back": []}
        with self._deploy_lock:
            if self._ring is not None:
                with self._lock:
                    alive = {w.wid for w in self._workers
                             if w.state == "alive" and
                             w.port is not None}
                owner = self._ring.owner(name, alive)
            for w in list(self._workers):
                if w.state != "alive" or w.port is None or \
                        (self._ring is not None and w.wid != owner):
                    # hash placement deploys to the name's OWNER only;
                    # everyone else picks the version up on re-placement
                    report["skipped"].append(w.name)
                    continue
                before = self._probe_health(w) or "unreachable"
                prev: Optional[str] = None
                try:
                    models = self._worker_get_json(
                        w, "/models", self._probe_timeout_s)
                    prev = (models.get(name) or {}).get("source")
                except Exception:
                    prev = None
                try:
                    status, detail = self._worker_post_json(
                        w, "/models", {"name": name, "file": path},
                        self._deploy_timeout_s)
                except Exception as exc:
                    report["verdict"] = "aborted"
                    report["error"] = (f"{w.name} unreachable during "
                                       f"swap: {type(exc).__name__}: "
                                       f"{exc}")
                    return report
                if status != 200:
                    # the worker's load failed BEFORE any swap (corrupt
                    # file, bad params): its old version is untouched —
                    # abort the roll, nothing to roll back
                    report["verdict"] = "aborted"
                    report["error"] = (f"{w.name} rejected the new "
                                       f"version ({status}): "
                                       f"{detail.get('error', detail)}")
                    return report
                after = self._probe_health(w)
                if after is None or (after == "degraded" and
                                     before == "ok"):
                    log_warning(f"fleet: {w.name} health regressed "
                                f"after swapping '{name}' "
                                f"({before} -> {after}); rolling back")
                    if prev:
                        try:
                            self._worker_post_json(
                                w, "/models", {"name": name,
                                               "file": prev},
                                self._deploy_timeout_s)
                            report["rolled_back"].append(w.name)
                        except Exception as exc:
                            report["rollback_error"] = \
                                f"{type(exc).__name__}: {exc}"
                    report["verdict"] = "rolled_back"
                    report["error"] = (f"{w.name} post-swap health "
                                       f"regressed ({before} -> "
                                       f"{after})")
                    return report
                report["deployed"].append(w.name)
                log_info(f"fleet: {w.name} now serves '{name}' from "
                         f"{os.path.basename(path)}")
            # future respawns boot the rolled-out version (new logical
            # names included — a respawned worker must serve every
            # model the fleet's clients can name)
            self._current_models[name] = path
            report["verdict"] = "deployed"
            return report

    # -- aggregated observability ------------------------------------------
    def health(self) -> Dict[str, Any]:
        """Fleet ``/healthz``: ``ok`` only while every worker is alive
        and individually healthy; otherwise ``degraded`` with reasons
        (still 200 — the tier answers as long as one worker does)."""
        self.slo_engine.evaluate()
        reasons: List[str] = []
        table: Dict[str, Any] = {}
        alive = 0
        for w in self._workers:
            table[w.name] = w.snapshot()
            if w.state == "alive":
                alive += 1
                if w.last_health == "degraded":
                    reasons.append(f"{w.name} reports degraded health")
            elif w.state == "quarantined":
                reasons.append(f"{w.name} quarantined (crash-loop "
                               f"breaker open)")
            elif w.state in ("starting", "backoff"):
                reasons.append(f"{w.name} restarting ({w.state})")
        for name in self.slo_engine.degraded():
            reasons.append(f"slo_fast_burn: {name}")
        out: Dict[str, Any] = {
            "status": "degraded" if reasons else "ok",
            "fleet": True,
            "workers_alive": alive,
            "workers_total": len(self._workers),
            "workers": table,
        }
        if reasons:
            out["reasons"] = reasons
        return out

    def slo_report(self) -> Dict[str, Any]:
        """Fleet ``/slo``: the declared objectives evaluated against
        the FLEET registry (dispatcher responses, worker gauges, retry
        counters), with each worker's own ``/slo`` verdict attached —
        one scrape answers both "is the tier meeting its SLOs" and
        "which worker is burning"."""
        fleet_rep = self.slo_engine.evaluate()
        workers: Dict[str, Any] = {}
        for w in self._workers:
            if w.state != "alive" or w.port is None:
                workers[w.name] = {"unreachable": True, "state": w.state}
                continue
            try:
                workers[w.name] = self._worker_get_json(
                    w, "/slo", self._probe_timeout_s)
            except Exception:
                workers[w.name] = {"unreachable": True,
                                   "state": w.state}
        return {"schema": "fleet-slo-report-v1",
                "ok": bool(fleet_rep.get("ok")),
                "fleet": fleet_rep,
                "workers": workers}

    def metrics_text(self) -> str:
        """Fleet ``/metrics``: the fleet registry (supervision gauges,
        restart/retry counters, dispatcher response codes, SLO burn
        gauges) plus every reachable worker's scrape re-exported as
        ``lgbm_tpu_worker_*`` with a ``worker`` label — one scrape
        carries the whole tier."""
        from .loadgen import parse_prometheus
        from ..telemetry.export import _labels, _num, render_prometheus
        self.slo_engine.evaluate()   # burn gauges refresh pre-render
        lines = [render_prometheus(registry=self._metrics).rstrip("\n")]
        for w in list(self._workers):
            if w.state != "alive" or w.port is None:
                continue
            try:
                text = self._worker_get_text(w, "/metrics", 2.0)
            except Exception:
                continue
            for name, series in sorted(parse_prometheus(text).items()):
                wname = name.replace("lgbm_tpu_", "lgbm_tpu_worker_", 1)
                for lbl, val in series:
                    lbl2 = dict(lbl)
                    lbl2["worker"] = w.name
                    lines.append(f"{wname}{_labels(lbl2)} {_num(val)}")
        return "\n".join(lines) + "\n"

    def proxy_get(self, path: str) -> Dict[str, Any]:
        """Per-worker fan-out of a worker JSON endpoint (``/models``,
        ``/stats``)."""
        out: Dict[str, Any] = {}
        for w in list(self._workers):
            if w.state != "alive" or w.port is None:
                out[w.name] = {"unreachable": True, "state": w.state}
                continue
            try:
                out[w.name] = self._worker_get_json(
                    w, path, self._probe_timeout_s)
            except Exception as exc:
                out[w.name] = {"unreachable": True,
                               "error": f"{type(exc).__name__}"}
        return out

    def placement_table(self) -> Optional[Dict[str, Any]]:
        """The live worker -> placed-models map (hash placement only,
        None otherwise): every ``_current_models`` name resolved
        through the ring against the routable set — the assignment the
        dispatcher is using RIGHT NOW, dead workers already routed
        around."""
        if self._ring is None:
            return None
        with self._lock:
            routable = {w.wid for w in self._workers
                        if w.state == "alive" and w.port is not None}
        table: Dict[str, List[str]] = {w.name: [] for w in self._workers}
        unplaced: List[str] = []
        for n in sorted(self._current_models):
            wid = self._ring.owner(n, routable)
            if wid is None:
                unplaced.append(n)
            else:
                table[f"w{wid}"].append(n)
        out: Dict[str, Any] = {"mode": "hash",
                               "vnodes": self._ring.vnodes,
                               "epoch": self._placement_gen,
                               "workers": table}
        if unplaced:
            out["unplaced"] = unplaced
        return out

    def workers_table(self) -> Dict[str, Any]:
        out = {"workers": {w.name: w.snapshot()
                           for w in self._workers},
               "breaker": {"failures": self._breaker_failures,
                           "window_s": self._breaker_window_s,
                           "halfopen_s": self._halfopen_s}}
        pl = self.placement_table()
        if pl is not None:
            out["placement"] = pl
        return out

    # -- dispatcher handler accounting --------------------------------------
    def _enter(self) -> bool:
        with self._active_cv:
            if self._draining:
                return False
            self._active += 1
            return True

    def _exit(self) -> None:
        with self._active_cv:
            self._active -= 1
            if self._active <= 0:
                self._active_cv.notify_all()


def _make_fleet_handler(fleet: FleetSupervisor):
    class FleetHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            log_debug("fleet: " + fmt % args)

        def _reply(self, code: int, payload: Dict[str, Any],
                   extra_headers: Optional[Dict[str, str]] = None
                   ) -> None:
            body = json.dumps(payload).encode()
            self._reply_raw(code, body, extra_headers)

        def _reply_raw(self, code: int, body: bytes,
                       extra_headers: Optional[Dict[str, str]] = None,
                       content_type: str = "application/json") -> None:
            fleet._responses.inc(1, code=str(int(code)))
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra_headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            try:
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass   # the client went away mid-write

        def do_GET(self):
            if self.path == "/healthz":
                self._reply(200, fleet.health())
            elif self.path == "/slo":
                self._reply(200, fleet.slo_report())
            elif self.path == "/workers":
                self._reply(200, fleet.workers_table())
            elif self.path == "/metrics":
                from ..telemetry.export import PROMETHEUS_CONTENT_TYPE
                self._reply_raw(200, fleet.metrics_text().encode(),
                                content_type=PROMETHEUS_CONTENT_TYPE)
            elif self.path in ("/models", "/stats"):
                out = fleet.proxy_get(self.path)
                if self.path == "/models":
                    pl = fleet.placement_table()
                    if pl is not None:
                        # the worker -> placed-models aggregation rides
                        # the same payload under a non-worker key
                        out["_placement"] = pl
                self._reply(200, out)
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            if self.path == "/predict":
                self._post_predict()
            elif self.path == "/models":
                self._post_models()
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def _post_predict(self) -> None:
            rid = self.headers.get("X-Request-Id") or \
                f"fleet-{os.getpid():x}-{threading.get_ident():x}-" \
                f"{time.monotonic_ns():x}"
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) if length > 0 else b""

            def reply(code: int, payload: bytes,
                      headers: Dict[str, str]) -> None:
                fleet._predict_responses.inc(1, code=str(int(code)))
                headers = dict(headers)
                headers.setdefault("X-Request-Id", rid)
                self._reply_raw(code, payload, headers)

            if not fleet._enter():
                reply(503, json.dumps(
                    {"error": "fleet is draining"}).encode(),
                    {"Retry-After": "1"})
                return
            try:
                status, data, headers = fleet.dispatch_predict(body, rid)
            except Exception as exc:   # dispatcher bug, not worker's
                reply(500, json.dumps(
                    {"error": f"{type(exc).__name__}: {exc}"}).encode(),
                    {})
                return
            finally:
                fleet._exit()
            reply(status, data, headers)

        def _post_models(self) -> None:
            try:
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length)) if length \
                    else {}
            except (ValueError, UnicodeDecodeError) as exc:
                self._reply(400, {"error": f"bad JSON body: {exc}"})
                return
            name, path = req.get("name"), req.get("file")
            if not name or not path:
                self._reply(400, {"error": "body needs 'name' and "
                                           "'file'"})
                return
            report = fleet.deploy(str(name), str(path))
            code = 200 if report.get("verdict") == "deployed" else 409
            self._reply(code, report)

    return FleetHandler


# keys the fleet CLI consumes itself; everything else passes through to
# the worker command lines
_FLEET_KEYS = {
    "workers", "host", "port", "retry_budget", "deadline_ms",
    "probe_interval_s", "probe_timeout_s", "hang_probes",
    "breaker_failures", "breaker_window_s", "breaker_halfopen_s",
    "backoff_base_s", "backoff_max_s", "drain_timeout_s",
    "startup_timeout_s", "run_dir", "publish_dir", "publish_model",
    "placement", "vnodes",
}


def main(argv: List[str]) -> int:
    """``python -m lightgbm_tpu serve-fleet model.txt [workers=4]
    [port=8080] [key=value ...]``.

    Fleet keys: workers (2), host, port (8080), retry_budget (1),
    deadline_ms (0), probe_interval_s (1.0), probe_timeout_s (2.0),
    hang_probes (3), breaker_failures (3), breaker_window_s (30),
    breaker_halfopen_s (5), backoff_base_s (0.2), backoff_max_s (5),
    drain_timeout_s (30), startup_timeout_s (120), run_dir,
    publish_dir (follow a trainer's delta journal and live-refresh
    every worker), publish_model (logical name the deltas apply to;
    defaults to the first model), placement (replicate | hash — hash
    shards the model set across workers by consistent hash: the
    dispatcher routes /predict by the request's model to its owner,
    workers boot/sync only their placed subset in zoo mode, a dead
    worker's names fall to the next ring node), vnodes (64).  Every
    other ``key=value`` passes through to the worker serve processes
    (``max_queue_rows``, ``max_wait_ms``, ``deadline_ms`` stays
    fleet-side, ...).  SIGTERM runs a rolling drain and exits
    ``128+signum``.
    """
    from ..utils.log import log_fatal
    files = [a for a in argv if "=" not in a]
    kv = {k: v for k, v in (a.split("=", 1) for a in argv if "=" in a)}
    if not files:
        log_fatal("serve-fleet needs at least one model file: "
                  "python -m lightgbm_tpu serve-fleet model.txt "
                  "[workers=4 port=8080 ...]")
    worker_args = {k: v for k, v in kv.items() if k not in _FLEET_KEYS}
    fleet = FleetSupervisor(
        files,
        workers=int(kv.get("workers", 2)),
        host=kv.get("host", "127.0.0.1"),
        port=int(kv.get("port", 8080)),
        worker_args=worker_args,
        run_dir=kv.get("run_dir"),
        probe_interval_s=float(kv.get("probe_interval_s", 1.0)),
        probe_timeout_s=float(kv.get("probe_timeout_s", 2.0)),
        hang_probes=int(kv.get("hang_probes", 3)),
        breaker_failures=int(kv.get("breaker_failures", 3)),
        breaker_window_s=float(kv.get("breaker_window_s", 30.0)),
        breaker_halfopen_s=float(kv.get("breaker_halfopen_s", 5.0)),
        backoff_base_s=float(kv.get("backoff_base_s", 0.2)),
        backoff_max_s=float(kv.get("backoff_max_s", 5.0)),
        retry_budget=int(kv.get("retry_budget", 1)),
        deadline_ms=float(kv.get("deadline_ms", 0.0)),
        drain_timeout_s=float(kv.get("drain_timeout_s", 30.0)),
        startup_timeout_s=float(kv.get("startup_timeout_s", 120.0)),
        publish_dir=kv.get("publish_dir"),
        publish_model=kv.get("publish_model"),
        placement=kv.get("placement", "replicate"),
        placement_vnodes=int(kv.get("vnodes", 64)))
    fleet.start()
    try:
        fleet.install_signal_handlers()
    except ValueError:
        pass   # not the main thread
    log_info(f"fleet: dispatching on http://{fleet.host}:{fleet.port} "
             f"({len(fleet.workers())} workers, run dir "
             f"{fleet.run_dir})")
    try:
        # the dispatcher already serves on its own thread; the main
        # thread just waits for a signal-driven drain
        while fleet.signal_received is None:
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    sig = fleet.signal_received
    fleet.shutdown()
    log_info("fleet: drained")
    return 0 if sig is None else 128 + int(sig)
