"""Multi-tenant model zoo: bounded admission/eviction over the registry
plus batched cross-model MXU dispatch.

The registry (PR 1) already shares same-shape compile caches across
named models; this module grows it into a thousands-of-resident-models
tier with two properties the per-model path cannot have:

**Batched cross-model dispatch** (the hot path).  Tenants whose
predictors share one ``DenseExecutable.signature`` — same tree/node/leaf
envelope, leaf_bits, shard spec, table shapes — are fused into a
:class:`~.compiler.StackedExecutable`: their lowered tables stacked on a
leading model axis (the way ``multitrain/batched.py`` stacks training
lanes), so ONE MXU launch serves every member's micro-batch in a single
fused contraction.  The cross-model :class:`_StackBatcher` (a
``MicroBatcher`` whose dispatch hook forms (model-lane, bucket)
super-batches) coalesces per-tenant queues under the existing
max-wait/deadline discipline; each tenant's slice of the stacked output
is bitwise identical to a solo dispatch (every contraction in
``_dense_raw`` becomes a batched contraction of the same per-slice
shape under ``vmap`` — asserted by the zoo parity tests).

**Bounded admission/eviction.**  ``max_resident`` caps the resident set;
over budget the zoo evicts by traffic-weighted LRU (an exponentially
decayed per-tenant request weight — a hot tenant survives a recency
blip, a cold one does not).  A request for a non-resident model cold
loads it on miss through ``source_resolver``, spending the request's
remaining deadline budget — and 504s cleanly past it (the model stays
resident; only the requester that paid the compile is late).  Nothing
is silent: ``zoo_evictions_total{reason}`` / ``zoo_cold_loads_total``
count every decision, and eviction releases the tenant's metric series
and (for the last model of a shape) its compile-cache mirror entries.

**Per-tenant quotas** ride the PR 14 ``model=`` label machinery: each
tenant's lane backlog is bounded (``tenant_queue_rows``) and sheds
BEFORE the shared queue bound does — a hot tenant is refused before it
crowds out co-batched neighbours — tracked by the ``serve/tenant_quota``
ratio SLO declared below.

Program contracts (machine-checked by the ``serve_zoo`` lint config):
the ``serve/zoo_stack`` MemoryBudget bounds one stacked bucket program
(M times the per-model curve), and ``serve/zoo_stack/score_psum`` pins
the tree-sharded stacked program to exactly ONE psum of the (M, bucket,
num_class) partials — one collective per STACK, not one per tenant.
"""

from __future__ import annotations

import hashlib
import threading
import time
from concurrent.futures import InvalidStateError
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..analysis.contracts import collective_contract, memory_budget
from ..models.tree import bucket_rows, pad_rows
from ..resilience.admission import DeadlineExceeded, ServerClosed
from ..telemetry.metrics import default_registry
from ..telemetry.slo import register_metric_ensurer, slo
from .batcher import (_FUT, _LANE, _RID, _TSUB, _X, MicroBatcher,
                      TenantQueueFull)
from .compiler import StackedExecutable, dense_predict_hbm_bytes
from .predictor import _note_dispatch, release_compile_keys
from .registry import ModelRegistry

__all__ = ["ModelZoo"]

# ---------------------------------------------------------------------------
# program contracts — declared next to the stacked dispatch they bound
# ---------------------------------------------------------------------------

collective_contract(
    "serve/zoo_stack/score_psum", "psum",
    max_count=1,
    max_bytes_per_op=lambda ctx: 4 * int(ctx.get("models", 8)) *
    int(ctx.get("bucket", 4096)) * max(1, int(ctx.get("num_class", 1))),
    note="ONE psum of the per-shard (models, bucket, num_class) partial "
         "scores — one collective per stack, never one per tenant")


def zoo_stack_hbm_bytes(ctx):
    """Per-device HBM curve of one stacked bucket program: M model lanes
    each pay the per-model dense curve (the vmap batches every
    intermediate over the model axis)."""
    m = max(1, int(ctx.get("models", 8)))
    return m * dense_predict_hbm_bytes(ctx) + (8 << 20)


memory_budget("serve/zoo_stack", ("serve_zoo",), zoo_stack_hbm_bytes,
              note="M stacked model lanes of the dense bucket program")


# ---------------------------------------------------------------------------
# zoo telemetry — never-silent admission decisions + the quota SLO
# ---------------------------------------------------------------------------

def _zoo_metrics(reg):
    return (
        reg.counter("zoo_evictions_total",
                    "models evicted from the zoo, by reason",
                    labels=("reason",)),
        reg.counter("zoo_cold_loads_total",
                    "models cold-loaded on a request miss"),
        reg.histogram("zoo_cold_load_ms",
                      "cold load-on-miss latency (resolve+build+warm)"),
        reg.counter("zoo_stack_batches_total",
                    "fused cross-model stacked launches, by stack group",
                    labels=("group",)),
        reg.counter("zoo_tenant_shed_total",
                    "requests shed by a tenant's own quota (before the "
                    "shared queue bound)", labels=("model",)),
        reg.gauge("zoo_resident_models", "models resident in the zoo"),
    )


@register_metric_ensurer
def _ensure_zoo_metrics(reg) -> None:
    _zoo_metrics(reg)


# Tenant-quota objective: the share of client predict calls refused by a
# PER-TENANT quota (not the shared queue bound — that is serve/shed_rate)
# must stay inside budget; a sustained burn means one tenant's quota is
# sized below its real traffic.
slo("serve/tenant_quota", metric="zoo_tenant_shed_total",
    total_metric="serve_requests_total", kind="ratio", target=0.99,
    min_events=50,
    note="per-tenant quota sheds over client predict calls")


def _sig_digest(sig) -> str:
    """Short stable digest of a shape signature — the operator-facing
    group key (matches ``CompiledPredictor.group_key``)."""
    return hashlib.sha1(repr(sig).encode()).hexdigest()[:12]


# ---------------------------------------------------------------------------
# the cross-model batcher
# ---------------------------------------------------------------------------

class _StackBatcher(MicroBatcher):
    """One shared coalescing queue for every tenant of one stack group.

    Inherits the whole admission/window/deadline discipline of
    :class:`MicroBatcher` (per-tenant quota included — submits carry
    ``lane=<model>``); only the dispatch hook differs: a drained window
    is regrouped into (model-lane, bucket) super-batches, each run as
    ONE stacked launch, each tenant's slice returned bitwise identical
    to solo dispatch.  Lanes whose model left the stack between submit
    and dispatch (membership churn) fall back to their solo predictor —
    correct, just not fused."""

    def __init__(self, zoo: "ModelZoo", sig: tuple, buckets: tuple,
                 **kw) -> None:
        # set before super().__init__: the worker thread starts there
        self._zoo_ref = zoo
        self._member_sig = sig
        self._stack_buckets = tuple(buckets)
        self._group = _sig_digest(sig)
        super().__init__(self._unused_fn, name=f"zoo:{self._group}",
                         buckets=buckets, **kw)

    @staticmethod
    def _unused_fn(X, raw_score):  # dispatch is fully overridden
        raise NotImplementedError

    def _fail(self, items, exc) -> None:
        for it in items:
            try:
                it[_FUT].set_exception(exc)
            except InvalidStateError:
                pass  # its waiter expired it in the race window

    def _dispatch_group(self, raw: bool, cols: int, group) -> None:
        zoo = self._zoo_ref
        stack = zoo.current_stack(self._member_sig)
        lanes: Dict[str, list] = {}
        for it in group:
            lanes.setdefault(it[_LANE], []).append(it)
        per_bucket: Dict[int, list] = {}
        for lane, items in lanes.items():
            if stack is None or lane not in stack.names:
                self._solo_fallback(lane, raw, items)
                continue
            Xl = (items[0][_X] if len(items) == 1 else
                  np.concatenate([it[_X] for it in items], axis=0))
            nb = bucket_rows(Xl.shape[0], self._stack_buckets)
            per_bucket.setdefault(nb, []).append((lane, Xl, items))
        for nb in sorted(per_bucket):
            self._dispatch_stacked(stack, raw, cols, nb, per_bucket[nb])

    def _dispatch_stacked(self, stack: StackedExecutable, raw: bool,
                          cols: int, nb: int, ents: list) -> None:
        """One (stack, bucket) super-batch: every active lane's padded
        block rides one fused launch; idle lanes are zero-filled so the
        stacked shape — and therefore the jit signature — never varies
        with WHICH tenants happen to be in the window."""
        from ..telemetry.trace import span
        zoo = self._zoo_ref
        t0 = time.monotonic()
        Xs = np.zeros((stack.width, nb, cols), np.float32)
        for lane, Xl, _items in ents:
            Xs[stack.lane(lane)] = pad_rows(Xl, self._stack_buckets)
        new = _note_dispatch((stack.signature, nb))
        try:
            with span(f"serve/zoo_stack/b{nb}"):
                out = np.asarray(stack.predict_raw(Xs))
        except Exception as exc:
            for _lane, _Xl, items in ents:
                self._fail(items, exc)
            return
        t1 = time.monotonic()
        zoo._stack_batches.inc(1, group=self._group)
        for j, (lane, Xl, items) in enumerate(ents):
            pred = zoo.peek(lane)
            if pred is None:
                # evicted between dispatch start and slicing: the lane's
                # scores exist but the objective transform is gone with
                # the predictor — a typed 503, never a torn result
                self._fail(items, ServerClosed(
                    f"model '{lane}' was evicted while the request was "
                    f"in flight"))
                continue
            n_l = int(Xl.shape[0])
            res = zoo._finish_raw(pred, out[stack.lane(lane)][:n_l], raw)
            ofs = 0
            for it in items:
                k = int(it[_X].shape[0])
                try:
                    it[_FUT].set_result(res[ofs:ofs + k])
                except InvalidStateError:
                    pass  # its waiter expired it in the race window
                ofs += k
            rids = tuple(it[_RID] for it in items if it[_RID])
            # one XLA trace per super-batch: attribute it once, not once
            # per lane, so serve_recompiles_total mirrors actual traces
            pred.stats.record_batch(n_l, nb, (t1 - t0) * 1e3,
                                    recompiled=new and j == 0,
                                    request_ids=rids if new else ())
            t_done = time.monotonic()
            for it in items:
                pred.stats.record_request_timing(
                    int(it[_X].shape[0]), nb,
                    queue_ms=(t0 - it[_TSUB]) * 1e3,
                    device_ms=(t1 - t0) * 1e3,
                    total_ms=(t_done - it[_TSUB]) * 1e3,
                    request_id=it[_RID])
        self._ewma_batch_s = 0.8 * self._ewma_batch_s + 0.2 * (t1 - t0)

    def _solo_fallback(self, lane: str, raw: bool, items: list) -> None:
        """Lane left the stack between submit and dispatch: serve it
        through its own predictor (same values, one extra launch)."""
        pred = self._zoo_ref.peek(lane)
        if pred is None:
            self._fail(items, ServerClosed(
                f"model '{lane}' was evicted while the request was "
                f"queued"))
            return
        t0 = time.monotonic()
        X = (items[0][_X] if len(items) == 1 else
             np.concatenate([it[_X] for it in items], axis=0))
        try:
            out = pred.predict(X, raw_score=raw, request_ids=tuple(
                it[_RID] for it in items if it[_RID]))
        except Exception as exc:
            self._fail(items, exc)
            return
        t1 = time.monotonic()
        ofs = 0
        for it in items:
            k = int(it[_X].shape[0])
            try:
                it[_FUT].set_result(out[ofs:ofs + k])
            except InvalidStateError:
                pass
            ofs += k
        nb = bucket_rows(X.shape[0], self._stack_buckets)
        t_done = time.monotonic()
        for it in items:
            pred.stats.record_request_timing(
                int(it[_X].shape[0]), nb,
                queue_ms=(t0 - it[_TSUB]) * 1e3,
                device_ms=(t1 - t0) * 1e3,
                total_ms=(t_done - it[_TSUB]) * 1e3,
                request_id=it[_RID])


# ---------------------------------------------------------------------------
# the zoo
# ---------------------------------------------------------------------------

class ModelZoo:
    """Bounded multi-tenant serving tier over a :class:`ModelRegistry`.

    ``source_resolver`` supplies cold-load sources: either a callable
    ``name -> source`` (path/text/Booster) or a directory path holding
    ``<name>.txt`` model files.  ``max_resident=0`` means unbounded.
    ``stacking`` gates the cross-model fused dispatch; with it off the
    zoo still does admission/eviction over per-model batchers.
    """

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 max_resident: int = 0,
                 source_resolver=None,
                 stacking: bool = True,
                 batching: bool = True,
                 max_batch_rows: int = 4096,
                 max_wait_ms: float = 2.0,
                 max_queue_rows: int = 0,
                 tenant_queue_rows: int = 0,
                 warmup: bool = False,
                 load_kwargs: Optional[dict] = None) -> None:
        self.registry = registry if registry is not None else ModelRegistry()
        self.max_resident = max(0, int(max_resident))
        self._resolver = self._as_resolver(source_resolver)
        self._stacking = bool(stacking)
        self._batching = bool(batching)
        self._max_batch_rows = int(max_batch_rows)
        self._max_wait_ms = float(max_wait_ms)
        self._max_queue_rows = int(max_queue_rows)
        self._tenant_queue_rows = int(tenant_queue_rows)
        self._warmup = bool(warmup)
        self._load_kwargs = dict(load_kwargs or {})
        self._lock = threading.Lock()
        # traffic-weighted LRU state: name -> [decayed weight, last touch]
        self._traffic: Dict[str, list] = {}
        self.traffic_tau_s = 60.0
        self._load_locks: Dict[str, threading.Lock] = {}
        self._stacks: Dict[tuple, StackedExecutable] = {}
        self._stack_batchers: Dict[tuple, _StackBatcher] = {}
        self._solo_batchers: Dict[str, MicroBatcher] = {}
        self._closed = False
        reg = default_registry()
        (self._evictions, self._cold_loads, self._cold_ms,
         self._stack_batches, self._tenant_shed,
         self._resident_gauge) = _zoo_metrics(reg)
        for name in self.registry.names():
            self._traffic[name] = [0.0, time.monotonic()]
        self._refresh_stacks()
        self._resident_gauge.set(len(self.registry.names()))

    @staticmethod
    def _as_resolver(source_resolver
                     ) -> Optional[Callable[[str], Any]]:
        if source_resolver is None or callable(source_resolver):
            return source_resolver
        base = str(source_resolver)

        def _from_dir(name: str) -> str:
            import os
            path = os.path.join(base, f"{name}.txt")
            if not os.path.exists(path):
                raise KeyError(f"unknown model '{name}' (no {path})")
            return path
        return _from_dir

    # -- admission ----------------------------------------------------------
    def load(self, name: str, source, **predictor_kwargs):
        """Load/hot-swap ``name`` (registry hot-swap discipline), then
        enforce the resident budget and refresh stack membership."""
        kw = {**self._load_kwargs, **predictor_kwargs}
        pred = self.registry.load(name, source, warmup=self._warmup, **kw)
        with self._lock:
            self._traffic.setdefault(name, [0.0, time.monotonic()])
        self._enforce_budget(exclude=name)
        self._refresh_stacks()
        self._resident_gauge.set(len(self.registry.names()))
        return pred

    def evict(self, name: str, reason: str = "manual") -> bool:
        """Evict ``name`` (never silent: counted by reason).  In-flight
        requests that already resolved the predictor complete normally
        — predictors are immutable — later ones get a typed 503."""
        try:
            ok = self.registry.evict(name, force=True)
        except KeyError:
            ok = False
        if not ok:
            return False
        with self._lock:
            self._traffic.pop(name, None)
            batcher = self._solo_batchers.pop(name, None)
        if batcher is not None:
            batcher.close(timeout=2.0)
        self._evictions.inc(1, reason=reason)
        self._refresh_stacks()
        self._resident_gauge.set(len(self.registry.names()))
        return True

    def _decayed_weight(self, name: str, now: float) -> float:
        w, t = self._traffic.get(name, (0.0, now))
        return w * np.exp(-(now - t) / self.traffic_tau_s)

    def _touch(self, name: str, rows: int) -> None:
        now = time.monotonic()
        with self._lock:
            ent = self._traffic.setdefault(name, [0.0, now])
            ent[0] = ent[0] * np.exp(-(now - ent[1]) /
                                     self.traffic_tau_s) + max(1, rows)
            ent[1] = now

    def _enforce_budget(self, exclude: Optional[str] = None) -> None:
        """Traffic-weighted LRU: while over budget, evict the resident
        with the smallest decayed request weight (hot tenants survive a
        recency blip; cold ones are the cheapest to reload later)."""
        if not self.max_resident:
            return
        while True:
            names = self.registry.names()
            if len(names) <= self.max_resident:
                return
            now = time.monotonic()
            with self._lock:
                candidates = [n for n in names if n != exclude]
                if not candidates:
                    return
                victim = min(candidates,
                             key=lambda n: self._decayed_weight(n, now))
            self.evict(victim, reason="capacity")

    # -- resolution (cold load-on-miss) -------------------------------------
    def peek(self, name: str):
        """Resident predictor or None — never loads."""
        try:
            return self.registry.get(name)
        except KeyError:
            return None

    def resolve(self, name: str, deadline: Optional[float] = None):
        """Resident predictor, or a cold load-on-miss that spends the
        request's remaining deadline budget: past the deadline the
        request 504s cleanly (:class:`DeadlineExceeded`) — if the load
        completed, the model STAYS resident, so only the requester that
        paid the compile is late, not the next one."""
        pred = self.peek(name)
        if pred is not None:
            return pred
        if self._resolver is None:
            raise KeyError(f"unknown model '{name}'")
        with self._lock:
            if self._closed:
                raise ServerClosed("zoo is closed")
            lock = self._load_locks.setdefault(name, threading.Lock())
        with lock:  # single-flight: one compile per missed name
            pred = self.peek(name)
            if pred is None:
                if deadline is not None and time.monotonic() >= deadline:
                    raise DeadlineExceeded(
                        f"deadline spent before cold load of '{name}' "
                        f"could start")
                t0 = time.perf_counter()
                source = self._resolver(name)
                pred = self.load(name, source)
                self._cold_loads.inc(1)
                self._cold_ms.observe((time.perf_counter() - t0) * 1e3)
        if deadline is not None and time.monotonic() > deadline:
            raise DeadlineExceeded(
                f"cold load of '{name}' consumed the request deadline")
        return pred

    # -- continuous-learning lane -------------------------------------------
    def apply_delta(self, name: str, record) -> dict:
        """Registry ``apply_delta`` plus stack maintenance: an
        in-envelope extension splices ONLY this tenant's lane of its
        stacked program (same signature — zero recompiles for every
        co-batched neighbour); a rebuild refreshes membership."""
        res = self.registry.apply_delta(name, record)
        pred = self.peek(name)
        spliced = False
        if pred is not None and pred.stackable:
            sig = pred.signature
            with self._lock:
                stack = self._stacks.get(sig)
                if stack is not None and name in stack.names:
                    self._stacks[sig] = stack.splice(name, pred._dense)
                    spliced = True
        if not spliced:
            self._refresh_stacks()
        return res

    # -- stacking -----------------------------------------------------------
    def current_stack(self, sig: tuple) -> Optional[StackedExecutable]:
        with self._lock:
            return self._stacks.get(sig)

    def _refresh_stacks(self) -> None:
        """Recompute stack membership from the resident set: every
        signature with >= 2 stackable tenants gets one stack (lanes in
        sorted-name order so membership is deterministic).  Unchanged
        memberships keep their existing stack — and their jit cache."""
        groups: Dict[tuple, List[Tuple[str, Any]]] = {}
        if self._stacking:
            for name in self.registry.names():
                pred = self.peek(name)
                if pred is not None and pred.stackable:
                    groups.setdefault(pred.signature, []).append(
                        (name, pred))
        with self._lock:
            fresh: Dict[tuple, StackedExecutable] = {}
            for sig, members in groups.items():
                if len(members) < 2:
                    continue
                members.sort(key=lambda kv: kv[0])
                names = [n for n, _p in members]
                old = self._stacks.get(sig)
                if old is not None and list(old.names) == names:
                    fresh[sig] = old
                else:
                    fresh[sig] = StackedExecutable(
                        names, [p._dense for _n, p in members])
            # a dissolved or re-shaped stack's program is dead (its
            # jit-cache key embeds the member list width): drop its
            # entries from the dispatch mirror or zoo churn ratchets it
            for sig, old in self._stacks.items():
                new = fresh.get(sig)
                if new is None or new.signature != old.signature:
                    release_compile_keys(old.signature)
            self._stacks = fresh
            # batchers for dissolved groups keep draining via the solo
            # fallback until closed with the zoo

    # -- the hot path -------------------------------------------------------
    def _finish_raw(self, pred, raw_out: np.ndarray,
                    raw_score: bool) -> np.ndarray:
        """Solo-path output contract on a stacked lane's raw scores:
        the RF mean divisor, the single-class squeeze, the objective
        transform — all elementwise/per-row, so slicing before or after
        cannot change a row's bits."""
        import jax.numpy as jnp
        out = raw_out
        if pred._avg_div != 1:
            out = out / pred._avg_div
        out = out[:, 0] if pred.num_class == 1 else out
        if raw_score or pred.objective is None:
            return out
        return np.asarray(pred.objective.convert_output(jnp.asarray(out)))

    def _batcher_for(self, name: str, pred):
        if not self._batching:
            return None
        if self._stacking and pred.stackable:
            sig = pred.signature
            with self._lock:
                stack = self._stacks.get(sig)
                if stack is not None and name in stack.names:
                    b = self._stack_batchers.get(sig)
                    if b is None:
                        b = self._stack_batchers[sig] = _StackBatcher(
                            self, sig, pred.buckets,
                            max_batch_rows=self._max_batch_rows,
                            max_wait_ms=self._max_wait_ms,
                            max_queue_rows=self._max_queue_rows,
                            tenant_queue_rows=self._tenant_queue_rows)
                    return b
        with self._lock:
            b = self._solo_batchers.get(name)
            if b is None:
                b = self._solo_batchers[name] = MicroBatcher(
                    lambda Xb, raw, request_ids=(), _n=name:
                    self.registry.get(_n).predict(
                        Xb, raw_score=raw, request_ids=request_ids),
                    max_batch_rows=self._max_batch_rows,
                    max_wait_ms=self._max_wait_ms,
                    max_queue_rows=self._max_queue_rows,
                    name=name, stats=pred.stats, buckets=pred.buckets)
            return b

    def predict(self, name: str, X, raw_score: bool = False,
                timeout_s: Optional[float] = None,
                request_id: Optional[str] = None) -> np.ndarray:
        """One tenant request end to end: resolve (cold load within the
        deadline), quota-checked admission, stacked or solo dispatch."""
        deadline = (time.monotonic() + float(timeout_s)
                    if timeout_s is not None else None)
        pred = self.resolve(name, deadline)
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        self._touch(name, X.shape[0])
        pred.stats.record_request(X.shape[0])
        batcher = self._batcher_for(name, pred)
        if batcher is None:
            t0 = time.monotonic()
            out = pred.predict(X, raw_score=raw_score,
                               request_ids=(request_id,)
                               if request_id else ())
            ms = (time.monotonic() - t0) * 1e3
            pred.stats.record_request_timing(
                X.shape[0], bucket_rows(X.shape[0], pred.buckets),
                queue_ms=0.0, device_ms=ms, total_ms=ms,
                request_id=request_id)
            return out
        lane = name if isinstance(batcher, _StackBatcher) else None
        remaining = (None if deadline is None
                     else max(0.0, deadline - time.monotonic()))
        try:
            return batcher.predict(X, raw_score, timeout_s=remaining,
                                   request_id=request_id, lane=lane)
        except TenantQueueFull:
            self._tenant_shed.inc(1, model=name)
            raise

    # -- introspection ------------------------------------------------------
    def stack_membership(self) -> Dict[str, List[str]]:
        """{group_key: [member names]} for every live stack."""
        with self._lock:
            return {_sig_digest(sig): list(stack.names)
                    for sig, stack in self._stacks.items()}

    def info(self) -> Dict[str, dict]:
        """Registry ``info()`` with per-model stack membership merged in
        (the ``GET /models`` payload: operators see which tenants
        co-batch and in which lane)."""
        base = self.registry.info()
        with self._lock:
            stacks = list(self._stacks.values())
        for name, entry in base.items():
            entry["stack"] = None
            for stack in stacks:
                if name in stack.names:
                    entry["stack"] = {
                        "group": _sig_digest(stack.member_sig),
                        "lane": stack.lane(name),
                        "width": stack.width,
                        "members": list(stack.names),
                    }
                    break
        return base

    def zoo_stats(self) -> dict:
        """The ``/stats`` zoo section: admission + stacking posture."""
        names = self.registry.names()
        now = time.monotonic()
        with self._lock:
            weights = {n: round(float(self._decayed_weight(n, now)), 3)
                       for n in names}
        return {
            "resident": len(names),
            "max_resident": self.max_resident,
            "stacking": self._stacking,
            "groups": self.stack_membership(),
            "traffic_weight": weights,
        }

    def close(self, timeout: Optional[float] = 5.0) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            batchers = (list(self._stack_batchers.values()) +
                        list(self._solo_batchers.values()))
            self._stack_batchers.clear()
            self._solo_batchers.clear()
        for b in batchers:
            b.close(timeout=timeout)
