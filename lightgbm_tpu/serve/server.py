"""Dependency-free JSON inference endpoint over ``http.server``.

Endpoints:
  GET  /healthz  -> {"status": "ok"|"degraded", "models": [...]} —
                    degraded (with "reasons") while serving on the CPU
                    fallback backend, while admission control shed
                    requests in the last minute, or while an SLO's fast
                    burn window has run hot for several consecutive
                    evaluations; still 200
  GET  /models   -> per-model info (trees, classes, buckets, version)
  GET  /stats    -> per-model counters (requests/rows/batches/recompiles/
                    bucket histogram/p50/p99 latency + queue-wait vs
                    device-compute split) plus live batcher saturation
                    (queue rows, in-flight requests)
  GET  /metrics  -> Prometheus text format: the process-wide telemetry
                    registry (serving counters, time tags, SLO burn-rate
                    gauges) plus the last training run's TrainRecord
  GET  /slo      -> declared-SLO verdicts: multi-window burn rates per
                    objective, breach flags, and — whenever something is
                    burning — the slowest-request exemplar ring
  POST /predict  -> {"rows": [[...], ...]} or {"row": [...]}, optional
                    "model" (required only with >1 loaded), "raw_score";
                    returns {"model", "num_rows", "predictions",
                    "request_id"}.  An ``X-Request-Id`` header is
                    propagated through the micro-batcher into the
                    predictor (and echoed back); absent one, the server
                    assigns one
  POST /models   -> {"name": ..., "file": ...} loads or atomically
                    hot-swaps a model from a model_text file
  POST /models/<name>/delta
                 -> {"record_b64": ...} appends a published training
                    delta (publish/delta.py wire record, base64) to the
                    serving model without a full reload; 409 on a chain
                    mismatch tells the caller to full-reload + replay

Each HTTP request runs on its own thread (ThreadingHTTPServer); /predict
routes through a per-model :class:`MicroBatcher`, so concurrent small
requests coalesce into one bucketed device call.  Started by the CLI
verb ``python -m lightgbm_tpu serve model.txt [key=value ...]``.

Lifecycle: the CLI installs SIGTERM/SIGINT handlers that run the same
drain discipline training's ``PreemptionGuard`` gives checkpoints —
stop accepting, fail queued batcher futures with :class:`ServerClosed`,
let in-flight requests finish writing their responses, exit
``128+signum`` (a repeat signal aborts immediately).  ``port_file=``
announces the bound port to a supervisor (``serve/fleet.py``) via an
atomic write, so ``port=0`` workers are discoverable without stdout
parsing.  The chaos layer's serve-side fault points
(``serve_crash_after_n`` / ``serve_hang_ms`` / ``serve_drop_conn``,
``resilience/faults.py``) hook the top of every handler.
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

import numpy as np

from .batcher import MicroBatcher
from .registry import ModelRegistry
from .stats import request_exemplars
from ..resilience.admission import (DeadlineExceeded, QueueFullError,
                                    ServerClosed)
from ..resilience.faults import faults
from ..telemetry.metrics import default_registry
from ..telemetry.slo import (SloEngine, default_engine,
                             register_metric_ensurer, slo)
from ..utils.log import log_debug, log_info, log_warning

__all__ = ["PredictionServer", "main"]

# /healthz reports "degraded" while sheds happened inside this window —
# the tier is up but actively refusing some traffic
SHED_DEGRADED_WINDOW_S = 60.0

# Availability objective, declared next to the handler that serves the
# responses it counts: at most 0.1% of /predict responses may be 5xx
# (sheds, deadline expiries and server errors all land there).  Keyed
# to the PREDICT-only counter, not the all-endpoints one — a tier
# scraped every second by probes/Prometheus would otherwise pad the
# denominator with its own monitoring 200s and hide a total /predict
# outage inside the diluted ratio.
slo("serve/availability", metric="serve_predict_responses_total",
    kind="ratio", target=0.999,
    total_metric="serve_predict_responses_total",
    bad_labels={"code": "5*"}, min_events=50,
    note="non-5xx response ratio over /predict traffic")

# monotonically unique server-assigned request ids (requests that arrive
# without an X-Request-Id header still get a trace handle)
_REQ_SEQ = itertools.count(1)
_REQ_PREFIX = f"srv-{os.getpid():x}"


def _gen_request_id() -> str:
    return f"{_REQ_PREFIX}-{next(_REQ_SEQ):x}"


def _http_response_counter():
    return default_registry().counter(
        "serve_http_responses_total", "HTTP responses by status code",
        labels=("code",))


def _predict_response_counter():
    return default_registry().counter(
        "serve_predict_responses_total",
        "/predict responses by status code (the availability SLO's "
        "series — monitoring-endpoint traffic excluded)",
        labels=("code",))


def _explain_response_counter():
    # the explain lane's own series: /explain errors must not dilute
    # (or hide inside) the /predict availability SLO's denominator
    return default_registry().counter(
        "serve_explain_responses_total",
        "/explain responses by status code", labels=("code",))


@register_metric_ensurer
def _ensure_http_metrics(reg) -> None:
    """SLO-coverage ensurer for the counters the availability SLO above
    reads — declared here, next to the handler that bumps them, so the
    lint validates the REAL schema and not a copy that could drift."""
    reg.counter("serve_http_responses_total",
                "HTTP responses by status code", labels=("code",))
    reg.counter("serve_predict_responses_total",
                "/predict responses by status code (the availability "
                "SLO's series — monitoring-endpoint traffic excluded)",
                labels=("code",))
    reg.counter("serve_explain_responses_total",
                "/explain responses by status code", labels=("code",))


class PredictionServer:
    """Registry + HTTP front end + per-model micro-batchers.

    Admission control: ``max_queue_rows`` bounds each model's batcher
    backlog (an over-limit submit is shed with 503 + ``Retry-After``);
    ``deadline_ms`` (server default, per-request override in the JSON
    body) fails slow requests with 504 instead of hanging the handler
    thread.  Both ride the micro-batcher queue and are inert with
    ``batching=False`` (the direct-dispatch debug path has no queue to
    bound or expire).  ``/healthz`` reports ``degraded`` while traffic
    is served on the CPU fallback backend, sheds happened recently, or
    an SLO fast-burn has been sustained (``slo_engine.sustain``
    consecutive hot evaluations)."""

    def __init__(self, registry: ModelRegistry, host: str = "127.0.0.1",
                 port: int = 8080, max_batch_rows: int = 4096,
                 max_wait_ms: float = 2.0, batching: bool = True,
                 max_queue_rows: int = 0,
                 deadline_ms: float = 0.0,
                 slo_engine: Optional[SloEngine] = None,
                 zoo=None) -> None:
        # zoo mode (serve/zoo.py): admission/eviction + cross-model
        # stacked dispatch replace the per-model batcher path; the zoo's
        # registry IS the server's registry
        self._zoo = zoo
        if zoo is not None:
            registry = zoo.registry
        self.registry = registry
        self._batching = batching
        self._batch_opts = (max_batch_rows, max_wait_ms)
        self._max_queue_rows = int(max_queue_rows)
        self._deadline_ms = float(deadline_ms)  # 0 = no default deadline
        self._batchers: Dict[str, MicroBatcher] = {}
        # /explain coalesces in its OWN batchers: phi batches are
        # (rows, K*(F+1)) wide, so mixing them into the /predict queue
        # would let a handful of explain rows starve the predict
        # latency budget they share a window with
        self._explain_batchers: Dict[str, MicroBatcher] = {}
        self._batchers_lock = threading.Lock()
        self._last_shed_t = 0.0
        self.slo_engine = slo_engine if slo_engine is not None \
            else default_engine()
        self._responses = _http_response_counter()
        self._predict_responses = _predict_response_counter()
        self._explain_responses = _explain_response_counter()
        # drain bookkeeping: in-flight /predict handlers are counted so
        # a graceful shutdown can wait for their responses to be written
        self._active_cv = threading.Condition()
        self._active_predicts = 0
        self._draining = False
        self.signal_received: Optional[int] = None
        handler = _make_handler(self)
        # http.server's default listen backlog is 5: a fan-out wave (N
        # clients scoring N zoo tenants in the same instant) overflows
        # it, and the dropped SYNs come back ~1s later via retransmit —
        # a latency cliff no queue metric ever sees.  Size the backlog
        # for burst arrival instead.
        server_cls = type("_ZooHTTPServer", (ThreadingHTTPServer,),
                          {"request_queue_size": 128})
        self._httpd = server_cls((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def host(self) -> str:
        # server_address is typed (str | bytes, int); ours is always str
        host = self._httpd.server_address[0]
        return host.decode() if isinstance(host, (bytes, bytearray)) \
            else str(host)

    def _predict(self, name: Optional[str], X: np.ndarray,
                 raw_score: bool,
                 deadline_ms: Optional[float] = None,
                 request_id: Optional[str] = None) -> np.ndarray:
        if deadline_ms is None:
            deadline_ms = self._deadline_ms
        timeout_s = float(deadline_ms) / 1e3 if deadline_ms and \
            deadline_ms > 0 else None
        if self._zoo is not None:
            # zoo path: per-tenant admission, cold load-on-miss inside
            # the deadline, stacked or solo dispatch (serve/zoo.py).  A
            # nameless request still resolves the single resident model.
            resolved = name if name is not None \
                else self.registry.get(None).stats.model
            return self._zoo.predict(resolved, X, raw_score=raw_score,
                                     timeout_s=timeout_s,
                                     request_id=request_id)
        pred = self.registry.get(name)  # resolves None -> the single model
        pred.stats.record_request(X.shape[0])
        if not self._batching:
            # direct-dispatch path: no queue, so the split is all device
            t0 = time.monotonic()
            out = pred.predict(X, raw_score=raw_score,
                               request_ids=(request_id,) if request_id
                               else ())
            dt_ms = (time.monotonic() - t0) * 1e3
            from ..models.tree import bucket_rows
            pred.stats.record_request_timing(
                int(X.shape[0]), bucket_rows(int(X.shape[0]), pred.buckets),
                queue_ms=0.0, device_ms=dt_ms, total_ms=dt_ms,
                request_id=request_id)
            return out
        # key by the RESOLVED model name: a nameless request to a
        # single-model server and an explicit-name request must share
        # one batcher (two batchers under one name would clobber each
        # other's saturation gauges and split the coalescing window)
        key = pred.stats.model
        with self._batchers_lock:
            batcher = self._batchers.get(key)
            if batcher is None:
                # the closure re-resolves the registry per batch (by the
                # RESOLVED name, so loading a second model later never
                # breaks this batcher's dispatch) and a hot-swap
                # redirects batched traffic without a restart
                batcher = MicroBatcher(
                    lambda Xb, raw, request_ids=(), _n=key:
                        self.registry.get(_n).predict(
                            Xb, raw_score=raw, request_ids=request_ids),
                    max_batch_rows=self._batch_opts[0],
                    max_wait_ms=self._batch_opts[1],
                    max_queue_rows=self._max_queue_rows,
                    name=key, stats=pred.stats, buckets=pred.buckets)
                self._batchers[key] = batcher
        return batcher.predict(X, raw_score=raw_score, timeout_s=timeout_s,
                               request_id=request_id)

    def _explain(self, name: Optional[str], X: np.ndarray,
                 deadline_ms: Optional[float] = None,
                 request_id: Optional[str] = None) -> np.ndarray:
        """Dispatch one /explain request: per-row SHAP contributions in
        the host ``pred_contrib`` layout.  Same admission machinery as
        :meth:`_predict` but through the explain lane's own batchers and
        latency series — the two lanes share a process, not a queue.

        Zoo mode dispatches directly against the resident predictor:
        stacked cross-model launches only fuse same-shape PREDICTION
        programs, and a non-resident tenant gets 404 rather than a cold
        load (an explain burst must never evict serving models)."""
        if deadline_ms is None:
            deadline_ms = self._deadline_ms
        timeout_s = float(deadline_ms) / 1e3 if deadline_ms and \
            deadline_ms > 0 else None
        resolved_name = name
        if self._zoo is not None and name is None:
            resolved_name = self.registry.get(None).stats.model
        pred = self.registry.get(resolved_name)
        if not self._batching or self._zoo is not None:
            t0 = time.monotonic()
            out = pred.explain(X, request_ids=(request_id,) if request_id
                               else ())
            dt_ms = (time.monotonic() - t0) * 1e3
            from ..models.tree import bucket_rows
            pred.stats.record_explain_timing(
                int(X.shape[0]), bucket_rows(int(X.shape[0]), pred.buckets),
                queue_ms=0.0, device_ms=dt_ms, total_ms=dt_ms,
                request_id=request_id)
            return out
        key = pred.stats.model
        with self._batchers_lock:
            batcher = self._explain_batchers.get(key)
            if batcher is None:
                batcher = MicroBatcher(
                    lambda Xb, raw, request_ids=(), _n=key:
                        self.registry.get(_n).explain(
                            Xb, request_ids=request_ids),
                    max_batch_rows=self._batch_opts[0],
                    max_wait_ms=self._batch_opts[1],
                    max_queue_rows=self._max_queue_rows,
                    name=f"{key}:explain",
                    stats=pred.stats.explain_timing_stats(),
                    buckets=pred.buckets)
                self._explain_batchers[key] = batcher
        return batcher.predict(X, raw_score=False, timeout_s=timeout_s,
                               request_id=request_id)

    def health(self) -> dict:
        """``/healthz`` payload: ``ok``, or ``degraded`` with reasons
        while traffic runs on the CPU fallback backend, admission
        control shed requests in the last minute, or an SLO's fast burn
        window has run hot for ``slo_engine.sustain`` consecutive
        evaluations — still 200 (the tier answers), but a reason for an
        operator to look."""
        from ..utils.backend import fallback_reason
        reasons = []
        fb = fallback_reason()
        if fb:
            reasons.append(f"cpu_fallback: {fb}")
        if self._last_shed_t and \
                time.monotonic() - self._last_shed_t < SHED_DEGRADED_WINDOW_S:
            reasons.append("shedding: request queue hit its limit in the "
                           f"last {int(SHED_DEGRADED_WINDOW_S)}s")
        report = self.slo_engine.evaluate()
        for name in report["degraded"]:
            v = next((s for s in report["slos"] if s["name"] == name), None)
            burn = v["burn"]["fast"] if v else 0.0
            reasons.append(f"slo_fast_burn: {name} has burned at "
                           f"{burn:.1f}x budget for "
                           f"{self.slo_engine.sustain}+ evaluations")
        out = {"status": "degraded" if reasons else "ok",
               "models": self.registry.names()}
        if reasons:
            out["reasons"] = reasons
        return out

    def slo_report(self) -> dict:
        """``/slo`` payload: verdicts per declared objective; breaches
        and fast burns carry the slowest-request exemplar ring so a tail
        regression arrives with the offending requests attached."""
        report = self.slo_engine.evaluate()
        if report["breached"] or report["fast_burning"]:
            report["exemplars"] = request_exemplars().snapshot()
        return report

    def models_info(self) -> dict:
        """``/models`` payload: registry info, with per-model stack
        membership merged in when the zoo is on.  Stays a name->dict
        mapping either way — the fleet supervisor's model sync reads it
        as one."""
        return self._zoo.info() if self._zoo is not None \
            else self.registry.info()

    def stats_payload(self) -> dict:
        """``/stats`` payload: per-model counters plus live batcher
        saturation — a load test can watch the backlog build, not just
        requests die.  Zoo mode adds a ``_zoo`` section (resident count,
        stack groups, traffic weights); existing consumers key by model
        name, so the extra entry is inert to them."""
        out = self.registry.stats()
        with self._batchers_lock:
            batchers = list(self._batchers.values()) \
                + list(self._explain_batchers.values())
        for b in batchers:
            entry = out.setdefault(b.name, {})
            entry["saturation"] = {
                "queue_rows": int(b.backlog_rows),
                "inflight_requests": b.inflight_requests(),
                "max_queue_rows": self._max_queue_rows,
            }
        if self._zoo is not None:
            out["_zoo"] = self._zoo.zoo_stats()
        return out

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "PredictionServer":
        """Serve on a background thread (tests / embedding)."""
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="lgb-tpu-serve")
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    def _enter_predict(self) -> bool:
        """Admit one /predict handler; False while draining (the caller
        replies 503 instead of racing the batcher teardown)."""
        with self._active_cv:
            if self._draining:
                return False
            self._active_predicts += 1
            return True

    def _exit_predict(self) -> None:
        with self._active_cv:
            self._active_predicts -= 1
            if self._active_predicts <= 0:
                self._active_cv.notify_all()

    def drain(self, timeout: float = 30.0) -> None:
        """Graceful shutdown in the strict order a rolling restart
        needs: (1) stop accepting — new /predict requests get an
        immediate 503 and the accept loop stops; (2) drain the
        micro-batchers — queued futures fail with
        :class:`ServerClosed`, the in-flight device batch completes and
        settles its futures; (3) wait for in-flight handler threads to
        write their responses; (4) close the sockets.  Every admitted
        request therefore gets exactly one terminal response — a result
        or a typed 5xx — never a hang."""
        with self._active_cv:
            self._draining = True
        self._httpd.shutdown()   # no-op if serve_forever already returned
        with self._batchers_lock:
            batchers = list(self._batchers.values()) \
                + list(self._explain_batchers.values())
            self._batchers, self._explain_batchers = {}, {}
        for b in batchers:
            b.close()
        if self._zoo is not None:
            self._zoo.close()
        deadline = time.monotonic() + max(0.0, timeout)
        with self._active_cv:
            while self._active_predicts > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    log_warning(f"serve: drain timed out with "
                                f"{self._active_predicts} request(s) "
                                f"still in flight")
                    break
                self._active_cv.wait(remaining)
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)

    def shutdown(self) -> None:
        self.drain(timeout=5.0)

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT -> drain-and-exit, the serving twin of
        training's ``PreemptionGuard``: the handler only flags the
        signal and stops the accept loop (from a helper thread —
        ``shutdown()`` called inside the handler would deadlock the
        main-thread ``serve_forever``); ``main`` then drains and exits
        ``128+signum``.  A repeat signal aborts immediately instead of
        waiting out the drain.  Main-thread only (``signal.signal``'s
        constraint); embedded servers use :meth:`drain` directly."""
        def _on_signal(signum: int, frame) -> None:
            if self.signal_received is not None:
                os._exit(128 + int(signum))
            self.signal_received = int(signum)
            log_warning(f"serve: received signal {signum}; draining "
                        f"in-flight requests (repeat to abort)")
            threading.Thread(target=self._httpd.shutdown,
                             daemon=True).start()
        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)


def _make_handler(server: PredictionServer):
    class ServeHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route access logs to debug
            log_debug("serve: " + fmt % args)

        def _reply(self, code: int, payload: dict,
                   extra_headers: Optional[Dict[str, str]] = None) -> None:
            body = json.dumps(payload).encode()
            server._responses.inc(1, code=str(int(code)))
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra_headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _read_json(self) -> dict:
            length = int(self.headers.get("Content-Length", 0))
            if length <= 0:
                return {}
            return json.loads(self.rfile.read(length).decode())

        def _chaos(self) -> bool:
            """Armed serve-side fault points fire here (top of every
            handler).  True = the connection was severed; stop."""
            if faults.check_serve_request(self.path) == "drop":
                try:
                    self.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                self.close_connection = True
                return True
            return False

        def do_GET(self):
            if self._chaos():
                return
            if self.path == "/healthz":
                self._reply(200, server.health())
            elif self.path == "/models":
                self._reply(200, server.models_info())
            elif self.path == "/stats":
                self._reply(200, server.stats_payload())
            elif self.path == "/slo":
                self._reply(200, server.slo_report())
            elif self.path == "/metrics":
                # Prometheus text: serving counters (registry-managed
                # models label themselves into the default metrics
                # registry) + the last training run's TrainRecord
                from ..telemetry.export import (PROMETHEUS_CONTENT_TYPE,
                                                render_prometheus)
                body = render_prometheus().encode()
                server._responses.inc(1, code="200")
                self.send_response(200)
                self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            if self._chaos():
                return
            try:
                req = self._read_json()
            except (ValueError, UnicodeDecodeError) as exc:
                self._reply(400, {"error": f"bad JSON body: {exc}"})
                return
            if self.path == "/predict":
                self._predict(req)
            elif self.path == "/explain":
                self._explain(req)
            elif self.path == "/models":
                self._load_model(req)
            elif self.path.startswith("/models/") and \
                    self.path.endswith("/delta"):
                self._apply_delta(req, self.path[len("/models/"):
                                                 -len("/delta")])
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def _predict(self, req: dict) -> None:
            # per-request trace handle: propagate the caller's id (or
            # assign one) server -> batcher -> predictor, echo it back
            rid = self.headers.get("X-Request-Id") or _gen_request_id()
            rid_hdr = {"X-Request-Id": rid}

            def reply(code: int, payload: dict,
                      headers: Optional[Dict[str, str]] = None) -> None:
                # the availability SLO's series: /predict responses
                # only, so monitoring scrapes never pad the denominator
                server._predict_responses.inc(1, code=str(int(code)))
                self._reply(code, payload, headers or rid_hdr)

            # drain gate + in-flight accounting: an admitted request is
            # guaranteed a written response before sockets close
            if not server._enter_predict():
                reply(503, {"error": "server is draining"},
                      {"Retry-After": "1", **rid_hdr})
                return
            try:
                self._predict_admitted(req, reply, rid)
            finally:
                server._exit_predict()

        def _predict_admitted(self, req: dict, reply, rid: str) -> None:
            rid_hdr = {"X-Request-Id": rid}
            name = req.get("model")
            rows = req.get("rows")
            if rows is None and "row" in req:
                rows = [req["row"]]
            if not isinstance(rows, list) or not rows:
                reply(400, {"error": "body needs 'rows' (list of "
                                     "feature lists) or 'row'"})
                return
            deadline_ms = req.get("deadline_ms")
            if deadline_ms is not None:
                if isinstance(deadline_ms, bool) or \
                        not isinstance(deadline_ms, (int, float)):
                    reply(400, {"error": "deadline_ms must be a "
                                         "number of milliseconds"})
                    return
                deadline_ms = float(deadline_ms)
            try:
                X = np.asarray(rows, np.float32)
                if X.ndim != 2:
                    raise ValueError(f"rows must be 2-D, got shape {X.shape}")
                out = server._predict(name, X, bool(req.get("raw_score")),
                                      deadline_ms=deadline_ms,
                                      request_id=rid)
            except KeyError as exc:
                reply(404, {"error": str(exc.args[0])})
                return
            except QueueFullError as exc:
                # load shed: admission control refused the request; tell
                # the client when the backlog should have drained
                server._last_shed_t = time.monotonic()
                reply(503, {"error": str(exc),
                            "retry_after_s": exc.retry_after},
                      {"Retry-After":
                       str(max(1, int(-(-exc.retry_after // 1)))),
                       **rid_hdr})
                return
            except DeadlineExceeded as exc:
                reply(504, {"error": str(exc)})
                return
            except ServerClosed as exc:
                reply(503, {"error": str(exc)})
                return
            except Exception as exc:
                try:
                    server.registry.get(name).stats.record_error()
                except KeyError:
                    pass
                reply(400, {"error": f"{type(exc).__name__}: {exc}"})
                return
            reply(200, {"model": name, "num_rows": int(X.shape[0]),
                        "predictions": np.asarray(out).tolist(),
                        "request_id": rid})

        def _explain(self, req: dict) -> None:
            """``POST /explain``: same body shape as /predict (``rows``
            or ``row``, optional ``model``/``deadline_ms``), answers
            per-row SHAP contributions — for each class, one value per
            feature plus a trailing expected-value column (the host
            ``pred_contrib`` layout).  Shares the drain gate and error
            ladder with /predict but counts into its own response
            series and latency SLO."""
            rid = self.headers.get("X-Request-Id") or _gen_request_id()
            rid_hdr = {"X-Request-Id": rid}

            def reply(code: int, payload: dict,
                      headers: Optional[Dict[str, str]] = None) -> None:
                server._explain_responses.inc(1, code=str(int(code)))
                self._reply(code, payload, headers or rid_hdr)

            if not server._enter_predict():
                reply(503, {"error": "server is draining"},
                      {"Retry-After": "1", **rid_hdr})
                return
            try:
                self._explain_admitted(req, reply, rid)
            finally:
                server._exit_predict()

        def _explain_admitted(self, req: dict, reply, rid: str) -> None:
            rid_hdr = {"X-Request-Id": rid}
            name = req.get("model")
            rows = req.get("rows")
            if rows is None and "row" in req:
                rows = [req["row"]]
            if not isinstance(rows, list) or not rows:
                reply(400, {"error": "body needs 'rows' (list of "
                                     "feature lists) or 'row'"})
                return
            deadline_ms = req.get("deadline_ms")
            if deadline_ms is not None:
                if isinstance(deadline_ms, bool) or \
                        not isinstance(deadline_ms, (int, float)):
                    reply(400, {"error": "deadline_ms must be a "
                                         "number of milliseconds"})
                    return
                deadline_ms = float(deadline_ms)
            try:
                X = np.asarray(rows, np.float32)
                if X.ndim != 2:
                    raise ValueError(f"rows must be 2-D, got shape {X.shape}")
                out = server._explain(name, X, deadline_ms=deadline_ms,
                                      request_id=rid)
            except KeyError as exc:
                reply(404, {"error": str(exc.args[0])})
                return
            except QueueFullError as exc:
                server._last_shed_t = time.monotonic()
                reply(503, {"error": str(exc),
                            "retry_after_s": exc.retry_after},
                      {"Retry-After":
                       str(max(1, int(-(-exc.retry_after // 1)))),
                       **rid_hdr})
                return
            except DeadlineExceeded as exc:
                reply(504, {"error": str(exc)})
                return
            except ServerClosed as exc:
                reply(503, {"error": str(exc)})
                return
            except Exception as exc:
                try:
                    server.registry.get(name).stats.record_error()
                except KeyError:
                    pass
                reply(400, {"error": f"{type(exc).__name__}: {exc}"})
                return
            reply(200, {"model": name, "num_rows": int(X.shape[0]),
                        "contributions": np.asarray(out).tolist(),
                        "request_id": rid})

        def _apply_delta(self, req: dict, name: str) -> None:
            """``POST /models/<name>/delta``: append a published delta's
            trees to the serving model without a full reload.  The wire
            record rides base64 inside the JSON body (``record_b64``) so
            the one-body-shape-per-POST read above stands.  409 = chain
            mismatch (the caller's typed signal to fall back to a full
            reload + replay); 404 = unknown model."""
            import base64
            b64 = req.get("record_b64")
            if not name or not isinstance(b64, str) or not b64:
                self._reply(400, {"error": "body needs 'record_b64' (the "
                                           "delta record, base64)"})
                return
            try:
                raw = base64.b64decode(b64.encode("ascii"), validate=True)
            except (ValueError, UnicodeEncodeError) as exc:
                self._reply(400, {"error": f"bad record_b64: {exc}"})
                return
            from ..publish.delta import DeltaChainError
            try:
                # zoo mode: an in-envelope delta splices only this
                # tenant's stacked lane (zero recompiles for neighbours)
                out = server._zoo.apply_delta(name, raw) \
                    if server._zoo is not None \
                    else server.registry.apply_delta(name, raw)
            except KeyError as exc:
                self._reply(404, {"error": str(exc.args[0])})
                return
            except DeltaChainError as exc:
                self._reply(409, {"error": str(exc)})
                return
            except Exception as exc:
                self._reply(400, {"error": f"{type(exc).__name__}: {exc}"})
                return
            self._reply(200, out)

        def _load_model(self, req: dict) -> None:
            name, path = req.get("name"), req.get("file")
            if not name or not path:
                self._reply(400, {"error": "body needs 'name' and 'file'"})
                return
            # optional lowering knobs ride the same body, so a reload
            # can reproduce the serving config of the entry it replaces
            kwargs = {}
            try:
                for key, cast in (("num_iteration", int), ("shard", int),
                                  ("leaf_bits", int), ("compiler", str)):
                    if req.get(key) is not None:
                        kwargs[key] = cast(req[key])
            except (TypeError, ValueError) as exc:
                self._reply(400, {"error": f"bad lowering knob: {exc}"})
                return
            try:
                # zoo mode: admission goes through the zoo so the budget
                # is enforced and stack membership refreshes
                pred = server._zoo.load(str(name), str(path), **kwargs) \
                    if server._zoo is not None \
                    else server.registry.load(str(name), str(path), **kwargs)
            except Exception as exc:
                self._reply(400, {"error": f"{type(exc).__name__}: {exc}"})
                return
            self._reply(200, {"model": name, **pred.info()})

    return ServeHandler


def _parse_bool(v, default: bool) -> bool:
    """Accept the repo's config bool spellings (true/false/1/0)."""
    if v is None:
        return default
    s = str(v).strip().lower()
    if s in ("true", "1", "yes", "on"):
        return True
    if s in ("false", "0", "no", "off"):
        return False
    raise ValueError(f"expected a boolean (true/false/1/0), got {v!r}")


def main(argv: List[str]) -> int:
    """``python -m lightgbm_tpu serve <model.txt> [key=value ...]``.

    Keys: host (127.0.0.1), port (8080), name (single model's registry
    name), warmup (1), batching (1), max_batch (4096), max_wait_ms (2.0),
    max_queue_rows (0 = unbounded; over-limit requests are shed with 503
    + Retry-After), deadline_ms (0 = none; slow requests fail with 504),
    slo_latency_ms (re-declares the serve/latency_p99 threshold for this
    deployment), explain_slo_latency_ms (same for the /explain lane's
    serve/explain_latency_p99), num_iteration (-1: all), port_file
    (announce the bound
    port by atomic write — the fleet supervisor's discovery channel for
    port=0 workers).  Multiple model files register under their
    basenames.

    Zoo keys (any of them switches on zoo mode, serve/zoo.py):
    zoo (0; force-enable), max_resident (0 = unbounded; over budget the
    zoo evicts by traffic-weighted LRU), zoo_dir (cold load-on-miss
    directory — requests for <name> load <zoo_dir>/<name>.txt inside
    their deadline, so a zoo server can start with NO model files),
    tenant_queue_rows (0 = no per-tenant quota; a tenant over its own
    backlog bound is shed before the shared queue bound), stacking (1;
    fuse same-lowering-shape tenants into one stacked MXU launch per
    (stack, bucket) super-batch).

    SIGTERM/SIGINT drain the server (stop accepting, fail queued
    futures with ServerClosed, finish in-flight requests) and exit
    ``128+signum``; a repeat signal aborts immediately.
    """
    from ..utils.backend import default_backend
    from ..utils.log import log_fatal
    # resolve the backend before any model touches the device: a broken
    # accelerator plugin downgrades the server to CPU instead of killing
    # it during warmup
    default_backend()
    files = [a for a in argv if "=" not in a]
    kv = {k: v for k, v in
          (a.split("=", 1) for a in argv if "=" in a)}
    if kv.get("model"):
        files.append(kv["model"])
    max_resident = int(kv.get("max_resident", 0))
    tenant_rows = int(kv.get("tenant_queue_rows", 0))
    zoo_mode = _parse_bool(kv.get("zoo"), False) or max_resident > 0 \
        or bool(kv.get("zoo_dir")) or tenant_rows > 0
    if not files and not kv.get("zoo_dir"):
        log_fatal("serve needs at least one model file: "
                  "python -m lightgbm_tpu serve model.txt [port=8080 ...] "
                  "(or zoo_dir=<dir> to cold-load models on demand)")
    if kv.get("slo_latency_ms"):
        from ..telemetry.slo import set_latency_threshold
        set_latency_threshold("serve/latency_p99",
                              float(kv["slo_latency_ms"]))
    if kv.get("explain_slo_latency_ms"):
        from ..telemetry.slo import set_latency_threshold
        set_latency_threshold("serve/explain_latency_p99",
                              float(kv["explain_slo_latency_ms"]))
    registry = ModelRegistry()
    n_iter = int(kv.get("num_iteration", -1))
    zoo = None
    if zoo_mode:
        from .zoo import ModelZoo
        zoo = ModelZoo(
            registry=registry, max_resident=max_resident,
            source_resolver=kv.get("zoo_dir") or None,
            stacking=_parse_bool(kv.get("stacking"), True),
            batching=_parse_bool(kv.get("batching"), True),
            max_batch_rows=int(kv.get("max_batch", 4096)),
            max_wait_ms=float(kv.get("max_wait_ms", 2.0)),
            max_queue_rows=int(kv.get("max_queue_rows", 0)),
            tenant_queue_rows=tenant_rows,
            warmup=_parse_bool(kv.get("warmup"), True),
            load_kwargs={} if n_iter < 0 else {"num_iteration": n_iter})
    seen = set()
    for path in files:
        name = (kv["name"] if len(files) == 1 and kv.get("name") else
                os.path.splitext(os.path.basename(path))[0])
        if name in seen:
            log_fatal(f"two model files share the registry name '{name}' "
                      f"(names come from basenames); rename one file or "
                      f"serve them from separate processes")
        seen.add(name)
        if zoo is not None:
            zoo.load(name, path)
        else:
            registry.load(name, path,
                          warmup=_parse_bool(kv.get("warmup"), True),
                          num_iteration=None if n_iter < 0 else n_iter)
    srv = PredictionServer(
        registry, host=kv.get("host", "127.0.0.1"),
        port=int(kv.get("port", 8080)),
        max_batch_rows=int(kv.get("max_batch", 4096)),
        max_wait_ms=float(kv.get("max_wait_ms", 2.0)),
        batching=_parse_bool(kv.get("batching"), True),
        max_queue_rows=int(kv.get("max_queue_rows", 0)),
        deadline_ms=float(kv.get("deadline_ms", 0.0)),
        zoo=zoo)
    if kv.get("port_file"):
        # atomic announce AFTER the bind: a supervisor polling this file
        # can only ever read a complete, live port
        from ..io_utils import atomic_write_bytes
        atomic_write_bytes(kv["port_file"], f"{srv.port}\n".encode())
    try:
        srv.install_signal_handlers()
    except ValueError:
        pass  # not the main thread (embedded run); signals stay default
    log_info(f"serve: listening on http://{srv.host}:{srv.port} "
             f"(models: {', '.join(registry.names())})")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        log_info("serve: shutting down")
        srv.shutdown()
        return 0
    if srv.signal_received is not None:
        # accept loop already stopped by the handler; finish the drain
        srv.drain()
        log_info(f"serve: drained after signal {srv.signal_received}")
        return 128 + int(srv.signal_received)
    return 0
