"""Micro-batching queue: coalesce concurrent small requests into one
bucketed device call, with admission control in front of it.

A single worker thread drains the queue under a max-wait/max-rows
policy: the first waiting request opens a window of ``max_wait_ms``;
every request arriving inside it joins the batch until ``max_batch_rows``
is reached.  One concatenated predict runs, and each waiter gets its row
slice back through a Future — so N concurrent single-row requests cost
one device dispatch on the next bucket up instead of N dispatches.

Requests are grouped by (raw_score, feature-count) inside a window: a
malformed request can only fail its own group, never poison co-batched
traffic with a different shape.

Admission control (resilience/admission.py semantics):

  * ``max_queue_rows`` bounds the backlog; a submit that would exceed it
    is rejected with :class:`QueueFullError` carrying a ``retry_after``
    estimated from the EWMA batch latency — admitting more work than the
    device drains only grows everyone's latency, so shed at the door.
  * a per-request ``deadline`` (monotonic seconds) expires queued work:
    the worker fails expired requests with :class:`DeadlineExceeded`
    instead of spending device time on an answer nobody is waiting for,
    and ``predict`` stops blocking at the deadline either way.
  * ``close()`` drains the queue and fails every pending future with
    :class:`ServerClosed` — a shutdown never leaves a caller blocked
    until its own client timeout.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Callable, Optional

import numpy as np

from ..resilience.admission import (DeadlineExceeded, QueueFullError,
                                    ServerClosed, deadline_counter,
                                    shed_counter)

__all__ = ["MicroBatcher"]

_CLOSE = object()


class MicroBatcher:
    """Thread-safe request coalescer in front of a predict function.

    ``predict_fn(X, raw_score) -> np.ndarray`` must be row-aligned:
    output row i corresponds to input row i (true for every predictor
    path).  ``submit`` returns a Future; ``predict`` blocks on it.
    ``name`` labels the shed/deadline telemetry counters.
    """

    def __init__(self, predict_fn: Callable[[np.ndarray, bool], np.ndarray],
                 max_batch_rows: int = 4096,
                 max_wait_ms: float = 2.0,
                 max_queue_rows: int = 0,
                 name: str = "default") -> None:
        self._predict_fn = predict_fn
        self._max_rows = int(max_batch_rows)
        self._max_wait = max(0.0, float(max_wait_ms)) / 1e3
        self._max_queue_rows = max(0, int(max_queue_rows))  # 0 = unbounded
        self.name = str(name)
        self._q: "queue.Queue" = queue.Queue()
        self._closed = False
        self._backlog_rows = 0  # rows admitted but not yet dispatched
        self._ewma_batch_s = 0.05  # device-call latency estimate
        self._state_lock = threading.Lock()  # serializes submit vs close
        self._shed = shed_counter()
        self._deadline = deadline_counter()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="lgb-tpu-microbatcher")
        self._thread.start()

    # -- client side --------------------------------------------------------
    @property
    def backlog_rows(self) -> int:
        return self._backlog_rows

    def submit(self, X: np.ndarray, raw_score: bool = False,
               deadline: Optional[float] = None) -> Future:
        """Queue one request.  ``deadline`` is an absolute
        ``time.monotonic()`` instant after which the request is failed
        with :class:`DeadlineExceeded` rather than dispatched."""
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        fut: Future = Future()
        rows = int(X.shape[0])
        # the closed/limit checks and the put are one atomic step, so no
        # item can land behind the _CLOSE sentinel or sneak past the
        # queue bound under concurrent submitters
        with self._state_lock:
            if self._closed:
                raise ServerClosed("batcher is closed")
            if self._max_queue_rows and \
                    self._backlog_rows + rows > self._max_queue_rows:
                retry = self._retry_after_locked()
                self._shed.inc(1, model=self.name)
                raise QueueFullError(self._backlog_rows,
                                     self._max_queue_rows, retry)
            self._backlog_rows += rows
            self._q.put((X, bool(raw_score), fut, deadline))
        return fut

    def _retry_after_locked(self) -> float:
        """Backoff hint: how long the current backlog takes to drain at
        the EWMA device-call latency (>= one batch window)."""
        batches = max(1.0, self._backlog_rows / max(1, self._max_rows))
        return max(0.05, batches * self._ewma_batch_s + self._max_wait)

    def predict(self, X: np.ndarray, raw_score: bool = False,
                timeout_s: Optional[float] = None) -> np.ndarray:
        """Blocking submit; with ``timeout_s`` the call raises
        :class:`DeadlineExceeded` at the deadline instead of hanging the
        calling (handler) thread on a future that is still queued."""
        deadline = None if timeout_s is None else \
            time.monotonic() + float(timeout_s)
        fut = self.submit(X, raw_score, deadline=deadline)
        if deadline is None:
            return fut.result()
        try:
            return fut.result(timeout=max(0.0, deadline - time.monotonic()))
        except FutureTimeout:
            exc = DeadlineExceeded(
                f"request did not complete within {float(timeout_s or 0):.3f}s")
            try:
                # mark the future failed so the worker neither batches
                # nor double-counts this request when it dequeues it
                fut.set_exception(exc)
            except InvalidStateError:
                return fut.result()  # completed in the race window
            self._deadline.inc(1, model=self.name)
            raise exc from None

    def close(self, timeout: Optional[float] = 5.0) -> None:
        with self._state_lock:
            if self._closed:
                return
            self._closed = True
            self._q.put(_CLOSE)
        self._thread.join(timeout)
        # drain: fail anything the worker left behind rather than leaving
        # its caller blocked until a client-side timeout
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not _CLOSE:
                try:
                    item[2].set_exception(ServerClosed(
                        "batcher closed while the request was queued"))
                except InvalidStateError:
                    pass  # its waiter expired it in the race window

    # -- worker side --------------------------------------------------------
    def _take(self, item) -> bool:
        """Account one dequeued request; expire it instead of batching it
        when its deadline already passed."""
        with self._state_lock:
            self._backlog_rows -= int(item[0].shape[0])
        if item[3] is not None and time.monotonic() > item[3]:
            if not item[2].done():
                self._deadline.inc(1, model=self.name)
                item[2].set_exception(DeadlineExceeded(
                    "request expired while queued"))
            return False
        return True

    def _loop(self) -> None:
        while True:
            first = self._q.get()
            if first is _CLOSE:
                return
            if not self._take(first):
                continue
            batch = [first]
            rows = first[0].shape[0]
            deadline = time.monotonic() + self._max_wait
            stop = False
            while rows < self._max_rows:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    if len(batch) == 1:
                        # uncontended request: dispatch immediately — the
                        # wait window only opens once a second request is
                        # already queued, so sequential traffic pays no
                        # max_wait latency tax
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        nxt = self._q.get(timeout=remaining)
                    except queue.Empty:
                        break
                if nxt is _CLOSE:
                    stop = True
                    break
                if self._take(nxt):
                    batch.append(nxt)
                    rows += nxt[0].shape[0]
            self._run(batch)
            if stop:
                return

    def _run(self, batch) -> None:
        groups: dict = {}
        for item in batch:
            groups.setdefault((item[1], item[0].shape[1]), []).append(item)
        for (raw, _cols), group in groups.items():
            t0 = time.monotonic()
            try:
                X = (group[0][0] if len(group) == 1 else
                     np.concatenate([g[0] for g in group], axis=0))
                out = self._predict_fn(X, raw)
                ofs = 0
                for g in group:
                    n = g[0].shape[0]
                    try:
                        g[2].set_result(out[ofs:ofs + n])
                    except InvalidStateError:
                        pass  # its waiter expired it in the race window
                    ofs += n
                # retry-after estimates ride this (reads are unlocked —
                # a slightly stale float is fine)
                self._ewma_batch_s = 0.8 * self._ewma_batch_s + \
                    0.2 * (time.monotonic() - t0)
            except Exception as exc:  # propagate to every waiter in group
                for g in group:
                    try:
                        g[2].set_exception(exc)
                    except InvalidStateError:
                        pass  # its waiter expired it in the race window
