"""Micro-batching queue: coalesce concurrent small requests into one
bucketed device call, with admission control in front of it.

A single worker thread drains the queue under a max-wait/max-rows
policy: the first waiting request opens a window of ``max_wait_ms``;
every request arriving inside it joins the batch until ``max_batch_rows``
is reached.  One concatenated predict runs, and each waiter gets its row
slice back through a Future — so N concurrent single-row requests cost
one device dispatch on the next bucket up instead of N dispatches.

Requests are grouped by (raw_score, feature-count) inside a window: a
malformed request can only fail its own group, never poison co-batched
traffic with a different shape.

Admission control (resilience/admission.py semantics):

  * ``max_queue_rows`` bounds the backlog; a submit that would exceed it
    is rejected with :class:`QueueFullError` carrying a ``retry_after``
    estimated from the EWMA batch latency — admitting more work than the
    device drains only grows everyone's latency, so shed at the door.
  * a per-request ``deadline`` (monotonic seconds) expires queued work:
    the worker fails expired requests with :class:`DeadlineExceeded`
    instead of spending device time on an answer nobody is waiting for,
    and ``predict`` stops blocking at the deadline either way.
  * ``close()`` drains the queue and fails every pending future with
    :class:`ServerClosed` — a shutdown never leaves a caller blocked
    until its own client timeout.

Observability (fleet-observability tentpole):

  * ``serve_queue_rows{model}`` / ``serve_inflight_requests{model}``
    gauges track saturation building, not just requests dying — a load
    test watches the backlog grow BEFORE the shed counter moves;
  * each request's ``X-Request-Id`` rides the queue item; ``stats``
    (a :class:`ModelStats`) receives the per-request queue-wait vs
    device-compute split, and the ids propagate into the predictor when
    its ``predict`` accepts ``request_ids`` (recompile attribution).
"""

from __future__ import annotations

import inspect
import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Callable, Optional

import numpy as np

from ..resilience.admission import (DeadlineExceeded, QueueFullError,
                                    ServerClosed, deadline_counter,
                                    shed_counter)
from ..telemetry.metrics import default_registry

__all__ = ["MicroBatcher", "TenantQueueFull"]

_CLOSE = object()

# queue item slots:
# (X, raw_score, future, deadline, request_id, t_submit, lane)
_X, _RAW, _FUT, _DEADLINE, _RID, _TSUB, _LANE = range(7)


class TenantQueueFull(QueueFullError):
    """Per-tenant quota shed: ONE tenant's lane backlog hit its bound
    while the shared queue still had room — the hot tenant is refused
    before it can crowd out co-batched neighbours (zoo quota
    semantics: per-tenant shed happens BEFORE cross-tenant shed)."""


class MicroBatcher:
    """Thread-safe request coalescer in front of a predict function.

    ``predict_fn(X, raw_score) -> np.ndarray`` must be row-aligned:
    output row i corresponds to input row i (true for every predictor
    path).  ``submit`` returns a Future; ``predict`` blocks on it.
    ``name`` labels the shed/deadline counters and saturation gauges;
    ``stats`` (optional :class:`ModelStats`) receives each request's
    queue-wait vs device-compute timing split.
    """

    def __init__(self, predict_fn: Callable[..., np.ndarray],
                 max_batch_rows: int = 4096,
                 max_wait_ms: float = 2.0,
                 max_queue_rows: int = 0,
                 name: str = "default",
                 stats=None,
                 buckets: Optional[tuple] = None,
                 tenant_queue_rows: int = 0) -> None:
        self._predict_fn = predict_fn
        self._max_rows = int(max_batch_rows)
        self._max_wait = max(0.0, float(max_wait_ms)) / 1e3
        self._max_queue_rows = max(0, int(max_queue_rows))  # 0 = unbounded
        # per-lane (tenant) row bound, checked BEFORE the shared bound
        self._tenant_rows = max(0, int(tenant_queue_rows))
        self._lane_rows: dict = {}
        self.name = str(name)
        self.stats = stats
        self._buckets = tuple(buckets) if buckets is not None else None
        try:
            self._fn_takes_rids = "request_ids" in \
                inspect.signature(predict_fn).parameters
        except (TypeError, ValueError):
            self._fn_takes_rids = False
        self._q: "queue.Queue" = queue.Queue()
        self._closed = False
        self._backlog_rows = 0  # rows admitted but not yet dispatched
        self._ewma_batch_s = 0.05  # device-call latency estimate
        self._state_lock = threading.Lock()  # serializes submit vs close
        self._shed = shed_counter()
        self._deadline = deadline_counter()
        # the saturation gauges live NEXT TO the stats' series (a server
        # with a private metrics registry keeps its saturation private
        # too); without stats they land in the process-wide registry.
        # No zero-init: a gauge series appears on the first submit, so
        # constructing a second batcher can never clobber a live one's
        # reading under the same model label.
        reg = stats.registry if stats is not None and \
            hasattr(stats, "registry") else default_registry()
        self._queue_gauge = reg.gauge(
            "serve_queue_rows",
            "rows admitted to the micro-batcher but not yet dispatched",
            labels=("model",))
        self._inflight_gauge = reg.gauge(
            "serve_inflight_requests",
            "requests admitted and not yet completed", labels=("model",))
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="lgb-tpu-microbatcher")
        self._thread.start()

    # -- client side --------------------------------------------------------
    @property
    def backlog_rows(self) -> int:
        return self._backlog_rows

    def inflight_requests(self) -> int:
        return int(self._inflight_gauge.value(model=self.name))

    def submit(self, X: np.ndarray, raw_score: bool = False,
               deadline: Optional[float] = None,
               request_id: Optional[str] = None,
               lane: Optional[str] = None) -> Future:
        """Queue one request.  ``deadline`` is an absolute
        ``time.monotonic()`` instant after which the request is failed
        with :class:`DeadlineExceeded` rather than dispatched;
        ``request_id`` tags the request's telemetry trail (exemplars,
        recompile attribution).  ``lane`` names the tenant for
        cross-model batchers: it keys the per-tenant quota and tells the
        dispatcher which model lane of the stacked program the rows ride
        (plain per-model batchers leave it None)."""
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        fut: Future = Future()
        rows = int(X.shape[0])
        # the closed/limit checks and the put are one atomic step, so no
        # item can land behind the _CLOSE sentinel or sneak past the
        # queue bound under concurrent submitters
        with self._state_lock:
            if self._closed:
                raise ServerClosed("batcher is closed")
            if lane is not None and self._tenant_rows:
                cur = self._lane_rows.get(lane, 0)
                if cur + rows > self._tenant_rows:
                    # the tenant's own quota sheds first — attributed to
                    # the TENANT's series, not the shared batcher's
                    retry = self._retry_after_locked()
                    self._shed.inc(1, model=lane)
                    raise TenantQueueFull(cur, self._tenant_rows, retry)
            if self._max_queue_rows and \
                    self._backlog_rows + rows > self._max_queue_rows:
                retry = self._retry_after_locked()
                self._shed.inc(1, model=self.name)
                raise QueueFullError(self._backlog_rows,
                                     self._max_queue_rows, retry)
            self._backlog_rows += rows
            if lane is not None:
                self._lane_rows[lane] = self._lane_rows.get(lane, 0) + rows
            self._queue_gauge.set(self._backlog_rows, model=self.name)
            self._inflight_gauge.add(1, model=self.name)
            # the done-callback fires exactly once whichever path settles
            # the future (dispatch, deadline expiry, shutdown drain), so
            # the gauge can never leak under the racy failure paths
            fut.add_done_callback(
                lambda _f: self._inflight_gauge.add(-1, model=self.name))
            self._q.put((X, bool(raw_score), fut, deadline, request_id,
                         time.monotonic(), lane))
        return fut

    def _retry_after_locked(self) -> float:
        """Backoff hint: how long the current backlog takes to drain at
        the EWMA device-call latency (>= one batch window)."""
        batches = max(1.0, self._backlog_rows / max(1, self._max_rows))
        return max(0.05, batches * self._ewma_batch_s + self._max_wait)

    def predict(self, X: np.ndarray, raw_score: bool = False,
                timeout_s: Optional[float] = None,
                request_id: Optional[str] = None,
                lane: Optional[str] = None) -> np.ndarray:
        """Blocking submit; with ``timeout_s`` the call raises
        :class:`DeadlineExceeded` at the deadline instead of hanging the
        calling (handler) thread on a future that is still queued."""
        deadline = None if timeout_s is None else \
            time.monotonic() + float(timeout_s)
        fut = self.submit(X, raw_score, deadline=deadline,
                          request_id=request_id, lane=lane)
        if deadline is None:
            return fut.result()
        try:
            return fut.result(timeout=max(0.0, deadline - time.monotonic()))
        except FutureTimeout:
            exc = DeadlineExceeded(
                f"request did not complete within {float(timeout_s or 0):.3f}s")
            try:
                # mark the future failed so the worker neither batches
                # nor double-counts this request when it dequeues it
                fut.set_exception(exc)
            except InvalidStateError:
                return fut.result()  # completed in the race window
            self._deadline.inc(1, model=self.name)
            raise exc from None

    def close(self, timeout: Optional[float] = 5.0) -> None:
        with self._state_lock:
            if self._closed:
                return
            self._closed = True
            self._q.put(_CLOSE)
        self._thread.join(timeout)
        # drain: fail anything the worker left behind rather than leaving
        # its caller blocked until a client-side timeout — and release
        # the queue-gauge accounting, or the process-wide registry keeps
        # reporting phantom queued rows for a batcher that no longer
        # exists
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not _CLOSE:
                with self._state_lock:
                    self._debit_locked(item)
                try:
                    item[_FUT].set_exception(ServerClosed(
                        "batcher closed while the request was queued"))
                except InvalidStateError:
                    pass  # its waiter expired it in the race window

    # -- worker side --------------------------------------------------------
    def _debit_locked(self, item) -> None:
        """Release one item's backlog accounting (shared + per-lane).
        Caller holds ``_state_lock``."""
        rows = int(item[_X].shape[0])
        self._backlog_rows -= rows
        lane = item[_LANE]
        if lane is not None and lane in self._lane_rows:
            left = self._lane_rows[lane] - rows
            if left > 0:
                self._lane_rows[lane] = left
            else:
                del self._lane_rows[lane]
        self._queue_gauge.set(self._backlog_rows, model=self.name)

    def _take(self, item) -> bool:
        """Account one dequeued request; expire it instead of batching it
        when its deadline already passed."""
        with self._state_lock:
            self._debit_locked(item)
        if item[_DEADLINE] is not None and \
                time.monotonic() > item[_DEADLINE]:
            if not item[_FUT].done():
                self._deadline.inc(1, model=self.name)
                item[_FUT].set_exception(DeadlineExceeded(
                    "request expired while queued"))
            return False
        return True

    def _loop(self) -> None:
        while True:
            first = self._q.get()
            if first is _CLOSE:
                return
            if not self._take(first):
                continue
            batch = [first]
            rows = first[_X].shape[0]
            deadline = time.monotonic() + self._max_wait
            stop = False
            while rows < self._max_rows:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    if len(batch) == 1:
                        # uncontended request: dispatch immediately — the
                        # wait window only opens once a second request is
                        # already queued, so sequential traffic pays no
                        # max_wait latency tax
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        nxt = self._q.get(timeout=remaining)
                    except queue.Empty:
                        break
                if nxt is _CLOSE:
                    stop = True
                    break
                if self._take(nxt):
                    batch.append(nxt)
                    rows += nxt[_X].shape[0]
            self._run(batch)
            if stop:
                return

    def _record_timing(self, group, t_dispatch: float, device_s: float,
                       t_done: float) -> None:
        """Per-request split for one dispatched group: queue wait is the
        time from submit to dispatch, device compute is the group's
        batched call (shared — every co-batched request rode the same
        dispatch)."""
        if self.stats is None:
            return
        from ..models.tree import SHAPE_BUCKETS, bucket_rows
        total_rows = sum(g[_X].shape[0] for g in group)
        # label with the PREDICTOR's ladder when given (a custom-bucket
        # predictor must not report timings under phantom global-ladder
        # buckets it never dispatches)
        bucket = bucket_rows(total_rows, self._buckets
                             if self._buckets is not None else SHAPE_BUCKETS)
        for g in group:
            self.stats.record_request_timing(
                int(g[_X].shape[0]), bucket,
                queue_ms=(t_dispatch - g[_TSUB]) * 1e3,
                device_ms=device_s * 1e3,
                total_ms=(t_done - g[_TSUB]) * 1e3,
                request_id=g[_RID])

    def _run(self, batch) -> None:
        groups: dict = {}
        for item in batch:
            groups.setdefault((item[_RAW], item[_X].shape[1]),
                              []).append(item)
        for (raw, cols), group in groups.items():
            try:
                self._dispatch_group(raw, cols, group)
            except Exception as exc:  # propagate to every waiter in group
                for g in group:
                    try:
                        g[_FUT].set_exception(exc)
                    except InvalidStateError:
                        pass  # its waiter expired it in the race window

    def _dispatch_group(self, raw: bool, cols: int, group) -> None:
        """Run one (raw_score, feature-count) group as a single device
        call and slice results back per request.  The cross-model stack
        batcher (serve/zoo.py) overrides this to form (model-lane,
        bucket) super-batches; everything upstream — window drain,
        deadline expiry, admission accounting — is shared."""
        t0 = time.monotonic()
        X = (group[0][_X] if len(group) == 1 else
             np.concatenate([g[_X] for g in group], axis=0))
        if self._fn_takes_rids:
            out = self._predict_fn(
                X, raw, request_ids=tuple(
                    g[_RID] for g in group if g[_RID]))
        else:
            out = self._predict_fn(X, raw)
        t1 = time.monotonic()
        ofs = 0
        for g in group:
            n = g[_X].shape[0]
            try:
                g[_FUT].set_result(out[ofs:ofs + n])
            except InvalidStateError:
                pass  # its waiter expired it in the race window
            ofs += n
        self._record_timing(group, t0, t1 - t0, time.monotonic())
        # retry-after estimates ride this (reads are unlocked — a
        # slightly stale float is fine)
        self._ewma_batch_s = 0.8 * self._ewma_batch_s + 0.2 * (t1 - t0)
