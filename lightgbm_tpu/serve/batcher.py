"""Micro-batching queue: coalesce concurrent small requests into one
bucketed device call.

A single worker thread drains the queue under a max-wait/max-rows
policy: the first waiting request opens a window of ``max_wait_ms``;
every request arriving inside it joins the batch until ``max_batch_rows``
is reached.  One concatenated predict runs, and each waiter gets its row
slice back through a Future — so N concurrent single-row requests cost
one device dispatch on the next bucket up instead of N dispatches.

Requests are grouped by (raw_score, feature-count) inside a window: a
malformed request can only fail its own group, never poison co-batched
traffic with a different shape.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future
from typing import Callable, Optional

import numpy as np

__all__ = ["MicroBatcher"]

_CLOSE = object()


class MicroBatcher:
    """Thread-safe request coalescer in front of a predict function.

    ``predict_fn(X, raw_score) -> np.ndarray`` must be row-aligned:
    output row i corresponds to input row i (true for every predictor
    path).  ``submit`` returns a Future; ``predict`` blocks on it.
    """

    def __init__(self, predict_fn: Callable[[np.ndarray, bool], np.ndarray],
                 max_batch_rows: int = 4096,
                 max_wait_ms: float = 2.0) -> None:
        self._predict_fn = predict_fn
        self._max_rows = int(max_batch_rows)
        self._max_wait = max(0.0, float(max_wait_ms)) / 1e3
        self._q: "queue.Queue" = queue.Queue()
        self._closed = False
        self._state_lock = threading.Lock()  # serializes submit vs close
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="lgb-tpu-microbatcher")
        self._thread.start()

    # -- client side --------------------------------------------------------
    def submit(self, X: np.ndarray, raw_score: bool = False) -> Future:
        X = np.asarray(X, np.float32)
        if X.ndim == 1:
            X = X.reshape(1, -1)
        fut: Future = Future()
        # the closed-check and the put are one atomic step, so no item
        # can land behind the _CLOSE sentinel and hang its waiter
        with self._state_lock:
            if self._closed:
                raise RuntimeError("batcher is closed")
            self._q.put((X, bool(raw_score), fut))
        return fut

    def predict(self, X: np.ndarray, raw_score: bool = False) -> np.ndarray:
        return self.submit(X, raw_score).result()

    def close(self, timeout: Optional[float] = 5.0) -> None:
        with self._state_lock:
            if self._closed:
                return
            self._closed = True
            self._q.put(_CLOSE)
        self._thread.join(timeout)
        # fail anything the worker left behind rather than hanging waiters
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not _CLOSE and not item[2].done():
                item[2].set_exception(RuntimeError("batcher closed"))

    # -- worker side --------------------------------------------------------
    def _loop(self) -> None:
        import time
        while True:
            first = self._q.get()
            if first is _CLOSE:
                return
            batch = [first]
            rows = first[0].shape[0]
            deadline = time.monotonic() + self._max_wait
            stop = False
            while rows < self._max_rows:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    if len(batch) == 1:
                        # uncontended request: dispatch immediately — the
                        # wait window only opens once a second request is
                        # already queued, so sequential traffic pays no
                        # max_wait latency tax
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        nxt = self._q.get(timeout=remaining)
                    except queue.Empty:
                        break
                if nxt is _CLOSE:
                    stop = True
                    break
                batch.append(nxt)
                rows += nxt[0].shape[0]
            self._run(batch)
            if stop:
                return

    def _run(self, batch) -> None:
        groups: dict = {}
        for item in batch:
            groups.setdefault((item[1], item[0].shape[1]), []).append(item)
        for (raw, _cols), group in groups.items():
            try:
                X = (group[0][0] if len(group) == 1 else
                     np.concatenate([g[0] for g in group], axis=0))
                out = self._predict_fn(X, raw)
                ofs = 0
                for g in group:
                    n = g[0].shape[0]
                    g[2].set_result(out[ofs:ofs + n])
                    ofs += n
            except Exception as exc:  # propagate to every waiter in group
                for g in group:
                    if not g[2].done():
                        g[2].set_exception(exc)
