"""Per-model serving counters surfaced on ``/stats`` and ``/metrics``.

Rebased onto :mod:`lightgbm_tpu.telemetry.metrics`: every counter and the
latency ring are labeled series (``model=<name>``) in a
:class:`MetricsRegistry`, so the Prometheus exporter reads the same
numbers the JSON ``/stats`` endpoint reports.  Registry-managed models
(the HTTP server path) share the process-wide default registry; an
anonymous ``ModelStats()`` (e.g. ``Booster.to_predictor()``) gets a
private registry so unrelated predictors never alias each other's
series.

The counters are bumped on every device call (micro-batches, not client
requests, are the expensive unit); latency percentiles come from a
bounded ring of recent batch latencies — a serving dashboard wants the
current tail, not the all-time one.  ``percentile`` is re-exported from
telemetry.metrics (the single shared implementation).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..telemetry.metrics import (MetricsRegistry, percentile)

__all__ = ["ModelStats", "percentile"]


class ModelStats:
    """Counters for one served model (requests, rows, batches, recompiles,
    per-bucket histogram, p50/p99 latency over a sliding window)."""

    WINDOW = 4096  # batch latencies kept for percentile estimates

    def __init__(self, model: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None) -> None:
        self.model = model if model is not None else "default"
        self._reg = registry if registry is not None else MetricsRegistry()
        self._requests = self._reg.counter(
            "serve_requests_total", "client-level predict calls",
            labels=("model",))
        self._rows = self._reg.counter(
            "serve_rows_total", "data rows predicted (pre-padding)",
            labels=("model",))
        self._batches = self._reg.counter(
            "serve_batches_total", "device calls (post micro-batching)",
            labels=("model",))
        self._recompiles = self._reg.counter(
            "serve_recompiles_total", "XLA traces triggered by novel shapes",
            labels=("model",))
        self._errors = self._reg.counter(
            "serve_errors_total", "failed predict calls", labels=("model",))
        self._bucket = self._reg.counter(
            "serve_batches_by_bucket_total", "device calls per shape bucket",
            labels=("model", "bucket"))
        self._latency = self._reg.histogram(
            "serve_batch_latency_ms", "device-call latency",
            labels=("model",), window=self.WINDOW)
        # touch this model's series so a fresh model scrapes as 0 rather
        # than being absent until its first request
        for c in (self._requests, self._rows, self._batches,
                  self._recompiles, self._errors):
            c.inc(0, model=self.model)

    def record_request(self, n_rows: int = 1) -> None:
        self._requests.inc(1, model=self.model)

    def record_error(self) -> None:
        self._errors.inc(1, model=self.model)

    def record_batch(self, n_rows: int, bucket: int, latency_ms: float,
                     recompiled: bool) -> None:
        m = self.model
        self._batches.inc(1, model=m)
        self._rows.inc(int(n_rows), model=m)
        self._bucket.inc(1, model=m, bucket=str(int(bucket)))
        if recompiled:
            self._recompiles.inc(1, model=m)
        self._latency.observe(latency_ms, model=m)

    def snapshot(self) -> Dict:
        m = self.model
        bucket_hist = {}
        for lbl, val in self._bucket.series():
            if lbl.get("model") == m and val:
                bucket_hist[int(lbl["bucket"])] = int(val)
        lat = self._latency.values_of(model=m)
        return {
            "requests": int(self._requests.value(model=m)),
            "rows": int(self._rows.value(model=m)),
            "batches": int(self._batches.value(model=m)),
            "recompiles": int(self._recompiles.value(model=m)),
            "errors": int(self._errors.value(model=m)),
            "bucket_histogram": {str(k): v for k, v in
                                 sorted(bucket_hist.items())},
            "latency_ms": {
                "p50": round(percentile(lat, 50.0), 4),
                "p99": round(percentile(lat, 99.0), 4),
                "window": len(lat),
            },
        }
