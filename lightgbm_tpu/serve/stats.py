"""Per-model serving counters surfaced on ``/stats`` and ``/metrics``.

Rebased onto :mod:`lightgbm_tpu.telemetry.metrics`: every counter and the
latency ring are labeled series (``model=<name>``) in a
:class:`MetricsRegistry`, so the Prometheus exporter reads the same
numbers the JSON ``/stats`` endpoint reports.  Registry-managed models
(the HTTP server path) share the process-wide default registry; an
anonymous ``ModelStats()`` (e.g. ``Booster.to_predictor()``) gets a
private registry so unrelated predictors never alias each other's
series.

The counters are bumped on every device call (micro-batches, not client
requests, are the expensive unit); latency percentiles come from a
bounded ring of recent batch latencies — a serving dashboard wants the
current tail, not the all-time one.  ``percentile`` is re-exported from
telemetry.metrics (the single shared implementation).

Per-request tracing (fleet observability): ``record_request_timing``
lands each request's queue-wait / device-compute / total split in
``(model, bucket)``-labeled histograms — the series the per-bucket p99
latency SLO declared below is keyed to — and feeds the process-wide
slowest-N exemplar ring, so an SLO breach dumps the offending requests
(id, bucket, split) instead of a bare percentile.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional

from ..telemetry.metrics import (Counter, MetricsRegistry,
                                 WindowedHistogram, default_registry,
                                 percentile)
from ..telemetry.slo import (ExemplarRing, register_metric_ensurer, slo)

__all__ = ["ModelStats", "ExplainTimingStats", "percentile",
           "request_exemplars", "EXEMPLAR_CAPACITY"]

# bounded ring of the slowest requests seen, dumped alongside SLO
# breaches (/slo attaches it whenever something burns)
EXEMPLAR_CAPACITY = 32
_exemplars = ExemplarRing(EXEMPLAR_CAPACITY)


def request_exemplars() -> ExemplarRing:
    """The process-wide slowest-request ring (worst-first snapshot)."""
    return _exemplars


# The per-bucket tail objective, declared next to the code that records
# the series it reads: every (model, bucket) combination of the request
# latency histogram is evaluated independently, so one declaration
# covers the whole SHAPE_BUCKETS ladder.  threshold_ms is the
# environment knob (the load-test harness re-declares it per env via
# slo.set_latency_threshold).
slo("serve/latency_p99", metric="serve_request_latency_ms", kind="latency",
    target=0.99, threshold_ms=500.0, min_events=20,
    note="99% of requests complete under threshold_ms, per shape bucket")

# The explanation lane's tail objective: /explain requests land their
# end-to-end latency in their OWN (model, bucket) histogram so TreeSHAP
# traffic (a much heavier program: (T*L, D) path slots per row) never
# dilutes — nor hides behind — the predict p99 above.  Threshold is an
# environment knob, same as serve/latency_p99.
slo("serve/explain_latency_p99", metric="serve_explain_latency_ms",
    kind="latency", target=0.99, threshold_ms=2000.0, min_events=20,
    note="99% of /explain requests complete under threshold_ms, per "
         "shape bucket")


class ModelStats:
    """Counters for one served model (requests, rows, batches, recompiles,
    per-bucket histogram, p50/p99 latency over a sliding window)."""

    WINDOW = 4096  # batch latencies kept for percentile estimates

    def __init__(self, model: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None,
                 prime: bool = True) -> None:
        self.model = model if model is not None else "default"
        self._reg = registry if registry is not None else MetricsRegistry()
        fam = _metric_family(self._reg)
        self._requests = fam.requests
        self._rows = fam.rows
        self._batches = fam.batches
        self._recompiles = fam.recompiles
        self._errors = fam.errors
        self._bucket = fam.bucket
        self._latency = fam.latency
        self._req_latency = fam.req_latency
        self._queue_wait = fam.queue_wait
        self._device = fam.device
        self._explain_latency = fam.explain_latency
        self._explain_requests = fam.explain_requests
        if prime:
            self.prime_series()
        self.last_recompile_requests: tuple = ()
        # per-bucket hot-path handles for the three timing windows
        # (label resolution once per bucket, not once per request)
        self._timing_handles: Dict[str, tuple] = {}

    def prime_series(self) -> None:
        """Touch this model's series so it scrapes as 0 rather than
        being absent until its first request.  ``ModelRegistry`` defers
        this until a first load succeeds, so a failed load never leaves
        phantom ``model=<name>`` series in the shared registry."""
        for c in (self._requests, self._rows, self._batches,
                  self._recompiles, self._errors):
            c.inc(0, model=self.model)

    @property
    def registry(self) -> MetricsRegistry:
        """The registry this model's series live in (the micro-batcher
        parks its saturation gauges next to them)."""
        return self._reg

    def record_request(self, n_rows: int = 1) -> None:
        self._requests.inc(1, model=self.model)

    def record_error(self) -> None:
        self._errors.inc(1, model=self.model)

    def record_batch(self, n_rows: int, bucket: int, latency_ms: float,
                     recompiled: bool, request_ids: tuple = ()) -> None:
        m = self.model
        self._batches.inc(1, model=m)
        self._rows.inc(int(n_rows), model=m)
        self._bucket.inc(1, model=m, bucket=str(int(bucket)))
        if recompiled:
            self._recompiles.inc(1, model=m)
            if request_ids:
                # which requests paid an XLA trace: a p99 exemplar that
                # says "recompile" answers itself
                self.last_recompile_requests = tuple(request_ids)
        self._latency.observe(latency_ms, model=m)

    def record_request_timing(self, n_rows: int, bucket: int,
                              queue_ms: float, device_ms: float,
                              total_ms: float,
                              request_id: Optional[str] = None) -> None:
        """One client request's latency split (micro-batcher or direct
        path): queue wait vs device compute, plus the total, all
        ``(model, bucket)``-labeled — the per-bucket p99 SLO series.
        This is the serving hot path (per request, not per batch), so
        the exemplar dict is only built for requests the slowest-N ring
        would actually keep."""
        m, b = self.model, str(int(bucket))
        handles = self._timing_handles.get(b)
        if handles is None:
            handles = self._timing_handles[b] = (
                self._req_latency.handle(model=m, bucket=b),
                self._queue_wait.handle(model=m, bucket=b),
                self._device.handle(model=m, bucket=b))
        handles[0].observe(total_ms)
        handles[1].observe(queue_ms)
        handles[2].observe(device_ms)
        if _exemplars.would_accept(total_ms):
            _exemplars.offer(total_ms, {
                "request_id": request_id or "-", "model": m,
                "rows": int(n_rows), "bucket": int(bucket),
                "queue_ms": round(queue_ms, 4),
                "device_ms": round(device_ms, 4),
                "total_ms": round(total_ms, 4),
                "recompile": bool(request_id and request_id in
                                  self.last_recompile_requests),
            })

    def record_explain_timing(self, n_rows: int, bucket: int,
                              queue_ms: float, device_ms: float,
                              total_ms: float,
                              request_id: Optional[str] = None) -> None:
        """One /explain request's end-to-end latency, landed in the
        dedicated ``serve_explain_latency_ms`` histogram (the
        ``serve/explain_latency_p99`` SLO series) plus the shared
        slowest-N exemplar ring with a ``lane: explain`` tag."""
        m, b = self.model, str(int(bucket))
        self._explain_requests.inc(1, model=m)
        self._explain_latency.observe(total_ms, model=m, bucket=b)
        if _exemplars.would_accept(total_ms):
            _exemplars.offer(total_ms, {
                "request_id": request_id or "-", "model": m,
                "lane": "explain", "rows": int(n_rows),
                "bucket": int(bucket),
                "queue_ms": round(queue_ms, 4),
                "device_ms": round(device_ms, 4),
                "total_ms": round(total_ms, 4),
            })

    def explain_timing_stats(self) -> "ExplainTimingStats":
        """A stats facade for the explain lane's micro-batcher: same
        registry (the batcher's saturation gauges park next to this
        model's series under a distinct label), but request timings land
        in the explain histogram instead of the predict one."""
        return ExplainTimingStats(self)

    def release(self) -> int:
        """Retire every ``model=<name>`` series this instance created in
        its registry (counters, per-bucket histograms, the batcher's
        saturation gauges).  Called on zoo eviction so a churned tenant
        leaves nothing behind; returns the number of series dropped.
        The instance must not record after release."""
        self._timing_handles.clear()
        return self._reg.remove_series(model=self.model)

    def bucket_timing(self, bucket: int) -> Dict[str, list]:
        """One bucket's raw timing windows (sorted copies) — the
        serve-latency benchmark reads the queue-wait vs device-compute
        split per bucket from here."""
        m, b = self.model, str(int(bucket))
        return {
            "request_latency_ms": self._req_latency.values_of(
                model=m, bucket=b),
            "queue_wait_ms": self._queue_wait.values_of(model=m, bucket=b),
            "device_ms": self._device.values_of(model=m, bucket=b),
        }

    def _timing_summary(self, hist, ps=(50.0, 99.0)) -> Dict:
        vals: list = []
        for lbl, _summ in hist.series():
            if lbl.get("model") == self.model:
                vals.extend(hist.values_of(**lbl))
        vals.sort()
        out = {f"p{p:g}": round(percentile(vals, p), 4) for p in ps}
        out["window"] = len(vals)
        return out

    def snapshot(self) -> Dict:
        m = self.model
        bucket_hist = {}
        for lbl, val in self._bucket.series():
            if lbl.get("model") == m and val:
                bucket_hist[int(lbl["bucket"])] = int(val)
        lat = self._latency.values_of(model=m)
        return {
            "requests": int(self._requests.value(model=m)),
            "rows": int(self._rows.value(model=m)),
            "batches": int(self._batches.value(model=m)),
            "recompiles": int(self._recompiles.value(model=m)),
            "errors": int(self._errors.value(model=m)),
            "bucket_histogram": {str(k): v for k, v in
                                 sorted(bucket_hist.items())},
            "latency_ms": {
                "p50": round(percentile(lat, 50.0), 4),
                "p99": round(percentile(lat, 99.0), 4),
                "window": len(lat),
            },
            # the per-request split (pooled over buckets; the labeled
            # series carry the per-bucket detail on /metrics)
            "request_latency_ms": self._timing_summary(self._req_latency),
            "queue_wait_ms": self._timing_summary(self._queue_wait),
            "device_ms": self._timing_summary(self._device),
            "explain_requests": int(self._explain_requests.value(model=m)),
            "explain_latency_ms": self._timing_summary(
                self._explain_latency),
        }


class ExplainTimingStats:
    """Duck-typed ``stats`` for the explain lane's ``MicroBatcher``:
    exposes the same registry (saturation gauges) and model name, but
    routes ``record_request_timing`` into the explain latency series so
    the two lanes' p99 objectives stay independent."""

    def __init__(self, base: ModelStats) -> None:
        self._base = base
        self.model = f"{base.model}#explain"

    @property
    def registry(self) -> MetricsRegistry:
        return self._base.registry

    def record_request(self, n_rows: int = 1) -> None:
        pass  # request counting is the explain counter's job

    def record_request_timing(self, n_rows: int, bucket: int,
                              queue_ms: float, device_ms: float,
                              total_ms: float,
                              request_id: Optional[str] = None) -> None:
        self._base.record_explain_timing(n_rows, bucket, queue_ms,
                                         device_ms, total_ms, request_id)


class _Family(NamedTuple):
    requests: Counter
    rows: Counter
    batches: Counter
    recompiles: Counter
    errors: Counter
    bucket: Counter
    latency: WindowedHistogram
    req_latency: WindowedHistogram
    queue_wait: WindowedHistogram
    device: WindowedHistogram
    explain_latency: WindowedHistogram
    explain_requests: Counter


def _metric_family(reg: MetricsRegistry) -> _Family:
    """Create (get-or-create) the serving metric families in ``reg``.
    ModelStats binds these per instance; the SLO-coverage ensurer calls
    it standalone so every series an SLO may key to exists in the
    registry before any traffic does."""
    return _Family(
        requests=reg.counter(
            "serve_requests_total", "client-level predict calls",
            labels=("model",)),
        rows=reg.counter(
            "serve_rows_total", "data rows predicted (pre-padding)",
            labels=("model",)),
        batches=reg.counter(
            "serve_batches_total", "device calls (post micro-batching)",
            labels=("model",)),
        recompiles=reg.counter(
            "serve_recompiles_total",
            "XLA traces triggered by novel shapes", labels=("model",)),
        errors=reg.counter(
            "serve_errors_total", "failed predict calls",
            labels=("model",)),
        bucket=reg.counter(
            "serve_batches_by_bucket_total",
            "device calls per shape bucket", labels=("model", "bucket")),
        latency=reg.histogram(
            "serve_batch_latency_ms", "device-call latency",
            labels=("model",), window=ModelStats.WINDOW),
        req_latency=reg.histogram(
            "serve_request_latency_ms",
            "per-request end-to-end latency (queue + device + copy)",
            labels=("model", "bucket"), window=ModelStats.WINDOW),
        queue_wait=reg.histogram(
            "serve_queue_wait_ms",
            "per-request micro-batcher queue wait before dispatch",
            labels=("model", "bucket"), window=ModelStats.WINDOW),
        device=reg.histogram(
            "serve_device_ms",
            "per-request share of the batched device call",
            labels=("model", "bucket"), window=ModelStats.WINDOW),
        explain_latency=reg.histogram(
            "serve_explain_latency_ms",
            "per-request end-to-end /explain latency (queue + device + "
            "copy)", labels=("model", "bucket"),
            window=ModelStats.WINDOW),
        explain_requests=reg.counter(
            "serve_explain_requests_total",
            "client-level explain calls", labels=("model",)),
    )


@register_metric_ensurer
def _ensure_serving_metrics(reg: MetricsRegistry) -> None:
    _metric_family(reg)
    # the batcher's saturation gauges (serve/batcher.py bumps them)
    reg.gauge("serve_queue_rows",
              "rows admitted to the micro-batcher but not yet dispatched",
              labels=("model",))
    reg.gauge("serve_inflight_requests",
              "requests admitted and not yet completed", labels=("model",))


# eagerly materialize the families in the default registry so a scrape
# (or the coverage lint) sees them before the first served request
_ensure_serving_metrics(default_registry())
