"""Per-model serving counters surfaced on the ``/stats`` endpoint.

Thread-safe by a single lock per model: the counters are bumped on every
device call (micro-batches, not client requests, are the expensive unit)
and snapshots are cheap dict copies.  Latency percentiles come from a
bounded ring of recent batch latencies — a serving dashboard wants the
current tail, not the all-time one.
"""

from __future__ import annotations

import threading
from typing import Dict, List

__all__ = ["ModelStats", "percentile"]


def percentile(sorted_vals: List[float], p: float) -> float:
    """Nearest-rank percentile over pre-sorted values (shared by /stats
    and the latency benchmark so the two never diverge)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class ModelStats:
    """Counters for one served model (requests, rows, batches, recompiles,
    per-bucket histogram, p50/p99 latency over a sliding window)."""

    WINDOW = 4096  # batch latencies kept for percentile estimates

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.requests = 0      # client-level calls (HTTP or registry)
        self.rows = 0          # data rows predicted (pre-padding)
        self.batches = 0       # device calls (post micro-batching)
        self.recompiles = 0    # XLA traces triggered by novel shapes
        self.errors = 0
        self.bucket_hist: Dict[int, int] = {}
        self._lat_ms: List[float] = []
        self._lat_pos = 0

    def record_request(self, n_rows: int = 1) -> None:
        with self._lock:
            self.requests += 1

    def record_error(self) -> None:
        with self._lock:
            self.errors += 1

    def record_batch(self, n_rows: int, bucket: int, latency_ms: float,
                     recompiled: bool) -> None:
        with self._lock:
            self.batches += 1
            self.rows += int(n_rows)
            self.bucket_hist[bucket] = self.bucket_hist.get(bucket, 0) + 1
            if recompiled:
                self.recompiles += 1
            if len(self._lat_ms) < self.WINDOW:
                self._lat_ms.append(latency_ms)
            else:
                self._lat_ms[self._lat_pos] = latency_ms
                self._lat_pos = (self._lat_pos + 1) % self.WINDOW

    def snapshot(self) -> Dict:
        with self._lock:
            lat = sorted(self._lat_ms)
            return {
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "recompiles": self.recompiles,
                "errors": self.errors,
                "bucket_histogram": {str(k): v for k, v in
                                     sorted(self.bucket_hist.items())},
                "latency_ms": {
                    "p50": round(percentile(lat, 50.0), 4),
                    "p99": round(percentile(lat, 99.0), 4),
                    "window": len(lat),
                },
            }
