"""Model registry: named warm predictors with atomic hot-swap.

A registry maps names to :class:`CompiledPredictor` instances.  Loading
builds (and optionally warms) the new predictor entirely OUTSIDE the
lock, then swaps the reference in one locked assignment — readers either
get the old version or the new one, never a half-built model, and
traffic is served without interruption during a rollout.

Stats survive a swap: the new predictor inherits the old entry's
``ModelStats``, so ``/stats`` counters (including recompiles — usually 0
on a same-shape rollout thanks to the shared compile cache) track the
NAME, not the version.  Since the series live in the process-wide
telemetry registry (labeled ``model=<name>``), they are monotone across
ModelRegistry instances too — Prometheus counter semantics: a new
registry serving a previously-served name continues the name's series
rather than resetting it (scrapers take rates; pass a private
``metrics_registry`` for isolated counters).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from .predictor import CompiledPredictor, release_compile_keys
from .stats import ModelStats
from ..publish.delta import DeltaChainError, DeltaRecord, fingerprint_text
from ..telemetry.metrics import default_registry
from ..utils.log import log_info

__all__ = ["ModelRegistry", "ModelInUseError"]


class ModelInUseError(ValueError):
    """Refused eviction: the model is the registry's only (i.e. the
    default-served) entry.  Pass ``force=True`` to evict anyway."""


class ModelRegistry:
    """Thread-safe named model store with atomic hot-swap and eviction."""

    def __init__(self, max_models: Optional[int] = None,
                 metrics_registry=None) -> None:
        self._lock = threading.Lock()
        self._models: Dict[str, CompiledPredictor] = {}
        # stats live keyed by NAME, independent of predictor versions, so
        # counters survive hot-swaps and racing first-time loads of the
        # same name share one instance
        self._stats: Dict[str, ModelStats] = {}
        self._versions: Dict[str, int] = {}
        # file path behind each loaded name (None for in-memory sources)
        # — a rolling deploy reads it back to roll a regressed swap back
        self._sources: Dict[str, Optional[str]] = {}
        # delta-chain position per name: (round, fingerprint) of the
        # last applied record; cleared by load()/evict() so a full
        # reload re-anchors the chain
        self._chain: Dict[str, tuple] = {}
        self._max_models = max_models
        # registry-managed models report into the process-wide metrics
        # registry (labeled model=<name>) so /metrics covers them
        self._metrics = (metrics_registry if metrics_registry is not None
                         else default_registry())

    def load(self, name: str, source, warmup: bool = True,
             **predictor_kwargs) -> CompiledPredictor:
        """Load or hot-swap ``name``.  The predictor is built and warmed
        before the swap, so in-flight traffic never waits on a compile;
        the swap itself is one dict assignment under the lock.  A build
        or warmup failure (corrupt file -> :class:`ModelCorruptError`,
        bad params, ...) therefore leaves the OLD entry serving
        untouched — same version, same stats, never torn or evicted —
        and surfaces the typed error to the caller."""
        with self._lock:
            stats = self._stats.get(name)
            created_stats = stats is None
            if created_stats:
                # priming deferred: a failed first load must not leave
                # phantom model=<name> series in the shared metrics
                # registry either (only registry-private bookkeeping is
                # rolled back below)
                stats = self._stats[name] = ModelStats(
                    model=name, registry=self._metrics, prime=False)
        try:
            pred = CompiledPredictor(source, stats=stats,
                                     **predictor_kwargs)
            if warmup:
                pred.warmup()
        except Exception:
            with self._lock:
                # a failed FIRST load must not leave a phantom stats
                # entry for a name that never served (hot-swap failures
                # keep theirs: the old version is still live)
                if created_stats and name not in self._models:
                    self._stats.pop(name, None)
            raise
        if created_stats:
            stats.prime_series()
        with self._lock:
            swapped = name in self._models
            self._models[name] = pred
            self._versions[name] = self._versions.get(name, 0) + 1
            self._sources[name] = source if isinstance(source, str) \
                else None
            self._chain.pop(name, None)   # full load re-anchors deltas
            if self._max_models is not None and \
                    len(self._models) > self._max_models:
                # evict the oldest OTHER entry (insertion order)
                for victim in list(self._models):
                    if victim != name:
                        self._drop_locked(victim)
                        break
        log_info(f"serve: {'hot-swapped' if swapped else 'loaded'} model "
                 f"'{name}' (v{self._versions[name]}, "
                 f"{pred.num_trees} trees)")
        return pred

    def get(self, name: Optional[str] = None) -> CompiledPredictor:
        """Predictor by name; with ``name=None`` the single loaded model
        (the common one-model deployment needs no name in requests)."""
        with self._lock:
            if name is None:
                if len(self._models) == 1:
                    return next(iter(self._models.values()))
                raise KeyError(
                    f"registry holds {len(self._models)} models; requests "
                    "must name one" if self._models else "no models loaded")
            if name not in self._models:
                raise KeyError(f"unknown model '{name}'")
            return self._models[name]

    def evict(self, name: str, force: bool = False) -> bool:
        """Drop ``name``.  Evicting the registry's ONLY model — the one
        unnamed requests resolve to — raises :class:`ModelInUseError`
        unless ``force=True``, so a fat-fingered evict cannot take a
        single-model deployment dark.  In-flight readers that already
        resolved the predictor finish normally either way: predictors
        are immutable and handlers hold their own reference."""
        with self._lock:
            if name not in self._models:
                return False
            if not force and len(self._models) == 1:
                raise ModelInUseError(
                    f"'{name}' is the only loaded model (the default "
                    f"served one); evicting it would take the service "
                    f"dark — pass force=True to do it anyway")
            self._drop_locked(name)
            log_info(f"serve: evicted model '{name}'")
            return True

    def _drop_locked(self, name: str) -> None:
        """Remove ``name`` and release everything it held: its metric
        series (stats.release) and — when no surviving model shares its
        shape signature — the signature's compile-cache mirror entries.
        Without this, zoo churn ratchets the process: same-shape compile
        caches are shared (PR 1), so only the LAST model of a shape may
        release them.  Caller holds ``self._lock``."""
        victim = self._models.pop(name)
        stats = self._stats.pop(name, None)
        self._sources.pop(name, None)
        self._chain.pop(name, None)
        if stats is not None:
            stats.release()
        sig = victim.signature
        if not any(p.signature == sig for p in self._models.values()):
            release_compile_keys(sig)

    # -- continuous-learning lane (publish/) --------------------------------
    def apply_delta(self, name: str, record) -> dict:
        """Append a published delta's trees to ``name`` without a full
        reload: parse the fragment, extend the predictor (dense-table
        splice inside the shard-padding envelope — zero recompiles — or
        a rebuild), and hot-swap atomically exactly like :meth:`load`.

        ``record`` is a :class:`DeltaRecord` or its wire bytes.  The
        chain position is validated first — a round gap or fingerprint
        mismatch raises :class:`DeltaChainError` BEFORE any work, and a
        failed build leaves the old predictor serving — so a subscriber
        that fell behind gets a typed signal to fall back to a full
        reload instead of serving a torn ensemble."""
        from ..publish.subscriber import trees_from_fragment
        if isinstance(record, (bytes, bytearray)):
            record = DeltaRecord.from_bytes(bytes(record))
        with self._lock:
            if name not in self._models:
                raise KeyError(f"unknown model '{name}'")
            pred = self._models[name]
            chain = self._chain.get(name)
            source = self._sources.get(name)
        if chain is not None:
            rnd, fp = chain
            if record.round <= rnd:
                return {"model": name, "round": rnd, "mode": "noop",
                        "num_trees": pred.num_trees}
            if record.base_round != rnd or record.parent_fp != fp:
                raise DeltaChainError(
                    f"model '{name}' is at round {rnd} "
                    f"(fp {fp[:12]}...); delta extends round "
                    f"{record.base_round} (fp {record.parent_fp[:12]}...)"
                    f" — reload the full model to re-anchor")
        else:
            k = max(1, pred.num_class)
            have = pred.num_trees // k
            if record.base_round != have:
                raise DeltaChainError(
                    f"model '{name}' holds {have} rounds; delta extends "
                    f"round {record.base_round} — reload the full model "
                    f"to re-anchor")
            if source is not None:
                with open(source, "rb") as fh:
                    src_fp = fingerprint_text(fh.read().decode("utf-8"))
                if src_fp != record.parent_fp:
                    raise DeltaChainError(
                        f"model '{name}' was loaded from a base with "
                        f"fingerprint {src_fp[:12]}...; delta chains "
                        f"from {record.parent_fp[:12]}... — reload the "
                        f"full model to re-anchor")
        trees, frag_k = trees_from_fragment(
            record.payload, source=f"<delta round {record.round}>")
        if frag_k != max(1, pred.num_class):
            raise DeltaChainError(
                f"delta num_tree_per_iteration {frag_k} != model "
                f"{pred.num_class}")
        # build outside the lock (hot-swap discipline): a failure here
        # leaves the old predictor — and its chain position — untouched
        pred2, mode = pred.extended(trees)
        with self._lock:
            if self._models.get(name) is not pred:
                raise DeltaChainError(
                    f"model '{name}' was swapped while the delta was "
                    f"being applied; replay from its new round")
            self._models[name] = pred2
            self._versions[name] = self._versions.get(name, 0) + 1
            self._chain[name] = (record.round, record.fp)
        log_info(f"serve: applied delta to '{name}' -> round "
                 f"{record.round} ({mode}, {pred2.num_trees} trees)")
        return {"model": name, "round": record.round, "mode": mode,
                "num_trees": pred2.num_trees}

    def round_of(self, name: str) -> Optional[int]:
        """Last delta-applied round for ``name`` (None before any
        delta)."""
        with self._lock:
            chain = self._chain.get(name)
            return chain[0] if chain is not None else None

    def source_of(self, name: str) -> Optional[str]:
        """File path serving under ``name`` (None when loaded from an
        in-memory object) — the rollback source for a rolling deploy."""
        with self._lock:
            return self._sources.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return list(self._models)

    def info(self) -> Dict[str, dict]:
        with self._lock:
            items = list(self._models.items())
            versions = dict(self._versions)
            sources = dict(self._sources)
        return {name: {**pred.info(), "version": versions.get(name, 1),
                       "source": sources.get(name)}
                for name, pred in items}

    def stats(self) -> Dict[str, dict]:
        with self._lock:
            items = list(self._models.items())
        return {name: pred.stats.snapshot() for name, pred in items}
