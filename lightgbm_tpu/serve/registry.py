"""Model registry: named warm predictors with atomic hot-swap.

A registry maps names to :class:`CompiledPredictor` instances.  Loading
builds (and optionally warms) the new predictor entirely OUTSIDE the
lock, then swaps the reference in one locked assignment — readers either
get the old version or the new one, never a half-built model, and
traffic is served without interruption during a rollout.

Stats survive a swap: the new predictor inherits the old entry's
``ModelStats``, so ``/stats`` counters (including recompiles — usually 0
on a same-shape rollout thanks to the shared compile cache) track the
NAME, not the version.  Since the series live in the process-wide
telemetry registry (labeled ``model=<name>``), they are monotone across
ModelRegistry instances too — Prometheus counter semantics: a new
registry serving a previously-served name continues the name's series
rather than resetting it (scrapers take rates; pass a private
``metrics_registry`` for isolated counters).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from .predictor import CompiledPredictor
from .stats import ModelStats
from ..telemetry.metrics import default_registry
from ..utils.log import log_info

__all__ = ["ModelRegistry"]


class ModelRegistry:
    """Thread-safe named model store with atomic hot-swap and eviction."""

    def __init__(self, max_models: Optional[int] = None,
                 metrics_registry=None) -> None:
        self._lock = threading.Lock()
        self._models: Dict[str, CompiledPredictor] = {}
        # stats live keyed by NAME, independent of predictor versions, so
        # counters survive hot-swaps and racing first-time loads of the
        # same name share one instance
        self._stats: Dict[str, ModelStats] = {}
        self._versions: Dict[str, int] = {}
        # file path behind each loaded name (None for in-memory sources)
        # — a rolling deploy reads it back to roll a regressed swap back
        self._sources: Dict[str, Optional[str]] = {}
        self._max_models = max_models
        # registry-managed models report into the process-wide metrics
        # registry (labeled model=<name>) so /metrics covers them
        self._metrics = (metrics_registry if metrics_registry is not None
                         else default_registry())

    def load(self, name: str, source, warmup: bool = True,
             **predictor_kwargs) -> CompiledPredictor:
        """Load or hot-swap ``name``.  The predictor is built and warmed
        before the swap, so in-flight traffic never waits on a compile;
        the swap itself is one dict assignment under the lock.  A build
        or warmup failure (corrupt file -> :class:`ModelCorruptError`,
        bad params, ...) therefore leaves the OLD entry serving
        untouched — same version, same stats, never torn or evicted —
        and surfaces the typed error to the caller."""
        with self._lock:
            stats = self._stats.get(name)
            created_stats = stats is None
            if created_stats:
                # priming deferred: a failed first load must not leave
                # phantom model=<name> series in the shared metrics
                # registry either (only registry-private bookkeeping is
                # rolled back below)
                stats = self._stats[name] = ModelStats(
                    model=name, registry=self._metrics, prime=False)
        try:
            pred = CompiledPredictor(source, stats=stats,
                                     **predictor_kwargs)
            if warmup:
                pred.warmup()
        except Exception:
            with self._lock:
                # a failed FIRST load must not leave a phantom stats
                # entry for a name that never served (hot-swap failures
                # keep theirs: the old version is still live)
                if created_stats and name not in self._models:
                    self._stats.pop(name, None)
            raise
        if created_stats:
            stats.prime_series()
        with self._lock:
            swapped = name in self._models
            self._models[name] = pred
            self._versions[name] = self._versions.get(name, 0) + 1
            self._sources[name] = source if isinstance(source, str) \
                else None
            if self._max_models is not None and \
                    len(self._models) > self._max_models:
                # evict the oldest OTHER entry (insertion order)
                for victim in list(self._models):
                    if victim != name:
                        del self._models[victim]
                        self._stats.pop(victim, None)
                        self._sources.pop(victim, None)
                        break
        log_info(f"serve: {'hot-swapped' if swapped else 'loaded'} model "
                 f"'{name}' (v{self._versions[name]}, "
                 f"{pred.num_trees} trees)")
        return pred

    def get(self, name: Optional[str] = None) -> CompiledPredictor:
        """Predictor by name; with ``name=None`` the single loaded model
        (the common one-model deployment needs no name in requests)."""
        with self._lock:
            if name is None:
                if len(self._models) == 1:
                    return next(iter(self._models.values()))
                raise KeyError(
                    f"registry holds {len(self._models)} models; requests "
                    "must name one" if self._models else "no models loaded")
            if name not in self._models:
                raise KeyError(f"unknown model '{name}'")
            return self._models[name]

    def evict(self, name: str) -> bool:
        with self._lock:
            if name not in self._models:
                return False
            del self._models[name]
            self._stats.pop(name, None)
            self._sources.pop(name, None)
            log_info(f"serve: evicted model '{name}'")
            return True

    def source_of(self, name: str) -> Optional[str]:
        """File path serving under ``name`` (None when loaded from an
        in-memory object) — the rollback source for a rolling deploy."""
        with self._lock:
            return self._sources.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return list(self._models)

    def info(self) -> Dict[str, dict]:
        with self._lock:
            items = list(self._models.items())
            versions = dict(self._versions)
            sources = dict(self._sources)
        return {name: {**pred.info(), "version": versions.get(name, 1),
                       "source": sources.get(name)}
                for name, pred in items}

    def stats(self) -> Dict[str, dict]:
        with self._lock:
            items = list(self._models.items())
        return {name: pred.stats.snapshot() for name, pred in items}
