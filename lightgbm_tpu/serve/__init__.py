"""lightgbm_tpu.serve — low-latency inference subsystem.

Holds trained models warm on device and answers request traffic without
per-request Python dispatch costs or fresh XLA traces:

- :class:`CompiledPredictor` — device-resident ensemble arrays +
  jit-compiled prediction per shape bucket (``SHAPE_BUCKETS`` ladder),
  with ahead-of-time ``warmup()``;
- :class:`MicroBatcher` — coalesces concurrent small requests into one
  bucketed device call under a max-wait/max-rows policy;
- :class:`ModelRegistry` — named models, shared compile caches across
  versions, atomic hot-swap for rollouts;
- :class:`PredictionServer` — dependency-free ``http.server`` JSON
  endpoint (``/predict``, ``/models``, ``/healthz``, ``/stats``),
  exposed as the ``python -m lightgbm_tpu serve`` CLI verb;
- :class:`ModelStats` — per-model serving counters behind ``/stats``;
- :class:`ModelZoo` — bounded multi-tenant tier over the registry:
  traffic-weighted LRU eviction under a resident budget, cold
  load-on-miss inside the request deadline, per-tenant quotas, and
  batched cross-model dispatch (same-lowering-shape tenants fused into
  one stacked MXU launch per (stack, bucket) super-batch);
- :class:`FleetSupervisor` — N worker processes behind one dispatcher
  with crash-restart, a crash-loop circuit breaker, rolling drain and
  zero-downtime rolling deploys (``python -m lightgbm_tpu
  serve-fleet``).
"""

from .batcher import MicroBatcher
from .compiler import DenseExecutable, DenseLoweringError, \
    compile_ensemble, fallback_counts
from .fleet import FleetSupervisor
from .predictor import SHAPE_BUCKETS, CompiledPredictor
from .registry import ModelRegistry
from .server import PredictionServer
from .stats import ModelStats
from .zoo import ModelZoo

__all__ = ["CompiledPredictor", "MicroBatcher", "ModelRegistry",
           "PredictionServer", "ModelStats", "SHAPE_BUCKETS",
           "DenseExecutable", "DenseLoweringError", "compile_ensemble",
           "fallback_counts", "FleetSupervisor", "ModelZoo"]
