"""Text data parsers: CSV / TSV / LibSVM with format auto-detection
(reference: src/io/parser.cpp:235 ``Parser::CreateParser`` + parser.hpp
CSVParser/TSVParser/LibSVMParser; label column handling per config
label_column)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np


def _detect_format(line: str) -> str:
    """LibSVM iff a post-label token looks like ``<int>:<number>`` (a
    headered CSV whose second column name contains ':' must NOT be
    misrouted); otherwise by delimiter."""
    tokens = line.split()
    for tok in tokens[1:3]:
        head, _, tail = tok.partition(":")
        if _ and head.isdigit():
            try:
                float(tail)
                return "libsvm"
            except ValueError:
                pass
    if "\t" in line:
        return "tsv"
    if "," in line:
        return "csv"
    return "tsv"


def load_sidecar(path: str, kind: str) -> Optional[np.ndarray]:
    """Load a ``<data>.weight`` / ``<data>.query`` sidecar file if present
    (reference dataset_loader.cpp Metadata::Init weight/query file
    convention: one value per line)."""
    import os
    side = f"{path}.{kind}"
    if not os.path.exists(side):
        return None
    return np.loadtxt(side, dtype=np.float64).ravel()


def load_data_file(path: str, params: Optional[Dict[str, Any]] = None
                   ) -> Tuple[np.ndarray, List[str], Optional[np.ndarray]]:
    """Load a CSV/TSV/LibSVM file -> (features, names, label).

    Follows the reference CLI convention: first column is the label unless
    ``label_column`` says otherwise; ``header=true`` skips/uses a header row.
    """
    params = params or {}
    header = str(params.get("header", "false")).lower() in ("true", "1")
    label_col = 0
    lc = str(params.get("label_column", "") or params.get("label", ""))
    if lc.startswith("column_") or lc.isdigit():
        label_col = int(lc.replace("column_", "") or 0)

    with open(path) as fh:
        first = fh.readline()
        if not first.strip():
            raise ValueError(f"{path} is empty")
    fmt = _detect_format(first.strip())

    if fmt == "libsvm":
        return _load_libsvm(path)

    delim = "," if fmt == "csv" else "\t"
    skip = 1 if header else 0
    raw = np.genfromtxt(path, delimiter=delim, skip_header=skip,
                        dtype=np.float64)
    if raw.ndim == 1:
        raw = raw.reshape(-1, 1)
    names: List[str] = []
    if header:
        with open(path) as fh:
            names = [c.strip() for c in fh.readline().strip().split(delim)]
    label = raw[:, label_col].copy()
    feats = np.delete(raw, label_col, axis=1)
    if names:
        names = names[:label_col] + names[label_col + 1:]
    else:
        names = [f"Column_{i}" for i in range(feats.shape[1])]
    return feats, names, label


def _load_libsvm(path: str) -> Tuple[np.ndarray, List[str], np.ndarray]:
    labels: List[float] = []
    rows: List[Dict[int, float]] = []
    max_idx = -1
    with open(path) as fh:
        for line in fh:
            parts = line.strip().split()
            if not parts:
                continue
            labels.append(float(parts[0]))
            row = {}
            for tok in parts[1:]:
                if ":" not in tok:
                    continue
                idx, val = tok.split(":", 1)
                j = int(idx)
                row[j] = float(val)
                max_idx = max(max_idx, j)
            rows.append(row)
    n, f = len(rows), max_idx + 1
    out = np.zeros((n, f), np.float64)
    for i, row in enumerate(rows):
        for j, v in row.items():
            out[i, j] = v
    names = [f"Column_{i}" for i in range(f)]
    return out, names, np.asarray(labels)
