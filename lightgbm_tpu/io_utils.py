"""Text data parsers (CSV / TSV / LibSVM with format auto-detection,
reference: src/io/parser.cpp:235 ``Parser::CreateParser`` + parser.hpp
CSVParser/TSVParser/LibSVMParser; label column handling per config
label_column) and crash-safe file writing shared by model saves and the
resilience checkpoint subsystem."""

from __future__ import annotations

import itertools
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

# concurrent writers to the SAME target must not share a temp file, or one
# open('wb') truncates the other mid-write and the rename publishes the
# interleaved bytes this module exists to prevent
_tmp_seq = itertools.count()

# missing-value tokens every CSV path coerces to NaN (genfromtxt-ish
# tolerance) — single-sourced so the in-core loader below and the
# streaming CSVSource (ingest/source.py) cannot drift
CSV_NA_VALUES = ("", "NA", "nan", "NULL", "null", "?", "N/A", "na")


def parse_label_column(params: Dict[str, Any]) -> int:
    """The reference CLI ``label_column`` convention: column 0 unless
    ``label_column``/``label`` names ``column_<i>`` or a bare index —
    shared by :func:`load_data_file` and the streaming CSVSource."""
    lc = str(params.get("label_column", "") or params.get("label", ""))
    if lc.startswith("column_") or lc.isdigit():
        return int(lc.replace("column_", "") or 0)
    return 0


def atomic_write_bytes(path: str, data: Optional[bytes] = None,
                       writer: Optional[Callable] = None) -> None:
    """Write a file so a crash at ANY point leaves either the old content
    or the new — never a truncated hybrid: write to a same-directory temp
    file, flush + fsync it, ``os.replace`` onto the target (atomic on
    POSIX), then fsync the directory so the rename itself is durable.

    Pass raw ``data`` bytes, or a ``writer(fh)`` callback for producers
    that stream into a file object (``np.savez``)."""
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    tmp = os.path.join(
        d, f".{os.path.basename(path)}.tmp.{os.getpid()}"
           f".{threading.get_ident()}.{next(_tmp_seq)}")
    try:
        with open(tmp, "wb") as fh:
            if writer is not None:
                writer(fh)
            else:
                fh.write(data or b"")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        try:
            dfd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass  # some filesystems refuse directory fsync; rename landed
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_text(path: str, text: str) -> None:
    """Crash-safe text-file write (see :func:`atomic_write_bytes`)."""
    atomic_write_bytes(path, text.encode("utf-8"))


def _detect_format(line: str) -> str:
    """LibSVM iff a post-label token looks like ``<int>:<number>`` (a
    headered CSV whose second column name contains ':' must NOT be
    misrouted); otherwise by delimiter."""
    tokens = line.split()
    for tok in tokens[1:3]:
        head, _, tail = tok.partition(":")
        if _ and head.isdigit():
            try:
                float(tail)
                return "libsvm"
            except ValueError:
                pass
    if "\t" in line:
        return "tsv"
    if "," in line:
        return "csv"
    return "tsv"


def load_sidecar(path: str, kind: str) -> Optional[np.ndarray]:
    """Load a ``<data>.weight`` / ``<data>.query`` sidecar file if present
    (reference dataset_loader.cpp Metadata::Init weight/query file
    convention: one value per line)."""
    import os
    side = f"{path}.{kind}"
    if not os.path.exists(side):
        return None
    return np.loadtxt(side, dtype=np.float64).ravel()


def load_data_file(path: str, params: Optional[Dict[str, Any]] = None
                   ) -> Tuple[np.ndarray, List[str], Optional[np.ndarray]]:
    """Load a CSV/TSV/LibSVM file -> (features, names, label).

    Follows the reference CLI convention: first column is the label unless
    ``label_column`` says otherwise; ``header=true`` skips/uses a header row.
    """
    params = params or {}
    header = str(params.get("header", "false")).lower() in ("true", "1")
    label_col = parse_label_column(params)

    with open(path) as fh:
        first = fh.readline()
        if not first.strip():
            raise ValueError(f"{path} is empty")
    fmt = _detect_format(first.strip())

    two_round = False  # honor reference aliases (config.h two_round)
    for key in ("two_round", "two_round_loading", "use_two_round_loading"):
        if str(params.get(key, "false")).lower() in ("true", "1"):
            two_round = True

    if fmt == "libsvm":
        if two_round:
            from .utils.log import log_warning
            log_warning("two_round chunked loading applies to dense "
                        "CSV/TSV only; the LibSVM parser loads in one "
                        "pass")
        return _load_libsvm(path)

    delim = "," if fmt == "csv" else "\t"
    skip = 1 if header else 0
    raw = _load_dense(path, delim, skip, two_round)
    if raw.ndim == 1:
        raw = raw.reshape(-1, 1)
    names: List[str] = []
    if header:
        with open(path) as fh:
            names = [c.strip() for c in fh.readline().strip().split(delim)]
    label = raw[:, label_col].copy()
    feats = np.delete(raw, label_col, axis=1)
    if names:
        names = names[:label_col] + names[label_col + 1:]
    else:
        names = [f"Column_{i}" for i in range(feats.shape[1])]
    return feats, names, label


def _load_dense(path: str, delim: str, skip: int,
                two_round: bool) -> np.ndarray:
    """Dense CSV/TSV -> float64 matrix.

    Default: one-shot C-parser read.  ``two_round=true`` (reference
    config.h two_round + dataset_loader.cpp:902's two-pass low-memory
    loading) streams the file in bounded chunks into a preallocated
    array instead of materializing parser intermediates for the whole
    file — for datasets close to memory size.
    """
    try:
        import pandas as pd
    except ImportError:           # minimal environments: numpy fallback
        return np.genfromtxt(path, delimiter=delim, skip_header=skip,
                             dtype=np.float64)
    # match genfromtxt's tolerance: '#' comments stripped, missing markers
    # and ANY unparseable token coerced to NaN rather than raising (the
    # slow coerce path only runs when the fast typed parse fails)
    kw = dict(sep=delim, header=None, skiprows=skip, comment="#",
              na_values=list(CSV_NA_VALUES))

    def _to_f64(df):
        """Clean numeric columns are already float64 after type inference
        (no copy cost); mixed/object columns go through per-column coerce
        so junk tokens become NaN like genfromtxt."""
        try:
            return df.astype(np.float64).to_numpy()
        except (ValueError, TypeError):
            return df.apply(pd.to_numeric, errors="coerce").to_numpy(
                np.float64)

    if not two_round:
        return _to_f64(pd.read_csv(path, **kw))
    # pass 1: count only parseable data rows (comment/blank lines would
    # otherwise inflate the preallocation this low-memory mode exists to
    # bound)
    with open(path) as fh:
        for _ in range(skip):
            fh.readline()
        n = sum(1 for line in fh
                if line.strip() and not line.lstrip().startswith("#"))
    out: Optional[np.ndarray] = None
    r = 0
    for chunk in pd.read_csv(path, chunksize=1 << 18, **kw):
        a = _to_f64(chunk)
        if out is None:
            out = np.empty((n, a.shape[1]), np.float64)
        out[r:r + len(a)] = a
        r += len(a)
    if out is None:
        raise ValueError(f"{path} has no data rows")
    if r < n:
        # release the slack instead of keeping a view over the larger
        # buffer alive
        return np.ascontiguousarray(out[:r])
    return out[:r]


def _load_libsvm(path: str) -> Tuple[np.ndarray, List[str], np.ndarray]:
    labels: List[float] = []
    rows: List[Dict[int, float]] = []
    max_idx = -1
    with open(path) as fh:
        for line in fh:
            parts = line.strip().split()
            if not parts:
                continue
            labels.append(float(parts[0]))
            row = {}
            for tok in parts[1:]:
                if ":" not in tok:
                    continue
                idx, val = tok.split(":", 1)
                j = int(idx)
                row[j] = float(val)
                max_idx = max(max_idx, j)
            rows.append(row)
    n, f = len(rows), max_idx + 1
    out = np.zeros((n, f), np.float64)
    for i, row in enumerate(rows):
        for j, v in row.items():
            out[i, j] = v
    names = [f"Column_{i}" for i in range(f)]
    return out, names, np.asarray(labels)
