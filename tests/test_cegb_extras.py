"""CEGB, feature_fraction_bynode, and prediction early stop tests
(reference: cost_effective_gradient_boosting.hpp, col_sampler.hpp bynode,
prediction_early_stop.cpp)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(2)
    n = 1000
    X = rng.randn(n, 6)
    y = (X[:, 0] + 0.8 * X[:, 1] + 0.3 * X[:, 2] > 0).astype(float)
    return X, y


P = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
     "metric": "binary_logloss"}


def test_cegb_coupled_penalty_blocks_expensive_features(data):
    X, y = data
    base = lgb.train(P, lgb.Dataset(X, y), 15)

    def used(bst):
        s = set()
        for t in bst._gbdt.models:
            s.update(int(f) for f in t.split_feature[:t.num_leaves - 1]
                     if f >= 0)
        return s
    assert 1 in used(base)
    # a huge coupled penalty on feature 1 keeps it out of the model
    pen = [0.0, 1e9, 0.0, 0.0, 0.0, 0.0]
    bst = lgb.train({**P, "cegb_penalty_feature_coupled": pen},
                    lgb.Dataset(X, y), 15)
    assert 1 not in used(bst)
    # a small penalty is paid once: feature 1 comes back
    pen2 = [0.0, 1e-3, 0.0, 0.0, 0.0, 0.0]
    bst2 = lgb.train({**P, "cegb_penalty_feature_coupled": pen2},
                     lgb.Dataset(X, y), 15)
    assert 1 in used(bst2)


def test_cegb_split_penalty_shrinks_trees(data):
    X, y = data
    base = lgb.train(P, lgb.Dataset(X, y), 10)
    bst = lgb.train({**P, "cegb_penalty_split": 0.01}, lgb.Dataset(X, y), 10)

    def leaves(b):
        return sum(t.num_leaves for t in b._gbdt.models)
    assert leaves(bst) < leaves(base)


def test_feature_fraction_bynode(data):
    X, y = data
    bst = lgb.train({**P, "feature_fraction_bynode": 0.5},
                    lgb.Dataset(X, y), 15)
    # still learns
    pred = bst.predict(X)
    auc_order = np.argsort(-pred)
    assert y[auc_order[:200]].mean() > 0.7
    # deterministic given the seed
    bst2 = lgb.train({**P, "feature_fraction_bynode": 0.5},
                     lgb.Dataset(X, y), 15)
    np.testing.assert_array_equal(bst.predict(X), bst2.predict(X))
    # different from un-sampled training
    base = lgb.train(P, lgb.Dataset(X, y), 15)
    assert not np.allclose(bst.predict(X), base.predict(X))


def test_feature_contri(data):
    """Per-feature gain multipliers (feature_histogram.hpp:94 penalty)."""
    X, y = data
    base = lgb.train(P, lgb.Dataset(X, y), 10)
    pen = lgb.train({**P, "feature_contri": [1, 0.01, 1, 1, 1, 1]},
                    lgb.Dataset(X, y), 10)

    def uses(b, f):
        return sum(int(np.sum(t.split_feature[:t.num_leaves - 1] == f))
                   for t in b._gbdt.models)
    assert uses(pen, 1) < uses(base, 1)


def test_pos_neg_bagging(data):
    """Balanced bagging (gbdt.cpp:199): per-class sampling fractions."""
    X, y = data
    bst = lgb.train({**P, "bagging_freq": 1, "pos_bagging_fraction": 0.9,
                     "neg_bagging_fraction": 0.3}, lgb.Dataset(X, y), 3)
    mask = np.asarray(bst._gbdt._bag_mask)
    pos_rate = mask[y > 0].mean()
    neg_rate = mask[y <= 0].mean()
    assert abs(pos_rate - 0.9) < 0.02
    assert abs(neg_rate - 0.3) < 0.02


def test_interaction_constraints(data):
    """col_sampler.hpp GetByNode semantics: two features may share a branch
    only when some constraint set contains both."""
    X, y = data
    bst = lgb.train({**P, "interaction_constraints": "[0,3],[1,2]"},
                    lgb.Dataset(X, y), 10)

    def check(tree, node, path):
        if node < 0:
            return
        f = int(tree.split_feature[node])
        path2 = path | {f}
        assert path2 <= {0, 3} or path2 <= {1, 2}, \
            f"branch uses features {path2} across constraint groups"
        check(tree, int(tree.left_child[node]), path2)
        check(tree, int(tree.right_child[node]), path2)

    for tree in bst._gbdt.models:
        if tree.num_leaves > 1:
            check(tree, 0, set())
    # features outside every group (4, 5) never appear
    for tree in bst._gbdt.models:
        sf = set(int(f) for f in tree.split_feature[:tree.num_leaves - 1])
        assert not (sf & {4, 5})


def test_forced_splits(data, tmp_path):
    """forcedsplits_filename JSON BFS (serial_tree_learner.cpp:450): the
    first tree's top splits follow the file regardless of gain."""
    import json
    X, y = data
    fs = {"feature": 5, "threshold": 0.0,
          "left": {"feature": 4, "threshold": 0.5}}
    path = str(tmp_path / "forced.json")
    json.dump(fs, open(path, "w"))
    bst = lgb.train({**P, "forcedsplits_filename": path},
                    lgb.Dataset(X, y), 5)
    for tree in bst._gbdt.models:
        assert tree.split_feature[0] == 5
        assert abs(tree.threshold[0] - 0.0) < 0.1
        # node 1 is the forced left-child split on feature 4
        assert tree.split_feature[1] == 4
    # feature 5 is noise: an unforced model would not split it at the root
    base = lgb.train(P, lgb.Dataset(X, y), 5)
    assert base._gbdt.models[0].split_feature[0] != 5


def test_pred_early_stop_binary(data):
    X, y = data
    bst = lgb.train(P, lgb.Dataset(X, y), 60)
    full = bst.predict(X, raw_score=True)
    es = bst.predict(X, raw_score=True, pred_early_stop=True,
                     pred_early_stop_freq=5, pred_early_stop_margin=2.0)
    # stopped rows froze their margin beyond the threshold, same sign
    assert np.all(np.sign(es[np.abs(full) > 3]) ==
                  np.sign(full[np.abs(full) > 3]))
    # a huge margin means no early exit at all
    es_off = bst.predict(X, raw_score=True, pred_early_stop=True,
                         pred_early_stop_margin=1e9)
    np.testing.assert_allclose(es_off, full, rtol=1e-5, atol=1e-6)


def test_pred_early_stop_multiclass():
    rng = np.random.RandomState(4)
    X = rng.randn(600, 5)
    y = np.argmax(X[:, :3] + 0.3 * rng.randn(600, 3), axis=1).astype(float)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 15, "verbosity": -1}, lgb.Dataset(X, y), 40)
    full = bst.predict(X)
    es = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=5,
                     pred_early_stop_margin=1e9)
    np.testing.assert_allclose(es, full, rtol=1e-5, atol=1e-6)
    es2 = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=2,
                      pred_early_stop_margin=0.5)
    # class decisions overwhelmingly agree even with early exits
    assert (np.argmax(es2, 1) == np.argmax(full, 1)).mean() > 0.95


def test_cegb_feature_lazy_discourages_new_features(data):
    """cegb_penalty_feature_lazy charges per row whose feature was never
    computed on its path (CalculateOndemandCosts): a prohibitive lazy
    penalty on every feature kills all splits; a penalty on one feature
    steers trees away from it; zero penalties change nothing."""
    X, y = data
    base = {**P, "tree_grow_mode": "wave"}

    # prohibitive penalty everywhere -> no split clears the gain bar
    bst = lgb.train({**base, "cegb_tradeoff": 1.0,
                     "cegb_penalty_feature_lazy": [1e6] * X.shape[1]},
                    lgb.Dataset(X, y), 3)
    assert np.allclose(np.var(bst.predict(X)), 0.0, atol=1e-12)

    # penalty on feature 0 only -> its importance collapses
    free = lgb.train(base, lgb.Dataset(X, y), 8)
    pen = lgb.train({**base, "cegb_tradeoff": 1.0,
                     "cegb_penalty_feature_lazy":
                     [50.0] + [0.0] * (X.shape[1] - 1)},
                    lgb.Dataset(X, y), 8)
    imp_free = free.feature_importance("split")
    imp_pen = pen.feature_importance("split")
    assert imp_free[0] > 0
    assert imp_pen[0] < imp_free[0]

    # zero lazy penalties are a no-op
    zero = lgb.train({**base, "cegb_tradeoff": 1.0,
                      "cegb_penalty_feature_lazy": [0.0] * X.shape[1]},
                     lgb.Dataset(X, y), 8)
    np.testing.assert_allclose(zero.predict(X), free.predict(X), atol=2e-5)


def test_cegb_feature_lazy_dp_matches_serial(data):
    X, y = data
    kw = {**P, "tree_grow_mode": "wave", "cegb_tradeoff": 0.8,
          "cegb_penalty_feature_lazy": [0.2] * X.shape[1]}
    ps = lgb.train(kw, lgb.Dataset(X, y), 5).predict(X)
    pd_ = lgb.train({**kw, "tree_learner": "data"},
                    lgb.Dataset(X, y), 5).predict(X)
    np.testing.assert_allclose(pd_, ps, atol=2e-5)


def test_cegb_feature_lazy_bitmap_persists_across_trees(data):
    """The used-feature bitmap lives for the whole training run (the
    reference's feature_used_in_data_ is allocated once and never
    cleared), so features paid for in tree 1 are free in tree 2."""
    X, y = data
    ds = lgb.Dataset(X, y, params={**P, "tree_grow_mode": "wave",
                                   "cegb_tradeoff": 1.0,
                                   "cegb_penalty_feature_lazy":
                                   [0.05] * X.shape[1]})
    bst = lgb.Booster(params={**P, "tree_grow_mode": "wave",
                              "cegb_tradeoff": 1.0,
                              "cegb_penalty_feature_lazy":
                              [0.05] * X.shape[1]}, train_set=ds)
    bst.update()
    used1 = int(np.asarray(bst._gbdt.learner._lazy_used).sum())
    bst.update()
    used2 = int(np.asarray(bst._gbdt.learner._lazy_used).sum())
    assert used1 > 0
    assert used2 >= used1  # never cleared between trees
