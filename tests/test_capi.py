"""LGBM_* C-API surface tests (reference pattern: tests/c_api_test/test_.py
— dataset + booster round trips through the handle-based API)."""

import numpy as np
import pytest

import lightgbm_tpu.capi as capi


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(8)
    X = rng.randn(400, 5)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


def test_dataset_booster_roundtrip(data, tmp_path):
    X, y = data
    code, dh = capi.LGBM_DatasetCreateFromMat(
        X, "objective=binary verbosity=-1", label=y)
    assert code == 0
    assert capi.LGBM_DatasetGetNumData(dh) == (0, 400)
    assert capi.LGBM_DatasetGetNumFeature(dh) == (0, 5)

    code, bh = capi.LGBM_BoosterCreate(
        dh, "objective=binary num_leaves=15 verbosity=-1 metric=auc "
            "is_training_metric=true")
    assert code == 0
    for _ in range(10):
        code, finished = capi.LGBM_BoosterUpdateOneIter(bh)
        assert code == 0
    assert capi.LGBM_BoosterGetCurrentIteration(bh) == (0, 10)
    assert capi.LGBM_BoosterNumberOfTotalModel(bh) == (0, 10)

    code, evals = capi.LGBM_BoosterGetEval(bh, 0)
    assert code == 0 and evals and evals[0][0] == "auc"
    assert evals[0][1] > 0.8

    code, preds = capi.LGBM_BoosterPredictForMat(bh, X)
    assert code == 0 and preds.shape == (400,)

    path = str(tmp_path / "m.txt")
    assert capi.LGBM_BoosterSaveModel(bh, path)[0] == 0
    code, bh2 = capi.LGBM_BoosterCreateFromModelfile(path)
    assert code == 0
    code, preds2 = capi.LGBM_BoosterPredictForMat(bh2, X)
    np.testing.assert_allclose(preds2, preds, rtol=1e-5, atol=1e-6)

    assert capi.LGBM_BoosterFree(bh)[0] == 0
    assert capi.LGBM_DatasetFree(dh)[0] == 0


def test_fields_and_custom_update(data):
    X, y = data
    _, dh = capi.LGBM_DatasetCreateFromMat(X, "objective=none verbosity=-1")
    assert capi.LGBM_DatasetSetField(dh, "label", y)[0] == 0
    code, lab = capi.LGBM_DatasetGetField(dh, "label")
    np.testing.assert_array_equal(lab, y.astype(np.float32))

    _, bh = capi.LGBM_BoosterCreate(dh, "objective=none verbosity=-1 "
                                        "num_leaves=7")
    score = np.zeros(len(y), np.float32)
    for _ in range(3):
        p = 1.0 / (1.0 + np.exp(-score))
        code, _ = capi.LGBM_BoosterUpdateOneIterCustom(bh, p - y, p * (1 - p))
        assert code == 0
    assert capi.LGBM_BoosterNumberOfTotalModel(bh) == (0, 3)


def test_error_contract():
    # default mode: exceptions propagate with real stack traces
    with pytest.raises(ValueError):
        capi.LGBM_BoosterCreate(99999, "")
    # ABI-strict mode restores the -1 + GetLastError contract
    capi.strict_abi(True)
    try:
        code, _ = capi.LGBM_BoosterCreate(99999, "")
        assert code == -1
        assert "handle" in capi.LGBM_GetLastError()
    finally:
        capi.strict_abi(False)


def test_streaming_push_rows_matches_from_mat(data):
    """PushRows ingestion == direct from-mat construction
    (c_api.h:66-270 streaming contract)."""
    X, y = data
    _, ref = capi.LGBM_DatasetCreateFromMat(
        X, "objective=binary verbosity=-1 num_leaves=7", label=y)
    _, sh = capi.LGBM_DatasetCreateByReference(ref, len(X))
    for lo in range(0, len(X), 150):
        code, _ = capi.LGBM_DatasetPushRows(sh, X[lo:lo + 150], lo)
        assert code == 0
    capi.LGBM_DatasetSetField(sh, "label", y)

    preds = {}
    for name, dh in (("mat", ref), ("stream", sh)):
        _, bh = capi.LGBM_BoosterCreate(
            dh, "objective=binary num_leaves=7 verbosity=-1")
        for _ in range(5):
            capi.LGBM_BoosterUpdateOneIter(bh)
        _, preds[name] = capi.LGBM_BoosterPredictForMat(bh, X)
    np.testing.assert_allclose(preds["stream"], preds["mat"], atol=1e-7)


def test_push_rows_by_csr_and_sparse_predict(data):
    import scipy.sparse as sp
    X, y = data
    Xs = X.copy()
    Xs[np.abs(Xs) < 0.8] = 0.0
    csr = sp.csr_matrix(Xs)
    _, ref = capi.LGBM_DatasetCreateFromMat(
        Xs, "objective=binary verbosity=-1 num_leaves=7", label=y)
    _, sh = capi.LGBM_DatasetCreateByReference(ref, len(X))
    for lo in range(0, len(X), 128):
        capi.LGBM_DatasetPushRowsByCSR(sh, csr[lo:lo + 128], lo)
    capi.LGBM_DatasetSetField(sh, "label", y)
    _, bh = capi.LGBM_BoosterCreate(
        sh, "objective=binary num_leaves=7 verbosity=-1")
    for _ in range(4):
        capi.LGBM_BoosterUpdateOneIter(bh)
    _, dense_pred = capi.LGBM_BoosterPredictForMat(bh, Xs)
    _, sparse_pred = capi.LGBM_BoosterPredictForCSR(bh, csr)
    np.testing.assert_allclose(sparse_pred, dense_pred, atol=1e-7)
    _, one = capi.LGBM_BoosterPredictForCSRSingleRow(bh, csr[3])
    np.testing.assert_allclose(one, dense_pred[3], atol=1e-7)


def test_single_row_subset_and_file_predict(data, tmp_path):
    X, y = data
    _, dh = capi.LGBM_DatasetCreateFromMat(
        X, "objective=binary verbosity=-1", label=y)
    _, bh = capi.LGBM_BoosterCreate(
        dh, "objective=binary num_leaves=7 verbosity=-1")
    for _ in range(4):
        capi.LGBM_BoosterUpdateOneIter(bh)
    _, full = capi.LGBM_BoosterPredictForMat(bh, X)
    _, single = capi.LGBM_BoosterPredictForMatSingleRow(bh, X[7])
    np.testing.assert_allclose(single, full[7], atol=1e-9)

    # subset shares bin mappers
    idx = np.arange(0, 200)
    code, sub = capi.LGBM_DatasetGetSubset(dh, idx)
    assert code == 0
    assert capi.LGBM_DatasetGetNumData(sub) == (0, 200)

    # file prediction round trip
    f = tmp_path / "rows.csv"
    np.savetxt(f, np.column_stack([y, X]), delimiter="\t")
    out = tmp_path / "preds.txt"
    code, _ = capi.LGBM_BoosterPredictForFile(
        bh, str(f), result_filename=str(out))
    assert code == 0
    got = np.loadtxt(out)
    np.testing.assert_allclose(got, full, rtol=1e-6, atol=1e-7)


def test_predict_types(data):
    X, y = data
    _, dh = capi.LGBM_DatasetCreateFromMat(
        X, "objective=binary verbosity=-1", label=y)
    _, bh = capi.LGBM_BoosterCreate(dh, "objective=binary verbosity=-1 "
                                        "num_leaves=7")
    for _ in range(5):
        capi.LGBM_BoosterUpdateOneIter(bh)
    _, raw = capi.LGBM_BoosterPredictForMat(
        bh, X, predict_type=capi.C_API_PREDICT_RAW_SCORE)
    _, leaf = capi.LGBM_BoosterPredictForMat(
        bh, X, predict_type=capi.C_API_PREDICT_LEAF_INDEX)
    _, contrib = capi.LGBM_BoosterPredictForMat(
        bh, X, predict_type=capi.C_API_PREDICT_CONTRIB)
    assert leaf.shape == (400, 5) and leaf.dtype.kind == "i"
    assert contrib.shape == (400, 6)
    np.testing.assert_allclose(contrib.sum(1), raw, rtol=1e-4, atol=1e-4)
