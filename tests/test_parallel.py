"""Distributed learner tests on the 8-device CPU mesh (SURVEY.md §4: the
reference's test_dask.py pattern — N workers on localhost, compare to
serial — becomes mesh-sharded training compared to the serial learner)."""

import jax
import numpy as np
import pytest

from conftest import FP_SKIP

import lightgbm_tpu as lgb

SMALL = {"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1}


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(11)
    n = 700  # deliberately not divisible by 8 to exercise padding
    X = rng.randn(n, 6)
    y = ((X[:, 0] + 0.5 * X[:, 1] - X[:, 2] ** 2 * 0.2) > 0).astype(np.float64)
    return X, y


def test_mesh_available():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("tree_learner", [
    "data", pytest.param("feature", marks=FP_SKIP), "voting"])
def test_parallel_matches_serial(tree_learner, data):
    X, y = data
    p = {}
    for tl in ("serial", tree_learner):
        bst = lgb.train({**SMALL, "objective": "binary", "tree_learner": tl},
                        lgb.Dataset(X, y), 5)
        p[tl] = bst.predict(X)
    np.testing.assert_allclose(p[tree_learner], p["serial"], atol=2e-5)


def test_data_parallel_regression(data):
    X, y = data
    yr = X[:, 0] * 2 + np.sin(X[:, 1])
    serial = lgb.train({**SMALL, "objective": "regression"},
                       lgb.Dataset(X, yr), 5).predict(X)
    dp = lgb.train({**SMALL, "objective": "regression",
                    "tree_learner": "data"}, lgb.Dataset(X, yr), 5).predict(X)
    np.testing.assert_allclose(dp, serial, atol=1e-4)


@pytest.mark.parametrize("tree_learner", [
    "data", pytest.param("feature", marks=FP_SKIP)])
def test_parallel_bagging_goss_matches_serial(tree_learner, data):
    """Sampling paths under shard_map: bagging masks and GOSS gradient
    amplification must reproduce the serial learner exactly (the mask is
    computed host-side and sharded with the rows)."""
    X, y = data
    for extra in ({"bagging_fraction": 0.6, "bagging_freq": 1},
                  {"boosting": "goss", "top_rate": 0.3, "other_rate": 0.2,
                   "learning_rate": 0.5}):
        p = {}
        for tl in ("serial", tree_learner):
            bst = lgb.train({**SMALL, "objective": "binary",
                             "tree_learner": tl, **extra},
                            lgb.Dataset(X, y), 4)
            p[tl] = bst.predict(X)
        np.testing.assert_allclose(p[tree_learner], p["serial"], atol=2e-5)


def test_parallel_multiclass_matches_serial(data):
    X, _ = data
    rng = np.random.RandomState(5)
    y = rng.randint(0, 3, len(X)).astype(np.float64)
    p = {}
    for tl in ("serial", "data"):
        bst = lgb.train({**SMALL, "objective": "multiclass", "num_class": 3,
                         "tree_learner": tl}, lgb.Dataset(X, y), 3)
        p[tl] = bst.predict(X)
    np.testing.assert_allclose(p["data"], p["serial"], atol=2e-5)


def test_parallel_categorical_nan_matches_serial():
    rng = np.random.RandomState(9)
    n = 640
    c = rng.randint(0, 8, n).astype(float)
    x1 = rng.randn(n)
    x1[rng.rand(n) < 0.15] = np.nan  # NaN bin routing under shard_map
    y = np.where(c % 2 == 0, 1.5, -1.5) + np.nan_to_num(x1) * 0.3
    X = np.stack([c, x1], 1)
    p = {}
    for tl in ("serial", "data"):
        bst = lgb.train({**SMALL, "objective": "regression",
                         "tree_learner": tl, "cat_smooth": 1.0,
                         "min_data_per_group": 1},
                        lgb.Dataset(X, y, categorical_feature=[0]), 4)
        p[tl] = bst.predict(X)
    np.testing.assert_allclose(p["data"], p["serial"], atol=2e-5)


def test_voting_with_many_features():
    rng = np.random.RandomState(1)
    n, f = 640, 24
    X = rng.randn(n, f)
    y = (X[:, :4].sum(axis=1) > 0).astype(np.float64)
    bst = lgb.train({**SMALL, "objective": "binary", "tree_learner": "voting",
                     "top_k": 5}, lgb.Dataset(X, y), 5)
    p = bst.predict(X)
    # voting restricts aggregated features but must still learn the signal
    order = np.argsort(-p)
    assert y[order[: n // 4]].mean() > 0.8


def test_data_parallel_wave_matches_serial_wave(data):
    """The wave grower under shard_map (one histogram psum per wave) must
    reproduce the single-device wave grower: psum'd histograms make every
    shard's candidate scans identical."""
    X, y = data
    p = {**SMALL, "objective": "binary", "tree_grow_mode": "wave"}
    serial = lgb.train(p, lgb.Dataset(X, y), 5).predict(X)
    dp = lgb.train({**p, "tree_learner": "data"},
                   lgb.Dataset(X, y), 5).predict(X)
    np.testing.assert_allclose(dp, serial, atol=2e-5)


def test_data_parallel_wave_bagging_multiclass(data):
    X, y = data
    rng = np.random.RandomState(3)
    ym = (rng.rand(len(y)) < 0.3).astype(int) + y.astype(int)
    p = {**SMALL, "objective": "multiclass", "num_class": 3,
         "tree_grow_mode": "wave", "bagging_fraction": 0.7,
         "bagging_freq": 1}
    serial = lgb.train(p, lgb.Dataset(X, ym.astype(float)), 4).predict(X)
    dp = lgb.train({**p, "tree_learner": "data"},
                   lgb.Dataset(X, ym.astype(float)), 4).predict(X)
    np.testing.assert_allclose(dp, serial, atol=5e-5)


@pytest.mark.parametrize("extra", [
    {"extra_trees": True},
    {"feature_fraction_bynode": 0.5},
    {"cegb_tradeoff": 0.5, "cegb_penalty_split": 0.05},
    {"interaction_constraints": "[0,3],[1,2]"},
])
def test_dp_wave_extras_match_serial_wave(extra, data):
    """The round-4 DP-wave feature completion: extra_trees / bynode
    sampling / CEGB / interaction constraints under tree_learner=data
    reproduce the serial wave grower exactly (replicated node-key
    streams, identical node ids; parallel_tree_learner.h:54's 'DP wraps
    the serial learner' contract)."""
    X, y = data
    preds = {}
    for tl in ("serial", "data"):
        bst = lgb.train({**SMALL, "objective": "binary",
                         "tree_learner": tl, "tree_grow_mode": "wave",
                         **extra}, lgb.Dataset(X, y), 5)
        preds[tl] = bst.predict(X)
    np.testing.assert_allclose(preds["data"], preds["serial"], atol=2e-5)


def test_wave_extras_quality_vs_partitioned(data):
    """Serial wave with per-node sampling stays quality-par with the
    partitioned grower's implementation of the same features."""
    X, y = data
    ll = {}
    for mode in ("wave", "partition"):
        bst = lgb.train({**SMALL, "objective": "binary",
                         "tree_grow_mode": mode, "extra_trees": True,
                         "feature_fraction_bynode": 0.7,
                         "interaction_constraints": "[0,1,3],[2,4,5]"},
                        lgb.Dataset(X, y), 8)
        pred = np.clip(bst.predict(X), 1e-9, 1 - 1e-9)
        ll[mode] = -np.mean(y * np.log(pred) + (1 - y) * np.log(1 - pred))
    assert ll["wave"] < ll["partition"] * 1.15 + 5e-3


def test_wave_interaction_constraints_respected(data):
    """Trees grown by the wave grower never mix features across
    constraint groups on one branch."""
    X, y = data
    bst = lgb.train({**SMALL, "objective": "binary",
                     "tree_grow_mode": "wave",
                     "interaction_constraints": "[0,3],[1,2],[4,5]"},
                    lgb.Dataset(X, y), 6)
    groups = [{0, 3}, {1, 2}, {4, 5}]
    for tree in bst._gbdt.models:
        nl = int(tree.num_leaves)
        if nl <= 1:
            continue
        # walk root->leaf paths collecting used features
        def walk(node, used):
            f = int(tree.split_feature[node])
            used = used | {f}
            assert any(used <= g for g in groups), used
            for child in (int(tree.left_child[node]),
                          int(tree.right_child[node])):
                if child >= 0:
                    walk(child, used)
        walk(0, set())
