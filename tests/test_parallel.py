"""Distributed learner tests on the 8-device CPU mesh (SURVEY.md §4: the
reference's test_dask.py pattern — N workers on localhost, compare to
serial — becomes mesh-sharded training compared to the serial learner)."""

import jax
import numpy as np
import pytest

import lightgbm_tpu as lgb

SMALL = {"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1}


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(11)
    n = 700  # deliberately not divisible by 8 to exercise padding
    X = rng.randn(n, 6)
    y = ((X[:, 0] + 0.5 * X[:, 1] - X[:, 2] ** 2 * 0.2) > 0).astype(np.float64)
    return X, y


def test_mesh_available():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("tree_learner", ["data", "feature", "voting"])
def test_parallel_matches_serial(tree_learner, data):
    X, y = data
    p = {}
    for tl in ("serial", tree_learner):
        bst = lgb.train({**SMALL, "objective": "binary", "tree_learner": tl},
                        lgb.Dataset(X, y), 5)
        p[tl] = bst.predict(X)
    np.testing.assert_allclose(p[tree_learner], p["serial"], atol=2e-5)


def test_data_parallel_regression(data):
    X, y = data
    yr = X[:, 0] * 2 + np.sin(X[:, 1])
    serial = lgb.train({**SMALL, "objective": "regression"},
                       lgb.Dataset(X, yr), 5).predict(X)
    dp = lgb.train({**SMALL, "objective": "regression",
                    "tree_learner": "data"}, lgb.Dataset(X, yr), 5).predict(X)
    np.testing.assert_allclose(dp, serial, atol=1e-4)


def test_voting_with_many_features():
    rng = np.random.RandomState(1)
    n, f = 640, 24
    X = rng.randn(n, f)
    y = (X[:, :4].sum(axis=1) > 0).astype(np.float64)
    bst = lgb.train({**SMALL, "objective": "binary", "tree_learner": "voting",
                     "top_k": 5}, lgb.Dataset(X, y), 5)
    p = bst.predict(X)
    # voting restricts aggregated features but must still learn the signal
    order = np.argsort(-p)
    assert y[order[: n // 4]].mean() > 0.8
