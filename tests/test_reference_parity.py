"""Cross-implementation parity against the REAL LightGBM.

The committed ``tests/golden/`` fixtures were produced by the reference
CLI binary built CPU-only from /root/reference (empty vendored
submodules shimmed — see scripts/make_golden.py's module docstring; the
build itself: ``cmake -DCMAKE_BUILD_TYPE=Release -DCMAKE_CXX_STANDARD=17
-DCMAKE_CXX_FLAGS="-I<shim> -I<tensorflow>/include"`` for Eigen).  These
tests therefore pin this framework to the reference WITHOUT needing the
binary (the reference's own cross-impl suite is
tests/python_package_test/test_consistency.py + the published metric
discipline of tests/python_package_test/test_dual.py:15-34):

  * reference-trained model files load here and reproduce the
    reference's own predictions bit-for-bit-ish (float tolerance) —
    including multi-category bitset splits and linear-tree leaves;
  * bin boundaries: every split threshold the reference chose is one of
    OUR BinMapper's boundaries on the same data (the thresholds ARE bin
    upper bounds, gbdt_model_text.cpp);
  * same-config training reaches the reference's test metrics.

Set LGBM_TPU_REFERENCE_BIN=/path/to/lightgbm to additionally run the
reverse direction (our model files scored by the reference binary).
"""

import json
import os
import subprocess

import numpy as np
import pytest

import lightgbm_tpu as lgb

HERE = os.path.dirname(os.path.abspath(__file__))
GOLD = os.path.join(HERE, "golden")
EX = os.path.join(HERE, "..", "examples", "binary_classification")
REF_BIN = os.environ.get("LGBM_TPU_REFERENCE_BIN", "")

pytestmark = pytest.mark.skipif(
    not os.path.exists(os.path.join(GOLD, "golden.json")),
    reason="golden fixtures not generated")


def _meta():
    with open(os.path.join(GOLD, "golden.json")) as fh:
        return json.load(fh)


def _logloss(y, p):
    p = np.clip(p, 1e-12, 1 - 1e-12)
    return -np.mean(y * np.log(p) + (1 - y) * np.log(1 - p))


def _auc(y, p):
    order = np.argsort(p)
    ranks = np.empty(len(p))
    ranks[order] = np.arange(1, len(p) + 1)
    npos = y.sum()
    return (ranks[y > 0].sum() - npos * (npos + 1) / 2) / \
        (npos * (len(y) - npos))


def test_reference_binary_model_predicts_identically():
    bst = lgb.Booster(model_file=os.path.join(
        GOLD, "golden_binary_model.txt"))
    test = np.loadtxt(os.path.join(EX, "binary.test"))
    want = np.loadtxt(os.path.join(GOLD, "golden_binary_preds.txt"))
    got = bst.predict(test[:, 1:])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)


def test_reference_catlin_model_predicts_identically():
    """Multi-category bitset splits + linear-tree leaves round-trip."""
    bst = lgb.Booster(model_file=os.path.join(
        GOLD, "golden_catlin_model.txt"))
    data = np.loadtxt(os.path.join(GOLD, "golden_catlin_data.csv"),
                      delimiter=",")
    want = np.loadtxt(os.path.join(GOLD, "golden_catlin_preds.txt"))
    got = bst.predict(data[:, 1:])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)


def test_bin_boundaries_match_reference_thresholds():
    """Every numeric split threshold the reference picked must be one of
    OUR bin upper bounds on the same data (dataset_loader.cpp:950's
    FindBin vs binning.py find_bin)."""
    from lightgbm_tpu.basic import Booster
    bst = Booster(model_file=os.path.join(GOLD, "golden_binary_model.txt"))
    train = np.loadtxt(os.path.join(EX, "binary.train"))
    ds = lgb.Dataset(train[:, 1:], train[:, 0],
                     params={"max_bin": 255, "verbosity": -1})
    from lightgbm_tpu.config import Config
    ds.construct(Config({"max_bin": 255, "verbosity": -1}))

    thresholds = {}  # feature -> set of numeric thresholds
    for tree in bst._gbdt.models:
        nl = int(tree.num_leaves)
        for i in range(nl - 1):
            f = int(tree.split_feature[i])
            if int(tree.decision_type[i]) & 1:      # categorical
                continue
            thresholds.setdefault(f, set()).add(float(tree.threshold[i]))
    assert thresholds, "no numeric splits in the golden model"
    checked = 0
    for f, ts in thresholds.items():
        ub = np.asarray(ds.bin_mappers[f].bin_upper_bound)
        for t in ts:
            d = np.abs(ub - t)
            rel = d / max(abs(t), 1e-12)
            assert (rel.min() < 1e-10) or (d.min() < 1e-12), \
                f"feature {f} threshold {t} not a bin boundary (ours: " \
                f"{ub[np.argsort(np.abs(ub - t))[:3]]})"
            checked += 1
    assert checked > 50


def test_same_config_training_matches_reference_quality():
    meta = _meta()
    p = dict(meta["binary_params"])
    p.pop("num_trees", None)
    p.pop("force_row_wise", None)
    p.pop("num_threads", None)
    train = np.loadtxt(os.path.join(EX, "binary.train"))
    w = np.loadtxt(os.path.join(EX, "binary.train.weight"))
    test = np.loadtxt(os.path.join(EX, "binary.test"))
    bst = lgb.train(p, lgb.Dataset(train[:, 1:], train[:, 0], weight=w),
                    num_boost_round=20)
    pred = bst.predict(test[:, 1:])
    ll = _logloss(test[:, 0], pred)
    auc = _auc(test[:, 0], pred)
    assert ll < meta["binary_test_logloss"] * 1.03 + 1e-3, \
        (ll, meta["binary_test_logloss"])
    assert auc > meta["binary_test_auc"] - 0.015, \
        (auc, meta["binary_test_auc"])


def test_committed_reverse_fixture_matches():
    """Reverse interchange WITHOUT the binary: the committed model was
    saved by THIS framework and scored by the reference CLI once
    (scripts/make_golden_reverse.py); loading the committed model here
    must reproduce the committed reference predictions — both parsers
    agree on our emitted format."""
    model = os.path.join(GOLD, "golden_ours_model.txt")
    refp = os.path.join(GOLD, "golden_ours_refpreds.txt")
    test = np.loadtxt(os.path.join(EX, "binary.test"))
    bst = lgb.Booster(model_file=model)
    ours = bst.predict(test[:, 1:])
    theirs = np.loadtxt(refp)
    np.testing.assert_allclose(theirs, ours, rtol=1e-5, atol=1e-7)

    # when a reference binary is available (env override or the build
    # recipe's default path), ALSO run the live direction: the CLI loads
    # a freshly-trained model of ours and reproduces its predictions
    ref_bin = REF_BIN or ("/tmp/lgbm_build/lightgbm"
                          if os.path.exists("/tmp/lgbm_build/lightgbm")
                          else "")
    if not ref_bin:
        return
    import subprocess
    import tempfile
    train = np.loadtxt(os.path.join(EX, "binary.train"))
    p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "min_data_in_leaf": 20}
    live = lgb.train(p, lgb.Dataset(train[:, 1:], train[:, 0]),
                     num_boost_round=8)
    with tempfile.TemporaryDirectory() as td:
        mpath = os.path.join(td, "ours.txt")
        live.save_model(mpath)
        opath = os.path.join(td, "preds.txt")
        subprocess.run(
            [ref_bin, "task=predict",
             f"data={os.path.join(EX, 'binary.test')}",
             f"input_model={mpath}", f"output_result={opath}",
             "verbosity=-1", "num_threads=1"], check=True,
            capture_output=True, timeout=300)
        np.testing.assert_allclose(np.loadtxt(opath),
                                   live.predict(test[:, 1:]),
                                   rtol=1e-5, atol=1e-7)
