"""Speculative-ramp tests (learner/wave.py _spec_state).

The spec ramp grows a provisional subtree on a row subsample and commits
only splits verified against full-data channel histograms, so:
  (a) with the subsample == the full data, the grown tree must be
      IDENTICAL to the plain wave grower's (same splits, same numbering);
  (b) with a real (strided) subsample, misses may shrink the committed
      prefix but the result must stay a valid, learning tree — every
      recorded number is full-data exact by construction.
Both growers run the real Pallas kernels in interpret mode on CPU.
"""

import jax.numpy as jnp
import numpy as np

from lightgbm_tpu.learner.wave import make_wave_grow_fn
from lightgbm_tpu.ops.histogram_pallas import pad_rows
from lightgbm_tpu.ops.split import SplitParams


def _mk_data(n_raw=6000, f=6, b=64, seed=0):
    rng = np.random.RandomState(seed)
    n = pad_rows(n_raw)
    bins = rng.randint(0, b - 1, (f, n)).astype(np.uint8)
    # learnable structure over bin codes
    logit = (bins[0].astype(np.float32) / b - 0.5) * 3 + \
        ((bins[1] > 40).astype(np.float32) - 0.5) * 2
    y = (logit + rng.randn(n) * 0.7 > 0).astype(np.float32)
    p0 = 0.5
    grad = (p0 - y).astype(np.float32)
    hess = np.full(n, p0 * (1 - p0), np.float32)
    mask = np.ones(n, np.float32)
    mask[n_raw:] = 0.0
    return (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(mask), y, n)


def _grow(spec, n, f=6, b=64, leaves=13, wave=4, quantized=False,
          spec_subsample=1 << 18):
    sp = SplitParams(min_data_in_leaf=5, min_sum_hessian_in_leaf=0.0,
                     any_cat=False)
    return make_wave_grow_fn(
        num_leaves=leaves, num_features=f, max_bins=b, max_depth=0,
        split_params=sp, hist_impl="pallas", any_cat=False, interpret=True,
        jit=False, wave_size=wave, quantized=quantized, stochastic=False,
        spec_ramp=spec, spec_tol=0.02, spec_subsample=spec_subsample)


def _call(grow, bins, grad, hess, mask, f=6, b=64):
    nb = jnp.full((f,), b, jnp.int32)
    return grow(bins, grad, hess, mask, nb,
                jnp.zeros((f,), bool), jnp.zeros((f,), bool),
                jnp.zeros((f,), jnp.int32), jnp.zeros((f,), jnp.float32),
                (), jnp.ones((f,), bool))


def test_spec_full_subsample_matches_plain_exactly():
    bins, grad, hess, mask, y, n = _mk_data()
    t_plain = _call(_grow(False, n), bins, grad, hess, mask)
    t_spec = _call(_grow(True, n), bins, grad, hess, mask)
    assert int(t_spec.num_leaves) == int(t_plain.num_leaves)
    np.testing.assert_array_equal(np.asarray(t_spec.split_feature),
                                  np.asarray(t_plain.split_feature))
    np.testing.assert_array_equal(np.asarray(t_spec.threshold_bin),
                                  np.asarray(t_plain.threshold_bin))
    np.testing.assert_array_equal(np.asarray(t_spec.row_leaf),
                                  np.asarray(t_plain.row_leaf))
    np.testing.assert_allclose(np.asarray(t_spec.leaf_value),
                               np.asarray(t_plain.leaf_value),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(t_spec.split_gain),
                               np.asarray(t_plain.split_gain),
                               rtol=1e-4, atol=1e-4)


def test_spec_quantized_matches_plain():
    bins, grad, hess, mask, y, n = _mk_data(seed=3)
    t_plain = _call(_grow(False, n, quantized=True), bins, grad, hess, mask)
    t_spec = _call(_grow(True, n, quantized=True), bins, grad, hess, mask)
    assert int(t_spec.num_leaves) == int(t_plain.num_leaves)
    np.testing.assert_array_equal(np.asarray(t_spec.split_feature),
                                  np.asarray(t_plain.split_feature))
    np.testing.assert_array_equal(np.asarray(t_spec.row_leaf),
                                  np.asarray(t_plain.row_leaf))


def test_spec_strided_subsample_valid_tree():
    """Real subsampling (stride 2): commits may miss, but the tree must
    be structurally valid, full-data exact, and actually learn."""
    bins, grad, hess, mask, y, n = _mk_data(seed=7)
    t = _call(_grow(True, n, spec_subsample=4096), bins, grad, hess, mask)
    nl = int(t.num_leaves)
    assert 2 <= nl <= 13
    sf = np.asarray(t.split_feature)
    assert (sf >= 0).sum() == nl - 1
    # leaf counts: every live leaf obeys min_data_in_leaf; counts sum to n
    cnt = np.asarray(t.leaf_count)[:nl]
    assert cnt.min() >= 5
    assert cnt.sum() == float(np.asarray(mask).sum())
    # row_leaf consistent with leaf_count
    rl = np.asarray(t.row_leaf)
    m = np.asarray(mask) > 0
    bc = np.bincount(rl[m], minlength=13)
    np.testing.assert_array_equal(bc[:nl], cnt.astype(np.int64))
    # the pseudo-prediction from leaf values must beat the constant model
    lv = np.asarray(t.leaf_value)
    pred = 1.0 / (1.0 + np.exp(-4.0 * lv[rl]))  # lr-free monotone map
    base = -np.mean(y[m] * np.log(0.5) + (1 - y[m]) * np.log(0.5))
    p = np.clip(pred[m], 1e-6, 1 - 1e-6)
    ll = -np.mean(y[m] * np.log(p) + (1 - y[m]) * np.log(1 - p))
    assert ll < base


# ---------------------------------------------------------------------------
# Data-parallel speculative ramp (WaveDPStrategy.spec_ok): every shard
# strides its LOCAL rows and the provisional passes psum their histogram
# batches, so all shards grow one identical provisional tree verified
# against the full sharded data.  With stride 1 on both sides the serial
# and DP spec paths see identical pooled histograms, so the trees must
# match exactly (quantized: bit-for-bit — integer channel sums psum
# exactly).
# ---------------------------------------------------------------------------


def _mk_grow_dp(strategy, spec, wave=4, leaves=13, quantized=True):
    sp = SplitParams(min_data_in_leaf=5, min_sum_hessian_in_leaf=0.0,
                     any_cat=False)
    return make_wave_grow_fn(
        num_leaves=leaves, num_features=6, max_bins=64, max_depth=0,
        split_params=sp, hist_impl="pallas", any_cat=False, interpret=True,
        jit=False, wave_size=wave, quantized=quantized, stochastic=False,
        spec_ramp=spec, spec_tol=0.02, strategy=strategy)


def _wrap_dp(grow, mesh, ax):
    import jax
    from jax.sharding import PartitionSpec as P
    from lightgbm_tpu.parallel.data_parallel import DataParallelTreeLearner
    from lightgbm_tpu.parallel.mesh import shard_map_compat
    return jax.jit(shard_map_compat(
        lambda X_T, g, h, m, nb, ic, hn, mono, cp, fm: grow(
            X_T, g, h, m, nb, ic, hn, mono, cp, (), fm),
        mesh=mesh,
        in_specs=(P(None, ax), P(ax), P(ax), P(ax), P(), P(), P(), P(),
                  P(), P()),
        out_specs=DataParallelTreeLearner._tree_specs(ax)))


def test_spec_dp_matches_serial_on_mesh():
    """8-way row-sharded spec ramp == serial spec ramp, bit-for-bit on
    the quantized path (stride 1 both sides -> identical pooled
    histograms -> identical provisional trees and commits)."""
    from lightgbm_tpu.parallel.data_parallel import WaveDPStrategy
    from lightgbm_tpu.parallel.mesh import get_mesh
    mesh = get_mesh(8)
    ax = mesh.axis_names[0]
    bins, grad, hess, mask, y, n = _mk_data(n_raw=8 * 4096 - 100)
    assert n == 8 * 4096
    t_serial = _call(_mk_grow_dp(None, True), bins, grad, hess, mask)
    dp = _wrap_dp(_mk_grow_dp(WaveDPStrategy(ax, nshards=8), True),
                  mesh, ax)
    nb = jnp.full((6,), 64, jnp.int32)
    t_dp = dp(bins, grad, hess, mask, nb,
              jnp.zeros((6,), bool), jnp.zeros((6,), bool),
              jnp.zeros((6,), jnp.int32), jnp.zeros((6,), jnp.float32),
              jnp.ones((6,), bool))
    assert int(t_dp.num_leaves) == int(t_serial.num_leaves)
    for name in ("split_feature", "threshold_bin", "left_child",
                 "right_child", "decision_type"):
        np.testing.assert_array_equal(
            np.asarray(getattr(t_dp, name)),
            np.asarray(getattr(t_serial, name)), err_msg=name)
    np.testing.assert_array_equal(np.asarray(t_dp.row_leaf),
                                  np.asarray(t_serial.row_leaf))
    np.testing.assert_allclose(np.asarray(t_dp.leaf_value),
                               np.asarray(t_serial.leaf_value),
                               rtol=0, atol=1e-6)
    assert int(t_dp.hist_passes) == int(t_serial.hist_passes)


def test_spec_dp_one_psum_per_provisional_pass():
    """The DP spec ramp's only extra collectives are ONE histogram psum
    per provisional subsample pass (ceil(log2(W)) of them) — counted on
    the traced program: spec-on minus spec-off psum count == provisional
    passes + the verification mega-pass - the root pass it replaces."""
    import math
    from lightgbm_tpu.parallel.data_parallel import WaveDPStrategy
    from lightgbm_tpu.parallel.mesh import get_mesh
    mesh = get_mesh(8)
    ax = mesh.axis_names[0]
    bins, grad, hess, mask, y, n = _mk_data(n_raw=8 * 4096 - 100)
    nb = jnp.full((6,), 64, jnp.int32)
    args = (bins, grad, hess, mask, nb,
            jnp.zeros((6,), bool), jnp.zeros((6,), bool),
            jnp.zeros((6,), jnp.int32), jnp.zeros((6,), jnp.float32),
            jnp.ones((6,), bool))

    from lightgbm_tpu.analysis import ir

    def count_psums(spec):
        g = _wrap_dp(_mk_grow_dp(WaveDPStrategy(ax, nshards=8), spec),
                     mesh, ax)
        return ir.count_primitive(ir.trace(lambda *a: g(*a), *args), "psum")

    w = 4
    extra = count_psums(True) - count_psums(False)
    # spec-on adds ceil(log2(W)) provisional psums + 1 mega-pass psum and
    # drops the root-pass psum
    assert extra == math.ceil(math.log2(w)), extra
