"""Native LGBM_* ABI shim tests (native/capi_shim.cc).

The shim exports real C symbols with the reference's out-pointer
calling convention (include/LightGBM/c_api.h); here it is dlopen'd via
ctypes and driven exactly the way reference ctypes bindings drive the
real liblightgbm — raw double* matrices in, handles and result buffers
out.  Inside this test process the shim reuses the already-running
interpreter through PyGILState."""

import ctypes

import numpy as np
import pytest

from lightgbm_tpu.utils.native import build_capi_shim

_SHIM = build_capi_shim()

pytestmark = pytest.mark.skipif(
    _SHIM is None, reason="native toolchain/python headers unavailable")


def _load():
    lib = ctypes.CDLL(_SHIM)
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    lib.LGBM_DatasetCreateFromMat.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_void_p)]
    lib.LGBM_DatasetSetField.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int,
        ctypes.c_int]
    lib.LGBM_BoosterCreate.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p)]
    lib.LGBM_BoosterUpdateOneIter.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int)]
    lib.LGBM_BoosterPredictForMat.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double)]
    lib.LGBM_BoosterSaveModel.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p]
    lib.LGBM_BoosterCreateFromModelfile.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_void_p)]
    return lib


def test_native_abi_train_predict_roundtrip(tmp_path):
    lib = _load()
    rng = np.random.RandomState(4)
    X = np.ascontiguousarray(rng.randn(300, 4))
    y = np.ascontiguousarray(
        (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32))

    dh = ctypes.c_void_p()
    code = lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), 1, 300, 4, 1,
        b"objective=binary verbosity=-1 min_data_in_leaf=5", None,
        ctypes.byref(dh))
    assert code == 0, lib.LGBM_GetLastError()
    code = lib.LGBM_DatasetSetField(
        dh, b"label", y.ctypes.data_as(ctypes.c_void_p), 300, 0)
    assert code == 0, lib.LGBM_GetLastError()

    bh = ctypes.c_void_p()
    code = lib.LGBM_BoosterCreate(
        dh, b"objective=binary num_leaves=7 verbosity=-1 "
            b"min_data_in_leaf=5", ctypes.byref(bh))
    assert code == 0, lib.LGBM_GetLastError()
    fin = ctypes.c_int(0)
    for _ in range(5):
        assert lib.LGBM_BoosterUpdateOneIter(bh, ctypes.byref(fin)) == 0

    out = np.zeros(300, np.float64)
    out_len = ctypes.c_int64(0)
    code = lib.LGBM_BoosterPredictForMat(
        bh, X.ctypes.data_as(ctypes.c_void_p), 1, 300, 4, 1, 0, 0, -1,
        b"", ctypes.byref(out_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert code == 0, lib.LGBM_GetLastError()
    assert out_len.value == 300
    assert np.isfinite(out).all() and 0 < out.mean() < 1
    # the model learned something
    auc_ord = np.argsort(out)
    assert y[auc_ord[-50:]].mean() > y[auc_ord[:50]].mean()

    # model file round trip through the ABI, checked against python API
    model = str(tmp_path / "native_model.txt").encode()
    assert lib.LGBM_BoosterSaveModel(bh, 0, -1, 0, model) == 0
    it = ctypes.c_int(0)
    bh2 = ctypes.c_void_p()
    assert lib.LGBM_BoosterCreateFromModelfile(
        model, ctypes.byref(it), ctypes.byref(bh2)) == 0
    assert it.value == 5
    out2 = np.zeros(300, np.float64)
    assert lib.LGBM_BoosterPredictForMat(
        bh2, X.ctypes.data_as(ctypes.c_void_p), 1, 300, 4, 1, 0, 0, -1,
        b"", ctypes.byref(out_len),
        out2.ctypes.data_as(ctypes.POINTER(ctypes.c_double))) == 0
    np.testing.assert_allclose(out2, out, rtol=1e-9)

    import lightgbm_tpu as lgb
    py_pred = lgb.Booster(model_file=model.decode()).predict(X)
    np.testing.assert_allclose(out, py_pred, rtol=1e-7, atol=1e-9)

    # float32 column-major input path
    X32 = np.asfortranarray(X.astype(np.float32))
    out3 = np.zeros(300, np.float64)
    assert lib.LGBM_BoosterPredictForMat(
        bh, X32.ctypes.data_as(ctypes.c_void_p), 0, 300, 4, 0, 0, 0, -1,
        b"", ctypes.byref(out_len),
        out3.ctypes.data_as(ctypes.POINTER(ctypes.c_double))) == 0
    np.testing.assert_allclose(out3, out, rtol=1e-5, atol=1e-6)

    # error contract through the ABI
    bad = ctypes.c_void_p()
    code = lib.LGBM_BoosterCreate(ctypes.c_void_p(99999), b"",
                                  ctypes.byref(bad))
    assert code == -1
    assert b"handle" in lib.LGBM_GetLastError()

    assert lib.LGBM_BoosterFree(bh) == 0
    assert lib.LGBM_BoosterFree(bh2) == 0
    assert lib.LGBM_DatasetFree(dh) == 0


_ALL_C_SYMBOLS = [
    # the complete extern-C surface of reference include/LightGBM/c_api.h
    "LGBM_BoosterAddValidData", "LGBM_BoosterCalcNumPredict",
    "LGBM_BoosterCreate", "LGBM_BoosterCreateFromModelfile",
    "LGBM_BoosterDumpModel", "LGBM_BoosterFeatureImportance",
    "LGBM_BoosterFree", "LGBM_BoosterFreePredictSparse",
    "LGBM_BoosterGetCurrentIteration", "LGBM_BoosterGetEval",
    "LGBM_BoosterGetEvalCounts", "LGBM_BoosterGetEvalNames",
    "LGBM_BoosterGetFeatureNames", "LGBM_BoosterGetLeafValue",
    "LGBM_BoosterGetLinear", "LGBM_BoosterGetLowerBoundValue",
    "LGBM_BoosterGetNumClasses", "LGBM_BoosterGetNumFeature",
    "LGBM_BoosterGetNumPredict", "LGBM_BoosterGetPredict",
    "LGBM_BoosterGetUpperBoundValue", "LGBM_BoosterLoadModelFromString",
    "LGBM_BoosterMerge", "LGBM_BoosterNumModelPerIteration",
    "LGBM_BoosterNumberOfTotalModel", "LGBM_BoosterPredictForCSC",
    "LGBM_BoosterPredictForCSR", "LGBM_BoosterPredictForCSRSingleRow",
    "LGBM_BoosterPredictForCSRSingleRowFast",
    "LGBM_BoosterPredictForCSRSingleRowFastInit",
    "LGBM_BoosterPredictForFile", "LGBM_BoosterPredictForMat",
    "LGBM_BoosterPredictForMatSingleRow",
    "LGBM_BoosterPredictForMatSingleRowFast",
    "LGBM_BoosterPredictForMatSingleRowFastInit",
    "LGBM_BoosterPredictForMats", "LGBM_BoosterPredictSparseOutput",
    "LGBM_BoosterRefit", "LGBM_BoosterResetParameter",
    "LGBM_BoosterResetTrainingData", "LGBM_BoosterRollbackOneIter",
    "LGBM_BoosterSaveModel", "LGBM_BoosterSaveModelToString",
    "LGBM_BoosterSetLeafValue", "LGBM_BoosterShuffleModels",
    "LGBM_BoosterUpdateOneIter", "LGBM_BoosterUpdateOneIterCustom",
    "LGBM_DatasetAddFeaturesFrom", "LGBM_DatasetCreateByReference",
    "LGBM_DatasetCreateFromCSC", "LGBM_DatasetCreateFromCSR",
    "LGBM_DatasetCreateFromCSRFunc", "LGBM_DatasetCreateFromFile",
    "LGBM_DatasetCreateFromMat", "LGBM_DatasetCreateFromMats",
    "LGBM_DatasetCreateFromSampledColumn", "LGBM_DatasetDumpText",
    "LGBM_DatasetFree", "LGBM_DatasetGetFeatureNames",
    "LGBM_DatasetGetField", "LGBM_DatasetGetNumData",
    "LGBM_DatasetGetNumFeature", "LGBM_DatasetGetSubset",
    "LGBM_DatasetPushRows", "LGBM_DatasetPushRowsByCSR",
    "LGBM_DatasetSaveBinary", "LGBM_DatasetSetFeatureNames",
    "LGBM_DatasetSetField", "LGBM_DatasetUpdateParamChecking",
    "LGBM_FastConfigFree", "LGBM_NetworkFree", "LGBM_NetworkInit",
    "LGBM_NetworkInitWithFunctions", "LGBM_RegisterLogCallback",
    "LGBM_GetLastError",
]


def test_all_c_api_symbols_resolve():
    """Every c_api.h symbol must dlsym from the shim — a real C/R/Java
    client never hits an unresolved symbol."""
    lib = ctypes.CDLL(_SHIM)
    missing = []
    for name in _ALL_C_SYMBOLS:
        try:
            getattr(lib, name)
        except AttributeError:
            missing.append(name)
    assert not missing, f"unresolved: {missing}"


def test_reference_style_csr_fast_and_strings(tmp_path):
    """Reference tests/c_api_test/test_.py style drive: CSR dataset,
    training, GetEvalNames (char** convention), SaveModelToString,
    fast single-row init/predict, leaf get/set, bounds."""
    import scipy.sparse as sp
    lib = ctypes.CDLL(_SHIM)
    lib.LGBM_GetLastError.restype = ctypes.c_char_p

    rng = np.random.RandomState(5)
    X = np.ascontiguousarray(rng.randn(400, 5))
    X[X < -1.2] = 0.0
    y = np.ascontiguousarray(
        (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32))
    csr = sp.csr_matrix(X)
    indptr = np.ascontiguousarray(csr.indptr.astype(np.int32))
    indices = np.ascontiguousarray(csr.indices.astype(np.int32))
    data = np.ascontiguousarray(csr.data.astype(np.float64))

    dh = ctypes.c_void_p()
    rc = lib.LGBM_DatasetCreateFromCSR(
        indptr.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(0),
        indices.ctypes.data_as(ctypes.c_void_p),
        data.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(1),
        ctypes.c_int64(len(indptr)), ctypes.c_int64(csr.nnz),
        ctypes.c_int64(5),
        b"objective=binary verbosity=-1 min_data_in_bin=1", None,
        ctypes.byref(dh))
    assert rc == 0, lib.LGBM_GetLastError()
    rc = lib.LGBM_DatasetSetField(
        dh, b"label", y.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int(400), ctypes.c_int(0))
    assert rc == 0, lib.LGBM_GetLastError()

    bh = ctypes.c_void_p()
    rc = lib.LGBM_BoosterCreate(
        dh, b"objective=binary num_leaves=15 verbosity=-1 "
            b"metric=binary_logloss,auc", ctypes.byref(bh))
    assert rc == 0, lib.LGBM_GetLastError()
    fin = ctypes.c_int(0)
    for _ in range(8):
        assert lib.LGBM_BoosterUpdateOneIter(bh, ctypes.byref(fin)) == 0

    # GetEvalNames through the (len, buffer_len, char**) convention
    n_metrics = ctypes.c_int(0)
    assert lib.LGBM_BoosterGetEvalCounts(bh, ctypes.byref(n_metrics)) == 0
    assert n_metrics.value == 2
    bufs = [(ctypes.c_char * 64)() for _ in range(n_metrics.value)]
    arr = (ctypes.c_char_p * n_metrics.value)(
        *[ctypes.addressof(b) for b in bufs])
    out_n = ctypes.c_int(0)
    out_buf = ctypes.c_size_t(0)
    rc = lib.LGBM_BoosterGetEvalNames(
        bh, ctypes.c_int(n_metrics.value), ctypes.byref(out_n),
        ctypes.c_size_t(64), ctypes.byref(out_buf), arr)
    assert rc == 0, lib.LGBM_GetLastError()
    names = {bufs[i].value.decode() for i in range(out_n.value)}
    assert names == {"binary_logloss", "auc"}

    # eval values land in a double buffer
    evals = np.zeros(2, np.float64)
    out_len = ctypes.c_int(0)
    rc = lib.LGBM_BoosterGetEval(
        bh, ctypes.c_int(0), ctypes.byref(out_len),
        evals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0 and out_len.value == 2

    # model string round trip
    out_sz = ctypes.c_int64(0)
    rc = lib.LGBM_BoosterSaveModelToString(
        bh, 0, -1, 0, ctypes.c_int64(0), ctypes.byref(out_sz), None)
    assert rc == 0 and out_sz.value > 100
    buf = ctypes.create_string_buffer(out_sz.value)
    rc = lib.LGBM_BoosterSaveModelToString(
        bh, 0, -1, 0, ctypes.c_int64(out_sz.value), ctypes.byref(out_sz),
        buf)
    assert rc == 0 and b"tree" in buf.value
    bh2 = ctypes.c_void_p()
    it2 = ctypes.c_int(0)
    assert lib.LGBM_BoosterLoadModelFromString(
        buf.value, ctypes.byref(it2), ctypes.byref(bh2)) == 0
    assert it2.value == 8

    # dense predict == CSR predict
    pred = np.zeros(400, np.float64)
    plen = ctypes.c_int64(0)
    Xc = np.ascontiguousarray(X, np.float64)
    assert lib.LGBM_BoosterPredictForMat(
        bh, Xc.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(1),
        ctypes.c_int32(400), ctypes.c_int32(5), ctypes.c_int(1),
        ctypes.c_int(0), ctypes.c_int(0), ctypes.c_int(-1), b"",
        ctypes.byref(plen),
        pred.ctypes.data_as(ctypes.POINTER(ctypes.c_double))) == 0
    pred_csr = np.zeros(400, np.float64)
    assert lib.LGBM_BoosterPredictForCSR(
        bh, indptr.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(0),
        indices.ctypes.data_as(ctypes.c_void_p),
        data.ctypes.data_as(ctypes.c_void_p), ctypes.c_int(1),
        ctypes.c_int64(len(indptr)), ctypes.c_int64(csr.nnz),
        ctypes.c_int64(5), ctypes.c_int(0), ctypes.c_int(0),
        ctypes.c_int(-1), b"", ctypes.byref(plen),
        pred_csr.ctypes.data_as(ctypes.POINTER(ctypes.c_double))) == 0
    np.testing.assert_allclose(pred_csr, pred, rtol=1e-6)

    # fast single-row
    fc = ctypes.c_void_p()
    assert lib.LGBM_BoosterPredictForMatSingleRowFastInit(
        bh, ctypes.c_int(0), ctypes.c_int(0), ctypes.c_int(-1),
        ctypes.c_int(1), ctypes.c_int32(5), b"", ctypes.byref(fc)) == 0
    row = np.ascontiguousarray(X[7], np.float64)
    one = np.zeros(1, np.float64)
    assert lib.LGBM_BoosterPredictForMatSingleRowFast(
        fc, row.ctypes.data_as(ctypes.c_void_p), ctypes.byref(plen),
        one.ctypes.data_as(ctypes.POINTER(ctypes.c_double))) == 0
    assert one[0] == pytest.approx(pred[7], rel=1e-6)
    assert lib.LGBM_FastConfigFree(fc) == 0

    # leaf get/set + bounds
    lv = ctypes.c_double(0.0)
    assert lib.LGBM_BoosterGetLeafValue(
        bh, 0, 0, ctypes.byref(lv)) == 0
    assert lib.LGBM_BoosterSetLeafValue(
        bh, 0, 0, ctypes.c_double(lv.value)) == 0
    lo = ctypes.c_double(0.0)
    hi = ctypes.c_double(0.0)
    assert lib.LGBM_BoosterGetLowerBoundValue(bh, ctypes.byref(lo)) == 0
    assert lib.LGBM_BoosterGetUpperBoundValue(bh, ctypes.byref(hi)) == 0
    assert lo.value < hi.value

    lib.LGBM_BoosterFree(bh)
    lib.LGBM_BoosterFree(bh2)
    lib.LGBM_DatasetFree(dh)
