"""Native LGBM_* ABI shim tests (native/capi_shim.cc).

The shim exports real C symbols with the reference's out-pointer
calling convention (include/LightGBM/c_api.h); here it is dlopen'd via
ctypes and driven exactly the way reference ctypes bindings drive the
real liblightgbm — raw double* matrices in, handles and result buffers
out.  Inside this test process the shim reuses the already-running
interpreter through PyGILState."""

import ctypes
import os

import numpy as np
import pytest

from lightgbm_tpu.utils.native import build_capi_shim

_SHIM = build_capi_shim()

pytestmark = pytest.mark.skipif(
    _SHIM is None, reason="native toolchain/python headers unavailable")


def _load():
    lib = ctypes.CDLL(_SHIM)
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    lib.LGBM_DatasetCreateFromMat.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_void_p)]
    lib.LGBM_DatasetSetField.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int,
        ctypes.c_int]
    lib.LGBM_BoosterCreate.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p)]
    lib.LGBM_BoosterUpdateOneIter.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int)]
    lib.LGBM_BoosterPredictForMat.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double)]
    lib.LGBM_BoosterSaveModel.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_char_p]
    lib.LGBM_BoosterCreateFromModelfile.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_void_p)]
    return lib


def test_native_abi_train_predict_roundtrip(tmp_path):
    lib = _load()
    rng = np.random.RandomState(4)
    X = np.ascontiguousarray(rng.randn(300, 4))
    y = np.ascontiguousarray(
        (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32))

    dh = ctypes.c_void_p()
    code = lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), 1, 300, 4, 1,
        b"objective=binary verbosity=-1 min_data_in_leaf=5", None,
        ctypes.byref(dh))
    assert code == 0, lib.LGBM_GetLastError()
    code = lib.LGBM_DatasetSetField(
        dh, b"label", y.ctypes.data_as(ctypes.c_void_p), 300, 0)
    assert code == 0, lib.LGBM_GetLastError()

    bh = ctypes.c_void_p()
    code = lib.LGBM_BoosterCreate(
        dh, b"objective=binary num_leaves=7 verbosity=-1 "
            b"min_data_in_leaf=5", ctypes.byref(bh))
    assert code == 0, lib.LGBM_GetLastError()
    fin = ctypes.c_int(0)
    for _ in range(5):
        assert lib.LGBM_BoosterUpdateOneIter(bh, ctypes.byref(fin)) == 0

    out = np.zeros(300, np.float64)
    out_len = ctypes.c_int64(0)
    code = lib.LGBM_BoosterPredictForMat(
        bh, X.ctypes.data_as(ctypes.c_void_p), 1, 300, 4, 1, 0, 0, -1,
        b"", ctypes.byref(out_len),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert code == 0, lib.LGBM_GetLastError()
    assert out_len.value == 300
    assert np.isfinite(out).all() and 0 < out.mean() < 1
    # the model learned something
    auc_ord = np.argsort(out)
    assert y[auc_ord[-50:]].mean() > y[auc_ord[:50]].mean()

    # model file round trip through the ABI, checked against python API
    model = str(tmp_path / "native_model.txt").encode()
    assert lib.LGBM_BoosterSaveModel(bh, 0, -1, 0, model) == 0
    it = ctypes.c_int(0)
    bh2 = ctypes.c_void_p()
    assert lib.LGBM_BoosterCreateFromModelfile(
        model, ctypes.byref(it), ctypes.byref(bh2)) == 0
    assert it.value == 5
    out2 = np.zeros(300, np.float64)
    assert lib.LGBM_BoosterPredictForMat(
        bh2, X.ctypes.data_as(ctypes.c_void_p), 1, 300, 4, 1, 0, 0, -1,
        b"", ctypes.byref(out_len),
        out2.ctypes.data_as(ctypes.POINTER(ctypes.c_double))) == 0
    np.testing.assert_allclose(out2, out, rtol=1e-9)

    import lightgbm_tpu as lgb
    py_pred = lgb.Booster(model_file=model.decode()).predict(X)
    np.testing.assert_allclose(out, py_pred, rtol=1e-7, atol=1e-9)

    # float32 column-major input path
    X32 = np.asfortranarray(X.astype(np.float32))
    out3 = np.zeros(300, np.float64)
    assert lib.LGBM_BoosterPredictForMat(
        bh, X32.ctypes.data_as(ctypes.c_void_p), 0, 300, 4, 0, 0, 0, -1,
        b"", ctypes.byref(out_len),
        out3.ctypes.data_as(ctypes.POINTER(ctypes.c_double))) == 0
    np.testing.assert_allclose(out3, out, rtol=1e-5, atol=1e-6)

    # error contract through the ABI
    bad = ctypes.c_void_p()
    code = lib.LGBM_BoosterCreate(ctypes.c_void_p(99999), b"",
                                  ctypes.byref(bad))
    assert code == -1
    assert b"handle" in lib.LGBM_GetLastError()

    assert lib.LGBM_BoosterFree(bh) == 0
    assert lib.LGBM_BoosterFree(bh2) == 0
    assert lib.LGBM_DatasetFree(dh) == 0
