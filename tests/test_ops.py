"""Histogram + split-finding op tests against numpy references
(the kernels replacing dense_bin.hpp ConstructHistogram and
feature_histogram.hpp FindBestThresholdSequentially)."""

import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.histogram import build_histogram, histogram_subtract
from lightgbm_tpu.ops.split import (NEG_INF, SplitParams,
                                    best_split_per_feature, leaf_output)


def _np_histogram(bins, grad, hess, mask, B):
    n, f = bins.shape
    out = np.zeros((f, B, 3))
    for i in range(n):
        if mask[i] == 0:
            continue
        for j in range(f):
            b = bins[i, j]
            out[j, b, 0] += grad[i]
            out[j, b, 1] += hess[i]
            out[j, b, 2] += 1.0
    return out


@pytest.mark.parametrize("impl", ["segment", "onehot"])
def test_histogram_matches_numpy(impl):
    rng = np.random.RandomState(0)
    n, f, B = 500, 4, 16
    bins = rng.randint(0, B, (n, f)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = rng.rand(n).astype(np.float32)
    mask = (rng.rand(n) > 0.3).astype(np.float32)
    got = np.asarray(build_histogram(jnp.asarray(bins), jnp.asarray(grad),
                                     jnp.asarray(hess), jnp.asarray(mask),
                                     num_bins=B, impl=impl))
    want = _np_histogram(bins, grad, hess, mask, B)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("impl", ["segment", "onehot"])
def test_histogram_chunked(impl):
    rng = np.random.RandomState(1)
    n, f, B = 1000, 3, 8
    bins = rng.randint(0, B, (n, f)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = np.ones(n, np.float32)
    mask = np.ones(n, np.float32)
    full = np.asarray(build_histogram(jnp.asarray(bins), jnp.asarray(grad),
                                      jnp.asarray(hess), jnp.asarray(mask),
                                      num_bins=B, impl=impl))
    chunked = np.asarray(build_histogram(jnp.asarray(bins), jnp.asarray(grad),
                                         jnp.asarray(hess), jnp.asarray(mask),
                                         num_bins=B, impl=impl,
                                         rows_per_chunk=96))
    np.testing.assert_allclose(full, chunked, rtol=1e-4, atol=1e-4)


def test_histogram_subtraction():
    rng = np.random.RandomState(2)
    n, f, B = 300, 3, 8
    bins = rng.randint(0, B, (n, f)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = np.ones(n, np.float32)
    left = (rng.rand(n) > 0.5).astype(np.float32)
    all_mask = np.ones(n, np.float32)
    h_all = build_histogram(jnp.asarray(bins), jnp.asarray(grad),
                            jnp.asarray(hess), jnp.asarray(all_mask), num_bins=B)
    h_left = build_histogram(jnp.asarray(bins), jnp.asarray(grad),
                             jnp.asarray(hess), jnp.asarray(left), num_bins=B)
    h_right = build_histogram(jnp.asarray(bins), jnp.asarray(grad),
                              jnp.asarray(hess), jnp.asarray(1 - left),
                              num_bins=B)
    np.testing.assert_allclose(np.asarray(histogram_subtract(h_all, h_left)),
                               np.asarray(h_right), rtol=1e-3, atol=1e-3)


def _np_best_split(hist, parent, l1, l2, min_cnt, min_hess):
    """Brute-force split scan for one numerical feature, missing->right."""
    def thr_l1(g):
        return np.sign(g) * max(abs(g) - l1, 0.0)

    def gain(g, h):
        return thr_l1(g) ** 2 / (h + l2) if h + l2 > 0 else 0.0

    B = hist.shape[0]
    pg = gain(parent[0], parent[1])
    best = (-np.inf, -1)
    for b in range(B - 1):
        gl = hist[: b + 1, 0].sum()
        hl = hist[: b + 1, 1].sum()
        cl = hist[: b + 1, 2].sum()
        gr, hr, cr = parent[0] - gl, parent[1] - hl, parent[2] - cl
        if cl < min_cnt or cr < min_cnt or hl < min_hess or hr < min_hess:
            continue
        g = gain(gl, hl) + gain(gr, hr) - pg
        if g > best[0]:
            best = (g, b)
    return best


def test_split_matches_bruteforce():
    rng = np.random.RandomState(3)
    B, F = 12, 3
    hist = rng.randn(F, B, 3).astype(np.float32)
    hist[..., 1] = np.abs(hist[..., 1]) + 0.1   # positive hessians
    hist[..., 2] = rng.randint(5, 50, (F, B))   # counts
    parent = hist.sum(axis=1)[0]  # use feature 0's totals for all (same data)
    hist = np.broadcast_to(hist[0], (F, B, 3)).copy()
    params = SplitParams(lambda_l1=0.1, lambda_l2=0.5, min_data_in_leaf=10,
                         min_sum_hessian_in_leaf=1e-3, min_gain_to_split=0.0)
    fs = best_split_per_feature(
        jnp.asarray(hist), jnp.asarray(parent),
        jnp.full(F, B, jnp.int32), jnp.zeros(F, jnp.bool_),
        jnp.zeros(F, jnp.bool_), params)
    want_gain, want_bin = _np_best_split(hist[0], parent, 0.1, 0.5, 10, 1e-3)
    np.testing.assert_allclose(float(fs.gain[0]), want_gain, rtol=1e-4)
    assert int(fs.threshold_bin[0]) == want_bin


def test_split_min_data_constraint():
    B = 8
    hist = np.zeros((1, B, 3), np.float32)
    hist[0, :, 0] = np.linspace(-1, 1, B)
    hist[0, :, 1] = 1.0
    hist[0, :, 2] = 3.0  # 3 per bin, 24 total
    parent = hist[0].sum(axis=0)
    params = SplitParams(min_data_in_leaf=20, min_sum_hessian_in_leaf=0.0)
    fs = best_split_per_feature(
        jnp.asarray(hist), jnp.asarray(parent), jnp.asarray([B], jnp.int32),
        jnp.zeros(1, jnp.bool_), jnp.zeros(1, jnp.bool_), params)
    # no split leaves >=20 on both sides of 24 rows
    assert float(fs.gain[0]) <= NEG_INF / 2


def test_split_nan_direction():
    B = 8
    # feature with NaN bin at index B-1 holding strong negative gradients
    hist = np.zeros((1, B, 3), np.float32)
    hist[0, :4, 0] = 1.0
    hist[0, 4:7, 0] = -1.0
    hist[0, B - 1, 0] = -5.0
    hist[0, :, 1] = 1.0
    hist[0, :, 2] = 10.0
    parent = hist[0].sum(axis=0)
    params = SplitParams(min_data_in_leaf=1, min_sum_hessian_in_leaf=0.0)
    fs = best_split_per_feature(
        jnp.asarray(hist), jnp.asarray(parent), jnp.asarray([B], jnp.int32),
        jnp.zeros(1, jnp.bool_), jnp.asarray([True]), params)
    assert float(fs.gain[0]) > 0
    # NaN joins the negative side: either missing-right with negatives right,
    # or missing-left grouping NaN with negatives; sums must be consistent
    total = parent
    ls = np.asarray(fs.left_sum[0])
    rs = np.asarray(fs.right_sum[0])
    np.testing.assert_allclose(ls + rs, total, rtol=1e-5)


def test_categorical_split():
    B = 6
    # category 2 is strongly negative -> best one-vs-rest split
    hist = np.zeros((1, B, 3), np.float32)
    hist[0, :, 0] = np.array([0.5, 0.2, -4.0, 0.1, 0.3, 0.0])
    hist[0, :, 1] = 1.0
    hist[0, :, 2] = 20.0
    parent = hist[0].sum(axis=0)
    params = SplitParams(min_data_in_leaf=1, min_sum_hessian_in_leaf=0.0,
                         cat_l2=0.0)
    fs = best_split_per_feature(
        jnp.asarray(hist), jnp.asarray(parent), jnp.asarray([B], jnp.int32),
        jnp.asarray([True]), jnp.zeros(1, jnp.bool_), params)
    assert int(fs.threshold_bin[0]) == 2
    assert float(fs.gain[0]) > 0


def test_leaf_output():
    params = SplitParams(lambda_l1=0.0, lambda_l2=1.0)
    out = leaf_output(jnp.asarray(4.0), jnp.asarray(3.0), params)
    np.testing.assert_allclose(float(out), -1.0)
    params2 = SplitParams(lambda_l1=1.0, lambda_l2=0.0, max_delta_step=0.5)
    out2 = leaf_output(jnp.asarray(4.0), jnp.asarray(3.0), params2)
    np.testing.assert_allclose(float(out2), -0.5)  # clipped


def test_hist_impl_autotune_times_both(monkeypatch):
    """ShareStates-style one-shot timing on real shapes
    (learner/autotune.py; dataset.cpp:659-670 analog)."""
    import numpy as np
    monkeypatch.setenv("LGBM_TPU_AUTOTUNE_CACHE", "")  # no disk writes
    from lightgbm_tpu.learner.autotune import _CACHE, pick_hist_impl
    from lightgbm_tpu.utils.backend import default_backend
    rng = np.random.RandomState(0)
    X = rng.randint(0, 63, (2000, 5)).astype(np.uint8)
    win = pick_hist_impl(X, 63, candidates=("onehot", "segment"))
    assert win in ("onehot", "segment")
    assert (default_backend(), 2000, 5, 63,
            ("onehot", "segment")) in _CACHE
    # cached second call returns instantly with the same answer
    assert pick_hist_impl(X, 63, candidates=("onehot", "segment")) == win
