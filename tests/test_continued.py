"""Continued training / refit / snapshot tests (reference patterns:
test_engine.py:606 test_continue_train*, refit tests, snapshot_freq)."""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def reg_data():
    rng = np.random.RandomState(42)
    X = rng.randn(500, 6)
    y = X[:, 0] * 2 + np.sin(X[:, 1] * 3) + 0.1 * rng.randn(500)
    return X, y


PARAMS = {"objective": "regression", "num_leaves": 15, "metric": "l2",
          "verbosity": -1}


def test_init_model_roundtrip(reg_data, tmp_path):
    X, y = reg_data
    full = lgb.train(PARAMS, lgb.Dataset(X, y), 60)
    mse_full = np.mean((full.predict(X) - y) ** 2)

    first = lgb.train(PARAMS, lgb.Dataset(X, y), 30)
    mse_half = np.mean((first.predict(X) - y) ** 2)
    path = str(tmp_path / "half.txt")
    first.save_model(path)
    cont = lgb.train(PARAMS, lgb.Dataset(X, y), 30, init_model=path)
    assert cont.num_trees() == 60
    mse_cont = np.mean((cont.predict(X) - y) ** 2)
    # train30+save+load+train30 reaches the same quality as train60 (exact
    # prediction equality is not guaranteed: f32 score-rebuild rounding can
    # flip individual split choices; the reference's continue-train tests
    # assert metric quality the same way, test_engine.py:606)
    assert mse_cont < mse_half
    assert abs(mse_cont - mse_full) < 0.3 * mse_full + 1e-4
    # the first 30 trees of the continued model are exactly the saved ones
    for t_old, t_new in zip(first._gbdt.models, cont._gbdt.models[:30]):
        np.testing.assert_allclose(
            t_old.threshold[:t_old.num_leaves - 1],
            t_new.threshold[:t_new.num_leaves - 1])
        np.testing.assert_allclose(t_old.leaf_value[:t_old.num_leaves],
                                   t_new.leaf_value[:t_new.num_leaves])


def test_init_model_booster_object(reg_data):
    X, y = reg_data
    first = lgb.train(PARAMS, lgb.Dataset(X, y), 20)
    cont = lgb.train(PARAMS, lgb.Dataset(X, y), 10, init_model=first)
    assert cont.num_trees() == 30
    # training continued (loss decreased vs the 20-tree model)
    mse_first = np.mean((first.predict(X) - y) ** 2)
    mse_cont = np.mean((cont.predict(X) - y) ** 2)
    assert mse_cont < mse_first


def test_init_model_with_valid(reg_data):
    X, y = reg_data
    ds = lgb.Dataset(X, y)
    first = lgb.train(PARAMS, ds, 15)
    evals = {}
    lgb.train(PARAMS, lgb.Dataset(X, y), 10, init_model=first,
              valid_sets=[lgb.Dataset(X, y)],
              callbacks=[lgb.record_evaluation(evals)])
    l2 = evals["valid_0"]["l2"]
    # validation scores must include the loaded trees: first recorded value
    # already reflects 15+1 trees, so it is far better than a fresh model's
    fresh = lgb.train(PARAMS, lgb.Dataset(X, y), 1)
    mse_fresh = np.mean((fresh.predict(X) - y) ** 2)
    assert l2[0] < mse_fresh * 0.9


def test_refit_keeps_structure_changes_leaves(reg_data):
    X, y = reg_data
    bst = lgb.train(PARAMS, lgb.Dataset(X, y), 10)
    rng = np.random.RandomState(1)
    y2 = y + 1.0 + 0.05 * rng.randn(len(y))
    new_bst = bst.refit(X, y2, decay_rate=0.5)
    assert new_bst.num_trees() == bst.num_trees()
    for t_old, t_new in zip(bst._gbdt.models, new_bst._gbdt.models):
        assert t_old.num_leaves == t_new.num_leaves
        np.testing.assert_array_equal(
            t_old.threshold[:t_old.num_leaves - 1],
            t_new.threshold[:t_new.num_leaves - 1])
    # leaf values moved toward the shifted labels
    assert not np.allclose(new_bst.predict(X), bst.predict(X))
    assert np.mean(new_bst.predict(X)) > np.mean(bst.predict(X)) + 0.2


def test_refit_decay_one_is_identity(reg_data):
    X, y = reg_data
    bst = lgb.train(PARAMS, lgb.Dataset(X, y), 5)
    same = bst.refit(X, y + 5.0, decay_rate=1.0)
    np.testing.assert_allclose(same.predict(X), bst.predict(X), rtol=1e-6)


def test_snapshot_freq(reg_data, tmp_path):
    X, y = reg_data
    out = str(tmp_path / "model.txt")
    lgb.train({**PARAMS, "snapshot_freq": 4, "output_model": out},
              lgb.Dataset(X, y), 10)
    snaps = sorted(f for f in os.listdir(tmp_path) if "snapshot" in f)
    assert snaps == ["model.txt.snapshot_iter_4", "model.txt.snapshot_iter_8"]
    snap = lgb.Booster(model_file=str(tmp_path / snaps[0]))
    assert snap.num_trees() == 4


def test_rollback_one_iter(reg_data):
    X, y = reg_data
    bst = lgb.train(PARAMS, lgb.Dataset(X, y), 10)
    p10 = bst.predict(X)
    bst.rollback_one_iter()
    assert bst.num_trees() == 9
    assert not np.allclose(bst.predict(X), p10)
