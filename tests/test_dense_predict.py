"""Inference-compiler tests (serve/compiler.py + models/dense_predict.py):
bitwise/tolerance parity of the fused dense program against the
sequential walk across categorical (incl. multi-word bitsets),
NaN/missing, multiclass, linear leaves, pred-leaf routing and bucket
boundary shapes; jaxpr structure assertions (zero while loops, exactly
one psum sharded); fallback telemetry; quantized-leaf tolerance; the
serve_dense lint config."""

import numpy as np
import pytest

import lightgbm_tpu as lgb

SMALL = {"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1}


def _cat_model(num_leaves=7, trees=10, max_cat=70):
    """Binary model splitting on a categorical with values up to
    ``max_cat`` — past 32 the bitsets span MULTIPLE uint32 words."""
    rng = np.random.RandomState(5)
    n = 600
    X = rng.randn(n, 6)
    X[:, 3] = rng.randint(0, max_cat, n)
    y = ((X[:, 3] % 3 == 0) * 2.0 + 0.3 * X[:, 0] +
         0.3 * rng.randn(n) > 1.0).astype(np.float64)
    p = {**SMALL, "objective": "binary", "num_leaves": num_leaves}
    ds = lgb.Dataset(X, y, categorical_feature=[3], params=p)
    return lgb.train(p, ds, trees)


def _cat_queries(n, max_cat=80, nan_rows=True):
    rng = np.random.RandomState(11)
    Xq = rng.randn(n, 6)
    Xq[:, 3] = rng.randint(0, max_cat, n)  # incl. unseen categories
    if nan_rows and n >= 4:
        Xq[1, 3] = np.nan       # NaN categorical -> default direction
        Xq[2, 0] = np.nan       # NaN numeric
        Xq[3, 3] = 3.5          # non-integer category -> not a member
    return Xq


@pytest.fixture(scope="module")
def cat_booster():
    return _cat_model()


# -- parity matrix ----------------------------------------------------------
def test_dense_vs_walk_parity_categorical(cat_booster):
    """Multi-word bitset membership as a contraction == the sequential
    FindInBitset walk, to f32-sum tolerance; dense predictor == dense
    Booster.predict bitwise (same compiled program)."""
    bst = cat_booster
    Xq = _cat_queries(37)
    dense = bst.to_predictor(compiler="dense")
    walk = bst.to_predictor(compiler="walk")
    assert dense.info()["compiler"] == "dense"
    assert dense.info()["dense"]["has_cat"]
    out_d = dense.predict(Xq, raw_score=True)
    out_w = walk.predict(Xq, raw_score=True)
    np.testing.assert_allclose(out_d, out_w, rtol=1e-5, atol=1e-6)


def test_dense_multiclass_parity(multiclass_data):
    X, y = multiclass_data
    p = {**SMALL, "objective": "multiclass", "num_class": 3}
    bst = lgb.train(p, lgb.Dataset(X, y, params=p), 6)
    dense = bst.to_predictor(compiler="dense")
    walk = bst.to_predictor(compiler="walk")
    rng = np.random.RandomState(3)
    Xq = rng.randn(23, 6)
    Xq[4, 1] = np.nan
    out_d = dense.predict(Xq)
    assert out_d.shape == (23, 3)
    np.testing.assert_allclose(out_d, walk.predict(Xq), rtol=1e-5,
                               atol=1e-6)


def test_dense_linear_leaves_parity(regression_data):
    """Linear leaves = leaf-gather + matmul in the fused program, with
    the reference NaN fallback to the plain leaf output."""
    X, y = regression_data
    p = {**SMALL, "objective": "regression", "linear_tree": True}
    bst = lgb.train(p, lgb.Dataset(X, y, params=p), 8)
    dense = bst.to_predictor(compiler="dense")
    walk = bst.to_predictor(compiler="walk")
    assert dense.info()["dense"]["has_linear"]
    rng = np.random.RandomState(6)
    Xq = rng.randn(15, 6)
    Xq[3, 0] = np.nan
    Xq[7, :] = np.nan
    np.testing.assert_allclose(dense.predict(Xq), walk.predict(Xq),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("n", [1, 7, 8, 9, 63, 65, 511, 513])
def test_dense_bucket_boundary_parity(n, cat_booster):
    """N = bucket +- 1 shapes: the dense predictor is bitwise identical
    to Booster.predict when both route dense (one shared program per
    bucket), and walk-close everywhere."""
    bst = cat_booster
    Xq = _cat_queries(n, nan_rows=n >= 4)
    dense = bst.to_predictor(compiler="dense")
    ref = bst._gbdt  # route Booster.predict through the same compiler
    old = ref.config.tpu_predict_compiler
    try:
        ref.config.tpu_predict_compiler = "dense"
        assert np.array_equal(dense.predict(Xq), bst.predict(Xq))
    finally:
        ref.config.tpu_predict_compiler = old


def test_dense_pred_leaf_routing(cat_booster):
    """pred_leaf through the compiled program (argmax of the hit
    one-hot) == the per-tree walk's leaf indices, exactly."""
    bst = cat_booster
    Xq = _cat_queries(9)
    cfg = bst._gbdt.config
    old = cfg.tpu_predict_compiler
    try:
        cfg.tpu_predict_compiler = "dense"
        leaves_d = bst.predict(Xq, pred_leaf=True)
        cfg.tpu_predict_compiler = "walk"
        leaves_w = bst.predict(Xq, pred_leaf=True)
    finally:
        cfg.tpu_predict_compiler = old
    assert np.array_equal(leaves_d, leaves_w)


def test_dense_stump_and_mixed_depth():
    """num_leaves-2 stumpy trees and unbalanced trees resolve through
    the same satisfied-count program."""
    rng = np.random.RandomState(9)
    X = rng.randn(300, 4)
    y = (X[:, 0] > 0).astype(np.float64)
    p = {"objective": "binary", "num_leaves": 2, "min_data_in_leaf": 5,
         "verbosity": -1}
    bst = lgb.train(p, lgb.Dataset(X, y, params=p), 5)
    dense = bst.to_predictor(compiler="dense")
    walk = bst.to_predictor(compiler="walk")
    Xq = rng.randn(9, 4)
    np.testing.assert_allclose(dense.predict(Xq), walk.predict(Xq),
                               rtol=1e-6, atol=1e-7)


# -- quantized leaf tables --------------------------------------------------
@pytest.mark.parametrize("bits", [8, 16])
def test_quantized_leaf_tolerance(bits, cat_booster):
    """i8/i16 leaf codes dequantized in the final contraction: absolute
    error bounded by sum of per-tree scales / 2 (bit-controlled)."""
    bst = cat_booster
    Xq = _cat_queries(64)
    exact = bst.to_predictor(compiler="dense", leaf_bits=0)
    quant = bst.to_predictor(compiler="dense", leaf_bits=bits)
    assert quant.info()["dense"]["leaf_bits"] == bits
    out_e = exact.predict(Xq, raw_score=True)
    out_q = quant.predict(Xq, raw_score=True)
    scales = np.asarray(quant._dense.arrays.leaf_scale).ravel()
    tol = scales.sum() / 2 + 1e-6
    assert np.max(np.abs(out_q - out_e)) <= tol
    if bits == 16:
        # 16-bit codes are 256x finer than 8-bit
        q8 = bst.to_predictor(compiler="dense", leaf_bits=8)
        err16 = np.max(np.abs(out_q - out_e))
        err8 = np.max(np.abs(q8.predict(Xq, raw_score=True) - out_e))
        assert err16 <= err8 + 1e-12


# -- jaxpr structure --------------------------------------------------------
def test_dense_program_has_no_loops(cat_booster):
    """The compiled dense program is loop-free: zero while/scan in the
    jaxpr at every bucket (the whole point — no sequential tree walk,
    no depth loop)."""
    import jax
    from lightgbm_tpu.analysis import ir
    from lightgbm_tpu.models.dense_predict import dense_predict_raw
    from lightgbm_tpu.models.tree import pad_rows
    pred = cat_booster.to_predictor(compiler="dense")
    exe = pred._dense
    for n in (1, 64, 513):
        Xp = pad_rows(np.zeros((n, 6), np.float32))
        jx = jax.make_jaxpr(
            lambda X, A: dense_predict_raw(X, A, exe.meta))(Xp, exe.arrays)
        assert ir.count_primitive(jx, "while") == 0
        assert ir.count_primitive(jx, "scan") == 0
        assert ir.count_primitive(jx, "psum") == 0


def test_dense_sharded_one_psum(cat_booster):
    """Tree-axis sharding: per-shard partials merge in EXACTLY one psum
    and the result matches the unsharded program to f32 tolerance."""
    import jax
    from lightgbm_tpu.analysis import ir
    from lightgbm_tpu.models.tree import pad_rows
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    pred = cat_booster.to_predictor(compiler="dense")
    sharded = cat_booster.to_predictor(compiler="dense", shard=4)
    assert sharded.info()["dense"]["shard"] == 4
    exe = sharded._dense
    Xp = pad_rows(np.zeros((9, 6), np.float32))
    jx = jax.make_jaxpr(lambda X, A: exe._sharded_fn(X, A))(Xp, exe.arrays)
    assert ir.count_primitive(jx, "psum") == 1
    assert ir.count_primitive(jx, "while") == 0
    Xq = _cat_queries(37)
    np.testing.assert_allclose(sharded.predict(Xq, raw_score=True),
                               pred.predict(Xq, raw_score=True),
                               rtol=1e-5, atol=1e-6)


# -- fallback telemetry -----------------------------------------------------
def test_fallback_reason_recorded(cat_booster):
    """Auto-mode walks are never silent: the reason lands in info() and
    the serve_compiler_fallback counter."""
    from lightgbm_tpu.serve.compiler import fallback_counts
    from lightgbm_tpu.serve import compile_ensemble
    g = cat_booster._gbdt
    before = fallback_counts()
    # a categorical with a huge raw value blows the bitset-table budget
    import lightgbm_tpu.models.dense_predict as dp
    exe, reason = compile_ensemble(
        g.models, 1, 6, mode="auto")
    if exe is None:
        assert reason  # whatever auto decided, it said why
    # force a budget fallback deterministically
    import lightgbm_tpu.serve.compiler as comp

    def tiny_budget_lower(*a, **kw):
        kw["cat_budget"] = 1
        return dp.lower_ensemble(*a, **kw)

    orig = comp.lower_ensemble
    comp.lower_ensemble = tiny_budget_lower
    try:
        exe2, reason2 = comp.compile_ensemble(g.models, 1, 6, mode="auto")
    finally:
        comp.lower_ensemble = orig
    assert exe2 is None and reason2 == "cat_table_budget"
    after = fallback_counts()
    assert after.get("cat_table_budget", 0) > before.get(
        "cat_table_budget", 0)
    # dense mode raises instead of silently walking
    comp.lower_ensemble = tiny_budget_lower
    try:
        with pytest.raises(comp.DenseLoweringError):
            comp.compile_ensemble(g.models, 1, 6, mode="dense")
    finally:
        comp.lower_ensemble = orig


def test_forced_walk_reason(cat_booster):
    pred = cat_booster.to_predictor(compiler="walk")
    assert pred.info()["compiler"] == "walk"
    assert pred.info()["fallback_reason"] == "forced_walk"


def test_cost_model_backend_awareness():
    from lightgbm_tpu.serve.compiler import dense_cost_model
    # the MXU always profits (per-row gathers are the slow primitive)
    assert dense_cost_model(50, 255, 30, backend="tpu")
    # on CPU, deep wide trees keep the walk; shallow ensembles go dense
    assert not dense_cost_model(50, 255, 30, backend="cpu")
    assert dense_cost_model(50, 4, 3, backend="cpu")


def test_compiler_param_validation():
    from lightgbm_tpu.config import Config
    with pytest.raises(ValueError):
        Config({"tpu_predict_compiler": "bogus"})
    with pytest.raises(ValueError):
        Config({"tpu_predict_leaf_bits": 5})


def test_auto_consistency_booster_vs_predictor(cat_booster):
    """Whatever auto decides, Booster.predict and the predictor decide
    it IDENTICALLY (same cost model, same trees) and match bitwise."""
    bst = cat_booster
    Xq = _cat_queries(9)
    pred = bst.to_predictor()  # auto from the model's params
    assert np.array_equal(pred.predict(Xq), bst.predict(Xq))


# -- serve_dense lint config ------------------------------------------------
def test_serve_dense_lint_config_clean():
    """The serve_dense trace-lint config (bucket-ladder retrace probes +
    the sharded psum contract) runs clean at head."""
    from lightgbm_tpu.analysis.lint import ALL_RULES, build_unit
    from lightgbm_tpu.analysis.rules import run_rules
    unit = build_unit("serve_dense", nshards=4)
    assert unit.jaxpr is not None
    violations = run_rules([unit], rules=ALL_RULES)
    assert not violations, [v.to_json() for v in violations]
    # the ladder stays within its distinct-program bound and the main
    # trace carries the one-psum tally
    assert unit.ctx["max_distinct_programs"] >= len(
        {h for _, h in unit.hashes})
    assert "serve/dense_predict/score_psum" in unit.collectives
