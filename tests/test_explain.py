"""Explanation-serving tests (lightgbm_tpu.explain): dense TreeSHAP
parity vs the f64 host walk across the ensemble-shape matrix, the
additivity invariant on BOTH paths, the no-row-loop jaxpr guarantee,
iteration-window regression coverage, the memoized expected values, the
CompiledPredictor explain lane + fallback counters, and the /explain
HTTP endpoint (slow-marked, like the other localhost e2e tests)."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.explain import (ExplainAdditivityError, check_additivity,
                                  compile_explain, explain_fallback_counts)

SMALL = {"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1}


def _train(params, X, y, rounds=8, **ds_kw):
    p = {**SMALL, **params}
    return lgb.train(p, lgb.Dataset(X, y, params=p, **ds_kw), rounds)


def _cat_data(n=500, n_cat=80):
    """Categorical feature 0 with >=70 distinct values, so its split
    bitsets span multiple uint32 words — the multi-word lowering path."""
    rng = np.random.RandomState(5)
    cat = rng.randint(0, n_cat, n)
    X = np.column_stack([cat.astype(np.float64), rng.randn(n, 3)])
    y = ((cat % 3 == 0).astype(np.float64) + 0.3 * X[:, 1] > 0.5)
    return X, y.astype(np.float64)


def _contrib_both(bst, X, **kw):
    """(dense phi, walk phi) for one Booster via the routing config."""
    bst.config.tpu_explain_compiler = "dense"
    dense = bst.predict(X, pred_contrib=True, **kw)
    bst.config.tpu_explain_compiler = "walk"
    walk = bst.predict(X, pred_contrib=True, **kw)
    bst.config.tpu_explain_compiler = "auto"
    return dense, walk


def _check_additive(bst, phi, X, k=1, **kw):
    raw = bst.predict(X, raw_score=True, **kw)
    sums = phi.reshape(len(X), k, -1).sum(axis=2)
    np.testing.assert_allclose(
        sums[:, 0] if k == 1 else sums, raw, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# additivity + dense-vs-host parity across the ensemble-shape matrix
# ---------------------------------------------------------------------------

def test_additivity_binary(binary_data):
    X, y = binary_data
    bst = _train({"objective": "binary"}, X, y)
    dense, walk = _contrib_both(bst, X[:64])
    assert dense.shape == (64, X.shape[1] + 1)
    np.testing.assert_allclose(dense, walk, rtol=1e-4, atol=1e-5)
    _check_additive(bst, dense, X[:64])
    _check_additive(bst, walk, X[:64])


def test_additivity_multiword_categorical():
    X, y = _cat_data()
    p = {"objective": "binary", "max_cat_threshold": 48,
         "cat_smooth": 1.0, "min_data_per_group": 2}
    bst = _train(p, X, y, categorical_feature=[0])
    assert any(t.cat_threshold is not None and len(t.cat_threshold) >
               len(t.cat_boundaries) - 1 for t in bst._gbdt.models), \
        "expected at least one multi-word bitset split"
    dense, walk = _contrib_both(bst, X[:50])
    np.testing.assert_allclose(dense, walk, rtol=1e-4, atol=1e-5)
    _check_additive(bst, dense, X[:50])


def test_additivity_nan(binary_data):
    X, y = binary_data
    Xn = X.copy()
    rng = np.random.RandomState(0)
    Xn[rng.rand(*Xn.shape) < 0.15] = np.nan
    bst = _train({"objective": "binary", "use_missing": True}, Xn, y)
    dense, walk = _contrib_both(bst, Xn[:50])
    np.testing.assert_allclose(dense, walk, rtol=1e-4, atol=1e-5)
    _check_additive(bst, dense, Xn[:50])


def test_additivity_multiclass(multiclass_data):
    X, y = multiclass_data
    bst = _train({"objective": "multiclass", "num_class": 3}, X, y)
    dense, walk = _contrib_both(bst, X[:40])
    assert dense.shape == (40, 3 * (X.shape[1] + 1))
    np.testing.assert_allclose(dense, walk, rtol=1e-4, atol=1e-5)
    _check_additive(bst, dense, X[:40], k=3)
    _check_additive(bst, walk, X[:40], k=3)


def test_additivity_linear_leaf(regression_data, capsys):
    X, y = regression_data
    bst = _train({"objective": "regression", "linear_tree": True,
                  "verbosity": 1}, X, y, rounds=5)
    dense, walk = _contrib_both(bst, X[:30])
    # the plain-output warning fires on BOTH routes
    out = capsys.readouterr().out
    assert "PLAIN output" in out
    np.testing.assert_allclose(dense, walk, rtol=1e-4, atol=1e-5)
    # additivity holds against the PLAIN leaf score by construction
    # (the dense path's internal check enforced it), NOT against the
    # linear-corrected raw predict — the exact limitation the warning
    # states, so the raw score must genuinely differ here
    raw = bst.predict(X[:30], raw_score=True)
    assert not np.allclose(dense.sum(axis=1), raw, rtol=1e-4, atol=1e-4)


def test_additivity_stump():
    # constant target -> every tree is a stump (no split clears the
    # gain floor); contributions are all-bias
    rng = np.random.RandomState(9)
    X = rng.randn(200, 4)
    y = np.full(200, 3.25)
    bst = _train({"objective": "regression"}, X, y, rounds=3)
    assert all(t.num_leaves == 1 for t in bst._gbdt.models)
    dense, walk = _contrib_both(bst, X[:16])
    np.testing.assert_allclose(dense, walk, rtol=1e-4, atol=1e-5)
    _check_additive(bst, dense, X[:16])
    assert np.allclose(dense[:, :-1], 0.0)


def test_parity_at_bucket_boundaries(binary_data):
    X, y = binary_data
    bst = _train({"objective": "binary"}, X, y)
    for n in (1, 7, 8, 9, 63, 64, 65):
        dense, walk = _contrib_both(bst, X[:n])
        np.testing.assert_allclose(dense, walk, rtol=1e-4, atol=1e-5,
                                   err_msg=f"rows={n}")


# ---------------------------------------------------------------------------
# iteration-window regression (the dropped start/num_iteration bug)
# ---------------------------------------------------------------------------

def test_contrib_respects_iteration_window(binary_data):
    X, y = binary_data
    bst = _train({"objective": "binary"}, X, y, rounds=10)
    for mode in ("dense", "walk"):
        bst.config.tpu_explain_compiler = mode
        phi = bst.predict(X[:20], pred_contrib=True, start_iteration=3,
                          num_iteration=4)
        raw = bst.predict(X[:20], raw_score=True, start_iteration=3,
                          num_iteration=4)
        np.testing.assert_allclose(phi.sum(axis=1), raw, rtol=1e-4,
                                   atol=1e-4, err_msg=mode)
        full = bst.predict(X[:20], pred_contrib=True)
        assert not np.allclose(phi, full), \
            "windowed contrib must differ from the full model's"
    bst.config.tpu_explain_compiler = "auto"


# ---------------------------------------------------------------------------
# dense program properties
# ---------------------------------------------------------------------------

def test_dense_jaxpr_has_no_row_loops(binary_data):
    """The tentpole guarantee: zero while/scan in the row dimension —
    the whole program is vectorized algebra over (rows, leaves, depth)."""
    import jax
    X, y = binary_data
    bst = _train({"objective": "binary"}, X, y)
    exe, reason = compile_explain(bst._gbdt.models, 1, X.shape[1],
                                  num_cols=X.shape[1] + 1)
    assert reason is None
    jaxpr = jax.make_jaxpr(
        lambda Xa: exe.explain_padded(Xa))(
            np.zeros((64, X.shape[1]), np.float32))
    text = str(jaxpr)
    assert "while" not in text and "scan" not in text


def test_expected_value_memo(binary_data):
    from lightgbm_tpu.models.shap import node_expectations
    X, y = binary_data
    bst = _train({"objective": "binary"}, X, y, rounds=2)
    tree = bst._gbdt.models[0]
    e0 = node_expectations(tree)
    assert node_expectations(tree) is e0  # memo hit
    # in-place leaf mutation (refit does this) must invalidate the memo
    tree.leaf_value[0] += 1.0
    e1 = node_expectations(tree)
    assert e1 is not e0 and not np.allclose(e0, e1)
    tree.leaf_value[0] -= 1.0


def test_check_additivity_raises():
    phi = np.array([[0.5, 0.5, 1.0]])
    check_additivity(phi, np.array([[2.0]]), 3)
    with pytest.raises(ExplainAdditivityError):
        check_additivity(phi, np.array([[5.0]]), 3)


def test_forced_walk_and_fallback_counters(binary_data):
    X, y = binary_data
    bst = _train({"objective": "binary"}, X, y, rounds=3)
    before = explain_fallback_counts().get("forced_walk", 0)
    exe, reason = compile_explain(bst._gbdt.models, 1, X.shape[1],
                                  mode="walk")
    assert exe is None and reason == "forced_walk"
    assert explain_fallback_counts()["forced_walk"] == before + 1


def test_additivity_failure_falls_back_to_walk(binary_data, monkeypatch):
    """A corrupted dense program trips the additivity invariant and the
    Booster answers via the host walk WITH a recorded reason."""
    from lightgbm_tpu.explain import compiler as ec
    from lightgbm_tpu.telemetry.metrics import default_registry
    X, y = binary_data
    bst = _train({"objective": "binary"}, X, y, rounds=3)
    ref = bst.predict(X[:10], pred_contrib=True)

    orig = ec.compile_explain

    def corrupted(*a, **kw):
        exe, reason = orig(*a, **kw)
        if exe is not None:
            exe.exp = exe.exp._replace(bias=exe.exp.bias + 1.0)
        return exe, reason

    monkeypatch.setattr(ec, "compile_explain", corrupted)
    c = default_registry().counter(
        "serve_explain_fallback_batches_total", "x",
        labels=("reason", "model"))
    before = c.value(reason="additivity", model="-")
    bst.config.tpu_explain_compiler = "dense"
    try:
        phi = bst.predict(X[:10], pred_contrib=True)
    finally:
        bst.config.tpu_explain_compiler = "auto"
    np.testing.assert_allclose(phi, ref, rtol=1e-4, atol=1e-5)
    assert c.value(reason="additivity", model="-") == before + 1


# ---------------------------------------------------------------------------
# serving lane: CompiledPredictor.explain + /explain endpoint
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def booster(binary_data):
    X, y = binary_data
    p = {**SMALL, "objective": "binary"}
    return lgb.train(p, lgb.Dataset(X, y, params=p), 10)


def test_predictor_explain_parity(binary_data, booster):
    X, _y = binary_data
    pred = booster.to_predictor()
    phi = pred.explain(X[:33].astype(np.float32))
    ref = booster.predict(X[:33], pred_contrib=True)
    np.testing.assert_allclose(phi, ref, rtol=1e-4, atol=1e-5)
    info = pred.info()
    assert info["explain_compiler"] == "dense"
    assert info["explain"]["trees"] == 10


def test_predictor_explain_lazy_until_first_call(booster):
    pred = booster.to_predictor()
    assert pred.info()["explain_compiler"] == "lazy"
    pred.explain(np.zeros((1, pred.num_features), np.float32))
    assert pred.info()["explain_compiler"] == "dense"


def test_predictor_explain_walk_mode(binary_data, booster):
    X, _y = binary_data
    pred = booster.to_predictor(explain_compiler="walk")
    before = explain_fallback_counts().get("forced_walk", 0)
    phi = pred.explain(X[:5].astype(np.float32))
    ref = booster.predict(X[:5], pred_contrib=True)
    np.testing.assert_allclose(phi, ref, rtol=1e-4, atol=1e-5)
    assert explain_fallback_counts()["forced_walk"] == before + 1
    assert pred.info()["explain_compiler"] == "walk"
    assert pred.info()["explain_fallback_reason"] == "forced_walk"


@pytest.mark.slow
def test_server_explain_endpoint(tmp_path, booster, binary_data):
    from lightgbm_tpu.serve import ModelRegistry, PredictionServer
    X, _y = binary_data
    path = str(tmp_path / "m.txt")
    booster.save_model(path)
    reg = ModelRegistry()
    reg.load("m", path)
    srv = PredictionServer(reg, port=0).start()
    url = f"http://{srv.host}:{srv.port}"

    def post(p, body):
        r = urllib.request.urlopen(urllib.request.Request(
            url + p, json.dumps(body).encode(),
            {"Content-Type": "application/json"}))
        return json.loads(r.read())

    try:
        rows = X[:5].tolist()
        out = post("/explain", {"model": "m", "rows": rows})
        phi = np.asarray(out["contributions"])
        ref = booster.predict(X[:5], pred_contrib=True)
        np.testing.assert_allclose(phi, ref, rtol=1e-4, atol=1e-4)
        assert out["request_id"]
        # additivity against the SERVED predictions, not just the model
        pr = post("/predict", {"model": "m", "rows": rows,
                               "raw_score": True})
        np.testing.assert_allclose(
            phi.sum(axis=1), np.asarray(pr["predictions"]),
            rtol=1e-4, atol=1e-4)
        with pytest.raises(urllib.error.HTTPError) as ei:
            post("/explain", {"model": "nope", "rows": rows})
        assert ei.value.code == 404
        stats = json.loads(urllib.request.urlopen(url + "/stats").read())
        assert "m:explain" in stats  # the lane's own batcher saturation
        assert stats["m"]["explain_requests"] >= 1
        met = urllib.request.urlopen(url + "/metrics").read().decode()
        assert "serve_explain_latency_ms" in met
        assert "serve_explain_responses_total" in met
    finally:
        srv.drain(timeout=5)
