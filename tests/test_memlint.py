"""Memory lint (ISSUE 11 tentpole a, lightgbm_tpu/analysis/memory.py).

Contract under test:
  * the live-range sweep estimates peak live bytes of a traced program
    (args + intermediates, transients of nested sub-jaxprs) and sizes
    shard_map bodies PER SHARD;
  * a planted footprint inflation — the un-scattered full histogram on
    the dp path — exceeds the declared ``data_parallel/wave_sliced``
    curve and fires with a site-named diagnostic, while the scattered
    program stays under it;
  * VMEM: a pallas kernel's block bytes are checked against the
    per-core ceiling;
  * the XLA ``memory_analysis()`` cross-check holds within 2x where the
    backend reports one, and a drifted estimate fires;
  * ``lint-mem`` CLI: clean exit at head, report carries the
    environment block, and the rows=/devices= fit mode answers the
    pod-scale question statically.
"""

import jax
import jax.numpy as jnp
import pytest

from lightgbm_tpu.analysis import ir, lint
from lightgbm_tpu.analysis import memory as memlint
from lightgbm_tpu.analysis.contracts import memory_budget_for
from lightgbm_tpu.analysis.lint import MEM_GEOMETRY, TRACE_GEOMETRY
from lightgbm_tpu.analysis.rules import TraceUnit


# ---------------------------------------------------------------------------
# the estimator
# ---------------------------------------------------------------------------

def test_estimator_counts_args_and_intermediates():
    def f(x):
        big = jnp.concatenate([x, x, x, x])        # 4x intermediate
        return big.sum()

    est = memlint.estimate_memory(ir.trace(f, jnp.ones((1024,))))
    assert est.args_bytes == 4096
    # peak >= args + the 4x concat output
    assert est.peak_bytes >= 4096 + 4 * 4096
    assert est.peak_bytes_per_device == est.peak_bytes  # no mesh
    assert any(b.bytes == 4 * 4096 for b in est.top_buffers)


def test_estimator_nested_transient():
    """A big buffer living only INSIDE a jitted sub-program still counts
    at the call site (the transient term)."""
    def inner(x):
        blown = jnp.tile(x, (16, 1))
        return blown.sum(0)

    def f(x):
        return jax.jit(inner)(x) * 2

    est = memlint.estimate_memory(ir.trace(f, jnp.ones((512,))))
    assert est.peak_bytes >= 16 * 512 * 4


def test_estimator_shard_map_body_is_per_device():
    from jax.sharding import PartitionSpec as P
    from lightgbm_tpu.parallel.mesh import get_mesh, shard_map_compat
    mesh = get_mesh(8)
    ax = mesh.axis_names[0]
    fn = shard_map_compat(lambda x: jax.lax.psum(x * 2, ax), mesh=mesh,
                          in_specs=(P(ax),), out_specs=P())
    est = memlint.estimate_memory(
        ir.trace(lambda x: fn(x), jnp.ones((8 * 1024, 16))))
    # global sweep sees the full (8192, 16) arg; the body only its
    # (1024, 16) shard
    assert est.peak_bytes >= 8 * 1024 * 16 * 4
    assert est.peak_bytes_per_device < est.peak_bytes
    assert est.peak_bytes_per_device >= 1024 * 16 * 4


def test_pallas_kernel_vmem_recorded():
    """The wave config's pallas kernels report VMEM block bytes (and
    stay under the 16 MB/core ceiling at lint geometry)."""
    unit = lint.build_unit("wave", geometry=TRACE_GEOMETRY)
    est = memlint.estimate_memory(unit.jaxpr)
    assert est.vmem_kernels, "no pallas kernels seen in the wave program"
    assert all(0 < b <= memlint.VMEM_BYTES_PER_CORE
               for b in est.vmem_kernels.values())
    # planted: a tiny ceiling makes every kernel fire, site-named
    unit.ctx.update(check_memory=True, memory_estimate=est,
                    vmem_limit=1024)
    vs = memlint.MemoryBudgetRule().check(unit)
    vmem_vs = [v for v in vs if "VMEM" in v.message]
    assert vmem_vs and "pallas_call" in vmem_vs[0].site


# ---------------------------------------------------------------------------
# planted footprint inflation: un-scattered full histogram on dp
# ---------------------------------------------------------------------------

def _dp_estimate(hist_scatter: bool):
    from lightgbm_tpu.analysis.lint import (_dp_entry, _mk_train_args,
                                            _mk_wave_grow, _trace_mesh)
    from lightgbm_tpu.parallel.data_parallel import WaveDPStrategy
    mesh, _ = _trace_mesh(8)
    ax = mesh.axis_names[0]
    grow = _mk_wave_grow(
        WaveDPStrategy(ax, nshards=8, hist_scatter=hist_scatter),
        MEM_GEOMETRY, quantized=True, spec=False)
    fn = _dp_entry(grow, mesh, ax)
    args = _mk_train_args(0, 8 * 4096, MEM_GEOMETRY, True)
    return memlint.estimate_memory(ir.trace(lambda *a: fn(*a), *args))


def test_planted_unscattered_histogram_exceeds_budget():
    """hist_scatter=False re-inflates the post-merge histograms to full
    F on every shard; the dp_scatter budget curve must catch it with a
    diagnostic naming the budget and the offending buffers."""
    est = _dp_estimate(hist_scatter=False)
    ctx = {"rows": 8 * 4096, "features": MEM_GEOMETRY.features,
           "bins": MEM_GEOMETRY.bins, "leaves": MEM_GEOMETRY.leaves,
           "wave_size": MEM_GEOMETRY.wave, "itemsize": 4,
           "world_size": 8, "quantized": True,
           "check_memory": True, "memory_estimate": est}
    unit = TraceUnit(name="dp_scatter", jaxpr=object(), ctx=ctx)
    vs = memlint.MemoryBudgetRule().check(unit)
    assert vs, "un-scattered full histogram not flagged"
    msg = vs[0].message
    assert "data_parallel/wave_sliced" in vs[0].site
    assert "exceeds" in msg and "largest live buffers" in msg
    # the diagnostic names a concrete buffer shape, not just a number
    assert "int32" in msg


def test_scattered_dp_stays_under_budget():
    est = _dp_estimate(hist_scatter=True)
    budget = memory_budget_for("dp_scatter")
    assert budget is not None
    from lightgbm_tpu.analysis.contracts import resolve_limit
    ctx = {"rows": 8 * 4096, "features": MEM_GEOMETRY.features,
           "bins": MEM_GEOMETRY.bins, "leaves": MEM_GEOMETRY.leaves,
           "wave_size": MEM_GEOMETRY.wave, "itemsize": 4,
           "world_size": 8, "quantized": True}
    limit = resolve_limit(budget.hbm_per_device, ctx)
    assert est.peak_bytes_per_device <= limit, (
        f"scattered dp {est.peak_bytes_per_device} over budget {limit}")


def test_missing_budget_is_a_violation():
    unit = TraceUnit(name="brand_new_config", jaxpr=ir.trace(
        lambda x: x * 2, jnp.ones((4,))), ctx={"check_memory": True})
    vs = memlint.MemoryBudgetRule().check(unit)
    assert vs and "no declared MemoryBudget" in vs[0].message


def test_xla_crosscheck_drift_fires():
    """An estimate outside [0.5, 2]x of the compiler's number fails."""
    jx = ir.trace(lambda x: x * 2, jnp.ones((1024,)))
    est = memlint.estimate_memory(jx)
    unit = TraceUnit(
        name="serial", jaxpr=jx,
        ctx={"check_memory": True, "memory_estimate": est,
             "rows": 1024, "features": 1, "bins": 2, "leaves": 2,
             "wave_size": 2,
             "xla_memory": {"argument_bytes": 0, "output_bytes": 0,
                            "temp_bytes": est.peak_bytes * 100,
                            "total_bytes": est.peak_bytes * 100}})
    vs = memlint.MemoryBudgetRule().check(unit)
    assert any("drifted" in v.message and v.site == "<xla-crosscheck>"
               for v in vs), vs


# ---------------------------------------------------------------------------
# the driver + CLI + fit mode
# ---------------------------------------------------------------------------

def test_run_lint_mem_serve_clean_with_xla_crosscheck():
    """The fast config end-to-end: estimate under budget AND within 2x
    of XLA's memory_analysis (the backend reports one on CPU)."""
    report = memlint.run_lint_mem(["serve"], crosscheck=True)
    assert report["ok"], report
    entry = report["configs"]["serve"]
    assert entry["ok"]
    if "estimate_over_xla" in entry:   # backend reported an analysis
        assert 0.5 <= entry["estimate_over_xla"] <= 2.0


def test_fit_report_pod_scale():
    """The static 'will 10^8 rows fit at W=64?' answer, no tracing."""
    # budgets register at module import
    import lightgbm_tpu.multitrain.batched  # noqa: F401
    import lightgbm_tpu.serve.predictor  # noqa: F401
    ctx = {"rows": 10 ** 8, "features": 28, "bins": 255, "leaves": 255,
           "wave_size": 42, "models": 64, "itemsize": 4, "bucket": 4096,
           "world_size": 64, "nshards": 64, "quantized": True}
    fit = memlint._fit_report(ctx, hbm_gb=16.0)
    assert "data_parallel/wave_sliced" in fit["budgets"]
    dp = fit["budgets"]["data_parallel/wave_sliced"]
    assert dp["fits"] and dp["hbm_bytes_per_device"] < 1 << 30
    assert "wave/grow" in fit["budgets"]
    assert "serve/bucket_ladder" in fit["budgets"]
    assert "multitrain/stacked_state" in fit["budgets"]
    # and 10^9 rows on ONE device must NOT fit a 16 GB part
    ctx1 = dict(ctx, rows=10 ** 9, world_size=1, nshards=1)
    fit1 = memlint._fit_report(ctx1, hbm_gb=16.0)
    assert not fit1["budgets"]["wave/grow"]["fits"]
    # a curve that raises (reads a ctx key the fit ctx lacks) must fail
    # the verdict, never silently count as fitting
    from lightgbm_tpu.analysis import contracts
    contracts.memory_budget("test/raising_curve", ("nowhere",),
                            lambda c: c["no_such_ctx_key"])
    try:
        fit2 = memlint._fit_report(ctx, hbm_gb=16.0)
        assert "error" in fit2["budgets"]["test/raising_curve"]
        assert not fit2["all_fit"]
    finally:
        contracts.remove_memory_budget("test/raising_curve")


def test_lint_mem_cli_exit_and_environment(tmp_path, capsys):
    out = tmp_path / "mem.json"
    rc = memlint.main(["configs=serve", f"out={out}", "crosscheck=0"])
    capsys.readouterr()
    assert rc == 0 and out.exists()
    import json
    rep = json.loads(out.read_text())
    assert rep["schema"] == "lint-mem-v1" and rep["ok"]
    env = rep["environment"]
    assert env["jax_version"] == jax.__version__
    assert env["device_count"] >= 1 and "backend" in env
    assert "virtual_devices" in env


@pytest.mark.slow
def test_full_matrix_crosscheck_within_2x():
    """Acceptance: the whole six-config matrix runs clean at head and
    every config where the backend reports a memory analysis is within
    2x of the static estimate."""
    report = memlint.run_lint_mem(crosscheck=True)
    assert report["ok"], report
    checked = [name for name, e in report["configs"].items()
               if "estimate_over_xla" in e]
    assert checked, "no config produced an XLA cross-check"
    for name in checked:
        r = report["configs"][name]["estimate_over_xla"]
        assert 0.5 <= r <= 2.0, (name, r)
