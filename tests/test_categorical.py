"""Categorical split tests: sorted-subset search, bitset model IO,
reference-format multi-category model loading (reference patterns:
test_engine.py:118-375 categorical semantics)."""

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.models.tree import CAT_MASK


def _cat_data(n=2000, ncat=12, seed=3):
    rng = np.random.RandomState(seed)
    c = rng.randint(0, ncat, n)
    x1 = rng.randn(n)
    # group structure: categories {0,2,4,...} push y up, odd down — a
    # subset split can capture it in one node, one-vs-rest cannot
    y = np.where(c % 2 == 0, 2.0, -2.0) + 0.3 * x1 + 0.1 * rng.randn(n)
    X = np.stack([c.astype(float), x1], 1)
    return X, y


PARAMS = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
          "metric": "l2", "min_data_in_leaf": 5, "cat_smooth": 1.0,
          "min_data_per_group": 1}


def test_subset_split_learns_group_structure():
    X, y = _cat_data()
    ds = lgb.Dataset(X, y, categorical_feature=[0])
    bst = lgb.train(PARAMS, ds, 20)
    mse = np.mean((bst.predict(X) - y) ** 2)
    assert mse < 0.1
    # at least one node carries a multi-category set
    multi = [t for t in bst._gbdt.models
             for i in range(t.num_leaves - 1)
             if t.decision_type[i] & CAT_MASK and len(t.cat_values(i)) > 1]
    assert multi, "expected sorted-subset (multi-category) splits"


def test_subset_beats_onehot_in_early_trees():
    X, y = _cat_data()
    ds1 = lgb.Dataset(X, y, categorical_feature=[0])
    subset = lgb.train(PARAMS, ds1, 2)
    ds2 = lgb.Dataset(X, y, categorical_feature=[0])
    onehot = lgb.train({**PARAMS, "max_cat_to_onehot": 64}, ds2, 2)
    mse_s = np.mean((subset.predict(X) - y) ** 2)
    mse_o = np.mean((onehot.predict(X) - y) ** 2)
    assert mse_s < mse_o


def test_cat_model_roundtrip(tmp_path):
    X, y = _cat_data()
    ds = lgb.Dataset(X, y, categorical_feature=[0])
    bst = lgb.train(PARAMS, ds, 10)
    p0 = bst.predict(X)
    path = str(tmp_path / "cat.txt")
    bst.save_model(path)
    loaded = lgb.Booster(model_file=path)
    np.testing.assert_allclose(loaded.predict(X), p0, rtol=1e-5, atol=1e-6)


def test_cat_continued_training():
    X, y = _cat_data()
    first = lgb.train(PARAMS, lgb.Dataset(X, y, categorical_feature=[0]), 10)
    cont = lgb.train(PARAMS, lgb.Dataset(X, y, categorical_feature=[0]), 10,
                     init_model=first)
    assert cont.num_trees() == 20
    mse = np.mean((cont.predict(X) - y) ** 2)
    assert mse <= np.mean((first.predict(X) - y) ** 2) + 1e-9


def test_reference_format_multicat_bitset_loads():
    """A reference-format model with a multi-category bitset node must
    predict with FULL set membership (round-2 verdict: the old loader kept
    only the first category)."""
    # one tree: root splits feature 0 on categories {1, 3, 34} -> left
    # leaf 0 (value 5.0), else right leaf 1 (value -5.0).
    # bitset words: cats 1,3 -> word0 = 2|8 = 10; cat 34 -> word1 = 4.
    model = """tree
version=v3
num_class=1
num_tree_per_iteration=1
label_index=0
max_feature_idx=1
objective=regression
feature_names=c0 f1
feature_infos=0:1:2:3:34 [-1:1]
tree_sizes=400

Tree=0
num_leaves=2
num_cat=1
split_feature=0
split_gain=100
threshold=0
decision_type=1
left_child=-1
right_child=-2
leaf_value=5 -5
leaf_weight=10 10
leaf_count=10 10
internal_value=0
internal_weight=20
internal_count=20
cat_boundaries=0 2
cat_threshold=10 4
is_linear=0
shrinkage=1

end of trees

parameters:
end of parameters
"""
    bst = lgb.Booster(model_str=model)
    X = np.array([[1.0, 0.0], [3.0, 0.0], [34.0, 0.0],
                  [0.0, 0.0], [2.0, 0.0], [5.0, 0.0], [33.0, 0.0]])
    pred = bst.predict(X)
    np.testing.assert_allclose(pred, [5, 5, 5, -5, -5, -5, -5], atol=1e-9)


def test_cat_shap_consistency():
    X, y = _cat_data(n=400)
    ds = lgb.Dataset(X, y, categorical_feature=[0])
    bst = lgb.train(PARAMS, ds, 5)
    contrib = bst.predict(X[:50], pred_contrib=True)
    raw = bst.predict(X[:50], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-4, atol=1e-4)
