"""path_smooth + extra_trees behavioral tests (reference:
test_engine.py's path_smooth/extra_trees checks — the params must change
the model, keep quality sane, and stay deterministic under a fixed seed)."""

import numpy as np

import lightgbm_tpu as lgb


def _data(n=3000, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f)
    y = X @ w + np.sin(2 * X[:, 0]) + 0.3 * rng.randn(n)
    return X, y


BASE = {"objective": "regression", "num_leaves": 31, "max_bin": 63,
        "metric": "l2", "verbosity": -1, "min_data_in_leaf": 10,
        "learning_rate": 0.15}


def _mse(bst, X, y):
    return float(np.mean((bst.predict(X) - y) ** 2))


def test_path_smooth_changes_model_and_shrinks_leaves():
    X, y = _data()
    b0 = lgb.train(BASE, lgb.Dataset(X, y), num_boost_round=10)
    bs = lgb.train(dict(BASE, path_smooth=200.0), lgb.Dataset(X, y),
                   num_boost_round=10)
    p0, ps = b0.predict(X), bs.predict(X)
    assert not np.allclose(p0, ps)
    # smoothing regularizes: training fit is weaker but sane
    m0, ms = _mse(b0, X, y), _mse(bs, X, y)
    assert ms >= m0 * 0.99
    assert ms < np.var(y) * 0.7


def test_path_smooth_wave_matches_partition_semantics():
    X, y = _data(seed=1)
    p = dict(BASE, path_smooth=50.0)
    pred_p = lgb.train(dict(p, tree_grow_mode="partition"),
                       lgb.Dataset(X, y), num_boost_round=6).predict(X)
    pred_w = lgb.train(dict(p, tree_grow_mode="wave", tpu_wave_size=1),
                       lgb.Dataset(X, y), num_boost_round=6).predict(X)
    np.testing.assert_allclose(pred_w, pred_p, atol=2e-4)


def test_path_smooth_with_monotone():
    rng = np.random.RandomState(2)
    n = 2000
    x0, x1 = rng.rand(n), rng.rand(n)
    y = 4 * x0 + np.sin(8 * np.pi * x0) + 2 * x1 + 0.1 * rng.randn(n)
    X = np.stack([x0, x1], 1).astype(np.float32)
    p = dict(BASE, path_smooth=20.0, monotone_constraints=[1, 0])
    bst = lgb.train(p, lgb.Dataset(X, y), num_boost_round=15)
    grid = np.linspace(0, 1, 101)
    for _ in range(8):
        row = rng.rand(2)
        batch = np.tile(row, (101, 1))
        batch[:, 0] = grid
        assert (np.diff(bst.predict(batch)) >= -1e-9).all()


def test_extra_trees_trains_and_differs():
    X, y = _data(seed=3)
    b0 = lgb.train(BASE, lgb.Dataset(X, y), num_boost_round=10)
    be = lgb.train(dict(BASE, extra_trees=True), lgb.Dataset(X, y),
                   num_boost_round=10)
    assert not np.allclose(b0.predict(X), be.predict(X))
    # random single-threshold splits still learn the signal
    assert _mse(be, X, y) < np.var(y) * 0.6


def test_extra_trees_deterministic_under_seed():
    X, y = _data(seed=4)
    p = dict(BASE, extra_trees=True, extra_seed=7)
    b1 = lgb.train(p, lgb.Dataset(X, y), num_boost_round=5)
    b2 = lgb.train(p, lgb.Dataset(X, y), num_boost_round=5)
    np.testing.assert_allclose(b1.predict(X), b2.predict(X))


def test_extra_trees_seed_changes_model():
    X, y = _data(seed=5)
    b1 = lgb.train(dict(BASE, extra_trees=True, extra_seed=1),
                   lgb.Dataset(X, y), num_boost_round=5)
    b2 = lgb.train(dict(BASE, extra_trees=True, extra_seed=99),
                   lgb.Dataset(X, y), num_boost_round=5)
    assert not np.allclose(b1.predict(X), b2.predict(X))
