"""Voting-parallel (PV-Tree, arXiv:1611.01276) histogram merging on the
wave grower (ISSUE 18 tentpole; learner/wave.py use_voting +
WaveVotingStrategy — the reference's VotingParallelTreeLearner
GlobalVoting/local-vote refinement, voting_parallel_tree_learner.cpp,
amortized over the wave's leaf batch).

Contract under test:
  * bit-identity — with 2k >= F the sorted global top-2k selection is
    the identity permutation, so the voted psum merges exactly the full
    histogram batch and the trained tree is IDENTICAL to the DP
    full-psum path and the serial grower (quantized path: bit-for-bit);
  * collective shape — the traced program holds one O(W*top_k) id
    all_gather per merge site and, at 2k < F, NO psum as large as a
    full (c, F, B, 3) histogram batch: every voted psum operand is at
    most (2k/F) of the full merge — the cross-host byte ratio the
    ISSUE's pod budget bounds;
  * typed config error — use_quantized_grad on the masked (non-wave)
    voting path raises QuantizedGradUnsupportedError instead of the old
    silent downgrade;
  * auto-selection — tree_learner=auto resolves to a concrete learner
    before training and records it in the model text.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

import lightgbm_tpu as lgb
from lightgbm_tpu.learner.wave import make_wave_grow_fn
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.parallel.data_parallel import WaveDPStrategy
from lightgbm_tpu.parallel.mesh import get_mesh, shard_map_compat
from lightgbm_tpu.parallel.voting_parallel import (
    QuantizedGradUnsupportedError, VotingParallelTreeLearner,
    WaveVotingStrategy, modeled_pass_bytes, voting_favored)

F, B, LEAVES, WAVE = 6, 64, 13, 4
NSH = 4            # shards: pallas row_block=4096 per shard bounds n


def _mk_data(seed=0):
    rng = np.random.RandomState(seed)
    n = NSH * 4096
    bins = rng.randint(0, B - 1, (F, n)).astype(np.uint8)
    logit = (bins[0].astype(np.float32) / B - 0.5) * 3 + \
        ((bins[1] > 40).astype(np.float32) - 0.5) * 2
    y = (logit + rng.randn(n) * 0.7 > 0).astype(np.float32)
    grad = (0.5 - y).astype(np.float32)
    hess = np.full(n, 0.25, np.float32)
    mask = np.ones(n, np.float32)
    return (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(mask))


def _mk_grow(strategy, quantized=True, spec=False):
    sp = SplitParams(min_data_in_leaf=5, min_sum_hessian_in_leaf=0.0,
                     any_cat=False)
    return make_wave_grow_fn(
        num_leaves=LEAVES, num_features=F, max_bins=B, max_depth=0,
        split_params=sp, hist_impl="pallas", any_cat=False, interpret=True,
        jit=False, wave_size=WAVE, quantized=quantized, stochastic=False,
        spec_ramp=spec, spec_tol=0.02, strategy=strategy)


def _wrap_dp(grow, mesh, ax):
    return jax.jit(shard_map_compat(
        lambda X_T, g, h, m, nb, ic, hn, mono, cp, fm: grow(
            X_T, g, h, m, nb, ic, hn, mono, cp, (), fm),
        mesh=mesh,
        in_specs=(P(None, ax), P(ax), P(ax), P(ax), P(), P(), P(), P(),
                  P(), P()),
        out_specs=VotingParallelTreeLearner._tree_specs(ax)))


def _meta_args():
    return (jnp.full((F,), B, jnp.int32), jnp.zeros((F,), bool),
            jnp.zeros((F,), bool), jnp.zeros((F,), jnp.int32),
            jnp.zeros((F,), jnp.float32), jnp.ones((F,), bool))


def _serial_call(grow, data):
    bins, grad, hess, mask = data
    nb, ic, hn, mono, cp, fm = _meta_args()
    return grow(bins, grad, hess, mask, nb, ic, hn, mono, cp, (), fm)


BITWISE = ("num_leaves", "split_feature", "threshold_bin", "nan_bin",
           "decision_type", "left_child", "right_child", "row_leaf")


def test_voting_matches_allreduce_and_serial_bitwise():
    """Quantized voting wave at top_k=3 (2k=6 >= F=6, identity
    selection): voting == full-psum DP == serial, bit-for-bit (endgame
    engages at 13 leaves / wave 4, so the shard-local bank and the
    winner exchange ride the vote too)."""
    mesh = get_mesh(NSH)
    ax = mesh.axis_names[0]
    data = _mk_data()
    args = data + _meta_args()
    t_ser = _serial_call(_mk_grow(None), data)
    t_ar = _wrap_dp(_mk_grow(WaveDPStrategy(ax, nshards=NSH)),
                    mesh, ax)(*args)
    t_vo = _wrap_dp(_mk_grow(WaveVotingStrategy(ax, nshards=NSH, top_k=3)),
                    mesh, ax)(*args)
    for name in BITWISE + ("split_gain", "leaf_value", "leaf_count"):
        np.testing.assert_array_equal(
            np.asarray(getattr(t_vo, name)),
            np.asarray(getattr(t_ar, name)),
            err_msg=f"voting != allreduce: {name}")
    for name in BITWISE:
        np.testing.assert_array_equal(
            np.asarray(getattr(t_vo, name)),
            np.asarray(getattr(t_ser, name)),
            err_msg=f"voting != serial: {name}")
    np.testing.assert_allclose(np.asarray(t_vo.leaf_value),
                               np.asarray(t_ser.leaf_value),
                               rtol=0, atol=1e-6)
    assert int(t_vo.hist_passes) == int(t_ser.hist_passes)


def test_voting_spec_ramp_rides_the_vote():
    """Spec ramp + voting: provisional subsample passes vote too, and
    the committed tree still equals serial spec growth bit-for-bit on
    the quantized path (2k >= F)."""
    mesh = get_mesh(NSH)
    ax = mesh.axis_names[0]
    data = _mk_data(seed=3)
    args = data + _meta_args()
    t_ser = _serial_call(_mk_grow(None, spec=True), data)
    t_vo = _wrap_dp(_mk_grow(WaveVotingStrategy(ax, nshards=NSH, top_k=3),
                             spec=True),
                    mesh, ax)(*args)
    for name in BITWISE:
        np.testing.assert_array_equal(
            np.asarray(getattr(t_vo, name)),
            np.asarray(getattr(t_ser, name)), err_msg=name)
    assert int(t_vo.hist_passes) == int(t_ser.hist_passes)


def test_voting_small_topk_still_grows():
    """top_k=1 (2k=2 < F=6): real feature filtering.  The tree is no
    longer guaranteed identical to serial, but it must be a valid full
    growth of the same size whose splits all hit voted features."""
    mesh = get_mesh(NSH)
    ax = mesh.axis_names[0]
    data = _mk_data(seed=5)
    args = data + _meta_args()
    t = _wrap_dp(_mk_grow(WaveVotingStrategy(ax, nshards=NSH, top_k=1)),
                 mesh, ax)(*args)
    assert int(t.num_leaves) == LEAVES
    sf = np.asarray(t.split_feature)[:int(t.num_leaves) - 1]
    assert ((sf >= 0) & (sf < F)).all()


# ---------------------------------------------------------------------------
# Traced-program shape: the vote's id all_gather per merge site and the
# voted psum never as large as the full histogram batch at 2k < F.
# ---------------------------------------------------------------------------

from lightgbm_tpu.analysis.ir import collect_collectives as _collectives_of


def test_voting_traced_collectives_shape():
    """At top_k=1 the voted psum operand is (c, 2, B, 3) against the
    allreduce baseline's (c, F, B, 3): per-leaf bytes ratio == 2k/F —
    the ISSUE's cross-host budget — and an all_gather per merge site
    carries the O(W*k) ids."""
    mesh = get_mesh(NSH)
    ax = mesh.axis_names[0]
    args = _mk_data() + _meta_args()
    g_vo = _wrap_dp(_mk_grow(WaveVotingStrategy(ax, nshards=NSH, top_k=1)),
                    mesh, ax)
    g_ar = _wrap_dp(_mk_grow(WaveDPStrategy(ax, nshards=NSH)), mesh, ax)
    coll_vo = _collectives_of(lambda *a: g_vo(*a), *args)
    coll_ar = _collectives_of(lambda *a: g_ar(*a), *args)

    ag_names = [k for k in coll_vo if "all_gather" in k]
    assert ag_names, f"no all_gather traced: {sorted(coll_vo)}"
    # one id gather per histogram-merge site (root + body + endgame)
    n_ag = sum(len(coll_vo[k]) for k in ag_names)
    assert n_ag == 3, (n_ag, coll_vo)
    assert not any("all_gather" in k for k in coll_ar), coll_ar

    # full hist batch per leaf: F*B*3; voted: min(2k,F)*B*3 = 2*B*3
    full_leaf = F * B * 3
    voted_leaf = 2 * B * 3
    big_ar = [s for s in coll_ar.get("psum", []) if s >= WAVE * full_leaf]
    assert big_ar, "allreduce baseline lost its histogram psum?"
    # the voting program's biggest psum is the voted batch — per-leaf
    # exactly (2k/F) of the full merge, never a full-F histogram
    vo_psums = coll_vo.get("psum", [])
    assert vo_psums
    assert max(vo_psums) <= max(2 * WAVE, LEAVES) * voted_leaf, vo_psums
    assert not [s for s in vo_psums if s >= WAVE * full_leaf], vo_psums


def test_modeled_pass_bytes_ratio_and_auto_rule():
    """The byte model the auto-selection + CI artifact share: voting's
    total undercuts reduce-scatter once F is wide, ratio == 2k/F, and
    voting_favored flips on exactly when modeled cross-host bytes drop
    below the DP path's (and never below the world-size floor)."""
    m = modeled_pass_bytes(num_features=512, bins=64, top_k=16, world=64)
    assert m["hosts"] == 8
    assert m["voted_full_ratio"] == pytest.approx(32 / 512)
    assert m["voting"]["cross_host"] < m["reduce_scatter"]["cross_host"]
    assert voting_favored(512, 64, 16, 64)
    # narrow F: the vote's id gather overhead loses
    assert not voting_favored(4, 64, 20, 64)
    # below the world floor voting never engages
    assert not voting_favored(512, 64, 16, 2)


# ---------------------------------------------------------------------------
# Public API: tree_learner=voting parity, typed quantized error, auto
# ---------------------------------------------------------------------------

SMALL = {"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1,
         "tree_grow_mode": "wave"}


def test_voting_api_matches_data_quantized():
    """lgb.train with tree_learner=voting (wave path, default top_k=20
    >= F so selection is identity) against tree_learner=data on the
    quantized path: the sharded learners must agree (stochastic rounding
    folds the shard index into the key, so they agree with EACH OTHER
    exactly, not with unsharded serial rounding — float voting-vs-serial
    parity is proven bitwise at grower level above)."""
    rng = np.random.RandomState(11)
    n = 704
    X = rng.randn(n, 6)
    y = ((X[:, 0] + 0.5 * X[:, 1]) > 0).astype(np.float64)
    pq = {**SMALL, "objective": "binary", "use_quantized_grad": True}
    dp_q = lgb.train({**pq, "tree_learner": "data"},
                     lgb.Dataset(X, y), 4).predict(X)
    vo_q = lgb.train({**pq, "tree_learner": "voting"},
                     lgb.Dataset(X, y), 4).predict(X)
    np.testing.assert_allclose(vo_q, dp_q, atol=2e-6,
                               err_msg="voting != data (quantized)")


def test_voting_quantized_masked_path_raises_typed():
    """use_quantized_grad on the masked (partition-mode) voting path:
    loud typed error, not the old silent downgrade."""
    rng = np.random.RandomState(7)
    X = rng.randn(256, 4)
    y = (X[:, 0] > 0).astype(np.float64)
    p = {**SMALL, "objective": "binary", "tree_learner": "voting",
         "tree_grow_mode": "partition", "use_quantized_grad": True}
    with pytest.raises(QuantizedGradUnsupportedError):
        lgb.train(p, lgb.Dataset(X, y), 2)


def test_tree_learner_auto_resolves_and_records():
    """tree_learner=auto trains and the model text records the RESOLVED
    learner (never the literal 'auto')."""
    rng = np.random.RandomState(3)
    X = rng.randn(512, 6)
    y = (X[:, 0] - X[:, 2] > 0).astype(np.float64)
    p = {**SMALL, "objective": "binary", "tree_learner": "auto"}
    bst = lgb.train(p, lgb.Dataset(X, y), 3)
    txt = bst.model_to_string()
    line = [ln for ln in txt.splitlines()
            if ln.startswith("[tree_learner:")]
    assert line and "auto" not in line[0], line
    serial = lgb.train({**SMALL, "objective": "binary"},
                       lgb.Dataset(X, y), 3).predict(X)
    np.testing.assert_allclose(bst.predict(X), serial, atol=2e-5)
