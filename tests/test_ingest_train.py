"""Chunked streamed training (tpu_ingest_mode=chunked): bit-identity to
in-core training on the quantized matrix, f32 parity, chunk-boundary
shapes, resume-mid-stream via the PR-6 checkpoint path, envelope
errors and GOSS thinning."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ingest import (ArraySource, StreamedDataset,
                                 StreamedEnvelopeError, train_streamed)


def _data(n=3001, f=6, seed=7, task="binary"):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    raw = X[:, 0] + 0.5 * X[:, 1] + rng.randn(n) * 0.5
    if task == "binary":
        y = (raw > 0).astype(np.float64)
    elif task == "mc":
        y = np.digitize(raw, [-0.5, 0.5]).astype(np.float64)
    else:
        y = raw
    return X, y


# the chunked grower's envelope, pinned identically for both runs: wave
# grower, taper tail (endgame/spec off), deterministic rounding
_PIN = {"verbosity": -1, "num_leaves": 15, "learning_rate": 0.2,
        "max_bin": 63, "min_data_in_leaf": 5, "enable_bundle": False,
        "seed": 3, "tree_grow_mode": "wave", "tpu_exact_endgame": False,
        "tpu_speculative_ramp": False, "stochastic_rounding": False}


def _both(params, X, y, rounds=6, chunk_rows=512):
    ds = lgb.Dataset(X.copy(), label=y.copy())
    b1 = lgb.train(params, ds, num_boost_round=rounds)
    sd = StreamedDataset(ArraySource(X, y, chunk_rows=chunk_rows),
                         params=params)
    b2 = train_streamed(params, sd, num_boost_round=rounds)
    return b1, b2


# ---------------------------------------------------------------------------
# bit-identity: quantized matrix (int32 histogram sums are exact under
# any chunk partition, so streamed == in-core bit for bit)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,extra", [
    # W=1 reproduces the TRUE sequential best-first order (wave.py docs)
    ("serial_order", {"use_quantized_grad": True, "tpu_wave_size": 1}),
    ("wave", {"use_quantized_grad": True, "tpu_wave_size": 4}),
    ("quantized_default_wave", {"use_quantized_grad": True}),
    ("quantized_16bins", {"use_quantized_grad": True,
                          "num_grad_quant_bins": 16, "tpu_wave_size": 4}),
])
def test_chunked_bit_identity(name, extra):
    X, y = _data()
    p = dict(_PIN, objective="binary")
    p.update(extra)
    b1, b2 = _both(p, X, y)
    assert b1.model_to_string() == b2.model_to_string(), name
    assert np.array_equal(b1.predict(X[:64]), b2.predict(X[:64]))


def test_chunked_bit_identity_regression():
    X, y = _data(task="regression")
    p = dict(_PIN, objective="regression", use_quantized_grad=True,
             tpu_wave_size=4)
    b1, b2 = _both(p, X, y)
    assert b1.model_to_string() == b2.model_to_string()


def test_chunked_matches_dp_scatter_structure():
    """The DP rung's BIT-identity is covered on the hbm route
    (test_ingest.py::test_hbm_route_bit_identity[dp_scatter] — same
    program, streamed ingestion).  Here the CHUNKED trainer is compared
    against an in-core DP-wave reduce-scatter run: identical tree
    structures and f32-tolerance outputs (the in-core DP path's winner
    exchange re-derives recorded gain/weight fields from dequantized
    payloads, which drifts the last f32 ulps vs the serial grower on
    this config — so bitwise equality is not the right bar between the
    two in-core paths either)."""
    import jax
    if jax.device_count() < 8:
        pytest.skip("needs 8 (virtual) devices")
    X, y = _data(4096, 6)
    p = dict(_PIN, objective="binary", use_quantized_grad=True,
             tpu_wave_size=4)
    dp = dict(p, tree_learner="data", num_machines=8, num_devices=8,
              tpu_dp_hist_scatter=True)
    ds = lgb.Dataset(X.copy(), label=y.copy())
    b_dp = lgb.train(dp, ds, num_boost_round=4)
    sd = StreamedDataset(ArraySource(X, y, chunk_rows=512), params=p)
    b_st = train_streamed(p, sd, num_boost_round=4)
    s1 = [(t.split_feature.tolist(), t.threshold_bin.tolist())
          for t in b_dp._gbdt.models]
    s2 = [(t.split_feature.tolist(), t.threshold_bin.tolist())
          for t in b_st._gbdt.models]
    assert s1 == s2
    assert np.allclose(b_dp.predict(X), b_st.predict(X), atol=1e-5)


# ---------------------------------------------------------------------------
# f32 path: same structure, f32-tolerance outputs
# ---------------------------------------------------------------------------

def test_chunked_f32_structure_and_tolerance():
    X, y = _data()
    p = dict(_PIN, objective="binary", tpu_wave_size=4)
    b1, b2 = _both(p, X, y)
    s1 = [t.split_feature.tolist() for t in b1._gbdt.models]
    s2 = [t.split_feature.tolist() for t in b2._gbdt.models]
    assert s1 == s2
    assert np.allclose(b1.predict(X), b2.predict(X), atol=1e-5)


def test_chunked_bit_identity_pallas_interpret():
    """The Pallas chunk path (the on-TPU configuration: fused row-update
    kernel + q8 leaf-channel kernel per chunk) in interpret mode, vs the
    in-core pallas-interpret run — int32 accumulation stays exact across
    the kernel boundary too."""
    X, y = _data(8192, 4, seed=5)
    p = dict(_PIN, objective="binary", use_quantized_grad=True,
             num_leaves=7, max_bin=15, tpu_wave_size=2,
             tpu_histogram_impl="pallas", tpu_hist_pack4=False)
    b1, b2 = _both(p, X, y, rounds=2, chunk_rows=4096)
    assert b1.model_to_string() == b2.model_to_string()


# ---------------------------------------------------------------------------
# chunk-boundary shapes
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [2048, 2049])
def test_chunked_boundary_shapes(n):
    X, y = _data(n, 5, seed=11)
    p = dict(_PIN, objective="binary", use_quantized_grad=True,
             tpu_wave_size=4)
    b1, b2 = _both(p, X, y, rounds=4)
    assert b1.model_to_string() == b2.model_to_string()


# ---------------------------------------------------------------------------
# engine.train routing + envelope errors
# ---------------------------------------------------------------------------

def test_engine_routes_chunked_mode():
    X, y = _data(2048, 5, seed=2)
    p = dict(_PIN, objective="binary", use_quantized_grad=True,
             tpu_wave_size=4)
    sd = StreamedDataset(ArraySource(X, y, chunk_rows=512),
                         params=dict(p, tpu_ingest_mode="chunked"))
    bst = lgb.train(dict(p, tpu_ingest_mode="chunked"), sd,
                    num_boost_round=3)
    ds = lgb.Dataset(X.copy(), label=y.copy())
    t1 = lgb.train(p, ds, num_boost_round=3).model_to_string()
    # tpu_ingest_mode is excluded from the params dump, so the streamed
    # route's model text matches the in-core twin byte for byte
    assert bst.model_to_string() == t1


def test_engine_chunked_rejects_callbacks():
    X, y = _data(2048, 5, seed=2)
    p = dict(_PIN, objective="binary", tpu_ingest_mode="chunked")
    sd = StreamedDataset(ArraySource(X, y, chunk_rows=512), params=p)
    with pytest.raises(ValueError, match="callbacks"):
        lgb.train(p, sd, num_boost_round=2, callbacks=[lambda env: None])


def test_envelope_errors():
    X, y = _data(2048, 5, seed=2)
    sd = StreamedDataset(ArraySource(X, y, chunk_rows=512),
                         params={"verbosity": -1})
    with pytest.raises(StreamedEnvelopeError, match="objective"):
        train_streamed(dict(_PIN, objective="poisson"), sd, 2)
    with pytest.raises(StreamedEnvelopeError, match="monotone"):
        train_streamed(dict(_PIN, objective="binary",
                            monotone_constraints=[1, 0, 0, 0, 0]), sd, 2)
    # ranking stays in-core only (query segments are not chunk-sliceable);
    # multiclassova's per-class label weights likewise
    with pytest.raises(StreamedEnvelopeError, match="objective"):
        train_streamed({"objective": "lambdarank", "verbosity": -1}, sd, 2)
    with pytest.raises(StreamedEnvelopeError, match="objective"):
        train_streamed({"objective": "multiclassova", "num_class": 3,
                        "verbosity": -1}, sd, 2)
    # dart batches now, but not with checkpointing (drop weights are not
    # reconstructible from model text)
    with pytest.raises(StreamedEnvelopeError, match="checkpoint"):
        train_streamed(dict(_PIN, objective="binary", boosting="dart",
                            snapshot_freq=1), sd, 2)


# ---------------------------------------------------------------------------
# bagging / feature_fraction parity, GOSS thinning
# ---------------------------------------------------------------------------

def test_chunked_bagging_feature_fraction_identity():
    X, y = _data()
    p = dict(_PIN, objective="binary", use_quantized_grad=True,
             tpu_wave_size=4, bagging_fraction=0.7, bagging_freq=2,
             feature_fraction=0.8)
    b1, b2 = _both(p, X, y)
    assert b1.model_to_string() == b2.model_to_string()


def test_chunked_goss_bit_identity():
    """GOSS rides the SHARED host sampler (models.gbdt.goss_sample_np):
    the streamed run thins exactly the rows the in-core run thins,
    warmup included, so the quantized model text matches byte for
    byte."""
    X, y = _data(4096, 6)
    p = dict(_PIN, objective="binary", boosting="goss",
             use_quantized_grad=True, tpu_wave_size=4,
             learning_rate=0.5, top_rate=0.2, other_rate=0.1)
    b1, b2 = _both(p, X, y)
    assert b1.model_to_string() == b2.model_to_string()
    pred = b2.predict(X)
    acc = float(((pred > 0.5) == (y > 0)).mean())
    assert acc > 0.7


@pytest.mark.parametrize("extra", [
    {"uniform_drop": True},
    {"uniform_drop": False, "xgboost_dart_mode": True, "max_drop": 3},
])
def test_chunked_dart_bit_identity(extra):
    """DART's drop/Normalize bookkeeping replayed host-side (same
    (drop_seed, iteration) streams, f32 axpys) == the in-core device
    run, in both drop modes."""
    X, y = _data()
    p = dict(_PIN, objective="binary", boosting="dart",
             use_quantized_grad=True, tpu_wave_size=4, drop_rate=0.5,
             drop_seed=9)
    p.update(extra)
    b1, b2 = _both(p, X, y, rounds=8)
    assert b1.model_to_string() == b2.model_to_string()
    assert np.array_equal(b1.predict(X[:64]), b2.predict(X[:64]))


def test_chunked_multiclass_bit_identity():
    """Softmax gradients are rowwise -> chunk-sliceable; the K-tree
    iteration grid matches the in-core class loop byte for byte."""
    X, y = _data(task="mc")
    p = dict(_PIN, objective="multiclass", num_class=3,
             use_quantized_grad=True, tpu_wave_size=4)
    b1, b2 = _both(p, X, y)
    assert b1.model_to_string() == b2.model_to_string()
    assert np.array_equal(b1.predict(X[:64]), b2.predict(X[:64]))


@pytest.mark.slow
def test_chunked_multiclass_bagging_feature_fraction_identity():
    X, y = _data(task="mc")
    p = dict(_PIN, objective="multiclass", num_class=3,
             use_quantized_grad=True, tpu_wave_size=4,
             bagging_fraction=0.7, bagging_freq=2, feature_fraction=0.8)
    b1, b2 = _both(p, X, y)
    assert b1.model_to_string() == b2.model_to_string()


# ---------------------------------------------------------------------------
# streamed validation + early stopping: same stop round as in-core
# ---------------------------------------------------------------------------

def _split(X, y, cut=3000):
    return X[:cut], y[:cut], X[cut:], y[cut:]


@pytest.mark.slow
def test_chunked_early_stop_same_round():
    X, y = _data(4096, 6)
    Xtr, ytr, Xv, yv = _split(X, y)
    p = dict(_PIN, objective="binary", use_quantized_grad=True,
             tpu_wave_size=4, early_stopping_round=3)
    ds = lgb.Dataset(Xtr.copy(), label=ytr.copy())
    dv = lgb.Dataset(Xv.copy(), label=yv.copy(), reference=ds)
    b1 = lgb.train(p, ds, num_boost_round=60, valid_sets=[dv],
                   valid_names=["va"])
    pc = dict(p, tpu_ingest_mode="chunked")
    sd = StreamedDataset(ArraySource(Xtr, ytr, chunk_rows=512), params=pc)
    sv = StreamedDataset(ArraySource(Xv, yv, chunk_rows=512), params=pc)
    b2 = lgb.train(pc, sd, num_boost_round=60, valid_sets=[sv],
                   valid_names=["va"])
    assert b1.best_iteration == b2.best_iteration
    # the streamed valid walk sees the same f32 scores -> same metric
    assert b1.best_score == b2.best_score
    assert b1.model_to_string() == b2.model_to_string()


@pytest.mark.slow
def test_chunked_early_stop_in_core_valid():
    """An in-core Dataset as the valid of a chunked streamed run (mixed
    types): binned against the streamed train's mappers via reference."""
    X, y = _data(4096, 6)
    Xtr, ytr, Xv, yv = _split(X, y)
    p = dict(_PIN, objective="binary", use_quantized_grad=True,
             tpu_wave_size=4, early_stopping_round=3,
             tpu_ingest_mode="chunked")
    sd = StreamedDataset(ArraySource(Xtr, ytr, chunk_rows=512), params=p)
    dv = lgb.Dataset(Xv.copy(), label=yv.copy())
    b = lgb.train(p, sd, num_boost_round=60, valid_sets=[dv])
    assert b.best_iteration > 0
    assert "valid_0" in b.best_score


@pytest.mark.slow
def test_chunked_dart_early_stop_same_round():
    X, y = _data(4096, 6)
    Xtr, ytr, Xv, yv = _split(X, y)
    p = dict(_PIN, objective="binary", boosting="dart", drop_rate=0.5,
             drop_seed=9, use_quantized_grad=True, tpu_wave_size=4,
             early_stopping_round=4)
    ds = lgb.Dataset(Xtr.copy(), label=ytr.copy())
    dv = lgb.Dataset(Xv.copy(), label=yv.copy(), reference=ds)
    b1 = lgb.train(p, ds, num_boost_round=25, valid_sets=[dv])
    pc = dict(p, tpu_ingest_mode="chunked")
    sd = StreamedDataset(ArraySource(Xtr, ytr, chunk_rows=512), params=pc)
    sv = StreamedDataset(ArraySource(Xv, yv, chunk_rows=512), params=pc)
    b2 = lgb.train(pc, sd, num_boost_round=25, valid_sets=[sv])
    assert b1.best_iteration == b2.best_iteration
    assert b1.model_to_string() == b2.model_to_string()


@pytest.mark.slow
def test_chunked_multiclass_goss_early_stop_same_round():
    X, y = _data(4096, 6, task="mc")
    Xtr, ytr, Xv, yv = _split(X, y)
    p = dict(_PIN, objective="multiclass", num_class=3, boosting="goss",
             learning_rate=0.5, top_rate=0.2, other_rate=0.1,
             use_quantized_grad=True, tpu_wave_size=4,
             early_stopping_round=3)
    ds = lgb.Dataset(Xtr.copy(), label=ytr.copy())
    dv = lgb.Dataset(Xv.copy(), label=yv.copy(), reference=ds)
    b1 = lgb.train(p, ds, num_boost_round=40, valid_sets=[dv])
    pc = dict(p, tpu_ingest_mode="chunked")
    sd = StreamedDataset(ArraySource(Xtr, ytr, chunk_rows=512), params=pc)
    sv = StreamedDataset(ArraySource(Xv, yv, chunk_rows=512), params=pc)
    b2 = lgb.train(pc, sd, num_boost_round=40, valid_sets=[sv])
    assert b1.best_iteration == b2.best_iteration
    assert b1.model_to_string() == b2.model_to_string()


# ---------------------------------------------------------------------------
# resume-mid-stream via the PR-6 checkpoint path
# ---------------------------------------------------------------------------

def test_resume_mid_stream_bit_identical(tmp_path):
    X, y = _data()
    # checkpoint cadence params stay IDENTICAL between the uninterrupted
    # and the resumed run (only resume/checkpoint_dir are excluded from
    # the model-text params dump)
    p = dict(_PIN, objective="binary", use_quantized_grad=True,
             tpu_wave_size=4, snapshot_freq=2,
             checkpoint_dir=str(tmp_path / "ck_full"))
    # uninterrupted run
    sd = StreamedDataset(ArraySource(X, y, chunk_rows=512), params=p)
    full = train_streamed(p, sd, num_boost_round=8).model_to_string()
    # interrupted at iteration 4, resumed from the bundle: the bundle's
    # fingerprint is the streamed crc and must match the re-streamed
    # dataset across the "restart"
    ck = dict(p, checkpoint_dir=str(tmp_path / "ck"))
    sd1 = StreamedDataset(ArraySource(X, y, chunk_rows=512), params=ck)
    train_streamed(ck, sd1, num_boost_round=4)
    sd2 = StreamedDataset(ArraySource(X, y, chunk_rows=512),
                          params=dict(ck, resume="latest"))
    resumed = train_streamed(dict(ck, resume="latest"), sd2,
                             num_boost_round=8)
    assert resumed.model_to_string() == full
    assert sd1.fingerprint() == sd2.fingerprint()


def test_resume_rejects_fingerprint_mismatch(tmp_path):
    from lightgbm_tpu.resilience.checkpoint import CheckpointError
    X, y = _data(2048, 5, seed=2)
    p = dict(_PIN, objective="binary", use_quantized_grad=True,
             checkpoint_dir=str(tmp_path / "ck"), snapshot_freq=1)
    sd = StreamedDataset(ArraySource(X, y, chunk_rows=512), params=p)
    train_streamed(p, sd, num_boost_round=2)
    X2 = X.copy()
    X2[0, 0] += 1.0  # different data -> different streamed crc
    sd2 = StreamedDataset(ArraySource(X2, y, chunk_rows=512),
                          params=dict(p, resume="latest"))
    with pytest.raises(CheckpointError, match="fingerprint|match"):
        train_streamed(dict(p, resume="latest"), sd2, num_boost_round=4)
