"""SPMD-safety lint (ISSUE 11 tentpole b, lightgbm_tpu/analysis/spmd.py).

Contract under test:
  * ``collective_trace`` extracts the ordered per-axis collective
    schedule of a program;
  * a planted divergent-collective conditional arm fires with a
    site-named diagnostic (the static cross-host deadlock), identical
    arms stay quiet;
  * a planted shard_map mesh/spec mismatch fires;
  * the real DP configs pass both SPMD rules, and ALL existing
    collective contracts hold when checked at W=4, W=8 and W=64 (the
    last trace-only over an AbstractMesh);
  * the lint-trace report records the jax version and device/mesh
    environment it traced under (8-virtual-device runs distinguishable
    from real-chip runs).
"""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from lightgbm_tpu.analysis import ir, lint, spmd
from lightgbm_tpu.analysis.lint import ALL_RULES
from lightgbm_tpu.analysis.rules import TraceUnit, run_rules
from lightgbm_tpu.parallel.mesh import get_mesh, shard_map_compat
from lightgbm_tpu.telemetry import _config as tele_config


def _mesh8(axis_name="workers"):
    return get_mesh(8, axis_name)


# ---------------------------------------------------------------------------
# collective_trace
# ---------------------------------------------------------------------------

def test_collective_trace_orders_ops():
    mesh = _mesh8()
    ax = mesh.axis_names[0]

    def f(x):
        a = jax.lax.psum(x, ax)
        b = jax.lax.pmax(a, ax)
        return jax.lax.psum(b * 2, ax)

    fn = shard_map_compat(f, mesh=mesh, in_specs=(P(ax),), out_specs=P(ax))
    seq = spmd.collective_trace(ir.trace(fn, jnp.ones((16, 4))))
    assert [op[0] for op in seq] == ["psum", "pmax", "psum"]
    assert all("workers" in op[1] for op in seq)
    assert seq[0][2] == (2, 4)          # per-shard wire shape


# ---------------------------------------------------------------------------
# collective-order: planted divergent arms
# ---------------------------------------------------------------------------

def _cond_program(divergent: bool):
    mesh = _mesh8()
    ax = mesh.axis_names[0]

    def arm_with_psum(v):
        return jax.lax.psum(v, ax)

    def arm_identity(v):
        return v * 2.0

    def f(x):
        pred = x.sum() > 0
        other = arm_identity if divergent else arm_with_psum
        return jax.lax.cond(pred, arm_with_psum, other, x)

    return shard_map_compat(f, mesh=mesh, in_specs=(P(ax),),
                            out_specs=P(ax) if divergent else P())


def test_divergent_cond_arm_fires():
    fn = _cond_program(divergent=True)
    unit = TraceUnit(name="planted",
                     jaxpr=ir.trace(fn, jnp.ones((16,))))
    vs = spmd.CollectiveOrderRule().check(unit)
    assert vs, "divergent collective arms not flagged"
    assert "DIVERGENT" in vs[0].message and "deadlock" in vs[0].message
    assert "psum" in vs[0].message and "cond" in vs[0].site


def test_identical_cond_arms_quiet():
    fn = _cond_program(divergent=False)
    unit = TraceUnit(name="ok", jaxpr=ir.trace(fn, jnp.ones((16,))))
    assert spmd.CollectiveOrderRule().check(unit) == []


# ---------------------------------------------------------------------------
# sharding-consistency: planted mesh/spec mismatch
# ---------------------------------------------------------------------------

def test_shard_map_mesh_mismatch_fires():
    """A program sharded over axis 'model' while the config declares a
    ('workers',) mesh — the launcher would never build it."""
    mesh = get_mesh(4, axis_name="model")
    fn = shard_map_compat(lambda x: jax.lax.psum(x, "model"), mesh=mesh,
                          in_specs=(P("model"),), out_specs=P())
    unit = TraceUnit(name="planted",
                     jaxpr=ir.trace(fn, jnp.ones((8, 2))),
                     ctx={"mesh_axes": ("workers",)})
    vs = spmd.ShardingConsistencyRule().check(unit)
    assert vs, "mesh-axis mismatch not flagged"
    assert "('model',)" in vs[0].message and "('workers',)" in vs[0].message
    assert "shard_map" in vs[0].site


def test_shard_map_matching_mesh_quiet():
    mesh = _mesh8()
    ax = mesh.axis_names[0]
    fn = shard_map_compat(lambda x: jax.lax.psum(x, ax), mesh=mesh,
                          in_specs=(P(ax),), out_specs=P())
    unit = TraceUnit(name="ok", jaxpr=ir.trace(fn, jnp.ones((16,))),
                     ctx={"mesh_axes": ("workers",)})
    assert spmd.ShardingConsistencyRule().check(unit) == []


# ---------------------------------------------------------------------------
# the real programs, across world sizes
# ---------------------------------------------------------------------------

def test_dp_unit_passes_spmd_rules():
    unit = lint.build_unit("dp_scatter")
    vs = [v for r in spmd.SPMD_RULES for v in r.check(unit)]
    assert vs == [], vs


@pytest.mark.skipif(not tele_config.enabled(),
                    reason="telemetry disabled via LGBM_TPU_TELEMETRY=0")
@pytest.mark.parametrize("w", [4, 64])
def test_contracts_hold_at_world_size(w):
    """The re-parameterized contracts: the same declarations pass at a
    real W=4 submesh and a trace-only W=64 AbstractMesh (W=8 is the
    whole-suite default exercised by test_analysis.py)."""
    for cfg in ("dp_scatter", "spec_ramp", "voting"):
        unit = lint.build_unit(cfg, nshards=w)
        assert unit.ctx["world_size"] == w
        vs = run_rules([unit], rules=ALL_RULES)
        assert vs == [], (w, cfg, vs)
        rs = unit.collectives.get("data_parallel/wave/hist_reduce_scatter")
        if rs is not None:
            assert rs["count"] == (3 if cfg == "dp_scatter" else 5)
        if cfg == "voting":
            # PV-Tree wire shape: an id all_gather and a voted-slice
            # psum per merge site, with the modeled DCN split bounded
            # by the contracts the rules just enforced
            ag = unit.collectives["voting_parallel/wave/vote_allgather"]
            vp = unit.collectives["voting_parallel/wave/voted_hist_psum"]
            assert ag["count"] == vp["count"] == 3


def test_w64_traces_over_abstract_mesh():
    """W past the attached device count must still produce a full
    program trace (shapes + collectives exact, nothing executable)."""
    mesh, abstract = lint._trace_mesh(64)
    assert abstract, "expected an AbstractMesh for W=64 on this host"
    unit = lint.build_unit("dp_scatter", nshards=64)
    shard_maps = [i for i in ir.iter_eqns(unit.jaxpr)
                  if i.prim == "shard_map"]
    assert shard_maps
    # the traced per-shard row count reflects the 64-way split
    body = shard_maps[0].eqn.params["jaxpr"]
    row_args = [tuple(v.aval.shape) for v in body.invars
                if getattr(v.aval, "ndim", 0) == 1]
    assert (4096,) in row_args          # 64*4096 global / 64 shards


# ---------------------------------------------------------------------------
# report environment (the 'which env traced this?' fix)
# ---------------------------------------------------------------------------

def test_lint_trace_report_records_environment():
    report = lint.run_lint(["serve"])
    env = report["environment"]
    assert env["jax_version"] == jax.__version__
    assert env["device_count"] >= 1
    assert env["backend"] in ("cpu", "tpu", "gpu")
    assert isinstance(env["virtual_devices"], bool)
    # the SPMD rules are part of the shipped matrix
    assert "collective-order" in report["rules"]
    assert "sharding-consistency" in report["rules"]
