"""Continuous-learning lane tests (publish/ + serve delta path):

* delta wire format + crash-safe journal: crc/fingerprint guards, torn
  tails, gaps, compaction, the restart re-anchor rule;
* trainer-side publisher: cadence, completion flush, journal head ==
  ``save_model`` at the same iteration;
* incremental serving refresh: ``ModelRegistry.apply_delta`` builds a
  predictor bitwise-identical to a cold full load at every published
  round, across the dense/walk compilers and the quantized-leaf path,
  with ZERO dense recompiles while the append fits inside the
  shard-padding envelope (signature-cache asserted);
* the eviction guard and the init_model+resume_from typed error;
* the HTTP surface (``POST /models/<name>/delta``) and, slow/chaos, a
  fleet live-refresh run with a worker killed mid-publish: every
  response comes from a published round — never a torn mix — and the
  ``fleet/model_staleness`` SLO is re-met after recovery.
"""

import base64
import http.client
import json
import os
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.models.model_text import model_to_string
from lightgbm_tpu.publish.delta import (DeltaChainError, DeltaJournal,
                                        DeltaRecord, chain_fingerprint,
                                        fingerprint_text)
from lightgbm_tpu.publish.publisher import DeltaPublisher
from lightgbm_tpu.publish.subscriber import fold_chain, load_journal
from lightgbm_tpu.serve.registry import ModelInUseError, ModelRegistry

SMALL = {"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1}

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _train(binary_data, rounds, publish_dir=None, every=1, **extra):
    X, y = binary_data
    p = {**SMALL, "objective": "binary", **extra}
    if publish_dir is not None:
        p["publish_dir"] = str(publish_dir)
        p["publish_every"] = every
    return lgb.train(p, lgb.Dataset(X, y, params=p), rounds)


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------

def _record(payload="tree text", base_round=1, round=2, parent_fp=None):
    parent = parent_fp if parent_fp is not None \
        else fingerprint_text("base")
    return DeltaRecord(base_round=base_round, round=round,
                       parent_fp=parent,
                       fp=chain_fingerprint(parent, payload),
                       num_tree_per_iteration=1, payload=payload)


def test_record_wire_roundtrip():
    rec = _record(payload="fragment é text")
    back = DeltaRecord.from_bytes(rec.to_bytes())
    assert back == rec


def test_record_wire_guards():
    rec = _record()
    raw = rec.to_bytes()
    with pytest.raises(DeltaChainError, match="truncated"):
        DeltaRecord.from_bytes(raw[:10])
    with pytest.raises(DeltaChainError, match="magic"):
        DeltaRecord.from_bytes(b"X" * len(raw))
    with pytest.raises(DeltaChainError, match="torn"):
        DeltaRecord.from_bytes(raw[:-3])
    flipped = bytearray(raw)
    flipped[-1] ^= 0xFF            # payload bit flip -> crc mismatch
    with pytest.raises(DeltaChainError, match="crc"):
        DeltaRecord.from_bytes(bytes(flipped))
    # a record whose payload does not hash to its declared fp
    forged = DeltaRecord(base_round=1, round=2, parent_fp=rec.parent_fp,
                         fp=rec.fp, num_tree_per_iteration=1,
                         payload="tampered")
    with pytest.raises(DeltaChainError, match="fingerprint"):
        DeltaRecord.from_bytes(forged.to_bytes())
    bad_rounds = DeltaRecord(base_round=3, round=3,
                             parent_fp=rec.parent_fp,
                             fp=chain_fingerprint(rec.parent_fp, "x"),
                             num_tree_per_iteration=1, payload="x")
    with pytest.raises(DeltaChainError, match="non-monotonic"):
        DeltaRecord.from_bytes(bad_rounds.to_bytes())


# ---------------------------------------------------------------------------
# journal
# ---------------------------------------------------------------------------

def test_journal_chain_and_replay(tmp_path):
    j = DeltaJournal(str(tmp_path / "j"))
    assert j.head() is None
    fp0 = j.write_base("base text", 2)
    r3 = j.append_delta("round 3 trees", 3)
    r4 = j.append_delta("round 4 trees", 4)
    assert r3.parent_fp == fp0 and r4.parent_fp == r3.fp
    h = j.head()
    assert h is not None and h.round == 4 and h.fp == r4.fp
    base_text, base_round, records = j.chain()
    assert base_text == "base text" and base_round == 2
    assert [r.round for r in records] == [3, 4]
    assert [r.round for r in j.records_after(3)] == [4]
    path, rnd = j.base_entry()
    assert rnd == 2 and open(path).read() == "base text"


def test_journal_append_guards(tmp_path):
    j = DeltaJournal(str(tmp_path / "j"))
    with pytest.raises(DeltaChainError, match="empty"):
        j.append_delta("x", 1)
    j.write_base("base", 3)
    with pytest.raises(DeltaChainError, match="non-monotonic"):
        j.append_delta("x", 3)


def test_journal_torn_tail_falls_back(tmp_path):
    """A crash mid-append can leave a torn tail entry; ``head`` must
    fall back to the newest intact entry instead of failing."""
    j = DeltaJournal(str(tmp_path / "j"))
    j.write_base("base", 1)
    rec = j.append_delta("round 2", 2)
    torn = os.path.join(j.directory, "DELTA.00002")
    with open(torn, "wb") as fh:
        fh.write(rec.to_bytes()[:-5])     # torn write
    h = j.head()
    assert h is not None and h.kind == "base" and h.round == 1


def test_journal_gap_detected(tmp_path):
    j = DeltaJournal(str(tmp_path / "j"))
    j.write_base("base", 1)
    j.append_delta("round 2", 2)
    j.append_delta("round 3", 3)
    os.unlink(os.path.join(j.directory, "DELTA.00002"))
    with pytest.raises(DeltaChainError, match="chain gap"):
        j.chain()


def test_journal_compact_prunes(tmp_path):
    j = DeltaJournal(str(tmp_path / "j"))
    j.write_base("base", 1)
    j.append_delta("round 2", 2)
    j.append_delta("round 3", 3)
    assert j.chain_length() == 2
    j.compact("folded text", 3)
    assert j.chain_length() == 0
    names = sorted(os.listdir(j.directory))
    assert names == ["BASE.00003.txt", "HEAD"]
    base_text, base_round, records = j.chain()
    assert base_text == "folded text" and base_round == 3 and not records


# ---------------------------------------------------------------------------
# publisher (trainer side)
# ---------------------------------------------------------------------------

def test_publisher_cadence_and_journal_parity(tmp_path, binary_data):
    X, y = binary_data
    jdir = tmp_path / "journal"
    bst = _train(binary_data, 6, publish_dir=jdir, every=2)
    names = sorted(n for n in os.listdir(jdir) if n != "HEAD")
    # cadence 2 over 6 rounds: BASE at the first publish, deltas after
    assert names == ["BASE.00002.txt", "DELTA.00004", "DELTA.00006"]
    g, rnd = load_journal(str(jdir))
    assert rnd == 6 and len(g.models) == 6
    # the folded chain predicts exactly like the trained booster
    folded = lgb.Booster(model_str=model_to_string(g))
    np.testing.assert_allclose(folded.predict(X[:64]),
                               bst.predict(X[:64]), rtol=1e-6)
    # publish knobs are deployment-transient: never serialized into the
    # model text (a journal payload replayed elsewhere must not re-arm
    # publishing there)
    assert "publish_dir" not in model_to_string(bst._gbdt)


def test_publisher_completion_flush_off_cadence(tmp_path, binary_data):
    """5 rounds at cadence 2: rounds 2 and 4 publish in-loop, round 5
    lands via the completion flush — the journal head always equals the
    final model."""
    jdir = tmp_path / "journal"
    _train(binary_data, 5, publish_dir=jdir, every=2)
    j = DeltaJournal(str(jdir))
    assert j.head().round == 5
    _, rnd = load_journal(str(jdir))
    assert rnd == 5


def test_publisher_restart_reanchors_with_fresh_base(tmp_path,
                                                     binary_data):
    X, y = binary_data
    p = {**SMALL, "objective": "binary"}
    b3 = lgb.train(p, lgb.Dataset(X, y, params=p), 3)
    jdir = str(tmp_path / "journal")
    p1 = DeltaPublisher(jdir)
    assert p1.publish(b3._gbdt)
    b5 = lgb.train(p, lgb.Dataset(X, y, params=p), 2, init_model=b3)
    assert p1.publish(b5._gbdt)
    j = DeltaJournal(jdir)
    assert j.head().round == 5 and j.chain_length() == 1
    # a restarted trainer must NOT guess at the prior chain: its first
    # publish re-anchors with a fresh BASE at its own round
    p2 = DeltaPublisher(jdir)
    assert p2.publish(b5._gbdt)
    h = j.head()
    assert h.kind == "base" and h.round == 5
    assert j.chain_length() == 0 and not j.records_after(5)


def test_publisher_compacts_after_chain_limit(tmp_path, binary_data):
    jdir = tmp_path / "journal"
    _train(binary_data, 6, publish_dir=jdir, every=1)
    j = DeltaJournal(str(jdir))
    assert j.chain_length() == 5       # engine default: never compact
    X, y = binary_data
    p = {**SMALL, "objective": "binary"}
    pub = DeltaPublisher(str(tmp_path / "j2"), compact_after=2)
    b = lgb.train(p, lgb.Dataset(X, y, params=p), 1)
    pub.publish(b._gbdt)
    for _ in range(3):                 # rounds 2, 3, 4
        b = lgb.train(p, lgb.Dataset(X, y, params=p), 1, init_model=b)
        pub.publish(b._gbdt)
    assert pub.journal.chain_length() < 2
    assert pub.journal.head().round == 4


# ---------------------------------------------------------------------------
# incremental serving refresh: delta parity + zero-recompile envelope
# ---------------------------------------------------------------------------

def _journal_and_model(tmp_path, data, rounds=5, **extra):
    jdir = tmp_path / "journal"
    bst = _train(data, rounds, publish_dir=jdir, every=1, **extra)
    mfile = str(tmp_path / "model.txt")
    bst.save_model(mfile)
    return DeltaJournal(str(jdir)), mfile


@pytest.mark.parametrize("kwargs", [
    {"shard": 4},                      # dense, in-envelope appends
    {"compiler": "walk"},              # no dense tables: rebuild path
    {"shard": 4, "leaf_bits": 8},      # quantized leaf codes
], ids=["dense-shard4", "walk", "quantized-leaf8"])
def test_delta_parity_bitwise_with_cold_load(tmp_path, binary_data,
                                             kwargs):
    """Acceptance: a predictor grown round-by-round via ``apply_delta``
    is BITWISE identical to a cold full load at every published round,
    across bucket boundaries."""
    X, _ = binary_data
    j, mfile = _journal_and_model(tmp_path, binary_data, rounds=5)
    base_path, base_round = j.base_entry()
    reg = ModelRegistry()
    reg.load("m", base_path, warmup=False, **kwargs)
    rng = np.random.RandomState(0)
    queries = [rng.randn(n, X.shape[1]).astype(np.float32)
               for n in (1, 7, 9, 63)]
    for rec in j.records_after(base_round):
        out = reg.apply_delta("m", rec)
        assert out["round"] == rec.round
        # cold-load reference at the SAME round
        cold = ModelRegistry()
        cold.load("m", mfile, warmup=False,
                  num_iteration=rec.round, **kwargs)
        for Xq in queries:
            got = np.asarray(reg.get("m").predict(Xq))
            ref = np.asarray(cold.get("m").predict(Xq))
            assert np.array_equal(got, ref), \
                f"round {rec.round}: delta-applied != cold load"


def test_delta_parity_multiclass(tmp_path, multiclass_data):
    X, y = multiclass_data
    jdir = tmp_path / "journal"
    p = {**SMALL, "objective": "multiclass", "num_class": 3,
         "publish_dir": str(jdir), "publish_every": 1}
    bst = lgb.train(p, lgb.Dataset(X, y, params=p), 3)
    mfile = str(tmp_path / "model.txt")
    bst.save_model(mfile)
    j = DeltaJournal(str(jdir))
    base_path, base_round = j.base_entry()
    reg = ModelRegistry()
    reg.load("m", base_path, warmup=False, shard=8)
    for rec in j.records_after(base_round):
        assert rec.num_tree_per_iteration == 3
        reg.apply_delta("m", rec)
    cold = ModelRegistry()
    cold.load("m", mfile, warmup=False, shard=8)
    got = np.asarray(reg.get("m").predict(X[:32]))
    ref = np.asarray(cold.get("m").predict(X[:32]))
    assert got.shape == (32, 3)
    assert np.array_equal(got, ref)


def test_zero_recompiles_inside_shard_envelope(tmp_path, binary_data):
    """Acceptance: an in-envelope delta append splices lowered rows into
    the shard-padding slack — the dense signature is UNCHANGED (same
    jit cache entry) and serving the grown model recompiles nothing."""
    X, _ = binary_data
    j, _ = _journal_and_model(tmp_path, binary_data, rounds=2)
    base_path, base_round = j.base_entry()
    reg = ModelRegistry()
    # shard=4 pads the 1-tree base to capacity 4: rounds 2..4 append
    # in place; warmup compiles every bucket once
    reg.load("m", base_path, warmup=True, shard=4)
    p1 = reg.get("m")
    assert p1.info()["dense"]["capacity"] == 4
    sig_before = p1._sig
    r_before = p1.stats.snapshot()["recompiles"]
    (rec,) = j.records_after(base_round)
    out = reg.apply_delta("m", rec)
    assert out["mode"] == "extend"
    p2 = reg.get("m")
    assert p2 is not p1 and p2.num_trees == 2
    assert p2._sig == sig_before, "in-envelope append changed the " \
                                  "dense signature (jit cache miss)"
    rng = np.random.RandomState(1)
    for n in (1, 7, 8, 9, 63):
        p2.predict(rng.randn(n, X.shape[1]))
    assert p2.stats.snapshot()["recompiles"] == r_before, \
        "in-envelope delta append must not trigger dense recompiles"


def test_extend_past_envelope_rebuilds(tmp_path, binary_data):
    """Appending past the padded capacity falls back to a full rebuild
    (mode 'rebuild') and still serves the right ensemble."""
    j, mfile = _journal_and_model(tmp_path, binary_data, rounds=6)
    base_path, base_round = j.base_entry()
    reg = ModelRegistry()
    reg.load("m", base_path, warmup=False, shard=4)
    modes = [reg.apply_delta("m", rec)["mode"]
             for rec in j.records_after(base_round)]
    assert "rebuild" in modes          # capacity 4 crossed at round 5
    assert modes[0] == "extend"        # round 2 fit in the envelope
    X, _ = binary_data
    cold = ModelRegistry()
    cold.load("m", mfile, warmup=False, shard=4)
    assert np.array_equal(np.asarray(reg.get("m").predict(X[:16])),
                          np.asarray(cold.get("m").predict(X[:16])))


def test_extend_refuses_train_attached_predictor(binary_data):
    """Delta trees are text-parsed (REAL feature indices); a train-set
    attached predictor remaps through inner indices — mixing them would
    mis-route splits, so ``extended`` refuses with a typed error."""
    from lightgbm_tpu.serve.predictor import CompiledPredictor
    X, y = binary_data
    Xw = np.hstack([X, np.zeros((X.shape[0], 2))])   # unused columns
    p = {**SMALL, "objective": "binary"}
    bst = lgb.train(p, lgb.Dataset(Xw, y, params=p), 2)
    pred = CompiledPredictor(bst)
    if pred._used is None:
        pytest.skip("all features used; no inner remap to guard")
    with pytest.raises(ValueError, match="train-set-attached"):
        pred.extended(bst._gbdt.models[:1])


def test_registry_chain_guards(tmp_path, binary_data):
    X, y = binary_data
    j, mfile = _journal_and_model(tmp_path, binary_data, rounds=3)
    base_path, base_round = j.base_entry()
    recs = j.records_after(base_round)
    reg = ModelRegistry()
    reg.load("m", base_path, warmup=False, shard=4)
    # gap: skipping a round is a typed chain error, not silent drift
    with pytest.raises(DeltaChainError, match="re-anchor"):
        reg.apply_delta("m", recs[1])
    reg.apply_delta("m", recs[0])
    assert reg.round_of("m") == recs[0].round
    # replayed record -> idempotent noop (at-least-once push safe)
    out = reg.apply_delta("m", recs[0])
    assert out["mode"] == "noop"
    # wire-bytes input works identically
    out = reg.apply_delta("m", recs[1].to_bytes())
    assert out["round"] == recs[1].round
    # unknown model
    with pytest.raises(KeyError):
        reg.apply_delta("ghost", recs[0])
    # divergent base: a different 1-round model has the right round
    # count but the wrong fingerprint
    p = {**SMALL, "objective": "binary", "learning_rate": 0.31}
    other = lgb.train(p, lgb.Dataset(X, y, params=p), 1)
    ofile = str(tmp_path / "other.txt")
    other.save_model(ofile)
    reg2 = ModelRegistry()
    reg2.load("m", ofile, warmup=False)
    with pytest.raises(DeltaChainError, match="fingerprint"):
        reg2.apply_delta("m", recs[0])
    # a full reload clears the chain position
    reg.load("m", mfile, warmup=False)
    assert reg.round_of("m") is None


def test_evict_guard_and_inflight_readers(tmp_path, binary_data):
    _, mfile = _journal_and_model(tmp_path, binary_data, rounds=2)
    X, _ = binary_data
    reg = ModelRegistry()
    reg.load("only", mfile, warmup=False)
    with pytest.raises(ModelInUseError, match="force=True"):
        reg.evict("only")
    assert reg.names() == ["only"]     # refused evict left it serving
    # an in-flight reader that already resolved the predictor finishes
    # even across a forced eviction (predictors are immutable; handlers
    # hold their own reference)
    pred = reg.get("only")
    assert reg.evict("only", force=True)
    out = pred.predict(X[:8])
    assert np.asarray(out).shape == (8,)
    assert reg.names() == []
    # with >1 models the guard does not bite
    reg.load("a", mfile, warmup=False)
    reg.load("b", mfile, warmup=False)
    assert reg.evict("b")
    assert reg.names() == ["a"]
    assert reg.evict("missing") is False


def test_engine_refuses_init_model_plus_resume(tmp_path, binary_data):
    from lightgbm_tpu.resilience.checkpoint import CheckpointError
    X, y = binary_data
    ck = str(tmp_path / "ckpt")
    p = {**SMALL, "objective": "binary", "checkpoint_dir": ck}
    warm = lgb.train(p, lgb.Dataset(X, y, params=p), 2)
    with pytest.raises(CheckpointError, match="init_model and "
                                              "resume_from"):
        lgb.train({**p, "resume": "latest"}, lgb.Dataset(X, y, params=p),
                  4, init_model=warm)


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------

def _post(host, port, path, payload, timeout=30):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        body = json.dumps(payload).encode()
        conn.request("POST", path, body,
                     {"Content-Type": "application/json",
                      "Content-Length": str(len(body))})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def test_server_delta_endpoint(tmp_path, binary_data):
    from lightgbm_tpu.serve.server import PredictionServer
    j, _ = _journal_and_model(tmp_path, binary_data, rounds=3)
    base_path, base_round = j.base_entry()
    recs = j.records_after(base_round)
    reg = ModelRegistry()
    reg.load("m", base_path, warmup=False, shard=4)
    srv = PredictionServer(reg, port=0, max_wait_ms=0.5).start()
    try:
        def b64(rec):
            return base64.b64encode(rec.to_bytes()).decode("ascii")

        status, body = _post(srv.host, srv.port, "/models/m/delta",
                             {"record_b64": b64(recs[0])})
        assert status == 200 and body["round"] == recs[0].round, body
        assert reg.round_of("m") == recs[0].round
        # replay -> still 200, noop (pushes are at-least-once)
        status, body = _post(srv.host, srv.port, "/models/m/delta",
                             {"record_b64": b64(recs[0])})
        assert status == 200 and body["mode"] == "noop"
        # a gap is 409: the subscriber's fall-back-to-full-reload signal
        bad = DeltaRecord(base_round=recs[1].round + 3,
                          round=recs[1].round + 4,
                          parent_fp=recs[1].fp,
                          fp=chain_fingerprint(recs[1].fp, "x"),
                          num_tree_per_iteration=1, payload="x")
        status, body = _post(srv.host, srv.port, "/models/m/delta",
                             {"record_b64": b64(bad)})
        assert status == 409, body
        status, body = _post(srv.host, srv.port, "/models/ghost/delta",
                             {"record_b64": b64(recs[1])})
        assert status == 404, body
        status, body = _post(srv.host, srv.port, "/models/m/delta",
                             {"record_b64": "!!!not-base64!!!"})
        assert status == 400, body
        status, body = _post(srv.host, srv.port, "/models/m/delta", {})
        assert status == 400, body
        # the happy path continues after the rejects
        status, body = _post(srv.host, srv.port, "/models/m/delta",
                             {"record_b64": b64(recs[1])})
        assert status == 200 and body["round"] == recs[1].round
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# slow/chaos: fleet live refresh with a worker killed mid-publish
# ---------------------------------------------------------------------------

def _get_json(host, port, path, timeout=30):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def _wait_for(predicate, timeout=60.0, interval=0.05, desc="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc}")


@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_live_refresh_kill_mid_publish(tmp_path, binary_data):
    """Acceptance: a 2-worker fleet following a delta journal under
    live traffic, with one worker KILLED mid-publish, (a) serves every
    response from some published round — never a torn mix of rounds,
    (b) converges both workers to the journal head with delta pushes
    (not just respawn reloads), and (c) re-meets the
    ``fleet/model_staleness`` SLO after recovery."""
    from lightgbm_tpu.serve.fleet import FleetSupervisor
    X, y = binary_data
    p = {**SMALL, "objective": "binary"}
    full = lgb.train(p, lgb.Dataset(X, y, params=p), 6)
    g = full._gbdt
    jdir = str(tmp_path / "journal")
    j = DeltaJournal(jdir)
    base_text = model_to_string(g, num_iteration=3)
    model_file = str(tmp_path / "model.txt")
    with open(model_file, "w") as fh:
        fh.write(base_text)
    j.write_base(base_text, 3)
    Xq = X[:4].astype(np.float32)
    # reference predictions per published round: every served response
    # must match one of these exactly (floats round-trip JSON via repr)
    refs = {r: lgb.Booster(model_str=model_to_string(
                g, num_iteration=r)).predict(Xq).tolist()
            for r in (3, 4, 5, 6)}
    assert len({tuple(v) for v in refs.values()}) == 4
    fleet = FleetSupervisor(
        [model_file], workers=2,
        worker_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO},
        worker_args={"warmup": "0", "max_wait_ms": "0.5"},
        probe_interval_s=0.25, probe_timeout_s=5.0,
        breaker_failures=5, breaker_window_s=20.0,
        breaker_halfopen_s=1.0, backoff_base_s=0.2, backoff_max_s=1.0,
        startup_timeout_s=180.0, drain_timeout_s=30.0,
        forward_timeout_s=60.0, publish_dir=jdir,
        run_dir=str(tmp_path / "fleet-run"))
    fleet.start()
    try:
        _wait_for(lambda: all(w.acked_round == 3
                              for w in fleet.workers()),
                  desc="both workers anchored at the base round")
        stop = threading.Event()
        responses, mixes = [], []

        def poller():
            while not stop.is_set():
                try:
                    status, body = _post(fleet.host, fleet.port,
                                         "/predict",
                                         {"rows": Xq.tolist()},
                                         timeout=60)[0:2]
                except Exception:
                    continue
                if status != 200:
                    continue
                preds = body["predictions"]
                rounds = [r for r, v in refs.items() if v == preds]
                responses.append(rounds[0] if rounds else None)
                if not rounds:
                    mixes.append(preds)
                time.sleep(0.02)

        pt = threading.Thread(target=poller, daemon=True)
        pt.start()
        # publish rounds 4..6 while traffic flows; kill w0 right after
        # round 5 lands (mid-publish: its round-5 push or replay races
        # the respawn)
        for r in (4, 5, 6):
            j.append_delta(model_to_string(g, start_iteration=r - 1,
                                           num_iteration=1), r)
            if r == 5:
                w0 = fleet.workers()[0]
                if w0.proc is not None and w0.proc.poll() is None:
                    w0.proc.kill()
            time.sleep(0.8)
        _wait_for(lambda: all(w.state == "alive" and w.acked_round == 6
                              for w in fleet.workers()),
                  timeout=90.0,
                  desc="both workers recovered and caught up to round 6")
        stop.set()
        pt.join(10)
        # (a) every successful response came from a published round
        assert not mixes, f"responses matched NO published round: " \
                          f"{mixes[:2]}"
        assert len(responses) > 0 and None not in responses
        # traffic actually observed a refresh, not one static round
        assert len(set(responses)) >= 2, set(responses)
        # (b) deltas were pushed and applied (the ok counter moved)
        reg = fleet.metrics_registry
        pushes = reg.get("fleet_delta_pushes_total")
        assert pushes is not None and pushes.value(outcome="ok") >= 3
        # the fleet now serves the head round everywhere
        for _ in range(6):
            status, body = _post(fleet.host, fleet.port, "/predict",
                                 {"rows": Xq.tolist()}, timeout=60)
            assert status == 200 and body["predictions"] == refs[6]
        # (c) the staleness SLO is re-met after recovery: gauges read 0
        # rounds behind and the objective is not breached
        behind = reg.get("fleet_model_rounds_behind")
        assert behind is not None
        _wait_for(lambda: max(behind.value(model="model", worker=w.name)
                              for w in fleet.workers()) == 0.0,
                  timeout=15.0, desc="rounds-behind gauges back to 0")
        report = fleet.slo_engine.evaluate()
        stale = next(s for s in report["slos"]
                     if s["name"] == "fleet/model_staleness")
        assert stale["breached"] is False, stale
    finally:
        fleet.shutdown()
